"""Benchmark: Guppi-style spectroscopy pipeline throughput on one chip.

Mirrors the reference's north-star pipeline (reference:
testbench/gpuspec_simple.py:44-58 — FFT(fine_time) -> detect('stokes')
-> reduce) running through the REAL bifrost_tpu machinery: ring buffers,
thread-per-block pipeline, the fused FFT->Stokes->reduce stage chain as
ONE jitted computation per gulp.

Prints ONE JSON line:
  {"metric": ..., "value": Msamples/s, "unit": "Msamples/s",
   "vs_baseline": value / A100_BASELINE_MSPS}

MEASUREMENT HONESTY: on this environment's tunneled TPU backend,
``block_until_ready`` returns before device execution completes, so
naive timings overstate throughput by orders of magnitude.  This bench
forces REAL completion by reading back a scalar that depends on the
final gulp (TPU programs execute in enqueue order, so the last gulp's
value materializing implies the whole queue drained).  The same forcing
bounds the warmup phase before the clock starts.

Baseline derivation (BASELINE.md publishes no absolute number, so we use
a bandwidth model of the same device-resident chain on an A100 running
the CUDA reference): per complex sample, cuFFT 4096-pt c2c fp32 does
~2 r/w passes (32 B) plus detect read+write (~20 B) and reduce (~4 B)
≈ 56 B of HBM traffic; at ~1.55 TB/s effective that is ~28 Gsamples/s.
A100_BASELINE_MSPS = 28000.  For calibration, this environment's chip
measures ~14 TFLOPS on a pure f32 8k matmul (nominal v5e-1 is far
higher), so numbers here are a lower bound on on-prem v5e performance.
"""

import json
import sys
import time

import numpy as np

A100_BASELINE_MSPS = 28000.0

NTIME = 16384        # frames per gulp
NPOL = 2
NFINE = 4096         # fine-time samples -> FFT length
RFACTOR = 4
NGULP_WARM = 3
NGULP_BENCH = 32
SYNC_DEPTH = 4       # gulps of dispatch-ahead per block


def _force(arr):
    """Force REAL device completion of ``arr``'s dependency chain by
    materializing a scalar on the host."""
    import jax.numpy as jnp
    return float(jnp.sum(arr))


def build_and_run():
    import jax
    import jax.numpy as jnp
    import bifrost_tpu as bf
    from bifrost_tpu.pipeline import SourceBlock, SinkBlock
    from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage

    class VoltageSource(SourceBlock):
        """Emits device-resident ci8 voltage gulps (device rep: int8
        with trailing (re, im) axis), pre-staged so the bench measures
        the device pipeline, not host RNG."""

        def __init__(self, ngulp, **kwargs):
            super(VoltageSource, self).__init__(['bench'], NTIME,
                                                space='tpu', **kwargs)
            self.ngulp = ngulp
            rng = np.random.RandomState(0)
            host = rng.randint(-64, 64,
                               size=(NTIME, NPOL, NFINE, 2)).astype(np.int8)
            self.gulp = jnp.asarray(host)
            self.count = 0

        def create_reader(self, name):
            class R(object):
                def __enter__(self):
                    return self

                def __exit__(self, *exc):
                    return False
            return R()

        def on_sequence(self, reader, name):
            self.count = 0
            return [{'name': 'bench', 'time_tag': 0,
                     '_tensor': {'shape': [-1, NPOL, NFINE],
                                 'dtype': 'ci8',
                                 'labels': ['time', 'pol', 'fine_time'],
                                 'scales': [[0, 1]] * 3,
                                 'units': [None] * 3}}]

        def on_data(self, reader, ospans):
            if self.count >= self.ngulp:
                return [0]
            self.count += 1
            ospans[0].set(self.gulp)
            return [NTIME]

    class SpectraSink(SinkBlock):
        def __init__(self, iring, **kwargs):
            super(SpectraSink, self).__init__(iring, **kwargs)
            self.n = 0
            self.t_start = None
            self.elapsed = None
            self.checksum = 0.0

        def on_sequence(self, iseq):
            pass

        def on_data(self, ispan):
            self.n += 1
            if self.n == NGULP_WARM:
                # drain the queue (forces everything enqueued so far),
                # then start the clock
                self.checksum += _force(ispan.data)
                self.t_start = time.time()
            elif self.n == NGULP_WARM + NGULP_BENCH:
                # force the final gulp -> whole benched queue has
                # really executed
                self.checksum += _force(ispan.data)
                self.elapsed = time.time() - self.t_start

    with bf.Pipeline(sync_depth=SYNC_DEPTH) as p:
        src = VoltageSource(NGULP_WARM + NGULP_BENCH)
        # the whole FFT->detect->reduce chain fuses into ONE XLA
        # computation per gulp (blocks/fused.py)
        b = bf.blocks.fused(src, [
            FftStage('fine_time', axis_labels='freq'),
            DetectStage('stokes', axis='pol'),
            ReduceStage('freq', RFACTOR),
        ])
        sink = SpectraSink(b)
        p.run()
    if sink.elapsed is None:
        raise RuntimeError(
            "Benchmark incomplete: sink received %d gulps, expected %d"
            % (sink.n, NGULP_WARM + NGULP_BENCH))
    nsamples = NGULP_BENCH * NTIME * NPOL * NFINE
    return nsamples / sink.elapsed / 1e6


def main():
    msps = build_and_run()
    print(json.dumps({
        'metric': 'Guppi spectroscopy pipeline (FFT-detect-reduce) '
                  'throughput per chip',
        'value': round(msps, 1),
        'unit': 'Msamples/s',
        'vs_baseline': round(msps / A100_BASELINE_MSPS, 4),
    }))


if __name__ == '__main__':
    sys.exit(main())
