"""Benchmark: Guppi-style spectroscopy pipeline throughput on one chip.

Mirrors the reference's north-star pipeline (reference:
testbench/gpuspec_simple.py:44-58 — FFT(fine_time) -> detect('stokes')
-> reduce) running through the REAL bifrost_tpu machinery: ring buffers,
thread-per-block pipeline, the fused FFT->Stokes->reduce stage chain as
ONE jitted computation per gulp.

Prints ONE JSON line:
  {"metric": ..., "value": Msamples/s, "unit": "Msamples/s",
   "vs_baseline": value / A100_BASELINE_MSPS}

MEASUREMENT HONESTY: on this environment's tunneled TPU backend,
``block_until_ready`` returns before device execution completes, so
naive timings overstate throughput by orders of magnitude.  This bench
forces REAL completion by reading back a scalar that depends on the
final gulp (TPU programs execute in enqueue order, so the last gulp's
value materializing implies the whole queue drained).  The same forcing
bounds the warmup phase before the clock starts.

Baseline derivation (BASELINE.md publishes no absolute number, so we use
a bandwidth model of the same device-resident chain on an A100 running
the CUDA reference): per complex sample, cuFFT 4096-pt c2c fp32 does
~2 r/w passes (32 B) plus detect read+write (~20 B) and reduce (~4 B)
≈ 56 B of HBM traffic; at ~1.55 TB/s effective that is ~28 Gsamples/s.
A100_BASELINE_MSPS = 28000.  For calibration, this environment's chip
measures ~14 TFLOPS on a pure f32 8k matmul (nominal v5e-1 is far
higher), so numbers here are a lower bound on on-prem v5e performance.
"""

import json
import os
import sys
import time

import numpy as np

# the package __init__ honors JAX_PLATFORMS under PJRT plugins that
# ignore the env var (the tunneled TPU plugin here does), so CPU
# validation runs work; import it before jax initializes any backend
import bifrost_tpu  # noqa: F401

A100_BASELINE_MSPS = 28000.0

# HBM traffic of the XLA fused chain, per input sample: ci8 read (2 B)
# + unpack kernel c64 write (8) + XLA FFT custom-call read + write
# (8 + 8) + fused detect/reduce read (8) + reduced Stokes f32 write
# (2) = 36 B.  (The 56 B figure in the baseline model above is the
# UNFUSED cuFFT chain on the A100 and is used only for vs_baseline.)
CHAIN_BYTES_PER_SAMPLE = 36.0
# ... and of the fused Pallas spectrometer kernel: ci8 read (2 B) +
# reduced Stokes f32 write (2 B); nothing else leaves VMEM.  The
# BF_SPEC_TRANSPOSE=epilogue variant adds an XLA reorder of the
# reduced output (+4 B).
CHAIN_BYTES_PER_SAMPLE_PALLAS = 4.0
CHAIN_BYTES_PER_SAMPLE_PALLAS_EPI = 8.0


def flagship_header():
    """The flagship gulp's ring header (shared by the bench pipeline
    and the roofline probe so the two can never drift apart)."""
    return {'name': 'bench', 'time_tag': 0,
            '_tensor': {'shape': [-1, NPOL, NFINE],
                        'dtype': 'ci8',
                        'labels': ['time', 'pol', 'fine_time'],
                        'scales': [[0, 1]] * 3,
                        'units': [None] * 3}}


def flagship_stages():
    """The flagship FFT->detect->reduce stage chain (single source of
    truth for build_and_run and the traffic model)."""
    from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage
    return [FftStage('fine_time', axis_labels='freq'),
            DetectStage('stokes', axis='pol'),
            ReduceStage('freq', RFACTOR)]


def chain_traffic_model(impl_info):
    """(bytes_per_sample, impl_label) for the flagship chain from the
    impl record the FusedBlock PUBLISHED for the plan it executed
    (FusedBlock.impl_info / ProcLog ``<block>/impl``).  Pure
    bookkeeping — no probes, no env reads — so the label can never
    disagree with the path that ran (VERDICT r3 item 4)."""
    info = impl_info or {}
    if info.get('impl') == 'pallas-spectrometer':
        label = 'pallas-spectrometer[%s,%s]' % (
            info.get('precision', 'default'),
            info.get('transpose', 'kernel'))
        if info.get('transpose') == 'epilogue':
            return CHAIN_BYTES_PER_SAMPLE_PALLAS_EPI, label
        return CHAIN_BYTES_PER_SAMPLE_PALLAS, label
    return CHAIN_BYTES_PER_SAMPLE, 'xla-fused'

NTIME = 16384        # frames per gulp
NPOL = 2
NFINE = 4096         # fine-time samples -> FFT length
RFACTOR = 4
NGULP_WARM = 3
NGULP_BENCH = 32
SYNC_DEPTH = 4       # gulps of dispatch-ahead per block


def _force(arr):
    """Force REAL device completion of ``arr``'s dependency chain by
    materializing a scalar on the host."""
    import jax.numpy as jnp
    return float(jnp.sum(arr))


def build_and_run():
    import jax
    import jax.numpy as jnp
    import bifrost_tpu as bf
    bf.enable_compilation_cache()    # reuse XLA programs across runs
    from bifrost_tpu.pipeline import SourceBlock, SinkBlock

    class VoltageSource(SourceBlock):
        """Emits device-resident ci8 voltage gulps (device rep: int8
        with trailing (re, im) axis), pre-staged so the bench measures
        the device pipeline, not host RNG."""

        def __init__(self, ngulp, **kwargs):
            super(VoltageSource, self).__init__(['bench'], NTIME,
                                                space='tpu', **kwargs)
            self.ngulp = ngulp
            rng = np.random.RandomState(0)
            host = rng.randint(-64, 64,
                               size=(NTIME, NPOL, NFINE, 2)).astype(np.int8)
            self.gulp = jnp.asarray(host)
            self.count = 0

        def create_reader(self, name):
            class R(object):
                def __enter__(self):
                    return self

                def __exit__(self, *exc):
                    return False
            return R()

        def on_sequence(self, reader, name):
            self.count = 0
            return [flagship_header()]

        def on_data(self, reader, ospans):
            if self.count >= self.ngulp:
                return [0]
            self.count += 1
            ospans[0].set(self.gulp)
            return [NTIME]

    class SpectraSink(SinkBlock):
        def __init__(self, iring, **kwargs):
            super(SpectraSink, self).__init__(iring, **kwargs)
            self.n = 0
            self.t_start = None
            self.elapsed = None
            self.checksum = 0.0

        def on_sequence(self, iseq):
            pass

        def on_data(self, ispan):
            self.n += 1
            if self.n == NGULP_WARM:
                # drain the queue (forces everything enqueued so far),
                # then start the clock
                self.checksum += _force(ispan.data)
                self.t_start = time.time()
            elif self.n == NGULP_WARM + NGULP_BENCH:
                # force the final gulp -> whole benched queue has
                # really executed
                self.checksum += _force(ispan.data)
                self.elapsed = time.time() - self.t_start

    with bf.Pipeline(sync_depth=SYNC_DEPTH) as p:
        src = VoltageSource(NGULP_WARM + NGULP_BENCH)
        # the whole FFT->detect->reduce chain fuses into ONE XLA
        # computation per gulp (blocks/fused.py)
        fb = bf.blocks.fused(src, flagship_stages())
        sink = SpectraSink(fb)
        p.run()
    if sink.elapsed is None:
        raise RuntimeError(
            "Benchmark incomplete: sink received %d gulps, expected %d"
            % (sink.n, NGULP_WARM + NGULP_BENCH))
    nsamples = NGULP_BENCH * NTIME * NPOL * NFINE
    # what ran, as recorded by the block that ran it (also published to
    # ProcLog <block>/impl) — the roofline/label source of truth
    return nsamples / sink.elapsed / 1e6, fb.impl_info


def run_correctness_gate():
    """On-hardware correctness gate (VERDICT r1 item 7): run the ring +
    fused FFT->detect->reduce chain on the REAL chip, force completion
    via readback, and check the Stokes output:

    - TPU-vs-TPU determinism must be BIT-IDENTICAL (two runs of the
      same pipeline byte-compare equal);
    - the int8 correlation path (integer MXU arithmetic) must be
      BIT-IDENTICAL to the numpy integer oracle;
    - the float FFT chain must match the float64 numpy oracle to f32
      accuracy (different FFT algorithms cannot be bit-equal; the
      BASELINE bit-exactness bar applies to the integer paths and
      run-to-run determinism).

    Returns a dict; nonzero 'failures' means the gate failed.
    """
    import jax
    import jax.numpy as jnp
    import bifrost_tpu as bf
    from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage

    platform = jax.devices()[0].platform
    failures = []

    NT, NP, NF, RF = 64, 2, 1024, 4
    rng = np.random.RandomState(7)
    volt = rng.randint(-64, 64, size=(NT, NP, NF, 2)).astype(np.int8)

    def run_chain():
        import sys as _sys
        import os as _os
        _sys.path.insert(0, _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)), 'tests'))
        from util import NumpySourceBlock, GatherSink, simple_header
        hdr = simple_header([-1, NP, NF], 'ci8',
                            labels=['time', 'pol', 'fine_time'])
        raw = np.zeros((NT, NP, NF), dtype=np.dtype([('re', 'i1'),
                                                     ('im', 'i1')]))
        raw['re'] = volt[..., 0]
        raw['im'] = volt[..., 1]
        with bf.Pipeline() as p:
            src = NumpySourceBlock([raw], hdr, gulp_nframe=NT)
            b = bf.blocks.copy(src, space='tpu')
            b = bf.blocks.fused(b, [
                FftStage('fine_time', axis_labels='freq'),
                DetectStage('stokes', axis='pol'),
                ReduceStage('freq', RF)])
            b = bf.blocks.copy(b, space='system')
            sink = GatherSink(b)
            p.run()
        return sink.result()

    out1 = run_chain()
    out2 = run_chain()
    if not np.array_equal(out1, out2):
        failures.append('run-to-run Stokes output not bit-identical')

    # float64 numpy oracle for the FFT chain
    v = volt[..., 0].astype(np.float64) + 1j * volt[..., 1]
    s = np.fft.fft(v, axis=-1)
    x, y = s[:, 0], s[:, 1]
    xy = x * np.conj(y)
    stokes = np.stack([np.abs(x)**2 + np.abs(y)**2,
                       np.abs(x)**2 - np.abs(y)**2,
                       2 * xy.real, -2 * xy.imag], axis=1)
    oracle = stokes.reshape(NT, 4, NF // RF, RF).sum(-1)
    rel = np.max(np.abs(out1 - oracle) /
                 (np.max(np.abs(oracle)) + 1e-30))
    if rel > 1e-5:
        failures.append('Stokes vs numpy oracle rel err %.3g' % rel)

    # int8 correlation: integer arithmetic must be exactly the oracle's
    T, F, S, P = 32, 8, 4, 2
    ci = rng.randint(-64, 64, size=(T, F, S, P, 2)).astype(np.int8)
    xr = jnp.asarray(ci)
    re = ci[..., 0].astype(np.int64).reshape(T, F, S * P)
    im = ci[..., 1].astype(np.int64).reshape(T, F, S * P)
    rr = np.einsum('tfi,tfj->fij', re, re)
    ii = np.einsum('tfi,tfj->fij', im, im)
    k = np.einsum('tfi,tfj->fij', im, re)
    want = (rr + ii).astype(np.float32) + \
        1j * (k - np.swapaxes(k, -1, -2)).astype(np.float32)

    def corr(x):
        r8 = x[..., 0].reshape(T, F, S * P)
        i8 = x[..., 1].reshape(T, F, S * P)
        rr = jnp.einsum('tfi,tfj->fij', r8, r8,
                        preferred_element_type=jnp.int32)
        ii = jnp.einsum('tfi,tfj->fij', i8, i8,
                        preferred_element_type=jnp.int32)
        kk = jnp.einsum('tfi,tfj->fij', i8, r8,
                        preferred_element_type=jnp.int32)
        return (rr + ii).astype(jnp.float32), \
            (kk - jnp.swapaxes(kk, -1, -2)).astype(jnp.float32)

    gr, gi = jax.jit(corr)(xr)
    _force(gr)
    got = np.asarray(gr) + 1j * np.asarray(gi)
    if not np.array_equal(got, want):
        failures.append('int8 correlation not bit-identical to oracle')

    return {
        'metric': 'on-%s correctness gate' % platform,
        'platform': platform,
        'stokes_rel_err': float(rel),
        'deterministic': np.array_equal(out1, out2),
        'failures': failures,
        'ok': not failures,
    }


def _probe_backend(timeout=180.0, retries=None):
    """(healthy, history): probe the tunneled backend in FRESH
    subprocesses with backoff, never touching this process's PJRT
    state.  ``history`` records every attempt for the artifact, so a
    dead-tunnel run still documents what was tried (VERDICT r4
    item 4)."""
    import subprocess
    if retries is None:
        try:
            retries = int(os.environ.get('BF_BENCH_INIT_RETRIES', '3'))
        except ValueError:
            retries = 3
    here = os.path.dirname(os.path.abspath(__file__))
    probe_py = os.path.join(here, 'tools', 'tpu_probe.py')
    history = []
    if not os.path.exists(probe_py):
        return True, [{'note': 'no probe tool; assuming alive'}]
    env = dict(os.environ, BF_PROBE_DEADLINE=str(timeout))
    for attempt in range(1 + max(retries, 0)):
        if attempt:
            time.sleep(min(45.0 * attempt, 120.0))
        entry = {'t': time.strftime('%Y-%m-%dT%H:%M:%SZ',
                                    time.gmtime())}
        try:
            p = subprocess.run([sys.executable, probe_py], env=env,
                               capture_output=True, text=True,
                               timeout=timeout + 60)
            entry['rc'] = p.returncode
            try:
                entry.update(json.loads(
                    (p.stdout or '').strip().splitlines()[-1]))
            except (ValueError, IndexError):
                pass
        except subprocess.TimeoutExpired:
            entry['rc'] = 'timeout'
        history.append(entry)
        if entry.get('rc') == 0:
            return True, history
    return False, history


def _backend_alive(timeout=180.0, retries=None):
    """Probe in fresh subprocesses (a hung in-process init cannot be
    retried: the second call just blocks on the same PJRT init lock),
    then initialize THIS process's backend once a probe succeeds.  A
    failed (raised, not hung) in-process init after a healthy probe is
    a tunnel blip between the two — re-probe and retry rather than
    giving up.  Only child entrypoints call this; the parent
    aggregator never initializes a backend in-process (VERDICT r4
    item 5).  BF_SKIP_PROBE=1 (set by _run_isolated: the parent just
    proved health) skips the redundant probe subprocess."""
    import threading

    def init_inprocess(deadline):
        ok = []

        def probe():
            try:
                import jax
                jax.devices()
                ok.append(True)
            except Exception:
                pass

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(deadline)
        return bool(ok)

    if retries is None:
        try:
            retries = int(os.environ.get('BF_BENCH_INIT_RETRIES', '3'))
        except ValueError:
            retries = 3
    skip_probe = os.environ.get('BF_SKIP_PROBE') == '1'
    for attempt in range(1 + max(retries, 0)):
        if attempt:
            time.sleep(min(45.0 * attempt, 120.0))
        if skip_probe:
            return init_inprocess(timeout)
        healthy, _hist = _probe_backend(timeout, retries=0)
        if healthy and init_inprocess(timeout):
            return True
    return False


def bench_fft_impls():
    """Micro-compare the spectroscopy FFT step between jnp.fft and the
    4-step DFT-as-matmul MXU path (BF_FFT_IMPL=dftmm), on the bench
    shape.  Settles VERDICT r2 item 2's first question with one
    artifact."""
    import jax
    import jax.numpy as jnp
    from bifrost_tpu.ops.fft import dft_matmul_fft
    from bifrost_tpu.xfer import to_device

    T = 2048
    rng = np.random.RandomState(3)
    # complex input via re/im planes (raw complex transfer poisons the
    # tunneled backend — see xfer.py)
    x = to_device((rng.randn(T, NPOL, NFINE) +
                   1j * rng.randn(T, NPOL, NFINE))
                  .astype(np.complex64))
    n = x.size

    def force_c(arr):
        # complex outputs: force via |.| (float(<complex>) raises)
        return float(jnp.sum(jnp.abs(arr)))

    def timeit(fn):
        f = jax.jit(fn)
        force_c(f(x))                      # compile + drain
        t0 = time.perf_counter()
        iters = 8
        for _ in range(iters):
            y = f(x)
        force_c(y)
        return n * iters / (time.perf_counter() - t0) / 1e6

    out = {'jnp_fft_msps': round(timeit(
        lambda a: jnp.fft.fft(a, axis=-1)), 1)}
    out['dftmm_msps'] = round(timeit(
        lambda a: dft_matmul_fft(a, axis=-1)), 1)
    out['dftmm_speedup'] = round(out['dftmm_msps'] /
                                 max(out['jnp_fft_msps'], 1e-9), 3)
    return out


def bench_spectrometer_kernel():
    """Measure the fused Pallas spectrometer (ops/spectrometer.py) at
    the bench shape: accuracy vs the float64 oracle and throughput per
    precision/tile, plus which precision the auto mode would pick.
    The flagship number above already reflects auto mode (BF_SPEC_IMPL);
    this entry documents the kernel's standalone envelope."""
    import jax
    import jax.numpy as jnp
    from bifrost_tpu.ops.spectrometer import (fused_spectrometer,
                                              spectrometer_accuracy,
                                              choose_precision)
    if jax.devices()[0].platform != 'tpu':
        return {'skipped': 'tpu-only measurement'}
    out = {'chosen_by_auto': str(choose_precision(NFINE, RFACTOR))}
    rng = np.random.RandomState(5)
    T = 4096
    big = rng.randint(-64, 64,
                      size=(T, NPOL, NFINE, 2)).astype(np.int8)
    xb = jnp.asarray(big)
    n = T * NPOL * NFINE
    for prec, name in ((None, 'default'), ('high', 'high'),
                       ('highest', 'highest')):
        entry = {'rel_err': spectrometer_accuracy(prec, NFINE, RFACTOR)}
        if entry['rel_err'] >= 1e9:
            from bifrost_tpu.ops import spectrometer as _sp
            entry['probe_error'] = _sp._last_probe_error
        best = None
        for tile in (8, 16):
            for trans in ('kernel', 'epilogue'):
                try:
                    f = jax.jit(
                        lambda v, p=prec, t=tile, m=trans:
                        fused_spectrometer(v, rfactor=RFACTOR,
                                           time_tile=t, precision=p,
                                           transpose=m))
                    _force(f(xb))
                    t0 = time.perf_counter()
                    iters = 8
                    for _ in range(iters):
                        y = f(xb)
                    _force(y)
                    msps = n * iters / (time.perf_counter() - t0) / 1e6
                    if best is None or msps > best[2]:
                        best = (tile, trans, msps)
                except Exception as e:
                    entry.setdefault('tile_errors', {})[
                        '%d/%s' % (tile, trans)] = \
                        '%s: %s' % (type(e).__name__, str(e)[:120])
        if best:
            entry['best_tile'] = best[0]
            entry['best_transpose'] = best[1]
            entry['msps'] = round(best[2], 1)
            entry['vs_baseline'] = round(best[2] / A100_BASELINE_MSPS, 4)
        out[name] = entry
    return out


def bench_traffic_probe():
    """Cross-check chain_traffic_model's hand bytes-per-sample
    constants against the compiled program's own accounting (VERDICT
    r4 item 8): jit-lower the SAME composed stage chain the FusedBlock
    runs, at the bench gulp shape, and read XLA's 'bytes accessed' for
    the compiled executable.  The roofline's denominator can no longer
    drift silently — the artifact records modeled vs compiled and
    whether they agree within 15%.

    Caveat recorded in the result: for the Pallas whole-chain kernel,
    XLA models only the custom call's operands and results — which IS
    the model's claim (nothing else leaves VMEM), so agreement there
    confirms the interface traffic, not the kernel's internals."""
    import jax
    import jax.numpy as jnp
    from bifrost_tpu.stages import compose_stages, walk_headers
    stages = flagship_stages()
    headers = walk_headers(stages, flagship_header())
    shape = (NTIME, NPOL, NFINE, 2)
    fn, info = compose_stages(stages, headers, shape, 'int8')
    modeled, label = chain_traffic_model(info)
    nsamples = NTIME * NPOL * NFINE
    out = {'impl': label,
           'modeled_bytes_per_sample': modeled,
           'nsamples_per_gulp': nsamples}
    try:
        compiled = jax.jit(fn).lower(
            jax.ShapeDtypeStruct(shape, jnp.int8)).compile()
        ca = compiled.cost_analysis()
    except Exception as e:
        out['error'] = '%s: %s' % (type(e).__name__, str(e)[:200])
        return out
    d = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    bytes_acc = float(d.get('bytes accessed', 0.0) or 0.0)
    if not bytes_acc:
        out['error'] = 'cost_analysis reported no bytes accessed'
        return out
    measured = bytes_acc / nsamples
    out['compiled_bytes_per_sample'] = round(measured, 2)
    out['ratio_compiled_over_model'] = round(measured / modeled, 3)
    out['within_15pct'] = bool(abs(measured / modeled - 1.0) <= 0.15)
    return out


def bench_pallas_smoke():
    """Compile-and-run every Pallas kernel at tiny shapes on the LIVE
    backend (VERDICT r3 item 7): CI runs them interpret-mode only, so
    a Mosaic-lowering regression would otherwise surface mid-rewrite
    on the next chip session instead of in the previous one's
    artifact.  Folded into the driver JSON by run_suite_into."""
    import jax
    import jax.numpy as jnp
    out = {'platform': jax.devices()[0].platform}
    if out['platform'] != 'tpu':
        out['skipped'] = 'tpu-only gate (CI covers interpret mode)'
        return out
    rng = np.random.RandomState(2)
    oks = []

    # fused spectrometer: every precision x transpose variant
    from bifrost_tpu.ops.spectrometer import (fused_spectrometer,
                                              spectrometer_oracle)
    volt = rng.randint(-64, 64, size=(8, 2, 1024, 2)).astype(np.int8)
    xv = jnp.asarray(volt)
    want = spectrometer_oracle(volt, rfactor=4)
    spec = {}
    for prec in (None, 'high', 'highest'):
        for trans in ('kernel', 'epilogue'):
            k = '%s/%s' % (prec or 'default', trans)
            try:
                got = np.asarray(fused_spectrometer(
                    xv, rfactor=4, time_tile=8, precision=prec,
                    transpose=trans))
                rel = float(np.max(np.abs(got - want)) /
                            np.max(np.abs(want)))
                # 'default' is one bf16 pass per matmul — its accuracy
                # is whatever bf16 gives (the auto mode's 1e-5 gate
                # decides whether it SUBSTITUTES); the smoke gate asks
                # whether it still COMPILES AND RUNS under Mosaic
                bar = np.inf if prec is None else 1e-5
                spec[k] = {'ok': bool(np.isfinite(rel)) and rel < bar,
                           'rel_err': rel}
            except Exception as e:
                spec[k] = {'ok': False, 'error': '%s: %s'
                           % (type(e).__name__, str(e)[:150])}
            oks.append(spec[k]['ok'])
    out['spectrometer'] = spec

    # FDMT Pallas step pipeline
    from bifrost_tpu.ops.fdmt import Fdmt
    try:
        plan = Fdmt().init(32, 16, 1400.0, -0.1)
        x = rng.randn(32, 256).astype(np.float32)
        core = plan._core_pallas(False)
        got = np.asarray(jax.jit(core)(jnp.asarray(x)))
        ref = plan._core_numpy(x.astype(np.float64))
        rel = float(np.max(np.abs(got - ref)) / np.max(np.abs(ref)))
        out['fdmt_pallas'] = {'ok': rel < 1e-4, 'rel_err': rel}
    except Exception as e:
        out['fdmt_pallas'] = {'ok': False, 'error': '%s: %s'
                              % (type(e).__name__, str(e)[:150])}
    oks.append(out['fdmt_pallas']['ok'])

    # fused Hermitian int8 correlation kernel (measured xcorr
    # candidate 'pallas'; integer arithmetic must be bit-exact)
    try:
        from bifrost_tpu.ops.pallas_kernels import xcorr_herm
        Tc, Fc, nc = 16, 4, 256
        re8 = rng.randint(-64, 64, (Tc, Fc, nc)).astype(np.int8)
        im8 = rng.randint(-64, 64, (Tc, Fc, nc)).astype(np.int8)
        got = np.asarray(xcorr_herm(jnp.asarray(re8),
                                    jnp.asarray(im8),
                                    interpret=False))
        x = re8.astype(np.float64) + 1j * im8
        want = np.einsum('tfi,tfj->fij', x, np.conj(x))
        out['xcorr_herm'] = {
            'ok': bool(np.array_equal(got,
                                      want.astype(np.complex64)))}
    except Exception as e:
        out['xcorr_herm'] = {'ok': False, 'error': '%s: %s'
                             % (type(e).__name__, str(e)[:150])}
    oks.append(out['xcorr_herm']['ok'])

    # fused cross-correlation kernel (station-sharded mesh form)
    try:
        from bifrost_tpu.ops.pallas_kernels import xcorr_cross
        Tc, Fc, ni, nj = 16, 4, 128, 256
        ri8 = rng.randint(-64, 64, (Tc, Fc, ni)).astype(np.int8)
        ii8 = rng.randint(-64, 64, (Tc, Fc, ni)).astype(np.int8)
        rj8 = rng.randint(-64, 64, (Tc, Fc, nj)).astype(np.int8)
        ij8 = rng.randint(-64, 64, (Tc, Fc, nj)).astype(np.int8)
        got = np.asarray(xcorr_cross(
            jnp.asarray(ri8), jnp.asarray(ii8),
            jnp.asarray(rj8), jnp.asarray(ij8), interpret=False))
        xi = ri8.astype(np.float64) + 1j * ii8
        xj = rj8.astype(np.float64) + 1j * ij8
        want = np.einsum('tfi,tfj->fij', xi, np.conj(xj))
        out['xcorr_cross'] = {
            'ok': bool(np.array_equal(got,
                                      want.astype(np.complex64)))}
    except Exception as e:
        out['xcorr_cross'] = {'ok': False, 'error': '%s: %s'
                              % (type(e).__name__, str(e)[:150])}
    oks.append(out['xcorr_cross']['ok'])

    # stokes-detect elementwise kernel (stages.DetectStage fast path)
    try:
        from bifrost_tpu.ops import pallas_kernels as _pk
        if _pk.enabled():
            T, NF = 8, 256
            zr = rng.randn(T, NF).astype(np.float32)
            zi = rng.randn(T, NF).astype(np.float32)
            wr = rng.randn(T, NF).astype(np.float32)
            wi = rng.randn(T, NF).astype(np.float32)
            got = np.asarray(_pk.stokes_detect(
                jnp.asarray(zr), jnp.asarray(zi),
                jnp.asarray(wr), jnp.asarray(wi)))
            xx = zr ** 2 + zi ** 2
            yy = wr ** 2 + wi ** 2
            xyr = zr * wr + zi * wi
            xyi = zi * wr - zr * wi
            ref = np.stack([xx + yy, xx - yy, 2 * xyr, -2 * xyi], 1)
            rel = float(np.max(np.abs(got - ref)) /
                        np.max(np.abs(ref)))
            out['stokes_detect'] = {'ok': rel < 1e-6, 'rel_err': rel}
            oks.append(out['stokes_detect']['ok'])
        else:
            out['stokes_detect'] = {'skipped': 'kernel disabled'}
    except Exception as e:
        out['stokes_detect'] = {'ok': False, 'error': '%s: %s'
                                % (type(e).__name__, str(e)[:150])}
        oks.append(False)

    out['ok'] = bool(oks) and all(oks)
    return out


def _run_isolated(argv, timeout=900, env_extra=None):
    """Run a bench entrypoint in a FRESH subprocess and parse the last
    JSON line of its stdout.  Isolation matters on the tunneled
    backend: one op hitting UNIMPLEMENTED poisons every subsequent op
    in the process (this is what zeroed configs 4/5/7 + fft_impl in an
    earlier r3 run), so each config gets its own backend."""
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    # the parent already proved the backend alive; a child hitting a
    # mid-suite tunnel drop must fail fast with its graceful rc=2 JSON
    # rather than burn the isolation timeout in _backend_alive retries
    env = dict(os.environ, BF_BENCH_INIT_RETRIES='0',
               BF_SKIP_PROBE='1')
    if env_extra:
        env.update(env_extra)
    try:
        p = subprocess.run([sys.executable] + argv, cwd=here,
                           capture_output=True, text=True,
                           timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return {'error': 'subprocess timeout after %ds' % timeout}
    line = None
    for ln in (p.stdout or '').splitlines():
        ln = ln.strip()
        # skip preamble lines (e.g. bench_suite's chip_ceilings echo):
        # a crash between the preamble and the result must not record
        # the preamble as the config's result
        if ln.startswith('{') and '"chip_ceilings"' not in ln:
            line = ln
    if line is None or p.returncode != 0:
        err = 'rc=%d, stderr: %s' % (
            p.returncode, (p.stderr or '')[-200:].replace('\n', ' '))
        if line is None:
            return {'error': 'no JSON output (%s)' % err}
        try:
            parsed = json.loads(line)
        except ValueError:
            return {'error': 'unparseable output: %s' % line[:200]}
        parsed.setdefault('error', 'subprocess failed (%s)' % err)
        return parsed
    try:
        return json.loads(line)
    except ValueError:
        return {'error': 'unparseable output: %s' % line[:200]}


def run_suite_into(result):
    """Fold the bench_suite configs + chip ceilings + the correctness
    gate + the FFT-impl comparison into ``result`` (VERDICT r2 item 1:
    BENCH_r03.json alone must prove configs 1-6), and write the full
    detail next to this file: BENCH_SUITE_r04.json on real hardware,
    BENCH_SUITE_cpu_validation.json for CPU fallback runs (so a
    validation run can never clobber chip-measured numbers)."""
    here = os.path.dirname(os.path.abspath(__file__))
    platform = result.get('platform', 'unknown')
    detail = {'primary': dict(result), 'platform': platform}

    # every device-touching step runs in its own subprocess — the
    # parent aggregates JSON and never initializes PJRT, so no hung
    # init can cost the whole artifact (VERDICT r4 item 5)
    gate = _run_isolated(['bench.py', '--check'])
    result['check_ok'] = bool(gate.get('ok'))
    result['check'] = {k: gate[k] for k in
                       ('stokes_rel_err', 'deterministic', 'failures',
                        'error') if k in gate}
    detail['gate'] = gate

    ceil = _run_isolated(['bench.py', '--ceilings'])
    detail['ceilings'] = ceil
    result['ceilings'] = {k: round(v, 2) for k, v in ceil.items()
                          if isinstance(v, float)}
    if 'error' in ceil:
        # keep the root failure visible in the driver-recorded line,
        # not just as downstream KeyErrors in configs 3-5
        result['ceilings']['error'] = ceil['error']

    configs = {}
    # config 2 is the flagship measurement already in `result`.
    # the fraction of the MEASURED HBM ceiling the fused chain
    # sustains is the roofline verdict on the chain (VERDICT r2 item 2)
    chain_bytes_per_sample, impl = chain_traffic_model(
        result.get('impl_record'))
    c2 = {'config': 'Guppi spectroscopy (flagship, above)',
          'value': result['value'],
          'unit': result['unit'],
          'impl': impl,
          'vs_baseline': result['vs_baseline']}
    if isinstance(ceil.get('hbm_gbs'), float):
        achieved = result['value'] * 1e6 * chain_bytes_per_sample / 1e9
        c2['roofline'] = {
            'chain_bytes_per_sample': chain_bytes_per_sample,
            'achieved_GBs': round(achieved, 1),
            'hbm_GBs': round(ceil['hbm_gbs'], 1),
            'hbm_frac': round(achieved / ceil['hbm_gbs'], 3),
            'bound': ('HBM in/out (whole chain resident in VMEM)'
                      if impl.startswith('pallas') else
                      'HBM bandwidth (FFT custom call caps fusion; '
                      'see pallas fused-spectrometer path)')}
    configs['2'] = c2
    ceil_f = {k: v for k, v in ceil.items() if isinstance(v, float)}
    for cid in (1, 3, 4, 5, 6, 7, 8, 9):
        argv = ['bench_suite.py', '--config', str(cid)]
        if cid in (3, 4, 5) and ceil_f:
            # pass ceilings only when actually measured — an empty
            # dict would stop the fresh subprocess from measuring its
            # own after a parent-process backend failure
            argv += ['--ceil-json', json.dumps(ceil_f)]
        if cid == 7:
            argv += ['--msps-pipe', str(result['value'])]
        res = _run_isolated(argv)
        compact = _compact_config(res)
        detail['config_%d' % cid] = res
        configs[str(cid)] = compact
    result['configs'] = configs

    fft_cmp = _run_isolated(['bench.py', '--fft-impl'])
    result['fft_impl'] = fft_cmp
    detail['fft_impl'] = fft_cmp

    spec = _run_isolated(['bench.py', '--spectrometer'])
    result['spectrometer'] = spec
    detail['spectrometer'] = spec

    smoke = _run_isolated(['bench.py', '--pallas-smoke'])
    result['pallas_smoke'] = {k: smoke[k] for k in
                              ('ok', 'skipped', 'error')
                              if k in smoke}
    detail['pallas_smoke'] = smoke

    traffic = _run_isolated(['bench.py', '--traffic'])
    # the probe re-derives the impl in its own subprocess; if the
    # substitution decision diverged from the flagship run's published
    # record, the probe validated the WRONG denominator — flag it
    # rather than letting the artifact read as 'roofline validated'
    if 'impl' in traffic and traffic['impl'] != impl:
        traffic['impl_mismatch'] = (
            'probe compiled %s but the flagship ran %s; the roofline '
            'denominator is unvalidated' % (traffic['impl'], impl))
        traffic['within_15pct'] = False
    result['traffic_model'] = traffic
    detail['traffic_model'] = traffic

    # capture label from the watcher (BF_BENCH_ROUND, default stamped
    # by capture date) so future runs are never mislabeled with a
    # stale hardcoded round number
    round_tag = os.environ.get('BF_BENCH_ROUND') or \
        time.strftime('r%Y%m%d', time.gmtime())
    name = 'BENCH_SUITE_%s.json' % round_tag if platform == 'tpu' \
        else 'BENCH_SUITE_%s_validation.json' % platform
    try:
        with open(os.path.join(here, name), 'w') as f:
            json.dump(detail, f, indent=1, default=str)
    except OSError:
        pass
    return result


# the one projection both the healthy and the degraded artifact use,
# so the two can never silently report different fields
_COMPACT_KEYS = ('config', 'value', 'unit', 'vs_baseline', 'error',
                 'serial_s', 'pipeline_s', 'reference_bar',
                 'delivered_frac', 'delivery_ok')
_COMPACT_ROOF_KEYS = ('bw_frac', 'mfu', 'bound', 'pps_native_engine',
                      'goodput_Gbps', 'burst_eff', 'offered_pkts')


def _compact_config(res):
    """Project a config subprocess result onto the driver-line keys."""
    res.pop('config_id', None)
    compact = {}
    for k in _COMPACT_KEYS:
        if k in res:
            compact[k] = (round(res[k], 2)
                          if isinstance(res[k], float) else res[k])
    roof = res.get('roofline', {})
    for k in _COMPACT_ROOF_KEYS:
        if k in roof:
            compact[k] = (round(roof[k], 3)
                          if isinstance(roof[k], float) else roof[k])
    if 'core_compare' in res:
        compact['core_compare'] = res['core_compare']
    return compact


def _captured_date(here, pathn):
    """Commit date of an artifact, not mtime: a fresh checkout resets
    mtimes, and 'captured' must mean when the measurement was taken."""
    try:
        import subprocess
        p = subprocess.run(
            ['git', 'log', '-1', '--format=%cI', '--',
             os.path.basename(pathn)],
            cwd=here, capture_output=True, text=True, timeout=30)
        captured = (p.stdout or '').strip() or None
        if captured:
            return captured
    except Exception:
        pass
    return time.strftime('%Y-%m-%dT%H:%M:%SZ',
                         time.gmtime(os.path.getmtime(pathn)))


def degraded_result(history, reason=None):
    """Dead-backend artifact that still proves everything provable
    without a chip (VERDICT r4 item 4): host-only configs 1/6, the
    last-known-good chip artifact flagged stale, and the probe
    history — instead of a bare error line."""
    here = os.path.dirname(os.path.abspath(__file__))
    result = {
        'metric': 'Guppi spectroscopy pipeline (FFT-detect-reduce) '
                  'throughput per chip',
        'error': reason or (
            'jax backend failed to initialize after repeated probes '
            'with backoff (accelerator tunnel down?); host-only '
            'evidence below'),
        'platform': 'none',
        'value': 0.0, 'unit': 'Msamples/s', 'vs_baseline': 0.0,
        'probe_history': history,
        'configs': {},
    }
    # configs 1 (host sigproc) and 6 (capture loopback) need no chip
    for cid in (1, 6):
        res = _run_isolated(['bench_suite.py', '--config', str(cid)],
                            env_extra={'JAX_PLATFORMS': 'cpu'})
        result['configs'][str(cid)] = _compact_config(res)
    # newest chip-measured suite artifact, clearly flagged stale
    import glob
    best = None
    for pathn in sorted(glob.glob(
            os.path.join(here, 'BENCH_SUITE_r*.json'))):
        try:
            with open(pathn) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        if d.get('platform') == 'tpu':
            best = (pathn, d)
    if best:
        pathn, d = best
        captured = _captured_date(here, pathn)
        result['last_known_good'] = {
            'file': os.path.basename(pathn),
            'stale': True,
            'captured': captured,
            'flagship': d.get('primary', {}),
        }
    # the CPU-validation artifact proves the whole suite executes
    # end-to-end (pipeline, gate, traffic cross-check) even without a
    # chip — embed its summary, clearly labeled as validation numbers
    try:
        with open(os.path.join(
                here, 'BENCH_SUITE_cpu_validation.json')) as f:
            val = json.load(f)
        vpath = os.path.join(here, 'BENCH_SUITE_cpu_validation.json')
        prim = val.get('primary', {})
        result['cpu_validation'] = {
            'validation_only': True,
            'platform': val.get('platform'),
            'captured': _captured_date(here, vpath),
            'flagship_msps': prim.get('value'),
            'check_ok': val.get('gate', {}).get('ok'),
            'traffic_model': val.get('traffic_model'),
        }
    except (OSError, ValueError):
        pass
    # round-long watcher history, when a watcher has been running
    watch = os.path.join(here, 'bench_watch.log')
    try:
        with open(watch) as f:
            result['watch_log_tail'] = f.read().splitlines()[-12:]
    except OSError:
        pass
    return result


#: byte budget for the FINAL stdout line in degraded mode: the driver
#: tail-captures stdout and a fat one-line JSON defeats its parser
#: (VERDICT r5 item 3/5: `BENCH_r05.json parsed: null` — the degraded
#: line inlined the whole probe history + watch log).  ≤2 KB with
#: metric/error/pointer; the full detail goes to a side file.
DEGRADED_LINE_LIMIT = 2048


def _last_json_line(text):
    """The driver's parse path (mirrors _run_isolated): the last
    stdout line that is a JSON object, skipping preamble echoes.
    Returns the parsed dict or None — a line the driver cannot parse
    is exactly the `parsed: null` failure the compaction exists to
    prevent, so tests exercise THIS function."""
    line = None
    for ln in (text or '').splitlines():
        ln = ln.strip()
        if ln.startswith('{') and '"chip_ceilings"' not in ln:
            line = ln
    if line is None or len(line) > DEGRADED_LINE_LIMIT:
        return None
    try:
        return json.loads(line)
    except ValueError:
        return None


def _compact_probe_history(history):
    """Probe attempts compressed to counts + the last entry (VERDICT
    r5 item 5: the full history made the degraded line unparseable)."""
    history = list(history or [])
    rcs = [h.get('rc') for h in history]
    out = {'attempts': len(history),
           'rc_counts': {}}
    for rc in rcs:
        key = str(rc)
        out['rc_counts'][key] = out['rc_counts'].get(key, 0) + 1
    if history:
        last = dict(history[-1])
        err = last.get('error')
        if isinstance(err, str) and len(err) > 160:
            last['error'] = err[:160] + '...'
        out['last'] = last
    return out


def compact_degraded_line(result, limit=DEGRADED_LINE_LIMIT,
                          detail_name=None):
    """Project a degraded artifact onto a driver-parseable final line.

    Writes the FULL ``result`` to a side file (pointer included in the
    line), truncates the probe history to counts + last error, and
    drops progressively less-essential fields until the serialized
    line fits ``limit`` bytes.  The essentials — metric, error,
    value/unit/vs_baseline, platform — always survive."""
    here = os.path.dirname(os.path.abspath(__file__))
    if detail_name is None:
        round_tag = os.environ.get('BF_BENCH_ROUND') or \
            time.strftime('r%Y%m%d', time.gmtime())
        detail_name = 'BENCH_DEGRADED_%s.json' % round_tag
    try:
        with open(os.path.join(here, detail_name), 'w') as f:
            json.dump(result, f, indent=1, default=str)
        detail_ref = detail_name
    except OSError:
        detail_ref = None

    line = {k: result[k] for k in
            ('metric', 'error', 'platform', 'value', 'unit',
             'vs_baseline', 'flagship_error') if k in result}
    if isinstance(line.get('error'), str):
        line['error'] = line['error'][:300]
    line['probe'] = _compact_probe_history(result.get('probe_history'))
    if detail_ref:
        line['detail_file'] = detail_ref
    lkg = result.get('last_known_good')
    if isinstance(lkg, dict):
        line['last_known_good'] = {
            'file': lkg.get('file'), 'stale': True,
            'captured': lkg.get('captured'),
            'flagship_msps': (lkg.get('flagship') or {}).get('value')}
    val = result.get('cpu_validation')
    if isinstance(val, dict):
        line['cpu_validation'] = {
            'validation_only': True,
            'flagship_msps': val.get('flagship_msps'),
            'check_ok': val.get('check_ok')}
    cfgs = result.get('configs') or {}
    line['configs'] = {cid: {k: c[k] for k in
                             ('value', 'unit', 'error') if k in c}
                       for cid, c in cfgs.items()
                       if isinstance(c, dict)}
    # progressive drops until the line fits; the order is
    # least-essential first (everything dropped remains in the side
    # file, which the pointer names)
    drops = ['cpu_validation', 'configs', 'last_known_good', 'probe',
             'flagship_error']
    while len(json.dumps(line)) > limit and drops:
        line.pop(drops.pop(0), None)
    if len(json.dumps(line)) > limit:     # pathological error string
        line['error'] = (line.get('error') or '')[:100]
        line = {k: line[k] for k in ('metric', 'error', 'value',
                                     'unit', 'vs_baseline',
                                     'detail_file') if k in line}
    return line


_CHILD_MODES = ('--check', '--fft-impl', '--spectrometer',
                '--pallas-smoke', '--ceilings', '--traffic',
                '--flagship-only')


def main():
    if any(m in sys.argv for m in _CHILD_MODES):
        # child entrypoints own a backend; the parent below never does
        if not _backend_alive():
            print(json.dumps({
                'metric': 'backend initialization',
                'error': 'jax backend failed to initialize',
                'value': 0.0, 'unit': 'Msamples/s',
                'vs_baseline': 0.0}))
            return 2
        if '--check' in sys.argv:
            res = run_correctness_gate()
            print(json.dumps(res))
            return 0 if res['ok'] else 1
        if '--fft-impl' in sys.argv:
            print(json.dumps(bench_fft_impls()))
            return 0
        if '--spectrometer' in sys.argv:
            print(json.dumps(bench_spectrometer_kernel()))
            return 0
        if '--pallas-smoke' in sys.argv:
            res = bench_pallas_smoke()
            print(json.dumps(res))
            return 0 if res.get('ok') or res.get('skipped') else 1
        if '--ceilings' in sys.argv:
            import bench_suite
            print(json.dumps(bench_suite.measure_ceilings()))
            return 0
        if '--traffic' in sys.argv:
            print(json.dumps(bench_traffic_probe()))
            return 0
        # --flagship-only: the ring-pipeline measurement itself
        msps, impl_record = build_and_run()
        import jax
        print(json.dumps({
            'metric': 'Guppi spectroscopy pipeline (FFT-detect-reduce) '
                      'throughput per chip',
            # a 'cpu' platform marks a fallback-validation run, NOT
            # chip numbers — keep the label so artifacts can't be
            # misread
            'platform': jax.devices()[0].platform,
            'value': round(msps, 1),
            'unit': 'Msamples/s',
            'vs_baseline': round(msps / A100_BASELINE_MSPS, 4),
            # the impl record the executed FusedBlock published
            # (ProcLog <block>/impl): the artifact's label provably
            # comes from the executed pipeline, not a re-derivation
            'impl_record': impl_record,
            'impl': chain_traffic_model(impl_record)[1],
        }))
        return 0

    # PARENT AGGREGATOR: probes via subprocesses, runs every
    # measurement via _run_isolated, and only assembles JSON — no code
    # path here can hit the documented un-retryable PJRT init hang
    # (VERDICT r4 item 5)
    healthy, history = _probe_backend()
    if not healthy:
        # compact final line (≤2 KB, driver-parseable); the full
        # degraded detail lands in the side file the line points to
        print(json.dumps(compact_degraded_line(
            degraded_result(history))))
        return 2
    result = _run_isolated(['bench.py', '--flagship-only'],
                           timeout=2400)
    if 'value' not in result or result.get('error'):
        # healthy probe but the flagship child failed: degrade with
        # the child's error attached — and a reason that does NOT
        # claim an infra outage the probe history would contradict
        deg = degraded_result(
            history,
            reason='flagship pipeline subprocess failed (backend '
                   'probes were healthy — see flagship_error); '
                   'host-only evidence below')
        deg['flagship_error'] = result.get('error', 'no output')
        print(json.dumps(compact_degraded_line(deg)))
        return 2
    # fold gate + all suite configs + ceilings + FFT-impl compare
    # into the one line the driver records (VERDICT r2 item 1); any
    # sub-benchmark failure degrades to an error field instead of
    # losing the whole artifact
    try:
        result = run_suite_into(result)
    except Exception as e:
        result['suite_error'] = '%s: %s' % (type(e).__name__,
                                            str(e)[:300])
    print(json.dumps(result))


if __name__ == '__main__':
    sys.exit(main())
