"""Per-implementation oracles for the MXU GEMM paths (VERDICT r4 item
2): every planar / hi-lo / gram candidate must match the float64 numpy
oracle within its accuracy class, and the int8 paths must be exact, so
the measured probe can choose on speed alone.  Reference bar for the
capability: hand-tuned cherk/dp4a kernels, src/linalg.cu:210-226."""

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.ops.linalg import (LinAlg, xcorr_int8, _AB_IMPLS,
                                    _AAH_IMPLS, _I8_IMPLS,
                                    _XCORR_AUTO_IMPLS)


def _rand_c64(rng, shape):
    return (rng.randn(*shape) + 1j * rng.randn(*shape)) \
        .astype(np.complex64)


@pytest.mark.parametrize('impl', sorted(_AB_IMPLS))
def test_ab_impls_vs_f64_oracle(impl):
    rng = np.random.RandomState(0)
    a = _rand_c64(rng, (3, 24, 96))
    b = _rand_c64(rng, (3, 96, 40))
    la = LinAlg(ab_impl=impl)
    y = np.asarray(la.matmul(1.5, a, b, 0.0, None))
    oracle = 1.5 * (a.astype(np.complex128) @ b.astype(np.complex128))
    # hi-lo split drops the lo@lo term; planar/xla are f32-class; the
    # single-pass bf16 candidate is ~2^-8 (the accuracy gate, not this
    # oracle bar, decides whether it ever runs unforced)
    rtol = {'planar_hilo': 5e-4, 'planar_bf16': 3e-2}.get(impl, 1e-4)
    np.testing.assert_allclose(y, oracle.astype(np.complex64),
                               rtol=rtol, atol=rtol * 10)
    assert la.chosen['ab'] == impl


@pytest.mark.parametrize('impl', sorted(_AB_IMPLS))
def test_ab_impls_real_and_mixed(impl):
    rng = np.random.RandomState(1)
    ar = rng.randn(8, 32).astype(np.float32)
    bc = _rand_c64(rng, (32, 8))
    la = LinAlg(ab_impl=impl)
    rtol = 3e-2 if impl == 'planar_bf16' else 5e-4
    y = np.asarray(la.matmul(1.0, ar, bc, 0.0, None))
    oracle = ar.astype(np.complex128) @ bc.astype(np.complex128)
    np.testing.assert_allclose(y, oracle.astype(np.complex64),
                               rtol=rtol, atol=rtol * 10)
    # real x real stays real-valued
    br = rng.randn(32, 8).astype(np.float32)
    y2 = np.asarray(la.matmul(1.0, ar, br, 0.0, None))
    np.testing.assert_allclose(y2, ar @ br, rtol=rtol, atol=rtol * 10)


@pytest.mark.parametrize('impl', sorted(_AAH_IMPLS))
def test_aah_impls_vs_f64_oracle(impl):
    rng = np.random.RandomState(2)
    a = _rand_c64(rng, (2, 24, 64))
    la = LinAlg(aah_impl=impl)
    y = np.asarray(la.matmul(1.0, a, None, 0.0, None))
    a128 = a.astype(np.complex128)
    oracle = a128 @ np.conj(a128.transpose(0, 2, 1))
    rtol = {'planar_hilo': 5e-4, 'planar_bf16': 3e-2}.get(impl, 1e-4)
    np.testing.assert_allclose(y, oracle.astype(np.complex64),
                               rtol=rtol, atol=rtol * 100)
    # the diagonal is |a|^2: strictly real
    di = np.diagonal(y, axis1=-2, axis2=-1)
    assert np.max(np.abs(di.imag)) <= (2.0 if impl == 'planar_bf16'
                                       else 1e-2)


@pytest.mark.parametrize('impl', sorted(_I8_IMPLS))
def test_i8_impls_exact(impl):
    """Integer correlation must be bit-exact on every candidate."""
    rng = np.random.RandomState(3)
    n, k = 24, 48
    re = rng.randint(-64, 64, size=(n, k)).astype(np.int8)
    im = rng.randint(-64, 64, size=(n, k)).astype(np.int8)
    a = bf.empty((n, k), 'ci8', 'system')
    buf = a.as_numpy()
    buf['re'], buf['im'] = re, im
    ad = a.copy('tpu')
    la = LinAlg(i8_impl=impl)
    y = np.asarray(la.matmul(1.0, ad, None, 0.0, None))
    c = re.astype(np.float64) + 1j * im
    np.testing.assert_array_equal(y, (c @ np.conj(c.T))
                                  .astype(np.complex64))
    assert la.chosen['i8'] == impl


@pytest.mark.parametrize('impl', sorted(_I8_IMPLS))
def test_i8_impls_batched_beta(impl):
    rng = np.random.RandomState(4)
    b_, n, k = 3, 16, 32
    re = rng.randint(-32, 32, size=(b_, n, k)).astype(np.int8)
    im = rng.randint(-32, 32, size=(b_, n, k)).astype(np.int8)
    a = bf.empty((b_, n, k), 'ci8', 'system')
    buf = a.as_numpy()
    buf['re'], buf['im'] = re, im
    ad = a.copy('tpu')
    c = bf.zeros((b_, n, n), 'cf32', 'tpu')
    la = LinAlg(i8_impl=impl)
    la.matmul(2.0, ad, None, 0.0, c)
    v = re.astype(np.float64) + 1j * im
    expect = 2.0 * (v @ np.conj(v.transpose(0, 2, 1)))
    np.testing.assert_array_equal(np.asarray(c.data),
                                  expect.astype(np.complex64))


@pytest.mark.parametrize('impl', sorted(_XCORR_AUTO_IMPLS))
def test_xcorr_auto_impls_exact(impl):
    """Auto-correlation layouts: exact and identical across einsum /
    pre-transposed / widened-gram candidates."""
    import jax
    rng = np.random.RandomState(5)
    T, F, n = 12, 4, 10
    re = rng.randint(-64, 64, size=(T, F, n)).astype(np.int8)
    im = rng.randint(-64, 64, size=(T, F, n)).astype(np.int8)
    import jax.numpy as jnp
    y = np.asarray(xcorr_int8(jnp.asarray(re), jnp.asarray(im),
                              impl=impl))
    x = re.astype(np.float64) + 1j * im
    oracle = np.einsum('tfi,tfj->fij', x, np.conj(x))
    np.testing.assert_array_equal(y, oracle.astype(np.complex64))


@pytest.mark.parametrize('impl', ['einsum', 'fmt', 'pallas'])
def test_xcorr_cross_impls_exact(impl):
    """Cross-correlation (different i/j station blocks, as in the
    mesh-sharded correlator)."""
    import jax.numpy as jnp
    rng = np.random.RandomState(6)
    T, F, ni, nj = 8, 3, 6, 10
    re_i = rng.randint(-64, 64, size=(T, F, ni)).astype(np.int8)
    im_i = rng.randint(-64, 64, size=(T, F, ni)).astype(np.int8)
    re_j = rng.randint(-64, 64, size=(T, F, nj)).astype(np.int8)
    im_j = rng.randint(-64, 64, size=(T, F, nj)).astype(np.int8)
    y = np.asarray(xcorr_int8(jnp.asarray(re_i), jnp.asarray(im_i),
                              jnp.asarray(re_j), jnp.asarray(im_j),
                              impl=impl))
    xi = re_i.astype(np.float64) + 1j * im_i
    xj = re_j.astype(np.float64) + 1j * im_j
    oracle = np.einsum('tfi,tfj->fij', xi, np.conj(xj))
    np.testing.assert_array_equal(y, oracle.astype(np.complex64))


@pytest.mark.parametrize('impl', sorted(_AB_IMPLS))
def test_ab_impls_cf16_planes(impl):
    """cf16 voltages feed the planar GEMMs as raw f16 planes (half the
    HBM read width — the Cherk3mEx design point).  Every impl must
    match the float64 oracle of the f16-quantized values; hi-lo is
    exact-class for f16 planes (f16 splits exactly into two bf16
    planes), single-pass bf16 is the only lossy one."""
    rng = np.random.RandomState(10)
    t, a_, f = 12, 24, 16
    vr = rng.randn(t, a_, f).astype(np.float16)
    vi = rng.randn(t, a_, f).astype(np.float16)
    w = _rand_c64(rng, (8, 24))
    volt = bf.empty((t, a_, f), 'cf16', 'system')
    buf = volt.as_numpy()
    buf['re'], buf['im'] = vr, vi
    vd = volt.copy('tpu')
    la = LinAlg(ab_impl=impl)
    # (B, A) @ (T, A, F) broadcasts to the beamform contraction
    # einsum('ba,taf->tbf') under jnp.matmul semantics
    y = np.asarray(la.matmul(1.0, w, vd, 0.0, None))
    v = vr.astype(np.complex128) + 1j * vi.astype(np.complex128)
    oracle = np.einsum('ba,taf->tbf', w.astype(np.complex128), v)
    rtol = 2e-2 if impl == 'planar_bf16' else 1e-3
    np.testing.assert_allclose(y, oracle.astype(np.complex64),
                               rtol=rtol, atol=rtol * 10)
    assert la.chosen['ab'] == impl


def test_cf16_karatsuba_no_overflow():
    """re+im of large-but-in-range f16 values overflows f16; the
    Karatsuba m3 addends must be widened before the sum so planar
    paths stay finite where the xla baseline is finite."""
    rng = np.random.RandomState(12)
    t, a_, f = 4, 8, 8
    vr = np.full((t, a_, f), 4.0e4, np.float16)
    vi = np.full((t, a_, f), 4.0e4, np.float16)
    w = _rand_c64(rng, (4, 8)) * 1e-4
    volt = bf.empty((t, a_, f), 'cf16', 'system')
    buf = volt.as_numpy()
    buf['re'], buf['im'] = vr, vi
    vd = volt.copy('tpu')
    for impl in ('planar', 'planar_hilo'):
        la = LinAlg(ab_impl=impl)
        y = np.asarray(la.matmul(1.0, w, vd, 0.0, None))
        assert np.all(np.isfinite(y.view(np.float32))), impl
        v = vr.astype(np.complex128) + 1j * vi
        oracle = np.einsum('ba,taf->tbf', w.astype(np.complex128), v)
        np.testing.assert_allclose(y, oracle.astype(np.complex64),
                                   rtol=1e-3, atol=1e-2)


def test_cf16_aah_planes():
    """a @ a^H on cf16 input stays planar and matches the oracle."""
    rng = np.random.RandomState(11)
    n, k = 12, 32
    vr = rng.randn(n, k).astype(np.float16)
    vi = rng.randn(n, k).astype(np.float16)
    volt = bf.empty((n, k), 'cf16', 'system')
    buf = volt.as_numpy()
    buf['re'], buf['im'] = vr, vi
    vd = volt.copy('tpu')
    for impl in ('xla', 'planar', 'planar_hilo'):
        la = LinAlg(aah_impl=impl)
        y = np.asarray(la.matmul(1.0, vd, None, 0.0, None))
        v = vr.astype(np.complex128) + 1j * vi
        oracle = v @ np.conj(v.T)
        np.testing.assert_allclose(y, oracle.astype(np.complex64),
                                   rtol=1e-3, atol=1e-2)


def test_prewarm_winner_reaches_traced_xcorr(monkeypatch, tmp_path):
    """The production correlator calls xcorr_int8 under jax.jit, where
    measuring is impossible; a winner probed eagerly at on_sequence
    (xcorr_prewarm) must be what the traced call then uses."""
    import jax
    import jax.numpy as jnp
    from bifrost_tpu.ops import linalg as L
    monkeypatch.setenv('BF_LINALG_PROBE', '1')
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    monkeypatch.setattr(L, '_xcorr_chosen', {})
    T, F, n = 6, 2, 8
    L.xcorr_prewarm(T, F, n)
    key = 'auto=True i=%s j=%s' % ((T, F, n), (T, F, n))
    winner = L._xcorr_chosen.get(key)
    assert winner in L._XCORR_AUTO_IMPLS

    used = []
    orig = dict(L._XCORR_AUTO_IMPLS)

    def spy(name):
        def f(*a):
            used.append(name)
            return orig[name](*a)
        return f
    monkeypatch.setattr(L, '_XCORR_AUTO_IMPLS',
                        {k: spy(k) for k in orig})
    rng = np.random.RandomState(8)
    re = jnp.asarray(rng.randint(-64, 64, (T, F, n)).astype(np.int8))
    im = jnp.asarray(rng.randint(-64, 64, (T, F, n)).astype(np.int8))
    y = jax.jit(lambda r, i: L.xcorr_int8(r, i))(re, im)
    assert used == [winner]
    x = np.asarray(re, np.float64) + 1j * np.asarray(im, np.float64)
    oracle = np.einsum('tfi,tfj->fij', x, np.conj(x))
    np.testing.assert_array_equal(np.asarray(y),
                                  oracle.astype(np.complex64))


def test_traced_xcorr_consults_disk_cache(monkeypatch, tmp_path):
    """A winner cached by an earlier session (disk) is honored by a
    traced call even with no in-process prewarm."""
    import json
    import jax
    import jax.numpy as jnp
    from bifrost_tpu.ops import linalg as L
    from bifrost_tpu.ops import mprobe
    monkeypatch.setenv('BF_LINALG_PROBE', '1')
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    monkeypatch.setattr(L, '_xcorr_chosen', {})
    monkeypatch.setattr(mprobe, '_cache', {})
    T, F, n = 5, 2, 6
    key = 'auto=True i=%s j=%s' % ((T, F, n), (T, F, n))
    full_key = '%s|%s' % (mprobe.backend_tag(), key)
    with open(mprobe.cache_path('linalg_xcorr'), 'w') as f:
        json.dump({full_key: {'winner': 'gram', 'ms': {}}}, f)

    used = []
    orig = dict(L._XCORR_AUTO_IMPLS)

    def spy(name):
        def fn(*a):
            used.append(name)
            return orig[name](*a)
        return fn
    monkeypatch.setattr(L, '_XCORR_AUTO_IMPLS',
                        {k: spy(k) for k in orig})
    rng = np.random.RandomState(9)
    re = jnp.asarray(rng.randint(-8, 8, (T, F, n)).astype(np.int8))
    im = jnp.asarray(rng.randint(-8, 8, (T, F, n)).astype(np.int8))
    jax.jit(lambda r, i: L.xcorr_int8(r, i))(re, im)
    assert used == ['gram']


def test_probe_selects_and_records(monkeypatch, tmp_path):
    """With probing forced on (off-TPU), a winner is measured, recorded
    in chosen/probe_ms, and the result still matches the oracle."""
    monkeypatch.setenv('BF_LINALG_PROBE', '1')
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    rng = np.random.RandomState(7)
    a = _rand_c64(rng, (2, 16, 32))
    b = _rand_c64(rng, (2, 32, 16))
    la = LinAlg()
    y = np.asarray(la.matmul(1.0, a, b, 0.0, None))
    oracle = a.astype(np.complex128) @ b.astype(np.complex128)
    np.testing.assert_allclose(y, oracle.astype(np.complex64),
                               rtol=5e-4, atol=5e-3)
    assert la.chosen['ab'] in _AB_IMPLS
    assert la.probe_ms.get('ab'), la.probe_ms
