"""Tests for wav, convert_visibilities, dada file blocks."""

import os
import wave

import numpy as np

import bifrost_tpu as bf
from tests.util import NumpySourceBlock, GatherSink, simple_header


def test_wav_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    data = rng.randint(-3000, 3000, size=(1024, 2)).astype(np.int16)
    path = str(tmp_path / 'test.wav')
    with wave.open(path, 'wb') as w:
        w.setnchannels(2)
        w.setsampwidth(2)
        w.setframerate(8000)
        w.writeframes(data.tobytes())
    outdir = tmp_path / 'out'
    os.makedirs(str(outdir))
    with bf.Pipeline() as p:
        b = bf.blocks.read_wav([path], gulp_nframe=256)
        sink = GatherSink(b)
        b2 = bf.blocks.copy(b)
        bf.blocks.write_wav(b2, path=str(outdir))
        p.run()
    np.testing.assert_array_equal(sink.result(), data)
    with wave.open(str(outdir / 'test.wav'), 'rb') as w:
        assert w.getnframes() == 1024
        back = np.frombuffer(w.readframes(1024), np.int16).reshape(-1, 2)
    np.testing.assert_array_equal(back, data)


def test_convert_visibilities_matrix_roundtrip():
    """matrix(lower) -> storage -> matrix(full) recovers the Hermitian
    matrix."""
    T, F, S = 2, 3, 4
    rng = np.random.RandomState(1)
    full = (rng.randn(T, F, S, 2, S, 2) +
            1j * rng.randn(T, F, S, 2, S, 2)).astype(np.complex64)
    # make it Hermitian: V[i,pi,j,pj] = conj(V[j,pj,i,pi])
    sw = np.conj(np.transpose(full, (0, 1, 4, 5, 2, 3)))
    full = 0.5 * (full + sw)
    # keep only the lower triangle (incl. diagonal pol-lower)
    lower = full.copy()
    for i in range(S):
        for j in range(S):
            if i < j:
                lower[:, :, i, :, j, :] = 0
    hdr = simple_header([-1, F, S, 2, S, 2], 'cf32',
                        labels=['time', 'freq', 'station_i', 'pol_i',
                                'station_j', 'pol_j'], gulp_nframe=T)
    with bf.Pipeline() as p:
        src = NumpySourceBlock([lower], hdr, gulp_nframe=T)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.convert_visibilities(b, 'storage')
        b = bf.blocks.convert_visibilities(b, 'matrix')
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    out = sink.result()
    assert sink.headers[0]['_tensor']['labels'] == \
        ['time', 'freq', 'station_i', 'pol_i', 'station_j', 'pol_j']
    np.testing.assert_allclose(out, full, rtol=1e-4, atol=1e-5)


def test_dada_file_reader(tmp_path):
    hdr_text = (
        "HDR_SIZE 4096\nNBIT 8\nNPOL 2\nNCHAN 4\nNDIM 2\n"
        "TSAMP 1.0\nFREQ 1400.0\nBW 4.0\nSOURCE J0000+0000\n"
        "TELESCOPE TEST\n")
    rng = np.random.RandomState(2)
    data = rng.randint(-128, 128, size=(16, 4, 2, 2)).astype(np.int8)
    path = str(tmp_path / 'test.dada')
    with open(path, 'wb') as f:
        f.write(hdr_text.encode().ljust(4096))
        f.write(data.tobytes())
    with bf.Pipeline() as p:
        b = bf.blocks.read_dada_file([path], gulp_nframe=8)
        sink = GatherSink(b)
        p.run()
    hdr = sink.headers[0]
    assert hdr['_tensor']['dtype'] == 'ci8'
    assert hdr['source_name'] == 'J0000+0000'
    out = sink.result()
    got = np.stack([out['re'], out['im']], axis=-1)
    np.testing.assert_array_equal(got, data)


def test_numa_binding_helpers():
    """NUMA helpers are advisory: correct types, no crashes, graceful
    False where unsupported (reference: ring_impl.cpp:164-166)."""
    import numpy as np
    from bifrost_tpu import affinity
    node = affinity.numa_node_of_core(0)
    assert node is None or isinstance(node, int)
    arr = np.zeros(4096, np.uint8)
    ok = affinity.bind_memory_to_core(arr, 0)
    assert isinstance(ok, bool)
    assert affinity.bind_memory_to_core(arr, None) is False
    # ring plumbing: a core= ring allocates without error
    from bifrost_tpu.ring import Ring
    r = Ring(space='system', core=0)
    r.resize(1024, 4096)


def test_audio_block_with_fake_portaudio(monkeypatch):
    """The PortAudio block logic end-to-end against an injected fake
    library (no audio hardware in CI; reference analogue:
    blocks/audio.py + portaudio.py)."""
    import ctypes
    from bifrost_tpu.io import portaudio as pa_mod

    class FakePA(object):
        def __init__(self):
            self.reads = 0

        def Pa_Initialize(self):
            return 0

        def Pa_OpenDefaultStream(self, stream_p, channels, out_ch, fmt,
                                 rate, fpb, cb, user):
            return 0

        def Pa_StartStream(self, stream):
            return 0

        def Pa_ReadStream(self, stream, buf, nframe):
            self.reads += 1
            if self.reads > 3:
                return -9988              # input overflowed -> stop
            n = len(bytes(buf)) // 2
            samples = np.arange(n, dtype=np.int16) + 1000 * self.reads
            buf[:] = samples.tobytes()
            return 0

        def Pa_StopStream(self, stream):
            return 0

        def Pa_CloseStream(self, stream):
            return 0

        @property
        def Pa_GetErrorText(self):
            class F(object):
                restype = None

                def __call__(self, err):
                    return b'fake overflow'
            return F()

    fake = FakePA()
    pa_mod.set_library(fake)
    try:
        import importlib
        from bifrost_tpu.blocks import audio as audio_blocks
        importlib.reload(audio_blocks)
        with bf.Pipeline() as p:
            src = audio_blocks.read_audio(
                [{'rate': 8000, 'channels': 2, 'nbits': 16}],
                gulp_nframe=8)
            sink = GatherSink(src)
            p.run()
        hdr = sink.headers[0]
        assert hdr['_tensor']['dtype'] == 'i16'
        assert hdr['_tensor']['shape'] == [-1, 2]
        assert hdr['frame_rate'] == 8000
        out = sink.result()
        assert out.shape == (24, 2)       # 3 good reads x 8 frames
        np.testing.assert_array_equal(
            out[:8].reshape(-1), np.arange(16, dtype=np.int16) + 1000)
    finally:
        pa_mod.set_library(None)
        importlib.reload(audio_blocks)


def test_host_transpose_tiled_matches_numpy():
    from bifrost_tpu.blocks.transpose import _host_transpose
    rng = np.random.RandomState(9)
    cases = [
        ((300, 1, 200), (2, 1, 0)),       # tiled path, odd sizes
        ((128, 70), (1, 0)),              # tiled, non-divisible tile
        ((8, 6, 4), (2, 0, 1)),           # 3-D fallback
        ((5, 7), (1, 0)),                 # small fallback
        ((64, 1, 64, 1), (2, 1, 0, 3)),   # size-1 axes interleaved
    ]
    for shape, axes in cases:
        src = rng.randn(*shape).astype(np.float32)
        want = np.transpose(src, axes)
        out = np.empty_like(want)
        _host_transpose(out, src, axes)
        np.testing.assert_array_equal(out, want,
                                      err_msg=str((shape, axes)))


def test_host_reduce_matches_numpy():
    from bifrost_tpu.blocks.reduce import _host_reduce
    rng = np.random.RandomState(4)
    for dtype in (np.float32, np.complex64, np.int32):
        # shapes follow ReduceBlock's call convention: rax is an
        # inserted axis whose FULL length is the factor
        for shape, rax in [((6, 8, 4), 2), ((3, 4, 5), 1),
                           ((2, 700), 1), ((2, 130, 5), 1)]:
            x = (rng.randn(*shape) * 100).astype(dtype)
            f = shape[rax]
            for op in ('sum', 'mean', 'min', 'max'):
                if op in ('min', 'max') and dtype == np.complex64:
                    continue
                want = {'sum': np.sum, 'mean': np.mean,
                        'min': np.min, 'max': np.max}[op](x, axis=rax)
                got = _host_reduce(x, rax, f, op)
                np.testing.assert_allclose(
                    got, want, rtol=1e-5, atol=1e-3,
                    err_msg=str((dtype, shape, rax, op)))
