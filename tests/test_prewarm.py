"""Probe/compile pre-warming: measured probes and plan builds must run
at sequence start (on_sequence), never inside on_data — in the
reference's operating regime a first-gulp latency spike in a capture
pipeline is a dropped packet (its blocks pay plan build at sequence
start too: e.g. fdmt plan init in on_sequence, reference
python/bifrost/blocks/fdmt.py:38-140)."""

import numpy as np

import bifrost_tpu as bf
from tests.util import NumpySourceBlock, GatherSink, simple_header


def test_fused_plan_builds_outside_on_data(monkeypatch):
    """FusedBlock builds + compiles its plan during on_sequence; the
    steady-state gulps must not trigger a plan build."""
    from bifrost_tpu.blocks.fused import FusedBlock
    from bifrost_tpu.stages import FftStage, DetectStage
    from bifrost_tpu.dtype import ci8 as ci8_dtype

    state = {'in_on_data': False}
    builds = []
    orig_build = FusedBlock._build_plan
    orig_on_data = FusedBlock.on_data

    def spy_build(self, shape, dtype, donate=False):
        builds.append(state['in_on_data'])
        return orig_build(self, shape, dtype, donate=donate)

    def spy_on_data(self, ispan, ospan):
        state['in_on_data'] = True
        try:
            return orig_on_data(self, ispan, ospan)
        finally:
            state['in_on_data'] = False

    monkeypatch.setattr(FusedBlock, '_build_plan', spy_build)
    monkeypatch.setattr(FusedBlock, 'on_data', spy_on_data)

    rng = np.random.RandomState(0)
    raw = np.zeros((16, 2, 16), dtype=ci8_dtype)
    raw['re'] = rng.randint(-16, 16, size=(16, 2, 16))
    raw['im'] = rng.randint(-16, 16, size=(16, 2, 16))
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 2, 16], 'ci8',
                            labels=['time', 'pol', 'fine_time'])
        src = NumpySourceBlock([raw[:8], raw[8:]], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fused(b, [FftStage('fine_time', axis_labels='freq'),
                                DetectStage('stokes', axis='pol')])
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    out = sink.result()
    assert out.shape == (16, 4, 16)
    assert builds, 'plan was never built'
    assert not any(builds), \
        'FusedBlock plan build executed inside on_data (not pre-warmed)'


def test_fdmt_probe_outside_on_data(monkeypatch):
    """With measured core probing forced on, the probe must run during
    on_sequence pre-warm; neither the steady gulps nor the ragged final
    gulp may probe inside on_data (the tail reuses the locked winner)."""
    from bifrost_tpu.blocks.fdmt import FdmtBlock
    from bifrost_tpu.ops.fdmt import Fdmt

    monkeypatch.setenv('BF_FDMT_PROBE', '1')
    state = {'in_on_data': False}
    probes = []
    orig_probe = Fdmt._probe_cores
    orig_on_data = FdmtBlock.on_data

    def spy_probe(self, cands, shape, negative_delays):
        probes.append((state['in_on_data'], tuple(shape)))
        return orig_probe(self, cands, shape, negative_delays)

    def spy_on_data(self, ispan, ospan):
        state['in_on_data'] = True
        try:
            return orig_on_data(self, ispan, ospan)
        finally:
            state['in_on_data'] = False

    monkeypatch.setattr(Fdmt, '_probe_cores', spy_probe)
    monkeypatch.setattr(FdmtBlock, 'on_data', spy_on_data)

    nchan, T = 8, 64
    rng = np.random.RandomState(0)
    x = rng.rand(nchan, T).astype(np.float32)
    hdr = {
        'name': 'prewarm-test', 'time_tag': 0,
        '_tensor': {
            'shape': [nchan, -1],
            'dtype': 'f32',
            'labels': ['freq', 'time'],
            'scales': [[100.0, 1.0], [0.0, 1e-3]],
            'units': ['MHz', 's'],
        },
    }
    gulps = [x[:, i * 16:(i + 1) * 16].copy() for i in range(4)]

    class FreqSource(bf.SourceBlock):
        def create_reader(self, name):
            class R:
                def __enter__(self):
                    return self

                def __exit__(self, *e):
                    return False
            return R()

        def on_sequence(self, reader, name):
            self.i = 0
            return [dict(hdr)]

        def on_data(self, reader, ospans):
            if self.i >= len(gulps):
                return [0]
            g = gulps[self.i]
            self.i += 1
            d = ospans[0].data.as_numpy()
            d[...] = g
            return [g.shape[1]]

    collected = []

    class DMSink(bf.SinkBlock):
        def on_sequence(self, iseq):
            pass

        def on_data(self, ispan):
            collected.append(np.array(ispan.data.as_numpy()))

    with bf.Pipeline() as p:
        src = FreqSource(['freq'], gulp_nframe=16)
        b = bf.blocks.copy(src, space='tpu')
        b = FdmtBlock(b, max_delay=9)
        b = bf.blocks.copy(b, space='system')
        DMSink(b)
        p.run()

    assert collected, 'pipeline produced no output'
    assert probes, 'core probe never ran (BF_FDMT_PROBE=1 was set)'
    in_data = [s for flag, s in probes if flag]
    assert not in_data, \
        'FDMT core probe executed inside on_data at shapes %s' % in_data


def test_xcorr_probe_outside_on_data(monkeypatch, tmp_path):
    """With measured probing forced on, CorrelateBlock's X-engine
    probe must run at on_sequence (XEngine.prewarm); no mprobe.select
    may execute inside on_data — the traced call finds the winner in
    the cache."""
    from bifrost_tpu.blocks.correlate import CorrelateBlock
    from bifrost_tpu.ops import mprobe
    from bifrost_tpu.ops import linalg as L
    from bifrost_tpu.dtype import ci8 as ci8_dtype

    monkeypatch.setenv('BF_LINALG_PROBE', '1')
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    monkeypatch.setattr(L, '_xcorr_chosen', {})
    monkeypatch.setattr(mprobe, '_cache', {})
    state = {'in_on_data': False}
    probes = []
    orig_select = mprobe.select
    orig_on_data = CorrelateBlock.on_data

    def spy_select(name, *a, **k):
        probes.append((state['in_on_data'], name))
        return orig_select(name, *a, **k)

    def spy_on_data(self, ispan, ospan):
        state['in_on_data'] = True
        try:
            return orig_on_data(self, ispan, ospan)
        finally:
            state['in_on_data'] = False

    monkeypatch.setattr(mprobe, 'select', spy_select)
    monkeypatch.setattr(CorrelateBlock, 'on_data', spy_on_data)

    rng = np.random.RandomState(3)
    T, F, S, P = 16, 2, 3, 2
    raw = np.zeros((T, F, S, P), dtype=ci8_dtype)
    raw['re'] = rng.randint(-16, 16, size=raw.shape)
    raw['im'] = rng.randint(-16, 16, size=raw.shape)
    hdr = simple_header([-1, F, S, P], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=8)
    with bf.Pipeline() as p:
        src = NumpySourceBlock([raw[:8], raw[8:]], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        corr = bf.blocks.correlate(b, nframe_per_integration=16)
        b = bf.blocks.copy(corr, space='system')
        sink = GatherSink(b)
        p.run()
    assert sink.result() is not None
    xsel = [(ind, n) for ind, n in probes if n == 'xengine']
    assert xsel, 'X-engine probe never ran (prewarm missing)'
    assert not any(ind for ind, _ in xsel), \
        'X-engine probe executed inside on_data (not pre-warmed)'
    # the prewarmed winner must be keyed at the shape the traced
    # on_data call actually looks up — a t_eff/shape mismatch would
    # pass the asserts above while the gulps silently run the default
    n = S * P
    key = corr.engine._key((8, F, n), 'int8', True)
    assert key in corr.engine.chosen, (key, corr.engine.chosen)
