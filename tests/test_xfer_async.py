"""Async transfer engine (bifrost_tpu.xfer): staging aliasing safety,
out-of-order completion drain, deferred D2H ring fills, buffer
donation bit-exactness, and the sync_strict fallback."""

import gc

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu import xfer
from bifrost_tpu.telemetry import counters
from tests.util import NumpySourceBlock, GatherSink, simple_header


@pytest.fixture(autouse=True)
def _reset():
    counters.reset()
    yield
    xfer.reset_engine()


# ---------------------------------------------------------------------------
# staging aliasing safety (the bug the old defensive copy guarded)
# ---------------------------------------------------------------------------

def test_to_device_does_not_alias_recycled_host_memory():
    """A writer recycling its host buffer right after to_device must
    not corrupt the device array — the exact CPU-backend zero-copy bug
    the old defensive copy guarded against."""
    eng = xfer.TransferEngine()
    ringbuf = np.arange(64 * 1024, dtype=np.float32).reshape(64, 1024)
    want = ringbuf.copy()
    d = eng.to_device(ringbuf)
    ringbuf[...] = -1.0                 # writer recycles the gulp
    assert np.array_equal(np.asarray(d), want)


def test_to_device_alias_safe_under_inflight_compute():
    """Recycling the source while a dispatched computation is still
    running must not change its result (staging buffers are never
    reused while any consumer may read them)."""
    import jax
    eng = xfer.TransferEngine()
    fn = jax.jit(lambda x: (x @ x).sum())
    src = np.full((512, 512), 1.0, np.float32)
    d = eng.to_device(src)
    y = fn(d)                           # async dispatch reads d
    del d
    src[...] = 0.0                      # recycle immediately
    gc.collect()
    # a second transfer of the same shape must not steal the buffer
    eng.to_device(np.zeros((512, 512), np.float32))
    assert float(y) == 512.0 * 512 * 512


def test_staging_pool_recycles_only_completed_transfers():
    """Copying-backend protocol (forced via zero_copy=False): a slot
    returns to the pool only once its transfer is observed complete;
    a slot whose array died unobserved is dropped, not reused."""
    eng = xfer.TransferEngine(staging=2, zero_copy=False)
    a = np.ones((256, 256), np.float32)
    d1 = eng.to_device(a)
    d1.block_until_ready()
    assert counters.get('xfer.h2d_staged') == 1
    # d1 complete and still alive: its slot is reclaimable
    d2 = eng.to_device(a * 2)
    assert counters.get('xfer.h2d_staged') == 2
    pool = eng._pool
    assert pool._nalloc[((256, 256), 'float32')] <= 2
    # kill an array whose completion was never observed after this
    # point: the pool must DROP the slot (nalloc decremented), never
    # hand its buffer out for reuse
    slot_entry = [s for s in pool._busy if s.ref() is d2]
    assert slot_entry
    del d2
    gc.collect()
    assert slot_entry[0].recycled
    buf_id = id(slot_entry[0].buf)
    free = pool._free.get(((256, 256), 'float32'), [])
    assert all(id(b) != buf_id for b in free)


# ---------------------------------------------------------------------------
# non-blocking D2H: futures, queue bound, out-of-order drain
# ---------------------------------------------------------------------------

def test_staging_pool_survives_donated_arrays():
    """Regression: the pool's reclaim scan must not poll is_ready() on
    an array that was donated (deleted) downstream — that crashes the
    runtime.  And deletion happens at DISPATCH time, proving nothing
    about the DMA, so the slot must be DROPPED (never reused)."""
    from bifrost_tpu.ops.common import donating_jit
    eng = xfer.TransferEngine(staging=2, zero_copy=False)
    a = np.ones((128, 128), np.float32)
    d = eng.to_device(a)
    d.block_until_ready()
    pool = eng._pool
    slot = [s for s in pool._busy if s.ref() is d][0]
    buf_id = id(slot.buf)
    fn = donating_jit(lambda x: x + 1.0, donate_argnums=(0,))
    y = fn(d)                       # d is now deleted, slot still bound
    assert d.is_deleted()
    d2 = eng.to_device(a * 3)       # triggers the reclaim scan
    assert np.array_equal(np.asarray(d2), a * 3)
    assert float(y[0, 0]) == 2.0
    # the donated slot was retired, not recycled into the free list
    assert slot.recycled
    assert all(id(b) != buf_id
               for bufs in pool._free.values() for b in bufs)


def test_to_device_empty_array():
    """Zero-size gulps must transfer cleanly (regression: the aligned
    allocator rejected empty shapes)."""
    eng = xfer.TransferEngine()
    for zc in (True, False):
        e = xfer.TransferEngine(zero_copy=zc)
        d = e.to_device(np.empty((0, 4), np.float32))
        assert np.asarray(d).shape == (0, 4)
    assert np.asarray(eng.to_device(np.float32(3.0))).shape == ()


def test_early_completed_fill_still_mirrors_ghost(monkeypatch):
    """Regression: with the async queue disabled but the fill path
    active (sync_strict=False scope + BF_XFER_ASYNC=0), fills complete
    BEFORE the span closes; the ghost mirror for wrapped spans must
    still run (at attach), or readers of wrapped bytes see stale
    data."""
    monkeypatch.setenv('BF_XFER_ASYNC', '0')
    # Python ring core: its commit-time ghost mirror is SKIPPED for
    # spans carrying a fill (the fill owns mirroring), so an
    # early-completed fill relies entirely on the attach-time mirror.
    # (The native core re-mirrors inside bft_ring_commit, which runs
    # after a synchronously-completed fill's write — covered there.)
    monkeypatch.setenv('BF_NO_NATIVE', '1')
    from bifrost_tpu.ring import Ring
    rng = np.random.RandomState(21)
    data = rng.randn(24, 16).astype(np.float32)
    hdr = simple_header([-1, 16], 'f32', gulp_nframe=8)
    ring = Ring(space='system')
    eng = xfer.TransferEngine()
    with ring.begin_writing() as w:
        # 20-frame buffer, 8-frame spans: the third span ([16, 24))
        # wraps and writes frames 20-23 through the ghost region
        with w.begin_sequence(hdr, 8, 20) as seq:
            for g0 in (0, 8, 16):
                dev = eng.to_device(data[g0:g0 + 8])
                with seq.reserve(8) as sp:
                    fill = eng.host_fill(dev, 'f32',
                                         sp.data.as_numpy())
                    assert fill.done   # completed BEFORE close/attach
                    sp.set_fill(fill)
                    sp.commit(8)
            # a reader whose span starts INSIDE the wrapped region
            # ([18, 22)) reads the mirrored start-of-buffer bytes —
            # the path only the attach-time mirror feeds (a reader
            # framed like the writer reads back through the ghost
            # area directly and would never notice a missing mirror)
            with ring.open_earliest_sequence(guarantee=False) as rs:
                with rs.acquire(18, 4) as span:
                    got = np.array(span.data.as_numpy(), copy=True)
    np.testing.assert_allclose(got, data[18:22], rtol=1e-6)


def test_out_of_order_completion_drain():
    """Futures may be resolved in any order; the engine's drain retires
    whatever completed without disturbing the rest."""
    eng = xfer.TransferEngine(depth=16)
    arrs = [np.full((32, 32), i, np.float32) for i in range(8)]
    futs = [eng.to_host_async(eng.to_device(a)) for a in arrs]
    # resolve a scattered subset first, then drain, then the rest
    for i in (5, 1, 6, 2):
        assert np.array_equal(futs[i].result(), arrs[i])
    eng.drain()
    for i in (7, 0, 3, 4):
        assert np.array_equal(futs[i].result(), arrs[i])
    assert eng.outstanding == 0


def test_async_queue_bound_forces_oldest():
    """More than ``depth`` outstanding transfers retire the oldest
    first — bounded backpressure, not unbounded growth."""
    eng = xfer.TransferEngine(depth=2)
    futs = [eng.to_host_async(eng.to_device(
        np.full((16,), i, np.float32))) for i in range(6)]
    # the first four must have been forced by the bound
    assert all(f.done for f in futs[:4])
    assert eng.outstanding <= 2


def test_complex_roundtrip_via_futures():
    eng = xfer.TransferEngine()
    c = (np.random.RandomState(0).randn(32, 16) +
         1j * np.random.RandomState(1).randn(32, 16)).astype(np.complex64)
    fut = eng.to_host_async(eng.to_device(c))
    got = fut.result()
    assert got.dtype == np.complex64
    np.testing.assert_allclose(got, c, rtol=1e-6)


# ---------------------------------------------------------------------------
# deferred D2H ring fills through a real pipeline
# ---------------------------------------------------------------------------

def _chain_stages():
    from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage
    return [FftStage('fine_time', axis_labels='freq'),
            DetectStage('stokes', axis='pol'),
            ReduceStage('freq', 4)]


def _make_raw(nt=64, npol=2, nf=256, seed=7):
    rng = np.random.RandomState(seed)
    raw = np.zeros((nt, npol, nf), dtype=np.dtype([('re', 'i1'),
                                                   ('im', 'i1')]))
    raw['re'] = rng.randint(-64, 64, raw.shape)
    raw['im'] = rng.randint(-64, 64, raw.shape)
    return raw


def _run_chain(raw, ngulp=6, **scope):
    hdr = simple_header([-1, raw.shape[1], raw.shape[2]], 'ci8',
                        labels=['time', 'pol', 'fine_time'])
    with bf.Pipeline(**scope) as p:
        src = NumpySourceBlock([raw.copy() for _ in range(ngulp)], hdr,
                               gulp_nframe=raw.shape[0])
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(b, _chain_stages())
        b2 = bf.blocks.copy(fb, space='system')
        sink = GatherSink(b2)
        p.run()
    return sink.result(), fb


def test_async_d2h_fills_deliver_correct_data():
    """CopyBlock's deferred-fill D2H must deliver byte-identical data
    to the synchronous path, and must actually run async (d2h_async
    counter) with hard syncs bounded by sync_depth."""
    raw = _make_raw()
    out_async, _ = _run_chain(raw, ngulp=8, sync_depth=4)
    snap = counters.snapshot()
    assert snap.get('xfer.d2h_async', 0) >= 8
    waits = snap.get('pipeline.sync_waits', 0)
    dev_gulps = snap.get('pipeline.gulps_device', 1)
    assert waits <= dev_gulps / 4.0 + 1
    counters.reset()
    out_sync, _ = _run_chain(raw, ngulp=8, sync_depth=4,
                             sync_strict=True)
    assert np.array_equal(out_async, out_sync)


def test_sync_strict_fallback_is_synchronous():
    """sync_strict=True must route every D2H through the blocking path
    (no deferred fills, no async queue)."""
    raw = _make_raw(seed=3)
    _run_chain(raw, ngulp=4, sync_strict=True)
    assert counters.get('xfer.d2h_async') == 0


def test_strict_env_disables_async(monkeypatch):
    monkeypatch.setenv('BF_SYNC_STRICT', '1')
    assert not xfer.async_enabled()
    eng = xfer.TransferEngine()
    fut = eng.to_host_async(eng.to_device(np.ones(4, np.float32)))
    assert fut.done                     # completed synchronously


def test_partial_commit_fill_completes_synchronously():
    """A partially-committed span carrying a fill must complete it at
    close (the truncated tail's bytes roll back and become
    re-reservable — a deferred write there would corrupt the next
    span)."""
    from bifrost_tpu.ring import Ring
    rng = np.random.RandomState(8)
    data = rng.randn(8, 16).astype(np.float32)
    fresh = rng.randn(8, 16).astype(np.float32)
    hdr = simple_header([-1, 16], 'f32', gulp_nframe=8)
    ring = Ring(space='system')
    eng = xfer.TransferEngine(depth=16)
    with ring.begin_writing() as w:
        with w.begin_sequence(hdr, 8, 24) as seq:
            dev = eng.to_device(data)
            with seq.reserve(8) as sp:
                fill = eng.host_fill(dev, 'f32', sp.data.as_numpy())
                sp.set_fill(fill)
                sp.commit(4)            # partial: tail rolls back
            assert fill.done            # completed at close, not later
            # the rolled-back frames are re-reserved by the next span;
            # the old fill must not clobber them afterwards
            with seq.reserve(8) as sp2:
                sp2.data.as_numpy()[...] = fresh
                sp2.commit(8)
            eng.drain(block=True)
            with ring.open_earliest_sequence(guarantee=False) as rs:
                with rs.acquire(0, 12) as span:
                    got = np.array(span.data.as_numpy(), copy=True)
    np.testing.assert_allclose(got[:4], data[:4], rtol=1e-6)
    np.testing.assert_allclose(got[4:12], fresh, rtol=1e-6)


def test_host_fill_wraparound_ghost():
    """A deferred fill landing in a wrapped span must still mirror the
    ghost overflow so readers of the wrapped bytes see the data (the
    commit-time mirror ran before the bytes existed)."""
    # many small gulps through a deliberately tight ring forces wraps
    rng = np.random.RandomState(11)
    gulps = [rng.randn(8, 16).astype(np.float32) for _ in range(12)]
    hdr = simple_header([-1, 16], 'f32')
    with bf.Pipeline(buffer_nframe=20) as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    np.testing.assert_allclose(sink.result(),
                               np.concatenate(gulps, axis=0),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# buffer donation
# ---------------------------------------------------------------------------

def test_fused_chain_donation_bitexact_and_reported():
    """Acceptance: the donating fused chain reports donated inputs in
    its plan record and its output is bit-exact vs the non-donating
    path."""
    raw = _make_raw(seed=5)
    out_plain, fb_plain = _run_chain(raw, donate=False)
    assert 'donate_argnums' not in (fb_plain.impl_info or {})
    counters.reset()
    out_donate, fb_donate = _run_chain(raw, donate=True)
    assert (fb_donate.impl_info or {}).get('donate_argnums') == [0]
    assert counters.get('donation.hits') > 0
    assert np.array_equal(out_plain, out_donate)


def test_donation_roundtrip_ci8_planes():
    """ci8 device-rep gulps (int8 re/im planes) survive a donating
    identity-ish computation bit-exactly."""
    import jax.numpy as jnp
    from bifrost_tpu.devrep import to_device_rep, from_device_rep
    from bifrost_tpu.ops.common import donating_jit
    raw = _make_raw(nt=16, nf=32, seed=9)
    dev = to_device_rep(raw, 'ci8')
    ref = np.asarray(dev).copy()
    fn = donating_jit(lambda x: (x + jnp.int8(1)) - jnp.int8(1),
                      donate_argnums=(0,))
    out = fn(dev)
    assert dev.is_deleted()             # donated input is consumed
    assert np.array_equal(np.asarray(out), ref)
    back = np.zeros_like(raw)
    from_device_rep(out, 'ci8', back)
    assert np.array_equal(back, raw)


def test_donation_roundtrip_cf16_planes():
    """cf16 device-rep (complex64) round trip through a donating jit
    stays bit-exact."""
    from bifrost_tpu.devrep import to_device_rep, from_device_rep
    from bifrost_tpu.ops.common import donating_jit
    rng = np.random.RandomState(2)
    raw = np.zeros((16, 8), dtype=np.dtype([('re', 'f2'), ('im', 'f2')]))
    raw['re'] = rng.randn(16, 8).astype(np.float16)
    raw['im'] = rng.randn(16, 8).astype(np.float16)
    dev = to_device_rep(raw, 'cf16')
    ref = np.asarray(dev).copy()
    fn = donating_jit(lambda x: x * 1.0, donate_argnums=(0,))
    out = fn(dev)
    assert np.array_equal(np.asarray(out), ref)
    back = np.zeros_like(raw)
    from_device_rep(out, 'cf16', back)
    assert np.array_equal(back['re'], raw['re'])
    assert np.array_equal(back['im'], raw['im'])


def test_donation_denied_for_shared_chunks():
    """A ring chunk set WITHOUT owned=True (e.g. a source publishing a
    reused array) must never be taken for donation."""
    import jax.numpy as jnp
    from bifrost_tpu.ring import Ring
    ring = Ring(space='tpu')
    hdr = simple_header([-1, 4], 'f32', gulp_nframe=8)
    arr = jnp.ones((8, 4), jnp.float32)
    with ring.begin_writing() as w:
        with w.begin_sequence(hdr, 8, 24) as seq:
            with seq.reserve(8) as sp:
                sp.set(arr)             # owned defaults to False
                sp.commit(8)
            with ring.open_earliest_sequence(guarantee=True) as rs:
                with rs.acquire(0, 8) as ispan:
                    assert ispan.take_data() is None
                    assert np.array_equal(np.asarray(ispan.data),
                                          np.ones((8, 4), np.float32))


def test_donation_denied_with_second_reader():
    """Exclusivity: with two readers holding spans, take_data must
    refuse even owned chunks."""
    import jax.numpy as jnp
    from bifrost_tpu.ring import Ring
    ring = Ring(space='tpu')
    hdr = simple_header([-1, 4], 'f32', gulp_nframe=8)
    with ring.begin_writing() as w:
        with w.begin_sequence(hdr, 8, 24) as seq:
            with seq.reserve(8) as sp:
                sp.set(jnp.ones((8, 4), jnp.float32), owned=True)
                sp.commit(8)
            with ring.open_earliest_sequence(guarantee=True) as r1, \
                    ring.open_earliest_sequence(guarantee=True) as r2:
                with r1.acquire(0, 8) as s1, r2.acquire(0, 8) as s2:
                    assert s1.take_data() is None
                    assert s2.take_data() is None


def test_stage_block_donation_bitexact():
    """Unfused _StageBlock chains donate too: outputs bit-exact vs the
    non-donating run."""
    from bifrost_tpu.stages import FftStage, DetectStage

    def run(donate):
        raw = _make_raw(seed=13)
        hdr = simple_header([-1, 2, 256], 'ci8',
                            labels=['time', 'pol', 'fine_time'])
        with bf.Pipeline(donate=donate) as p:
            src = NumpySourceBlock([raw.copy() for _ in range(4)], hdr,
                                   gulp_nframe=64)
            b = bf.blocks.copy(src, space='tpu')
            b = bf.blocks.fft(b, 'fine_time', axis_labels='freq')
            b = bf.blocks.detect(b, 'stokes', axis='pol')
            b = bf.blocks.copy(b, space='system')
            sink = GatherSink(b)
            p.run()
        return sink.result()

    out0 = run(False)
    counters.reset()
    out1 = run(True)
    assert counters.get('donation.hits') > 0
    assert np.array_equal(out0, out1)
