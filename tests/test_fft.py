"""FFT oracle tests: every axes combination of 1D/2D/3D real & complex
transforms against np.fft (reference analogue: test/test_fft.py:147-210)."""

import itertools

import numpy as np
import pytest

from bifrost_tpu.ops.fft import Fft

RTOL, ATOL = 1e-4, 1e-4


def _run_c2c(shape, axes, inverse=False):
    rng = np.random.RandomState(hash((shape, tuple(axes))) % (2**31))
    x = (rng.randn(*shape) + 1j * rng.randn(*shape)).astype(np.complex64)
    plan = Fft().init(x, x, axes=list(axes))
    out = np.asarray(plan.execute(x, x.copy(), inverse=inverse))
    if inverse:
        expect = np.fft.ifftn(x, axes=axes) * np.prod(
            [shape[a] for a in axes])
    else:
        expect = np.fft.fftn(x, axes=axes)
    scale = max(np.abs(expect).max(), 1.0)
    np.testing.assert_allclose(out / scale, expect / scale,
                               rtol=RTOL, atol=ATOL)


def test_c2c_all_axes_combos():
    for ndim, shape in ((1, (64,)), (2, (16, 32)), (3, (8, 12, 16))):
        for r in range(1, ndim + 1):
            for axes in itertools.combinations(range(ndim), r):
                _run_c2c(shape, axes)


def test_c2c_inverse_unnormalized():
    _run_c2c((16, 32), (1,), inverse=True)
    _run_c2c((8, 12, 16), (1, 2), inverse=True)


def test_r2c_and_c2r():
    rng = np.random.RandomState(0)
    x = rng.randn(16, 64).astype(np.float32)
    out_tpl = np.zeros((16, 33), np.complex64)
    plan = Fft().init(x, out_tpl, axes=[1])
    out = np.asarray(plan.execute(x, out_tpl))
    np.testing.assert_allclose(out, np.fft.rfft(x, axis=1),
                               rtol=1e-3, atol=1e-3)
    # c2r (unnormalized, cuFFT convention)
    spec = np.fft.rfft(x, axis=1).astype(np.complex64)
    back_tpl = np.zeros((16, 64), np.float32)
    plan2 = Fft().init(spec, back_tpl, axes=[1])
    back = np.asarray(plan2.execute(spec, back_tpl))
    np.testing.assert_allclose(back / 64.0, x, rtol=1e-3, atol=1e-3)


def test_fftshift_fused():
    rng = np.random.RandomState(1)
    x = (rng.randn(8, 32) + 1j * rng.randn(8, 32)).astype(np.complex64)
    plan = Fft().init(x, x, axes=[1], apply_fftshift=True)
    out = np.asarray(plan.execute(x, x.copy()))
    np.testing.assert_allclose(
        out, np.fft.fftshift(np.fft.fft(x, axis=1), axes=[1]),
        rtol=1e-3, atol=1e-3)


def test_dft_matmul_fft_matches_fft():
    """The MXU DFT-matmul path (BF_FFT_IMPL=dftmm) matches jnp.fft for
    composite, prime, and pow2 lengths, both directions."""
    import jax
    import jax.numpy as jnp
    from bifrost_tpu.ops.fft import dft_matmul_fft
    rng = np.random.RandomState(11)
    for n in (256, 120, 97):
        x = (rng.randn(4, n) + 1j * rng.randn(4, n)).astype(np.complex64)
        got = np.asarray(jax.jit(
            lambda v: dft_matmul_fft(v, -1))(jnp.asarray(x)))
        np.testing.assert_allclose(got, np.fft.fft(x, axis=-1),
                                   rtol=2e-4, atol=2e-3)
        gi = np.asarray(jax.jit(
            lambda v: dft_matmul_fft(v, -1, inverse=True))(
                jnp.asarray(x)))
        np.testing.assert_allclose(gi, np.fft.ifft(x, axis=-1) * n,
                                   rtol=2e-4, atol=2e-3)


def test_fftn_dispatch_env_switch(monkeypatch):
    import jax
    import jax.numpy as jnp
    from bifrost_tpu.ops.fft import fftn_dispatch
    rng = np.random.RandomState(12)
    x = (rng.randn(4, 64) + 1j * rng.randn(4, 64)).astype(np.complex64)
    monkeypatch.setenv('BF_FFT_IMPL', 'dftmm')
    got = np.asarray(jax.jit(
        lambda v: fftn_dispatch(v, [-1]))(jnp.asarray(x)))
    np.testing.assert_allclose(got, np.fft.fft(x, axis=-1),
                               rtol=2e-4, atol=2e-3)
