"""Multi-host fabric: spec/verify, fan-out striping + re-striping,
fan-in interleave + gap marking, rejoin resume, membership, affinity,
and the proclog/telemetry host identity (bifrost_tpu.fabric;
docs/fabric.md)."""

from __future__ import annotations

import os
import socket
import threading
import time

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu import fabric, proclog
from bifrost_tpu.analysis.verify import verify_fabric
from bifrost_tpu.telemetry import counters, histograms

from util import NumpySourceBlock, GatherSink, simple_header

NT, NC = 4, 8
FRAME_NBYTE = NC * 4


@pytest.fixture(autouse=True)
def _fabric_env(tmp_path, monkeypatch):
    """Isolate durable fabric state per test and keep the membership
    timers snappy."""
    monkeypatch.setenv('BF_FABRIC_STATE', str(tmp_path / 'state'))
    monkeypatch.setenv('BF_FABRIC_HEARTBEAT_SECS', '0.05')
    monkeypatch.setenv('BF_FABRIC_DEADLINE_SECS', '0.4')
    monkeypatch.setenv('BF_FABRIC_REJOIN_CAP', '0.05')
    yield
    proclog.set_identity(None)


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(('127.0.0.1', 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _port_block(n, tries=64):
    """Base of n CONSECUTIVE free ports (fan endpoints use port+i)."""
    for _ in range(tries):
        socks = []
        try:
            s0 = socket.socket()
            s0.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s0.bind(('127.0.0.1', 0))
            base = s0.getsockname()[1]
            socks.append(s0)
            ok = True
            for i in range(1, n):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET,
                             socket.SO_REUSEADDR, 1)
                try:
                    s.bind(('127.0.0.1', base + i))
                except OSError:
                    s.close()
                    ok = False
                    break
                socks.append(s)
            if ok:
                return base
        finally:
            for s in socks:
                s.close()
    raise RuntimeError('no consecutive free ports')


def _gulps(origin, n, start=0):
    out = []
    for i in range(start, n):
        g = np.zeros((NT, NC), np.float32)
        g[:, 0] = origin
        g[:, 1] = np.arange(i * NT, (i + 1) * NT)
        out.append(g)
    return out


def _delta(before, key):
    return counters.get(key) - before.get(key, 0)


# ---------------------------------------------------------------------------
# spec + static verification
# ---------------------------------------------------------------------------

class TestFabricSpec:
    def test_roundtrip(self):
        spec = fabric.FabricSpec('t', hosts={
            'a': {'address': '10.0.0.1', 'control_port': 7000,
                  'cores': [0, 1], 'role': 'capture'},
            'b': {'address': '10.0.0.2', 'control_port': 7001},
        }, links={
            'l': {'kind': 'pipe', 'src': 'a', 'dst': 'b',
                  'port': 7100, 'window': 4, 'quota_mbps': 10.0,
                  'connect': {'b': ['10.9.9.9', 7200]}},
        })
        spec2 = fabric.FabricSpec.from_dict(spec.to_dict())
        assert spec2.hosts['a'].cores == [0, 1]
        assert spec2.links['l'].window == 4
        assert spec2.links['l'].dial_target(spec2, 'b', 0) == \
            ('10.9.9.9', 7200)
        assert spec2.to_dict() == spec.to_dict()

    def test_endpoint_views(self):
        spec = fabric.FabricSpec('t', hosts={
            'c0': {}, 'c1': {}, 'r': {}, 'l0': {}, 'l1': {},
        }, links={
            'in': {'kind': 'fanin', 'src': ['c0', 'c1'], 'dst': 'r',
                   'port': 7100},
            'out': {'kind': 'fanout', 'src': 'r',
                    'dst': ['l0', 'l1'], 'port': 7200},
        })
        assert [o for o, _ in spec.inbound_links('r')] == \
            [spec.links['in']] * 2
        assert spec.outbound_links('r') == [spec.links['out']]
        assert spec.inbound_links('l1')[0][1] == 1   # leg port offset
        assert spec.peers_of('r') == ['c0', 'c1', 'l0', 'l1']

    def test_unknown_kind_raises(self):
        with pytest.raises(fabric.FabricSpecError):
            fabric.LinkSpec('x', 'broadcast', 'a', 'b', 1)


class TestVerifyFabric:
    def _codes(self, diags):
        return sorted(d.code for d in diags)

    def test_endpoint_mismatch(self):
        spec = {'name': 't', 'hosts': {'a': {}},
                'links': {'l': {'kind': 'pipe', 'src': 'a',
                                'dst': 'ghost', 'port': 7100}}}
        assert 'BF-E200' in self._codes(verify_fabric(spec))

    def test_self_loop(self):
        spec = {'name': 't', 'hosts': {'a': {}},
                'links': {'l': {'kind': 'pipe', 'src': 'a',
                                'dst': 'a', 'port': 7100}}}
        assert 'BF-E200' in self._codes(verify_fabric(spec))

    def test_single_origin_fanin(self):
        spec = {'name': 't', 'hosts': {'a': {}, 'b': {}},
                'links': {'l': {'kind': 'fanin', 'src': ['a'],
                                'dst': 'b', 'port': 7100}}}
        assert 'BF-E200' in self._codes(verify_fabric(spec))

    def test_port_collision(self):
        # the fan-in's origin-1 endpoint (port+1) lands on b's
        # control port
        spec = {'name': 't',
                'hosts': {'a': {}, 'c': {},
                          'b': {'control_port': 7101}},
                'links': {'l': {'kind': 'fanin', 'src': ['a', 'c'],
                                'dst': 'b', 'port': 7100}}}
        assert 'BF-E201' in self._codes(verify_fabric(spec))

    def test_window_and_buffer_sizing(self):
        spec = {'name': 't', 'hosts': {'a': {}, 'b': {}},
                'links': {
                    'bad': {'kind': 'pipe', 'src': 'a', 'dst': 'b',
                            'port': 7100, 'window': 0},
                    'thin': {'kind': 'pipe', 'src': 'a', 'dst': 'b',
                             'port': 7200, 'window': 4,
                             'buffer_spans': 3}}}
        codes = self._codes(verify_fabric(spec))
        assert 'BF-E150' in codes and 'BF-W202' in codes

    def test_quota_below_span(self):
        spec = {'name': 't', 'hosts': {'a': {}, 'b': {}},
                'links': {'l': {'kind': 'pipe', 'src': 'a',
                                'dst': 'b', 'port': 7100,
                                'quota_mbps': 0.0001,
                                'gulp_nbyte': 1 << 20}}}
        assert 'BF-W203' in self._codes(verify_fabric(spec))

    def test_clean_spec(self):
        spec = {'name': 't',
                'hosts': {'a': {'control_port': 7001},
                          'b': {'control_port': 7002}},
                'links': {'l': {'kind': 'pipe', 'src': 'a',
                                'dst': 'b', 'port': 7100,
                                'window': 2}}}
        assert not [d for d in verify_fabric(spec) if d.is_error]


# ---------------------------------------------------------------------------
# loopback fabric: striping, re-striping, cross-host SLO
# ---------------------------------------------------------------------------

def _run_hosts(hosts):
    threads = {h: threading.Thread(
        target=fh.run, kwargs={'install_signals': False})
        for h, fh in hosts.items()}
    for t in threads.values():
        t.start()
    for t in threads.values():
        t.join(timeout=90)
    assert not any(t.is_alive() for t in threads.values()), \
        'fabric deadlocked: %s' % {h: t.is_alive()
                                   for h, t in threads.items()}


class TestFanOutLoopback:
    NSEQ = 6

    def _spec(self, nlegs, policy='block'):
        base = _port_block(nlegs)        # legs listen at base + i
        ports = [p for p in _free_ports(1 + nlegs)
                 if p not in range(base, base + nlegs)]
        while len(ports) < 1 + nlegs:
            ports += [p for p in _free_ports(1)
                      if p not in range(base, base + nlegs)]
        legs = ['leg%d' % i for i in range(nlegs)]
        hosts = {'src': {'address': '127.0.0.1',
                         'control_port': ports[0]}}
        for i, leg in enumerate(legs):
            hosts[leg] = {'address': '127.0.0.1',
                          'control_port': ports[1 + i]}
        return fabric.FabricSpec('fanout_t', hosts=hosts, links={
            'out': {'kind': 'fanout', 'src': 'src', 'dst': legs,
                    'port': base, 'window': 2,
                    'overload_policy': policy}})

    def _build(self, spec, dead_legs=()):
        sinks = {}
        legs = spec.links['out'].dst

        def build_src(ctx):
            hdr = simple_header([-1, NC], 'f32', name='stream',
                                gulp_nframe=NT)
            ctx.sink('out', _MultiSeqSource(self.NSEQ, hdr))

        def build_leg(leg):
            def b(ctx):
                sinks[leg] = GatherSink(ctx.source('out'))
            return b

        hosts = {}
        for leg in legs:
            hosts[leg] = fabric.FabricHost(spec, leg, build_leg(leg))
            hosts[leg].build()
        hosts['src'] = fabric.FabricHost(spec, 'src', build_src)
        hosts['src'].build()
        if dead_legs:
            # choreography stub: membership says these legs are dead
            fanout = [b for b in hosts['src'].pipeline.blocks
                      if isinstance(b, fabric.FanOutBlock)][0]
            fanout.membership = _StubMembership(dead_legs)
        return hosts, sinks

    def test_sequence_striping_and_fabric_slo(self):
        before = counters.snapshot()
        spec = self._spec(2)
        hosts, sinks = self._build(spec)
        _run_hosts(hosts)
        # sequences stripe round-robin: leg0 gets stripes 0,2,4...
        for i, leg in enumerate(('leg0', 'leg1')):
            stripes = [h['_fabric']['stripe']
                       for h in sinks[leg].headers]
            assert stripes == list(range(i, self.NSEQ, 2))
            assert all(h['_fabric']['leg'] == leg
                       for h in sinks[leg].headers)
        # lossless under 'block': every frame of every sequence lands
        total = sum(s.result().shape[0] for s in sinks.values())
        assert total == self.NSEQ * 4 * NT
        # the stream crossed a bridge hop: the cross-host fabric SLO
        # histogram recorded at the leg sinks (skew-corrected age)
        h = histograms.get('slo.fabric_exit_age_s')
        assert h is not None and h.count > 0
        assert _delta(before, 'fabric.fanout.sequences') == self.NSEQ

    def test_restripe_across_survivors_when_leg_dead(self):
        before = counters.snapshot()
        spec = self._spec(2)
        hosts, sinks = self._build(spec, dead_legs=('leg1',))
        _run_hosts(hosts)
        # every sequence re-striped onto the survivor, counted
        assert len(sinks['leg0'].headers) == self.NSEQ
        assert len(sinks['leg1'].headers) == 0
        assert _delta(before, 'fabric.fanout.restripes') == \
            self.NSEQ // 2
        total = sum(s.result().shape[0] for s in sinks.values()
                    if s.gulps)
        assert total == self.NSEQ * 4 * NT


class _MultiSeqSource(NumpySourceBlock):
    """NSEQ short sequences of 4 gulps each (fan-out stripes at
    sequence granularity)."""

    def __init__(self, nseq, hdr, **kwargs):
        NumpySourceBlock.__init__(self, [], hdr, NT, **kwargs)
        self.sourcenames = ['s%d' % i for i in range(nseq)]

    def create_reader(self, name):
        from util import _NumpyReader
        return _NumpyReader(_gulps(int(name[1:]), 4))

    def on_sequence(self, reader, name):
        hdr = dict(self._header)
        hdr['name'] = name
        return [hdr]


class _StubMembership(object):
    def __init__(self, dead):
        self.dead = set(dead)

    def is_dead(self, host):
        return host in self.dead


# ---------------------------------------------------------------------------
# fan-in: interleave, per-origin tagging, gap marking
# ---------------------------------------------------------------------------

class _StallingSource(NumpySourceBlock):
    """One sequence whose gulp stream stalls mid-sequence for
    ``stall_secs`` after ``stall_after`` gulps — the fan-in must mark
    the origin gapped (not stall the merge) and resume it as a tagged
    continuation."""

    def __init__(self, gulps, hdr, stall_after, stall_secs, **kw):
        NumpySourceBlock.__init__(self, gulps, hdr, NT, **kw)
        self._n = 0
        self._stall_after = stall_after
        self._stall_secs = stall_secs

    def on_data(self, reader, ospans):
        self._n += 1
        if self._n == self._stall_after + 1:
            time.sleep(self._stall_secs)
        return NumpySourceBlock.on_data(self, reader, ospans)


class TestFanIn:
    def test_interleave_tags_and_gap(self):
        before = counters.snapshot()
        with bf.Pipeline() as p:
            h0 = simple_header([-1, NC], 'f32', name='origA',
                               gulp_nframe=NT)
            h1 = simple_header([-1, NC], 'f32', name='origB',
                               gulp_nframe=NT)
            src0 = NumpySourceBlock(_gulps(0, 6), h0, NT)
            src1 = _StallingSource(_gulps(1, 6), h1, stall_after=2,
                                   stall_secs=0.8)
            fin = fabric.FanInBlock([src0, src1],
                                    origins=['hostA', 'hostB'],
                                    gap_secs=0.25, link='cap')
            sink = GatherSink(fin)
        p.run()
        # every frame arrives despite the gap (a gap is delay
        # disclosure, not loss)
        frames = np.concatenate(sink.gulps, axis=0)
        for origin in (0, 1):
            sel = np.sort(frames[frames[:, 0] == origin][:, 1])
            assert sel.shape[0] == 6 * NT
            assert (sel == np.arange(6 * NT)).all()
        # per-origin tagging
        origins = {(h['_fabric']['origin'], h['_fabric']['link'])
                   for h in sink.headers}
        assert origins == {('hostA', 'cap'), ('hostB', 'cap')}
        # the stalled origin was marked gapped and resumed as a
        # tagged continuation carrying the _overload disclosure
        assert _delta(before, 'fabric.fanin.gapped') >= 1
        resumed = [h for h in sink.headers
                   if h['_fabric'].get('resumed')]
        assert resumed
        stamped = [h for h in sink.headers
                   if (h.get('_overload') or {}).get('fabric_gapped')]
        assert stamped
        gapinfo = stamped[-1]['_overload']['fabric_gapped']
        assert 'hostB' in gapinfo and gapinfo['hostB']['gaps'] >= 1

    def test_origin_ordinals(self):
        with bf.Pipeline() as p:
            h0 = simple_header([-1, NC], 'f32', name='s',
                               gulp_nframe=NT)
            src = _MultiSeqSource(3, h0)
            fin = fabric.FanInBlock([src], origins=['solo'])
            sink = GatherSink(fin)
        p.run()
        ordinals = [h['_fabric']['origin_seq'] for h in sink.headers]
        assert ordinals == [0, 1, 2]


# ---------------------------------------------------------------------------
# whole-host rejoin: session adoption + resume probe + ack ledger
# ---------------------------------------------------------------------------

class TestRejoin:
    def test_rejoin_replays_only_unacked(self, tmp_path):
        """A sender dies without MSG_END mid-stream; a NEW sender
        (fresh session) probes the receiver's committed frontier and
        replays only the remainder — the receiver adopts the session
        and the merged stream is exactly-once."""
        from bifrost_tpu.io.bridge import (RingSender, query_resume,
                                           connect)
        from bifrost_tpu.ring import Ring, RingWriter
        before = counters.snapshot()

        with bf.Pipeline() as prx:
            bsrc = bf.blocks.bridge_source('127.0.0.1', 0,
                                           adopt_sessions=True)
            sink = GatherSink(bsrc)
        rx_thread = threading.Thread(target=prx.run)
        rx_thread.start()
        try:
            all_gulps = _gulps(7, 6)
            hdr = simple_header([-1, NC], 'f32', name='stream0',
                                gulp_nframe=NT)

            def send(gulps, end, expect_fail=False):
                ring = Ring(space='system', name=None)
                errors = []

                def pump():
                    s = RingSender(
                        ring,
                        dial=lambda: [connect('127.0.0.1',
                                              bsrc.port)])
                    try:
                        s.run()
                    except Exception as exc:
                        errors.append(exc)
                t = threading.Thread(target=pump)
                writer = RingWriter(ring)
                wseq = writer.begin_sequence(dict(hdr), NT,
                                             buf_nframe=8 * NT)
                t.start()
                for g in gulps:
                    span = wseq.reserve(NT)
                    span.data.as_numpy()[:] = g
                    span.commit(NT)
                    span.close()
                if end:
                    wseq.end()
                    ring.end_writing()
                    t.join(timeout=30)
                else:
                    # whole-host death: poison without MSG_END — the
                    # receiver must NOT treat the stream as complete
                    time.sleep(0.5)     # let the spans flush + ack
                    ring.poison(RuntimeError('host died'))
                    t.join(timeout=30)
                assert not t.is_alive()
                if expect_fail:
                    assert errors, 'sender should have died unclean'
                return errors

            # run 1: 3 of 6 gulps, then die without MSG_END
            send(all_gulps[:3], end=False, expect_fail=True)
            # rejoin probe: the receiver reports its committed
            # frontier for the sequence
            frontier = query_resume('127.0.0.1', bsrc.port,
                                    timeout=10.0)
            assert frontier.get('stream0') == 3 * NT
            # run 2 (new session): replay ONLY the unacked remainder
            start = frontier['stream0'] // NT
            errs = send(all_gulps[start:], end=True)
            assert not errs
            rx_thread.join(timeout=30)
            assert not rx_thread.is_alive()
        finally:
            if rx_thread.is_alive():
                prx.shutdown()
                rx_thread.join(timeout=10)
        frames = np.concatenate(sink.gulps, axis=0)
        idx = np.sort(frames[:, 1])
        assert (idx == np.arange(6 * NT)).all()       # exactly once
        assert _delta(before, 'bridge.rx.sessions_adopted') == 1

    def test_ack_ledger_durable(self, tmp_path, monkeypatch):
        monkeypatch.setenv('BF_FABRIC_STATE', str(tmp_path))
        led = fabric.AckLedger('fab', 'h', 'l')
        assert not led.has_history
        led.note_acked('s0', 0, 16, 1024)
        led.note_acked('s0', 16, 16, 1024)
        led.note_acked('s0', 0, 16, 1024)   # re-ack: frontier is max
        led.note_shed(2, 512)
        led.save(force=True)
        led2 = fabric.AckLedger('fab', 'h', 'l')
        assert led2.has_history
        assert led2.acked_frames('s0') == 32
        assert led2.shed_gulps == 2 and led2.shed_bytes == 512


# ---------------------------------------------------------------------------
# membership + affinity + identity
# ---------------------------------------------------------------------------

class TestMembership:
    def test_death_and_rejoin(self):
        ports = _free_ports(2)
        spec = fabric.FabricSpec('m', hosts={
            'a': {'address': '127.0.0.1', 'control_port': ports[0]},
            'b': {'address': '127.0.0.1', 'control_port': ports[1]},
        }, links={'l': {'kind': 'pipe', 'src': 'a', 'dst': 'b',
                        'port': 1}})
        before = counters.snapshot()
        ma = fabric.Membership(spec, 'a').start()
        mb = fabric.Membership(spec, 'b').start()
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    not ma.peers_snapshot()['b']['alive']:
                time.sleep(0.05)
            assert ma.peers_snapshot()['b']['alive']
            # a never-heartbeating peer is 'unknown', not dead — only
            # a peer that WAS alive can die
            assert not ma.is_dead('b')
            mb.stop()
            # the DETECTION (and its counter) lands on the membership
            # thread's next tick — poll the counted event, not the
            # client-side time math
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    _delta(before, 'fabric.peers.dead') < 1:
                time.sleep(0.05)
            assert ma.is_dead('b')
            assert _delta(before, 'fabric.peers.dead') >= 1
            # rejoin: a fresh membership on the same control port
            mb = fabric.Membership(spec, 'b').start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    _delta(before, 'fabric.peers.rejoined') < 1:
                time.sleep(0.05)
            assert not ma.is_dead('b')
            assert _delta(before, 'fabric.peers.rejoined') >= 1
        finally:
            ma.stop()
            mb.stop()


class TestAffinityAndIdentity:
    def test_affinity_applied_or_skipped(self):
        before = counters.snapshot()
        try:
            cores = sorted(os.sched_getaffinity(0))
        except AttributeError:
            cores = []
        host = fabric.HostSpec('h', cores=cores or [0])
        with bf.Pipeline() as p:
            src = NumpySourceBlock(
                _gulps(0, 1), simple_header([-1, NC], 'f32',
                                            gulp_nframe=NT), NT)
            GatherSink(src)
        state = fabric.apply_affinity(host, p)
        assert state in ('applied', 'skipped')
        key = 'fabric.affinity.%s' % state
        assert _delta(before, key) == 1
        if state == 'applied':
            assert all(b.core is not None for b in p.blocks)

    def test_no_cores_is_none(self):
        assert fabric.apply_affinity(fabric.HostSpec('h')) == 'none'

    def test_proclog_identity_layout(self, tmp_path, monkeypatch):
        monkeypatch.setenv('BF_PROCLOG_DIR', str(tmp_path))
        proclog.set_identity('nodeA', 'capture')
        try:
            entry = proclog.instance_name()
            assert entry == '%d@nodeA.capture' % os.getpid()
            assert proclog.entry_pid(entry) == os.getpid()
            assert proclog.entry_host(entry) == 'nodeA'
            log = proclog.ProcLog('fabric/testlog')
            log.update({'k': 1}, force=True)
            loaded = proclog.load_by_pid(os.getpid())
            assert loaded['fabric']['testlog']['k'] == 1
            # a full instance entry resolves too
            assert proclog.load_by_pid(entry)
        finally:
            proclog.set_identity(None)

    def test_identity_in_snapshot(self):
        from bifrost_tpu import telemetry
        proclog.set_identity('nodeB', 'reduce')
        try:
            ident = telemetry.snapshot()['identity']
            assert ident['fabric_host'] == 'nodeB'
            assert ident['fabric_role'] == 'reduce'
            assert ident['pid'] == os.getpid()
        finally:
            proclog.set_identity(None)


# ---------------------------------------------------------------------------
# verify-gate topology + overload stamp merge
# ---------------------------------------------------------------------------

class TestIntegration:
    def test_verify_topology_clean(self):
        import bench_suite
        pipelines = bench_suite.build_verify_topologies()[
            'config17_fabric']()
        assert len(pipelines) == 4
        for p in pipelines:
            errs = [d for d in p.validate() if d.is_error]
            assert not errs, 'fabric host %s: %s' % (p.name, errs)

    def test_overload_stamp_merges_upstream_fields(self):
        """A drop-policy ring's own _overload stamp must MERGE with an
        upstream stamp riding the header (the fan-in's fabric_gapped
        map), not replace it."""
        from bifrost_tpu.ring import Ring, RingWriter
        ring = Ring(space='system', name=None)
        ring.set_overload_policy('drop_oldest')
        hdr = simple_header([-1, NC], 'f32', gulp_nframe=NT)
        hdr['_overload'] = {'fabric_gapped': {'x': {'gaps': 1}}}
        writer = RingWriter(ring)
        wseq = writer.begin_sequence(hdr, NT, buf_nframe=4 * NT)
        stamped = wseq.header['_overload']
        assert stamped['fabric_gapped'] == {'x': {'gaps': 1}}
        assert stamped['policy'] == 'drop_oldest'
        wseq.end()
        ring.end_writing()
