"""Re-run the ring semantics tests against the pure-Python core (the
native C++ core is the default when built; both backends must stay
behavior-identical)."""

import pytest

import bifrost_tpu.native as native_mod
from tests import test_ring


@pytest.fixture(autouse=True)
def force_python_core(monkeypatch):
    monkeypatch.setattr(native_mod, '_lib', None)
    monkeypatch.setattr(native_mod, '_tried', True)
    yield


def test_python_core_selected():
    from bifrost_tpu.ring import Ring
    from bifrost_tpu.ring_native import NativeRing
    r = Ring(space='system')
    assert not isinstance(r, NativeRing)


test_write_read_simple = test_ring.test_write_read_simple
test_partial_final_span = test_ring.test_partial_final_span
test_multiple_sequences = test_ring.test_multiple_sequences
test_overlap_read = test_ring.test_overlap_read
test_ringlets = test_ring.test_ringlets
test_unguaranteed_overwrite_skip = test_ring.test_unguaranteed_overwrite_skip
test_resize_while_data_buffered = test_ring.test_resize_while_data_buffered


def test_native_core_is_default_when_available():
    """(sanity for the suite itself: without the monkeypatch the native
    core is used)"""
    # this test runs WITH the fixture, so just assert the fixture works
    assert native_mod.available() is False

test_partial_commit_with_outstanding_spans_is_clean_error = \
    test_ring.test_partial_commit_with_outstanding_spans_is_clean_error
test_partial_commit_on_newest_span_ok = \
    test_ring.test_partial_commit_on_newest_span_ok


def test_host_storage_ringlet_grow_preserves_lanes():
    """Growing nringlet during a live resize copies only the existing
    lanes (matches native/ring.cpp min-lane copy; ADVICE r1)."""
    import numpy as np
    from bifrost_tpu.ring import _HostStorage
    old = _HostStorage()
    old.allocate(16, 4, 1, 0, 0)
    old.buf[0, :8] = np.arange(8)
    new = _HostStorage()
    new.allocate(32, 4, 3, 0, 8, old=old)
    np.testing.assert_array_equal(new.buf[0, :8], np.arange(8))
    assert not new.buf[1:].any()
test_reserve_after_partial_commit_rejected = \
    test_ring.test_reserve_after_partial_commit_rejected

# multi-gulp (macro) span semantics must hold identically in the
# pure-Python core (macro-gulp execution reserves/acquires K gulps per
# ring operation — bifrost_tpu.macro)
test_macro_span_ghost_wrap = test_ring.test_macro_span_ghost_wrap
test_macro_commit_barrier_k2 = test_ring.test_macro_commit_barrier_k2
test_macro_blocked_acquire_partial_on_eod = \
    test_ring.test_macro_blocked_acquire_partial_on_eod
test_macro_blocked_reserve_wakes_on_poison = \
    test_ring.test_macro_blocked_reserve_wakes_on_poison
test_macro_overlap_history_ghost_wrap = \
    test_ring.test_macro_overlap_history_ghost_wrap
test_macro_overlap_history_eod_partial = \
    test_ring.test_macro_overlap_history_eod_partial
test_overlap_hold_ahead_grows_small_ring = \
    test_ring.test_overlap_hold_ahead_grows_small_ring
test_device_ring_take_tiling_macro_donation = \
    test_ring.test_device_ring_take_tiling_macro_donation

# credit-window span holds (io.bridge): the guarantee must pin at the
# oldest OPEN span in the pure-Python core exactly like the native one
test_multi_open_spans_pin_guarantee = \
    test_ring.test_multi_open_spans_pin_guarantee
test_open_span_survives_later_acquires = \
    test_ring.test_open_span_survives_later_acquires
test_out_of_order_span_release_frees_writer = \
    test_ring.test_out_of_order_span_release_frees_writer

# deferred (non-blocking) resize — the auto-tuner's retune protocol
# must defer identically in the pure-Python core (docs/autotune.md)
test_deferred_resize_defers_under_write_span = \
    test_ring.test_deferred_resize_defers_under_write_span
test_deferred_resize_defers_under_read_span = \
    test_ring.test_deferred_resize_defers_under_read_span
test_deferred_resize_applies_immediately_when_quiescent = \
    test_ring.test_deferred_resize_applies_immediately_when_quiescent
test_deferred_resize_multiple_open_spans_wait_for_all = \
    test_ring.test_deferred_resize_multiple_open_spans_wait_for_all
