"""Re-run the ring semantics tests against the pure-Python core (the
native C++ core is the default when built; both backends must stay
behavior-identical)."""

import pytest

import bifrost_tpu.native as native_mod
from tests import test_ring


@pytest.fixture(autouse=True)
def force_python_core(monkeypatch):
    monkeypatch.setattr(native_mod, '_lib', None)
    monkeypatch.setattr(native_mod, '_tried', True)
    yield


def test_python_core_selected():
    from bifrost_tpu.ring import Ring
    from bifrost_tpu.ring_native import NativeRing
    r = Ring(space='system')
    assert not isinstance(r, NativeRing)


test_write_read_simple = test_ring.test_write_read_simple
test_partial_final_span = test_ring.test_partial_final_span
test_multiple_sequences = test_ring.test_multiple_sequences
test_overlap_read = test_ring.test_overlap_read
test_ringlets = test_ring.test_ringlets
test_unguaranteed_overwrite_skip = test_ring.test_unguaranteed_overwrite_skip
test_resize_while_data_buffered = test_ring.test_resize_while_data_buffered


def test_native_core_is_default_when_available():
    """(sanity for the suite itself: without the monkeypatch the native
    core is used)"""
    # this test runs WITH the fixture, so just assert the fixture works
    assert native_mod.available() is False
