"""Every examples/ script is a runnable tutorial flow; run each in a
subprocess on the CPU backend (reference on-ramp analogue:
tutorial/ notebooks + testbench/ scripts)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, 'examples')


def _run(script, *args, env_extra=None, timeout=240):
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script)] + list(args),
        capture_output=True, text=True, env=env, timeout=timeout)


def test_your_first_block():
    res = _run('your_first_block.py')
    assert res.returncode == 0, res.stderr[-2000:]


def test_gpuspec_simple_demo(tmp_path):
    res = _run('gpuspec_simple.py', '--demo', str(tmp_path))
    assert res.returncode == 0, res.stderr[-2000:]
    assert 'wrote' in res.stdout
    assert (tmp_path / 'demo.raw.fil').exists()


def test_capture_spectrometer():
    res = _run('capture_spectrometer.py')
    assert res.returncode == 0, res.stderr[-2000:]
    assert 'detected tone at fine bin 37' in res.stdout


def test_mesh_spectrometer():
    res = _run('mesh_spectrometer.py', env_extra={
        'XLA_FLAGS': '--xla_force_host_platform_device_count=8'})
    assert res.returncode == 0, res.stderr[-2000:]


def test_fdmt_search():
    res = _run('fdmt_search.py')
    assert res.returncode == 0, res.stderr[-2000:]


def test_file_roundtrip(tmp_path):
    res = _run('file_roundtrip.py', str(tmp_path))
    assert res.returncode == 0, res.stderr[-2000:]
    assert 'file_roundtrip OK' in res.stdout


def test_serialize_replay(tmp_path):
    res = _run('serialize_replay.py', str(tmp_path))
    assert res.returncode == 0, res.stderr[-2000:]
    assert 'replay bit-identical to live run' in res.stdout
