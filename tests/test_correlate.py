"""FX-correlator tests (bench config 19; docs/perf.md "FX
correlator"): the raced X-engine against the exact int64 oracle, the
accuracy-class admission rules, the fused/macro chain's byte
stability, the corner-turn collective against the transpose oracle,
the zero-collective sharded channelizer, and the visibility-format
round trip against live correlator output."""

import os

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.ops import linalg as L

from util import NumpySourceBlock, GatherSink, simple_header


# (T, F, n) voltage-plane shapes for the oracle-parity sweep
SHAPES = [(8, 4, 6), (16, 3, 8), (12, 5, 4)]


def _planes(shape, seed=0):
    rng = np.random.RandomState(seed)
    re = rng.randint(-64, 64, shape).astype(np.int8)
    im = rng.randint(-64, 64, shape).astype(np.int8)
    return re, im


def _oracle_int(re, im):
    """The exactness reference: x @ x^H over time in int64, cast to
    complex64 (every sum is far below 2^24, so the cast is lossless)."""
    r = re.astype(np.int64)
    i = im.astype(np.int64)
    rr = np.einsum('tfi,tfj->fij', r, r) + np.einsum('tfi,tfj->fij',
                                                     i, i)
    ii = np.einsum('tfi,tfj->fij', i, r) - np.einsum('tfi,tfj->fij',
                                                     r, i)
    return (rr + 1j * ii).astype(np.complex64)


# ---------------------------------------------------------------------------
# X-engine candidates vs the exact oracle
# ---------------------------------------------------------------------------

EXACT_IMPLS = ['xla', 'planar', 'int8_3mm', 'int8_wide']


class TestXEngineOracle:
    @pytest.mark.parametrize('shape', SHAPES)
    @pytest.mark.parametrize('name', EXACT_IMPLS)
    def test_exact_candidates_bit_identical(self, shape, name):
        """Every non-lossy candidate is BIT-identical to the int64
        oracle on int8 planes — including the float lowerings, whose
        integer sums are exactly representable."""
        re, im = _planes(shape, seed=hash(shape) % 1000)
        eng = L.XEngine(accuracy='int8', impl=name)
        got = np.asarray(eng(re, im))
        np.testing.assert_array_equal(got, _oracle_int(re, im))

    def test_pallas_exact_on_tpu(self):
        import jax
        if jax.default_backend() != 'tpu':
            pytest.skip('pallas xcorr kernel is TPU-only')
        re, im = _planes(SHAPES[0])
        got = np.asarray(L.XEngine(accuracy='int8',
                                   impl='pallas')(re, im))
        np.testing.assert_array_equal(got, _oracle_int(re, im))

    def test_bf16_candidate_within_class(self):
        """The one-pass bf16 candidate is lossy by construction; it
        must sit inside its declared class bound vs the baseline."""
        re, im = _planes((16, 4, 8), seed=5)
        ref = _oracle_int(re, im)
        got = np.asarray(L.XEngine(accuracy='int8',
                                   impl='planar_bf16')(re, im))
        scale = float(np.max(np.abs(ref))) or 1.0
        assert float(np.max(np.abs(got - ref))) / scale \
            <= L.XCORR_CLASSES['bf16']

    def test_float_input_routes_float_path(self):
        """Float voltages cannot feed the int kernels: the engine
        must still match the oracle through its float baseline."""
        re, im = _planes((8, 3, 4), seed=2)
        eng = L.XEngine(accuracy='f32')
        got = np.asarray(eng(re.astype(np.float32),
                             im.astype(np.float32)))
        np.testing.assert_array_equal(got, _oracle_int(re, im))


class TestAccuracyClassGates:
    def test_f32_class_rejects_bf16_candidate(self):
        """'f32' admits only candidates whose construction error fits
        1e-3: the lossy one-pass bf16 GEMM is out..."""
        names = L.XEngine(accuracy='f32')._candidates(int_input=True)
        assert 'planar_bf16' not in names
        # ...but the EXACT int candidates race at every class
        assert 'int8_3mm' in names and 'int8_wide' in names

    def test_int8_class_admits_bf16_candidate(self):
        names = L.XEngine(accuracy='int8')._candidates(int_input=True)
        assert 'planar_bf16' in names

    def test_float_input_excludes_int_kernels(self):
        names = L.XEngine(accuracy='int8')._candidates(int_input=False)
        assert not (set(names) & L._XENGINE_INT_IMPLS)

    def test_lossy_set_is_only_bf16(self):
        assert L._XENGINE_LOSSY == frozenset(['planar_bf16'])

    def test_gate_rtol_env_override_keys_cache(self, monkeypatch):
        """BF_XCORR_GATE_RTOL changes the admitted set AND the probe
        key (a widened gate must not reuse a narrow gate's winner)."""
        eng = L.XEngine(accuracy='f32')
        base_key = eng._key((8, 4, 6), 'int8', True)
        monkeypatch.setenv('BF_XCORR_GATE_RTOL', '0.01')
        assert L.xcorr_class_rtol('f32') == 0.01
        widened = L.XEngine(accuracy='f32')._candidates(True)
        assert 'planar_bf16' in widened
        assert 'gate_rtol' in eng._key((8, 4, 6), 'int8', True)
        assert eng._key((8, 4, 6), 'int8', True) != base_key

    def test_bad_accuracy_rejected(self):
        with pytest.raises(ValueError):
            L.XEngine(accuracy='int4')


# ---------------------------------------------------------------------------
# the chain: F -> requantize -> X -> accumulate (blocks.correlate
# fusable form) — macro-gulp and segment byte stability
# ---------------------------------------------------------------------------

CNT, CNW, CNS, CNP = 16, 16, 4, 2
CR, CA = 4, 2


def _chain_volts(ngulp, seed=3):
    rng = np.random.RandomState(seed)
    gulps = []
    for _ in range(ngulp):
        raw = np.zeros((CNT, CNW, CNS, CNP),
                       dtype=np.dtype([('re', 'i1'), ('im', 'i1')]))
        raw['re'] = rng.randint(-64, 64, raw.shape)
        raw['im'] = rng.randint(-64, 64, raw.shape)
        gulps.append(raw)
    return gulps


def _chain_hdr():
    return simple_header([-1, CNW, CNS, CNP], 'ci8',
                         labels=['time', 'fine', 'station', 'pol'])


def _run_chain(ngulp=4, gulp_batch=1, segments=None, accuracy='int8'):
    with bf.Pipeline(gulp_batch=gulp_batch, segments=segments,
                     sync_depth=4) as p:
        src = NumpySourceBlock(_chain_volts(ngulp), _chain_hdr(),
                               gulp_nframe=CNT)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fft(b, axes='fine', axis_labels='freq')
        b = bf.blocks.quantize(b, 'ci8', scale=1. / CNW)
        b = bf.blocks.correlate(b, CR, accuracy=accuracy,
                                fusable=True)
        b = bf.blocks.accumulate(b, CA, fusable=True)
        sink = GatherSink(bf.blocks.copy(b, space='system'))
        p.run()
    return sink.result()


def _chain_oracle(ngulp=4):
    """Sequential reference: eager jnp F + quantize (the same XLA fft
    custom call the pipeline runs), then the int64 numpy X step."""
    import jax.numpy as jnp
    raw = np.concatenate(_chain_volts(ngulp), axis=0)
    v = raw['re'].astype(np.float32) + 1j * raw['im'].astype(np.float32)
    F = np.asarray(jnp.fft.fft(jnp.asarray(v), axis=1)) * \
        np.float32(1. / CNW)
    qr = np.clip(np.round(F.real), -128, 127).astype(np.int64)
    qi = np.clip(np.round(F.imag), -128, 127).astype(np.int64)
    n = CNS * CNP
    ntot = raw.shape[0]
    qr = qr.reshape(ntot // CR, CR, CNW, n)
    qi = qi.reshape(ntot // CR, CR, CNW, n)
    re = np.einsum('grfi,grfj->gfij', qr, qr) + \
        np.einsum('grfi,grfj->gfij', qi, qi)
    im = np.einsum('grfi,grfj->gfij', qi, qr) - \
        np.einsum('grfi,grfj->gfij', qr, qi)
    vis = (re + 1j * im).astype(np.complex64)
    vis = vis.reshape(-1, CA, CNW, n, n).sum(axis=1).astype(np.complex64)
    return vis.reshape(-1, CNW, CNS, CNP, CNS, CNP)


class TestCorrelatorChain:
    def test_chain_matches_sequential_oracle(self):
        got = _run_chain()
        np.testing.assert_array_equal(got, _chain_oracle())

    def test_macro_gulp_byte_identical(self):
        base = _run_chain(ngulp=4, gulp_batch=1)
        macro = _run_chain(ngulp=4, gulp_batch=4)
        np.testing.assert_array_equal(macro, base)

    def test_segment_fused_byte_identical(self):
        base = _run_chain(ngulp=4, segments='off')
        fused = _run_chain(ngulp=4, segments='force')
        np.testing.assert_array_equal(fused, base)

    def test_f32_arm_equals_int_arm(self):
        """Integer visibilities are exact in complex64: even the
        forced-float engine admits no tolerance on ci8 planes."""
        np.testing.assert_array_equal(_run_chain(accuracy='f32'),
                                      _run_chain(accuracy='int8'))

    def test_nondividing_integration_rejected(self):
        from bifrost_tpu.stages import CorrelateStage
        stage = CorrelateStage(5)
        hdr = simple_header([-1, CNW, CNS, CNP], 'ci8',
                            labels=['time', 'freq', 'station', 'pol'])
        stage.transform_header(hdr)       # header side is fine
        with pytest.raises(ValueError):
            stage.build({'shape': (16, CNW, CNS, CNP),
                         'dtype': 'int8'})


# ---------------------------------------------------------------------------
# corner turn vs the transpose oracle (CPU mesh; the pallas remote-DMA
# form needs real ICI and is raced only on TPU)
# ---------------------------------------------------------------------------

class TestCornerTurn:
    @pytest.mark.parametrize('impl', ['xla', 'ring'])
    def test_matches_transpose_oracle(self, impl):
        from bifrost_tpu.parallel import create_mesh, corner_turn
        mesh = create_mesh({'sp': 8})
        T, F = 16, 32
        rng = np.random.RandomState(7)
        x = rng.randint(-64, 64, (T, F, 3, 2)).astype(np.int8)
        fn = corner_turn(mesh, 'sp', impl=impl, stacked=True)
        got = np.asarray(fn(x))              # (D, T, F/D, 3, 2)
        fc = F // 8
        for d in range(8):
            np.testing.assert_array_equal(got[d],
                                          x[:, d * fc:(d + 1) * fc])

    def test_ring_equals_xla(self):
        from bifrost_tpu.parallel import create_mesh, corner_turn
        mesh = create_mesh({'sp': 8})
        rng = np.random.RandomState(8)
        x = (rng.randn(8, 16, 4) + 1j * rng.randn(8, 16, 4)) \
            .astype(np.complex64)
        a = np.asarray(corner_turn(mesh, 'sp', impl='xla',
                                   stacked=True)(x))
        b = np.asarray(corner_turn(mesh, 'sp', impl='ring',
                                   stacked=True)(x))
        np.testing.assert_array_equal(a, b)

    def test_ring_needs_static_ndev(self):
        import jax.numpy as jnp
        from bifrost_tpu.parallel import corner_turn_local
        with pytest.raises(ValueError):
            corner_turn_local(np.zeros((4, 8)), 'sp', impl='ring',
                              ndev=jnp.int32(8))

    def test_bad_impl_rejected(self):
        from bifrost_tpu.parallel import corner_turn_local
        with pytest.raises(ValueError):
            corner_turn_local(np.zeros((4, 8)), 'sp', impl='fft')


# ---------------------------------------------------------------------------
# cross-chip channelizer: decomposed DFT, channel-sharded, ZERO
# collectives inside a frame (compiled-HLO stats)
# ---------------------------------------------------------------------------

class TestShardedChannelizer:
    def test_exact_and_collective_free(self):
        import jax
        from bifrost_tpu.parallel import create_mesh, freq_sharded_dft
        from bifrost_tpu.parallel.scope import collective_counts
        mesh = create_mesh({'sp': 8})
        N = 64
        rng = np.random.RandomState(9)
        x = (rng.randn(4, N) + 1j * rng.randn(4, N)) \
            .astype(np.complex64)
        fn = freq_sharded_dft(mesh, N, axis_name='sp', nbatch=1)
        got = np.asarray(fn(x))
        ref = np.fft.fft(x, axis=-1)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-4)
        # the compiled program moves NO bytes between devices
        txt = jax.jit(fn).lower(x).compile().as_text()
        assert collective_counts(txt) == {}, collective_counts(txt)


# ---------------------------------------------------------------------------
# mesh-striped correlator: psum plan vs the corner-turn plan, both
# byte-equal to the single-device run
# ---------------------------------------------------------------------------

def _mesh_correlate(mesh, corner=None, monkeypatch=None):
    if corner is not None:
        monkeypatch.setenv('BF_XCORR_CORNER_TURN', corner)
    rng = np.random.RandomState(11)
    gulps = []
    for _ in range(2):
        raw = np.zeros((16, 8, 3, 2),
                       dtype=np.dtype([('re', 'i1'), ('im', 'i1')]))
        raw['re'] = rng.randint(-64, 64, raw.shape)
        raw['im'] = rng.randint(-64, 64, raw.shape)
        gulps.append(raw)
    hdr = simple_header([-1, 8, 3, 2], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=16)
    with bf.Pipeline() as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
        b = bf.blocks.copy(src, space='tpu')
        with bf.block_scope(mesh=mesh):
            b = bf.blocks.correlate(b, nframe_per_integration=16,
                                    accuracy='int8')
        sink = GatherSink(bf.blocks.copy(b, space='system'))
        p.run()
    return sink.result()


class TestMeshCorrelate:
    def test_psum_plan_matches_single(self):
        from bifrost_tpu.parallel import create_mesh
        base = _mesh_correlate(None)
        meshed = _mesh_correlate(create_mesh({'sp': 8}))
        np.testing.assert_array_equal(meshed, base)

    def test_corner_plan_matches_single(self, monkeypatch):
        from bifrost_tpu.parallel import create_mesh
        base = _mesh_correlate(None)
        meshed = _mesh_correlate(create_mesh({'sp': 8}), corner='xla',
                                 monkeypatch=monkeypatch)
        np.testing.assert_array_equal(meshed, base)

    def test_correlate_block_flags_collective_boundary(self):
        """The segment planner must see the mesh-resident correlator
        as a collective meeting point (BF-I191), never fuse across."""
        from bifrost_tpu.parallel import create_mesh
        from bifrost_tpu.blocks.correlate import CorrelateBlock
        with bf.Pipeline():
            src = NumpySourceBlock(
                [], simple_header([-1, 8, 3, 2], 'ci8',
                                  labels=['time', 'freq', 'station',
                                          'pol']), gulp_nframe=16)
            b = bf.blocks.copy(src, space='tpu')
            with bf.block_scope(mesh=create_mesh({'sp': 8})):
                corr = bf.blocks.correlate(b, 16)
            assert isinstance(corr, CorrelateBlock)
            assert corr._collective_boundary
            plain = bf.blocks.correlate(b, 16)
            assert not plain._collective_boundary


# ---------------------------------------------------------------------------
# visibility-format round trip against live correlator output
# ---------------------------------------------------------------------------

class TestConvertVisibilitiesRoundtrip:
    def _run(self, convert):
        with bf.Pipeline() as p:
            src = NumpySourceBlock(_chain_volts(2, seed=13),
                                   _chain_hdr(), gulp_nframe=CNT)
            b = bf.blocks.copy(src, space='tpu')
            b = bf.blocks.fft(b, axes='fine', axis_labels='freq')
            b = bf.blocks.quantize(b, 'ci8', scale=1. / CNW)
            b = bf.blocks.correlate(b, CR, accuracy='int8',
                                    fusable=True)
            if convert:
                b = bf.blocks.convert_visibilities(b, 'storage')
                if convert == 'roundtrip':
                    b = bf.blocks.convert_visibilities(b, 'matrix')
            sink = GatherSink(bf.blocks.copy(b, space='system'))
            p.run()
        return sink.result()

    def test_roundtrip_bit_identical(self):
        """matrix -> storage -> matrix over LIVE correlator output is
        the identity: the Stokes basis change halves exactly on the
        integer visibilities."""
        matrix = self._run(convert=None)
        back = self._run(convert='roundtrip')
        np.testing.assert_array_equal(back, matrix)

    def test_storage_packing_against_matrix(self):
        """The packed (time, baseline, freq, stokes) stream equals the
        IQUV combination of the full matrix's lower triangle."""
        matrix = self._run(convert=None)       # (t, f, s, p, s, p)
        storage = self._run(convert='storage')  # (t, nbl, f, 4)
        nbl = CNS * (CNS + 1) // 2
        assert storage.shape[1:] == (nbl, CNW, 4)
        k = 0
        for i in range(CNS):
            for j in range(i + 1):
                v = matrix[:, :, i, :, j, :]    # (t, f, 2, 2)
                I = v[..., 0, 0] + v[..., 1, 1]
                Q = v[..., 0, 0] - v[..., 1, 1]
                U = v[..., 0, 1] + v[..., 1, 0]
                V = (v[..., 0, 1] - v[..., 1, 0]) * 1j
                got = storage[:, k]             # (t, f, 4)
                np.testing.assert_array_equal(
                    got, np.stack([I, Q, U, V], axis=-1)
                    .astype(np.complex64))
                k += 1
