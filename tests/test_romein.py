"""Romein gridder tests vs a direct scatter oracle
(reference analogue: test/test_romein.py)."""

import numpy as np

from bifrost_tpu.ops import Romein


def _oracle(data, pos, kern, ngrid):
    grid = np.zeros((ngrid, ngrid), np.complex64)
    k = kern.shape[-1]
    for p in range(data.shape[0]):
        x0, y0 = pos[p]
        for dy in range(k):
            for dx in range(k):
                grid[(y0 + dy) % ngrid, (x0 + dx) % ngrid] += \
                    data[p] * kern[p, dy, dx]
    return grid


def test_gridding_matches_oracle():
    rng = np.random.RandomState(0)
    npts, ksize, ngrid = 50, 4, 32
    data = (rng.randn(npts) + 1j * rng.randn(npts)).astype(np.complex64)
    pos = rng.randint(0, ngrid - ksize, size=(npts, 2)).astype(np.int32)
    kern = (rng.randn(npts, ksize, ksize) +
            1j * rng.randn(npts, ksize, ksize)).astype(np.complex64)
    rom = Romein().init(pos, kern, ngrid)
    out = np.asarray(rom.execute(data))
    np.testing.assert_allclose(out, _oracle(data, pos, kern, ngrid),
                               rtol=1e-4, atol=1e-4)


def test_gridding_wraps_at_edge():
    rng = np.random.RandomState(1)
    npts, ksize, ngrid = 10, 3, 16
    data = np.ones(npts, np.complex64)
    pos = np.full((npts, 2), ngrid - 1, np.int32)   # kernel wraps
    kern = np.ones((npts, ksize, ksize), np.complex64)
    rom = Romein().init(pos, kern, ngrid)
    out = np.asarray(rom.execute(data))
    np.testing.assert_allclose(out, _oracle(data, pos, kern, ngrid),
                               rtol=1e-5)


def test_set_positions_and_kernels_update():
    """Plan updates between executes (reference: bfRomeinSetPositions /
    SetKernels, src/romein.cu:533-566)."""
    rng = np.random.RandomState(2)
    npts, ksize, ngrid = 20, 3, 24
    data = (rng.randn(npts) + 1j * rng.randn(npts)).astype(np.complex64)
    pos1 = rng.randint(0, ngrid - ksize, size=(npts, 2)).astype(np.int32)
    pos2 = rng.randint(0, ngrid - ksize, size=(npts, 2)).astype(np.int32)
    k1 = (rng.randn(npts, ksize, ksize) +
          1j * rng.randn(npts, ksize, ksize)).astype(np.complex64)
    k2 = (rng.randn(npts, ksize, ksize) +
          1j * rng.randn(npts, ksize, ksize)).astype(np.complex64)
    rom = Romein().init(pos1, k1, ngrid)
    np.testing.assert_allclose(np.asarray(rom.execute(data)),
                               _oracle(data, pos1, k1, ngrid),
                               rtol=1e-4, atol=1e-4)
    rom.set_positions(pos2)
    np.testing.assert_allclose(np.asarray(rom.execute(data)),
                               _oracle(data, pos2, k1, ngrid),
                               rtol=1e-4, atol=1e-4)
    rom.set_kernels(k2)
    np.testing.assert_allclose(np.asarray(rom.execute(data)),
                               _oracle(data, pos2, k2, ngrid),
                               rtol=1e-4, atol=1e-4)


def test_accumulate_into_existing_grid():
    """accumulate=True adds onto odata instead of zero-initializing
    (reference: romein.cu grid accumulation semantics)."""
    rng = np.random.RandomState(3)
    npts, ksize, ngrid = 15, 3, 16
    data = (rng.randn(npts) + 1j * rng.randn(npts)).astype(np.complex64)
    pos = rng.randint(0, ngrid - ksize, size=(npts, 2)).astype(np.int32)
    kern = (rng.randn(npts, ksize, ksize) +
            1j * rng.randn(npts, ksize, ksize)).astype(np.complex64)
    base = (rng.randn(ngrid, ngrid) +
            1j * rng.randn(ngrid, ngrid)).astype(np.complex64)
    rom = Romein().init(pos, kern, ngrid)
    out = np.empty((ngrid, ngrid), np.complex64)
    got = rom.execute(data, odata=base.copy(), accumulate=True)
    np.testing.assert_allclose(np.asarray(got),
                               base + _oracle(data, pos, kern, ngrid),
                               rtol=1e-4, atol=1e-4)


def test_batched_polarizations():
    """Leading batch axes (e.g. polarization) grid independently with
    shared positions/kernels."""
    rng = np.random.RandomState(4)
    npol, npts, ksize, ngrid = 2, 12, 3, 16
    data = (rng.randn(npol, npts) +
            1j * rng.randn(npol, npts)).astype(np.complex64)
    pos = rng.randint(0, ngrid - ksize, size=(npts, 2)).astype(np.int32)
    kern = (rng.randn(npts, ksize, ksize) +
            1j * rng.randn(npts, ksize, ksize)).astype(np.complex64)
    rom = Romein().init(pos, kern, ngrid)
    out = np.asarray(rom.execute(data))
    assert out.shape == (npol, ngrid, ngrid)
    for p in range(npol):
        np.testing.assert_allclose(out[p],
                                   _oracle(data[p], pos, kern, ngrid),
                                   rtol=1e-4, atol=1e-4)


def test_real_input_promotes():
    """Real float data grids into a complex grid."""
    rng = np.random.RandomState(5)
    npts, ksize, ngrid = 10, 2, 8
    data = rng.randn(npts).astype(np.float32)
    pos = rng.randint(0, ngrid - ksize, size=(npts, 2)).astype(np.int32)
    kern = np.ones((npts, ksize, ksize), np.complex64)
    rom = Romein().init(pos, kern, ngrid)
    out = np.asarray(rom.execute(data))
    np.testing.assert_allclose(
        out, _oracle(data.astype(np.complex64), pos, kern, ngrid),
        rtol=1e-5, atol=1e-5)
