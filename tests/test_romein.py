"""Romein gridder tests vs a direct scatter oracle
(reference analogue: test/test_romein.py)."""

import numpy as np

from bifrost_tpu.ops import Romein


def _oracle(data, pos, kern, ngrid):
    grid = np.zeros((ngrid, ngrid), np.complex64)
    k = kern.shape[-1]
    for p in range(data.shape[0]):
        x0, y0 = pos[p]
        for dy in range(k):
            for dx in range(k):
                grid[(y0 + dy) % ngrid, (x0 + dx) % ngrid] += \
                    data[p] * kern[p, dy, dx]
    return grid


def test_gridding_matches_oracle():
    rng = np.random.RandomState(0)
    npts, ksize, ngrid = 50, 4, 32
    data = (rng.randn(npts) + 1j * rng.randn(npts)).astype(np.complex64)
    pos = rng.randint(0, ngrid - ksize, size=(npts, 2)).astype(np.int32)
    kern = (rng.randn(npts, ksize, ksize) +
            1j * rng.randn(npts, ksize, ksize)).astype(np.complex64)
    rom = Romein().init(pos, kern, ngrid)
    out = np.asarray(rom.execute(data))
    np.testing.assert_allclose(out, _oracle(data, pos, kern, ngrid),
                               rtol=1e-4, atol=1e-4)


def test_gridding_wraps_at_edge():
    rng = np.random.RandomState(1)
    npts, ksize, ngrid = 10, 3, 16
    data = np.ones(npts, np.complex64)
    pos = np.full((npts, 2), ngrid - 1, np.int32)   # kernel wraps
    kern = np.ones((npts, ksize, ksize), np.complex64)
    rom = Romein().init(pos, kern, ngrid)
    out = np.asarray(rom.execute(data))
    np.testing.assert_allclose(out, _oracle(data, pos, kern, ngrid),
                               rtol=1e-5)
