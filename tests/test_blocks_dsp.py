"""Pipeline integration tests for the DSP blocks: fdmt (gulp overlap),
correlate (time integration), fir (state across gulps)."""

import numpy as np

import bifrost_tpu as bf
from tests.util import NumpySourceBlock, GatherSink, simple_header


def test_fdmt_block_with_overlap():
    """FDMT over a multi-gulp stream must equal FDMT over the whole
    stream (exercises define_input_overlap_nframe)."""
    from bifrost_tpu.ops.fdmt import Fdmt
    nchan, T = 8, 64
    rng = np.random.RandomState(0)
    x = rng.rand(nchan, T).astype(np.float32)   # (freq, time)

    # header: ['freq', 'time'] with time as the (last) frame axis
    hdr = {
        'name': 'fdmt-test', 'time_tag': 0,
        '_tensor': {
            'shape': [nchan, -1],
            'dtype': 'f32',
            'labels': ['freq', 'time'],
            'scales': [[100.0, 1.0], [0.0, 1e-3]],
            'units': ['MHz', 's'],
        },
    }
    # gulps along time (the ringlet layout: freq lanes)
    gulps = [x[:, i*16:(i+1)*16].copy() for i in range(4)]

    class FreqSource(bf.SourceBlock):
        def create_reader(self, name):
            class R:
                def __enter__(self):
                    return self

                def __exit__(self, *e):
                    return False
            return R()

        def on_sequence(self, reader, name):
            self.i = 0
            return [dict(hdr)]

        def on_data(self, reader, ospans):
            if self.i >= len(gulps):
                return [0]
            g = gulps[self.i]
            self.i += 1
            d = ospans[0].data.as_numpy()
            d[...] = g   # (freq, nframe)
            return [g.shape[1]]

    collected = []
    headers = []

    class DMSink(bf.SinkBlock):
        def on_sequence(self, iseq):
            headers.append(iseq.header)

        def on_data(self, ispan):
            from bifrost_tpu.xfer import to_host
            # span views are only valid while the span is held (same
            # semantics as the reference): copy before keeping
            collected.append(np.array(to_host(ispan.data), copy=True))

    with bf.Pipeline() as p:
        src = FreqSource(['x'], gulp_nframe=16)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fdmt(b, max_dm=0.15)  # -> max_delay ~9 frames
        b = bf.blocks.copy(b, space='system')
        DMSink(b)
        p.run()

    max_delay = headers[0]['_tensor']['shape'][-2]
    out = np.concatenate(collected, axis=-1)
    # oracle: full-stream FDMT, valid frames only
    plan = Fdmt().init(nchan, max_delay, 100.0, 1.0)
    full = np.asarray(plan.execute(x))
    n = out.shape[-1]
    np.testing.assert_allclose(out, full[:, :n], rtol=1e-4, atol=1e-3)
    assert n >= T - 2 * max_delay


def test_correlate_block_integration():
    T, F, S, P = 8, 4, 3, 2
    rng = np.random.RandomState(1)
    v = (rng.randn(T, F, S, P) + 1j * rng.randn(T, F, S, P)).astype(
        np.complex64)
    hdr = simple_header([-1, F, S, P], 'cf32',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=4)
    with bf.Pipeline() as p:
        src = NumpySourceBlock([v[:4], v[4:]], hdr, gulp_nframe=4)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.correlate(b, nframe_per_integration=8)
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    out = sink.result()
    assert out.shape == (1, F, S, P, S, P)
    vm = v.reshape(T, F, S * P)
    expect = np.einsum('tfi,tfj->fij', vm, vm.conj()).reshape(F, S, P, S, P)
    np.testing.assert_allclose(out[0], expect, rtol=1e-4)
    assert sink.headers[0]['matrix_fill_mode'] == 'full'


def test_correlate_block_ci8_integration():
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    T, F, S, P = 4, 2, 2, 2
    rng = np.random.RandomState(2)
    raw = np.zeros((T, F, S, P), dtype=ci8_dtype)
    raw['re'] = rng.randint(-8, 8, size=raw.shape)
    raw['im'] = rng.randint(-8, 8, size=raw.shape)
    hdr = simple_header([-1, F, S, P], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=4)
    with bf.Pipeline() as p:
        src = NumpySourceBlock([raw], hdr, gulp_nframe=4)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.correlate(b, nframe_per_integration=4)
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    out = sink.result()
    v = (raw['re'].astype(np.float64) + 1j * raw['im']).reshape(T, F, S * P)
    expect = np.einsum('tfi,tfj->fij', v, v.conj()).reshape(F, S, P, S, P)
    np.testing.assert_array_equal(out[0], expect.astype(np.complex64))


def test_fir_block_state():
    T, C = 32, 4
    rng = np.random.RandomState(3)
    x = rng.randn(T, C).astype(np.float32)
    coeffs = np.array([0.5, 0.3, 0.2], np.float32)
    hdr = simple_header([-1, C], 'f32')
    with bf.Pipeline() as p:
        src = NumpySourceBlock([x[:16], x[16:]], hdr, gulp_nframe=16)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fir(b, coeffs)
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    out = sink.result()
    xp = np.concatenate([np.zeros((2, C), np.float32), x])
    expect = sum(coeffs[t] * xp[2 - t:2 - t + T] for t in range(3))
    np.testing.assert_allclose(out, expect, rtol=1e-5)
