"""Golden-bytes wire-format tests.

Each fixture is a raw packet built BY HAND from the reference struct
layouts (src/formats/*.hpp) — independent of the codecs' pack() — so a
wire-layout error cannot cancel out in a pack->unpack round trip
(VERDICT r1 weakness 4).  Where the codec packs, the bytes are compared
against the same hand-built fixture."""

import math
import struct

import numpy as np
import pytest

from bifrost_tpu.io.packet_formats import (
    get_format, PacketDesc, ChipsFormat, TbnFormat, DrxFormat,
    Drx8Format, CorFormat, PBeamFormat, IBeamFormat, Snap2Format,
    VdifFormat, TbfFormat, VBeamFormat, SimpleFormat,
    TBN_FRAME_SIZE, DRX_FRAME_SIZE, DRX8_FRAME_SIZE)

SYNC_LE = struct.pack('<I', 0x5CDEC0DE)


def test_simple_golden():
    pld = bytes(range(16))
    wire = struct.pack('>Q', 9876543210) + pld
    d = SimpleFormat().unpack(wire)
    assert d.seq == 9876543210 and d.payload == pld
    assert SimpleFormat().pack(PacketDesc(seq=9876543210,
                                          payload=pld)) == wire


def test_chips_golden():
    # chips_hdr_type (chips.hpp:33-43): u8 roach(1b), u8 gbe, u8 nchan,
    # u8 nsubband, u8 subband, u8 nroach, u16be chan0, u64be seq(1b)
    pld = b'\xAB' * 64
    wire = (bytes([3, 1, 109, 1, 0, 16]) + struct.pack('>H', 0x1234) +
            struct.pack('>Q', 1000001) + pld)
    d = ChipsFormat().unpack(wire)
    assert d.src == 2            # roach - 1
    assert d.tuning == 1
    assert d.nchan == 109
    assert d.nsrc == 16
    assert d.chan0 == 0x1234
    assert d.seq == 1000000      # wire seq is 1-based
    assert d.payload == pld
    # filler mirror: roach = src+1, seq written verbatim
    packed = ChipsFormat().pack(PacketDesc(seq=1000001, src=2, nsrc=16,
                                           tuning=1, nchan=109,
                                           chan0=0x1234, payload=pld))
    assert packed == wire


def test_tbn_golden():
    # tbn_hdr_type (tbn.hpp:35-42): u32le sync, u32be framecount,
    # u32be tuning, u16be tbn_id(1b), u16be gain, u64be time_tag
    pld = bytes(range(256)) * 4            # 1024 bytes
    time_tag = 512 * 1234
    wire = (SYNC_LE + struct.pack('>IIHHQ', 42, 0x12345678, 5, 7,
                                  time_tag) + pld)
    assert len(wire) == TBN_FRAME_SIZE
    d = TbnFormat(decimation=1).unpack(wire)
    assert d.src == 4                       # (id & 1023) - 1
    assert d.tuning == 0x12345678
    assert d.gain == 7
    assert d.time_tag == time_tag
    assert d.seq == 1234                    # time_tag / decim / 512
    assert d.valid_mode == 0
    assert d.payload == pld
    # wrong frame size or sync word -> rejected like the reference
    assert TbnFormat().unpack(wire[:-1]) is None
    assert TbnFormat().unpack(b'\x00' * 4 + wire[4:]) is None
    packed = TbnFormat().pack(PacketDesc(seq=time_tag, src=4,
                                         tuning=0x12345678, gain=7,
                                         payload=pld), framecount=42)
    assert packed == wire


def test_drx_golden():
    # drx_hdr_type (drx.hpp:36-45): u32le sync, u32 frame_count_word
    # whose FIRST byte is the ID (beam 1-based bits0-2, tuning 1-based
    # bits3-5, pol bit7), u32be seconds, u16be decim, u16be time_offset,
    # u64be time_tag, u32be tuning_word, u32be flags
    pld = b'\x11' * 4096
    pkt_id = 2 | (2 << 3) | (1 << 7)        # beam 2, tuning 2, pol 1
    wire = (SYNC_LE + bytes([pkt_id, 0, 0, 0]) +
            struct.pack('>IHHQII', 0, 10, 4, 40960004, 0xCAFEBABE, 0) +
            pld)
    assert len(wire) == DRX_FRAME_SIZE
    d = DrxFormat().unpack(wire)
    assert d.beam == 1                      # (id & 7) - 1
    assert d.src == 3                       # ((tune-1) << 1) | pol
    assert d.time_tag == 40960000           # time_tag - time_offset
    assert d.decimation == 10
    assert d.seq == 40960000 // 10 // 4096
    assert d.tuning1 == 0xCAFEBABE          # src//2 != 0 -> tuning1
    assert d.tuning == 0
    assert d.payload == pld
    assert DrxFormat().unpack(wire[:-1]) is None


def test_drx8_golden():
    pld = b'\x22' * 8192
    pkt_id = 1 | (1 << 3)                   # beam 1, tuning 1, pol 0
    wire = (SYNC_LE + bytes([pkt_id, 0, 0, 0]) +
            struct.pack('>IHHQII', 0, 1, 0, 8192, 0xDEADBEEF, 0) + pld)
    assert len(wire) == DRX8_FRAME_SIZE
    d = Drx8Format().unpack(wire)
    assert d.src == 0 and d.beam == 0
    assert d.seq == 8192 // 1 // 4096
    assert d.tuning == 0xDEADBEEF           # src//2 == 0 -> tuning
    assert d.payload == pld


def test_cor_golden():
    # cor_hdr_type (cor.hpp:33-44): u32le sync, u32be fcw
    # (0x02<<24 | nchan_decim<<16 | nserver<<8 | server), u32be secs,
    # u16be first_chan, u16be gain, u64be time_tag, u32be navg,
    # u16be stand0(1b), u16be stand1(1b)
    nvis = 4
    pld = b'\x00' * (32 * nvis)             # 4 chans of 4x cf64
    fcw = (0x02 << 24) | (0 << 16) | (2 << 8) | 2
    time_tag = 196000000 * 2 * 50
    wire = (SYNC_LE + struct.pack('>IIHHQIHH', fcw, 0, 100, 9,
                                  time_tag, 200, 1, 2) + pld)
    fmt = CorFormat(nsrc=6)                 # 3 baselines x 2 servers
    d = fmt.unpack(wire)
    assert d.seq == 50                      # tt / 196e6 / (navg/100)
    assert d.decimation == 200
    assert d.gain == 9
    assert d.nchan == nvis
    # stand0=0, stand1=1, nstand=2: baseline idx (0*(2+1-0)/2 + 1 + 1)=2
    # src = 2*nserver + (server-1) = 5
    assert d.src == 5
    assert d.tuning == (2 << 8) | 1
    assert d.chan0 == 100                   # nchan_decim == 0
    assert d.payload == pld


def test_pbeam_golden():
    # pbeam_hdr_type (pbeam.hpp:33-46): u8 server(1b), u8 beam(1b),
    # u8 gbe, u8 nchan, u8 nbeam, u8 nserver, u16be navg, u16be chan0,
    # u64be seq(timestamp)
    pld = b'\x07' * 436
    wire = (bytes([2, 1, 0, 109, 2, 3]) +
            struct.pack('>HHQ', 24, 109 * 4, 24 * 777) + pld)
    d = PBeamFormat().unpack(wire)
    assert d.decimation == 24
    assert d.seq == 777                     # wire_seq / navg
    assert d.src == 1 * 3 + (2 - 1)         # beam*nserver + server-1
    assert d.nchan == 109
    assert d.chan0 == 109 * 4 - 109 * d.src
    assert d.payload == pld


def test_ibeam_golden():
    # ibeam_hdr_type (ibeam.hpp:33-41): u8 server(1b), u8 gbe, u8 nchan,
    # u8 nbeam, u8 nserver, u16be chan0(global), u64be seq(1b)
    pld = b'\x33' * 128
    wire = (bytes([4, 1, 96, 1, 6]) + struct.pack('>HQ', 96 * 3 + 50,
                                                  2001) + pld)
    d = IBeamFormat().unpack(wire)
    assert d.src == 3                       # server - 1
    assert d.seq == 2000                    # wire seq 1-based
    assert d.nsrc == 6
    assert d.nchan == 96
    assert d.chan0 == 50                    # global - nchan*src
    assert d.payload == pld
    # filler mirror: seq written verbatim (1-based wire convention)
    packed = IBeamFormat().pack(PacketDesc(seq=2001, src=3, nsrc=6,
                                           tuning=1, nchan=96, chan0=50,
                                           payload=pld))
    assert packed == wire


def test_snap2_golden():
    # snap2_hdr_type (snap2.hpp:50-60), big-endian per the decoder:
    # u64 seq, u32 sync_time, u16 npol, u16 npol_tot, u16 nchan,
    # u16 nchan_tot, u32 chan_block_id, u32 chan0, u32 pol0
    pld = b'\x44' * 512
    wire = struct.pack('>QIHHHHIII', 31337, 1700000000, 2, 4, 96, 192,
                       1, 384, 2) + pld
    d = Snap2Format().unpack(wire)
    assert d.seq == 31337
    assert d.time_tag == 1700000000
    assert d.npol == 2 and d.npol_tot == 4
    assert d.nchan == 96 and d.nchan_tot == 192
    assert d.chan0 == 96                    # chan_block_id * nchan
    assert d.tuning == 384                  # wire chan0 rides tuning
    # src = pol0//npol + chan_block_id*npol_blocks = 1 + 1*2
    assert d.src == 3
    assert d.nsrc == 4                      # npol_blocks * nchan_blocks
    assert d.payload == pld


def test_vdif_golden():
    # VDIF spec: 4 LE words with LSB-first bitfields + 16B ext header
    pld = b'\x55' * 64
    secs, fnum = 100, 7
    w0 = secs                               # legacy=0, invalid=0
    w1 = fnum | (2 << 24)                   # ref_epoch=2
    w2 = ((32 + 64) // 8) | (1 << 24)       # frame_length/8, log2_nchan=1
    w3 = 0x4142 | (5 << 16) | (7 << 26) | (1 << 31)
    wire = struct.pack('<4I', w0, w1, w2, w3) + b'\x00' * 16 + pld
    fmt = VdifFormat(frames_per_second=25600)
    d = fmt.unpack(wire)
    assert d.seq == 100 * 25600 + 7
    assert d.src == 5                       # thread_id
    assert d.chan0 == 2                     # 1 << log2_nchan
    assert d.tuning == (2 << 16) | (8 << 8) | 1
    assert d.payload == pld
    # invalid flag rejects
    bad = struct.pack('<I', w0 | (1 << 31)) + wire[4:]
    assert fmt.unpack(bad) is None
    # legacy frame: payload starts right after the 16-byte base header
    lw = struct.pack('<4I', w0 | (1 << 30), w1, w2, w3) + pld
    dl = fmt.unpack(lw)
    assert dl.payload == pld
    packed = VdifFormat(frames_per_second=25600, log2_nchan=1, nbit=8,
                        is_complex=True, station_id=0x4142,
                        ref_epoch=2).pack(
        PacketDesc(seq=100 * 25600 + 7, src=5, payload=pld))
    assert packed == wire


def test_tbf_golden():
    # tbf_hdr_type (tbf.hpp:33-41): u32le sync, u32be fcw (flag 0x01),
    # u32be secs, u16be first_chan, u16be nstand, u64be time_tag
    pld = b'\x66' * 6144
    fcw = (0x01 << 24) | 5
    wire = SYNC_LE + struct.pack('>IIHHQ', fcw, 0, 300, 64, 123456) + pld
    d = TbfFormat().unpack(wire)
    assert d.seq == 123456
    assert d.src == 300                     # first_chan rides src
    assert d.nsrc == 64
    assert d.payload == pld
    packed = TbfFormat().pack(PacketDesc(seq=123456, src=300, nsrc=64,
                                         payload=pld), framecount=5)
    assert packed == wire


def test_vbeam_golden():
    # vbeam_hdr_type (vbeam.hpp:33-42): u64le sync 0xAABBCCDD00000000,
    # u64le sync_time, u64be time_tag, f64 bw, f64 sfreq, u32le nchan,
    # u32le chan0, u32le npol
    pld = b'\x77' * 256
    wire = (struct.pack('<QQ', 0xAABBCCDD00000000, 1700000000) +
            struct.pack('>Q', 555) +
            struct.pack('<ddIII', 0.0, 0.0, 32, 64, 2) + pld)
    d = VBeamFormat().unpack(wire)
    assert d.seq == 555
    assert d.time_tag == 1700000000
    assert d.nchan == 32 and d.chan0 == 64 and d.npol == 2
    assert d.payload == pld
    packed = VBeamFormat().pack(PacketDesc(seq=555, time_tag=1700000000,
                                           nchan=32, chan0=64, npol=2,
                                           payload=pld))
    assert packed == wire


def test_header_sizes_match_reference_structs():
    """sizeof(packed struct) from the reference headers."""
    assert ChipsFormat().header_size == 16    # chips.hpp:33
    assert TbnFormat().header_size == 24      # tbn.hpp:35
    assert DrxFormat().header_size == 32      # drx.hpp:36
    assert Drx8Format().header_size == 32     # drx8.hpp:36
    assert CorFormat().header_size == 32      # cor.hpp:33
    assert PBeamFormat().header_size == 18    # pbeam.hpp:33
    assert IBeamFormat().header_size == 15    # ibeam.hpp:33
    assert Snap2Format().header_size == 32    # snap2.hpp:50
    # non-legacy VDIF = 16B base + 16B extended header (vdif.hpp)
    assert VdifFormat().header_size == 32
    assert VdifFormat(legacy=True).header_size == 16
    assert TbfFormat().header_size == 24      # tbf.hpp:33
    assert VBeamFormat().header_size == 52    # vbeam.hpp:33
    assert SimpleFormat().header_size == 8    # simple.hpp:33


def test_drx_pack_id_byte_position():
    """The DRX filler stores the raw ID in the first byte of the
    frame_count_word (drx.hpp:165: htobe32(id << 24))."""
    pld = b'\x00' * 4096
    pkt = DrxFormat().pack(PacketDesc(seq=0, src=0x91, decimation=10,
                                      tuning=1, payload=pld))
    assert len(pkt) == DRX_FRAME_SIZE
    assert pkt[:4] == SYNC_LE
    assert pkt[4] == 0x91 & 0xBF            # bit 6 masked off


def test_cor_pack_stand_recovery():
    """CORHeaderFiller inverts the baseline index to a 1-based stand
    pair (cor.hpp:123-130)."""
    fmt = CorFormat(nsrc=6)
    pkt = fmt.pack(PacketDesc(seq=0, src=2, nsrc=3, tuning=(2 << 8) | 1,
                              decimation=200, payload=b''))
    stand0, stand1 = struct.unpack_from('>HH', pkt, 28)
    # nsrc=3 baselines -> N=2; src=2 -> (1,1) -> wire (2,2)
    assert (stand0, stand1) == (2, 2)


def test_pbeam_src0_in_beam_units():
    """The reference subtracts src0 from the wire beam BEFORE scaling
    by nserver (pbeam.hpp:70: (beam - src0) * nserver + server - 1)."""
    pld = b'\x01' * 32
    # server=2, beam=2, nserver=3
    wire = (bytes([2, 2, 0, 8, 2, 3]) +
            struct.pack('>HHQ', 24, 0, 24 * 5) + pld)
    assert PBeamFormat().unpack(wire).src == 2 * 3 + 1
    assert PBeamFormat(src0=1).unpack(wire).src == (2 - 1) * 3 + 1
    # a flat post-decode rebase would have produced 2*3+1-1 == 6
    assert PBeamFormat(src0=1).unpack(wire).src != 6


def test_cor_src0_in_baseline_units():
    """cor.hpp:77-78: src = (baseline + 1 - src0)*nserver + server-1."""
    fmt0 = CorFormat(nsrc=6)
    pkt = fmt0.pack(PacketDesc(seq=0, src=2, nsrc=3,
                               tuning=(2 << 8) | 1, decimation=200,
                               payload=b''))
    base = fmt0.unpack(pkt).src
    shifted = CorFormat(nsrc=6, src0=1).unpack(pkt).src
    # one baseline unit = nserver composed sources
    assert base - shifted == 2


def test_capture_engine_delegates_src0_to_composed_formats():
    """_PacketCapture must push src0 into pbeam/cor codecs (which apply
    it in composed units) instead of flat-rebasing afterwards."""
    from bifrost_tpu.io.packet_capture import _PacketCapture

    class _FakeRing:
        name = 'src0-delegation-test'

    cap = _PacketCapture('pbeam', _FakeRing(), nsrc=8, src0=2,
                         max_payload_size=64, buffer_ntime=4,
                         slot_ntime=4, sequence_callback=lambda d: None)
    assert cap.src0 == 0
    assert cap.fmt.src0 == 2
    # the registry singleton must not have been mutated
    from bifrost_tpu.io.packet_formats import get_format
    assert get_format('pbeam').src0 == 0
