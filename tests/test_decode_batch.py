"""decode_batch <-> unpack parity for the full wire-format gallery.

Every codec's vectorized decoder (the recvmmsg fast path) must match the
scalar decoder field-for-field on packed wire bytes — including the
conventions that are easy to lose in a rewrite: chips/ibeam 1-based wire
seq, pbeam/cor composed src with src0 in wire units, tbn/drx frame-size
and sync gating, and vdif's no-uniform-offset ValueError on mixed legacy
framing.  Plus sharded-capture ledger exactness: every blasted packet is
accounted as exactly one of good / missing / late / alien."""

import struct
import threading
import time

import numpy as np
import pytest

from bifrost_tpu.io.packet_formats import (
    get_format, PacketDesc, SimpleFormat, ChipsFormat, PBeamFormat,
    TbnFormat, DrxFormat, Drx8Format, IBeamFormat, CorFormat,
    Snap2Format, VdifFormat, TbfFormat, VBeamFormat,
    TBN_FRAME_SIZE, DRX_FRAME_SIZE, DRX8_FRAME_SIZE)

SYNC_LE = struct.pack('<I', 0x5CDEC0DE)


def _batch(pkts):
    """Pack equal-length wire packets into the (npkt, pkt_bytes) uint8
    array decode_batch receives from the recvmmsg ring."""
    assert len({len(p) for p in pkts}) == 1
    return np.frombuffer(b''.join(pkts), np.uint8).reshape(len(pkts), -1)


def _assert_parity(fmt, pkts, expect_invalid=()):
    """decode_batch's per-row (seq, src, payload) must equal unpack's,
    and the validity mask (when returned) must flag exactly the rows
    unpack rejects."""
    arr = _batch(pkts)
    out = fmt.decode_batch(arr)
    seqs, srcs, hoff = out[0], out[1], out[2]
    valid = out[3] if len(out) > 3 else np.ones(len(pkts), bool)
    for i, pkt in enumerate(pkts):
        d = fmt.unpack(pkt)
        if i in expect_invalid:
            assert d is None or getattr(d, 'valid_mode', 0), \
                'row %d: scalar decoder accepted a packet the batch ' \
                'decoder must reject' % i
            assert not valid[i], 'row %d not flagged invalid' % i
            continue
        assert valid[i], 'row %d flagged invalid' % i
        assert int(seqs[i]) == d.seq, \
            'row %d seq: batch %d != scalar %d' % (i, seqs[i], d.seq)
        assert int(srcs[i]) == d.src, \
            'row %d src: batch %d != scalar %d' % (i, srcs[i], d.src)
        assert bytes(pkt[hoff:]) == bytes(d.payload), \
            'row %d payload offset %d mismatches scalar split' % (i, hoff)
    return seqs, srcs, hoff, valid


def test_simple_parity():
    fmt = SimpleFormat()
    pkts = [fmt.pack(PacketDesc(seq=s, payload=bytes([s & 0xFF]) * 32))
            for s in (0, 1, 7, 2**40 + 3)]
    _assert_parity(fmt, pkts)


def test_chips_parity_one_based_seq():
    fmt = ChipsFormat()
    pld = b'\xAB' * 64
    pkts = [fmt.pack(PacketDesc(seq=s, src=src, nsrc=16, tuning=1,
                                nchan=109, chan0=0x1234, payload=pld))
            for s, src in ((1, 0), (1000001, 2), (2**33, 15), (5, 7))]
    seqs, srcs, _, _ = _assert_parity(fmt, pkts)
    # the wire carries 1-based values; decoded fields are 0-based
    assert int(seqs[1]) == 1000000 and int(srcs[1]) == 2


def test_ibeam_parity_one_based_seq():
    fmt = IBeamFormat(nbeam=1)
    pld = b'\x21' * 96
    pkts = [fmt.pack(PacketDesc(seq=s, src=src, nsrc=6, tuning=1,
                                nchan=96, chan0=50, payload=pld))
            for s, src in ((2001, 3), (1, 0), (77, 5))]
    seqs, srcs, _, _ = _assert_parity(fmt, pkts)
    assert int(seqs[0]) == 2000 and int(srcs[0]) == 3


def test_pbeam_parity_composed_src():
    # nbeam=2, nsrc=6 -> nserver=3; src composes the 1-based wire
    # (beam, server) pair
    fmt = PBeamFormat(nbeam=2)
    pld = b'\x07' * 436
    pkts = [fmt.pack(PacketDesc(seq=24 * k, src=src, nsrc=6, tuning=0,
                                nchan=109, decimation=24, chan0=436,
                                payload=pld))
            for k, src in ((777, 0), (778, 4), (779, 5), (780, 2))]
    _assert_parity(fmt, pkts)


def test_pbeam_batch_applies_src0_in_wire_beam_units():
    """src0 subtracts from the wire beam BEFORE the nserver scaling
    (pbeam.hpp:70) — in the batch decoder too."""
    pld = b'\x01' * 32
    wire = (bytes([2, 2, 0, 8, 2, 3]) +
            struct.pack('>HHQ', 24, 0, 24 * 5) + pld)
    arr = _batch([wire])
    for src0 in (0, 1):
        fmt = PBeamFormat(src0=src0)
        seqs, srcs, _ = fmt.decode_batch(arr)
        d = fmt.unpack(wire)
        assert int(srcs[0]) == d.src == (2 - src0) * 3 + 1
        assert int(seqs[0]) == d.seq == 5


def test_tbn_parity_and_frame_gates():
    fmt = TbnFormat(decimation=1)
    pld = bytes(range(256)) * 4
    pkts = [fmt.pack(PacketDesc(seq=512 * k, src=src, tuning=0x12345678,
                                gain=7, payload=pld), framecount=k)
            for k, src in ((1234, 4), (1235, 0), (1236, 31))]
    # corrupt sync word on the last row: scalar decoder returns None,
    # batch decoder must mark the row invalid
    bad = b'\x00\x00\x00\x00' + pkts[-1][4:]
    pkts = pkts[:-1] + [bad]
    assert len(pkts[0]) == TBN_FRAME_SIZE
    _assert_parity(fmt, pkts, expect_invalid={2})
    # wrong datagram size rejects every row, like unpack's length gate
    arr = _batch([p + b'\x00' for p in pkts])
    assert not fmt.decode_batch(arr)[3].any()
    # ...but a padded receive stride with the TRUE length passed in is
    # fine (zero-copy lanes hand decode_batch a strided view)
    good = fmt.decode_batch(arr, length=TBN_FRAME_SIZE)[3]
    assert good[0] and good[1] and not good[2]


def _drx_pkts(fmt, pld):
    # desc.src is the raw wire id byte: beam 1-based bits 0-2, tuning
    # 1-based bits 3-5, pol bit 7
    ids = [1 | (1 << 3), 2 | (2 << 3) | (1 << 7), 3 | (1 << 3) | (1 << 7)]
    return [fmt.pack(PacketDesc(seq=(40960 * k + 4), src=pkt_id,
                                decimation=10, tuning=0xCAFEBABE,
                                payload=pld))
            for k, pkt_id in enumerate(ids)]


def test_drx_parity():
    fmt = DrxFormat()
    pkts = _drx_pkts(fmt, b'\x11' * 4096)
    assert len(pkts[0]) == DRX_FRAME_SIZE
    _assert_parity(fmt, pkts)
    # reserved bit 6 is the valid_mode reject in both decoders
    flagged = pkts[0][:4] + bytes([pkts[0][4] | 0x40]) + pkts[0][5:]
    arr = _batch([flagged])
    assert not fmt.decode_batch(arr)[3][0]


def test_drx8_parity():
    fmt = Drx8Format()
    pkts = _drx_pkts(fmt, b'\x22' * 8192)
    assert len(pkts[0]) == DRX8_FRAME_SIZE
    _assert_parity(fmt, pkts)


def test_cor_parity_composed_src():
    # 3 baselines x 2 servers; tuning carries (nserver << 8) | server
    pld = b'\x00' * (32 * 4)
    for src0 in (0, 1):
        fmt = CorFormat(nsrc=6, src0=src0)
        pkts = [fmt.pack(PacketDesc(seq=196000000 * 2 * k, src=bl,
                                    nsrc=3, tuning=(2 << 8) | server,
                                    decimation=200, payload=pld))
                for k, (bl, server) in enumerate(
                    [(0, 1), (1, 2), (2, 1), (2, 2)], start=50)]
        _assert_parity(fmt, pkts)


def test_snap2_parity():
    fmt = Snap2Format()
    pld = b'\x44' * 512
    pkts = [fmt.pack(PacketDesc(seq=31337 + k, time_tag=1700000000,
                                npol=2, npol_tot=4, nchan=96,
                                nchan_tot=192, src=blk, chan0=384,
                                pol0=pol0, nsrc=4, payload=pld))
            for k, (blk, pol0) in enumerate([(0, 0), (1, 2), (1, 0)])]
    _assert_parity(fmt, pkts)


def test_vdif_parity_and_legacy_mix_rejects():
    pld = b'\x55' * 64
    fmt = VdifFormat(frames_per_second=25600, ref_epoch=2,
                     log2_nchan=1, nbit=8, station_id=0x4142)
    pkts = [fmt.pack(PacketDesc(seq=100 * 25600 + f, src=thread,
                                payload=pld))
            for f, thread in ((7, 5), (8, 5), (9, 1023))]
    # invalid bit set on the last row
    w0 = struct.unpack_from('<I', pkts[-1])[0] | (1 << 31)
    pkts[-1] = struct.pack('<I', w0) + pkts[-1][4:]
    _assert_parity(fmt, pkts, expect_invalid={2})

    legacy = VdifFormat(frames_per_second=25600, legacy=True)
    lpkts = [legacy.pack(PacketDesc(seq=s, src=3, payload=pld))
             for s in (10, 11)]
    _assert_parity(legacy, lpkts)

    # mixed legacy/non-legacy framing has no single payload offset:
    # the engine must fall back to per-packet decode for that batch
    mixed = _batch([lpkts[0] + b'\x00' * 16, pkts[0]])
    with pytest.raises(ValueError):
        fmt.decode_batch(mixed)


def test_tbf_parity():
    fmt = TbfFormat()
    pld = b'\x66' * 6144
    pkts = [fmt.pack(PacketDesc(seq=123456 + k, src=chan, nsrc=64,
                                payload=pld), framecount=k)
            for k, chan in enumerate((300, 0, 65535))]
    _assert_parity(fmt, pkts)


def test_vbeam_parity():
    fmt = VBeamFormat()
    pld = b'\x77' * 256
    pkts = [fmt.pack(PacketDesc(seq=555 + k, time_tag=1700000000,
                                nchan=32, chan0=64, npol=2, payload=pld))
            for k in range(3)]
    _assert_parity(fmt, pkts)
    bad = b'\x00' * 8 + pkts[0][8:]
    assert not fmt.decode_batch(_batch([bad]))[3][0]


def test_gallery_every_registered_codec_has_decode_batch():
    """The engine's vectorized path covers the FULL gallery — a codec
    without decode_batch silently degrades to scalar decode."""
    from bifrost_tpu.io.packet_formats import FORMATS
    for name, fmt in FORMATS.items():
        assert callable(getattr(fmt, 'decode_batch', None)), name


# ---------------------------------------------------------------------
# sharded-capture ledger exactness
# ---------------------------------------------------------------------

NSRC, PAYLOAD, BT, NSEQ = 2, 64, 16, 64
DROP = {(5, 0), (17, 1)}


def _hdr_cb(desc):
    return desc.time_tag or 1, {'name': 'cap', '_tensor': {
        'shape': [-1, NSRC, PAYLOAD], 'dtype': 'u8',
        'labels': ['time', 'src', 'byte'],
        'scales': [[0, 1]] * 3, 'units': [None] * 3}}


def _mkpkt(fmt, seq, src, nsrc=NSRC):
    # chips wire fields are 1-based
    return fmt.header_struct.pack(src + 1, 0, 1, 1, 0, nsrc, 0,
                                  seq + 1) + bytes(
        [(seq * NSRC + src + b) % 256 for b in range(PAYLOAD)])


def _expected():
    exp = np.zeros((NSEQ, NSRC, PAYLOAD), np.uint8)
    for seq in range(NSEQ):
        for src in range(NSRC):
            if (seq, src) in DROP:
                continue
            exp[seq, src] = [(seq * NSRC + src + b) % 256
                             for b in range(PAYLOAD)]
    return exp


@pytest.mark.parametrize('nthreads', [1, 2])
def test_sharded_capture_ledger_exact(monkeypatch, nthreads):
    """Blast a known packet set (with holes, one alien source, one late
    straggler) through the sharded engine: the ring must hold exactly
    the good payloads with ONLY the missed cells blanked, and the loss
    ledger must account every packet: good + missing == window cells,
    nlate/nalien exactly the injected strays, nreceived == sent."""
    import socket as smod
    from bifrost_tpu.io.packet_capture import (
        ShardedUDPCapture, PacketCaptureCallback,
        CAPTURE_NO_DATA, CAPTURE_INTERRUPTED)
    from bifrost_tpu.io.udp_socket import Address
    from bifrost_tpu.ring import Ring

    monkeypatch.setenv('BF_NO_NATIVE_CAPTURE', '1')
    fmt = get_format('chips')
    cb = PacketCaptureCallback()
    cb.set_chips(_hdr_cb)
    ring = Ring(space='system',
                name='ledger-%d-%d' % (nthreads, time.monotonic_ns()))
    cap = ShardedUDPCapture('chips', Address('127.0.0.1', 0), ring,
                            NSRC, 0, PAYLOAD, BT, BT, cb,
                            nthreads=nthreads, vlen=8,
                            frame_size=fmt.header_size + PAYLOAD,
                            timeout=0.4)
    port = cap._socks[0].sock.getsockname()[1]

    chunks, attached = [], threading.Event()

    def reader():
        for seq in ring.read(guarantee=True):
            attached.set()
            for span in seq.read(BT):
                chunks.append(np.array(
                    span.data.as_numpy().view(np.uint8),
                    copy=True).reshape(BT, NSRC, PAYLOAD))
            return

    def cap_loop():
        while cap.recv() not in (CAPTURE_NO_DATA, CAPTURE_INTERRUPTED):
            pass

    rt = threading.Thread(target=reader)
    ct = threading.Thread(target=cap_loop)
    rt.start()
    ct.start()

    # two sender sockets = two flows, so REUSEPORT sharding actually
    # splits the load across workers when nthreads > 1
    txs = [smod.socket(smod.AF_INET, smod.SOCK_DGRAM) for _ in range(2)]
    sent = 0
    try:
        for seq in range(NSEQ):
            for src in range(NSRC):
                if (seq, src) in DROP:
                    continue
                txs[src].sendto(_mkpkt(fmt, seq, src),
                                ('127.0.0.1', port))
                sent += 1
            if seq == 0:
                assert attached.wait(10)
            if seq % 8 == 0:
                time.sleep(0.002)
        # strays: one alien (wire src beyond nsrc) and one late
        # straggler (seq 0 again, far behind the advanced window)
        time.sleep(0.3)
        txs[0].sendto(_mkpkt(fmt, 2, NSRC + 3), ('127.0.0.1', port))
        txs[0].sendto(_mkpkt(fmt, 0, 0), ('127.0.0.1', port))
        sent += 2
    finally:
        for tx in txs:
            tx.close()

    ct.join()
    cap.end()
    rt.join(timeout=10)

    data = np.concatenate(chunks, 0)[:NSEQ]
    np.testing.assert_array_equal(data, _expected())

    st = cap.stats
    ngood_pkts = NSEQ * NSRC - len(DROP)
    assert st['nreceived'] == sent
    assert st['ngood_bytes'] == ngood_pkts * PAYLOAD
    assert st['nmissing_bytes'] == len(DROP) * PAYLOAD
    assert st['nalien'] == 1
    assert st['nlate'] == 1
    # every received packet is exactly one of good/late/alien/dup
    assert (st['ngood_bytes'] // PAYLOAD + st['nlate'] + st['nalien'] +
            st['ndup']) == st['nreceived']
    # per-source ledger columns sum to the global good counter
    assert int(np.sum(st['src_ngood'])) == st['ngood_bytes']
    # per-worker counters cover every received packet
    assert sum(w['npackets'] for w in cap._wstats) == sent
    if nthreads > 1:
        # the fixed-frame chips stream must have engaged the zero-copy
        # scatter path for the bulk of the grid
        assert sum(w['zero_copy'] for w in cap._wstats) > 0
        assert cap._zero_copy_ok
