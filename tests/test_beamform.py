"""Quantized coherent-beamformer engine (ops/beamform.py, the Pallas
kernels in ops/pallas_kernels.py, BeamformBlock and the fused
beamform->detect->integrate substitution in stages.py).

Kernel parity runs in Pallas interpret mode on the CPU test backend;
the on-hardware timing and the published ops/s-per-chip row come from
bench_suite config 13 (tools/beam_gate.py -> BENCH_BEAM_cpu.json).
"""

import os

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.ops.beamform import (Beamformer, BEAM_CLASSES,
                                      beam_class_rtol,
                                      quantize_weights,
                                      _wide_weight_block)

from util import NumpySourceBlock, GatherSink, simple_header

ci8_np = np.dtype([('re', 'i1'), ('im', 'i1')])


def _weights(B, S, P=None, seed=0):
    rng = np.random.RandomState(seed)
    shape = (B, S) if P is None else (P, B, S)
    return (rng.randn(*shape) + 1j * rng.randn(*shape)) \
        .astype(np.complex64)


def _volt_planes(T, F, P, S, seed=1, lim=64):
    rng = np.random.RandomState(seed)
    re = rng.randint(-lim, lim, (T, F, P, S)).astype(np.int8)
    im = rng.randint(-lim, lim, (T, F, P, S)).astype(np.int8)
    return re, im


def _oracle(re, im, w):
    """float64 einsum oracle: (T, F, P, S) x (P, B, S) -> (T, F, P, B)."""
    x = re.astype(np.float64) + 1j * im.astype(np.float64)
    return np.einsum('tfps,pbs->tfpb', x, w.astype(np.complex128))


# ---------------------------------------------------------------------------
# engine candidates: parity + the exact-int contract
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('shape', [(8, 2, 1, 8), (16, 4, 2, 16),
                                   (32, 3, 2, 24)])
def test_candidate_parity_multiple_shapes(shape):
    """Every candidate implementation stays inside its accuracy class
    of the float64 oracle at several (T, F, P, S) shapes."""
    T, F, P, S = shape
    B = 6
    w = _weights(B, S, P if P > 1 else None)
    eng = Beamformer(w, accuracy='int8')
    re, im = _volt_planes(T, F, P, S)
    ref = _oracle(re, im, w if w.ndim == 3 else w[None])
    scale = np.max(np.abs(ref))
    bounds = {'xla': 1e-5, 'planar': 1e-3, 'planar_bf16': 8e-3,
              'pallas_bf16': 8e-3, 'int8_wide': 4e-2}
    for name, bound in bounds.items():
        y = np.asarray(eng._jit(name, P)(re, im))
        rel = np.max(np.abs(y - ref)) / scale
        assert rel <= bound, (name, rel)


def test_int8_wide_is_exact_int():
    """The widened-int8 candidate's integer core is bit-identical to
    the numpy int64 oracle — EXACT int32 accumulation, no float
    anywhere before the dequantization scale."""
    import jax.numpy as jnp
    T, F, P, S, B = 16, 3, 2, 24, 5
    w = _weights(B, S, P)
    eng = Beamformer(w, accuracy='int8')
    re, im = _volt_planes(T, F, P, S, lim=127)
    w2 = _wide_weight_block(eng.wr8, eng.wi8)
    yr, yi = Beamformer.int8_planes(jnp.asarray(re), jnp.asarray(im),
                                    jnp.asarray(w2), B)
    r64, i64 = re.astype(np.int64), im.astype(np.int64)
    wr64, wi64 = eng.wr8.astype(np.int64), eng.wi8.astype(np.int64)
    want_r = (np.einsum('tfps,pbs->tfpb', r64, wr64) -
              np.einsum('tfps,pbs->tfpb', i64, wi64))
    want_i = (np.einsum('tfps,pbs->tfpb', r64, wi64) +
              np.einsum('tfps,pbs->tfpb', i64, wr64))
    np.testing.assert_array_equal(np.asarray(yr, np.int64), want_r)
    np.testing.assert_array_equal(np.asarray(yi, np.int64), want_i)


def test_weight_quantization_symmetric_clip():
    """quantize_weights clips at +/-127 (never -128) so the widened
    block's negated -wi8 copy cannot overflow int8."""
    w = np.array([[1.0 + 0j, -1.0 + 1j]], np.complex64)
    wr8, wi8, scale = quantize_weights(w.real.astype(np.float32),
                                       w.imag.astype(np.float32))
    assert wr8.min() >= -127 and wr8.max() <= 127
    assert wi8.min() >= -127 and wi8.max() <= 127
    w2 = _wide_weight_block(wr8[None] if wr8.ndim == 2 else wr8,
                            wi8[None] if wi8.ndim == 2 else wi8)
    assert w2.dtype == np.int8
    assert w2.min() >= -127


# ---------------------------------------------------------------------------
# Pallas kernels (interpret mode) vs the engine's exact-int core
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('shape', [(8, 2, 8, 4), (16, 4, 16, 8)])
def test_pallas_beamform_int8_matches_oracle(shape):
    from bifrost_tpu.ops import pallas_kernels as pk
    T, F, S, B = shape
    rng = np.random.RandomState(3)
    wr = rng.randint(-127, 128, (B, S)).astype(np.int8)
    wi = rng.randint(-127, 128, (B, S)).astype(np.int8)
    re = rng.randint(-127, 128, (T, F, S)).astype(np.int8)
    im = rng.randint(-127, 128, (T, F, S)).astype(np.int8)
    yr, yi = pk.beamform_int8(wr, wi, re, im, interpret=True)
    r64, i64 = re.astype(np.int64), im.astype(np.int64)
    wr64, wi64 = wr.astype(np.int64), wi.astype(np.int64)
    np.testing.assert_array_equal(
        np.asarray(yr, np.int64),
        np.einsum('tfs,bs->tfb', r64, wr64) -
        np.einsum('tfs,bs->tfb', i64, wi64))
    np.testing.assert_array_equal(
        np.asarray(yi, np.int64),
        np.einsum('tfs,bs->tfb', r64, wi64) +
        np.einsum('tfs,bs->tfb', i64, wr64))


def test_pallas_beamform_bf16_within_class():
    from bifrost_tpu.ops import pallas_kernels as pk
    T, F, S, B = 16, 2, 16, 4
    rng = np.random.RandomState(4)
    wr = rng.randn(B, S).astype(np.float32)
    wi = rng.randn(B, S).astype(np.float32)
    re = rng.randint(-64, 64, (T, F, S)).astype(np.int8)
    im = rng.randint(-64, 64, (T, F, S)).astype(np.int8)
    yr, yi = pk.beamform_bf16(wr, wi, re, im, interpret=True)
    x = re.astype(np.float64) + 1j * im.astype(np.float64)
    w = wr.astype(np.float64) + 1j * wi.astype(np.float64)
    ref = np.einsum('tfs,bs->tfb', x, w)
    got = np.asarray(yr) + 1j * np.asarray(yi)
    rel = np.max(np.abs(got - ref)) / np.max(np.abs(ref))
    assert rel <= BEAM_CLASSES['bf16'], rel


def test_pallas_fused_detect_matches_quantized_oracle():
    """beamform_detect_int8: dual-pol beamform -> Stokes -> R-frame
    integrate in one program, vs the float64 oracle built from the
    QUANTIZED weights (the kernel's weights are int8 by construction)."""
    from bifrost_tpu.ops.beamform import fused_detect
    T, F, S, B, R = 16, 3, 8, 4, 4
    w = _weights(B, S)
    eng = Beamformer(w, accuracy='int8')
    rng = np.random.RandomState(6)
    x = np.zeros((T, F, S, 2, 2), np.int8)
    x[...] = rng.randint(-64, 64, x.shape)
    # interpret mode engages automatically off-TPU (_xcorr_interpret)
    out = np.asarray(fused_detect(eng, x, R))
    wq = (eng.wr8.astype(np.float64) +
          1j * eng.wi8.astype(np.float64))[0] * eng.wscale
    volt = x[..., 0].astype(np.float64) + 1j * x[..., 1].astype(np.float64)
    y = np.einsum('tfsp,bs->tfpb', volt, wq)
    bx, by = y[:, :, 0], y[:, :, 1]
    xx, yy = np.abs(bx) ** 2, np.abs(by) ** 2
    xy = bx * np.conj(by)
    st = np.stack([xx + yy, xx - yy, 2 * xy.real, -2 * xy.imag],
                  axis=2)
    ref = st.reshape(T // R, R, F, 4, B).sum(axis=1)
    rel = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
    assert out.shape == (T // R, F, 4, B)
    assert rel < 1e-5, rel


# ---------------------------------------------------------------------------
# the accuracy gate: lossy candidates stay opt-in
# ---------------------------------------------------------------------------

def test_gate_rejects_lossy_candidate_at_default_rtol():
    """The single-pass bf16 candidate (~2^-8 input rounding) fails the
    f32-class gate (rtol 1e-3) at a realistic shape — lossy winners
    cannot race their way into a default-accuracy session."""
    import jax.numpy as jnp
    T, F, P, S, B = 32, 4, 2, 32, 8
    w = _weights(B, S, P)
    eng = Beamformer(w, accuracy='f32')
    re, im = _volt_planes(T, F, P, S)
    rej = jnp.asarray(re)
    imj = jnp.asarray(im)
    keep, had_errors = eng._gate(['xla', 'planar', 'planar_bf16'], P,
                                 lambda: (rej, imj))
    assert not had_errors
    assert 'xla' in keep and 'planar' in keep
    assert 'planar_bf16' not in keep


def test_candidate_eligibility_per_class():
    """A class that does not admit a lossy candidate's error excludes
    it from the race outright; int candidates additionally need int
    input."""
    w = _weights(4, 8, 2)
    assert Beamformer(w, accuracy='f32')._candidates(True) == \
        ['xla', 'planar']
    bf16 = Beamformer(w, accuracy='bf16')._candidates(True)
    assert 'planar_bf16' in bf16 and 'int8_wide' not in bf16
    # the Pallas bf16 kernel races only where it compiles natively
    assert ('pallas_bf16' in bf16) == Beamformer._pallas_raceable()
    i8 = Beamformer(w, accuracy='int8')._candidates(True)
    assert 'int8_wide' in i8
    # float input can never feed the int8 kernels
    assert 'int8_wide' not in Beamformer(
        w, accuracy='int8')._candidates(False)


def test_gate_rtol_env_override(monkeypatch):
    monkeypatch.setenv('BF_BEAM_GATE_RTOL', '0.5')
    assert beam_class_rtol('f32') == 0.5
    monkeypatch.delenv('BF_BEAM_GATE_RTOL')
    assert beam_class_rtol('f32') == BEAM_CLASSES['f32']
    # a non-default bound is part of the probe-cache key
    w = _weights(4, 8)
    eng = Beamformer(w, accuracy='f32')
    k_default = eng._key((8, 2, 1, 8), 'int8', True)
    monkeypatch.setenv('BF_BEAM_GATE_RTOL', '0.5')
    k_wide = eng._key((8, 2, 1, 8), 'int8', True)
    assert k_default != k_wide and 'gate_rtol' in k_wide


def test_bf_beam_impl_forces_candidate(monkeypatch):
    """BF_BEAM_IMPL forces any candidate unconditionally — bypassing
    both the race and the gate (the operator's override)."""
    monkeypatch.setenv('BF_BEAM_IMPL', 'int8_wide')
    w = _weights(4, 8, 2)
    eng = Beamformer(w, accuracy='f32')
    assert eng._force == 'int8_wide'
    re, im = _volt_planes(8, 2, 2, 8)
    y = np.asarray(eng(re, im))
    # prewarm records the forced choice (the block path)
    assert eng.prewarm(8, 2, npol=2) == 'int8_wide'
    ref = _oracle(re, im, w)
    rel = np.max(np.abs(y - ref)) / np.max(np.abs(ref))
    assert rel <= BEAM_CLASSES['int8']
    # the explicit impl= argument does the same
    eng2 = Beamformer(w, accuracy='f32', impl='planar')
    assert eng2._force == 'planar'


def test_invalid_accuracy_and_weights_rejected():
    with pytest.raises(ValueError):
        Beamformer(_weights(4, 8), accuracy='f16')
    with pytest.raises(ValueError):
        Beamformer(np.zeros(4, np.complex64))


# ---------------------------------------------------------------------------
# BeamformBlock in a pipeline: standalone, fused substitution,
# macro-gulp K>1, mesh sharding
# ---------------------------------------------------------------------------

def _ci8_gulps(T, F, S, P, n=1, seed=5, lim=32):
    rng = np.random.RandomState(seed)
    gulps = []
    for _ in range(n):
        raw = np.zeros((T, F, S, P), dtype=ci8_np)
        raw['re'] = rng.randint(-lim, lim, raw.shape)
        raw['im'] = rng.randint(-lim, lim, raw.shape)
        gulps.append(raw)
    return gulps


def _run_block_chain(gulps, hdr, w, T, accuracy='int8', gulp_batch=1,
                     mesh=None, impl=None, fused_chain=None,
                     name='Beam'):
    import contextlib
    from bifrost_tpu.telemetry import counters
    counters.reset()
    scope = bf.block_scope(mesh=mesh) if mesh is not None \
        else contextlib.nullcontext()
    with bf.Pipeline(gulp_batch=gulp_batch) as p:
        src = NumpySourceBlock([g.copy() for g in gulps], hdr,
                               gulp_nframe=T)
        with scope:
            b = bf.blocks.copy(src, space='tpu')
            if fused_chain is not None:
                b = bf.blocks.fused(b, fused_chain, name=name)
            else:
                b = bf.blocks.beamform(b, w, accuracy=accuracy,
                                       impl=impl, name=name)
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    return sink.result(), counters.snapshot()


def test_block_perpol_matches_oracle():
    T, F, S, P, B = 16, 4, 8, 2, 4
    w = _weights(B, S, P)
    hdr = simple_header([-1, F, S, P], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'])
    out, _ = _run_block_chain(_ci8_gulps(T, F, S, P), hdr, w, T)
    raw = _ci8_gulps(T, F, S, P)[0]
    ref = np.einsum('tfsp,pbs->tfpb',
                    raw['re'].astype(np.float64) +
                    1j * raw['im'].astype(np.float64),
                    w.astype(np.complex128))
    assert out.shape == (T, F, P, B)
    rel = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
    assert rel <= BEAM_CLASSES['int8'], rel


def test_block_folded_pol_single_beam_axis():
    """(B, S*P) weights fold pol into the contraction: output labels
    ['time', 'freq', 'beam']."""
    T, F, S, P, B = 8, 2, 4, 2, 3
    w = _weights(B, S * P)
    hdr = simple_header([-1, F, S, P], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'])
    out, _ = _run_block_chain(_ci8_gulps(T, F, S, P), hdr, w, T)
    raw = _ci8_gulps(T, F, S, P)[0]
    x = (raw['re'].astype(np.float64) +
         1j * raw['im'].astype(np.float64)).reshape(T, F, S * P)
    ref = np.einsum('tfn,bn->tfb', x, w.astype(np.complex128))
    assert out.shape == (T, F, B)
    rel = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
    assert rel <= BEAM_CLASSES['int8'], rel


def test_block_macro_gulp_batches_without_fallback():
    """BeamformBlock is macro-gulp eligible: at K=4 the block runs
    batched dispatches (no macro.fallback.* for it) and the output is
    identical to the K=1 stream."""
    T, F, S, P, B = 16, 2, 8, 2, 4
    w = _weights(B, S, P)
    hdr = simple_header([-1, F, S, P], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'])
    gulps = _ci8_gulps(T, F, S, P, n=8)
    base, _ = _run_block_chain(gulps, hdr, w, T, name='BeamK1')
    batched, snap = _run_block_chain(gulps, hdr, w, T, gulp_batch=4,
                                     name='BeamK4')
    np.testing.assert_array_equal(batched, base)
    # the beamform block itself batched: 8 logical gulps in 2 dispatches
    disp = sum(v for k, v in snap.items()
               if 'BeamK4' in k and k.endswith('.dispatches'))
    glp = sum(v for k, v in snap.items()
              if 'BeamK4' in k and k.endswith('.gulps'))
    assert glp == 8 and disp <= 2, (disp, glp)
    # the only fallback reason in the chain is 'block' (the host
    # source/sink, normal per BF-I161) — the beamform block itself
    # never fell back (no overlap/nonlinear/dynamic/... counters)
    bad = {k: v for k, v in snap.items()
           if k.startswith('macro.fallback.') and v > 0 and
           k not in ('macro.fallback.block',
                     'macro.fallback.multi_reader_retired')}
    assert not bad, bad


def test_block_mesh_sharded_matches_and_zero_reshard():
    """Mesh-sharded execution (frame-local plan — beamforming is
    time-concat equivariant): output matches single-device and the
    steady state pays no reshard."""
    from bifrost_tpu.parallel import create_mesh
    T, F, S, P, B = 16, 2, 8, 2, 4
    w = _weights(B, S, P)
    hdr = simple_header([-1, F, S, P], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'])
    gulps = _ci8_gulps(T, F, S, P, n=4)
    base, _ = _run_block_chain(gulps, hdr, w, T, name='BeamSingle')
    mesh = create_mesh({'sp': 8})
    meshed, snap = _run_block_chain(gulps, hdr, w, T, mesh=mesh,
                                    name='BeamMesh')
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-5)
    # zero-reshard assertion on the frame-local path: only the prewarm
    # zeros gulp may relayout
    assert snap.get('mesh.reshards', 0) <= 1, snap


def test_fused_substitution_engages_and_matches(monkeypatch):
    """BF_BEAM_FUSED=force substitutes the fused Pallas kernel
    (interpret mode off-TPU) for the beamform->stokes->integrate
    chain; output matches the quantized-weights oracle."""
    from bifrost_tpu.stages import (BeamformStage, DetectStage,
                                    ReduceStage)
    monkeypatch.setenv('BF_BEAM_FUSED', 'force')
    T, F, S, P, B, R = 16, 2, 8, 2, 4, 4
    w = _weights(B, S)
    hdr = simple_header([-1, F, S, P], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'])
    gulps = _ci8_gulps(T, F, S, P)
    chain = [BeamformStage(w, accuracy='int8'),
             DetectStage('stokes', axis='pol'),
             ReduceStage('time', R)]
    out, _ = _run_block_chain(gulps, hdr, w, T, fused_chain=chain,
                              name='BeamFused')
    eng = Beamformer(w, accuracy='int8')
    wq = (eng.wr8.astype(np.float64) +
          1j * eng.wi8.astype(np.float64))[0] * eng.wscale
    raw = gulps[0]
    x = raw['re'].astype(np.float64) + 1j * raw['im'].astype(np.float64)
    y = np.einsum('tfsp,bs->tfpb', x, wq)
    bx, by = y[:, :, 0], y[:, :, 1]
    xx, yy = np.abs(bx) ** 2, np.abs(by) ** 2
    xy = bx * np.conj(by)
    st = np.stack([xx + yy, xx - yy, 2 * xy.real, -2 * xy.imag],
                  axis=2)
    ref = st.reshape(T // R, R, F, 4, B).sum(axis=1)
    assert out.shape == (T // R, F, 4, B)
    rel = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
    assert rel < 1e-5, rel


def test_fused_substitution_requires_int8_class(monkeypatch):
    """Under BF_BEAM_FUSED=auto the substitution is refused off-TPU
    and for accuracy classes below int8 — the XLA stage path runs and
    still produces a correct stream."""
    from bifrost_tpu.stages import (BeamformStage, DetectStage,
                                    ReduceStage, match_beamformer,
                                    walk_headers)
    monkeypatch.setenv('BF_BEAM_FUSED', 'auto')
    T, F, S, P, B, R = 8, 2, 4, 2, 3, 4
    w = _weights(B, S)
    hdr = simple_header([-1, F, S, P], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'])
    stages = [BeamformStage(w, accuracy='f32'),
              DetectStage('stokes', axis='pol'),
              ReduceStage('time', R)]
    headers = walk_headers(stages, hdr)
    assert match_beamformer(stages, headers, (T, F, S, P, 2),
                            'int8') is None
    # wrong detect mode never matches either
    stages = [BeamformStage(w, accuracy='int8'),
              DetectStage('coherence', axis='pol'),
              ReduceStage('time', R)]
    headers = walk_headers(stages, hdr)
    assert match_beamformer(stages, headers, (T, F, S, P, 2),
                            'int8') is None


def test_block_rejects_bad_streams():
    from bifrost_tpu.stages import BeamformStage
    w = _weights(4, 8)
    st = BeamformStage(w)
    with pytest.raises(ValueError):
        st.transform_header(simple_header(
            [-1, 4, 8], 'ci8', labels=['time', 'station', 'freq']))
    with pytest.raises(TypeError):
        st.transform_header(simple_header(
            [-1, 4, 8], 'f32', labels=['time', 'freq', 'station']))
    with pytest.raises(ValueError):
        # station count mismatch
        st.transform_header(simple_header(
            [-1, 4, 6], 'ci8', labels=['time', 'freq', 'station']))


def test_gemm_ops_accounting():
    """The engine's ops/frame accounting (8 real ops per complex MAC)
    feeds the gemm_gops_per_s perf key and the bench ops/s row."""
    w = _weights(4, 8, 2)
    eng = Beamformer(w, accuracy='int8')
    assert eng.ops_per_frame(nfreq=16) == 8 * 16 * 2 * 4 * 8
    assert eng.ops_per_frame(nfreq=16, npol=1) == 8 * 16 * 1 * 4 * 8
