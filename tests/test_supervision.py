"""Supervision-layer tests: failure propagation policies
(abort/restart/skip_sequence), ring poisoning in both ring cores,
deferred-fill error surfacing, and the stall watchdog — all driven by
the deterministic fault harness (bifrost_tpu.testing.faults) on the
CPU backend."""

import contextlib
import io
import threading
import time

import numpy as np
import pytest

import bifrost_tpu as bf
import bifrost_tpu.native as native_mod
from bifrost_tpu.ring import Ring, RingPoisonedError
from bifrost_tpu.supervision import (PipelineRuntimeError,
                                     PipelineStallError)
from bifrost_tpu.telemetry import counters
from bifrost_tpu.testing import faults
from tests.util import (NumpySourceBlock, GatherSink, simple_header,
                        _NumpyReader)

pytestmark = pytest.mark.faults

CORES = ['python'] + (['native'] if native_mod.available()
                      else [])


@pytest.fixture(autouse=True)
def clean_faults_and_counters():
    faults.clear()
    counters.reset()
    yield
    faults.clear()


def _hdr():
    return simple_header([-1, 3], 'f32')


def _gulps(n=5):
    return [np.full((4, 3), float(k), dtype=np.float32)
            for k in range(n)]


class Ident(bf.TransformBlock):
    """Pass-through host transform with a distinctive name for fault
    matching."""

    def on_sequence(self, iseq):
        return dict(iseq.header)

    def on_data(self, ispan, ospan):
        ospan.data.as_numpy()[...] = ispan.data.as_numpy()


class TwoSeqSource(NumpySourceBlock):
    """Emits the same gulp list as two separate sequences."""

    def __init__(self, *args, **kwargs):
        super(TwoSeqSource, self).__init__(*args, **kwargs)
        self.sourcenames = ['seq-a', 'seq-b']

    def create_reader(self, sourcename):
        return _NumpyReader(self._gulps)


def _run_with_timeout(pipeline, timeout=30.0):
    """Run the pipeline in a worker thread so a regression back to the
    silent-hang behavior fails the test instead of wedging the suite.
    Returns the exception ``run()`` raised (or None)."""
    box = []

    def target():
        try:
            with contextlib.redirect_stderr(io.StringIO()):
                pipeline.run()
            box.append(None)
        except BaseException as exc:
            box.append(exc)

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), \
        "Pipeline.run did not terminate within %gs" % timeout
    return box[0]


# ---------------------------------------------------------------------------
# failure propagation
# ---------------------------------------------------------------------------

def test_abort_midstream_no_hang():
    """A mid-stream block exception terminates the pipeline promptly
    and surfaces as PipelineRuntimeError carrying the original
    traceback (the ISSUE acceptance scenario)."""
    with faults.injected('block.on_data', match='Ident', after=1):
        with bf.Pipeline() as p:
            p.shutdown_timeout = 2.0
            src = NumpySourceBlock(_gulps(50), _hdr(), gulp_nframe=4)
            blk = Ident(src)
            GatherSink(blk)
            t0 = time.monotonic()
            exc = _run_with_timeout(p, timeout=20.0)
            elapsed = time.monotonic() - t0
    assert isinstance(exc, PipelineRuntimeError), repr(exc)
    # wind-down bounded by shutdown_timeout (+ slack for the join loop)
    assert elapsed < 2.0 + 8.0
    # original exception type, message, and traceback text survive
    msg = str(exc)
    assert 'FaultInjected' in msg and 'injected fault' in msg
    assert 'Traceback' in msg
    assert exc.primary is not None
    assert 'Ident' in exc.primary.block_name
    assert counters.get('block_failures') == 1
    assert counters.get('ring_poisoned') > 0


def test_abort_poisons_upstream_source():
    """The failed block's UPSTREAM source must stop too (the classic
    silent-hang case: a capture source happily feeding a ring whose
    only consumer died)."""
    with faults.injected('block.on_data', match='Ident', after=1):
        with bf.Pipeline() as p:
            p.shutdown_timeout = 2.0
            # many gulps: without poisoning, the source would keep
            # writing long after the consumer died
            src = NumpySourceBlock(_gulps(500), _hdr(), gulp_nframe=4)
            blk = Ident(src)
            GatherSink(blk)
            exc = _run_with_timeout(p, timeout=20.0)
    assert isinstance(exc, PipelineRuntimeError)
    for thread in p.threads:
        assert not thread.is_alive()


def test_restart_source_survives_transient_failures():
    """A restart-policy source survives 3 injected transient failures
    with backoff and the pipeline completes (ISSUE acceptance)."""
    with faults.injected('block.run', match='NumpySourceBlock',
                         count=3):
        with bf.Pipeline() as p:
            src = NumpySourceBlock(_gulps(3), _hdr(), gulp_nframe=4,
                                   on_failure='restart',
                                   max_restarts=5,
                                   restart_backoff=0.01)
            sink = GatherSink(src)
            exc = _run_with_timeout(p)
    assert exc is None, repr(exc)
    assert sink.result().shape == (12, 3)
    assert counters.get('block_restarts') == 3
    assert counters.get('block_failures') == 3


def test_restart_budget_exhaustion_escalates_to_abort():
    with faults.injected('block.run', match='NumpySourceBlock',
                         count=10):
        with bf.Pipeline() as p:
            p.shutdown_timeout = 2.0
            src = NumpySourceBlock(_gulps(3), _hdr(), gulp_nframe=4,
                                   on_failure='restart',
                                   max_restarts=2,
                                   restart_backoff=0.01)
            GatherSink(src)
            exc = _run_with_timeout(p)
    assert isinstance(exc, (PipelineRuntimeError,
                            bf.PipelineInitError)), repr(exc)
    assert counters.get('block_restarts') == 2


@pytest.mark.parametrize('core', CORES)
def test_restart_storm_budget_exhaustion_mid_macro_gulp(core,
                                                       monkeypatch):
    """Restart-storm drill (ISSUE 11): the restart budget
    (BF_RESTART_MAX) is exhausted MID-MACRO-GULP — a K=4 macro chain
    is active when the faulted source burns through every restart —
    and the escalation must be a clean poison cascade (no hang, every
    downstream block woken) with EXACT block_restarts/block_failures
    counters, in BOTH ring cores (the host rings of the chain run on
    the parametrized core; the device ring always runs the Python
    chunk-map core)."""
    if core == 'python':
        monkeypatch.setattr(native_mod, '_lib', None)
        monkeypatch.setattr(native_mod, '_tried', True)
    monkeypatch.setenv('BF_RESTART_MAX', '2')
    nt = 8
    gulps = [np.full((nt, 3), float(k), dtype=np.float32)
             for k in range(16)]
    hdr = _hdr()
    hdr['gulp_nframe'] = nt
    # the fault fires on gulp 3 of every (re)started source run:
    # mid-stream, while the device chain is consuming K=4 macro spans
    with faults.injected('block.on_data', match='NumpySourceBlock',
                         count=3, after=2):
        with bf.Pipeline(gulp_batch=4) as p:
            p.shutdown_timeout = 5.0
            src = NumpySourceBlock(gulps, hdr, gulp_nframe=nt,
                                   on_failure='restart',
                                   restart_backoff=0.01)
            dev = bf.blocks.copy(src, space='tpu')
            host = bf.blocks.copy(dev, space='system')
            sink = GatherSink(host)
            exc = _run_with_timeout(p)
    # budget (2) exhausted by the 3rd failure: fatal abort, poison
    # cascade reaches every block, run() re-raises the aggregate
    assert isinstance(exc, PipelineRuntimeError), repr(exc)
    assert counters.get('block_restarts') == 2
    assert counters.get('block_failures') == 3
    assert counters.get('ring_poisoned') >= 3   # every chain ring
    kinds = [f.kind for f in exc.failures]
    assert kinds.count('restarted') == 2
    assert kinds.count('error') == 1
    assert any(k == 'poisoned' for k in kinds), \
        "no poison-cascade record: downstream died uncleanly"


@pytest.mark.parametrize('core', CORES)
def test_skip_sequence_resets_slo_ages(core, monkeypatch):
    """ISSUE 11 satellite: a skip_sequence drain must reset the
    block's commit-age SLO histograms — the skipped sequence's stale
    origin must not poison the p99 forever."""
    if core == 'python':
        monkeypatch.setattr(native_mod, '_lib', None)
        monkeypatch.setattr(native_mod, '_tried', True)
    from bifrost_tpu.telemetry import histograms, slo
    histograms.reset()
    with faults.injected('block.on_data', match='Ident', count=1,
                         after=2):
        with bf.Pipeline() as p:
            src = TwoSeqSource(_gulps(5), _hdr(), gulp_nframe=4)
            blk = Ident(src, on_failure='skip_sequence')
            sink = GatherSink(blk)
            exc = _run_with_timeout(p)
    assert exc is None, repr(exc)
    # seq-a recorded 2 commit ages before the fault; the skip reset
    # them; seq-b recorded its 5 — without the reset this would be 7
    h = histograms.get('slo.%s.commit_age_s' % blk.name)
    assert h is not None, "no commit ages recorded at all"
    assert h.snapshot()['count'] == 5
    # the unit contract, directly:
    slo.observe_commit('unit_block', 123.0)
    assert histograms.get(
        'slo.unit_block.commit_age_s').snapshot()['count'] == 1
    slo.reset_block_ages('unit_block')
    assert histograms.get(
        'slo.unit_block.commit_age_s').snapshot()['count'] == 0


def test_skip_sequence_policy_degrades_gracefully():
    """A skip_sequence transform abandons the failing sequence (its
    output for it stays empty) and delivers the next one intact."""
    with faults.injected('block.on_sequence', match='Ident', count=1,
                         after=1):
        with bf.Pipeline() as p:
            src = TwoSeqSource(_gulps(3), _hdr(), gulp_nframe=4)
            blk = Ident(src, on_failure='skip_sequence')
            sink = GatherSink(blk)
            exc = _run_with_timeout(p)
    assert exc is None, repr(exc)
    # one of the two sequences was dropped, the other arrived whole
    assert len(sink.headers) == 1
    assert sink.result().shape == (12, 3)
    assert counters.get('block_failures') == 1
    assert counters.get('block_restarts') == 0


def test_unknown_policy_is_rejected():
    """A misspelled policy fails fast in the launching thread, before
    any block thread starts."""
    with bf.Pipeline() as p:
        NumpySourceBlock(_gulps(2), _hdr(), gulp_nframe=4,
                         on_failure='retry-plz')
        with pytest.raises(ValueError, match='retry-plz'):
            p.run()
    assert not p.threads


def test_init_failure_still_raises_pipeline_init_error():
    """Pre-barrier failures keep the historical PipelineInitError
    contract (now enriched with the traceback)."""

    class BadBlock(bf.TransformBlock):
        def on_sequence(self, iseq):
            raise RuntimeError("boom-at-init")

        def on_data(self, ispan, ospan):
            pass

    with bf.Pipeline() as p:
        p.shutdown_timeout = 2.0
        src = NumpySourceBlock(_gulps(1), _hdr(), gulp_nframe=4)
        BadBlock(src)
        exc = _run_with_timeout(p)
    assert isinstance(exc, bf.PipelineInitError)
    assert 'boom-at-init' in str(exc)


# ---------------------------------------------------------------------------
# ring poisoning (both cores)
# ---------------------------------------------------------------------------

@pytest.fixture(params=CORES)
def ring_core(request, monkeypatch):
    if request.param == 'python':
        monkeypatch.setattr(native_mod, '_lib', None)
        monkeypatch.setattr(native_mod, '_tried', True)
    return request.param


def test_poison_wakes_blocked_reader(ring_core):
    ring = Ring(space='system')
    if ring_core == 'python':
        from bifrost_tpu.ring_native import NativeRing
        assert not isinstance(ring, NativeRing)
    hdr = _hdr()
    caught = []

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(dict(hdr), gulp_nframe=4,
                                   buf_nframe=12) as seq:
                with seq.reserve(4) as span:
                    span.data.as_numpy()[...] = 1.0
                    span.commit(4)
                # hold the sequence open: the reader will block on
                # gulp 2, which never arrives
                time.sleep(30)

    wt = threading.Thread(target=writer, daemon=True)
    wt.start()

    def reader():
        try:
            for seq in ring.read(guarantee=True):
                for _span in seq.read(4):
                    pass
        except RingPoisonedError as exc:
            caught.append(exc)

    rt = threading.Thread(target=reader, daemon=True)
    rt.start()
    time.sleep(0.3)
    assert rt.is_alive()
    ring.poison(RuntimeError("producer died"))
    rt.join(5)
    assert not rt.is_alive(), "poison did not wake the blocked reader"
    assert caught and 'producer died' in str(caught[0])
    assert isinstance(caught[0].cause, RuntimeError)
    assert ring.poisoned
    assert counters.get('ring_poisoned') == 1


def test_poison_wakes_blocked_writer(ring_core):
    ring = Ring(space='system')
    hdr = _hdr()
    caught = []
    reader_ready = threading.Event()

    def writer():
        try:
            with ring.begin_writing() as wr:
                with wr.begin_sequence(dict(hdr), gulp_nframe=4,
                                       buf_nframe=8) as seq:
                    with seq.reserve(4) as span:
                        span.data.as_numpy()[...] = 0.0
                        span.commit(4)
                    assert reader_ready.wait(10)
                    for k in range(1, 100):
                        with seq.reserve(4) as span:
                            span.data.as_numpy()[...] = float(k)
                            span.commit(4)
        except RingPoisonedError as exc:
            caught.append(exc)

    wt = threading.Thread(target=writer, daemon=True)
    wt.start()
    with ring.open_earliest_sequence(guarantee=True) as rseq:
        span = rseq.acquire(0, 4)     # pins the guarantee at frame 0
        reader_ready.set()
        time.sleep(0.3)
        assert wt.is_alive(), "writer should be blocked on the full ring"
        ring.poison(RuntimeError("consumer died"))
        wt.join(5)
        alive = wt.is_alive()
        span.release()
    assert not alive, "poison did not wake the blocked writer"
    assert caught and 'consumer died' in str(caught[0])


def test_poisoned_ring_fails_fast_on_new_operations(ring_core):
    ring = Ring(space='system')
    ring.poison(RuntimeError("dead"))
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with pytest.raises(RingPoisonedError):
            wr.begin_sequence(dict(hdr), gulp_nframe=4, buf_nframe=12)
    occ = ring.occupancy()
    assert occ['poisoned'] is True


def test_poison_is_idempotent(ring_core):
    ring = Ring(space='system')
    ring.poison(RuntimeError("first"))
    ring.poison(RuntimeError("second"))
    assert counters.get('ring_poisoned') == 1
    try:
        ring._check_poison()
        assert False, "expected RingPoisonedError"
    except RingPoisonedError as exc:
        assert 'first' in str(exc)


# ---------------------------------------------------------------------------
# transfer-engine failure surfacing
# ---------------------------------------------------------------------------

def test_failed_hostfill_poisons_ring_and_wakes_reader():
    """A deferred D2H fill whose transfer fails must poison its ring:
    the waiting reader gets the error, later readers RingPoisonedError
    — not a silent span of stale bytes."""
    from bifrost_tpu.xfer import HostFill, TransferFuture

    ring = Ring(space='system')
    hdr = _hdr()

    def exploding_convert(_host):
        raise RuntimeError("DMA exploded")

    with ring.begin_writing() as wr:
        with wr.begin_sequence(dict(hdr), gulp_nframe=4,
                               buf_nframe=12) as seq:
            with seq.reserve(4) as span:
                fill = HostFill(TransferFuture([], exploding_convert),
                                'f32', span.data)
                span.set_fill(fill)
                span.commit(4)
        with ring.open_earliest_sequence(guarantee=True) as rseq:
            with pytest.raises(RuntimeError, match="DMA exploded"):
                rseq.acquire(0, 4)
    assert ring.poisoned
    assert counters.get('xfer.fill_errors') == 1
    # the same fill re-raises instead of pretending success
    with pytest.raises(RuntimeError, match="DMA exploded"):
        fill.wait()


def test_transfer_future_caches_error():
    from bifrost_tpu.xfer import TransferFuture

    calls = []

    def bad_convert(_host):
        calls.append(1)
        raise ValueError("bad transfer")

    fut = TransferFuture([], bad_convert)
    with pytest.raises(ValueError):
        fut.result()
    with pytest.raises(ValueError):
        fut.result()
    assert fut.done and len(calls) == 1
    assert counters.get('xfer.errors') == 1


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_stall_drill(monkeypatch):
    """A block wedged mid-gulp (delay-only fault) trips the watchdog:
    counter + stack/ring dump, and with escalation enabled the run
    raises PipelineStallError."""
    monkeypatch.setenv('BF_WATCHDOG_ESCALATE', '1')
    stderr = io.StringIO()
    with faults.injected('block.on_data', match='Ident', count=1,
                         after=1, delay=15, exc=None):
        with bf.Pipeline(watchdog_secs=0.5) as p:
            p.shutdown_timeout = 1.0
            src = NumpySourceBlock(_gulps(50), _hdr(), gulp_nframe=4)
            blk = Ident(src)
            GatherSink(blk)

            box = []

            def target():
                try:
                    with contextlib.redirect_stderr(stderr):
                        p.run()
                    box.append(None)
                except BaseException as exc:
                    box.append(exc)

            t = threading.Thread(target=target, daemon=True)
            t.start()
            t.join(20)
            assert not t.is_alive()
    exc = box[0]
    assert isinstance(exc, PipelineStallError), repr(exc)
    assert isinstance(exc, PipelineRuntimeError)   # subclass contract
    assert 'no block progressed' in str(exc)
    assert counters.get('watchdog_stalls') == 1
    dump = stderr.getvalue()
    assert 'watchdog' in dump
    assert 'Thread' in dump          # stack dump present
    assert 'ring' in dump            # ring occupancy present


def test_watchdog_quiet_on_healthy_pipeline(monkeypatch):
    monkeypatch.setenv('BF_WATCHDOG_ESCALATE', '1')
    with bf.Pipeline(watchdog_secs=5.0) as p:
        src = NumpySourceBlock(_gulps(5), _hdr(), gulp_nframe=4)
        sink = GatherSink(src)
        exc = _run_with_timeout(p)
    assert exc is None
    assert sink.result().shape == (20, 3)
    assert counters.get('watchdog_stalls') == 0


# ---------------------------------------------------------------------------
# fault harness + telemetry surfacing
# ---------------------------------------------------------------------------

def test_fault_counts_and_after_are_deterministic():
    f = faults.inject('unit.test', count=2, after=1)
    faults.fire('unit.test')                        # skipped (after)
    with pytest.raises(faults.FaultInjected):
        faults.fire('unit.test')
    with pytest.raises(faults.FaultInjected):
        faults.fire('unit.test')
    faults.fire('unit.test')                        # count exhausted
    assert f.fired == 2
    assert faults.fired('unit.test') == 2


def test_fault_match_filters_by_name():
    faults.inject('unit.site', match='target')
    faults.fire('unit.site', 'other-block')         # no match
    with pytest.raises(faults.FaultInjected):
        faults.fire('unit.site', 'my-target-block')


def test_arm_from_env(monkeypatch):
    faults.clear()
    monkeypatch.setenv('BF_FAULTS', 'unit.env:blk:2:1:0')
    faults.arm_from_env()
    faults.fire('unit.env', 'blk-0')                # after=1 skip
    with pytest.raises(faults.FaultInjected):
        faults.fire('unit.env', 'blk-0')


def test_telemetry_flush_surfaces_robustness_counters():
    import bifrost_tpu.telemetry as telemetry
    counters.inc('block_failures', 2)
    counters.inc('ring_poisoned')
    snap = telemetry.flush()
    assert snap['block_failures'] == 2
    assert snap['ring_poisoned'] == 1
    assert 'watchdog_stalls' not in snap or \
        snap['watchdog_stalls'] == 0


def test_socket_retry_transient_with_budget(monkeypatch):
    import errno
    from bifrost_tpu.io.udp_socket import retry_transient

    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) <= 2:
            raise OSError(errno.ECONNREFUSED, 'refused')
        return 'ok'

    assert retry_transient(flaky, budget=5, backoff=0.001) == 'ok'
    assert len(attempts) == 3
    assert counters.get('io.socket_retries') == 2

    # budget exhaustion surfaces the real error
    attempts[:] = []

    def always_refused():
        attempts.append(1)
        raise OSError(errno.ECONNREFUSED, 'refused')

    with pytest.raises(OSError):
        retry_transient(always_refused, budget=3, backoff=0.001)
    assert len(attempts) == 4       # initial try + 3 retries

    # non-transient errnos pass straight through
    def hard_fail():
        raise OSError(errno.EBADF, 'bad fd')

    with pytest.raises(OSError):
        retry_transient(hard_fail, budget=5, backoff=0.001)
