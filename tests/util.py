"""In-test source/sink blocks (pattern from reference:
test/test_pipeline.py:43-113 CallbackBlock)."""

from __future__ import annotations

import numpy as np

import bifrost_tpu as bf
from bifrost_tpu.pipeline import SourceBlock, SinkBlock, TransformBlock


class _NumpyReader(object):
    def __init__(self, arrays):
        self.arrays = list(arrays)
        self.pos = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def read(self, nframe):
        if self.pos >= len(self.arrays):
            return None
        out = self.arrays[self.pos]
        self.pos += 1
        return out


class NumpySourceBlock(SourceBlock):
    """Source emitting a list of numpy gulps with a given header tensor."""

    def __init__(self, gulps, header, gulp_nframe, space='system',
                 **kwargs):
        super(NumpySourceBlock, self).__init__(['numpy'], gulp_nframe,
                                               space=space, **kwargs)
        self._gulps = gulps
        self._header = header

    def create_reader(self, sourcename):
        return _NumpyReader(self._gulps)

    def static_oheaders(self):
        # static-verification protocol (bifrost_tpu.analysis.verify):
        # the header is fixed at construction, so advertise it
        return [dict(self._header)]

    def on_sequence(self, reader, sourcename):
        return [dict(self._header)]

    def on_data(self, reader, ospans):
        arr = reader.read(self.gulp_nframe)
        if arr is None:
            return [0]
        ospan = ospans[0]
        nframe = min(arr.shape[0], ospan.nframe)
        data = ospan.data.as_numpy()
        data[:nframe] = arr[:nframe]
        return [nframe]


class CallbackSinkBlock(SinkBlock):
    """Sink invoking callbacks on each header/gulp."""

    def __init__(self, iring, seq_callback=None, data_callback=None,
                 **kwargs):
        super(CallbackSinkBlock, self).__init__(iring, **kwargs)
        self._seq_cb = seq_callback
        self._data_cb = data_callback

    def on_sequence(self, iseq):
        if self._seq_cb is not None:
            self._seq_cb(iseq.header)

    def on_data(self, ispan):
        if self._data_cb is not None:
            if ispan.ring.space == 'tpu':
                from bifrost_tpu.xfer import to_host
                self._data_cb(to_host(ispan.data))
            else:
                self._data_cb(np.array(ispan.data.as_numpy(), copy=True))


class GatherSink(CallbackSinkBlock):
    """Sink that concatenates all received gulps for assertions."""

    def __init__(self, iring, **kwargs):
        self.headers = []
        self.gulps = []
        super(GatherSink, self).__init__(
            iring,
            seq_callback=self.headers.append,
            data_callback=self.gulps.append, **kwargs)

    def result(self):
        return np.concatenate(self.gulps, axis=0) if self.gulps else None


def simple_header(shape, dtype, labels=None, name='test', gulp_nframe=None):
    """Build a minimal sequence header; shape uses -1 for the time axis."""
    n = len(shape)
    if labels is None:
        labels = ['time'] + ['dim%d' % i for i in range(1, n)]
    hdr = {
        'name': name,
        'time_tag': 0,
        '_tensor': {
            'shape': list(shape),
            'dtype': str(dtype),
            'labels': list(labels),
            'scales': [[0, 1]] * n,
            'units': [None] * n,
        },
    }
    if gulp_nframe is not None:
        hdr['gulp_nframe'] = gulp_nframe
    return hdr


def run_pipeline(pipeline=None):
    p = pipeline or bf.get_default_pipeline()
    p.run()
    return p
