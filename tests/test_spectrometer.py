"""Fused Pallas spectrometer kernel vs the float64 numpy oracle.

Runs in Pallas interpret mode on the CPU test backend; the on-hardware
equivalence (and the MXU timing) is covered by bench.py's correctness
gate + the spectrometer entry in the bench suite.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from bifrost_tpu.ops.spectrometer import (fused_spectrometer,
                                          spectrometer_oracle)


def _run(T, nfft, rfactor, time_tile, seed=0):
    rng = np.random.RandomState(seed)
    volt = rng.randint(-64, 64, size=(T, 2, nfft, 2)).astype(np.int8)
    got = np.asarray(fused_spectrometer(
        jnp.asarray(volt), rfactor=rfactor, time_tile=time_tile,
        interpret=True))
    want = spectrometer_oracle(volt, rfactor=rfactor)
    rel = np.max(np.abs(got - want)) / (np.max(np.abs(want)) + 1e-30)
    return got, want, rel


def test_matches_oracle_4096():
    got, want, rel = _run(T=8, nfft=4096, rfactor=4, time_tile=4)
    assert got.shape == (8, 4, 1024)
    assert rel < 1e-5


def test_matches_oracle_small_fft():
    got, want, rel = _run(T=8, nfft=256, rfactor=4, time_tile=8)
    assert got.shape == (8, 4, 64)
    assert rel < 1e-5


def test_rfactor_variants():
    for rf in (1, 2, 8):
        got, want, rel = _run(T=4, nfft=1024, rfactor=rf, time_tile=4,
                              seed=rf)
        assert got.shape == (4, 4, 1024 // rf)
        assert rel < 1e-5, rf


def test_time_tile_not_dividing_T_shrinks():
    # T=6 with requested tile 4 -> falls back to a divisor (3)
    got, want, rel = _run(T=6, nfft=256, rfactor=4, time_tile=4)
    assert got.shape == (6, 4, 64)
    assert rel < 1e-5


def test_rejects_bad_shapes():
    volt = np.zeros((4, 2, 300, 2), np.int8)    # not a power of two
    with pytest.raises(ValueError):
        fused_spectrometer(jnp.asarray(volt), interpret=True)
    volt = np.zeros((4, 1, 256, 2), np.int8)    # single pol
    with pytest.raises(ValueError):
        fused_spectrometer(jnp.asarray(volt), interpret=True)


def test_rejects_rfactor_beyond_radix():
    # n1 for 256 is 16; rfactor 32 cannot divide the radix split
    volt = np.zeros((4, 2, 256, 2), np.int8)
    with pytest.raises(ValueError):
        fused_spectrometer(jnp.asarray(volt), rfactor=32,
                           interpret=True)


def _run_fused_ci8_chain(raw, rfactor=4, mesh=None):
    """Build the ci8 fused FFT->stokes->reduce pipeline the two
    substitution tests share and return the gathered output."""
    import bifrost_tpu as bf
    from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from util import NumpySourceBlock, GatherSink, simple_header
    import contextlib
    T, _, NF = raw.shape
    hdr = simple_header([-1, 2, NF], 'ci8',
                        labels=['time', 'pol', 'fine_time'])
    scope = bf.block_scope(mesh=mesh) if mesh is not None \
        else contextlib.nullcontext()
    with bf.Pipeline() as p:
        src = NumpySourceBlock([raw], hdr, gulp_nframe=T)
        with scope:
            b = bf.blocks.copy(src, space='tpu')
            b = bf.blocks.fused(b, [
                FftStage('fine_time', axis_labels='freq'),
                DetectStage('stokes', axis='pol'),
                ReduceStage('freq', rfactor),
            ])
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    return sink.result()


def test_fused_block_substitutes_kernel(monkeypatch):
    """The FusedBlock spectrometer pattern-match swaps in the Pallas
    kernel (interpret mode here) and the pipeline output still matches
    the oracle."""
    from bifrost_tpu.ops import spectrometer as spec
    from bifrost_tpu.dtype import ci8 as ci8_dtype

    calls = []
    real = spec.fused_spectrometer

    def fake(v, **kw):
        calls.append(kw)
        kw.pop('interpret', None)
        return real(v, interpret=True, **kw)

    monkeypatch.setattr(spec, 'choose_precision', lambda *a, **k: None)
    monkeypatch.setattr(spec, 'fused_spectrometer', fake)

    T, NF, RF = 8, 256, 4
    rng = np.random.RandomState(3)
    raw = np.zeros((T, 2, NF), dtype=ci8_dtype)
    raw['re'] = rng.randint(-32, 32, size=(T, 2, NF))
    raw['im'] = rng.randint(-32, 32, size=(T, 2, NF))
    out = _run_fused_ci8_chain(raw, rfactor=RF)
    assert calls, "pattern matcher did not substitute the kernel"
    volt = np.stack([raw['re'], raw['im']], axis=-1).astype(np.int8)
    want = spectrometer_oracle(volt, rfactor=RF)
    rel = np.max(np.abs(out - want)) / np.max(np.abs(want))
    assert out.shape == (T, 4, NF // RF)
    assert rel < 1e-5


def test_matcher_rejects_non_matching_chains(monkeypatch):
    """Chains that differ from the spectrometer pattern keep the XLA
    path (matcher returns None)."""
    from bifrost_tpu.ops import spectrometer as spec
    from bifrost_tpu.stages import (FftStage, DetectStage, ReduceStage,
                                    match_spectrometer)
    monkeypatch.setattr(spec, 'choose_precision', lambda *a, **k: None)
    hdr = {'_tensor': {'shape': [-1, 2, 256], 'dtype': 'ci8',
                       'labels': ['time', 'pol', 'fine_time'],
                       'scales': [[0, 1]] * 3, 'units': [None] * 3}}

    def build(stages):
        h = dict(hdr)
        headers = [h]
        for s in stages:
            h = s.transform_header(h)
            headers.append(h)
        return headers

    # matching chain sanity
    st = [FftStage('fine_time', axis_labels='freq'),
          DetectStage('stokes', axis='pol'), ReduceStage('freq', 4)]
    hs = build(st)
    assert match_spectrometer(st, hs, (8, 2, 256, 2), 'int8') is not None
    # wrong detect mode
    st = [FftStage('fine_time', axis_labels='freq'),
          DetectStage('coherence', axis='pol'), ReduceStage('freq', 4)]
    hs = build(st)
    assert match_spectrometer(st, hs, (8, 2, 256, 2), 'int8') is None
    # fftshift enabled
    st = [FftStage('fine_time', axis_labels='freq', apply_fftshift=True),
          DetectStage('stokes', axis='pol'), ReduceStage('freq', 4)]
    hs = build(st)
    assert match_spectrometer(st, hs, (8, 2, 256, 2), 'int8') is None
    # mean reduce
    st = [FftStage('fine_time', axis_labels='freq'),
          DetectStage('stokes', axis='pol'),
          ReduceStage('freq', 4, op='mean')]
    hs = build(st)
    assert match_spectrometer(st, hs, (8, 2, 256, 2), 'int8') is None
    # non-power-of-two nfft never reaches the kernel
    assert match_spectrometer(st, hs, (8, 2, 192, 2), 'int8') is None


def test_choose_split_prefers_lane_native():
    from bifrost_tpu.ops.spectrometer import _choose_split
    # minor dim a multiple of 128 (the only split Mosaic compiles)
    assert _choose_split(4096, 4) == (32, 128)
    assert _choose_split(1024, 8) == (8, 128)
    # square fallback when the lane-native n1 can't host rfactor
    assert _choose_split(256, 4) == (16, 16)
    # no valid split at all -> ValueError
    with pytest.raises(ValueError):
        _choose_split(256, 32)
    with pytest.raises(ValueError):
        _choose_split(192, 4)       # not a power of two


def test_precision_modes_match_oracle():
    rng = np.random.RandomState(2)
    volt = rng.randint(-64, 64, size=(4, 2, 1024, 2)).astype(np.int8)
    want = spectrometer_oracle(volt, rfactor=4)
    for prec in (None, 'high', 'highest'):
        got = np.asarray(fused_spectrometer(
            jnp.asarray(volt), rfactor=4, time_tile=4, precision=prec,
            interpret=True))
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        # interpret mode runs f32 throughout; all modes must agree
        assert rel < 1e-5, (prec, rel)


def test_epilogue_transpose_matches_kernel_transpose():
    rng = np.random.RandomState(9)
    volt = rng.randint(-64, 64, size=(4, 2, 1024, 2)).astype(np.int8)
    a = np.asarray(fused_spectrometer(jnp.asarray(volt), rfactor=4,
                                      time_tile=4, interpret=True,
                                      transpose='kernel'))
    b = np.asarray(fused_spectrometer(jnp.asarray(volt), rfactor=4,
                                      time_tile=4, interpret=True,
                                      transpose='epilogue'))
    assert np.array_equal(a, b)


def test_kernel_usable_rejects_invalid_config():
    from bifrost_tpu.ops import spectrometer as spec
    # no split supports rfactor 32 at nfft 256 -> unusable, no compile
    assert not spec.kernel_usable(256, 32, 16, None, 'kernel')


def test_matcher_probes_usability(monkeypatch):
    """match_spectrometer consults kernel_usable with the exact
    substitution config and returns None when it fails."""
    from bifrost_tpu.ops import spectrometer as spec
    from bifrost_tpu.stages import (FftStage, DetectStage, ReduceStage,
                                    match_spectrometer)
    monkeypatch.setattr(spec, 'choose_precision', lambda *a, **k: None)
    seen = {}

    def fake_usable(nfft, rfactor, tile, prec, trans):
        seen.update(nfft=nfft, rfactor=rfactor, tile=tile,
                    prec=prec, trans=trans)
        return False

    monkeypatch.setattr(spec, 'kernel_usable', fake_usable)
    hdr = {'_tensor': {'shape': [-1, 2, 256], 'dtype': 'ci8',
                       'labels': ['time', 'pol', 'fine_time'],
                       'scales': [[0, 1]] * 3, 'units': [None] * 3}}
    st = [FftStage('fine_time', axis_labels='freq'),
          DetectStage('stokes', axis='pol'), ReduceStage('freq', 4)]
    headers = [hdr]
    h = hdr
    for s in st:
        h = s.transform_header(h)
        headers.append(h)
    assert match_spectrometer(st, headers, (8, 2, 256, 2),
                              'int8') is None
    # tile is the EFFECTIVE one after shrink-to-divisor vs the real
    # frame count (8 here), not the raw BF_SPEC_TILE default
    assert seen == {'nfft': 256, 'rfactor': 4, 'tile': 8,
                    'prec': None, 'trans': 'kernel'}


def test_split_override(monkeypatch):
    monkeypatch.setenv('BF_SPEC_SPLIT', '128')
    got, want, rel = _run(T=4, nfft=4096, rfactor=4, time_tile=4)
    assert rel < 1e-5
    # invalid overrides fall back to the square split
    monkeypatch.setenv('BF_SPEC_SPLIT', 'nope')
    got, want, rel = _run(T=4, nfft=4096, rfactor=4, time_tile=4)
    assert rel < 1e-5


def test_mesh_scope_substitutes_kernel_per_shard(monkeypatch):
    """Under BlockScope(mesh=...) the FusedBlock substitutes the
    Pallas kernel PER SHARD via shard_map on the frame axis, and the
    pipeline output still matches the oracle."""
    from bifrost_tpu.ops import spectrometer as spec
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    from bifrost_tpu.parallel.mesh import create_mesh
    import jax

    if len(jax.devices()) < 8:
        pytest.skip('needs the 8-device virtual mesh')

    calls = []
    real = spec.fused_spectrometer

    def fake(v, **kw):
        calls.append(tuple(v.shape))
        kw.pop('interpret', None)
        return real(v, interpret=True, **kw)

    monkeypatch.setattr(spec, 'choose_precision', lambda *a, **k: None)
    monkeypatch.setattr(spec, 'fused_spectrometer', fake)

    T, NF = 16, 256
    rng = np.random.RandomState(6)
    raw = np.zeros((T, 2, NF), dtype=ci8_dtype)
    raw['re'] = rng.randint(-8, 8, size=(T, 2, NF))
    raw['im'] = rng.randint(-8, 8, size=(T, 2, NF))
    out = _run_fused_ci8_chain(raw, rfactor=4,
                               mesh=create_mesh({'sp': 8}))
    # matched at the per-shard shape: T/8 frames per device
    assert (T // 8, 2, NF, 2) in calls, calls
    volt = np.stack([raw['re'], raw['im']], axis=-1).astype(np.int8)
    want = spectrometer_oracle(volt, rfactor=4)
    assert out.shape == (T, 4, NF // 4)
    assert np.max(np.abs(out - want)) / np.max(np.abs(want)) < 1e-4


def test_mesh_scope_falls_back_to_gspmd_chain(monkeypatch):
    """When the kernel is not admitted (choose_precision 'off'), the
    mesh path still runs the GSPMD-sharded XLA chain."""
    from bifrost_tpu.ops import spectrometer as spec
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    from bifrost_tpu.parallel.mesh import create_mesh
    import jax

    if len(jax.devices()) < 8:
        pytest.skip('needs the 8-device virtual mesh')

    monkeypatch.setattr(spec, 'choose_precision',
                        lambda *a, **k: 'off')
    T, NF = 8, 256
    rng = np.random.RandomState(7)
    raw = np.zeros((T, 2, NF), dtype=ci8_dtype)
    raw['re'] = rng.randint(-8, 8, size=(T, 2, NF))
    raw['im'] = rng.randint(-8, 8, size=(T, 2, NF))
    out = _run_fused_ci8_chain(raw, rfactor=4,
                               mesh=create_mesh({'sp': 8}))
    volt = np.stack([raw['re'], raw['im']], axis=-1).astype(np.int8)
    want = spectrometer_oracle(volt, rfactor=4)
    assert np.max(np.abs(out - want)) / np.max(np.abs(want)) < 1e-4


def test_fused_block_publishes_impl_record(monkeypatch, tmp_path):
    """The FusedBlock records the path its plan executes (impl_info)
    and publishes it to ProcLog <block>/impl, so benchmarks read what
    ran instead of re-deriving the substitution decision (VERDICT r3
    item 4)."""
    import bifrost_tpu as bf
    from bifrost_tpu import proclog as proclog_mod
    from bifrost_tpu.ops import spectrometer as spec
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage
    import os
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from util import NumpySourceBlock, GatherSink, simple_header

    monkeypatch.setenv('BF_PROCLOG_DIR', str(tmp_path))
    real = spec.fused_spectrometer
    monkeypatch.setattr(spec, 'choose_precision', lambda *a, **k: None)
    monkeypatch.setattr(
        spec, 'fused_spectrometer',
        lambda v, **kw: real(v, **dict(kw, interpret=True)))

    T, NF, RF = 8, 256, 4
    rng = np.random.RandomState(3)
    raw = np.zeros((T, 2, NF), dtype=ci8_dtype)
    raw['re'] = rng.randint(-32, 32, size=(T, 2, NF))
    raw['im'] = rng.randint(-32, 32, size=(T, 2, NF))
    hdr = simple_header([-1, 2, NF], 'ci8',
                        labels=['time', 'pol', 'fine_time'])
    with bf.Pipeline() as p:
        src = NumpySourceBlock([raw], hdr, gulp_nframe=T)
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(b, [
            FftStage('fine_time', axis_labels='freq'),
            DetectStage('stokes', axis='pol'),
            ReduceStage('freq', RF),
        ])
        b = bf.blocks.copy(fb, space='system')
        sink = GatherSink(b)
        p.run()
    assert sink.result().shape == (T, 4, NF // RF)
    assert fb.impl_info['impl'] == 'pallas-spectrometer'
    assert fb.impl_info['rfactor'] == RF
    assert fb.impl_info['nfft'] == NF
    # published to the proclog tree
    logs = proclog_mod.load_by_pid(os.getpid())
    impl_logs = [blk['impl'] for blk in logs.values() if 'impl' in blk]
    assert any(v.get('impl') == 'pallas-spectrometer'
               for v in impl_logs), logs


def test_compose_stages_is_the_shared_chain_constructor():
    """compose_stages builds the same function a FusedBlock compiles;
    the driver entry (__graft_entry__) goes through it (VERDICT r3
    item 6)."""
    from bifrost_tpu.stages import (FftStage, DetectStage, ReduceStage,
                                    compose_stages, walk_headers)
    T, NF, RF = 8, 64, 4
    hdr = {'name': 's', 'time_tag': 0,
           '_tensor': {'shape': [-1, 2, NF], 'dtype': 'ci8',
                       'labels': ['time', 'pol', 'fine_time'],
                       'scales': [[0, 1]] * 3, 'units': [None] * 3}}
    st = [FftStage('fine_time', axis_labels='freq'),
          DetectStage('stokes', axis='pol'),
          ReduceStage('freq', RF)]
    headers = walk_headers(st, hdr)
    fn, info = compose_stages(st, headers, (T, 2, NF, 2), 'int8')
    assert info['impl'] in ('xla-fused', 'pallas-spectrometer')
    import jax.numpy as jnp
    rng = np.random.RandomState(5)
    volt = rng.randint(-32, 32, size=(T, 2, NF, 2)).astype(np.int8)
    got = np.asarray(fn(jnp.asarray(volt)))
    want = spectrometer_oracle(volt, rfactor=RF)
    rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
    assert got.shape == (T, 4, NF // RF)
    assert rel < 1e-5
