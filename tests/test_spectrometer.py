"""Fused Pallas spectrometer kernel vs the float64 numpy oracle.

Runs in Pallas interpret mode on the CPU test backend; the on-hardware
equivalence (and the MXU timing) is covered by bench.py's correctness
gate + the spectrometer entry in the bench suite.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from bifrost_tpu.ops.spectrometer import (fused_spectrometer,
                                          spectrometer_oracle)


def _run(T, nfft, rfactor, time_tile, seed=0):
    rng = np.random.RandomState(seed)
    volt = rng.randint(-64, 64, size=(T, 2, nfft, 2)).astype(np.int8)
    got = np.asarray(fused_spectrometer(
        jnp.asarray(volt), rfactor=rfactor, time_tile=time_tile,
        interpret=True))
    want = spectrometer_oracle(volt, rfactor=rfactor)
    rel = np.max(np.abs(got - want)) / (np.max(np.abs(want)) + 1e-30)
    return got, want, rel


def test_matches_oracle_4096():
    got, want, rel = _run(T=8, nfft=4096, rfactor=4, time_tile=4)
    assert got.shape == (8, 4, 1024)
    assert rel < 1e-5


def test_matches_oracle_small_fft():
    got, want, rel = _run(T=8, nfft=256, rfactor=4, time_tile=8)
    assert got.shape == (8, 4, 64)
    assert rel < 1e-5


def test_rfactor_variants():
    for rf in (1, 2, 8):
        got, want, rel = _run(T=4, nfft=1024, rfactor=rf, time_tile=4,
                              seed=rf)
        assert got.shape == (4, 4, 1024 // rf)
        assert rel < 1e-5, rf


def test_time_tile_not_dividing_T_shrinks():
    # T=6 with requested tile 4 -> falls back to a divisor (3)
    got, want, rel = _run(T=6, nfft=256, rfactor=4, time_tile=4)
    assert got.shape == (6, 4, 64)
    assert rel < 1e-5


def test_rejects_bad_shapes():
    volt = np.zeros((4, 2, 300, 2), np.int8)    # not a power of two
    with pytest.raises(ValueError):
        fused_spectrometer(jnp.asarray(volt), interpret=True)
    volt = np.zeros((4, 1, 256, 2), np.int8)    # single pol
    with pytest.raises(ValueError):
        fused_spectrometer(jnp.asarray(volt), interpret=True)


def test_rejects_rfactor_beyond_radix():
    # n1 for 256 is 16; rfactor 32 cannot divide the radix split
    volt = np.zeros((4, 2, 256, 2), np.int8)
    with pytest.raises(ValueError):
        fused_spectrometer(jnp.asarray(volt), rfactor=32,
                           interpret=True)
