"""Multi-chip sharding tests on the 8-device virtual CPU mesh
(SURVEY.md §2.9 TPU equivalents)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bifrost_tpu.parallel import (create_mesh, sharded_spectrometer,
                                  sharded_beamform, sharded_correlate,
                                  sharded_fdmt, sharded_fir,
                                  spectrometer_step)


def _mesh2d():
    return create_mesh({'sp': 2, 'tp': 4})


def test_create_mesh():
    mesh = create_mesh()
    assert mesh.devices.size == 8
    mesh2 = _mesh2d()
    assert mesh2.axis_names == ('sp', 'tp')


def test_sharded_spectrometer_matches_local():
    mesh = create_mesh({'sp': 8})
    rng = np.random.RandomState(0)
    v = (rng.randn(16, 2, 32) + 1j * rng.randn(16, 2, 32)).astype(
        np.complex64)
    fn = sharded_spectrometer(mesh, 'sp')
    out = np.asarray(jax.jit(fn)(jnp.asarray(v)))
    s = np.fft.fft(v, axis=-1)
    x, y = s[:, 0], s[:, 1]
    xx, yy = np.abs(x) ** 2, np.abs(y) ** 2
    xy = x * np.conj(y)
    stokes = np.stack([xx + yy, xx - yy, 2 * xy.real, -2 * xy.imag],
                      axis=-1)
    np.testing.assert_allclose(out, stokes.sum(axis=0), rtol=1e-4)


def test_sharded_beamform_matches_einsum():
    mesh = create_mesh({'tp': 8})
    rng = np.random.RandomState(1)
    w = (rng.randn(4, 16) + 1j * rng.randn(4, 16)).astype(np.complex64)
    v = (rng.randn(8, 16, 8) + 1j * rng.randn(8, 16, 8)).astype(
        np.complex64)
    fn = sharded_beamform(mesh, 'tp')
    out = np.asarray(jax.jit(fn)(jnp.asarray(w), jnp.asarray(v)))
    np.testing.assert_allclose(out, np.einsum('ba,taf->tbf', w, v),
                               rtol=1e-4)


def test_sharded_correlate_matches_einsum():
    mesh = _mesh2d()
    rng = np.random.RandomState(2)
    v = (rng.randn(8, 8, 4) + 1j * rng.randn(8, 8, 4)).astype(np.complex64)
    fn = sharded_correlate(mesh, 'tp', 'sp')
    out = np.asarray(jax.jit(fn)(jnp.asarray(v)))
    np.testing.assert_allclose(out, np.einsum('taf,tbf->fab', v, v.conj()),
                               rtol=1e-4)


def test_sharded_fir_halo_exchange():
    mesh = create_mesh({'sp': 8})
    coeffs = np.array([0.5, 0.3, 0.2], np.float32)
    x = np.arange(32, dtype=np.float32)
    fn = sharded_fir(mesh, coeffs, 'sp')
    out = np.asarray(jax.jit(fn)(jnp.asarray(x)))
    # reference: causal FIR with zero initial history
    xp = np.concatenate([np.zeros(2, np.float32), x])
    expect = sum(coeffs[t] * xp[2 - t:2 - t + 32] for t in range(3))
    np.testing.assert_allclose(out, expect, rtol=1e-5)


@pytest.mark.parametrize('negative', [False, True])
def test_sharded_fdmt_matches_numpy_oracle(negative):
    """Time-sharded FDMT with max_delay halo exchange == the float64
    numpy oracle of the same plan (long-sequence dedispersion)."""
    from bifrost_tpu.ops.fdmt import Fdmt
    mesh = create_mesh({'sp': 8})
    plan = Fdmt().init(32, 8, 1400.0, -0.1)
    rng = np.random.RandomState(3)
    x = rng.randn(32, 128).astype(np.float32)
    fn = jax.jit(sharded_fdmt(mesh, plan, 'sp',
                              negative_delays=negative))
    got = np.asarray(fn(jnp.asarray(x)))
    want = plan._core_numpy(x.astype(np.float64),
                            negative_delays=negative)
    rel = np.max(np.abs(got - want)) / (np.max(np.abs(want)) + 1e-30)
    assert rel < 1e-5, rel


def test_sharded_fdmt_rejects_short_shards():
    """A per-shard window smaller than max_delay cannot be served by an
    adjacent-neighbor halo and must be rejected loudly."""
    from bifrost_tpu.ops.fdmt import Fdmt
    mesh = create_mesh({'sp': 8})
    plan = Fdmt().init(32, 16, 1400.0, -0.1)
    x = jnp.zeros((32, 64), jnp.float32)    # 8 cols/shard < 16
    with pytest.raises(ValueError, match='max_delay'):
        jax.jit(sharded_fdmt(mesh, plan, 'sp'))(x)


def test_full_spectrometer_step_dryrun():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    assert out.shape == (32, 4, 1024)   # (time, stokes, reduced freq)
