"""Multi-chip sharding tests on the 8-device virtual CPU mesh
(SURVEY.md §2.9 TPU equivalents)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bifrost_tpu.parallel import (create_mesh, sharded_spectrometer,
                                  sharded_beamform, sharded_correlate,
                                  sharded_fir, spectrometer_step)


def _mesh2d():
    return create_mesh({'sp': 2, 'tp': 4})


def test_create_mesh():
    mesh = create_mesh()
    assert mesh.devices.size == 8
    mesh2 = _mesh2d()
    assert mesh2.axis_names == ('sp', 'tp')


def test_sharded_spectrometer_matches_local():
    mesh = create_mesh({'sp': 8})
    rng = np.random.RandomState(0)
    v = (rng.randn(16, 2, 32) + 1j * rng.randn(16, 2, 32)).astype(
        np.complex64)
    fn = sharded_spectrometer(mesh, 'sp')
    out = np.asarray(jax.jit(fn)(jnp.asarray(v)))
    s = np.fft.fft(v, axis=-1)
    x, y = s[:, 0], s[:, 1]
    xx, yy = np.abs(x) ** 2, np.abs(y) ** 2
    xy = x * np.conj(y)
    stokes = np.stack([xx + yy, xx - yy, 2 * xy.real, -2 * xy.imag],
                      axis=-1)
    np.testing.assert_allclose(out, stokes.sum(axis=0), rtol=1e-4)


def test_sharded_beamform_matches_einsum():
    mesh = create_mesh({'tp': 8})
    rng = np.random.RandomState(1)
    w = (rng.randn(4, 16) + 1j * rng.randn(4, 16)).astype(np.complex64)
    v = (rng.randn(8, 16, 8) + 1j * rng.randn(8, 16, 8)).astype(
        np.complex64)
    fn = sharded_beamform(mesh, 'tp')
    out = np.asarray(jax.jit(fn)(jnp.asarray(w), jnp.asarray(v)))
    np.testing.assert_allclose(out, np.einsum('ba,taf->tbf', w, v),
                               rtol=1e-4)


def test_sharded_correlate_matches_einsum():
    mesh = _mesh2d()
    rng = np.random.RandomState(2)
    v = (rng.randn(8, 8, 4) + 1j * rng.randn(8, 8, 4)).astype(np.complex64)
    fn = sharded_correlate(mesh, 'tp', 'sp')
    out = np.asarray(jax.jit(fn)(jnp.asarray(v)))
    np.testing.assert_allclose(out, np.einsum('taf,tbf->fab', v, v.conj()),
                               rtol=1e-4)


def test_sharded_fir_halo_exchange():
    mesh = create_mesh({'sp': 8})
    coeffs = np.array([0.5, 0.3, 0.2], np.float32)
    x = np.arange(32, dtype=np.float32)
    fn = sharded_fir(mesh, coeffs, 'sp')
    out = np.asarray(jax.jit(fn)(jnp.asarray(x)))
    # reference: causal FIR with zero initial history
    xp = np.concatenate([np.zeros(2, np.float32), x])
    expect = sum(coeffs[t] * xp[2 - t:2 - t + 32] for t in range(3))
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_full_spectrometer_step_dryrun():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    assert out.shape == (32, 4, 1024)   # (time, stokes, reduced freq)
