"""bf.map expression-language tests (reference analogue: test/test_map.py,
which defines the language contract)."""

import numpy as np
import pytest

import bifrost_tpu as bf


def run_simple(x, funcstr, func):
    a = bf.asarray(np.asarray(x), space='tpu')
    y = bf.empty_like(a)
    bf.map(funcstr, {'x': a, 'y': y})
    np.testing.assert_allclose(np.asarray(y.data), func(np.asarray(x)),
                               rtol=1e-6)


def test_simple_elementwise():
    np.random.seed(1234)
    x = np.random.randint(256, size=100).astype(np.int32)
    run_simple(x, "y = x+1", lambda x: x + 1)
    run_simple(x, "y = x*3", lambda x: x * 3)
    run_simple(x, "auto tmp = x; y = tmp*tmp", lambda x: x * x)
    run_simple(x, "y = x; y += x", lambda x: x + x)


def test_simple_2d_3d():
    np.random.seed(0)
    for shape in [(9, 9), (5, 6, 7)]:
        x = np.random.randint(256, size=shape).astype(np.float32)
        run_simple(x, "y = x+1", lambda x: x + 1)


def test_rint_pow():
    x = np.arange(10).astype(np.float32)
    run_simple(x, "y = rint(pow(x, 2.f))", lambda x: x ** 2)


def test_broadcast():
    n = 89
    a = np.arange(n).astype(np.float32)
    c = bf.empty((n, n), 'f32', 'tpu')
    bf.map("c = a*b", data={'a': a, 'b': a[:, None], 'c': c})
    np.testing.assert_allclose(np.asarray(c.data), a[None, :] * a[:, None])


def test_scalar_int_division():
    # C semantics: integer division truncates toward zero
    x = np.random.RandomState(3).randint(1, 256, size=100)
    a = bf.asarray(x.astype(np.int32), space='tpu')
    y = bf.empty_like(a)
    bf.map("y = (x-m)/s", data={'x': a, 'y': y, 'm': 1, 's': 3})
    np.testing.assert_array_equal(np.asarray(y.data),
                                  np.trunc((x - 1) / 3).astype(np.int32))


def test_fftshift_index_vector():
    shape = (16, 10, 12)
    a = np.random.RandomState(1).randint(1 << 16, size=shape)
    a = a.astype(np.int32)
    aa = bf.asarray(a, space='tpu')
    b = bf.empty_like(aa)
    bf.map("b = a(_-a.shape()/2)", data={'a': aa, 'b': b})
    np.testing.assert_array_equal(np.asarray(b.data), np.fft.fftshift(a))


def test_complex_float():
    n = 32
    rng = np.random.RandomState(5)
    x = (rng.randint(-127, 128, size=(n, n)) +
         1j * rng.randint(-127, 128, size=(n, n))).astype(np.complex64)
    run_simple(x, "y.assign(x.imag, x.real)",
               lambda x: x.imag + 1j * x.real)
    run_simple(x, "y = x*x.conj()", lambda x: x * x.conj())
    run_simple(x, "y = x.mag2()", lambda x: (x * x.conj()))
    run_simple(x, "y = 3*x", lambda x: 3 * x)


def test_explicit_indexing_transpose():
    shape = (5, 6, 7)
    a = np.random.RandomState(2).randint(100, size=shape).astype(np.int32)
    aa = bf.asarray(a, space='tpu')
    b = bf.empty((7, 5, 6), 'i32', 'tpu')
    bf.map("b(i,j,k) = a(j,k,i)", shape=b.shape, axis_names=('i', 'j', 'k'),
           data={'a': aa, 'b': b})
    np.testing.assert_array_equal(np.asarray(b.data), a.transpose([2, 0, 1]))


def test_custom_shape_fixed_index():
    shape = (5, 6, 7)
    a = np.random.RandomState(2).randint(100, size=shape).astype(np.int32)
    aa = bf.asarray(a, space='tpu')
    b = bf.empty((5, 7), 'i32', 'tpu')
    bf.map("b(i,k) = a(i,j,k)", shape=b.shape, axis_names=('i', 'k'),
           data={'a': aa, 'b': b, 'j': 3})
    np.testing.assert_array_equal(np.asarray(b.data), a[:, 3, :])


def test_polarisation_products():
    n = 16
    rng = np.random.RandomState(7)
    a = (rng.randint(-127, 128, size=(n, 2)) +
         1j * rng.randint(-127, 128, size=(n, 2))).astype(np.complex64)
    aa = bf.asarray(a, space='tpu')
    b = bf.empty_like(aa)
    bf.map('''
    auto x = a(_,0);
    auto y = a(_,1);
    b(_,0).assign(x.mag2(), y.mag2());
    b(_,1) = x*y.conj();
    ''', shape=(n,), data={'a': aa, 'b': b})
    out = np.asarray(b.data)

    def mag2(x):
        return x.real ** 2 + x.imag ** 2
    np.testing.assert_allclose(out[:, 0],
                               mag2(a[:, 0]) + 1j * mag2(a[:, 1]))
    np.testing.assert_allclose(out[:, 1], a[:, 0] * a[:, 1].conj())


def test_vectorized_if():
    n = 8
    a = np.arange(n * n, dtype=np.float32).reshape(n, n)
    aa = bf.asarray(a, space='tpu')
    b = bf.zeros((n, n), 'f32', 'tpu')
    bf.map('''
    if( i > j ) {
        b(i,j) = a(i,j);
    } else {
        b(i,j) = -a(j,i);
    }
    ''', shape=(n, n), axis_names=('i', 'j'), data={'a': aa, 'b': b})
    out = np.asarray(b.data)
    expect = np.where(np.arange(n)[:, None] > np.arange(n)[None, :],
                      a, -a.T)
    np.testing.assert_array_equal(out, expect)


def test_ternary_and_bool():
    x = np.arange(10).astype(np.float32)
    run_simple(x, "y = x > 5 ? x : -x", lambda x: np.where(x > 5, x, -x))
    run_simple(x, "y = (x > 2 && x < 7) ? 1.f : 0.f",
               lambda x: ((x > 2) & (x < 7)).astype(np.float32))


def test_define_macro():
    x = np.arange(1, 11).astype(np.int32)
    run_simple(x, """
    #define square(v) ((v)*(v))
    y = square(x);
    """, lambda x: x * x)


def test_complex_integer_ci8():
    n = 64
    rng = np.random.RandomState(11)
    a = bf.empty((n,), 'ci8', 'system')
    buf = a.as_numpy()
    buf['re'] = rng.randint(-128, 128, size=n)
    buf['im'] = rng.randint(-128, 128, size=n)
    b = bf.empty((n,), 'cf32', 'system')
    bf.map('b(i) = a(i)', {'a': a, 'b': b}, shape=a.shape, axis_names=('i',))
    np.testing.assert_array_equal(
        b.as_numpy(), buf['re'].astype(np.float32) + 1j * buf['im'])


def test_host_writeback():
    x = np.arange(10, dtype=np.float32)
    y = np.zeros(10, dtype=np.float32)
    bf.map("y = x*2", {'x': x, 'y': y})
    np.testing.assert_array_equal(y, x * 2)
