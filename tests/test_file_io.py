"""File I/O tests: sigproc round trip, guppi raw read, binary io,
serialize/deserialize (reference analogues: test/test_sigproc.py,
test_binary_io.py, test_serialize.py)."""

import json
import os
import struct

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.io import sigproc as sp_io
from bifrost_tpu.io import guppi as guppi_io
from tests.util import NumpySourceBlock, GatherSink, simple_header


def _make_filterbank(path, data, fch1=1400., foff=-1., tsamp=1e-3):
    """data: (T, nifs, nchans) uint8/int8/float32"""
    nbits = data.dtype.itemsize * 8
    if data.dtype == np.float32:
        nbits = 32
    hdr = {'telescope_id': 6, 'machine_id': 0, 'data_type': 1,
           'nchans': data.shape[2], 'nifs': data.shape[1], 'nbits': nbits,
           'fch1': fch1, 'foff': foff, 'tstart': 58000.0, 'tsamp': tsamp,
           'source_name': 'TEST'}
    if data.dtype == np.int8:
        hdr['signed'] = 1
    with open(path, 'wb') as f:
        sp_io.write_header(f, hdr)
        f.write(data.tobytes())


def test_sigproc_file_reader(tmp_path):
    rng = np.random.RandomState(0)
    data = rng.randint(0, 255, size=(32, 1, 16)).astype(np.uint8)
    path = str(tmp_path / 'test.fil')
    _make_filterbank(path, data)
    sf = sp_io.SigprocFile(path)
    assert sf.header['nchans'] == 16
    assert sf.header['source_name'] == 'TEST'
    assert sf.nframe() == 32
    out = sf.read(32)
    np.testing.assert_array_equal(out, data)
    sf.close()


def test_sigproc_pipeline_roundtrip(tmp_path):
    rng = np.random.RandomState(1)
    data = rng.randint(0, 255, size=(32, 1, 8)).astype(np.uint8)
    src_path = str(tmp_path / 'in.fil')
    _make_filterbank(src_path, data)
    outdir = str(tmp_path)
    with bf.Pipeline() as p:
        b = bf.blocks.read_sigproc([src_path], gulp_nframe=8)
        sink = GatherSink(b)
        b2 = bf.blocks.copy(b)
        bf.blocks.write_sigproc(b2, path=outdir)
        p.run()
    np.testing.assert_array_equal(sink.result(), data)
    # the sink writes <name>.fil where name = source path basename
    out_path = os.path.join(outdir, 'in.fil')
    assert os.path.exists(out_path)
    sf = sp_io.SigprocFile(out_path)
    np.testing.assert_array_equal(sf.read(32), data)
    assert sf.header['fch1'] == 1400.
    sf.close()


def test_guppi_raw_reader(tmp_path):
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    nchan, ntime, npol, nblock = 4, 16, 2, 3
    blocsize = nchan * ntime * npol * 2
    rng = np.random.RandomState(2)
    blocks_data = []
    path = str(tmp_path / 'test.raw')
    with open(path, 'wb') as f:
        for b in range(nblock):
            guppi_io.write_header(f, {
                'OBSNCHAN': nchan, 'NPOL': npol, 'NBITS': 8,
                'BLOCSIZE': blocsize, 'OBSFREQ': 1500.0, 'OBSBW': 4.0,
                'STT_IMJD': 58000, 'STT_SMJD': 0, 'PKTIDX': b,
                'PKTSIZE': 8192, 'TELESCOP': 'GBT', 'BACKEND': 'GUPPI',
                'SRC_NAME': 'B0329+54'})
            raw = rng.randint(-128, 128, size=blocsize).astype(np.int8)
            blocks_data.append(raw.copy())
            f.write(raw.tobytes())
    with bf.Pipeline() as p:
        b = bf.blocks.read_guppi_raw([path])
        sink = GatherSink(b)
        p.run()
    hdr = sink.headers[0]
    assert hdr['_tensor']['dtype'] == 'ci8'
    assert hdr['_tensor']['shape'] == [-1, nchan, ntime, npol]
    assert hdr['_tensor']['labels'] == ['time', 'freq', 'fine_time', 'pol']
    assert hdr['source_name'] == 'B0329+54'
    out = sink.result()
    assert out.shape == (nblock, nchan, ntime, npol)
    got = out.view(np.int8).reshape(nblock, -1)
    np.testing.assert_array_equal(got, np.stack(blocks_data))


def test_binary_io_roundtrip(tmp_path):
    rng = np.random.RandomState(3)
    data = rng.randn(64 * 16).astype(np.float32)
    path = str(tmp_path / 'raw.bin')
    data.tofile(path)
    with bf.Pipeline() as p:
        b = bf.blocks.binary_read([path], gulp_size=16, gulp_nframe=8,
                                  dtype='f32')
        sink = GatherSink(b)
        p.run()
    np.testing.assert_array_equal(sink.result().ravel(), data)


def test_serialize_deserialize_roundtrip(tmp_path):
    rng = np.random.RandomState(4)
    data = rng.randn(16, 4).astype(np.float32)
    hdr = simple_header([-1, 4], 'f32', name='stream0')
    os.chdir(str(tmp_path))
    with bf.Pipeline() as p:
        src = NumpySourceBlock([data[:8], data[8:]], hdr, gulp_nframe=8)
        bf.blocks.serialize(src, path=str(tmp_path))
        p.run()
    assert os.path.exists(str(tmp_path / 'stream0.bf.json'))
    with bf.Pipeline() as p:
        b = bf.blocks.deserialize([str(tmp_path / 'stream0')],
                                  gulp_nframe=8)
        sink = GatherSink(b)
        p.run()
    np.testing.assert_array_equal(sink.result(), data)
    assert sink.headers[0]['_tensor']['labels'] == ['time', 'dim1']


def test_serialize_max_file_size_splitting(tmp_path):
    """Data files rotate at max_file_size with frame-offset filenames;
    deserialize reads across segment boundaries (reference:
    blocks/serialize.py:173-179)."""
    rng = np.random.RandomState(5)
    data = rng.randn(64, 8).astype(np.float32)
    gulps = [data[i * 8:(i + 1) * 8] for i in range(8)]
    hdr = simple_header([-1, 8], 'f32', name='splitme')
    hdr['name'] = 'splitme'
    with bf.Pipeline() as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=8)
        # 8 frames * 8 chans * 4 B = 256 B per gulp; cap at 600 B
        # -> rotate every 2-3 gulps
        bf.blocks.serialize(src, path=str(tmp_path), max_file_size=600)
        p.run()
    import glob as glob_mod
    dats = sorted(glob_mod.glob(str(tmp_path / 'splitme.bf.*.dat')))
    assert len(dats) > 1, dats
    # segment filenames carry the frame offset
    offs = [int(d.rsplit('.', 2)[1]) for d in dats]
    assert offs[0] == 0 and offs == sorted(offs)
    total = sum(len(open(d, 'rb').read()) for d in dats)
    assert total == data.nbytes
    # read back across segments
    with bf.Pipeline() as p:
        b = bf.blocks.deserialize([str(tmp_path / 'splitme')],
                                  gulp_nframe=16)
        sink = GatherSink(b)
        p.run()
    np.testing.assert_array_equal(sink.result(), data)
