"""Direct coverage for the small utility blocks and helpers that were
only exercised indirectly: reverse (cyclic semantics, both spaces),
print_header, and TempStorage (reference: python/bifrost/blocks/
reverse.py:36-75, print_header.py, temp_storage.py:35-68)."""

import numpy as np
import pytest

import bifrost_tpu as bf
from tests.util import NumpySourceBlock, GatherSink, simple_header


def _cyclic_reverse(x, ax):
    """Independent oracle for b(i) = a(-i): an explicit index gather,
    NOT the roll+flip expression the implementation uses — so a wrong
    formula cannot be wrong in both places at once."""
    n = x.shape[ax]
    return np.take(x, (-np.arange(n)) % n, axis=ax)


@pytest.mark.parametrize('space', ['system', 'tpu'])
def test_reverse_block_cyclic_semantics(space):
    """b(i) = a(-i): element 0 stays put, the rest reverse — the
    reference's map-gather semantics, on both ring spaces."""
    rng = np.random.RandomState(0)
    x = rng.randn(8, 6, 4).astype(np.float32)
    hdr = simple_header([-1, 6, 4], 'f32',
                        labels=['time', 'freq', 'pol'])
    with bf.Pipeline() as p:
        src = NumpySourceBlock([x], hdr, gulp_nframe=8)
        b = src
        if space == 'tpu':
            b = bf.blocks.copy(b, space='tpu')
        b = bf.blocks.reverse(b, axes=[1])
        if space == 'tpu':
            b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    np.testing.assert_allclose(sink.result(), _cyclic_reverse(x, 1),
                               rtol=1e-6)


def test_reverse_block_multiple_axes():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 6, 4).astype(np.float32)
    hdr = simple_header([-1, 6, 4], 'f32',
                        labels=['time', 'freq', 'pol'])
    with bf.Pipeline() as p:
        src = NumpySourceBlock([x], hdr, gulp_nframe=4)
        b = bf.blocks.reverse(src, axes=[1, 2])
        sink = GatherSink(b)
        p.run()
    want = _cyclic_reverse(_cyclic_reverse(x, 1), 2)
    np.testing.assert_allclose(sink.result(), want, rtol=1e-6)


def test_print_header_block(capsys):
    x = np.zeros((4, 3), np.float32)
    hdr = simple_header([-1, 3], 'f32', labels=['time', 'freq'])
    with bf.Pipeline() as p:
        src = NumpySourceBlock([x], hdr, gulp_nframe=4)
        bf.blocks.print_header(src)
        p.run()
    out = capsys.readouterr().out
    assert '_tensor' in out and 'freq' in out


def test_temp_storage_reuses_and_reallocates():
    from bifrost_tpu.temp_storage import TempStorage
    ts = TempStorage('system')
    a = ts.allocate('k', (4, 4), 'f32')
    b = ts.allocate('k', (4, 4), 'f32')
    assert a is b                      # cached across calls
    c = ts.allocate('k', (8, 4), 'f32')
    assert c is not a and tuple(c.shape) == (8, 4)
    with ts.allocate_raw(128) as raw:
        assert raw.shape[0] >= 128
    with ts.allocate_raw(64) as raw2:
        assert raw2 is raw             # reuses the larger buffer
