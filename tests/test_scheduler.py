"""Elastic control plane (bifrost_tpu.scheduler — docs/scheduler.md):
placement bin-packing + displacement ranking, the joint BF-E22x
pre-gate, live migration with ledger resume, death-triggered
re-placement, the cross-tenant arbiter, membership session hold-down,
warm-start floor rejection, and the scheduler telemetry surfaces."""

import json
import os
import socket
import sys
import time

import numpy as np
import pytest

from bifrost_tpu import affinity, fabric, proclog, scheduler, service
from bifrost_tpu.analysis import verify
from bifrost_tpu.scheduler import (PlacementError, Scheduler,
                                   SchedulerError, plan_placement)
from bifrost_tpu.telemetry import counters

from util import GatherSink

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _sched_env(tmp_path, monkeypatch):
    """Isolate durable fabric state, keep membership timers snappy,
    and shield the drills from ambient control-plane knobs."""
    monkeypatch.setenv('BF_FABRIC_STATE', str(tmp_path / 'state'))
    monkeypatch.setenv('BF_FABRIC_HEARTBEAT_SECS', '0.05')
    monkeypatch.setenv('BF_FABRIC_DEADLINE_SECS', '0.4')
    for var in ('BF_SCHED_REBALANCE_SECS',
                'BF_SCHED_DISPLACE_QUOTA_FRAC',
                'BF_SCHED_MAX_REPLACEMENTS', 'BF_SCHED_ARBITER_FRAC',
                'BF_GULP_BATCH', 'BF_SEGMENTS', 'BF_SERVE_WARM'):
        monkeypatch.delenv(var, raising=False)
    counters.reset()
    service.reset_registry()
    service.reset_warm_registry()
    yield
    service.reset_registry()
    service.reset_warm_registry()
    counters.reset()
    proclog.set_identity(None)


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(('127.0.0.1', 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def mesh(cores_by_host, links=None, name='schedt'):
    """A FabricSpec whose hosts declare core pools (static tests only:
    the control ports are never bound)."""
    hosts = {}
    for i, (h, cores) in enumerate(sorted(cores_by_host.items())):
        hosts[h] = {'control_port': 7001 + i}
        if cores:
            hosts[h]['cores'] = list(cores)
    return fabric.FabricSpec(name, hosts=hosts, links=links or {})


def synth_spec(tid, nframe=64, gulp=16, nchan=8, seed=3, **kw):
    return service.TenantSpec(tid, source={
        'kind': 'synthetic', 'nframe_total': nframe,
        'gulp_nframe': gulp, 'nchan': nchan, 'seed': seed}, **kw)


def gather_build(store, tid):
    def build(gate):
        store[tid] = GatherSink(gate)
    return build


def _codes(diags):
    return sorted(d.code for d in diags)


def _error_codes(diags):
    return sorted({d.code for d in diags if d.is_error})


# ---------------------------------------------------------------------------
# plan_placement: worst-fit, pinning, exclusion, displacement ranking
# ---------------------------------------------------------------------------

class TestPlanPlacement:
    def test_worst_fit_priority_order(self):
        spec = mesh({'big': [0, 1, 2, 3], 'small': [10, 11]})
        tenants = [synth_spec('lo', priority=1, ncores=1),
                   synth_spec('hi', priority=3, ncores=2),
                   synth_spec('mid', priority=2, ncores=2)]
        p = plan_placement(spec, tenants)
        # hi lands first (most free cores), mid breaks the 2-2 tie by
        # host name, lo takes the remaining free host
        assert p.assignments == {'lo': 'small', 'hi': 'big',
                                 'mid': 'big'}
        # the assignments map preserves tenant-submission order
        assert list(p.assignments) == ['lo', 'hi', 'mid']
        assert p.displaced == []
        assert p.capacity == {'big': 4, 'small': 2}
        assert p.demand == {'big': 4, 'small': 1}

    def test_pinning_short_circuits_packer(self):
        spec = mesh({'big': [0, 1, 2, 3], 'small': [10, 11]})
        tenants = [synth_spec('hi', priority=3, ncores=2),
                   synth_spec('mid', priority=2, ncores=2)]
        p = plan_placement(spec, tenants, pinned={'hi': 'small'})
        assert p.assignments['hi'] == 'small'
        assert p.assignments['mid'] == 'big'

    def test_exclude_removes_host_and_displaces_overflow(self):
        spec = mesh({'big': [0, 1, 2, 3], 'small': [10, 11]})
        tenants = [synth_spec('hi', priority=3, ncores=2),
                   synth_spec('mid', priority=2, ncores=2),
                   synth_spec('lo', priority=1, ncores=1)]
        p = plan_placement(spec, tenants, exclude=('big',))
        assert set(p.assignments.values()) == {'small'}
        # 5 cores demanded against 2: everyone past the budget in
        # best-first order is displaced
        assert p.displaced == ['mid', 'lo']
        assert p.demand['small'] == 5

    def test_displacement_priority_tie_broken_by_id(self):
        spec = mesh({'solo': [0, 1]})
        tenants = [synth_spec('a', priority=1), synth_spec('b', priority=1),
                   synth_spec('c', priority=2)]
        p = plan_placement(spec, tenants)
        # c survives on priority; the a-b tie breaks by id, so b is
        # the one displaced
        assert p.displaced == ['b']

    def test_displacement_priority_over_id(self):
        spec = mesh({'solo': [0]})
        tenants = [synth_spec('a', priority=1), synth_spec('z', priority=2)]
        p = plan_placement(spec, tenants)
        assert p.displaced == ['a']

    def test_coreless_host_schedulable_at_capacity_one(self):
        spec = mesh({'bare': None})
        assert scheduler.host_capacity(spec) == {'bare': 1}
        p = plan_placement(spec, [synth_spec('a', priority=2),
                                  synth_spec('b', priority=1)])
        assert p.assignments == {'a': 'bare', 'b': 'bare'}
        assert p.displaced == ['b']

    def test_e220_unsatisfiable_demand(self):
        spec = mesh({'a': [0, 1]})
        with pytest.raises(PlacementError) as ei:
            plan_placement(spec, [synth_spec('fat', ncores=5)])
        assert _codes(ei.value.diagnostics) == ['BF-E220']
        assert 'BF-E220' in str(ei.value)

    def test_e220_waived_by_best_effort(self):
        # the re-placement path: an orphan lands displaced and
        # shedding rather than being refused
        spec = mesh({'a': [0, 1]})
        p = plan_placement(spec, [synth_spec('fat', ncores=5)],
                           best_effort=True)
        assert p.assignments == {'fat': 'a'}
        assert p.displaced == ['fat']

    def test_e221_unknown_pin_and_e220_compose(self):
        spec = mesh({'a': [0, 1]})
        with pytest.raises(PlacementError) as ei:
            plan_placement(spec, [synth_spec('fat', ncores=5),
                                  synth_spec('lost')],
                           pinned={'lost': 'ghost'})
        assert _codes(ei.value.diagnostics) == ['BF-E220', 'BF-E221']

    def test_all_hosts_excluded(self):
        spec = mesh({'a': [0], 'b': [1]})
        with pytest.raises(PlacementError) as ei:
            plan_placement(spec, [synth_spec('t')],
                           exclude=('a', 'b'))
        assert _codes(ei.value.diagnostics) == ['BF-E220']

    def test_as_dict_roundtrip(self):
        spec = mesh({'a': [0]})
        p = plan_placement(spec, [synth_spec('t')])
        d = p.as_dict()
        assert d['assignments'] == {'t': 'a'}
        assert json.loads(json.dumps(d)) == d
        assert p.tenants_on('a') == ['t']


# ---------------------------------------------------------------------------
# verify_placement: the joint BF-E22x pre-gate
# ---------------------------------------------------------------------------

class TestVerifyPlacement:
    def test_fabric_pregate_e222_exact_codes(self):
        # the fabric cannot come up (BF-E200 unknown endpoint), but
        # the tenant set is clean: only the fabric side may fail
        spec = {'name': 't', 'hosts': {'a': {'control_port': 7001}},
                'links': {'l': {'kind': 'pipe', 'src': 'a',
                                'dst': 'ghost', 'port': 7100}}}
        diags = verify.verify_placement(spec, [{'id': 't1'}],
                                        {'t1': 'a'})
        assert _error_codes(diags) == ['BF-E200', 'BF-E222']
        e222 = [d for d in diags if d.code == 'BF-E222'][0]
        assert 'BF-E200' in e222.message

    def test_service_pregate_e223_exact_codes(self):
        # fabric is clean; one host's tenant group fails
        # verify_service (BF-E211 shed quota below one gulp span)
        spec = {'name': 't',
                'hosts': {'a': {'control_port': 7001,
                                'cores': [0, 1]},
                          'b': {'control_port': 7002}},
                'links': {'l': {'kind': 'pipe', 'src': 'a', 'dst': 'b',
                                'port': 7100, 'window': 2}}}
        tenants = [{'id': 'bad', 'quota_bytes_per_s': 100,
                    'gulp_nbyte': 4096},
                   {'id': 'ok'}]
        diags = verify.verify_placement(spec, tenants,
                                        {'bad': 'a', 'ok': 'b'})
        assert _error_codes(diags) == ['BF-E211', 'BF-E223']
        e223 = [d for d in diags if d.code == 'BF-E223'][0]
        assert e223.block == 'host:a'
        assert 'BF-E211' in e223.message and 'bad' in e223.message

    def test_oversubscription_w224_matches_displacement(self):
        spec = mesh({'a': [0]})
        tenants = [synth_spec('hi', priority=2), synth_spec('lo', priority=1)]
        diags = verify.verify_placement(
            spec, tenants, {'hi': 'a', 'lo': 'a'})
        w = [d for d in diags if d.code == 'BF-W224']
        assert w and not w[0].is_error
        assert not [d for d in diags if d.is_error]
        # the warning and the packer agree on who pays
        assert plan_placement(spec, tenants).displaced == ['lo']

    def test_scheduler_place_strict_refuses_and_passes_diags(self):
        spec = mesh({'a': [0, 1], 'b': [2, 3]})
        bad = synth_spec('bad', quota_bytes_per_s=100)
        bad = service.TenantSpec.coerce(
            dict(bad.as_dict(), gulp_nbyte=4096))
        sched = Scheduler(spec)
        with pytest.raises(PlacementError) as ei:
            sched.place([bad], pinned={'bad': 'a'})
        codes = _codes(ei.value.diagnostics)
        assert 'BF-E211' in codes and 'BF-E223' in codes
        # a refused placement is not counted
        assert counters.get('scheduler.placements') == 0
        # non-strict: the placement comes back carrying the errors
        lax = Scheduler(spec, strict=False)
        p = lax.place([bad], pinned={'bad': 'a'})
        assert 'BF-E223' in _codes(p.diagnostics)
        assert counters.get('scheduler.placements') == 1


# ---------------------------------------------------------------------------
# partition_cores under displacement (the host-local half of the story)
# ---------------------------------------------------------------------------

class TestPartitionCores:
    def test_oversubscribed_round_robin_shares(self):
        shares = affinity.partition_cores(
            {'a': 3.0, 'b': 2.0, 'c': 1.0}, cores=[4, 5])
        # more tenants than cores: one SHARED core each, round-robin
        assert shares == {'a': [4], 'b': [5], 'c': [4]}

    def test_one_core_floor_under_skewed_weights(self):
        shares = affinity.partition_cores(
            {'big': 100.0, 'tiny': 1.0}, cores=[0, 1, 2, 3])
        assert len(shares['tiny']) == 1       # floored, not starved
        assert len(shares['big']) == 3
        assert sorted(shares['big'] + shares['tiny']) == [0, 1, 2, 3]


# ---------------------------------------------------------------------------
# ledger_frontier
# ---------------------------------------------------------------------------

def test_ledger_frontier_reads_durable_acks():
    led = fabric.AckLedger('fab', 'h1', 'stream')
    led.note_acked('s0', 0, 16, 1024)
    led.note_acked('s1', 0, 32, 2048)
    led.save(force=True)
    # default: the max frontier across sequences; seq_name selects
    assert scheduler.ledger_frontier('fab', 'h1', 'stream') == 32
    assert scheduler.ledger_frontier('fab', 'h1', 'stream',
                                     seq_name='s0') == 16
    assert scheduler.ledger_frontier('fab', 'h1', 'stream',
                                     seq_name='nope') == 0
    # no history == cold start == replay from frame 0
    assert scheduler.ledger_frontier('fab', 'ghost', 'stream') == 0


# ---------------------------------------------------------------------------
# Scheduler: apply + displacement, migration, re-placement, watch
# ---------------------------------------------------------------------------

class TestSchedulerApply:
    def test_apply_scales_displaced_quota_and_publishes(self):
        spec = mesh({'solo': [0]})
        mgr = service.JobManager(max_tenants=4, warm=False)
        sched = Scheduler(spec, managers={'solo': mgr})
        store = {}
        tenants = [synth_spec('keep', priority=3),
                   synth_spec('bulk', priority=1,
                              quota_bytes_per_s=50000.0)]
        p = sched.place(tenants)
        assert p.displaced == ['bulk']
        jobs = sched.apply(build={'keep': gather_build(store, 'keep'),
                                  'bulk': None})
        try:
            # the displaced tenant keeps running at a scaled quota
            # (BF_SCHED_DISPLACE_QUOTA_FRAC default 0.5), counted
            gate = Scheduler._quota_gate(jobs['bulk'])
            assert gate.quota_bytes_per_s == pytest.approx(25000.0)
            assert counters.get('scheduler.displaced') == 1
            assert counters.get('service.bulk.quota_retunes') >= 1
            assert mgr.wait(60) == {'keep': 'DONE', 'bulk': 'DONE'}
        finally:
            sched.shutdown()
        assert np.array_equal(store['keep'].result(),
                              service.SyntheticSource.payload(64, 8, 3))
        # the placement pane + the joined rollup both carry the row
        pane = proclog.load_by_pid(os.getpid())['sched']['placements']
        assert pane['p.keep.host'] == 'solo'
        assert pane['p.bulk.displaced'] == 1
        rows = scheduler.joined_rollup([os.getpid()])
        mine = [r for r in rows if r['tenants'].get('bulk')]
        assert mine and mine[0]['tenants']['bulk']['displaced'] == 1
        text = scheduler.format_rollup(rows)
        assert 'bulk' in text and 'displaced=1' in text
        assert scheduler.format_rollup([]).strip().startswith('(no ')

    def test_apply_without_placement_raises(self):
        sched = Scheduler(mesh({'a': [0]}))
        with pytest.raises(SchedulerError):
            sched.apply()


class TestMigration:
    def test_migrate_resumes_at_frontier_and_counts(self):
        spec = mesh({'h1': [0, 1], 'h2': [0, 1]})
        mgr1 = service.JobManager(max_tenants=2, warm=False)
        mgr2 = service.JobManager(max_tenants=2, warm=False)
        sched = Scheduler(spec, managers={'h1': mgr1, 'h2': mgr2})
        store = {}
        sched.place([synth_spec('mig', seed=5)], pinned={'mig': 'h1'})
        sched.apply(build={'mig': gather_build(store, 'mig')},
                    start=False)
        job = sched.migrate('mig', 'h2', resume_frame=16)
        try:
            assert job.wait(60) == 'DONE'
        finally:
            sched.shutdown()
        # only the unacked tail replays, byte-for-byte
        assert np.array_equal(
            store['mig'].result(),
            service.SyntheticSource.payload(64, 8, 5)[16:])
        assert sched.tenants['mig'].source.get('start_frame') == 16
        assert sched.placement.assignments['mig'] == 'h2'
        assert counters.get('scheduler.migrations') == 1
        assert counters.get('scheduler.resume.skipped_frames') == 16
        assert mgr1.job('mig').state == 'CANCELLED'

    def test_migrate_errors(self):
        spec = mesh({'h1': [0], 'h2': [0]})
        sched = Scheduler(spec, managers={})
        with pytest.raises(SchedulerError):
            sched.migrate('ghost', 'h1')
        sched.place([synth_spec('t')], pinned={'t': 'h1'})
        with pytest.raises(SchedulerError):
            sched.migrate('t', 'nowhere')
        with pytest.raises(SchedulerError):
            sched.migrate('t', 'h2')      # no local manager


class _StubMembership(object):
    def __init__(self, dead=()):
        self.dead = list(dead)

    def counts(self):
        return {'total': 2, 'alive': 2 - len(self.dead),
                'dead': list(self.dead), 'death_events': len(self.dead),
                'rejoin_events': 0, 'readopt_events': 0}


class TestReplacement:
    def _scheduler(self, store, tid, seed=9, resume=16):
        spec = mesh({'h1': [0, 1], 'h2': [0, 1]})
        mgr2 = service.JobManager(max_tenants=2, warm=False)
        sched = Scheduler(spec, managers={'h2': mgr2},
                          resume_of=lambda t, dead: resume)
        sched.place([synth_spec(tid, nframe=48, seed=seed)],
                    pinned={tid: 'h1'})
        # h1 has no local manager: apply places nothing here, but the
        # build must be registered for a later re-placement migrate
        assert sched.apply(build={tid: gather_build(store, tid)}) == {}
        sched.set_build(tid, gather_build(store, tid))
        return sched, mgr2

    def test_host_death_replaces_with_resume(self):
        store = {}
        sched, mgr2 = self._scheduler(store, 'vic')
        moved = sched.handle_host_death('h1')
        try:
            assert set(moved) == {'vic'}
            assert moved['vic'].wait(60) == 'DONE'
        finally:
            sched.shutdown()
        assert sched.placement.assignments['vic'] == 'h2'
        assert np.array_equal(
            store['vic'].result(),
            service.SyntheticSource.payload(48, 8, 9)[16:])
        assert counters.get('scheduler.replacements') == 1
        assert counters.get('scheduler.resume.skipped_frames') == 16

    def test_replacement_event_cap_refuses(self, monkeypatch):
        monkeypatch.setenv('BF_SCHED_MAX_REPLACEMENTS', '0')
        store = {}
        sched, _mgr2 = self._scheduler(store, 'capped')
        try:
            assert sched.handle_host_death('h1') == {}
        finally:
            sched.shutdown()
        assert counters.get('scheduler.replacements.refused') == 1
        assert counters.get('scheduler.replacements') == 0

    def test_check_handles_each_dead_host_once(self):
        spec = mesh({'h1': [0], 'h2': [0]})
        sched = Scheduler(spec, membership=_StubMembership(['h1']))
        sched.place([synth_spec('t')], pinned={'t': 'h2'})
        assert sched.check() == ['h1']
        assert sched.check() == []            # already handled
        # a membership-reported name outside the spec is ignored
        sched.membership = _StubMembership(['h1', 'elsewhere'])
        assert sched.check() == []

    def test_watch_replaces_in_background(self):
        store = {}
        sched, mgr2 = self._scheduler(store, 'wvic')
        sched.membership = _StubMembership(['h1'])
        sched.watch(poll_s=0.05)
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and \
                    (mgr2.job('wvic') is None or
                     mgr2.job('wvic').state != 'DONE'):
                time.sleep(0.05)
            assert mgr2.job('wvic') is not None
            assert mgr2.job('wvic').wait(30) == 'DONE'
        finally:
            sched.shutdown()
        assert counters.get('scheduler.replacements') == 1


# ---------------------------------------------------------------------------
# the cross-tenant arbiter
# ---------------------------------------------------------------------------

class _Gate(object):
    def __init__(self, rate):
        self.quota_bytes_per_s = rate
        self.retunes = []

    def retune(self, new):
        self.retunes.append(new)
        self.quota_bytes_per_s = new


class _FakeJob(object):
    def __init__(self, tid, priority, gate, ok=None):
        self.spec = service.TenantSpec(tid, priority=priority)
        self.state = 'RUNNING'
        self.gate = gate
        self.pipeline = None
        self._ok = ok

    def slo_rollup(self):
        return {'ok': self._ok} if self._ok is not None else {}


class _FakeMgr(object):
    def __init__(self, jobs):
        self._jobs = list(jobs)

    def jobs(self):
        return list(self._jobs)


class TestArbiter:
    @pytest.fixture(autouse=True)
    def _stub_gates(self, monkeypatch):
        monkeypatch.setattr(Scheduler, '_quota_gate',
                            staticmethod(lambda job: job.gate))

    def test_arbitrate_moves_quota_from_lowest_donor(self):
        violator = _FakeJob('v', 3, _Gate(200.0), ok=False)
        donor = _FakeJob('d', 1, _Gate(1000.0))
        peer = _FakeJob('p', 3, _Gate(500.0))   # same priority: exempt
        sched = Scheduler(mesh({'x': [0]}), managers={
            'x': _FakeMgr([violator, donor, peer])})
        transfers = sched.arbitrate(frac=0.5)
        assert transfers == [('v', 'd', pytest.approx(500.0))]
        assert donor.gate.quota_bytes_per_s == pytest.approx(500.0)
        assert violator.gate.quota_bytes_per_s == pytest.approx(700.0)
        assert peer.gate.retunes == []
        assert counters.get('scheduler.arbiter.retunes') == 1

    def test_arbitrate_refused_without_donor(self):
        violator = _FakeJob('v2', 2, _Gate(200.0), ok=False)
        rich_peer = _FakeJob('p2', 2, _Gate(900.0))  # equal priority
        sched = Scheduler(mesh({'x': [0]}), managers={
            'x': _FakeMgr([violator, rich_peer])})
        assert sched.arbitrate(frac=0.5) == []
        assert counters.get('scheduler.arbiter.refused') == 1
        assert counters.get('scheduler.arbiter.retunes') == 0
        assert violator.gate.retunes == []


# ---------------------------------------------------------------------------
# membership: new-session hold-down, confirm_resume, readopt counters
# ---------------------------------------------------------------------------

class TestSessionHoldDown:
    def _beat(self, sock, port, session, host='b', state='OK'):
        sock.sendto(json.dumps(
            {'host': host, 'role': 'worker', 'state': state,
             'session': session}).encode(), ('127.0.0.1', port))

    def _poll(self, fn, timeout=10):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if fn():
                return True
            time.sleep(0.02)
        return False

    def test_hold_down_confirm_resume_and_counters(self):
        ports = _free_ports(2)
        spec = fabric.FabricSpec('m', hosts={
            'a': {'address': '127.0.0.1', 'control_port': ports[0]},
            'b': {'address': '127.0.0.1', 'control_port': ports[1]},
        }, links={'l': {'kind': 'pipe', 'src': 'a', 'dst': 'b',
                        'port': 1}})
        before = counters.snapshot()
        ma = fabric.Membership(spec, 'a').start()
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # first contact: no prior session, adopted directly
            assert self._poll(lambda: (
                self._beat(tx, ports[0], 's1') or
                ma.peers_snapshot()['b']['alive']))
            assert ma.counts()['readopt_events'] == 0

            # a NEW session (restarted peer) is held for one heartbeat
            # interval: the first beat must NOT flip the table
            self._beat(tx, ports[0], 's2')
            time.sleep(0.02)
            assert ma.counts()['readopt_events'] == 0
            # ... and a later beat past the hold-down adopts it,
            # counted as a READOPT, not a rejoin (b never died)
            time.sleep(0.1)
            assert self._poll(lambda: (
                self._beat(tx, ports[0], 's2') or
                ma.counts()['readopt_events'] == 1))
            assert ma.counts()['rejoin_events'] == 0
            assert counters.get('fabric.peers.readopted') - \
                before.get('fabric.peers.readopted', 0) == 1
            assert counters.get('fabric.peers.rejoined') - \
                before.get('fabric.peers.rejoined', 0) == 0

            # confirm_resume short-circuits the hold-down: the resume
            # probe vouches for the new session immediately
            self._beat(tx, ports[0], 's3')
            assert self._poll(lambda: (
                ma.confirm_resume('b') or
                ma.counts()['readopt_events'] == 2))

            # probe-before-beat race: a confirmation with nothing held
            # is remembered, and the first new-session beat adopts
            ma.confirm_resume('b')
            assert self._poll(lambda: (
                self._beat(tx, ports[0], 's4') or
                ma.counts()['readopt_events'] == 3))
            assert ma.counts()['rejoin_events'] == 0

            # silence past the deadline: a real death — the DETECTION
            # lands on the membership thread's next tick, so poll the
            # counted event, not the client-side time math
            assert self._poll(
                lambda: ma.counts()['death_events'] >= 1)
            assert 'b' in ma.counts()['dead']
            # ...then a new session after death counts BOTH rejoin
            # and readopt once adopted
            self._beat(tx, ports[0], 's5')
            time.sleep(0.1)
            assert self._poll(lambda: (
                self._beat(tx, ports[0], 's5') or
                ma.counts()['rejoin_events'] == 1))
            assert ma.counts()['readopt_events'] == 4
            assert not ma.is_dead('b')
            assert counters.get('fabric.peers.rejoined') - \
                before.get('fabric.peers.rejoined', 0) == 1
        finally:
            tx.close()
            ma.stop()


# ---------------------------------------------------------------------------
# warm-start floor rejection (migration onto a smaller survivor)
# ---------------------------------------------------------------------------

class TestWarmFloors:
    def test_floor_violation_rejects_stale_profile(self, monkeypatch):
        """A harvested profile whose gulp_batch would introduce a
        ring-capacity BF-E on THIS build must not warm-start it: the
        rejection lands on service.warm.rejected_stale and the job
        runs cold."""
        store = {}
        mgr = service.JobManager(max_tenants=4, warm=True)
        cold = mgr.submit(synth_spec('wf0', nframe=32, gulp=8),
                          gather_build(store, 'wf0'))
        cold.start()
        assert cold.wait(60) == 'DONE'
        sig = cold.topology_hash
        assert sig in service._WARM

        # clean warm start first (control): same topology, new id
        warm = mgr.submit(synth_spec('wf1', nframe=32, gulp=8),
                          gather_build(store, 'wf1'))
        assert warm.warm and not warm.warm_rejected
        warm.start()
        assert warm.wait(60) == 'DONE'

        # poison the harvested knobs with a K the local verifier
        # refuses (the migration-onto-smaller-rings case)
        service._WARM[sig]['knobs']['gulp_batch'] = 64
        real = verify.verify_pipeline

        def vetoing(pipeline):
            out = list(real(pipeline))
            if verify._overrides():
                out.append(verify.Diagnostic(
                    'BF-E101', 'stale warm K deadlocks this ring',
                    block='x', ring='r'))
            return out
        monkeypatch.setattr(verify, 'verify_pipeline', vetoing)
        rejected0 = counters.get('service.warm.rejected_stale')
        job = mgr.submit(synth_spec('wf2', nframe=32, gulp=8),
                         gather_build(store, 'wf2'))
        assert not job.warm and job.warm_rejected
        assert counters.get('service.warm.rejected_stale') == \
            rejected0 + 1
        job.start()
        assert job.wait(60) == 'DONE'         # cold, but it runs

    def test_floors_helper_ignores_trivial_knobs(self):
        # no geometry overrides -> nothing to gate
        assert not service._warm_floors_violate(None, {})
        assert not service._warm_floors_violate(None, {'gulp_batch': 1})
        assert not service._warm_floors_violate(None, None)


# ---------------------------------------------------------------------------
# telemetry + CLI surfaces
# ---------------------------------------------------------------------------

def test_telemetry_section_in_snapshot():
    counters.inc('scheduler.placements')
    counters.inc('scheduler.migrations', 2)
    counters.inc('scheduler.resume.skipped_frames', 224)
    sec = scheduler.telemetry_section()
    assert sec['placements'] == 1
    assert sec['migrations'] == 2
    assert sec['resume_skipped_frames'] == 224
    from bifrost_tpu import telemetry
    snap = telemetry.snapshot()
    assert snap['scheduler']['migrations'] == 2


def test_like_top_sched_pane():
    sys.path.insert(0, os.path.join(ROOT, 'tools'))
    try:
        import like_top
    finally:
        sys.path.pop(0)
    sched_rows = {4321: {'fabric': 'schedt', 'ntenants': 2,
                         'replacement_events': 1, 'dead_hosts': 'h1',
                         'p.vic.host': 'h2', 'p.vic.displaced': 0,
                         'p.bulk.host': 'h2', 'p.bulk.displaced': 1}}
    lines = like_top.render_text(
        like_top.get_load_average(), {},
        like_top.get_memory_swap_usage(), None, {}, sched=sched_rows)
    text = '\n'.join(lines)
    assert '[sched] pid 4321  fabric schedt  2 tenant(s)' in text
    assert 'replacements 1' in text and 'dead: h1' in text
    assert 'bulk->h2(displaced)' in text
    assert 'vic->h2' in text and 'vic->h2(displaced)' not in text


def test_placement_codes_catalogued():
    for code in ('BF-E220', 'BF-E221', 'BF-E222', 'BF-E223',
                 'BF-W224'):
        assert code in verify.CODES
        with open(os.path.join(ROOT, 'docs', 'analysis.md')) as f:
            assert code in f.read()
