"""Mesh-in-the-pipeline tests: blocks consume ``BlockScope(mesh=...)``
and run their gulp functions sharded over the 8-device virtual CPU mesh,
with output identical to the single-device run (VERDICT r1 item 2;
the TPU generalization of the reference's per-block gpu=N placement,
reference: python/bifrost/pipeline.py:365-366)."""

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.parallel import create_mesh

from util import (NumpySourceBlock, GatherSink, CallbackSinkBlock,
                  simple_header)


def _spectro_inputs():
    rng = np.random.RandomState(42)
    gulps = [(rng.randn(16, 2, 32) + 1j * rng.randn(16, 2, 32))
             .astype(np.complex64) for _ in range(3)]
    hdr = simple_header([-1, 2, 32], 'cf32',
                        labels=['time', 'pol', 'fine_time'])
    return gulps, hdr


def _run_fused_chain(mesh):
    from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage
    gulps, hdr = _spectro_inputs()
    with bf.Pipeline() as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
        b = bf.blocks.copy(src, space='tpu')
        with bf.block_scope(mesh=mesh):
            b = bf.blocks.fused(b, [
                FftStage('fine_time', axis_labels='freq'),
                DetectStage('stokes', axis='pol'),
                ReduceStage('freq', factor=4)])
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    return sink.result()


def test_fused_chain_on_mesh_matches_single_device():
    """The fused FFT->detect->reduce chain through rings, sharded over
    the mesh (GSPMD over the frame axis), must be bit-compatible with
    the single-device run."""
    base = _run_fused_chain(None)
    meshed = _run_fused_chain(create_mesh({'sp': 8}))
    assert base is not None and meshed is not None
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-4)


def test_fused_chain_on_2d_mesh():
    meshed = _run_fused_chain(create_mesh({'sp': 2, 'tp': 4}))
    base = _run_fused_chain(None)
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-4)


def test_fused_chain_mesh_indivisible_falls_back():
    """gulp_nframe=12 does not divide 8 shards: the block must fall back
    to unsharded execution and still be correct."""
    from bifrost_tpu.stages import FftStage, DetectStage
    rng = np.random.RandomState(3)
    data = (rng.randn(12, 2, 16) + 1j * rng.randn(12, 2, 16)) \
        .astype(np.complex64)
    hdr = simple_header([-1, 2, 16], 'cf32',
                        labels=['time', 'pol', 'fine_time'])
    with bf.Pipeline() as p:
        src = NumpySourceBlock([data], hdr, gulp_nframe=12)
        b = bf.blocks.copy(src, space='tpu')
        with bf.block_scope(mesh=create_mesh({'sp': 8})):
            b = bf.blocks.fused(b, [FftStage('fine_time'),
                                    DetectStage('stokes', axis='pol')])
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    s = np.fft.fft(data, axis=-1)
    x, y = s[:, 0], s[:, 1]
    xy = x * np.conj(y)
    expect = np.stack([np.abs(x)**2 + np.abs(y)**2,
                       np.abs(x)**2 - np.abs(y)**2,
                       2 * xy.real, -2 * xy.imag], axis=1)
    np.testing.assert_allclose(sink.result(), expect, rtol=1e-4, atol=1e-3)


def _run_correlate(mesh, gulps, hdr, nint):
    with bf.Pipeline() as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=gulps[0].shape[0])
        b = bf.blocks.copy(src, space='tpu')
        with bf.block_scope(mesh=mesh):
            b = bf.blocks.correlate(b, nframe_per_integration=nint)
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    return sink.result()


def test_correlate_on_mesh_matches_single_device():
    """Time-parallel correlation: per-shard cross-multiply + psum over
    the mesh time axis (parallel.ops pattern), integrated across gulps."""
    rng = np.random.RandomState(7)
    gulps = [(rng.randn(8, 4, 3, 2) + 1j * rng.randn(8, 4, 3, 2))
             .astype(np.complex64) for _ in range(2)]
    hdr = simple_header([-1, 4, 3, 2], 'cf32',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=8)
    base = _run_correlate(None, gulps, hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 8}), gulps, hdr, 16)
    assert base is not None and meshed is not None
    np.testing.assert_allclose(meshed, base, rtol=1e-4, atol=1e-3)


def test_correlate_ci8_on_mesh():
    """int8 MXU 3-matmul path under shard_map: int32 partials psum."""
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    rng = np.random.RandomState(8)
    raw = np.zeros((16, 2, 3, 2), dtype=ci8_dtype)
    raw['re'] = rng.randint(-16, 16, size=raw.shape)
    raw['im'] = rng.randint(-16, 16, size=raw.shape)
    hdr = simple_header([-1, 2, 3, 2], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=16)
    base = _run_correlate(None, [raw], hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 8}), [raw], hdr, 16)
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-5)


def test_fir_on_mesh_matches_single_device():
    """Sequence-parallel FIR: inter-gulp state feeds shard 0, interior
    shard boundaries exchange halos via ppermute."""
    rng = np.random.RandomState(9)
    gulps = [rng.randn(16, 3).astype(np.float32) for _ in range(3)]
    coeffs = np.array([0.5, 0.3, 0.2], np.float32)
    hdr = simple_header([-1, 3], 'f32', gulp_nframe=16)

    def run(mesh):
        with bf.Pipeline() as p:
            src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
            b = bf.blocks.copy(src, space='tpu')
            with bf.block_scope(mesh=mesh):
                b = bf.blocks.fir(b, coeffs)
            b = bf.blocks.copy(b, space='system')
            sink = GatherSink(b)
            p.run()
        return sink.result()

    base = run(None)
    meshed = run(create_mesh({'sp': 8}))
    # oracle: causal FIR over the concatenated stream
    x = np.concatenate(gulps, axis=0)
    xp = np.concatenate([np.zeros((2, 3), np.float32), x])
    expect = sum(coeffs[t] * xp[2 - t:2 - t + 48] for t in range(3))
    np.testing.assert_allclose(base, expect, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(meshed, expect, rtol=1e-5, atol=1e-5)


def test_correlate_mesh_partial_gulp_fallback():
    """A partial gulp mid-integration routes to the single-device build
    while the carried accumulator lives on the mesh; the block must
    reconcile the device sets both directions (code-review regression)."""
    rng = np.random.RandomState(11)
    gulps = [(rng.randn(n, 2, 3, 2) + 1j * rng.randn(n, 2, 3, 2))
             .astype(np.complex64) for n in (8, 4, 4)]
    hdr = simple_header([-1, 2, 3, 2], 'cf32',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=8)
    base = _run_correlate(None, gulps, hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 8}), gulps, hdr, 16)
    assert base is not None and meshed is not None
    np.testing.assert_allclose(meshed, base, rtol=1e-4, atol=1e-3)


def test_fir_mesh_partial_gulp_fallback():
    """A partial final gulp after sharded gulps: the carried FIR state is
    mesh-committed but the tail build is single-device (code-review
    regression)."""
    rng = np.random.RandomState(12)
    gulps = [rng.randn(n, 3).astype(np.float32) for n in (16, 16, 4)]
    coeffs = np.array([0.5, 0.3, 0.2], np.float32)
    hdr = simple_header([-1, 3], 'f32', gulp_nframe=16)

    def run(mesh):
        with bf.Pipeline() as p:
            src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
            b = bf.blocks.copy(src, space='tpu')
            with bf.block_scope(mesh=mesh):
                b = bf.blocks.fir(b, coeffs)
            b = bf.blocks.copy(b, space='system')
            sink = GatherSink(b)
            p.run()
        return sink.result()

    base = run(None)
    meshed = run(create_mesh({'sp': 8}))
    assert base is not None and meshed is not None
    assert meshed.shape[0] == 36
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-5)


def test_fir_on_mesh_with_decimation():
    rng = np.random.RandomState(10)
    gulps = [rng.randn(16, 2).astype(np.float32) for _ in range(2)]
    coeffs = np.array([0.25, 0.5, 0.25], np.float32)
    hdr = simple_header([-1, 2], 'f32', gulp_nframe=16)

    def run(mesh):
        with bf.Pipeline() as p:
            src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
            b = bf.blocks.copy(src, space='tpu')
            with bf.block_scope(mesh=mesh):
                b = bf.blocks.fir(b, coeffs, decim=2)
            b = bf.blocks.copy(b, space='system')
            sink = GatherSink(b)
            p.run()
        return sink.result()

    base = run(None)
    meshed = run(create_mesh({'sp': 8}))
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-5)


def test_correlate_2d_mesh_station_sharding():
    """On a 2-D mesh the correlator also shards the station axis: each
    rank computes its antenna-row block against the all_gathered
    column axis (distributed visibility matrix)."""
    rng = np.random.RandomState(21)
    gulps = [(rng.randn(8, 3, 4, 2) + 1j * rng.randn(8, 3, 4, 2))
             .astype(np.complex64) for _ in range(2)]
    hdr = simple_header([-1, 3, 4, 2], 'cf32',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=8)
    base = _run_correlate(None, gulps, hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 4, 'tp': 2}),
                            gulps, hdr, 16)
    assert base is not None and meshed is not None
    np.testing.assert_allclose(meshed, base, rtol=1e-4, atol=1e-3)


def test_correlate_2d_mesh_ci8_station_sharding():
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    rng = np.random.RandomState(22)
    raw = np.zeros((16, 2, 4, 2), dtype=ci8_dtype)
    raw['re'] = rng.randint(-16, 16, size=raw.shape)
    raw['im'] = rng.randint(-16, 16, size=raw.shape)
    hdr = simple_header([-1, 2, 4, 2], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=16)
    base = _run_correlate(None, [raw], hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 4, 'tp': 2}),
                            [raw], hdr, 16)
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-5)


def _run_fdmt_block(mesh, x, gulp, md):
    """FDMT block pipeline over (freq, time) ringlet layout; returns
    (concatenated DM-time output, the block instance)."""
    nchan, T = x.shape
    hdr = {
        'name': 'fdmt-mesh', 'time_tag': 0,
        '_tensor': {
            'shape': [nchan, -1],
            'dtype': 'f32',
            'labels': ['freq', 'time'],
            'scales': [[100.0, 1.0], [0.0, 1e-3]],
            'units': ['MHz', 's'],
        },
    }
    gulps = [x[:, i:i + gulp].copy() for i in range(0, T, gulp)]

    class FreqSource(bf.SourceBlock):
        def create_reader(self, name):
            import contextlib
            return contextlib.nullcontext()

        def on_sequence(self, reader, name):
            self.i = 0
            return [dict(hdr)]

        def on_data(self, reader, ospans):
            if self.i >= len(gulps):
                return [0]
            g = gulps[self.i]
            self.i += 1
            ospans[0].data.as_numpy()[:, :g.shape[1]] = g
            return [g.shape[1]]

    collected = []
    with bf.Pipeline() as p:
        src = FreqSource(['x'], gulp_nframe=gulp)
        b = bf.blocks.copy(src, space='tpu')
        with bf.block_scope(mesh=mesh):
            blk = bf.blocks.fdmt(b, max_delay=md)
        b = bf.blocks.copy(blk, space='system')
        CallbackSinkBlock(b, data_callback=lambda a: collected.append(
            np.array(a, copy=True)))
        p.run()
    return np.concatenate(collected, axis=-1), blk


def test_fdmt_block_on_mesh_matches_single_device():
    """FdmtBlock under a time-axis mesh scope shards each gulp over the
    devices (max_delay halo via ppermute) and must equal the unsharded
    run; the mesh path must actually engage, not silently fall back."""
    rng = np.random.RandomState(30)
    nchan, T, gulp, md = 16, 120, 56, 8
    x = rng.rand(nchan, T).astype(np.float32)
    base, _ = _run_fdmt_block(None, x, gulp, md)
    meshed, blk = _run_fdmt_block(create_mesh({'sp': 8}), x, gulp, md)
    assert any(fn is not None for fn in blk._mesh_fns.values()), \
        blk._mesh_fns
    n = min(base.shape[-1], meshed.shape[-1])
    np.testing.assert_allclose(meshed[:, :n], base[:, :n],
                               rtol=1e-4, atol=1e-3)


def test_fdmt_block_mesh_indivisible_falls_back():
    """A gulp whose time extent does not divide the mesh (or is
    narrower than max_delay per shard) must fall back to the
    single-device core and still be correct."""
    rng = np.random.RandomState(31)
    nchan, T, gulp, md = 16, 60, 20, 9
    x = rng.rand(nchan, T).astype(np.float32)
    base, _ = _run_fdmt_block(None, x, gulp, md)
    meshed, blk = _run_fdmt_block(create_mesh({'sp': 8}), x, gulp, md)
    assert all(fn is None for fn in blk._mesh_fns.values())
    n = min(base.shape[-1], meshed.shape[-1])
    np.testing.assert_allclose(meshed[:, :n], base[:, :n],
                               rtol=1e-4, atol=1e-3)
