"""Mesh-in-the-pipeline tests: blocks consume ``BlockScope(mesh=...)``
and run their gulp functions sharded over the 8-device virtual CPU mesh,
with output identical to the single-device run (VERDICT r1 item 2;
the TPU generalization of the reference's per-block gpu=N placement,
reference: python/bifrost/pipeline.py:365-366)."""

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.parallel import create_mesh

from util import (NumpySourceBlock, GatherSink, CallbackSinkBlock,
                  simple_header)


def _spectro_inputs():
    rng = np.random.RandomState(42)
    gulps = [(rng.randn(16, 2, 32) + 1j * rng.randn(16, 2, 32))
             .astype(np.complex64) for _ in range(3)]
    hdr = simple_header([-1, 2, 32], 'cf32',
                        labels=['time', 'pol', 'fine_time'])
    return gulps, hdr


def _run_fused_chain(mesh):
    from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage
    gulps, hdr = _spectro_inputs()
    with bf.Pipeline() as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
        b = bf.blocks.copy(src, space='tpu')
        with bf.block_scope(mesh=mesh):
            b = bf.blocks.fused(b, [
                FftStage('fine_time', axis_labels='freq'),
                DetectStage('stokes', axis='pol'),
                ReduceStage('freq', factor=4)])
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    return sink.result()


def test_fused_chain_on_mesh_matches_single_device():
    """The fused FFT->detect->reduce chain through rings, sharded over
    the mesh (GSPMD over the frame axis), must be bit-compatible with
    the single-device run."""
    base = _run_fused_chain(None)
    meshed = _run_fused_chain(create_mesh({'sp': 8}))
    assert base is not None and meshed is not None
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-4)


def test_fused_chain_on_2d_mesh():
    meshed = _run_fused_chain(create_mesh({'sp': 2, 'tp': 4}))
    base = _run_fused_chain(None)
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-4)


def test_fused_chain_mesh_indivisible_falls_back():
    """gulp_nframe=12 does not divide 8 shards: the block must fall back
    to unsharded execution and still be correct."""
    from bifrost_tpu.stages import FftStage, DetectStage
    rng = np.random.RandomState(3)
    data = (rng.randn(12, 2, 16) + 1j * rng.randn(12, 2, 16)) \
        .astype(np.complex64)
    hdr = simple_header([-1, 2, 16], 'cf32',
                        labels=['time', 'pol', 'fine_time'])
    with bf.Pipeline() as p:
        src = NumpySourceBlock([data], hdr, gulp_nframe=12)
        b = bf.blocks.copy(src, space='tpu')
        with bf.block_scope(mesh=create_mesh({'sp': 8})):
            b = bf.blocks.fused(b, [FftStage('fine_time'),
                                    DetectStage('stokes', axis='pol')])
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    s = np.fft.fft(data, axis=-1)
    x, y = s[:, 0], s[:, 1]
    xy = x * np.conj(y)
    expect = np.stack([np.abs(x)**2 + np.abs(y)**2,
                       np.abs(x)**2 - np.abs(y)**2,
                       2 * xy.real, -2 * xy.imag], axis=1)
    np.testing.assert_allclose(sink.result(), expect, rtol=1e-4, atol=1e-3)


def _run_correlate(mesh, gulps, hdr, nint):
    with bf.Pipeline() as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=gulps[0].shape[0])
        b = bf.blocks.copy(src, space='tpu')
        with bf.block_scope(mesh=mesh):
            b = bf.blocks.correlate(b, nframe_per_integration=nint)
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    return sink.result()


def test_correlate_on_mesh_matches_single_device():
    """Time-parallel correlation: per-shard cross-multiply + psum over
    the mesh time axis (parallel.ops pattern), integrated across gulps."""
    rng = np.random.RandomState(7)
    gulps = [(rng.randn(8, 4, 3, 2) + 1j * rng.randn(8, 4, 3, 2))
             .astype(np.complex64) for _ in range(2)]
    hdr = simple_header([-1, 4, 3, 2], 'cf32',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=8)
    base = _run_correlate(None, gulps, hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 8}), gulps, hdr, 16)
    assert base is not None and meshed is not None
    np.testing.assert_allclose(meshed, base, rtol=1e-4, atol=1e-3)


def test_correlate_ci8_on_mesh():
    """int8 MXU 3-matmul path under shard_map: int32 partials psum."""
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    rng = np.random.RandomState(8)
    raw = np.zeros((16, 2, 3, 2), dtype=ci8_dtype)
    raw['re'] = rng.randint(-16, 16, size=raw.shape)
    raw['im'] = rng.randint(-16, 16, size=raw.shape)
    hdr = simple_header([-1, 2, 3, 2], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=16)
    base = _run_correlate(None, [raw], hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 8}), [raw], hdr, 16)
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-5)


def test_fir_on_mesh_matches_single_device():
    """Sequence-parallel FIR: inter-gulp state feeds shard 0, interior
    shard boundaries exchange halos via ppermute."""
    rng = np.random.RandomState(9)
    gulps = [rng.randn(16, 3).astype(np.float32) for _ in range(3)]
    coeffs = np.array([0.5, 0.3, 0.2], np.float32)
    hdr = simple_header([-1, 3], 'f32', gulp_nframe=16)

    def run(mesh):
        with bf.Pipeline() as p:
            src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
            b = bf.blocks.copy(src, space='tpu')
            with bf.block_scope(mesh=mesh):
                b = bf.blocks.fir(b, coeffs)
            b = bf.blocks.copy(b, space='system')
            sink = GatherSink(b)
            p.run()
        return sink.result()

    base = run(None)
    meshed = run(create_mesh({'sp': 8}))
    # oracle: causal FIR over the concatenated stream
    x = np.concatenate(gulps, axis=0)
    xp = np.concatenate([np.zeros((2, 3), np.float32), x])
    expect = sum(coeffs[t] * xp[2 - t:2 - t + 48] for t in range(3))
    np.testing.assert_allclose(base, expect, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(meshed, expect, rtol=1e-5, atol=1e-5)


def test_correlate_mesh_partial_gulp_fallback():
    """A partial gulp mid-integration routes to the single-device build
    while the carried accumulator lives on the mesh; the block must
    reconcile the device sets both directions (code-review regression)."""
    rng = np.random.RandomState(11)
    gulps = [(rng.randn(n, 2, 3, 2) + 1j * rng.randn(n, 2, 3, 2))
             .astype(np.complex64) for n in (8, 4, 4)]
    hdr = simple_header([-1, 2, 3, 2], 'cf32',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=8)
    base = _run_correlate(None, gulps, hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 8}), gulps, hdr, 16)
    assert base is not None and meshed is not None
    np.testing.assert_allclose(meshed, base, rtol=1e-4, atol=1e-3)


def test_fir_mesh_partial_gulp_fallback():
    """A partial final gulp after sharded gulps: the carried FIR state is
    mesh-committed but the tail build is single-device (code-review
    regression)."""
    rng = np.random.RandomState(12)
    gulps = [rng.randn(n, 3).astype(np.float32) for n in (16, 16, 4)]
    coeffs = np.array([0.5, 0.3, 0.2], np.float32)
    hdr = simple_header([-1, 3], 'f32', gulp_nframe=16)

    def run(mesh):
        with bf.Pipeline() as p:
            src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
            b = bf.blocks.copy(src, space='tpu')
            with bf.block_scope(mesh=mesh):
                b = bf.blocks.fir(b, coeffs)
            b = bf.blocks.copy(b, space='system')
            sink = GatherSink(b)
            p.run()
        return sink.result()

    base = run(None)
    meshed = run(create_mesh({'sp': 8}))
    assert base is not None and meshed is not None
    assert meshed.shape[0] == 36
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-5)


def test_fir_on_mesh_with_decimation():
    rng = np.random.RandomState(10)
    gulps = [rng.randn(16, 2).astype(np.float32) for _ in range(2)]
    coeffs = np.array([0.25, 0.5, 0.25], np.float32)
    hdr = simple_header([-1, 2], 'f32', gulp_nframe=16)

    def run(mesh):
        with bf.Pipeline() as p:
            src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
            b = bf.blocks.copy(src, space='tpu')
            with bf.block_scope(mesh=mesh):
                b = bf.blocks.fir(b, coeffs, decim=2)
            b = bf.blocks.copy(b, space='system')
            sink = GatherSink(b)
            p.run()
        return sink.result()

    base = run(None)
    meshed = run(create_mesh({'sp': 8}))
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-5)


def test_correlate_2d_mesh_station_sharding():
    """On a 2-D mesh the correlator also shards the station axis: each
    rank computes its antenna-row block against the all_gathered
    column axis (distributed visibility matrix)."""
    rng = np.random.RandomState(21)
    gulps = [(rng.randn(8, 3, 4, 2) + 1j * rng.randn(8, 3, 4, 2))
             .astype(np.complex64) for _ in range(2)]
    hdr = simple_header([-1, 3, 4, 2], 'cf32',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=8)
    base = _run_correlate(None, gulps, hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 4, 'tp': 2}),
                            gulps, hdr, 16)
    assert base is not None and meshed is not None
    np.testing.assert_allclose(meshed, base, rtol=1e-4, atol=1e-3)


def test_correlate_2d_mesh_ci8_station_sharding():
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    rng = np.random.RandomState(22)
    raw = np.zeros((16, 2, 4, 2), dtype=ci8_dtype)
    raw['re'] = rng.randint(-16, 16, size=raw.shape)
    raw['im'] = rng.randint(-16, 16, size=raw.shape)
    hdr = simple_header([-1, 2, 4, 2], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'],
                        gulp_nframe=16)
    base = _run_correlate(None, [raw], hdr, 16)
    meshed = _run_correlate(create_mesh({'sp': 4, 'tp': 2}),
                            [raw], hdr, 16)
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-5)


def _run_fdmt_block(mesh, x, gulp, md):
    """FDMT block pipeline over (freq, time) ringlet layout; returns
    (concatenated DM-time output, the block instance)."""
    nchan, T = x.shape
    hdr = {
        'name': 'fdmt-mesh', 'time_tag': 0,
        '_tensor': {
            'shape': [nchan, -1],
            'dtype': 'f32',
            'labels': ['freq', 'time'],
            'scales': [[100.0, 1.0], [0.0, 1e-3]],
            'units': ['MHz', 's'],
        },
    }
    gulps = [x[:, i:i + gulp].copy() for i in range(0, T, gulp)]

    class FreqSource(bf.SourceBlock):
        def create_reader(self, name):
            import contextlib
            return contextlib.nullcontext()

        def on_sequence(self, reader, name):
            self.i = 0
            return [dict(hdr)]

        def on_data(self, reader, ospans):
            if self.i >= len(gulps):
                return [0]
            g = gulps[self.i]
            self.i += 1
            ospans[0].data.as_numpy()[:, :g.shape[1]] = g
            return [g.shape[1]]

    collected = []
    with bf.Pipeline() as p:
        src = FreqSource(['x'], gulp_nframe=gulp)
        b = bf.blocks.copy(src, space='tpu')
        with bf.block_scope(mesh=mesh):
            blk = bf.blocks.fdmt(b, max_delay=md)
        b = bf.blocks.copy(blk, space='system')
        CallbackSinkBlock(b, data_callback=lambda a: collected.append(
            np.array(a, copy=True)))
        p.run()
    return np.concatenate(collected, axis=-1), blk


def test_fdmt_block_on_mesh_matches_single_device():
    """FdmtBlock under a time-axis mesh scope shards each gulp over the
    devices (max_delay halo via ppermute) and must equal the unsharded
    run; the mesh path must actually engage, not silently fall back."""
    rng = np.random.RandomState(30)
    nchan, T, gulp, md = 16, 120, 56, 8
    x = rng.rand(nchan, T).astype(np.float32)
    base, _ = _run_fdmt_block(None, x, gulp, md)
    meshed, blk = _run_fdmt_block(create_mesh({'sp': 8}), x, gulp, md)
    assert any(fn is not None for fn in blk._mesh_fns.values()), \
        blk._mesh_fns
    n = min(base.shape[-1], meshed.shape[-1])
    np.testing.assert_allclose(meshed[:, :n], base[:, :n],
                               rtol=1e-4, atol=1e-3)


def test_fdmt_block_mesh_indivisible_falls_back():
    """A gulp whose time extent does not divide the mesh (or is
    narrower than max_delay per shard) must fall back to the
    single-device core and still be correct."""
    rng = np.random.RandomState(31)
    nchan, T, gulp, md = 16, 60, 20, 9
    x = rng.rand(nchan, T).astype(np.float32)
    base, _ = _run_fdmt_block(None, x, gulp, md)
    meshed, blk = _run_fdmt_block(create_mesh({'sp': 8}), x, gulp, md)
    assert all(fn is None for fn in blk._mesh_fns.values())
    n = min(base.shape[-1], meshed.shape[-1])
    np.testing.assert_allclose(meshed[:, :n], base[:, :n],
                               rtol=1e-4, atol=1e-3)


# ---------------------------------------------------------------------------
# mesh-resident pipelines (PR 6): sharded rings, sharded H2D, zero-reshard
# plans, macro-gulp x mesh, donation under sharding
# ---------------------------------------------------------------------------

from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage
from bifrost_tpu.telemetry import counters


def _mesh_chain(mesh, k=1, donate=None, n=6, hlo_stats=False,
                monkeypatch=None):
    """config-8-style chain with the WHOLE device segment (H2D copy +
    fused chain) inside the mesh scope — the zero-reshard topology."""
    if hlo_stats and monkeypatch is not None:
        monkeypatch.setenv('BF_MESH_HLO_STATS', '1')
    counters.reset()
    rng = np.random.RandomState(42)
    gulps = [(rng.randn(16, 2, 32) + 1j * rng.randn(16, 2, 32))
             .astype(np.complex64) for _ in range(n)]
    hdr = simple_header([-1, 2, 32], 'cf32',
                        labels=['time', 'pol', 'fine_time'])
    with bf.Pipeline(gulp_batch=k, donate=donate) as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
        with bf.block_scope(mesh=mesh):
            b = bf.blocks.copy(src, space='tpu')
            fb = bf.blocks.fused(b, [
                FftStage('fine_time', axis_labels='freq'),
                DetectStage('stokes', axis='pol'),
                ReduceStage('freq', factor=4)], name='MeshFused')
        b = bf.blocks.copy(fb, space='system')
        sink = GatherSink(b)
        p.run()
    return sink.result(), counters.snapshot()


def test_sharded_ring_span_roundtrip():
    """A sharded jax Array committed into a 'tpu' ring span comes back
    with its NamedSharding intact (shard-local chunk storage), and the
    commit is counted on the sharded-gulp/per-shard-bytes telemetry."""
    import jax
    from bifrost_tpu.ring import Ring
    from bifrost_tpu.parallel.scope import time_sharding
    counters.reset()
    mesh = create_mesh({'sp': 8})
    sharding = time_sharding(mesh, 2, 0)
    ring = Ring(space='tpu', name='shard_rt')
    hdr = simple_header([-1, 4], 'f32', gulp_nframe=16)
    data = np.arange(64, dtype=np.float32).reshape(16, 4)
    arr = jax.device_put(data, sharding)
    with ring.begin_writing() as w:
        with w.begin_sequence(hdr, 16, 48) as seq:
            with ring.open_earliest_sequence(guarantee=True) as rseq:
                with seq.reserve(16) as ospan:
                    ospan.set(arr, owned=True)
                    ospan.commit(16)
                with rseq.acquire(0, 16) as ispan:
                    got = ispan.data
                    assert got.sharding == sharding
                    np.testing.assert_array_equal(np.asarray(got), data)
    snap = counters.snapshot()
    assert snap.get('ring.shard_rt.sharded_gulps') == 1
    assert snap.get('ring.shard_rt.shard_bytes') == data.nbytes // 8
    assert snap.get('mesh.sharded_commits') == 1


def test_sharded_h2d_placement():
    """xfer.to_device(sharding=...) stages per-shard aligned buffers and
    assembles with make_array_from_single_device_arrays — bytes land
    identical and mesh-resident, and per-shard telemetry is counted."""
    from bifrost_tpu import xfer
    from bifrost_tpu.parallel.scope import time_sharding
    counters.reset()
    mesh = create_mesh({'sp': 8})
    sharding = time_sharding(mesh, 3, 0)
    host = np.random.RandomState(0).randn(32, 3, 5).astype(np.float32)
    arr = xfer.to_device(host, sharding=sharding)
    assert arr.sharding == sharding
    np.testing.assert_array_equal(np.asarray(arr), host)
    snap = counters.snapshot()
    assert snap.get('xfer.h2d_sharded') == 1
    assert snap.get('xfer.h2d_shard_bytes') == host.nbytes // 8
    # complex rides as two sharded planes recombined on device
    chost = (host + 1j * host).astype(np.complex64)
    carr = xfer.to_device(chost, sharding=sharding)
    assert len(carr.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(carr), chost, rtol=1e-6)


def test_sharded_h2d_env_fallback(monkeypatch):
    """BF_MESH_H2D=0 still lands the gulp on the sharding (whole-array
    device_put fallback), counted separately."""
    from bifrost_tpu import xfer
    from bifrost_tpu.parallel.scope import time_sharding
    monkeypatch.setenv('BF_MESH_H2D', '0')
    counters.reset()
    mesh = create_mesh({'sp': 8})
    sharding = time_sharding(mesh, 2, 0)
    host = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    arr = xfer.to_device(host, sharding=sharding)
    assert arr.sharding == sharding
    np.testing.assert_array_equal(np.asarray(arr), host)
    assert counters.snapshot().get('xfer.h2d_sharded_fallback') == 1


def test_mesh_chain_zero_reshards(monkeypatch):
    """The mesh-resident chain: sharded H2D places gulps in exactly the
    fused plan's in_sharding, the plan carries out_shardings, and the
    compiled program contains NO collectives (frame-local shard_map) —
    the only reshard in the whole run is the prewarm's zeros gulp."""
    mesh = create_mesh({'sp': 8})
    meshed, snap = _mesh_chain(mesh, hlo_stats=True,
                               monkeypatch=monkeypatch)
    base, _ = _mesh_chain(None)
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-4)
    # compiled mesh plans are collective-free
    assert snap.get('mesh.plans_analyzed', 0) >= 1
    assert snap.get('mesh.plans_analyzed') == \
        snap.get('mesh.plans_collective_free')
    assert not any(k.startswith('mesh.collectives.') for k in snap)
    # steady-state gulps arrive pre-sharded: only the prewarm zeros
    # gulp needed a relayout, and the producer's advertised header
    # layout matched the consumer's expectation
    assert snap.get('mesh.reshards', 0) <= 1
    assert snap.get('mesh.layout_mismatch', 0) == 0
    # the H2D mover committed sharded spans (6 gulps x re+im planes)
    assert snap.get('xfer.h2d_sharded', 0) >= 6
    assert snap.get('mesh.sharded_commits', 0) >= 6


def test_mesh_fused_plan_hlo_direct():
    """Belt-and-braces zero-reshard assertion straight from compiled
    HLO text: the fused FFT->detect->reduce plan at the ring-resident
    sharding contains no all-gather / all-reduce / all-to-all /
    collective-permute instructions."""
    import jax
    from bifrost_tpu.parallel.scope import (time_sharding,
                                            frame_local_plan,
                                            collective_counts)
    from bifrost_tpu.stages import walk_headers, compose_stages
    mesh = create_mesh({'sp': 8})
    hdr = simple_header([-1, 2, 32], 'cf32',
                        labels=['time', 'pol', 'fine_time'])
    stages = [FftStage('fine_time', axis_labels='freq'),
              DetectStage('stokes', axis='pol'),
              ReduceStage('freq', factor=4)]
    headers = walk_headers(stages, hdr)
    shape = (16, 2, 32)

    def build_local(local_shape):
        fn, _info = compose_stages(stages, headers, local_shape,
                                   'complex64')
        return fn

    got = frame_local_plan(mesh, build_local, shape, 'complex64', 0, 0)
    assert got is not None
    plan, in_sh, out_sh = got
    arg = jax.ShapeDtypeStruct(shape, np.complex64, sharding=in_sh)
    txt = plan.lower(arg).compile().as_text()
    assert collective_counts(txt) == {}, collective_counts(txt)


def test_mesh_macro_gulp_k_gt_1():
    """macro-gulp x mesh: K>1 batched dispatch composes with sharded
    plans — no macro fallback for the mesh block, dispatches amortized,
    outputs equal the K=1 single-device stream."""
    mesh = create_mesh({'sp': 8})
    base, _ = _mesh_chain(None)
    meshed, snap = _mesh_chain(mesh, k=3, n=6)
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-4)
    # the fused mesh block took the macro path: 6 gulps in 2 dispatches
    disp = sum(v for k_, v in snap.items()
               if 'MeshFused' in k_ and k_.endswith('.dispatches'))
    gulps = sum(v for k_, v in snap.items()
                if 'MeshFused' in k_ and k_.endswith('.gulps'))
    assert (disp, gulps) == (2, 6)
    # no fallback reason fired for the mesh-eligible blocks (host
    # source/sink fallbacks are counted under 'block' and are expected)
    assert snap.get('macro.fallback.overlap', 0) == 0
    assert snap.get('macro.fallback.topology', 0) == 0
    assert snap.get('macro.fallback.multi_reader', 0) == 0


def test_mesh_donation_under_sharding():
    """BF_DONATE-style donation composes with sharded plans: the
    exclusively-owned sharded input chunk is donated into the mesh plan
    (per-device buffers alias shard by shard) and the output stream is
    unchanged."""
    mesh = create_mesh({'sp': 8})
    base, _ = _mesh_chain(None)
    meshed, snap = _mesh_chain(mesh, k=2, donate=True, n=6)
    np.testing.assert_allclose(meshed, base, rtol=1e-5, atol=1e-4)
    assert snap.get('donation.hits', 0) >= 3
    assert snap.get('donation.misses', 0) == 0


def test_mesh_stage_block_sharded_plan_parity():
    """An unfused _StageBlock chain under a mesh scope also runs
    sharded with ring-resident shardings (frame-local shard_map for
    batch_safe stages) and matches the single-device output."""
    rng = np.random.RandomState(5)
    gulps = [(rng.randn(16, 2, 32) + 1j * rng.randn(16, 2, 32))
             .astype(np.complex64) for _ in range(3)]
    hdr = simple_header([-1, 2, 32], 'cf32',
                        labels=['time', 'pol', 'fine_time'])

    def run(mesh):
        counters.reset()
        with bf.Pipeline() as p:
            src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
            with bf.block_scope(mesh=mesh):
                b = bf.blocks.copy(src, space='tpu')
                b = bf.blocks.fft(b, 'fine_time', axis_labels='freq')
                b = bf.blocks.detect(b, 'stokes', axis='pol')
            b = bf.blocks.copy(b, space='system')
            sink = GatherSink(b)
            p.run()
        return sink.result(), counters.snapshot()

    base, _ = run(None)
    meshed, snap = run(create_mesh({'sp': 8}))
    np.testing.assert_allclose(meshed, base, rtol=1e-4, atol=1e-3)
    # both stage blocks committed sharded output spans
    assert snap.get('mesh.sharded_commits', 0) >= 6


def test_mesh_macro_committed_single_device_input():
    """A producer OUTSIDE the mesh scope, pinned to device 0, commits
    COMMITTED single-device chunks; the mesh macro consumer must
    relayout them (counted on mesh.reshards) rather than crash — a jit
    with explicit in_shardings rejects committed mismatched inputs."""
    counters.reset()
    rng = np.random.RandomState(42)
    gulps = [(rng.randn(16, 2, 32) + 1j * rng.randn(16, 2, 32))
             .astype(np.complex64) for _ in range(6)]
    hdr = simple_header([-1, 2, 32], 'cf32',
                        labels=['time', 'pol', 'fine_time'])
    mesh = create_mesh({'sp': 8})
    with bf.Pipeline(gulp_batch=2) as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=16)
        b = bf.blocks.copy(src, space='tpu', device=0)   # committed
        with bf.block_scope(mesh=mesh):
            fb = bf.blocks.fused(b, [
                FftStage('fine_time', axis_labels='freq'),
                DetectStage('stokes', axis='pol'),
                ReduceStage('freq', factor=4)], name='MacroReshard')
        b = bf.blocks.copy(fb, space='system')
        sink = GatherSink(b)
        p.run()
    snap = counters.snapshot()
    base, _ = _mesh_chain(None)
    np.testing.assert_allclose(sink.result(), base, rtol=1e-5,
                               atol=1e-4)
    # the wrong-layout producer is visible: per-macro-span relayouts
    assert snap.get('mesh.reshards', 0) >= 3
