"""FIR tests (reference analogue: test/test_fir.py — scipy.lfilter
oracle + inter-gulp state)."""

import numpy as np

from bifrost_tpu.ops.fir import Fir


def _lfilter(coeffs, x):
    """Causal FIR oracle along axis 0 (zero initial state)."""
    ntap = len(coeffs)
    xp = np.concatenate([np.zeros((ntap - 1,) + x.shape[1:], x.dtype), x])
    out = np.zeros_like(x)
    for t in range(ntap):
        out = out + coeffs[t] * xp[ntap - 1 - t: xp.shape[0] - t]
    return out


def test_fir_matches_oracle():
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    coeffs = np.array([0.5, 0.3, 0.2], np.float32)
    fir = Fir().init(coeffs)
    out = np.asarray(fir.execute(x))
    np.testing.assert_allclose(out, _lfilter(coeffs, x), rtol=1e-5)


def test_fir_state_across_gulps():
    """Filtering two gulps must equal filtering the concatenation."""
    rng = np.random.RandomState(1)
    x = rng.randn(64, 3).astype(np.float32)
    coeffs = np.array([0.4, 0.3, 0.2, 0.1], np.float32)
    fir = Fir().init(coeffs)
    out1 = np.asarray(fir.execute(x[:32]))
    out2 = np.asarray(fir.execute(x[32:]))
    full = _lfilter(coeffs, x)
    np.testing.assert_allclose(np.concatenate([out1, out2]), full,
                               rtol=1e-5)
    fir.reset_state()
    out1b = np.asarray(fir.execute(x[:32]))
    np.testing.assert_allclose(out1b, full[:32], rtol=1e-5)


def test_fir_decimation():
    rng = np.random.RandomState(2)
    x = rng.randn(32, 2).astype(np.float32)
    coeffs = np.array([0.5, 0.5], np.float32)
    fir = Fir().init(coeffs, decim=4)
    out = np.asarray(fir.execute(x))
    np.testing.assert_allclose(out, _lfilter(coeffs, x)[::4], rtol=1e-5)


def test_fir_complex_per_channel_coeffs():
    rng = np.random.RandomState(3)
    x = (rng.randn(16, 2) + 1j * rng.randn(16, 2)).astype(np.complex64)
    coeffs = rng.randn(3, 2).astype(np.float32)   # per-channel taps
    fir = Fir().init(coeffs)
    out = np.asarray(fir.execute(x))
    expect = np.zeros_like(x)
    xp = np.concatenate([np.zeros((2, 2), x.dtype), x])
    for t in range(3):
        expect += coeffs[t] * xp[2 - t:2 - t + 16]
    np.testing.assert_allclose(out, expect, rtol=1e-5)
