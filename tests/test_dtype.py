"""DataType system tests (reference analogue: test/test_ndarray.py dtype
handling, python/bifrost/DataType.py semantics)."""

import numpy as np
import pytest

from bifrost_tpu.dtype import DataType, ci8, ci16, cf16


def test_parse_strings():
    assert DataType('f32').kind == 'f'
    assert DataType('f32').nbits == 32
    assert DataType('ci8').is_complex
    assert DataType('ci8').itemsize == 2
    assert DataType('cf32').as_numpy_dtype() == np.complex64
    assert DataType('i8').as_numpy_dtype() == np.int8
    assert str(DataType('u16')) == 'u16'


def test_from_numpy():
    assert DataType(np.float32) == DataType('f32')
    assert DataType(np.dtype(np.complex64)) == 'cf32'
    assert DataType(ci8) == 'ci8'
    assert DataType(ci16) == 'ci16'
    assert DataType(cf16) == 'cf16'
    assert DataType(np.int64) == 'i64'


def test_packed():
    ci4 = DataType('ci4')
    assert ci4.is_packed is False  # 4+4 = 8 bits = 1 byte
    assert ci4.itemsize == 1
    i4 = DataType('i4')
    assert i4.is_packed
    assert i4.itemsize_bits == 4
    with pytest.raises(ValueError):
        i4.itemsize
    assert DataType('i2').is_packed
    assert DataType('u1').itemsize_bits == 1


def test_conversions():
    assert DataType('ci8').as_floating_point() == 'cf32'
    assert DataType('i8').as_floating_point() == 'f32'
    assert DataType('f64').as_floating_point() == 'f64'
    assert DataType('cf32').as_real() == 'f32'
    assert DataType('f32').as_complex() == 'cf32'
    assert DataType('ci16').as_real() == 'i16'
    assert DataType('i32').as_nbit(8) == 'i8'


def test_vector():
    v = DataType('f32').as_vector(2)
    assert str(v) == 'f32_x2'
    assert v.itemsize == 8
    assert DataType('f32_x2') == v


def test_jax_dtypes():
    assert DataType('ci8').as_jax_dtype() == np.complex64
    assert DataType('f16').as_jax_dtype() == np.float16
    assert DataType('u8').as_jax_dtype() == np.uint8
