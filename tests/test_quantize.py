"""Quantize/unpack oracle tests (reference analogues: test_quantize.py,
test_guantize.py, test_unpack.py, test_gunpack.py)."""

import numpy as np

import bifrost_tpu as bf
from bifrost_tpu import ops


def test_quantize_f32_to_i8_scale_clip():
    x = bf.asarray(np.array([0.2, 1.0, -1.0, 300.0, -300.0], np.float32))
    dst = bf.empty((5,), 'i8', 'system')
    ops.quantize(x, dst, scale=100.)
    np.testing.assert_array_equal(dst.as_numpy(),
                                  [20, 100, -100, 127, -128])


def test_quantize_cf32_to_ci8():
    x = bf.asarray((np.array([1+2j, -3-4j, 200+0.4j])
                    ).astype(np.complex64))
    dst = bf.empty((3,), 'ci8', 'system')
    ops.quantize(x, dst, scale=10.)
    buf = dst.as_numpy()
    np.testing.assert_array_equal(buf['re'], [10, -30, 127])
    np.testing.assert_array_equal(buf['im'], [20, -40, 4])


def test_quantize_packed_i4():
    x = bf.asarray(np.array([1., -2., 3., -4., 5., -6., 7., -8.],
                            np.float32))
    dst = bf.empty((8,), 'i4', 'system')
    ops.quantize(x, dst, scale=1.)
    back = bf.empty((8,), 'i8', 'system')
    ops.unpack(dst, back)
    np.testing.assert_array_equal(back.as_numpy(),
                                  [1, -2, 3, -4, 5, -6, 7, -8])


def test_unpack_ci4_roundtrip():
    vals = (np.array([1+2j, -3-4j, 7-8j, -8+7j]).astype(np.complex64))
    dst4 = bf.empty((4,), 'ci4', 'system')
    ops.quantize(bf.asarray(vals), dst4, scale=1.)
    back = bf.empty((4,), 'cf32', 'system')
    ops.unpack(dst4, back)
    np.testing.assert_array_equal(back.as_numpy(), vals)


def test_unpack_u2():
    packed = bf.empty((8,), 'u2', 'system')
    # 8 2-bit values -> 2 bytes; values 0..3
    vals = np.array([0, 1, 2, 3, 3, 2, 1, 0])
    from bifrost_tpu.ops.quantize import _pack_into
    from bifrost_tpu.dtype import DataType
    _pack_into(vals, DataType('u2'), packed.as_numpy())
    out = bf.empty((8,), 'u8', 'system')
    ops.unpack(packed, out)
    np.testing.assert_array_equal(out.as_numpy(), vals)


def test_quantize_device_path():
    x = bf.asarray(np.linspace(-2, 2, 16).astype(np.float32),
                   space='tpu')
    dst = bf.empty((16,), 'i8', 'tpu')
    ops.quantize(x, dst, scale=50.)
    expect = np.clip(np.round(np.linspace(-2, 2, 16) * 50), -128, 127)
    np.testing.assert_array_equal(np.asarray(dst.data), expect)
