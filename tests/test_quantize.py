"""Quantize/unpack oracle tests (reference analogues: test_quantize.py,
test_guantize.py, test_unpack.py, test_gunpack.py)."""

import numpy as np

import bifrost_tpu as bf
from bifrost_tpu import ops


def test_quantize_f32_to_i8_scale_clip():
    x = bf.asarray(np.array([0.2, 1.0, -1.0, 300.0, -300.0], np.float32))
    dst = bf.empty((5,), 'i8', 'system')
    ops.quantize(x, dst, scale=100.)
    np.testing.assert_array_equal(dst.as_numpy(),
                                  [20, 100, -100, 127, -128])


def test_quantize_cf32_to_ci8():
    x = bf.asarray((np.array([1+2j, -3-4j, 200+0.4j])
                    ).astype(np.complex64))
    dst = bf.empty((3,), 'ci8', 'system')
    ops.quantize(x, dst, scale=10.)
    buf = dst.as_numpy()
    np.testing.assert_array_equal(buf['re'], [10, -30, 127])
    np.testing.assert_array_equal(buf['im'], [20, -40, 4])


def test_quantize_packed_i4():
    x = bf.asarray(np.array([1., -2., 3., -4., 5., -6., 7., -8.],
                            np.float32))
    dst = bf.empty((8,), 'i4', 'system')
    ops.quantize(x, dst, scale=1.)
    back = bf.empty((8,), 'i8', 'system')
    ops.unpack(dst, back)
    np.testing.assert_array_equal(back.as_numpy(),
                                  [1, -2, 3, -4, 5, -6, 7, -8])


def test_unpack_ci4_roundtrip():
    vals = (np.array([1+2j, -3-4j, 7-8j, -8+7j]).astype(np.complex64))
    dst4 = bf.empty((4,), 'ci4', 'system')
    ops.quantize(bf.asarray(vals), dst4, scale=1.)
    back = bf.empty((4,), 'cf32', 'system')
    ops.unpack(dst4, back)
    np.testing.assert_array_equal(back.as_numpy(), vals)


def test_unpack_u2():
    packed = bf.empty((8,), 'u2', 'system')
    # 8 2-bit values -> 2 bytes; values 0..3
    vals = np.array([0, 1, 2, 3, 3, 2, 1, 0])
    from bifrost_tpu.ops.quantize import _pack_into
    from bifrost_tpu.dtype import DataType
    _pack_into(vals, DataType('u2'), packed.as_numpy())
    out = bf.empty((8,), 'u8', 'system')
    ops.unpack(packed, out)
    np.testing.assert_array_equal(out.as_numpy(), vals)


def test_quantize_device_path():
    x = bf.asarray(np.linspace(-2, 2, 16).astype(np.float32),
                   space='tpu')
    dst = bf.empty((16,), 'i8', 'tpu')
    ops.quantize(x, dst, scale=50.)
    expect = np.clip(np.round(np.linspace(-2, 2, 16) * 50), -128, 127)
    np.testing.assert_array_equal(np.asarray(dst.data), expect)


def test_subbyte_bit_order_is_lsb_first():
    """Sample k lives in bits [k*nbits, (k+1)*nbits) of each byte — the
    reference convention (python/bifrost/sigproc.py:281 'assumes
    LSB-first ordering', bfUnpack).  Fixture bytes are hand-derived, so
    an MSB-first regression cannot cancel out in a round trip."""
    import numpy as np
    from bifrost_tpu.ops.map import _to_logical
    from bifrost_tpu.ops.quantize import _pack_into
    from bifrost_tpu.dtype import DataType

    # u2: byte 0xE4 = 0b11100100 -> samples [0, 1, 2, 3]
    vals = _to_logical(np.array([0xE4], np.uint8), DataType('u2'))
    np.testing.assert_array_equal(vals, [0, 1, 2, 3])
    out = np.zeros(1, np.uint8)
    _pack_into(np.array([0, 1, 2, 3], np.uint8), DataType('u2'), out)
    assert out[0] == 0xE4

    # u4: byte 0xBA -> samples [0xA, 0xB]
    vals = _to_logical(np.array([0xBA], np.uint8), DataType('u4'))
    np.testing.assert_array_equal(vals, [0xA, 0xB])

    # i4: byte 0xF7 -> low nibble 7, high nibble 0xF = -1
    vals = _to_logical(np.array([0xF7], np.uint8), DataType('i4'))
    np.testing.assert_array_equal(vals, [7, -1])

    # u1: byte 0b00000101 -> first three samples 1, 0, 1
    vals = _to_logical(np.array([0b00000101], np.uint8), DataType('u1'))
    np.testing.assert_array_equal(vals[:3], [1, 0, 1])


def test_sigproc_subbyte_read_lsb_first(tmp_path):
    """2-bit SIGPROC file packed LSB-first reads back in order."""
    import numpy as np
    from bifrost_tpu.io.sigproc import SigprocFile
    hdr = {'nbits': 2, 'nifs': 1, 'nchans': 4, 'data_type': 1,
           'tsamp': 1e-3, 'fch1': 100.0, 'foff': -1.0, 'tstart': 50000.0}
    from bifrost_tpu.io.sigproc import pack_header
    path = str(tmp_path / 'lsb.fil')
    with open(path, 'wb') as f:
        f.write(pack_header(hdr))
        # one frame of 4 chans [0,1,2,3] -> LSB-first byte 0xE4
        f.write(bytes([0xE4]))
    with SigprocFile(path) as r:
        data = r.read(1)
    np.testing.assert_array_equal(data.reshape(-1), [0, 1, 2, 3])


def test_packed_roundtrip_bit_exact_all_kinds():
    """quantize -> unpack is bit-exact for every packed 1/2/4-bit kind
    (i/u/ci) over the full representable range.  The packed-ci layout
    (ci1/ci2) interleaves re/im as 2*nbits fields, re in the HIGH
    nbits (the ci4 re<<4|im convention), fields LSB-first — this test
    surfaced (and now pins) the generic packed path silently dropping
    the imaginary part."""
    from bifrost_tpu.dtype import DataType
    from bifrost_tpu.ops.quantize import _clip_limits

    rng = np.random.RandomState(42)
    n = 64
    for s in ('i1', 'i2', 'i4', 'u1', 'u2', 'u4', 'ci1', 'ci2',
              'ci4'):
        dt = DataType(s)
        lo, hi = _clip_limits(dt)
        if dt.kind == 'ci':
            vals = (rng.randint(lo, hi + 1, n) +
                    1j * rng.randint(lo, hi + 1, n)
                    ).astype(np.complex64)
            back = bf.empty((n,), 'cf32', 'system')
        else:
            vals = rng.randint(lo, hi + 1, n).astype(np.float32)
            back = bf.empty((n,), 'i8' if dt.kind == 'i' else 'u8',
                            'system')
        dst = bf.empty((n,), s, 'system')
        ops.quantize(bf.asarray(vals), dst, scale=1.)
        ops.unpack(dst, back)
        np.testing.assert_array_equal(
            back.as_numpy().astype(vals.dtype), vals,
            err_msg='round trip not bit-exact for %s' % s)


def test_packed_roundtrip_range_extremes():
    """The clip limits themselves survive the round trip — lo would be
    the first casualty of a sign-extension or clip asymmetry (i4's -8
    packs to 0x8 and must come back as -8, not +8)."""
    from bifrost_tpu.dtype import DataType
    from bifrost_tpu.ops.quantize import _clip_limits

    for s in ('i1', 'i2', 'i4', 'u1', 'u2', 'u4'):
        dt = DataType(s)
        lo, hi = _clip_limits(dt)
        per = 8 // dt.nbits
        vals = np.resize([lo, hi], per).astype(np.float32)
        dst = bf.empty((per,), s, 'system')
        back = bf.empty((per,), 'i8' if dt.kind == 'i' else 'u8',
                        'system')
        ops.quantize(bf.asarray(vals), dst, scale=1.)
        ops.unpack(dst, back)
        np.testing.assert_array_equal(
            back.as_numpy().astype(np.float32), vals, err_msg=s)
    for s in ('ci1', 'ci2', 'ci4'):
        dt = DataType(s)
        lo, hi = _clip_limits(dt)
        per = max(8 // (2 * dt.nbits), 1)
        vals = np.resize([lo + 1j * hi, hi + 1j * lo],
                         per).astype(np.complex64)
        dst = bf.empty((per,), s, 'system')
        back = bf.empty((per,), 'cf32', 'system')
        ops.quantize(bf.asarray(vals), dst, scale=1.)
        ops.unpack(dst, back)
        np.testing.assert_array_equal(back.as_numpy(), vals,
                                      err_msg=s)


def test_packed_ci_field_layout():
    """Hand-derived packed-ci bytes: ci2 sample (re=1, im=-2) is the
    field 0b0110 (re high); two fields per byte, LSB-first."""
    from bifrost_tpu.ops.map import _to_logical, _from_logical
    from bifrost_tpu.dtype import DataType

    # ci2: fields s0=(1, -2) -> 0b0110 = 6, s1=(-1, 1) -> 0b1101 = 13
    # byte = s1 << 4 | s0 = 0xD6
    vals = _to_logical(np.array([0xD6], np.uint8), DataType('ci2'))
    np.testing.assert_array_equal(vals, [1 - 2j, -1 + 1j])
    packed = _from_logical(np.array([1 - 2j, -1 + 1j], np.complex64),
                           DataType('ci2'))
    np.testing.assert_array_equal(packed, [0xD6])

    # ci1: four (re, im) fields per byte, re the high bit of each pair
    # s0=(-1, 0) -> 0b10, s1=(0, -1) -> 0b01 -> byte 0b0110 = 0x06
    vals = _to_logical(np.array([0x06], np.uint8), DataType('ci1'))
    np.testing.assert_array_equal(vals[:2], [-1 + 0j, 0 - 1j])
    packed = _from_logical(np.asarray(vals), DataType('ci1'))
    np.testing.assert_array_equal(packed, [0x06])
