"""Quantize/unpack oracle tests (reference analogues: test_quantize.py,
test_guantize.py, test_unpack.py, test_gunpack.py)."""

import numpy as np

import bifrost_tpu as bf
from bifrost_tpu import ops


def test_quantize_f32_to_i8_scale_clip():
    x = bf.asarray(np.array([0.2, 1.0, -1.0, 300.0, -300.0], np.float32))
    dst = bf.empty((5,), 'i8', 'system')
    ops.quantize(x, dst, scale=100.)
    np.testing.assert_array_equal(dst.as_numpy(),
                                  [20, 100, -100, 127, -128])


def test_quantize_cf32_to_ci8():
    x = bf.asarray((np.array([1+2j, -3-4j, 200+0.4j])
                    ).astype(np.complex64))
    dst = bf.empty((3,), 'ci8', 'system')
    ops.quantize(x, dst, scale=10.)
    buf = dst.as_numpy()
    np.testing.assert_array_equal(buf['re'], [10, -30, 127])
    np.testing.assert_array_equal(buf['im'], [20, -40, 4])


def test_quantize_packed_i4():
    x = bf.asarray(np.array([1., -2., 3., -4., 5., -6., 7., -8.],
                            np.float32))
    dst = bf.empty((8,), 'i4', 'system')
    ops.quantize(x, dst, scale=1.)
    back = bf.empty((8,), 'i8', 'system')
    ops.unpack(dst, back)
    np.testing.assert_array_equal(back.as_numpy(),
                                  [1, -2, 3, -4, 5, -6, 7, -8])


def test_unpack_ci4_roundtrip():
    vals = (np.array([1+2j, -3-4j, 7-8j, -8+7j]).astype(np.complex64))
    dst4 = bf.empty((4,), 'ci4', 'system')
    ops.quantize(bf.asarray(vals), dst4, scale=1.)
    back = bf.empty((4,), 'cf32', 'system')
    ops.unpack(dst4, back)
    np.testing.assert_array_equal(back.as_numpy(), vals)


def test_unpack_u2():
    packed = bf.empty((8,), 'u2', 'system')
    # 8 2-bit values -> 2 bytes; values 0..3
    vals = np.array([0, 1, 2, 3, 3, 2, 1, 0])
    from bifrost_tpu.ops.quantize import _pack_into
    from bifrost_tpu.dtype import DataType
    _pack_into(vals, DataType('u2'), packed.as_numpy())
    out = bf.empty((8,), 'u8', 'system')
    ops.unpack(packed, out)
    np.testing.assert_array_equal(out.as_numpy(), vals)


def test_quantize_device_path():
    x = bf.asarray(np.linspace(-2, 2, 16).astype(np.float32),
                   space='tpu')
    dst = bf.empty((16,), 'i8', 'tpu')
    ops.quantize(x, dst, scale=50.)
    expect = np.clip(np.round(np.linspace(-2, 2, 16) * 50), -128, 127)
    np.testing.assert_array_equal(np.asarray(dst.data), expect)


def test_subbyte_bit_order_is_lsb_first():
    """Sample k lives in bits [k*nbits, (k+1)*nbits) of each byte — the
    reference convention (python/bifrost/sigproc.py:281 'assumes
    LSB-first ordering', bfUnpack).  Fixture bytes are hand-derived, so
    an MSB-first regression cannot cancel out in a round trip."""
    import numpy as np
    from bifrost_tpu.ops.map import _to_logical
    from bifrost_tpu.ops.quantize import _pack_into
    from bifrost_tpu.dtype import DataType

    # u2: byte 0xE4 = 0b11100100 -> samples [0, 1, 2, 3]
    vals = _to_logical(np.array([0xE4], np.uint8), DataType('u2'))
    np.testing.assert_array_equal(vals, [0, 1, 2, 3])
    out = np.zeros(1, np.uint8)
    _pack_into(np.array([0, 1, 2, 3], np.uint8), DataType('u2'), out)
    assert out[0] == 0xE4

    # u4: byte 0xBA -> samples [0xA, 0xB]
    vals = _to_logical(np.array([0xBA], np.uint8), DataType('u4'))
    np.testing.assert_array_equal(vals, [0xA, 0xB])

    # i4: byte 0xF7 -> low nibble 7, high nibble 0xF = -1
    vals = _to_logical(np.array([0xF7], np.uint8), DataType('i4'))
    np.testing.assert_array_equal(vals, [7, -1])

    # u1: byte 0b00000101 -> first three samples 1, 0, 1
    vals = _to_logical(np.array([0b00000101], np.uint8), DataType('u1'))
    np.testing.assert_array_equal(vals[:3], [1, 0, 1])


def test_sigproc_subbyte_read_lsb_first(tmp_path):
    """2-bit SIGPROC file packed LSB-first reads back in order."""
    import numpy as np
    from bifrost_tpu.io.sigproc import SigprocFile
    hdr = {'nbits': 2, 'nifs': 1, 'nchans': 4, 'data_type': 1,
           'tsamp': 1e-3, 'fch1': 100.0, 'foff': -1.0, 'tstart': 50000.0}
    from bifrost_tpu.io.sigproc import pack_header
    path = str(tmp_path / 'lsb.fil')
    with open(path, 'wb') as f:
        f.write(pack_header(hdr))
        # one frame of 4 chans [0,1,2,3] -> LSB-first byte 0xE4
        f.write(bytes([0xE4]))
    with SigprocFile(path) as r:
        data = r.read(1)
    np.testing.assert_array_equal(data.reshape(-1), [0, 1, 2, 3])
