"""Multi-tenant streaming service tier (bifrost_tpu.service —
docs/service.md): spec validation, admission control, core
partitioning, quota enforcement, blast-radius isolation, warm starts,
looped replay, and the per-tenant telemetry surfaces."""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu import affinity, service
from bifrost_tpu.analysis import verify
from bifrost_tpu.blocks.serialize import DeserializeBlock
from bifrost_tpu.telemetry import counters, exporter
from bifrost_tpu.testing import faults

from util import GatherSink, NumpySourceBlock, simple_header

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_service_state():
    counters.reset()
    service.reset_registry()
    service.reset_warm_registry()
    faults.clear()
    yield
    faults.clear()
    service.reset_registry()
    service.reset_warm_registry()
    counters.reset()


def synth_spec(tid, nframe=128, gulp=16, nchan=8, seed=3, tick=0.0,
               **kw):
    return service.TenantSpec(tid, source={
        'kind': 'synthetic', 'nframe_total': nframe,
        'gulp_nframe': gulp, 'nchan': nchan, 'seed': seed,
        'tick_s': tick}, **kw)


def gather_build(store, tid):
    def build(gate):
        store[tid] = GatherSink(gate)
    return build


# ---------------------------------------------------------------------------
# spec & static validation
# ---------------------------------------------------------------------------

def test_spec_from_dict_roundtrip():
    spec = service.TenantSpec.coerce({
        'id': 'a-1', 'source': {'kind': 'synthetic'}, 'priority': 3,
        'ncores': 2, 'quota_bytes_per_s': 1e6,
        'quota_policy': 'pace', 'slo_ms': 250, 'gulp_nframe': 64})
    d = spec.as_dict()
    spec2 = service.TenantSpec.coerce(d)
    assert spec2.id == 'a-1' and spec2.priority == 3
    assert spec2.quota_bytes_per_s == 1e6
    assert spec2.quota_policy == 'pace'
    assert spec2.slo_ms == 250
    # bad ids / kinds / policies fail at construction, not at run
    with pytest.raises(ValueError):
        service.TenantSpec('bad id!')
    with pytest.raises(ValueError):
        service.TenantSpec('x', source={'kind': 'nope'})
    with pytest.raises(ValueError):
        service.TenantSpec('x', quota_policy='drop')
    with pytest.raises(ValueError):
        service.TenantSpec.coerce({'id': 'x', 'bogus_field': 1})


def test_verify_service_duplicate_id():
    diags = verify.verify_service([{'id': 'a'}, {'id': 'a'}],
                                  ncores=64)
    assert [d.code for d in diags] == ['BF-E210']
    assert diags[0].is_error and diags[0].block == 'tenant:a'


def test_verify_service_quota_below_gulp():
    diags = verify.verify_service(
        [{'id': 'a', 'quota_bytes_per_s': 100, 'gulp_nbyte': 4096}],
        ncores=64)
    assert [d.code for d in diags] == ['BF-E211']


def test_verify_service_pace_quota_exempt():
    diags = verify.verify_service(
        [{'id': 'a', 'quota_bytes_per_s': 100, 'gulp_nbyte': 4096,
          'quota_policy': 'pace'}], ncores=64)
    assert diags == []


def test_verify_service_core_oversubscription():
    diags = verify.verify_service(
        [{'id': 'a', 'ncores': 3}, {'id': 'b', 'ncores': 2}],
        ncores=4)
    assert [d.code for d in diags] == ['BF-W212']
    assert not diags[0].is_error


def test_verify_service_codes_catalogued():
    for code in ('BF-E210', 'BF-E211', 'BF-W212'):
        assert code in verify.CODES
        with open(os.path.join(ROOT, 'docs', 'analysis.md')) as f:
            assert code in f.read()


# ---------------------------------------------------------------------------
# affinity partitioning
# ---------------------------------------------------------------------------

def test_partition_cores_priority_weighted():
    shares = affinity.partition_cores({'a': 3, 'b': 1},
                                      cores=list(range(8)))
    assert sorted(shares['a'] + shares['b']) == list(range(8))
    assert len(shares['a']) == 6 and len(shares['b']) == 2


def test_partition_cores_floor_and_equal_split():
    shares = affinity.partition_cores({'a': 100, 'b': 1},
                                      cores=[0, 1])
    # the 1-core floor holds even under extreme weights
    assert len(shares['a']) == 1 and len(shares['b']) == 1
    eq = affinity.partition_cores({'a': 1, 'b': 1, 'c': 1},
                                  cores=list(range(6)))
    assert all(len(v) == 2 for v in eq.values())


def test_partition_cores_oversubscription():
    # more tenants than cores: round-robin sharing, >= 1 core each
    shares = affinity.partition_cores(
        {'a': 1, 'b': 1, 'c': 1}, cores=[4, 5])
    assert [shares[t] for t in 'abc'] == [[4], [5], [4]]
    assert affinity.partition_cores({}, cores=[0]) == {}
    assert affinity.partition_cores({'a': 1}, cores=[]) == {'a': []}


def test_manager_counts_affinity_applied():
    before = counters.get('service.affinity.applied')
    mgr = service.JobManager(max_tenants=4, cores=[0], warm=False)
    store = {}
    mgr.submit(synth_spec('aff0', nframe=16), gather_build(store,
                                                           'aff0'))
    applied = counters.get('service.affinity.applied') - before
    job = mgr.job('aff0')
    assert applied == len(job.pipeline.blocks)
    assert all(b.core == 0 for b in job.pipeline.blocks)


# ---------------------------------------------------------------------------
# looped replay (blocks/serialize.py hardening)
# ---------------------------------------------------------------------------

def _record_stream(tmpdir, nframe=64, nchan=8, gulp=16):
    rng = np.random.RandomState(11)
    data = rng.randn(nframe, nchan).astype(np.float32)
    hdr = simple_header([-1, nchan], 'f32', name='rec',
                        gulp_nframe=gulp)
    with bf.Pipeline() as p:
        src = NumpySourceBlock(
            [data[i:i + gulp] for i in range(0, nframe, gulp)], hdr,
            gulp_nframe=gulp)
        bf.blocks.serialize(src, path=tmpdir)
    p.run()
    return os.path.join(tmpdir, 'rec'), data


def test_deserialize_loop_roundtrip(tmp_path):
    base, data = _record_stream(str(tmp_path))
    with bf.Pipeline() as p:
        b = DeserializeBlock([base], 16, loop=3, restamp=True)
        sink = GatherSink(b)
    p.run()
    assert np.array_equal(sink.result(), np.tile(data, (3, 1)))
    assert len(sink.headers) == 3


def test_deserialize_loop_renumber_and_restamp(tmp_path):
    base, _data = _record_stream(str(tmp_path))
    with bf.Pipeline() as p:
        b = DeserializeBlock([base], 16, loop=3, restamp=True)
        sink = GatherSink(b)
    p.run()
    names = [h.get('name') for h in sink.headers]
    tags = [h.get('time_tag') for h in sink.headers]
    traces = [h.get('_trace', {}).get('id') for h in sink.headers]
    assert names == ['rec', 'rec.loop1', 'rec.loop2']
    # renumbered on EVERY pass: unique, strictly increasing,
    # independent of whatever tag the recording carried
    assert tags == [0, 1, 2], tags
    assert all(traces) and len(set(traces)) == 3, traces


def test_deserialize_default_keeps_recorded_identity(tmp_path):
    # loop=1 / restamp=False: checkpoint/resume fidelity is unchanged
    base, data = _record_stream(str(tmp_path))
    with bf.Pipeline() as p:
        b = DeserializeBlock([base], 16)
        sink = GatherSink(b)
    p.run()
    assert np.array_equal(sink.result(), data)
    assert sink.headers[0]['name'] == 'rec'


# ---------------------------------------------------------------------------
# quotas
# ---------------------------------------------------------------------------

def test_quota_gate_sheds_counted():
    store = {}
    mgr = service.JobManager(max_tenants=2, warm=False)
    # 8 gulps of 16x8 f32 = 512 B each arrive un-paced; the bucket's
    # burst (quota x 0.1s = 600 B) covers the first gulp, the refill
    # cannot keep up with the burst — most gulps must shed, counted
    spec = synth_spec('shedq', nframe=128, gulp=16, nchan=8,
                      quota_bytes_per_s=6000, quota_policy='shed')
    mgr.submit(spec, gather_build(store, 'shedq'))
    mgr.start()
    states = mgr.wait(30)
    assert states['shedq'] == 'DONE'
    admitted = counters.get('service.shedq.admitted_gulps')
    shed = counters.get('service.shedq.quota_shed_gulps')
    assert admitted + shed == 8
    assert admitted >= 1 and shed >= 4
    assert counters.get('service.shedq.quota_shed_bytes') == shed * 512
    # delivered output is exactly the admitted gulps, nothing silent
    assert store['shedq'].result().shape[0] == admitted * 16


def test_quota_burst_floored_at_one_gulp():
    # a gulp larger than the burst window (quota x 0.1s = 100 B vs
    # 512 B gulps) but smaller than one second of quota: the bucket's
    # one-gulp capacity floor must still admit a trickle instead of
    # shedding 100% of a lint-clean (no BF-E211) spec
    assert verify.verify_service(
        [{'id': 'floorq', 'quota_bytes_per_s': 1000,
          'gulp_nbyte': 512}], ncores=64) == []
    store = {}
    mgr = service.JobManager(max_tenants=2, warm=False)
    spec = synth_spec('floorq', nframe=128, gulp=16, nchan=8,
                      quota_bytes_per_s=1000, quota_policy='shed')
    mgr.submit(spec, gather_build(store, 'floorq'))
    mgr.start()
    assert mgr.wait(30)['floorq'] == 'DONE'
    admitted = counters.get('service.floorq.admitted_gulps')
    shed = counters.get('service.floorq.quota_shed_gulps')
    assert admitted >= 1 and admitted + shed == 8


def test_quota_gate_paces_rate():
    store = {}
    mgr = service.JobManager(max_tenants=2, warm=False)
    # 16 KiB at 16 KiB/s -> ~1 s paced; nothing may be lost
    spec = synth_spec('paceq', nframe=512, gulp=32, nchan=8,
                      quota_bytes_per_s=16384, quota_policy='pace')
    job = mgr.submit(spec, gather_build(store, 'paceq'))
    mgr.start()
    assert mgr.wait(30)['paceq'] == 'DONE'
    assert counters.get('service.paceq.quota_shed_gulps') == 0
    assert store['paceq'].result().shape[0] == 512
    elapsed = job.finished_at - job.first_data_at
    achieved = 512 * 32 / elapsed          # bytes/s (32 B per frame)
    # generous tier-1 bounds; the bench gate holds the 10% bar
    assert achieved <= 16384 * 1.5, achieved
    assert elapsed >= 0.5, elapsed


# ---------------------------------------------------------------------------
# admission + lifecycle
# ---------------------------------------------------------------------------

def test_submit_duplicate_rejected():
    mgr = service.JobManager(max_tenants=4, warm=False)
    store = {}
    mgr.submit(synth_spec('dup', nframe=4096, gulp=16, tick=0.05),
               gather_build(store, 'dup'))
    before = counters.get('service.admission.rejected')
    with pytest.raises(service.ServiceAdmissionError):
        mgr.submit(synth_spec('dup'), gather_build(store, 'dup2'))
    assert counters.get('service.admission.rejected') == before + 1
    mgr.shutdown()


def test_capacity_admission():
    mgr = service.JobManager(max_tenants=1, warm=False)
    store = {}
    mgr.submit(synth_spec('cap1', nframe=4096, gulp=16, tick=0.05),
               gather_build(store, 'cap1'))
    with pytest.raises(service.ServiceAdmissionError):
        mgr.submit(synth_spec('cap2'), gather_build(store, 'cap2'))
    mgr.shutdown()


def test_submit_strict_rejects_spec_errors():
    mgr = service.JobManager(max_tenants=4, warm=False)
    bad = service.TenantSpec('badq', source={'kind': 'synthetic'},
                             quota_bytes_per_s=10, gulp_nbyte=4096)
    with pytest.raises(service.ServiceSpecError) as ei:
        mgr.submit(bad)
    assert any(d.code == 'BF-E211' for d in ei.value.diagnostics)


def test_two_tenants_concurrent_byte_correct():
    store = {}
    mgr = service.JobManager(max_tenants=4, warm=False)
    for tid in ('alpha', 'beta'):
        mgr.submit(synth_spec(tid, nframe=192, gulp=16, seed=5,
                              tick=0.01), gather_build(store, tid))
    mgr.start()
    states = mgr.wait(60)
    assert states == {'alpha': 'DONE', 'beta': 'DONE'}
    exp = service.SyntheticSource.payload(192, 8, 5)
    for tid in ('alpha', 'beta'):
        assert np.array_equal(store[tid].result(), exp), tid
    a, b = mgr.job('alpha'), mgr.job('beta')
    overlap = (min(a.finished_at, b.finished_at) -
               max(a.run_started_at, b.run_started_at))
    assert overlap > 0, 'tenants did not run concurrently'


def test_fault_isolation_blast_radius():
    store = {}
    mgr = service.JobManager(max_tenants=4, warm=False)
    mgr.submit(synth_spec('victim', nframe=640, gulp=16, tick=0.01),
               gather_build(store, 'victim'))
    mgr.submit(synth_spec('bystander', nframe=640, gulp=16,
                          tick=0.01), gather_build(store,
                                                   'bystander'))
    faults.inject('block.on_data', match='tenant.victim', count=1,
                  after=20)
    mgr.start()
    states = mgr.wait(60)
    assert states['victim'] == 'FAILED'
    assert states['bystander'] == 'DONE'
    victim, bystander = mgr.job('victim'), mgr.job('bystander')
    assert isinstance(victim.error, bf.PipelineRuntimeError)
    # the bystander's stream is complete and byte-correct
    exp = service.SyntheticSource.payload(640, 8, 3)
    assert np.array_equal(store['bystander'].result(), exp)
    # zero cross-tenant blast radius: no shed, no poisoned rings, no
    # failures recorded against the bystander
    bs = bystander.stats()
    assert bs['ring_shed_gulps'] == 0
    assert bs['rings_poisoned'] == 0
    assert bs['health'] in ('OK', 'DEGRADED')
    assert bystander.pipeline.supervisor.failures == []
    assert victim.stats()['rings_poisoned'] > 0


def test_job_registry_and_states():
    store = {}
    mgr = service.JobManager(max_tenants=2, warm=False)
    job = mgr.submit(synth_spec('reg', nframe=32), gather_build(
        store, 'reg'))
    assert job.state == 'PENDING'
    assert service.live_jobs()['reg'] is job
    mgr.start()
    assert job.wait(30) == 'DONE'
    assert job.start_latency_s is not None and job.start_latency_s > 0
    # a PENDING job stops to CANCELLED without ever running
    j2 = mgr.submit(synth_spec('reg2', nframe=32),
                    gather_build(store, 'reg2'))
    assert j2.stop() == 'CANCELLED'


# ---------------------------------------------------------------------------
# warm starts
# ---------------------------------------------------------------------------

def _device_build(sinks):
    from bifrost_tpu.stages import DetectStage, FftStage, ReduceStage

    def build(gate):
        b = bf.blocks.copy(gate, space='tpu')
        fbk = bf.blocks.fused(
            b, [FftStage('chan', axis_labels='freq'),
                DetectStage('scalar'), ReduceStage('freq', 3)])
        sinks.append(GatherSink(bf.blocks.copy(fbk, space='system')))
    return build


def _dev_spec(tid, nchan=64):
    return synth_spec(tid, nframe=96, gulp=32, nchan=nchan, seed=1)


def test_warm_start_zero_recompiles():
    sinks = []
    mgr = service.JobManager(max_tenants=4)
    cold = mgr.submit(_dev_spec('cold0'), _device_build(sinks))
    assert not cold.warm
    cold.start()
    assert cold.wait(120) == 'DONE'
    builds0 = counters.get('fused.plan_builds')
    hits0 = counters.get('fused.plan_depot_hits')
    adopt0 = counters.get('autotune.profile_adoptions')
    warm = mgr.submit(_dev_spec('warm0'), _device_build(sinks))
    assert warm.warm and not warm.warm_rejected
    assert warm.topology_hash == cold.topology_hash
    warm.start()
    assert warm.wait(120) == 'DONE'
    # zero recompiles: every plan came out of the depot
    assert counters.get('fused.plan_builds') == builds0
    assert counters.get('fused.plan_depot_hits') > hits0
    # knob-profile adoption (skipping convergence) is counted
    assert counters.get('autotune.profile_adoptions') == adopt0 + 1
    assert np.array_equal(sinks[0].result(), sinks[1].result())


def test_warm_stale_mismatch_rejected():
    from bifrost_tpu.stages import DetectStage, FftStage, ReduceStage
    sinks = []
    mgr = service.JobManager(max_tenants=4)
    cold = mgr.submit(_dev_spec('stale0'), _device_build(sinks))
    cold.start()
    assert cold.wait(120) == 'DONE'

    # SAME structural topology (block types + ring roles), DIFFERENT
    # stage math: the reduce factor changes, so the plan signature
    # must veto depot reuse even though the topology hash matches
    def build_other(gate):
        b = bf.blocks.copy(gate, space='tpu')
        fbk = bf.blocks.fused(
            b, [FftStage('chan', axis_labels='freq'),
                DetectStage('scalar'), ReduceStage('freq', 11)])
        sinks.append(GatherSink(bf.blocks.copy(fbk, space='system')))
    before = counters.get('service.warm.rejected_stale')
    other = mgr.submit(_dev_spec('stale1'), build_other)
    assert other.topology_hash == cold.topology_hash
    assert not other.warm and other.warm_rejected
    assert counters.get('service.warm.rejected_stale') == before + 1
    other.start()
    assert other.wait(120) == 'DONE'


def test_warm_disabled_by_env(monkeypatch):
    monkeypatch.setenv('BF_SERVE_WARM', '0')
    store = {}
    mgr = service.JobManager(max_tenants=4)
    assert not mgr.warm_enabled
    j1 = mgr.submit(synth_spec('nw0', nframe=32),
                    gather_build(store, 'nw0'))
    j1.start()
    assert j1.wait(30) == 'DONE'
    j2 = mgr.submit(synth_spec('nw1', nframe=32),
                    gather_build(store, 'nw1'))
    assert not j2.warm


# ---------------------------------------------------------------------------
# UDP capture tenants
# ---------------------------------------------------------------------------

def test_udp_capture_tenant(monkeypatch):
    import time

    from bifrost_tpu.io.packet_writer import HeaderInfo, UDPTransmit
    from bifrost_tpu.io.udp_socket import Address, UDPSocket
    monkeypatch.setenv('BF_NO_NATIVE_CAPTURE', '1')
    NSRC, PAYLOAD, BUF, NSEQ = 2, 64, 8, 32
    store = {}
    mgr = service.JobManager(max_tenants=2, warm=False)
    spec = service.TenantSpec('udp0', gulp_nframe=BUF, source={
        'kind': 'udp', 'port': 0, 'nsrc': NSRC, 'payload': PAYLOAD,
        'buffer_ntime': BUF, 'timeout_s': 0.2})
    job = mgr.submit(spec, build=lambda gate: store.setdefault(
        's', GatherSink(gate)))
    assert job._pump is not None and job._pump.port > 0
    job.start()
    time.sleep(0.3)                # let the ring reader attach
    tx_sock = UDPSocket().connect(Address('127.0.0.1',
                                          job._pump.port))
    rng = np.random.RandomState(0)
    data = rng.randint(0, 255, size=(NSEQ, NSRC,
                                     PAYLOAD)).astype(np.uint8)
    hi = HeaderInfo()
    hi.set_nsrc(NSRC)
    with UDPTransmit('chips', tx_sock) as tx:
        tx.send(hi, 1, 1, 0, 1, data[:1])
        # a mid-sequence gap longer than the socket timeout: the
        # service pump must keep listening, not end the stream
        time.sleep(0.3)
        tx.send(hi, 2, 1, 0, 1, data[1:])
        tx.send(hi, NSEQ + 1, 1, 0, 1,
                np.zeros((BUF * 2, NSRC, PAYLOAD), np.uint8))
    time.sleep(0.5)
    assert job.state == 'RUNNING'  # live capture runs until stopped
    assert job.stop(15) == 'DONE'
    out = store['s'].result()
    assert out is not None and out.shape[0] >= NSEQ
    assert np.array_equal(out[:NSEQ], data)
    assert counters.get('service.udp0.admitted_gulps') >= NSEQ // BUF


# ---------------------------------------------------------------------------
# telemetry surfaces
# ---------------------------------------------------------------------------

def test_snapshot_tenants_section():
    store = {}
    mgr = service.JobManager(max_tenants=2, warm=False)
    mgr.submit(synth_spec('tele', nframe=64, slo_ms=60000),
               gather_build(store, 'tele'))
    mgr.start()
    assert mgr.wait(30)['tele'] == 'DONE'
    snap = exporter.snapshot()
    assert 'tele' in snap['tenants']
    d = snap['tenants']['tele']
    assert d['state'] == 'DONE' and d['health'] == 'OK'
    assert d['gulps'] == 4 and d['bytes'] == 4 * 16 * 8 * 4
    assert d['quota_shed_gulps'] == 0
    slo = d['slo']
    assert slo['budget_ms'] == 60000 and slo['ok'] is True
    assert len(slo['trace_ids']) == 1


def test_prometheus_tenant_series():
    store = {}
    mgr = service.JobManager(max_tenants=2, warm=False)
    mgr.submit(synth_spec('prom', nframe=64),
               gather_build(store, 'prom'))
    mgr.start()
    assert mgr.wait(30)['prom'] == 'DONE'
    text = exporter.prometheus_text()
    assert 'bifrost_tpu_tenant{tenant="prom",kind="gulps"} 4' in text
    assert 'bifrost_tpu_tenant_health{tenant="prom",state="OK"} 1' \
        in text


def test_like_top_tenants_pane():
    sys.path.insert(0, os.path.join(ROOT, 'tools'))
    try:
        import like_top
    finally:
        sys.path.pop(0)
    tenants = {1234: {'ntenants': 2,
                      't.replay.state': 'RUNNING',
                      't.replay.health': 'OK',
                      't.replay.gulps': 42, 't.replay.q_shed': 3,
                      't.replay.warm': 1, 't.replay.age99_ms': 12.5,
                      't.synth.state': 'FAILED',
                      't.synth.health': 'FAILED',
                      't.synth.gulps': 7, 't.synth.q_shed': 0,
                      't.synth.warm': 0}}
    lines = like_top.render_text(
        like_top.get_load_average(), {}, like_top.
        get_memory_swap_usage(), None, {}, tenants=tenants)
    text = '\n'.join(lines)
    assert '[tenants] pid 1234  2 tenant(s)' in text
    assert 'replay' in text and 'RUNNING' in text and '12.5' in text
    assert 'FAILED' in text


def test_service_proclog_pane_published():
    from bifrost_tpu import proclog
    store = {}
    mgr = service.JobManager(max_tenants=2, warm=False)
    mgr.submit(synth_spec('pane', nframe=64),
               gather_build(store, 'pane'))
    mgr.start()
    assert mgr.wait(30)['pane'] == 'DONE'
    mgr.shutdown()
    logs = proclog.load_by_pid(os.getpid())
    pane = logs.get('service', {}).get('tenants')
    assert pane and pane.get('t.pane.state') == 'DONE'


# ---------------------------------------------------------------------------
# CLI + gate wiring
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bf_serve_validate_cli(tmp_path):
    spec = {'tenants': [
        {'id': 'synth0',
         'source': {'kind': 'synthetic', 'nframe_total': 64,
                    'gulp_nframe': 16, 'nchan': 8}},
        {'id': 'synth1', 'quota_bytes_per_s': 1e6,
         'quota_policy': 'pace', 'gulp_nframe': 16,
         'source': {'kind': 'synthetic', 'nframe_total': 64,
                    'gulp_nframe': 16, 'nchan': 8}},
    ]}
    path = str(tmp_path / 'svc.json')
    with open(path, 'w') as f:
        json.dump(spec, f)
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'tools', 'bf_serve.py'),
         path, '--validate'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=180)
    assert out.returncode == 0, out.stdout + out.stderr
    assert 'validate PASS' in out.stdout
    # a duplicate id must fail static validation with BF-E210
    spec['tenants'][1]['id'] = 'synth0'
    with open(path, 'w') as f:
        json.dump(spec, f)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'tools', 'bf_serve.py'),
         path, '--validate'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=180)
    assert out.returncode == 3
    assert 'BF-E210' in out.stdout


def test_service_gate_wired():
    with open(os.path.join(ROOT, 'tools',
                           'watch_and_bench.sh')) as f:
        sh = f.read()
    assert 'BF_SKIP_SERVICE_GATE' in sh
    assert 'tools/service_gate.py' in sh
    import bench_suite
    assert 'config18_service' in bench_suite.build_verify_topologies()
    assert 18 in bench_suite.ALL
