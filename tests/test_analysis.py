"""Static pipeline verifier + dynamic ring-protocol checker
(bifrost_tpu.analysis; docs/analysis.md).

Two halves, mirroring the module:

- seeded-misconfiguration fixtures asserting the verifier flags each
  class with its EXACT stable diagnostic code (the codes are API);
- fault-injected protocol corruptions in BOTH ring cores asserting the
  ringcheck shadow state machine trips every invariant class with a
  span-history trace.
"""

import threading
import time

import numpy as np
import pytest

import bifrost_tpu as bf
import bifrost_tpu.native as native_mod
from bifrost_tpu.analysis import ringcheck
from bifrost_tpu.analysis.ringcheck import RingProtocolError
from bifrost_tpu.analysis.verify import (CODES, PipelineValidationError)
from bifrost_tpu.ring import Ring, RingPoisonedError
from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage
from bifrost_tpu.testing import faults
from tests.util import NumpySourceBlock, GatherSink, simple_header

pytestmark = pytest.mark.faults


# ---------------------------------------------------------------------------
# static verifier: seeded misconfigurations -> exact codes
# ---------------------------------------------------------------------------

NT, NP, NF = 64, 2, 256


def _raw(n=1):
    raw = np.zeros((NT, NP, NF), dtype=np.dtype([('re', 'i1'),
                                                 ('im', 'i1')]))
    return [raw.copy() for _ in range(n)]


def _hdr():
    return simple_header([-1, NP, NF], 'ci8',
                         labels=['time', 'pol', 'fine_time'])


def _codes(diags):
    return sorted(d.code for d in diags)


def test_clean_chain_validates_clean():
    """The config-8 chain (the hot path every bench runs) must verify
    with zero errors/warnings — the strict gate depends on this.
    Info-level findings are allowed (BF-I190 inventories the unfused
    device-ring boundaries on every chain, by design); anything
    visible in warn mode is not."""
    with bf.Pipeline(sync_depth=4) as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(b, [FftStage('fine_time',
                                          axis_labels='freq'),
                                 DetectStage('stokes', axis='pol'),
                                 ReduceStage('freq', 4)])
        GatherSink(bf.blocks.copy(fb, space='system'))
        diags = p.validate()
    visible = [d for d in diags if d.severity != 'info']
    assert visible == [], _codes(visible)
    # the info inventory names each non-fused device-ring boundary
    assert {d.code for d in diags} <= {'BF-I190'}, _codes(diags)


def test_undersized_macro_ring_is_deadlock_error():
    """Seeded misconfiguration 1: the consumer reads a 4-gulp span
    batched by macro K=8 (32*NT frames held by its guarantee) but the
    largest declared capacity — its own buffer_nframe=16*NT, which
    also exceeds the writer's 2-macro-span depth — cannot hold that
    pin plus the writer's resident span: as declared, the writer
    deadlocks (only the runtime's silent auto-grow override rescues
    it) -> BF-E101."""
    with bf.Pipeline(gulp_batch=8) as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(b, [FftStage('fine_time',
                                          axis_labels='freq')],
                             gulp_nframe=4 * NT,
                             buffer_nframe=16 * NT)
        GatherSink(bf.blocks.copy(fb, space='system'))
        diags = p.validate()
    hits = [d for d in diags if d.code == 'BF-E101']
    assert len(hits) == 1
    assert 'macro K=8' in hits[0].message
    assert hits[0].ring is not None


def test_dtype_contract_break_is_error():
    """Seeded misconfiguration 2: a stage whose header contract the
    upstream stream cannot satisfy (reducing an axis label that does
    not exist yet) -> BF-E121 at submit time, not gulp 0."""
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(b, [ReduceStage('freq', 4)])  # no 'freq'
        GatherSink(bf.blocks.copy(fb, space='system'))
        diags = p.validate()
    assert [d.code for d in diags if d.is_error] == ['BF-E121']
    assert fb.name in [d.block for d in diags if d.is_error]


def test_donation_with_multi_reader_is_error():
    """Seeded misconfiguration 3: donate=True on a block whose input
    ring has a second reader -> exclusivity disprovable, BF-E130."""
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(b, [FftStage('fine_time',
                                          axis_labels='freq')],
                             donate=True)
        tap = bf.blocks.fused(b, [DetectStage('stokes', axis='pol')])
        GatherSink(bf.blocks.copy(fb, space='system'))
        GatherSink(bf.blocks.copy(tap, space='system'))
        diags = p.validate()
    hits = [d for d in diags if d.code == 'BF-E130']
    assert len(hits) == 1 and hits[0].block == fb.name


def test_forced_reshard_mesh_chain_warns():
    """Seeded misconfiguration 4: an H2D copy OUTSIDE the mesh scope
    feeding a mesh fused block -> every gulp pays a relayout,
    BF-W140 (mesh.reshards > 0 predicted statically)."""
    import jax
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:1]), ('sp',))
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')           # no mesh
        fb = bf.blocks.fused(b, [DetectStage('stokes', axis='pol')],
                             mesh=mesh)
        GatherSink(bf.blocks.copy(fb, space='system', mesh=mesh))
        diags = p.validate()
    hits = [d for d in diags if d.code == 'BF-W140']
    assert hits and hits[0].block == fb.name
    assert 'reshard' in hits[0].message


def test_covered_declaration_is_not_flagged():
    """An undersized buffer_nframe on one reader is harmless when
    another reader's request covers the bound (Ring.resize negotiates
    the MAX over all requests) — no BF-E101/W102 false positive on a
    pipeline that runs fine."""
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        fb1 = bf.blocks.fused(b, [FftStage('fine_time',
                                           axis_labels='freq')],
                              buffer_nframe=NT)        # undersized...
        fb2 = bf.blocks.fused(b, [DetectStage('scalar')],
                              buffer_nframe=64 * NT)   # ...but covered
        GatherSink(bf.blocks.copy(fb1, space='system'))
        GatherSink(bf.blocks.copy(fb2, space='system'))
        diags = p.validate()
    codes = _codes(diags)
    assert 'BF-E101' not in codes and 'BF-W102' not in codes, codes


def test_bridge_window_within_sender_resize_is_clean():
    """BF-W110 must account for RingSender's own runtime resize to
    window+2 spans — a plain window=4 bridge sink is NOT capped."""
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        bf.blocks.bridge.bridge_sink(src, '127.0.0.1', 59999,
                                     window=4)
        diags = p.validate()
    assert 'BF-W110' not in _codes(diags), _codes(diags)


def test_bridge_window_zero_is_error():
    """Seeded misconfiguration 5: BridgeSink(window=0) — the runtime
    clamp silently papers it over; the verifier flags the request."""
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        bf.blocks.bridge.bridge_sink(src, '127.0.0.1', 59999,
                                     window=0)
        diags = p.validate()
    assert [d.code for d in diags if d.is_error] == ['BF-E150']


def test_bridge_v1_wire_warnings():
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        bf.blocks.bridge.bridge_sink(src, '127.0.0.1', 59999,
                                     protocol=1, crc=True, window=4)
        diags = p.validate()
    codes = _codes(diags)
    assert 'BF-W151' in codes and 'BF-W152' in codes


def test_macro_ineligibility_reported():
    """A block that requests batching but is statically ineligible
    warns (BF-W160 with the reason); host blocks under a batching
    scope stay info-level (BF-I161)."""
    with bf.Pipeline(gulp_batch=8) as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(b, [DetectStage('stokes', axis='pol')],
                             guarantee=False)   # static ineligibility
        GatherSink(bf.blocks.copy(fb, space='system'))
        diags = p.validate()
    w = [d for d in diags if d.code == 'BF-W160']
    assert len(w) == 1 and w[0].block == fb.name
    assert 'unguaranteed' in w[0].message
    assert any(d.code == 'BF-I161' for d in diags)   # the host sink


def test_float_path_on_quantized_ring_warns():
    """Seeded misconfiguration: a BeamformBlock on a ci8 ring whose
    'f32' accuracy class excludes the int8 candidates -> BF-W170; the
    'int8' class (or a forced int candidate) is clean; a forced FLOAT
    candidate on the same ring warns again."""
    rng = np.random.RandomState(0)
    # weights (B, S) for a ['time', 'freq', 'station', 'pol'] stream
    S, P, B = 8, 2, 4
    w = (rng.randn(B, S) + 1j * rng.randn(B, S)).astype(np.complex64)
    hdr = simple_header([-1, NF, S, P], 'ci8',
                        labels=['time', 'freq', 'station', 'pol'])
    raw = np.zeros((NT, NF, S, P), dtype=np.dtype([('re', 'i1'),
                                                   ('im', 'i1')]))

    def build(**kw):
        with bf.Pipeline() as p:
            src = NumpySourceBlock([raw.copy()], hdr, gulp_nframe=NT)
            b = bf.blocks.copy(src, space='tpu')
            b = bf.blocks.beamform(b, w, **kw)
            GatherSink(bf.blocks.copy(b, space='system'))
            return p.validate()

    def visible(diags):
        # BF-I190 inventories unfused boundaries on every chain; this
        # test is about the warning
        return [d for d in diags if d.severity != 'info']

    diags = build(accuracy='f32')
    assert 'BF-W170' in _codes(diags), _codes(diags)
    assert visible(build(accuracy='int8')) == []
    assert visible(build(accuracy='f32', impl='int8_wide')) == []
    forced = build(accuracy='int8', impl='planar_bf16')
    assert 'BF-W170' in _codes(forced), _codes(forced)


def test_all_codes_catalogued():
    """Every diagnostic code the tests assert is in the stable
    catalog, and severities derive from the code letter."""
    for code, title in CODES.items():
        assert code.startswith('BF-') and code[3] in 'EWI'
        assert title


def test_validate_strict_refuses_to_run(monkeypatch):
    monkeypatch.setenv('BF_VALIDATE', 'strict')
    with bf.Pipeline(gulp_batch=8) as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        bf.blocks.fused(b, [FftStage('fine_time',
                                     axis_labels='freq')],
                        gulp_nframe=4 * NT, buffer_nframe=16 * NT)
        with pytest.raises(PipelineValidationError) as ei:
            p.run()
    assert 'BF-E101' in str(ei.value)


def test_validate_warn_still_runs(monkeypatch, capsys):
    """warn mode reports the same finding but the pipeline runs (the
    runtime's auto-grow sizing overrides the bad declaration)."""
    monkeypatch.setenv('BF_VALIDATE', 'warn')
    with bf.Pipeline(gulp_batch=8) as p:
        src = NumpySourceBlock(_raw(2), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(b, [FftStage('fine_time',
                                          axis_labels='freq')],
                             gulp_nframe=4 * NT,
                             buffer_nframe=16 * NT)
        sink = GatherSink(bf.blocks.copy(fb, space='system'))
        p.run()
    assert sink.result() is not None
    assert 'BF-E101' in capsys.readouterr().err


def test_lint_intercept_builds_without_running(monkeypatch, tmp_path):
    out = tmp_path / 'lint.jsonl'
    monkeypatch.setenv('BF_LINT', '1')
    monkeypatch.setenv('BF_LINT_OUT', str(out))
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_raw(), _hdr(), gulp_nframe=NT)
        sink = GatherSink(bf.blocks.copy(src))
        p.run()                      # validates and returns
    assert sink.result() is None     # nothing actually ran
    import json
    recs = [json.loads(line) for line in
            out.read_text().splitlines()]
    assert recs and recs[0]['pipeline'] == p.name
    assert recs[0]['nblocks'] == 3


# ---------------------------------------------------------------------------
# dynamic ring-protocol checker: corrupt the protocol, both cores
# ---------------------------------------------------------------------------

@pytest.fixture(params=['native', 'python'])
def ring_core(request, monkeypatch):
    """Run each checker test against BOTH ring cores (the same trick
    tests/test_ring_python_core.py uses to force the Python core)."""
    if request.param == 'python':
        monkeypatch.setattr(native_mod, '_lib', None)
        monkeypatch.setattr(native_mod, '_tried', True)
    elif not native_mod.available():
        pytest.skip('native core unavailable')
    return request.param


@pytest.fixture
def checker():
    ringcheck.set_enabled(True)
    ringcheck.reset()
    yield ringcheck
    faults.clear()
    ringcheck.set_enabled(False)
    ringcheck.reset()


def _open_seq(ring, gulp=8, buf=32):
    hdr = simple_header([-1, 4], 'f32')
    wr = ring.begin_writing()
    seq = wr.begin_sequence(hdr, gulp_nframe=gulp, buf_nframe=buf)
    return wr, seq


def test_double_commit_detected(ring_core, checker):
    ring = Ring(space='system', name='rc_dc_%s' % ring_core)
    wr, seq = _open_seq(ring)
    with faults.injected('ring.corrupt.double_commit',
                         match=ring.name):
        span = seq.reserve(8)
        span.data.as_numpy()[...] = 1.0
        span.commit(8)
        with pytest.raises(RingProtocolError) as ei:
            span.close()
    assert ei.value.invariant == 'double_commit'
    assert 'span history' in str(ei.value)
    assert ringcheck.violations()


def test_double_release_detected(ring_core, checker):
    ring = Ring(space='system', name='rc_dr_%s' % ring_core)
    wr, seq = _open_seq(ring)
    with seq.reserve(8) as span:
        span.data.as_numpy()[...] = 2.0
        span.commit(8)
    rseq = ring.open_earliest_sequence(guarantee=True)
    rspan = rseq.acquire(0, 8)
    with faults.injected('ring.corrupt.double_release',
                         match=ring.name):
        with pytest.raises(RingProtocolError) as ei:
            rspan.release()
    assert ei.value.invariant == 'double_release'
    assert 'release' in str(ei.value)


def test_acquire_uncommitted_detected(ring_core, checker):
    ring = Ring(space='system', name='rc_au_%s' % ring_core)
    wr, seq = _open_seq(ring)
    with seq.reserve(8) as span:
        span.data.as_numpy()[...] = 3.0
        span.commit(8)
    rseq = ring.open_earliest_sequence(guarantee=True)
    with faults.injected('ring.corrupt.acquire_uncommitted',
                         match=ring.name):
        with pytest.raises(RingProtocolError) as ei:
            rseq.acquire(0, 8)
    assert ei.value.invariant == 'acquire_uncommitted'
    assert 'committed head' in str(ei.value)


def test_commit_order_violation_detected(ring_core, checker):
    """A partial commit while a later reservation is outstanding
    breaks the in-order barrier's truncation rule — the checker
    catches it BEFORE the core does (no corruption seam needed; the
    illegal call sequence is enough)."""
    ring = Ring(space='system', name='rc_co_%s' % ring_core)
    wr, seq = _open_seq(ring, gulp=8, buf=64)
    s1 = seq.reserve(8)
    s2 = seq.reserve(8)
    s1.data.as_numpy()[...] = 1.0
    s1.commit(4)                      # partial, with s2 outstanding
    with pytest.raises(RingProtocolError) as ei:
        s1.close()
    assert ei.value.invariant == 'commit_order'
    # a zero-commit of the NEWEST span stays legal (clean unwind path)
    s2.commit(0)
    s2.close()


def test_guarantee_jump_detected(ring_core, checker):
    """Corrupt the CORE guarantee forward past a held span (the
    pre-PR-5 watermark bug): the checker flags the overwriting
    reserve the corrupted core then admits."""
    ring = Ring(space='system', name='rc_gj_%s' % ring_core)
    wr, seq = _open_seq(ring, gulp=8, buf=16)      # 2 spans capacity
    for val in (1.0, 2.0):
        with seq.reserve(8) as span:
            span.data.as_numpy()[...] = val
            span.commit(8)
    rseq = ring.open_earliest_sequence(guarantee=True)
    with faults.injected('ring.corrupt.guarantee_jump',
                         match=ring.name):
        rspan = rseq.acquire(0, 8)    # held span; guarantee jumps
    with pytest.raises(RingProtocolError) as ei:
        with seq.reserve(8) as span:  # overwrites the held span
            span.commit(0)
    assert ei.value.invariant == 'guarantee_pin'
    assert 'overwriting' in str(ei.value)


def test_poison_wakes_blocked_spans_clean(ring_core, checker):
    """The healthy path: poison wakes a blocked reader within the
    grace window — no violation recorded."""
    ring = Ring(space='system', name='rc_pw_%s' % ring_core)
    wr, seq = _open_seq(ring)
    woke = []

    def reader():
        try:
            rseq = ring.open_earliest_sequence(guarantee=True)
            rseq.acquire(0, 8)        # blocks: nothing committed
        except RingPoisonedError:
            woke.append('poisoned')
        except Exception as exc:      # pragma: no cover
            woke.append(repr(exc))

    t = threading.Thread(target=reader)
    t.start()
    time.sleep(0.2)
    ring.poison(RuntimeError('test poison'))
    t.join(5)
    assert not t.is_alive() and woke == ['poisoned']
    time.sleep(0.4)                   # let the wake timer run
    assert not ringcheck.violations()


def test_poison_nowake_detected(ring_core, checker, monkeypatch):
    """Corrupt poison to NOT wake blocked spans: the checker's wake
    timer must flag the still-blocked acquire with a span-history
    trace."""
    monkeypatch.setenv('BF_RINGCHECK_WAKE_SECS', '0.2')
    ring = Ring(space='system', name='rc_pn_%s' % ring_core)
    wr, seq = _open_seq(ring)
    woke = []

    def reader():
        try:
            rseq = ring.open_earliest_sequence(guarantee=True)
            rseq.acquire(0, 8)        # blocks: nothing committed
        except RingPoisonedError:
            woke.append('poisoned')
        except Exception as exc:      # pragma: no cover
            woke.append(repr(exc))

    t = threading.Thread(target=reader)
    t.start()
    time.sleep(0.2)
    with faults.injected('ring.corrupt.poison_nowake',
                         match=ring.name):
        ring.poison(RuntimeError('test poison'))
    deadline = time.monotonic() + 5
    while not ringcheck.violations() and time.monotonic() < deadline:
        time.sleep(0.05)
    viols = ringcheck.violations()
    assert viols and viols[-1].invariant == 'poison_wake'
    assert 'span history' in str(viols[-1])
    # un-hang the reader and close out
    ring._wake_all()
    t.join(5)
    assert not t.is_alive() and woke == ['poisoned']


def test_ringcheck_off_is_inert(ring_core):
    """BF_RINGCHECK=0: no shadow state is attached to rings at all —
    the disarmed seams are bit-identical in behavior to pre-checker
    code."""
    ringcheck.set_enabled(False)
    ring = Ring(space='system', name='rc_off_%s' % ring_core)
    wr, seq = _open_seq(ring)
    with seq.reserve(8) as span:
        span.data.as_numpy()[...] = 1.0
        span.commit(8)
    rseq = ring.open_earliest_sequence(guarantee=True)
    with rseq.acquire(0, 8):
        pass
    assert '_rc_shadow' not in ring.__dict__


def test_ringcheck_inside_pipeline(checker):
    """End to end: a real pipeline runs clean under BF_RINGCHECK=1
    (no false positives from the shadow model on the shipped
    protocol)."""
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_raw(2), _hdr(), gulp_nframe=NT)
        sink = GatherSink(bf.blocks.copy(src))
        p.run()
    assert sink.result() is not None
    assert not ringcheck.violations()


def test_resize_under_span_detected(ring_core, checker):
    """The resize_quiescence invariant (the auto-tuner's retune
    protocol, docs/autotune.md): a core reporting a storage re-layout
    while spans are open is caught by the shadow state machine — in
    BOTH cores, via the ``ring.corrupt.resize_under_span`` seam that
    simulates applying the deferred resize under a live span."""
    ring = Ring(space='system', name='rc_rz_%s' % ring_core)
    wr, seq = _open_seq(ring)
    span = seq.reserve(8)
    with faults.injected('ring.corrupt.resize_under_span',
                         match=ring.name):
        with pytest.raises(RingProtocolError) as ei:
            ring.request_resize(1, ring.total_span * 2)
    assert ei.value.invariant == 'resize_quiescence'
    assert 'dangle' in str(ei.value)
    assert ringcheck.violations()
    span.data.as_numpy()[...] = 1.0
    span.commit(8)
    span.close()


def test_deferred_resize_clean_under_checker(ring_core, checker):
    """The LEGITIMATE deferred-resize protocol — request under an open
    span, apply at quiescence — must run clean under BF_RINGCHECK=1 in
    both cores (no false positives from the new invariant)."""
    ring = Ring(space='system', name='rc_rzok_%s' % ring_core)
    wr, seq = _open_seq(ring)
    before = ring.total_span
    span = seq.reserve(8)
    assert not ring.request_resize(1, before * 2)
    span.data.as_numpy()[...] = 1.0
    span.commit(8)
    span.close()
    assert ring.total_span >= before * 2
    assert not ringcheck.violations()
