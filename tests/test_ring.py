"""Ring buffer semantics tests (reference analogues: test/test_resizing.py,
ring behavior described in SURVEY.md §2.1)."""

import threading

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.ring import Ring, EndOfDataStop
from tests.util import simple_header


def _hdr(frame_shape=(4,), dtype='f32', **kw):
    return simple_header([-1] + list(frame_shape), dtype, **kw)


def test_write_read_simple():
    ring = Ring(space='system')
    hdr = _hdr()
    received = []

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=24) as seq:
                for k in range(4):
                    with seq.reserve(8) as span:
                        data = span.data.as_numpy()
                        data[...] = np.arange(8 * 4).reshape(8, 4) + 100 * k
                        span.commit(8)

    t = threading.Thread(target=writer)
    t.start()
    for seq in ring.read(guarantee=True):
        seq.resize(gulp_nframe=8)
        for span in seq.read(8):
            received.append(np.array(span.data.as_numpy(), copy=True))
    t.join()
    assert len(received) == 4
    np.testing.assert_array_equal(received[2],
                                  np.arange(32).reshape(8, 4) + 200)


def test_partial_final_span():
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(2,))

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=24) as seq:
                with seq.reserve(8) as span:
                    span.data.as_numpy()[...] = 1.0
                    span.commit(8)
                with seq.reserve(8) as span:
                    span.data.as_numpy()[:3] = 2.0
                    span.commit(3)   # partial final gulp

    t = threading.Thread(target=writer)
    t.start()
    sizes = []
    for seq in ring.read():
        seq.resize(gulp_nframe=8)
        for span in seq.read(8):
            sizes.append(span.nframe)
    t.join()
    assert sizes == [8, 3]


def test_multiple_sequences():
    ring = Ring(space='system')

    def writer():
        with ring.begin_writing() as wr:
            for s in range(3):
                hdr = _hdr(name='seq%d' % s)
                hdr['time_tag'] = s
                with wr.begin_sequence(hdr, gulp_nframe=4,
                                       buf_nframe=12) as seq:
                    with seq.reserve(4) as span:
                        span.data.as_numpy()[...] = s
                        span.commit(4)

    t = threading.Thread(target=writer)
    t.start()
    names = []
    for seq in ring.read():
        seq.resize(gulp_nframe=4)
        for span in seq.read(4):
            names.append((seq.header['name'], float(
                span.data.as_numpy().ravel()[0])))
    t.join()
    assert names == [('seq0', 0.0), ('seq1', 1.0), ('seq2', 2.0)]


def test_overlap_read():
    """Overlapped gulps (stride < nframe), as used by FIR/FDMT."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(1,))

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=6, buf_nframe=32) as seq:
                for k in range(3):
                    with seq.reserve(6) as span:
                        span.data.as_numpy()[:, 0] = np.arange(6) + 6 * k
                        span.commit(6)

    t = threading.Thread(target=writer)
    t.start()
    got = []
    for seq in ring.read():
        seq.resize(gulp_nframe=8, buffer_factor=4)
        for span in seq.read(8, stride=6):
            got.append(np.array(span.data.as_numpy()[:, 0], copy=True))
    t.join()
    np.testing.assert_array_equal(got[0], np.arange(8))
    np.testing.assert_array_equal(got[1], np.arange(6, 14))


def test_device_ring_roundtrip():
    import jax.numpy as jnp
    ring = Ring(space='tpu')
    hdr = _hdr(frame_shape=(4,))

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=24) as seq:
                for k in range(3):
                    with seq.reserve(8) as span:
                        span.set(jnp.full((8, 4), float(k)))
                        span.commit(8)

    t = threading.Thread(target=writer)
    t.start()
    vals = []
    for seq in ring.read():
        seq.resize(gulp_nframe=8)
        for span in seq.read(8):
            vals.append(float(np.asarray(span.data)[0, 0]))
    t.join()
    assert vals == [0.0, 1.0, 2.0]


def test_ringlets():
    ring = Ring(space='system')
    hdr = simple_header([2, -1, 3], 'f32', labels=['beam', 'time', 'chan'])

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=4, buf_nframe=12) as seq:
                with seq.reserve(4) as span:
                    d = span.data.as_numpy()
                    assert d.shape == (2, 4, 3)
                    d[0] = 1.0
                    d[1] = 2.0
                    span.commit(4)

    t = threading.Thread(target=writer)
    t.start()
    for seq in ring.read():
        seq.resize(gulp_nframe=4)
        for span in seq.read(4):
            d = span.data.as_numpy()
            assert d.shape == (2, 4, 3)
            assert np.all(d[0] == 1.0)
            assert np.all(d[1] == 2.0)
    t.join()


def test_unguaranteed_overwrite_skip():
    """A slow unguaranteed reader gets frames skipped, not a deadlock."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(1,))
    start_reading = threading.Event()
    wrote_all = threading.Event()

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=4, buf_nframe=8) as seq:
                for k in range(16):
                    with seq.reserve(4) as span:
                        span.data.as_numpy()[:, 0] = k
                        span.commit(4)
                    if k == 0:
                        start_reading.set()
        wrote_all.set()

    t = threading.Thread(target=writer)
    t.start()
    start_reading.wait()
    wrote_all.wait()   # let the writer lap the reader completely
    skipped_total = 0
    frames = 0
    for seq in ring.read(guarantee=False):
        seq.resize(gulp_nframe=4, buffer_factor=2)
        for span in seq.read(4):
            skipped_total += span.nframe_skipped
            frames += span.nframe
    t.join()
    assert skipped_total > 0
    assert frames + skipped_total == 64


def test_resize_while_data_buffered():
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(2,))
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=4, buf_nframe=12) as seq:
            with seq.reserve(4) as span:
                span.data.as_numpy()[...] = 7.0
                span.commit(4)
            # grow the ring while data is buffered
            ring.resize(4 * 8, 64 * 8)
            with seq.reserve(4) as span:
                span.data.as_numpy()[...] = 9.0
                span.commit(4)
    # read it back after resize preserved the buffered bytes
    vals = []
    for seq in ring.read():
        for span in seq.read(4):
            vals.append(float(span.data.as_numpy().ravel()[0]))
    assert vals == [7.0, 9.0]


def test_stress_concurrent_churn():
    """Many small gulps through a small ring with a guaranteed reader:
    exercises wrap-around, ghost copies, and flow control under real
    thread contention (native or Python core, whichever is active)."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(16,))
    NGULP, GULP = 200, 8
    import hashlib
    write_hash = hashlib.sha256()
    read_hash = hashlib.sha256()
    # The guarantee only protects data once the reader has opened the
    # sequence; gate the writer so it can't lap the ring before that.
    reader_attached = threading.Event()

    def writer():
        rng = np.random.RandomState(42)
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=GULP,
                                   buf_nframe=GULP * 3) as seq:
                for k in range(NGULP):
                    if k == 1:
                        assert reader_attached.wait(30)
                    with seq.reserve(GULP) as span:
                        data = rng.randint(
                            0, 255, size=(GULP, 16)).astype(np.float32)
                        span.data.as_numpy()[...] = data
                        write_hash.update(data.tobytes())
                        span.commit(GULP)

    t = threading.Thread(target=writer)
    t.start()
    nframes = 0
    for seq in ring.read(guarantee=True):
        reader_attached.set()
        seq.resize(gulp_nframe=GULP)
        for span in seq.read(GULP):
            read_hash.update(
                np.ascontiguousarray(span.data.as_numpy()).tobytes())
            nframes += span.nframe
    t.join()
    assert nframes == NGULP * GULP
    assert write_hash.hexdigest() == read_hash.hexdigest()


def test_partial_commit_with_outstanding_spans_is_clean_error():
    """A partial commit is only legal on the newest outstanding span; the
    error must leave ring state untouched (no nwrite_open leak — a leak
    blocks resize quiescence forever; ADVICE r1)."""
    ring = Ring(space='system')
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=32) as seq:
            s1 = seq.reserve(8)
            s2 = seq.reserve(8)
            s1.commit(4)
            with pytest.raises(Exception):
                s1.close()
            # recover: full commits in order must still work
            s1.commit(8)
            s1.close()
            s2.commit(8)
            s2.close()
            # the leak symptom: resize waits for quiescence forever
            done = threading.Event()

            def do_resize():
                ring.resize(16 * 16, 64 * 16)
                done.set()

            t = threading.Thread(target=do_resize, daemon=True)
            t.start()
            assert done.wait(10), "resize deadlocked: nwrite_open leaked"
            t.join()


def test_partial_commit_on_newest_span_ok():
    """Partial commit on the newest span truncates the stream cleanly."""
    ring = Ring(space='system')
    hdr = _hdr()

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8,
                                   buf_nframe=32) as seq:
                with seq.reserve(8) as span:
                    span.data.as_numpy()[...] = 5
                    span.commit(3)

    t = threading.Thread(target=writer)
    t.start()
    got = []
    for seq in ring.read(guarantee=True):
        seq.resize(gulp_nframe=8)
        for span in seq.read(8):
            got.append(span.nframe)
    t.join()
    assert got == [3]


def test_reserve_after_partial_commit_rejected():
    """Reserving past a queued partial commit would hand out offsets the
    truncation then invalidates; both cores reject it up front."""
    ring = Ring(space='system')
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=32) as seq:
            s1 = seq.reserve(8)
            s2 = seq.reserve(8)
            s2.commit(4)
            s2.close()              # queued partial (s1 still open)
            with pytest.raises(Exception):
                seq.reserve(8)
            s1.commit(8)
            s1.close()              # barrier applies s1 full, s2 partial


def test_native_library_selftest():
    """The in-library C++ self-test (reference analogue: bfTestSuite,
    src/testsuite.cpp) passes through the ABI."""
    from bifrost_tpu import native
    if not native.available():
        pytest.skip('native library unavailable')
    assert native.load().bft_selftest() == 0
