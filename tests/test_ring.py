"""Ring buffer semantics tests (reference analogues: test/test_resizing.py,
ring behavior described in SURVEY.md §2.1)."""

import threading

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.ring import Ring, EndOfDataStop
from tests.util import simple_header


def _hdr(frame_shape=(4,), dtype='f32', **kw):
    return simple_header([-1] + list(frame_shape), dtype, **kw)


def test_write_read_simple():
    ring = Ring(space='system')
    hdr = _hdr()
    received = []

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=24) as seq:
                for k in range(4):
                    with seq.reserve(8) as span:
                        data = span.data.as_numpy()
                        data[...] = np.arange(8 * 4).reshape(8, 4) + 100 * k
                        span.commit(8)

    t = threading.Thread(target=writer)
    t.start()
    for seq in ring.read(guarantee=True):
        seq.resize(gulp_nframe=8)
        for span in seq.read(8):
            received.append(np.array(span.data.as_numpy(), copy=True))
    t.join()
    assert len(received) == 4
    np.testing.assert_array_equal(received[2],
                                  np.arange(32).reshape(8, 4) + 200)


def test_partial_final_span():
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(2,))

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=24) as seq:
                with seq.reserve(8) as span:
                    span.data.as_numpy()[...] = 1.0
                    span.commit(8)
                with seq.reserve(8) as span:
                    span.data.as_numpy()[:3] = 2.0
                    span.commit(3)   # partial final gulp

    t = threading.Thread(target=writer)
    t.start()
    sizes = []
    for seq in ring.read():
        seq.resize(gulp_nframe=8)
        for span in seq.read(8):
            sizes.append(span.nframe)
    t.join()
    assert sizes == [8, 3]


def test_multiple_sequences():
    ring = Ring(space='system')

    def writer():
        with ring.begin_writing() as wr:
            for s in range(3):
                hdr = _hdr(name='seq%d' % s)
                hdr['time_tag'] = s
                with wr.begin_sequence(hdr, gulp_nframe=4,
                                       buf_nframe=12) as seq:
                    with seq.reserve(4) as span:
                        span.data.as_numpy()[...] = s
                        span.commit(4)

    t = threading.Thread(target=writer)
    t.start()
    names = []
    for seq in ring.read():
        seq.resize(gulp_nframe=4)
        for span in seq.read(4):
            names.append((seq.header['name'], float(
                span.data.as_numpy().ravel()[0])))
    t.join()
    assert names == [('seq0', 0.0), ('seq1', 1.0), ('seq2', 2.0)]


def test_overlap_read():
    """Overlapped gulps (stride < nframe), as used by FIR/FDMT."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(1,))

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=6, buf_nframe=32) as seq:
                for k in range(3):
                    with seq.reserve(6) as span:
                        span.data.as_numpy()[:, 0] = np.arange(6) + 6 * k
                        span.commit(6)

    t = threading.Thread(target=writer)
    t.start()
    got = []
    for seq in ring.read():
        seq.resize(gulp_nframe=8, buffer_factor=4)
        for span in seq.read(8, stride=6):
            got.append(np.array(span.data.as_numpy()[:, 0], copy=True))
    t.join()
    np.testing.assert_array_equal(got[0], np.arange(8))
    np.testing.assert_array_equal(got[1], np.arange(6, 14))


def test_device_ring_roundtrip():
    import jax.numpy as jnp
    ring = Ring(space='tpu')
    hdr = _hdr(frame_shape=(4,))

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=24) as seq:
                for k in range(3):
                    with seq.reserve(8) as span:
                        span.set(jnp.full((8, 4), float(k)))
                        span.commit(8)

    t = threading.Thread(target=writer)
    t.start()
    vals = []
    for seq in ring.read():
        seq.resize(gulp_nframe=8)
        for span in seq.read(8):
            vals.append(float(np.asarray(span.data)[0, 0]))
    t.join()
    assert vals == [0.0, 1.0, 2.0]


def test_ringlets():
    ring = Ring(space='system')
    hdr = simple_header([2, -1, 3], 'f32', labels=['beam', 'time', 'chan'])

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=4, buf_nframe=12) as seq:
                with seq.reserve(4) as span:
                    d = span.data.as_numpy()
                    assert d.shape == (2, 4, 3)
                    d[0] = 1.0
                    d[1] = 2.0
                    span.commit(4)

    t = threading.Thread(target=writer)
    t.start()
    for seq in ring.read():
        seq.resize(gulp_nframe=4)
        for span in seq.read(4):
            d = span.data.as_numpy()
            assert d.shape == (2, 4, 3)
            assert np.all(d[0] == 1.0)
            assert np.all(d[1] == 2.0)
    t.join()


def test_unguaranteed_overwrite_skip():
    """A slow unguaranteed reader gets frames skipped, not a deadlock."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(1,))
    start_reading = threading.Event()
    wrote_all = threading.Event()

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=4, buf_nframe=8) as seq:
                for k in range(16):
                    with seq.reserve(4) as span:
                        span.data.as_numpy()[:, 0] = k
                        span.commit(4)
                    if k == 0:
                        start_reading.set()
        wrote_all.set()

    t = threading.Thread(target=writer)
    t.start()
    start_reading.wait()
    wrote_all.wait()   # let the writer lap the reader completely
    skipped_total = 0
    frames = 0
    for seq in ring.read(guarantee=False):
        seq.resize(gulp_nframe=4, buffer_factor=2)
        for span in seq.read(4):
            skipped_total += span.nframe_skipped
            frames += span.nframe
    t.join()
    assert skipped_total > 0
    assert frames + skipped_total == 64


def test_resize_while_data_buffered():
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(2,))
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=4, buf_nframe=12) as seq:
            with seq.reserve(4) as span:
                span.data.as_numpy()[...] = 7.0
                span.commit(4)
            # grow the ring while data is buffered
            ring.resize(4 * 8, 64 * 8)
            with seq.reserve(4) as span:
                span.data.as_numpy()[...] = 9.0
                span.commit(4)
    # read it back after resize preserved the buffered bytes
    vals = []
    for seq in ring.read():
        for span in seq.read(4):
            vals.append(float(span.data.as_numpy().ravel()[0]))
    assert vals == [7.0, 9.0]


def test_stress_concurrent_churn():
    """Many small gulps through a small ring with a guaranteed reader:
    exercises wrap-around, ghost copies, and flow control under real
    thread contention (native or Python core, whichever is active)."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(16,))
    NGULP, GULP = 200, 8
    import hashlib
    write_hash = hashlib.sha256()
    read_hash = hashlib.sha256()
    # The guarantee only protects data once the reader has opened the
    # sequence; gate the writer so it can't lap the ring before that.
    reader_attached = threading.Event()

    def writer():
        rng = np.random.RandomState(42)
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=GULP,
                                   buf_nframe=GULP * 3) as seq:
                for k in range(NGULP):
                    if k == 1:
                        assert reader_attached.wait(30)
                    with seq.reserve(GULP) as span:
                        data = rng.randint(
                            0, 255, size=(GULP, 16)).astype(np.float32)
                        span.data.as_numpy()[...] = data
                        write_hash.update(data.tobytes())
                        span.commit(GULP)

    t = threading.Thread(target=writer)
    t.start()
    nframes = 0
    for seq in ring.read(guarantee=True):
        reader_attached.set()
        seq.resize(gulp_nframe=GULP)
        for span in seq.read(GULP):
            read_hash.update(
                np.ascontiguousarray(span.data.as_numpy()).tobytes())
            nframes += span.nframe
    t.join()
    assert nframes == NGULP * GULP
    assert write_hash.hexdigest() == read_hash.hexdigest()


def test_partial_commit_with_outstanding_spans_is_clean_error():
    """A partial commit is only legal on the newest outstanding span; the
    error must leave ring state untouched (no nwrite_open leak — a leak
    blocks resize quiescence forever; ADVICE r1)."""
    ring = Ring(space='system')
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=32) as seq:
            s1 = seq.reserve(8)
            s2 = seq.reserve(8)
            s1.commit(4)
            with pytest.raises(Exception):
                s1.close()
            # recover: full commits in order must still work
            s1.commit(8)
            s1.close()
            s2.commit(8)
            s2.close()
            # the leak symptom: resize waits for quiescence forever
            done = threading.Event()

            def do_resize():
                ring.resize(16 * 16, 64 * 16)
                done.set()

            t = threading.Thread(target=do_resize, daemon=True)
            t.start()
            assert done.wait(10), "resize deadlocked: nwrite_open leaked"
            t.join()


def test_partial_commit_on_newest_span_ok():
    """Partial commit on the newest span truncates the stream cleanly."""
    ring = Ring(space='system')
    hdr = _hdr()

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8,
                                   buf_nframe=32) as seq:
                with seq.reserve(8) as span:
                    span.data.as_numpy()[...] = 5
                    span.commit(3)

    t = threading.Thread(target=writer)
    t.start()
    got = []
    for seq in ring.read(guarantee=True):
        seq.resize(gulp_nframe=8)
        for span in seq.read(8):
            got.append(span.nframe)
    t.join()
    assert got == [3]


def test_reserve_after_partial_commit_rejected():
    """Reserving past a queued partial commit would hand out offsets the
    truncation then invalidates; both cores reject it up front."""
    ring = Ring(space='system')
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=8, buf_nframe=32) as seq:
            s1 = seq.reserve(8)
            s2 = seq.reserve(8)
            s2.commit(4)
            s2.close()              # queued partial (s1 still open)
            with pytest.raises(Exception):
                seq.reserve(8)
            s1.commit(8)
            s1.close()              # barrier applies s1 full, s2 partial


# ---------------------------------------------------------------------------
# multi-gulp (macro) spans — macro-gulp execution reserves/acquires K
# gulps of ring span in one operation (bifrost_tpu.macro; docs/perf.md).
# These run against whichever core is active; test_ring_python_core.py
# re-runs them against the pure-Python core.
# ---------------------------------------------------------------------------

def test_macro_span_ghost_wrap():
    """A multi-gulp span that wraps the nominal end must round-trip
    through the ghost region: every byte written through wrapped macro
    reserves reads back identically at macro granularity."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(4,))
    NSPAN, MACRO = 5, 16          # 2-gulp macro spans, gulp=8
    # the guarantee only protects data once the reader attached; gate
    # the writer so it cannot lap the ring first (same pattern as
    # test_stress_concurrent_churn)
    reader_attached = threading.Event()

    def writer():
        with ring.begin_writing() as wr:
            # buf 56 = 3.5 macro spans: the span at offset 48 runs to
            # 64 > 56, crossing the nominal end mid-span — the
            # commit-side ghost mirror must cover the wrapped MACRO
            # span's overflow
            with wr.begin_sequence(hdr, gulp_nframe=MACRO,
                                   buf_nframe=56) as seq:
                for k in range(NSPAN):
                    if k == 1:
                        assert reader_attached.wait(30)
                    with seq.reserve(MACRO) as span:
                        span.data.as_numpy()[...] = \
                            np.arange(MACRO * 4).reshape(MACRO, 4) \
                            + 1000 * k
                        span.commit(MACRO)

    t = threading.Thread(target=writer)
    t.start()
    received = []
    for seq in ring.read(guarantee=True):
        reader_attached.set()
        seq.resize(gulp_nframe=MACRO, buffer_factor=3.5)
        for span in seq.read(MACRO):
            received.append(np.array(span.data.as_numpy(), copy=True))
    t.join()
    assert len(received) == NSPAN
    for k, arr in enumerate(received):
        np.testing.assert_array_equal(
            arr, np.arange(MACRO * 4).reshape(MACRO, 4) + 1000 * k)


def test_macro_commit_barrier_k2():
    """With two outstanding multi-gulp spans committed out of order,
    the in-order barrier publishes nothing until the FIRST commits —
    then both land atomically."""
    ring = Ring(space='system')
    hdr = _hdr()                   # frame = 4 x f32 = 16 B
    MACRO = 16
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=MACRO,
                               buf_nframe=4 * MACRO) as seq:
            s1 = seq.reserve(MACRO)
            s2 = seq.reserve(MACRO)
            s2.data.as_numpy()[...] = 2.0
            s2.commit(MACRO)
            s2.close()
            assert ring.occupancy()['head'] == 0, \
                "head advanced past an uncommitted earlier macro span"
            s1.data.as_numpy()[...] = 1.0
            s1.commit(MACRO)
            s1.close()
            assert ring.occupancy()['head'] == 2 * MACRO * 16
    vals = []
    for seq in ring.read():
        for span in seq.read(MACRO):
            vals.append(float(span.data.as_numpy().ravel()[0]))
    assert vals == [1.0, 2.0]


def test_macro_blocked_acquire_partial_on_eod():
    """A reader blocked acquiring a full macro span wakes at sequence
    end with the partial remainder (the macro-gulp partial-batch
    flush depends on this in both cores)."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(2,))
    MACRO = 16
    started = threading.Event()

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=MACRO,
                                   buf_nframe=4 * MACRO) as seq:
                with seq.reserve(MACRO) as span:
                    span.data.as_numpy()[...] = 1.0
                    span.commit(MACRO)
                started.wait(10)
                # 1.5 macro spans total: the final half-span is the
                # partial batch the blocked reader must receive
                with seq.reserve(MACRO // 2) as span:
                    span.data.as_numpy()[...] = 2.0
                    span.commit(MACRO // 2)

    t = threading.Thread(target=writer)
    t.start()
    sizes = []
    for seq in ring.read(guarantee=True):
        seq.resize(gulp_nframe=MACRO, buffer_factor=4)
        for span in seq.read(MACRO):
            sizes.append(span.nframe)
            started.set()
    t.join()
    assert sizes == [MACRO, MACRO // 2]


def test_macro_blocked_reserve_wakes_on_poison():
    """A writer blocked reserving a MACRO span against a pinned
    guarantee wakes with RingPoisonedError when the ring dies (EOD
    alone cannot wake a writer; poison must)."""
    from bifrost_tpu.ring import RingPoisonedError
    ring = Ring(space='system')
    hdr = _hdr()
    MACRO = 16
    caught = []
    reader_ready = threading.Event()

    def writer():
        try:
            with ring.begin_writing() as wr:
                with wr.begin_sequence(hdr, gulp_nframe=MACRO,
                                       buf_nframe=2 * MACRO) as seq:
                    with seq.reserve(MACRO) as span:
                        span.data.as_numpy()[...] = 0.0
                        span.commit(MACRO)
                    assert reader_ready.wait(10)
                    for k in range(1, 50):
                        with seq.reserve(MACRO) as span:
                            span.data.as_numpy()[...] = float(k)
                            span.commit(MACRO)
        except RingPoisonedError as exc:
            caught.append(exc)

    wt = threading.Thread(target=writer, daemon=True)
    wt.start()
    with ring.open_earliest_sequence(guarantee=True) as rseq:
        span = rseq.acquire(0, MACRO)   # pins the guarantee at frame 0
        reader_ready.set()
        import time
        time.sleep(0.3)
        assert wt.is_alive(), \
            "writer should be blocked reserving the macro span"
        ring.poison(RuntimeError("consumer died"))
        wt.join(5)
        alive = wt.is_alive()
        span.release()
    assert not alive, "poison did not wake the blocked macro reserve"
    assert caught and 'consumer died' in str(caught[0])


def test_macro_overlap_history_ghost_wrap():
    """K>1 macro-gulp OVERLAPPED reads (the halo-carry span shape:
    K strides plus one overlap history at the head, pipeline.py) must
    return history frames byte-identical to the previous span's tail
    at every stride — including spans whose head history wraps through
    the ghost region."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(4,))
    NSPAN, STRIDE, OV = 5, 16, 4   # K=2 gulps of 8, 4-frame halo
    GULP = STRIDE + OV
    reader_attached = threading.Event()

    def writer():
        with ring.begin_writing() as wr:
            # buf 56 frames: strides land at 48 -> 64 across the
            # nominal end, so at least one overlapped acquire reads
            # its history through the ghost mirror
            with wr.begin_sequence(hdr, gulp_nframe=STRIDE,
                                   buf_nframe=56) as seq:
                for k in range(NSPAN):
                    if k == 1:
                        assert reader_attached.wait(30)
                    with seq.reserve(STRIDE) as span:
                        span.data.as_numpy()[...] = \
                            np.arange(STRIDE * 4).reshape(STRIDE, 4) \
                            + 1000 * k
                        span.commit(STRIDE)

    ref = np.concatenate(
        [np.arange(STRIDE * 4).reshape(STRIDE, 4) + 1000 * k
         for k in range(NSPAN)])
    t = threading.Thread(target=writer)
    t.start()
    received = []
    for seq in ring.read(guarantee=True):
        reader_attached.set()
        seq.resize(gulp_nframe=GULP, buffer_factor=3)
        for span in seq.read(GULP, STRIDE):
            assert span.nframe_skipped == 0
            received.append((span.frame_offset,
                             np.array(span.data.as_numpy(),
                                      copy=True)))
    t.join()
    # 4 full overlapped spans + the EOD partial (final stride has no
    # successor to lend it a halo)
    assert [n.shape[0] for _, n in received] == \
        [GULP] * (NSPAN - 1) + [STRIDE]
    for i, (off, arr) in enumerate(received):
        assert off == i * STRIDE
        np.testing.assert_array_equal(arr, ref[off:off + arr.shape[0]])
        if i > 0:
            # the halo IS the previous span's tail, byte for byte
            np.testing.assert_array_equal(arr[:OV],
                                          received[i - 1][1][-OV:])


def test_macro_overlap_history_eod_partial():
    """An overlapped reader blocked on a full K-gulp span wakes at
    sequence end with the partial remainder — and the partial's halo
    history frames are byte-identical to the previous span's tail
    (the macro-gulp EOD partial-batch flush depends on this)."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(2,))
    STRIDE, OV = 16, 4
    GULP = STRIDE + OV
    got_first = threading.Event()

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=STRIDE,
                                   buf_nframe=4 * STRIDE) as seq:
                with seq.reserve(STRIDE) as span:
                    span.data.as_numpy()[...] = \
                        np.arange(STRIDE * 2).reshape(STRIDE, 2)
                    span.commit(STRIDE)
                with seq.reserve(STRIDE // 2) as span:
                    span.data.as_numpy()[...] = \
                        np.arange((STRIDE // 2) * 2).reshape(
                            STRIDE // 2, 2) + 5000
                    span.commit(STRIDE // 2)
                # reader now blocks wanting [16, 36); ending the
                # sequence must wake it with the partial [16, 24)
                assert got_first.wait(30)

    t = threading.Thread(target=writer)
    t.start()
    received = []
    for seq in ring.read(guarantee=True):
        seq.resize(gulp_nframe=GULP, buffer_factor=3)
        for span in seq.read(GULP, STRIDE):
            assert span.nframe_skipped == 0
            received.append(np.array(span.data.as_numpy(), copy=True))
            got_first.set()
    t.join()
    # the writer produced 24 frames: span0 covers [0, 20), the EOD
    # partial covers [16, 24) — OV frames of history plus the 4 new
    assert [r.shape[0] for r in received] == [GULP, STRIDE // 2]
    # the EOD partial still carries its OV-frame history at the head
    np.testing.assert_array_equal(received[1][:OV], received[0][-OV:])


def test_overlap_hold_ahead_grows_small_ring():
    """Hold-ahead regression (the overlapped-reader guarantee race):
    an overlapped reader keeps span N open while acquiring span N+1,
    so the writer can never reclaim the shared history frames — and
    when the ring is too small to also absorb the writer's reserve
    granularity, ReadSequence.read must GROW it (request_resize)
    instead of deadlocking.  Every span arrives unskipped and
    byte-exact even with the writer racing ahead."""
    ring = Ring(space='system')
    hdr = _hdr(frame_shape=(4,))
    NSPAN, STRIDE, OV = 30, 8, 4
    GULP = STRIDE + OV
    reader_attached = threading.Event()
    received = []
    errors = []

    def writer():
        with ring.begin_writing() as wr:
            # 2 strides of buffering: far below the hold-ahead
            # capacity bound (gulp + stride + ghost)
            with wr.begin_sequence(hdr, gulp_nframe=STRIDE,
                                   buf_nframe=2 * STRIDE) as seq:
                for k in range(NSPAN):
                    if k == 1:
                        assert reader_attached.wait(30)
                    with seq.reserve(STRIDE) as span:
                        span.data.as_numpy()[...] = \
                            np.arange(STRIDE * 4).reshape(STRIDE, 4) \
                            + 1000 * k
                        span.commit(STRIDE)

    def reader():
        try:
            for seq in ring.read(guarantee=True):
                reader_attached.set()
                seq.resize(gulp_nframe=GULP, buffer_factor=2)
                for span in seq.read(GULP, STRIDE):
                    assert span.nframe_skipped == 0
                    received.append(
                        np.array(span.data.as_numpy(), copy=True))
        except Exception as exc:          # pragma: no cover
            errors.append(exc)

    wt = threading.Thread(target=writer, daemon=True)
    rt = threading.Thread(target=reader, daemon=True)
    wt.start()
    rt.start()
    wt.join(60)
    rt.join(60)
    assert not wt.is_alive() and not rt.is_alive(), \
        "overlapped read deadlocked on an undersized ring"
    assert not errors
    ref = np.concatenate(
        [np.arange(STRIDE * 4).reshape(STRIDE, 4) + 1000 * k
         for k in range(NSPAN)])
    assert [r.shape[0] for r in received] == \
        [GULP] * (NSPAN - 1) + [STRIDE]
    off = 0
    for arr in received:
        np.testing.assert_array_equal(arr, ref[off:off + arr.shape[0]])
        off += STRIDE
    # the generator grew the ring to the deadlock-free bound
    fb = 4 * 4
    assert ring.total_span >= (GULP + STRIDE) * fb + ring.ghost_span


def test_device_ring_take_tiling_macro_donation():
    """Macro-span donation proof: several exclusively-owned per-gulp
    chunks exactly tiling a macro span are claimed as a list; a
    foreign (unowned) chunk in the run blocks the claim (Python device
    core only — device rings never use the native core)."""
    import jax.numpy as jnp
    ring = Ring(space='tpu')
    hdr = _hdr(frame_shape=(4,))
    frame_nbyte = 16
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=8,
                               buf_nframe=64) as seq:
            for k in range(4):
                with seq.reserve(8) as span:
                    span.set(jnp.full((8, 4), float(k)), owned=True)
                    span.commit(8)
            with ring.open_earliest_sequence(guarantee=True) as rseq:
                with rseq.acquire(0, 16) as span:
                    parts = span.take_data(allow_parts=True)
                    assert isinstance(parts, list) and len(parts) == 2
                    assert float(np.asarray(parts[0])[0, 0]) == 0.0
                    assert float(np.asarray(parts[1])[0, 0]) == 1.0
                # the claimed range is consumed: re-reading it now
                # zero-fills (single-consumer contract)
                with rseq.acquire(16, 16) as span2:
                    # remaining chunks still intact
                    assert float(np.asarray(
                        span2.data)[0, 0]) == 2.0
    # unowned chunk blocks the tiling claim
    ring2 = Ring(space='tpu')
    with ring2.begin_writing() as wr:
        with wr.begin_sequence(_hdr(frame_shape=(4,)), gulp_nframe=8,
                               buf_nframe=64) as seq:
            with seq.reserve(8) as span:
                span.set(jnp.zeros((8, 4)), owned=True)
                span.commit(8)
            with seq.reserve(8) as span:
                span.set(jnp.ones((8, 4)), owned=False)
                span.commit(8)
            with ring2.open_earliest_sequence(guarantee=True) as rseq:
                with rseq.acquire(0, 16) as span:
                    assert span.take_data(allow_parts=True) is None
                    # the fallback path still reads the data
                    assert span.data.shape[0] == 16


def test_native_library_selftest():
    """The in-library C++ self-test (reference analogue: bfTestSuite,
    src/testsuite.cpp) passes through the ABI."""
    from bifrost_tpu import native
    if not native.available():
        pytest.skip('native library unavailable')
    assert native.load().bft_selftest() == 0


def test_multi_open_spans_pin_guarantee():
    """A guaranteed reader holding SEVERAL open spans (the bridge's
    credit window keeps spans un-released until the peer acks their
    bytes) pins the guarantee at the OLDEST open span: the writer must
    not overwrite a held span's bytes, in either core (the reference
    refcount-locks the tail per span, ring_impl.hpp:110-141)."""
    ring = Ring(space='system')
    hdr = _hdr()
    wrote = threading.Event()
    reader_ready = threading.Event()
    done = threading.Event()

    def writer():
        with ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8,
                                   buf_nframe=32) as seq:
                for k in range(12):
                    with seq.reserve(8) as span:
                        span.data.as_numpy()[...] = float(k)
                        span.commit(8)
                    if k == 3:
                        # buffer full; hold until the reader's spans
                        # are pinned so the lap attempt races nothing
                        wrote.set()
                        assert reader_ready.wait(10)
        done.set()

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    with ring.open_earliest_sequence(guarantee=True) as rseq:
        assert wrote.wait(10)
        spans = [rseq.acquire(k * 8, 8) for k in range(3)]
        reader_ready.set()
        # the writer wants to lap the 32-frame ring; the three held
        # spans (frames 0..24) must pin the tail at frame 0
        assert not done.wait(0.4), \
            "writer lapped the ring over held read spans"
        for k, span in enumerate(spans):
            np.testing.assert_array_equal(
                np.asarray(span.data.as_numpy()),
                np.full((8, 4), float(k), np.float32))
        # releasing the spans returns write credit
        for span in spans:
            span.release()
    # (closing the read sequence drops the remaining guarantee so the
    # writer can lap freely and finish)
    assert done.wait(10), "writer still blocked after release"
    t.join(5)


def test_open_span_survives_later_acquires():
    """Acquiring a NEWER span must not unprotect an older still-open
    one (the historical watermark semantics did)."""
    ring = Ring(space='system')
    hdr = _hdr()

    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=4,
                               buf_nframe=16) as seq:
            for k in range(4):
                with seq.reserve(4) as span:
                    span.data.as_numpy()[...] = float(k)
                    span.commit(4)
            with ring.open_earliest_sequence(guarantee=True) as rseq:
                first = rseq.acquire(0, 4)
                later = rseq.acquire(8, 4)
                # the ring is full (16/16 frames): another gulp would
                # need to reclaim frames 0..4, which the held FIRST
                # span forbids even though a LATER acquire moved past
                # it (the old watermark semantics allowed this)
                from bifrost_tpu.ring import WouldBlock
                with pytest.raises(WouldBlock):
                    seq.reserve(4, nonblocking=True)
                first.release()
                # with only the later span (frames 8..12) open, one
                # gulp of tail reclaim is legal again
                with seq.reserve(4, nonblocking=True) as span:
                    span.commit(0)
                later.release()


def test_out_of_order_span_release_frees_writer():
    """Releasing held spans OUT of acquisition order (the bridge's
    striped acks can complete newest-first) must advance the guarantee
    to the released high-water mark once nothing is open — parking it
    at the last-released begin deadlocks the writer."""
    ring = Ring(space='system')
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=4,
                               buf_nframe=16) as seq:
            for k in range(4):
                with seq.reserve(4) as span:
                    span.data.as_numpy()[...] = float(k)
                    span.commit(4)
            with ring.open_earliest_sequence(guarantee=True) as rseq:
                first = rseq.acquire(0, 4)
                later = rseq.acquire(8, 4)
                later.release()          # newest first
                first.release()
                # both released: frames 0..12 are reclaimable — two
                # more gulps must fit without blocking
                with seq.reserve(4, nonblocking=True) as span:
                    span.commit(4)
                with seq.reserve(4, nonblocking=True) as span:
                    span.commit(0)


# ---------------------------------------------------------------------------
# deferred (non-blocking) resize — the auto-tuner's retune protocol
# (docs/autotune.md): a resize requested while spans are open must
# DEFER until the oldest open span releases instead of re-layouting
# storage under a live span's zero-copy view
# ---------------------------------------------------------------------------

def test_deferred_resize_defers_under_write_span():
    ring = Ring(space='system')
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=8,
                               buf_nframe=24) as seq:
            before = ring.total_span
            with seq.reserve(8) as span:
                view = span.data.as_numpy()
                view[...] = 7.0
                assert not ring.request_resize(1, before * 2)
                assert ring.resize_pending
                # the live view must still be the OLD storage: writes
                # through it land in the committed data below
                view[...] = 9.0
                span.commit(8)
            # oldest (only) open span released: the growth applies
            assert not ring.resize_pending
            assert ring.total_span >= before * 2
    with ring.open_earliest_sequence(guarantee=True) as rseq:
        with rseq.acquire(0, 8) as span:
            np.testing.assert_array_equal(span.data.as_numpy(), 9.0)


def test_deferred_resize_defers_under_read_span():
    ring = Ring(space='system')
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=4,
                               buf_nframe=16) as seq:
            for k in range(3):
                with seq.reserve(4) as span:
                    span.data.as_numpy()[...] = float(k)
                    span.commit(4)
            before = ring.total_span
            with ring.open_earliest_sequence(guarantee=True) as rseq:
                first = rseq.acquire(0, 4)
                assert not ring.request_resize(1, before * 2)
                assert ring.resize_pending
                np.testing.assert_array_equal(
                    first.data.as_numpy(), 0.0)
                first.release()
            assert not ring.resize_pending
            assert ring.total_span >= before * 2
            # data written before the re-layout survives it
            with ring.open_earliest_sequence(guarantee=True) as rseq:
                with rseq.acquire(8, 4) as span:
                    np.testing.assert_array_equal(
                        span.data.as_numpy(), 2.0)


def test_deferred_resize_applies_immediately_when_quiescent():
    ring = Ring(space='system')
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=8,
                               buf_nframe=24) as seq:
            with seq.reserve(8) as span:
                span.data.as_numpy()[...] = 1.0
                span.commit(8)
            before = ring.total_span
            assert ring.request_resize(1, before * 2)
            assert not ring.resize_pending
            assert ring.total_span >= before * 2
            # MAX semantics: a smaller request is a no-op, not a shrink
            assert ring.request_resize(1, before)
            assert ring.total_span >= before * 2


def test_deferred_resize_multiple_open_spans_wait_for_all():
    """The growth lands only when NO span remains open — releasing the
    oldest while a newer span is still held must keep deferring (the
    newer span's view is just as live)."""
    ring = Ring(space='system')
    hdr = _hdr()
    with ring.begin_writing() as wr:
        with wr.begin_sequence(hdr, gulp_nframe=4,
                               buf_nframe=16) as seq:
            for k in range(4):
                with seq.reserve(4) as span:
                    span.data.as_numpy()[...] = float(k)
                    span.commit(4)
            before = ring.total_span
            with ring.open_earliest_sequence(guarantee=True) as rseq:
                first = rseq.acquire(0, 4)
                second = rseq.acquire(4, 4)
                assert not ring.request_resize(1, before * 2)
                first.release()
                assert ring.resize_pending       # second still open
                second.release()
                assert not ring.resize_pending
            assert ring.total_span >= before * 2
