"""FDMT tests (reference analogue: test/test_fdmt.py — slow-reference
oracle comparison, plus physical impulse tests)."""

import numpy as np
import pytest

from bifrost_tpu.ops.fdmt import Fdmt, fdmt_numpy, _cff


def test_jax_matches_numpy_oracle():
    nchan, max_delay, T = 16, 12, 64
    f0, df = 100.0, 1.0
    rng = np.random.RandomState(0)
    x = rng.rand(nchan, T).astype(np.float32)
    plan = Fdmt().init(nchan, max_delay, f0, df)
    out_jax = np.asarray(plan.execute(x))
    out_np = plan._core_numpy(x.astype(np.float64))
    np.testing.assert_allclose(out_jax, out_np, rtol=1e-5, atol=1e-4)


def test_non_power_of_two_channels():
    nchan, max_delay, T = 12, 8, 48
    rng = np.random.RandomState(1)
    x = rng.rand(nchan, T).astype(np.float32)
    plan = Fdmt().init(nchan, max_delay, 1400.0, 0.5)
    out_jax = np.asarray(plan.execute(x))
    out_np = plan._core_numpy(x.astype(np.float64))
    np.testing.assert_allclose(out_jax, out_np, rtol=1e-5, atol=1e-4)


def test_zero_dm_row_is_channel_sum():
    """Row 0 (no dispersion) must be the plain channel sum."""
    nchan, max_delay, T = 8, 6, 32
    rng = np.random.RandomState(2)
    x = rng.rand(nchan, T).astype(np.float32)
    plan = Fdmt().init(nchan, max_delay, 100.0, 1.0)
    out = np.asarray(plan.execute(x))
    np.testing.assert_allclose(out[0], x.sum(axis=0), rtol=1e-5)


def test_dispersed_impulse_recovered():
    """A quadratically-dispersed impulse concentrates at its delay row."""
    nchan, max_delay, T = 32, 24, 128
    f0, df = 100.0, 1.0
    d_true = 16
    x = np.zeros((nchan, T), np.float32)
    band = _cff(f0, f0 + nchan * df, -2.0)
    t0 = 20
    for c in range(nchan):
        # delay of channel c relative to the bottom of the band
        delay = d_true * _cff(f0, f0 + c * df, -2.0) / band
        ti = t0 + int(round(delay))
        x[c, ti] = 1.0
    plan = Fdmt().init(nchan, max_delay, f0, df)
    out = np.asarray(plan.execute(x))
    # the peak over all (dm row, time) should be at (~d_true, t0) and
    # recover most of the nchan units of power
    row, t = np.unravel_index(np.argmax(out), out.shape)
    assert abs(row - d_true) <= 1
    assert abs(t - t0) <= 1   # tree delay rounding can shift by one
    assert out[row, t] >= 0.8 * nchan


def test_batched_execute():
    nchan, max_delay, T = 8, 6, 32
    rng = np.random.RandomState(3)
    x = rng.rand(3, nchan, T).astype(np.float32)
    plan = Fdmt().init(nchan, max_delay, 100.0, 1.0)
    out = np.asarray(plan.execute(x))
    assert out.shape == (3, max_delay, T)
    one = np.asarray(plan.execute(x[1]))
    np.testing.assert_allclose(out[1], one, rtol=1e-5)


def test_negative_delays():
    nchan, max_delay, T = 8, 6, 32
    rng = np.random.RandomState(4)
    x = rng.rand(nchan, T).astype(np.float32)
    plan = Fdmt().init(nchan, max_delay, 100.0, 1.0)
    out_jax = np.asarray(plan.execute(x, negative_delays=True))
    out_np = plan._core_numpy(x.astype(np.float64), negative_delays=True)
    np.testing.assert_allclose(out_jax, out_np, rtol=1e-5, atol=1e-4)


def test_fdmt_pallas_core_interpret_matches_oracle():
    """The Pallas FDMT step pipeline (default on TPU hardware) validated
    against the numpy oracle via interpret mode on CPU."""
    import jax
    import jax.numpy as jnp
    from bifrost_tpu.ops.fdmt import Fdmt
    rng = np.random.RandomState(3)
    for (nchan, md, T, neg) in [(16, 12, 100, False), (8, 5, 64, True),
                                (13, 7, 130, False)]:
        x = rng.randn(nchan, T).astype(np.float32)
        plan = Fdmt().init(nchan, md, 1400.0, 0.1)
        core = plan._core_pallas(neg, interpret=True)
        out = np.asarray(jax.jit(core)(jnp.asarray(x)))
        ref = plan._core_numpy(x.astype(np.float64), neg)
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 1e-5, (nchan, md, T, neg, err)


def test_fdmt_pallas_smem_fallback_step_interpret(monkeypatch):
    """Steps whose delay tables exceed the SMEM budget run the XLA
    gather on the padded state; the mix must stay exact."""
    import jax
    import jax.numpy as jnp
    from bifrost_tpu.ops import fdmt as fdmt_mod
    rng = np.random.RandomState(4)
    x = rng.randn(16, 100).astype(np.float32)
    plan = fdmt_mod.Fdmt().init(16, 12, 1400.0, 0.1)
    ref = plan._core_numpy(x.astype(np.float64), False)
    # force every step through the XLA fallback
    monkeypatch.setattr(fdmt_mod, 'SMEM_TABLE_BUDGET', 0)
    out = np.asarray(jax.jit(plan._core_pallas(False, interpret=True))(
        jnp.asarray(x)))
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert err < 1e-5
    # and a half-and-half mix (first big step XLA, later small pallas)
    monkeypatch.setattr(fdmt_mod, 'SMEM_TABLE_BUDGET', 200)
    out = np.asarray(jax.jit(plan._core_pallas(False, interpret=True))(
        jnp.asarray(x)))
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
    assert err < 1e-5


def test_rolls_core_matches_oracle():
    """The static-roll core (BF_FDMT_IMPL=rolls) is exact against the
    numpy oracle across shapes, tails, and both delay signs."""
    import jax
    rng = np.random.RandomState(5)
    for (nchan, md, T, neg) in [(64, 37, 300, False), (7, 5, 64, False),
                                (33, 12, 100, True), (1, 4, 32, False)]:
        x = rng.randn(nchan, T).astype(np.float32)
        plan = Fdmt().init(nchan, md, 1400.0, -0.1)
        want = plan._core_numpy(x, negative_delays=neg)
        got = np.asarray(jax.jit(plan._core_jax_rolls(neg))(x))
        rel = np.max(np.abs(got - want)) / (np.max(np.abs(want)) + 1e-30)
        assert rel < 1e-4, (nchan, md, T, neg, rel)


def test_rolls_core_selected_by_env(monkeypatch):
    monkeypatch.setenv('BF_FDMT_IMPL', 'rolls')
    plan = Fdmt().init(32, 16, 1400.0, -0.1)
    core = plan._pick_core(False)
    assert core.__qualname__.startswith(
        Fdmt._core_jax_rolls.__qualname__)


def test_probe_selects_measured_winner(monkeypatch, tmp_path):
    """BF_FDMT_PROBE=1 oracle-gates and measures every candidate core
    at the actual shape through the shared mprobe harness (family
    'fdmt') and picks + caches the fastest (VERDICT r3 item 3: core
    choice is measured per (plan, backend), not asserted)."""
    from bifrost_tpu.ops import mprobe
    monkeypatch.setenv('BF_FDMT_PROBE', '1')
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    monkeypatch.setattr(mprobe, '_cache', {})
    plan = Fdmt().init(16, 8, 1400.0, -0.1)
    core = plan._pick_core(False, shape=(16, 128))
    assert plan.chosen_core in ('xla', 'rolls', 'pallas')
    assert plan.core_probe_ms
    assert plan.chosen_core == min(plan.core_probe_ms,
                                   key=plan.core_probe_ms.get)
    # the probed winner is a working core
    rng = np.random.RandomState(0)
    x = rng.rand(16, 128).astype(np.float32)
    got = np.asarray(core(x))
    want = plan._core_numpy(x.astype(np.float64))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)
    # disk cache written under the family file (a non-decisive or
    # errored race legitimately skips the write); a fresh plan with a
    # fresh in-process cache reads the winner back without
    # re-measuring when it was persisted
    monkeypatch.setattr(mprobe, '_cache', {})
    plan2 = Fdmt().init(16, 8, 1400.0, -0.1)
    plan2._pick_core(False, shape=(16, 128))
    if (tmp_path / 'fdmt.json').exists():
        assert plan2.chosen_core == plan.chosen_core
    else:
        assert plan2.chosen_core in ('xla', 'rolls', 'pallas')


def test_probe_off_keeps_heuristic(monkeypatch):
    monkeypatch.setenv('BF_FDMT_PROBE', '0')
    plan = Fdmt().init(16, 8, 1400.0, -0.1)
    plan._pick_core(False, shape=(16, 128))
    assert plan.chosen_core == 'rolls'
    assert plan.core_probe_ms is None
