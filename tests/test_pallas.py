"""Pallas kernel tests.  Where Pallas does not compile natively (e.g.
the CPU test backend) the kernels run in interpret mode — same program,
emulated execution — so the math is verified everywhere and only the
Mosaic lowering is left to the on-hardware smoke gate
(bench.py --pallas-smoke)."""

import numpy as np
import pytest  # noqa: F401

from bifrost_tpu.ops import pallas_kernels as pk

# native where available, interpret elsewhere — never skip the math
INTERPRET = not pk.available()


def test_stokes_detect_matches_jnp():
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    T, F = 16, 256
    xr, xi, yr, yi = (rng.randn(T, F).astype(np.float32)
                      for _ in range(4))
    out = np.asarray(pk.stokes_detect(jnp.asarray(xr), jnp.asarray(xi),
                                      jnp.asarray(yr), jnp.asarray(yi),
                                      interpret=INTERPRET))
    x = xr + 1j * xi
    y = yr + 1j * yi
    xy = x * np.conj(y)
    expect = np.stack([np.abs(x) ** 2 + np.abs(y) ** 2,
                       np.abs(x) ** 2 - np.abs(y) ** 2,
                       2 * xy.real, -2 * xy.imag], axis=1)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-4)


def test_xcorr_herm_exact_interpret():
    """Fused Hermitian int8 correlation kernel vs the integer oracle
    at a lane-aligned shape (interpret mode; the on-chip compile is
    gated by bench.py --pallas-smoke)."""
    import jax.numpy as jnp
    rng = np.random.RandomState(1)
    T, F, n = 16, 3, 256
    re = rng.randint(-64, 64, (T, F, n)).astype(np.int8)
    im = rng.randint(-64, 64, (T, F, n)).astype(np.int8)
    got = np.asarray(pk.xcorr_herm(jnp.asarray(re), jnp.asarray(im),
                                   interpret=True))
    x = re.astype(np.float64) + 1j * im
    want = np.einsum('tfi,tfj->fij', x, np.conj(x))
    np.testing.assert_array_equal(got, want.astype(np.complex64))
