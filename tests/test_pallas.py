"""Pallas kernel tests (skipped where Pallas is unavailable, e.g. some
CPU backends)."""

import numpy as np
import pytest

from bifrost_tpu.ops import pallas_kernels as pk


pytestmark = pytest.mark.skipif(not pk.available(),
                                reason="Pallas unavailable on backend")


def test_stokes_detect_matches_jnp():
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    T, F = 16, 256
    xr, xi, yr, yi = (rng.randn(T, F).astype(np.float32)
                      for _ in range(4))
    out = np.asarray(pk.stokes_detect(jnp.asarray(xr), jnp.asarray(xi),
                                      jnp.asarray(yr), jnp.asarray(yi)))
    x = xr + 1j * xi
    y = yr + 1j * yi
    xy = x * np.conj(y)
    expect = np.stack([np.abs(x) ** 2 + np.abs(y) ** 2,
                       np.abs(x) ** 2 - np.abs(y) ** 2,
                       2 * xy.real, -2 * xy.imag], axis=1)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-4)
