"""The degraded bench artifact's FINAL stdout line must stay within
what the driver's tail-capture parses (VERDICT r5 items 3/5:
`BENCH_r05.json parsed: null` — the one-line degraded JSON inlined the
whole probe history + watch-log tail).  bench.compact_degraded_line
caps the line at DEGRADED_LINE_LIMIT bytes with the detail in a side
file; these tests round-trip its output through the driver's parse
path (bench._last_json_line, which mirrors _run_isolated)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

import bench  # noqa: E402


def _fat_history(n=120):
    """A probe history big enough to defeat any naive inlining."""
    return [{'t': '2026-08-0%dT00:00:00Z' % (i % 9 + 1),
             'rc': 'timeout' if i % 3 else 1,
             'error': 'tunnel reset mid-handshake while probing the '
                      'accelerator backend attempt %d ' % i + 'x' * 200}
            for i in range(n)]


@pytest.fixture
def no_subprocesses(monkeypatch):
    """degraded_result shells out for host-only configs; stub it."""
    monkeypatch.setattr(
        bench, '_run_isolated',
        lambda argv, timeout=900, env_extra=None: {
            'config': 'stub config for %s' % argv[-1],
            'value': 1.23, 'unit': 'stub/s',
            'roofline': {'bound': 'stub ' * 40}})


def test_degraded_line_fits_and_roundtrips(tmp_path, no_subprocesses):
    result = bench.degraded_result(_fat_history())
    # simulate further bloat the real artifact carries
    result['watch_log_tail'] = ['probe[%d] rc=1 %s' % (i, 'y' * 160)
                                for i in range(12)]
    detail = str(tmp_path / 'detail.json')
    line_obj = bench.compact_degraded_line(result, detail_name=detail)
    line = json.dumps(line_obj)
    assert len(line) <= bench.DEGRADED_LINE_LIMIT
    # the driver's parse path accepts it
    parsed = bench._last_json_line('preamble noise\n' + line + '\n')
    assert parsed is not None
    assert parsed['metric'] == result['metric']
    assert 'error' in parsed
    assert parsed['value'] == 0.0 and parsed['vs_baseline'] == 0.0
    # history is truncated to counts + last entry, not inlined
    assert parsed['probe']['attempts'] == 120
    assert 'rc_counts' in parsed['probe']
    assert len(json.dumps(parsed.get('probe', {}))) < 1000
    # the full detail survives in the side file the line points to
    with open(detail) as f:
        full = json.load(f)
    assert len(full['probe_history']) == 120
    assert 'watch_log_tail' in full


def test_degraded_line_survives_pathological_error(tmp_path,
                                                   no_subprocesses):
    result = bench.degraded_result(_fat_history(400),
                                   reason='z' * 5000)
    line_obj = bench.compact_degraded_line(
        result, detail_name=str(tmp_path / 'd.json'))
    line = json.dumps(line_obj)
    assert len(line) <= bench.DEGRADED_LINE_LIMIT
    assert bench._last_json_line(line) is not None


def test_driver_parse_rejects_oversize_line():
    """The guard the compaction exists for: an over-limit line parses
    to None (the `parsed: null` failure mode, now caught in CI)."""
    fat = json.dumps({'metric': 'x', 'blob': 'y' * (2 * 4096)})
    assert bench._last_json_line(fat) is None


def test_last_json_line_skips_preamble_and_picks_last():
    text = '\n'.join([
        json.dumps({'chip_ceilings': {'hbm_gbs': 100.0}}),
        'INFO: some log line',
        json.dumps({'metric': 'old'}),
        json.dumps({'metric': 'new', 'value': 1}),
    ])
    parsed = bench._last_json_line(text)
    assert parsed == {'metric': 'new', 'value': 1}
