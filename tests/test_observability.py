"""Observability-layer tests (docs/observability.md): gulp-span
tracing with Chrome trace export, log2 latency histograms, the unified
snapshot / Prometheus export surface, and the watchdog flight
recorder — all on the CPU backend, driven where useful by the
deterministic fault harness (bifrost_tpu.testing.faults)."""

import contextlib
import io
import json
import os
import re
import subprocess
import sys
import threading

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu import proclog, trace
from bifrost_tpu.supervision import PipelineStallError
from bifrost_tpu.telemetry import (counters, exporter, histograms,
                                   spans)
from bifrost_tpu.testing import faults
from tests.util import NumpySourceBlock, GatherSink, simple_header

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, 'tools')


@pytest.fixture(autouse=True)
def clean_state():
    faults.clear()
    counters.reset()
    histograms.reset()
    spans.reset()
    yield
    faults.clear()
    counters.reset()
    histograms.reset()
    spans.reset()


def _hdr():
    return simple_header([-1, 3], 'f32')


def _gulps(n=5):
    return [np.full((4, 3), float(k), dtype=np.float32)
            for k in range(n)]


class Ident(bf.TransformBlock):
    """Pass-through host transform with a distinctive name."""

    def on_sequence(self, iseq):
        return dict(iseq.header)

    def on_data(self, ispan, ospan):
        ospan.data.as_numpy()[...] = ispan.data.as_numpy()


def _run_simple_pipeline(ngulp=5, device_hop=False, **pipe_kwargs):
    with bf.Pipeline(**pipe_kwargs) as p:
        src = NumpySourceBlock(_gulps(ngulp), _hdr(), gulp_nframe=4)
        if device_hop:
            up = bf.blocks.copy(src, space='tpu')
            down = bf.blocks.copy(up, space='system')
            sink = GatherSink(down)
        else:
            blk = Ident(src)
            sink = GatherSink(blk)
        p.run()
    return p, sink


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------

def test_histogram_basic_stats():
    h = histograms.Histogram('t.basic')
    for v in (0.001, 0.002, 0.004, 0.008, 0.016):
        h.record(v)
    snap = h.snapshot()
    assert snap['count'] == 5
    assert snap['sum'] == pytest.approx(0.031)
    assert snap['min'] == pytest.approx(0.001)
    assert snap['max'] == pytest.approx(0.016)
    # five distinct powers of two -> five distinct buckets
    assert len(snap['buckets']) == 5
    assert sum(snap['buckets'].values()) == 5


def test_histogram_percentiles_monotonic():
    rng = np.random.RandomState(7)
    h = histograms.get_or_create('t.mono')
    for v in np.exp(rng.randn(500) * 2.0 - 6.0):
        h.record(float(v))
    last = 0.0
    for p in range(1, 101):
        cur = h.percentile(p)
        assert cur >= last, 'p%d < p%d' % (p, p - 1)
        last = cur
    snap = h.snapshot()
    assert snap['p50'] <= snap['p90'] <= snap['p99']
    # estimates stay inside the observed range
    assert snap['min'] <= snap['p50'] <= snap['max']
    assert snap['min'] <= snap['p99'] <= snap['max']


def test_histogram_edge_values():
    h = histograms.Histogram('t.edge')
    assert h.percentile(99) == 0.0        # empty
    h.record(0.0)
    h.record(-1.0)                        # clamps to 0
    h.record(float('nan'))                # clamps to 0
    h.record(1e30)                        # clamps to top bucket
    snap = h.snapshot()
    assert snap['count'] == 4
    assert snap['min'] == 0.0 and snap['max'] == 1e30


def test_histogram_registry_observe_and_reset():
    histograms.observe('t.reg', 0.5)
    histograms.observe('t.reg', 0.5)
    assert histograms.get('t.reg').count == 2
    assert 't.reg' in histograms.snapshot()
    histograms.reset()
    assert histograms.get('t.reg') is None


# ---------------------------------------------------------------------------
# gulp-span tracing / Chrome trace export
# ---------------------------------------------------------------------------

def test_trace_file_has_complete_spans_per_gulp(monkeypatch, tmp_path):
    """The acceptance-criterion run: BF_TRACE_FILE set, a CPU pipeline
    with a device hop produces a valid Chrome trace with block-compute,
    ring-wait, and transfer spans, one complete compute span per
    gulp with (sequence, gulp) identity."""
    path = tmp_path / 'trace.json'
    monkeypatch.setenv('BF_TRACE_FILE', str(path))
    trace.reset()                      # satellite: re-read env
    ngulp = 5
    _run_simple_pipeline(ngulp=ngulp, device_hop=True)

    data = json.loads(path.read_text())
    evs = [e for e in data['traceEvents'] if e.get('ph') == 'X']
    assert evs, 'no complete events exported'
    for e in evs:
        assert 'ts' in e and 'dur' in e and e['dur'] >= 0

    # block-compute spans carry per-gulp identity
    copies = [e for e in evs
              if 'CopyBlock' in e['name'] and e['cat'] == 'compute']
    by_block = {}
    for e in copies:
        by_block.setdefault(e['name'], []).append(e)
    assert len(by_block) == 2          # both copy blocks traced
    for name, block_evs in by_block.items():
        idents = sorted((e['args']['seq'], e['args']['gulp'])
                        for e in block_evs)
        assert idents == [(0, g) for g in range(ngulp)], \
            '%s: %r' % (name, idents)

    # ring-wait spans from the flow-control seam
    ring_evs = [e for e in evs if e['cat'] == 'ring']
    assert any(e['name'].endswith('.reserve') for e in ring_evs)
    assert any(e['name'].endswith('.acquire') for e in ring_evs)
    # transfer spans from the device hop
    xfer_names = {e['name'] for e in evs if e['cat'] == 'xfer'}
    assert 'h2d' in xfer_names and 'd2h' in xfer_names
    # thread tracks are labeled with block names
    meta = [e for e in data['traceEvents']
            if e.get('ph') == 'M' and e.get('name') == 'thread_name']
    tnames = {e['args']['name'] for e in meta}
    assert any('CopyBlock' in t for t in tnames)


def test_spans_nest_and_close_under_faults(monkeypatch, tmp_path):
    monkeypatch.setenv('BF_TRACE_FILE', str(tmp_path / 't.json'))
    spans.reconfigure()
    with faults.injected('xfer.h2d', count=1):
        with pytest.raises(faults.FaultInjected):
            with spans.span('outer', 'test', k=1):
                with spans.span('inner', 'test'):
                    faults.fire('xfer.h2d')
    evs = [ev for _t, ev in spans.events() if ev[1] == 'test']
    assert [ev[0] for ev in evs] == ['inner', 'outer']  # close order
    (iname, _c, its, idur, _a), (oname, _c2, ots, odur, oargs) = evs
    # inner nests inside outer despite the exception exit
    assert ots <= its
    assert its + idur <= ots + odur + 1.0   # 1us slack
    assert oargs == {'k': 1}


def test_trace_exported_even_when_pipeline_aborts(monkeypatch,
                                                  tmp_path):
    path = tmp_path / 'abort.json'
    monkeypatch.setenv('BF_TRACE_FILE', str(path))
    with faults.injected('block.on_data', match='Ident', after=2,
                         count=1):
        with pytest.raises(Exception):
            _run_simple_pipeline(ngulp=5)
    data = json.loads(path.read_text())
    idents = [e for e in data['traceEvents']
              if e.get('ph') == 'X' and 'Ident' in e['name']
              and e.get('cat') == 'compute']
    # gulps 0 and 1 completed; the faulted gulp raised BEFORE its
    # compute span opened (the fault seam precedes dispatch), so
    # exactly the completed gulps are traced
    assert len(idents) == 2


def test_span_buffer_env_bounds_events(monkeypatch, tmp_path):
    monkeypatch.setenv('BF_TRACE_FILE', str(tmp_path / 'b.json'))
    monkeypatch.setenv('BF_SPAN_BUFFER', '16')
    spans.reconfigure()
    for i in range(100):
        spans.record('ev%d' % i, 'test', float(i), 1.0)
    mine = [ev for _t, ev in spans.events() if ev[1] == 'test']
    assert len(mine) == 16
    assert mine[0][0] == 'ev84'        # ring kept the newest
    monkeypatch.delenv('BF_SPAN_BUFFER')
    spans.reconfigure()


# ---------------------------------------------------------------------------
# unified snapshot + exporters
# ---------------------------------------------------------------------------

def test_snapshot_merges_counters_histograms_rings():
    p, sink = _run_simple_pipeline(ngulp=5)
    snap = bf.telemetry.snapshot()
    assert set(snap) == {'counters', 'histograms', 'rings',
                         'devices', 'mesh', 'tenants', 'scheduler',
                         'identity'}
    assert snap['identity']['pid'] == os.getpid()
    assert snap['counters'].get('pipeline.gulps', 0) > 0
    assert any(k.startswith('block.') and k.endswith('.gulp_s')
               for k in snap['histograms'])
    assert any(k.startswith('ring.') and k.endswith('.reserve_s')
               for k in snap['histograms'])
    assert snap['rings'], 'live ring occupancy missing'
    for occ in snap['rings'].values():
        if 'fill' in occ:
            assert 0.0 <= occ['fill'] <= 1.0
    # per-ring throughput counters feed the gulps/s rate
    assert any(k.startswith('ring.') and k.endswith('.gulps')
               for k in snap['counters'])


_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [-+0-9.eE]+$|'
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \+Inf$')


def test_prometheus_file_written_and_parses(monkeypatch, tmp_path):
    prom = tmp_path / 'metrics.prom'
    monkeypatch.setenv('BF_METRICS_FILE', str(prom))
    _run_simple_pipeline(ngulp=5)
    text = prom.read_text()
    assert text.endswith('\n')
    for line in text.splitlines():
        if not line or line.startswith('#'):
            continue
        assert _PROM_LINE.match(line), 'unparseable line: %r' % line
    # histogram buckets are cumulative and capped by _count
    counts = {}
    buckets = {}
    for line in text.splitlines():
        m = re.match(r'bifrost_tpu_hist_count\{name="([^"]+)"\} (\d+)',
                     line)
        if m:
            counts[m.group(1)] = int(m.group(2))
        m = re.match(r'bifrost_tpu_hist_bucket\{name="([^"]+)",'
                     r'le="([^"]+)"\} (\d+)', line)
        if m:
            buckets.setdefault(m.group(1), []).append(
                (m.group(2), int(m.group(3))))
    assert counts and buckets
    for name, bs in buckets.items():
        cum = [n for _le, n in bs]
        assert cum == sorted(cum), '%s buckets not cumulative' % name
        assert bs[-1][0] == '+Inf'
        assert bs[-1][1] == counts[name]
    assert 'bifrost_tpu_counter_total{name="pipeline.gulps"}' in text
    assert 'bifrost_tpu_ring_fill_ratio' in text


def test_proclog_metrics_and_rings_flow_published():
    p, _sink = _run_simple_pipeline(ngulp=5)
    contents = proclog.load_by_pid(os.getpid())
    metrics = contents.get('telemetry', {}).get('metrics', {})
    assert any(k.startswith('c.pipeline.gulps') for k in metrics)
    assert any(k.startswith('h.block.') and k.endswith('.p99')
               for k in metrics)
    flow = {}
    for block, logs in contents.items():
        if block.replace(os.sep, '/').startswith('rings_flow'):
            flow.update(logs)
    assert flow, 'no rings_flow/<name> proclogs published'
    entry = next(iter(flow.values()))
    assert 'occupancy_pct' in entry
    assert 'gulps' in entry and 'gulps_per_s' in entry


# ---------------------------------------------------------------------------
# flight recorder + watchdog integration
# ---------------------------------------------------------------------------

def test_watchdog_dump_includes_flight_recorder(monkeypatch):
    """A forced stall dumps the span timeline alongside the thread
    stacks (the PR's acceptance criterion)."""
    monkeypatch.setenv('BF_WATCHDOG_ESCALATE', '1')
    stderr = io.StringIO()
    with faults.injected('block.on_data', match='Ident', count=1,
                         after=1, delay=10, exc=None):
        with bf.Pipeline(watchdog_secs=0.5) as p:
            p.shutdown_timeout = 1.0
            src = NumpySourceBlock(_gulps(50), _hdr(), gulp_nframe=4)
            blk = Ident(src)
            GatherSink(blk)
            box = []

            def target():
                try:
                    with contextlib.redirect_stderr(stderr):
                        p.run()
                    box.append(None)
                except BaseException as exc:
                    box.append(exc)

            t = threading.Thread(target=target, daemon=True)
            t.start()
            t.join(20)
            assert not t.is_alive()
    assert isinstance(box[0], PipelineStallError)
    dump = stderr.getvalue()
    assert 'Thread' in dump                  # stacks, as before
    assert 'flight recorder' in dump         # plus the timeline
    # the recorder shows spans leading up to the stall (gulp 0 made it
    # through before the delay fault wedged gulp 1)
    assert '.on_data' in dump or '.reserve' in dump


def test_flight_record_formats_empty_state():
    spans.reset()
    text = spans.flight_record()
    assert 'no spans recorded' in text


# ---------------------------------------------------------------------------
# satellites: trace.reset, CLI status, tool columns/labels
# ---------------------------------------------------------------------------

def test_trace_reset_rereads_env(monkeypatch):
    monkeypatch.delenv('BF_TRACE', raising=False)
    trace.reset()
    assert not trace.tracing_enabled()
    monkeypatch.setenv('BF_TRACE', '1')
    assert not trace.tracing_enabled()       # cached until reset
    trace.reset()
    assert trace.tracing_enabled()
    monkeypatch.delenv('BF_TRACE')
    trace.reset()
    assert not trace.tracing_enabled()


def test_trace_reset_rereads_span_config(monkeypatch, tmp_path):
    path = str(tmp_path / 'via_reset.json')
    monkeypatch.setenv('BF_TRACE_FILE', path)
    trace.reset()
    assert spans.trace_file() == path
    assert spans.enabled()
    monkeypatch.delenv('BF_TRACE_FILE')
    trace.reset()
    assert spans.trace_file() is None


def _tool(name, *args):
    # explicit cwd: tests elsewhere in the suite may chdir away from
    # the repo root, and the subprocess must still import bifrost_tpu
    return subprocess.run([sys.executable,
                           os.path.join(TOOLS, name)] + list(args),
                          capture_output=True, text=True, cwd=ROOT,
                          env=dict(os.environ), timeout=120)


def test_telemetry_cli_status_prints_live_snapshot(tmp_path):
    env = dict(os.environ)
    env['BF_CACHE_DIR'] = str(tmp_path)
    res = subprocess.run(
        [sys.executable, '-m', 'bifrost_tpu.telemetry', '--status'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=120)
    assert res.returncode == 0, res.stderr
    assert 'live process counters' in res.stdout
    assert 'live process histograms' in res.stdout


def test_like_top_shows_percentile_columns():
    _run_simple_pipeline(ngulp=5)
    res = _tool('like_top.py', '--once')
    assert res.returncode == 0, res.stderr
    assert 'p50(ms)' in res.stdout and 'p99(ms)' in res.stdout
    assert 'Wait99' in res.stdout


def test_pipeline2dot_labels_ring_edges_with_flow():
    _run_simple_pipeline(ngulp=5)
    res = _tool('pipeline2dot.py', str(os.getpid()))
    assert res.returncode == 0, res.stderr
    assert '% full' in res.stdout
    assert 'gulps' in res.stdout


def test_obs_overhead_tool_importable():
    res = _tool('obs_overhead.py', '--help')
    assert res.returncode == 0, res.stderr
    assert '--threshold' in res.stdout
    assert '--stack' in res.stdout        # full-stack E2E arm option


# ---------------------------------------------------------------------------
# distributed tracing: trace context (header_standard + pipeline)
# ---------------------------------------------------------------------------

def test_trace_context_helpers():
    from bifrost_tpu import header_standard as hs
    hdr = {'name': 'x'}
    ctx = hs.ensure_trace_context(hdr)
    assert ctx is hdr['_trace']
    assert len(ctx['id']) == 16 and ctx['origin_ns'] > 0
    # idempotent: a second ensure keeps the stamp
    assert hs.ensure_trace_context(hdr) is ctx
    # propagation copies into outputs lacking one
    o1, o2 = {'a': 1}, {'_trace': {'id': 'keepme', 'origin_ns': 1}}
    got = hs.propagate_trace_context(hdr, [o1, o2])
    assert got['id'] == ctx['id']
    assert o1['_trace']['id'] == ctx['id']
    assert o2['_trace']['id'] == 'keepme'     # never overwritten
    # headers without context propagate nothing
    assert hs.propagate_trace_context({'name': 'y'}, [{}]) is None


def test_trace_context_env_toggle(monkeypatch):
    from bifrost_tpu import header_standard as hs
    monkeypatch.setenv('BF_TRACE_CONTEXT', '0')
    hdr = {}
    assert hs.ensure_trace_context(hdr) is None
    assert '_trace' not in hdr
    monkeypatch.delenv('BF_TRACE_CONTEXT')
    assert hs.ensure_trace_context(hdr) is not None


def test_pipeline_stamps_and_propagates_trace_context():
    """Source stamps at first commit; transform and sink sequences
    inherit the same stream-unique id end to end."""
    p, sink = _run_simple_pipeline(ngulp=3)
    assert sink.headers, 'sink saw no sequences'
    ctx = sink.headers[0].get('_trace')
    assert ctx and len(ctx['id']) == 16
    assert ctx['origin_ns'] > 0 and ctx.get('host')


def test_pipeline_trace_context_disabled(monkeypatch):
    monkeypatch.setenv('BF_TRACE_CONTEXT', '0')
    p, sink = _run_simple_pipeline(ngulp=3)
    assert '_trace' not in sink.headers[0]


def test_compute_spans_carry_trace_id(monkeypatch, tmp_path):
    path = tmp_path / 'ctx_trace.json'
    monkeypatch.setenv('BF_TRACE_FILE', str(path))
    p, sink = _run_simple_pipeline(ngulp=3)
    data = json.loads(path.read_text())
    trace_id = sink.headers[0]['_trace']['id']
    computes = [e for e in data['traceEvents']
                if e.get('ph') == 'X' and e.get('cat') == 'compute']
    assert computes
    for e in computes:
        assert e['args']['trace'] == trace_id
        assert 'seq' in e['args'] and 'gulp' in e['args']
    # clock-correlation metadata rides along for trace_merge.py
    assert 'bf_clock' in data['otherData']


# ---------------------------------------------------------------------------
# capture-to-commit SLOs (telemetry.slo)
# ---------------------------------------------------------------------------

def test_slo_capture_age_extrapolates_tsamp():
    from bifrost_tpu.telemetry import slo
    import time as time_mod
    now = time_mod.time()
    hdr = {'_trace': {'id': 'x' * 16,
                      'origin_ns': int((now - 10.0) * 1e9)},
           'tsamp': 2.0}
    # frame 4 was captured at origin + 8s -> age ~2s, not ~10s
    age = slo.capture_age_s(hdr, frame_end=4, now=now)
    assert age == pytest.approx(2.0, abs=0.1)
    # no tsamp: age measured against the sequence origin
    del hdr['tsamp']
    assert slo.capture_age_s(hdr, frame_end=4, now=now) == \
        pytest.approx(10.0, abs=0.1)
    # no context: no observation
    assert slo.capture_age_s({'name': 'x'}) is None


def test_slo_histograms_and_exit_p99():
    ngulp = 5
    p, sink = _run_simple_pipeline(ngulp=ngulp)
    snap = bf.telemetry.snapshot()
    hists = snap['histograms']
    # per-block commit ages from ring._note_commit (both the source's
    # and the transform's output rings commit with a traced header)
    commit = [k for k in hists
              if k.startswith('slo.') and k.endswith('.commit_age_s')]
    assert commit, 'no commit-age histograms recorded'
    # THE pipeline-exit percentile (sink blocks)
    h = hists.get('slo.exit_age_s')
    assert h and h['count'] == ngulp
    assert h['p99'] >= h['p50'] > 0.0
    # no budget armed: no violations
    assert snap['counters'].get('slo.violations', 0) == 0


def test_slo_budget_violations(monkeypatch):
    from bifrost_tpu.telemetry import counters as tc
    monkeypatch.setenv('BF_SLO_MS', '0.000001')   # 1ns budget
    p, sink = _run_simple_pipeline(ngulp=4)
    snap = bf.telemetry.snapshot()
    assert snap['counters'].get('slo.violations', 0) > 0
    per_block = [k for k, v in snap['counters'].items()
                 if k.startswith('slo.') and k.endswith('.violations')
                 and k != 'slo.violations' and v > 0]
    assert per_block
    monkeypatch.setenv('BF_SLO_MS', '60000')      # 60s budget
    tc.reset()
    _run_simple_pipeline(ngulp=4)
    assert tc.get('slo.violations') == 0


def test_slo_age99_reaches_like_top():
    _run_simple_pipeline(ngulp=5)
    res = _tool('like_top.py', '--once')
    assert res.returncode == 0, res.stderr
    assert 'Age99' in res.stdout


# ---------------------------------------------------------------------------
# satellite: span-buffer overflow accounting
# ---------------------------------------------------------------------------

def test_dropped_spans_counted_and_snapshot(monkeypatch, tmp_path):
    monkeypatch.setenv('BF_TRACE_FILE', str(tmp_path / 'd.json'))
    monkeypatch.setenv('BF_SPAN_BUFFER', '16')
    spans.reconfigure()
    for i in range(40):
        spans.record('ov%d' % i, 'test', float(i), 1.0)
    assert spans.dropped_spans() == 40 - 16
    snap = bf.telemetry.snapshot()
    assert snap['counters']['trace.dropped_spans'] == 24
    # the flight recorder discloses the saturation
    dump = spans.flight_record()
    assert 'dropped' in dump and 'saturation' in dump
    monkeypatch.delenv('BF_SPAN_BUFFER')
    spans.reconfigure()


def test_dropped_spans_survive_buffer_prune(monkeypatch, tmp_path):
    """trace.dropped_spans is exported as a cumulative counter: a
    dead thread's drops must survive prune_dead_buffers (Pipeline.run
    calls it at every start) instead of vanishing."""
    import threading
    monkeypatch.setenv('BF_TRACE_FILE', str(tmp_path / 'p.json'))
    monkeypatch.setenv('BF_SPAN_BUFFER', '16')
    spans.reconfigure()

    def overflow():
        for i in range(30):
            spans.record('pr%d' % i, 'test', float(i), 1.0)

    t = threading.Thread(target=overflow)
    t.start()
    t.join()
    assert spans.dropped_spans() == 14
    spans.prune_dead_buffers()         # the thread is dead: pruned
    assert spans.dropped_spans() == 14  # ...but the count is kept
    monkeypatch.delenv('BF_SPAN_BUFFER')
    spans.reconfigure()


def test_no_drops_no_counter():
    spans.enable_flight_recorder()
    try:
        spans.record('small', 'test', 0.0, 1.0)
        snap = bf.telemetry.snapshot()
        assert 'trace.dropped_spans' not in snap['counters']
    finally:
        spans.disable_flight_recorder()


# ---------------------------------------------------------------------------
# satellite: Prometheus textfile path (escaping / atomicity / round-trip)
# ---------------------------------------------------------------------------

def test_prometheus_label_escaping():
    counters.inc('weird"name\\with\nnasties')
    histograms.observe('hist"quoted\\slash', 0.5)
    text = exporter.prometheus_text()
    for line in text.splitlines():
        if not line or line.startswith('#'):
            continue
        assert _PROM_LINE.match(line), 'unparseable line: %r' % line
    # the escapes round-trip: \" for quotes, \\ for backslash, \n as
    # the two-character escape (never a raw newline inside a label)
    assert r'weird\"name\\with\nnasties' in text
    assert r'hist\"quoted\\slash' in text


def test_prometheus_atomic_publish(tmp_path):
    counters.inc('atomic.probe')
    path = str(tmp_path / 'm.prom')
    exporter.write_prometheus(path)
    # the tmp staging file was renamed away, never left behind
    leftovers = [p for p in os.listdir(str(tmp_path))
                 if p != 'm.prom']
    assert not leftovers, leftovers
    assert 'atomic.probe' in open(path).read()


def _parse_prometheus(text):
    """{(metric, frozenset(labels)): value} over every sample line."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith('#'):
            continue
        m = re.match(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
                     r'(?:\{(.*)\})? (.+)$', line)
        assert m, 'unparseable line: %r' % line
        name, labels, value = m.groups()
        label_set = frozenset((labels or '').split(','))
        out[(name, label_set)] = value
    return out


def test_prometheus_roundtrip_every_snapshot_key():
    """Every counter, histogram, ring, and device entry snapshot()
    emits appears in the textfile with the right value."""
    p, _sink = _run_simple_pipeline(ngulp=4, device_hop=True)
    snap = bf.telemetry.snapshot()
    parsed = _parse_prometheus(exporter.prometheus_text(snap))

    def esc(v):
        return str(v).replace('\\', r'\\').replace('"', r'\"') \
                     .replace('\n', r'\n')

    for name, val in snap['counters'].items():
        key = ('bifrost_tpu_counter_total',
               frozenset(['name="%s"' % esc(name)]))
        assert key in parsed, 'counter %r missing' % name
        assert int(parsed[key]) == val
    for name, h in snap['histograms'].items():
        key = ('bifrost_tpu_hist_count',
               frozenset(['name="%s"' % esc(name)]))
        assert key in parsed, 'histogram %r missing' % name
        assert int(parsed[key]) == h['count']
    for name, d in snap['rings'].items():
        if 'fill' in d:
            key = ('bifrost_tpu_ring_fill_ratio',
                   frozenset(['ring="%s"' % esc(name)]))
            assert key in parsed, 'ring %r missing' % name
    for idx, d in snap['devices'].items():
        if 'bytes_in_use' in d:
            key = ('bifrost_tpu_device_bytes',
                   frozenset(['device="%s"' % esc(idx),
                              'kind="in_use"']))
            assert key in parsed, 'device %r missing' % idx


def test_snapshot_device_and_mesh_sections():
    _run_simple_pipeline(ngulp=3, device_hop=True)
    snap = bf.telemetry.snapshot()
    # jax is imported (device hop ran), so device stats are sampled
    assert snap['devices'], 'no device memory stats'
    entry = next(iter(snap['devices'].values()))
    assert 'platform' in entry
    assert isinstance(snap['mesh'], dict)


def test_metrics_publisher_tracks_hbm_watermark():
    pub = exporter.MetricsPublisher(interval=60)
    snap = {'devices': {0: {'bytes_in_use': 100}}}
    pub._note_watermarks(snap)
    assert snap['devices'][0]['watermark_bytes'] == 100
    snap2 = {'devices': {0: {'bytes_in_use': 40}}}
    pub._note_watermarks(snap2)
    # the watermark is the peak across publishes, not the sample
    assert snap2['devices'][0]['watermark_bytes'] == 100


# ---------------------------------------------------------------------------
# tools: trace_merge / telemetry_diff / pipeline2dot bridge nodes
# ---------------------------------------------------------------------------

def _synthetic_trace(path, host, events, sessions):
    data = {'traceEvents': events, 'displayTimeUnit': 'ms',
            'otherData': {'bf_clock': {'host': host, 'pid': 1234,
                                       'sessions': sessions}}}
    with open(str(path), 'w') as f:
        json.dump(data, f)


def test_trace_merge_shifts_clocks(tmp_path):
    """The rx file's timeline lands on the tx file's clock via the
    handshake offset."""
    ev = {'ph': 'X', 'name': 'x.on_data', 'cat': 'compute',
          'pid': 1, 'tid': 1, 'dur': 5.0,
          'args': {'trace': 'abc', 'seq': 0, 'gulp': 0}}
    _synthetic_trace(tmp_path / 'tx.json', 'hostA',
                     [dict(ev, ts=1000.0)],
                     {'sess1': {'role': 'tx', 'offset_us': 500.0,
                                'rtt_us': 10.0}})
    _synthetic_trace(tmp_path / 'rx.json', 'hostB',
                     [dict(ev, ts=1600.0)],
                     {'sess1': {'role': 'rx'}})
    out = tmp_path / 'merged.json'
    res = _tool('trace_merge.py', '-o', str(out),
                str(tmp_path / 'tx.json'), str(tmp_path / 'rx.json'))
    assert res.returncode == 0, res.stderr
    data = json.loads(out.read_text())
    evs = [e for e in data['traceEvents'] if e.get('ph') == 'X']
    assert len(evs) == 2
    by_pid = {e['pid']: e for e in evs}
    assert by_pid[1]['ts'] == 1000.0          # reference unchanged
    # rx timestamp 1600 on a clock 500us ahead -> 1100 on tx clock
    assert by_pid[2]['ts'] == pytest.approx(1100.0)
    # process labels carry the host names
    names = [e['args']['name'] for e in data['traceEvents']
             if e.get('ph') == 'M' and e.get('name') == 'process_name']
    assert any('hostA' in n for n in names)
    assert any('hostB' in n for n in names)


def test_telemetry_diff_flags_regressions(tmp_path):
    base = {'value': 10.0, 'gulps_per_s': 100.0,
            'wait_p99_ms': 4.0, 'counters': {'slo.violations': 0}}
    cur = {'value': 10.0, 'gulps_per_s': 50.0,      # -50% throughput
           'wait_p99_ms': 9.0,                      # +125% latency
           'counters': {'slo.violations': 3}}       # new violations
    b, c = tmp_path / 'b.json', tmp_path / 'c.json'
    b.write_text(json.dumps(base))
    c.write_text(json.dumps(cur))
    res = _tool('telemetry_diff.py', str(b), str(c))
    assert res.returncode == 0, res.stderr        # advisory: exit 0
    assert 'REGRESSED' in res.stdout
    assert 'gulps_per_s' in res.stdout
    assert 'violations' in res.stdout
    # strict mode turns regressions into a failing exit
    res = _tool('telemetry_diff.py', str(b), str(c), '--strict')
    assert res.returncode == 3
    # identical inputs: clean
    res = _tool('telemetry_diff.py', str(b), str(b), '--strict')
    assert res.returncode == 0
    assert '0 regression(s)' in res.stdout
    # zero-base watched counter (violations 0 -> 3): the --out report
    # must stay valid RFC-8259 JSON — no Infinity token from the
    # undefined % change
    out = tmp_path / 'report.json'
    res = _tool('telemetry_diff.py', str(b), str(c),
                '--out', str(out))
    assert res.returncode == 0, res.stderr

    def _no_const(name):
        raise AssertionError('non-standard JSON token %r' % name)

    rep = json.loads(out.read_text(), parse_constant=_no_const)
    viol = [f for f in rep['findings'] if 'violations' in f['path']]
    assert viol and viol[0]['pct'] is None
    assert viol[0]['severity'] == 'regression'


# ---------------------------------------------------------------------------
# end-to-end: trace context + SLO + boundary rendering across a bridge
# ---------------------------------------------------------------------------

def test_bridge_carries_trace_context_and_slo(monkeypatch):
    """Two pipelines over a loopback bridge in ONE process: the sink
    pipeline's sequences carry the ORIGIN pipeline's trace id, its
    GatherSink reports capture-to-commit exit ages, and pipeline2dot
    renders the bridge endpoints as cross-host boundary nodes."""
    import threading
    from tests.util import NumpySourceBlock

    rng = np.random.RandomState(5)
    NT = 8
    gulps = [rng.randn(NT, 4).astype(np.float32) for _ in range(4)]
    hdr = simple_header([-1, 4], 'f32', name='e2ectx', gulp_nframe=NT)

    with bf.Pipeline() as prx:
        bsrc = bf.blocks.bridge_source('127.0.0.1', 0)
        sink = GatherSink(bsrc)
    with bf.Pipeline() as ptx:
        nsrc = NumpySourceBlock(gulps, hdr, gulp_nframe=NT)
        bf.blocks.bridge_sink(nsrc, '127.0.0.1', bsrc.port)

    rx_errors = []

    def run_rx():
        try:
            prx.run()
        except BaseException as exc:
            rx_errors.append(exc)

    t = threading.Thread(target=run_rx, daemon=True)
    t.start()
    ptx.run()
    t.join(30)
    assert not rx_errors, rx_errors

    # the stream identity crossed the wire
    rx_ctx = sink.headers[0].get('_trace')
    assert rx_ctx and len(rx_ctx['id']) == 16
    # the sink pipeline reports capture-to-commit ages (acceptance:
    # snapshot() has an exit p99 for the sink pipeline)
    snap = bf.telemetry.snapshot()
    h = snap['histograms'].get('slo.exit_age_s')
    assert h and h['count'] == len(gulps) and h['p99'] > 0
    # the receiver's commits aged too (BridgeSource's output ring)
    assert any('BridgeSource' in k and k.endswith('.commit_age_s')
               for k in snap['histograms'])

    # pipeline2dot renders the endpoints as boundary nodes with the
    # transport's live figures
    res = _tool('pipeline2dot.py', str(os.getpid()))
    assert res.returncode == 0, res.stderr
    assert 'bridge sink <->' in res.stdout
    assert 'bridge source <->' in res.stdout
    assert 'cds' in res.stdout
    assert 'tx ' in res.stdout and 'rx ' in res.stdout
    # the per-endpoint stats dirs are not rendered as stray blocks
    assert '_bridge_transmit"' not in res.stdout


# ---------------------------------------------------------------------------
# BF_JAX_PROFILE one-shot (telemetry.profiling)
# ---------------------------------------------------------------------------

def test_profiled_dispatch_passthrough_without_env(monkeypatch):
    from bifrost_tpu.telemetry import profiling
    monkeypatch.delenv('BF_JAX_PROFILE', raising=False)
    profiling.reset()
    assert profiling.profiled_dispatch(lambda: 42) == 42
    assert counters.get('jaxprof.captures') == 0


def test_profiled_dispatch_one_shot(monkeypatch, tmp_path):
    from bifrost_tpu.telemetry import profiling
    monkeypatch.setenv('BF_JAX_PROFILE', str(tmp_path / 'prof'))
    profiling.reset()
    calls = []
    monkeypatch.setattr('jax.profiler.start_trace',
                        lambda d: calls.append(('start', d)))
    monkeypatch.setattr('jax.profiler.stop_trace',
                        lambda: calls.append(('stop',)))
    assert profiling.profiled_dispatch(lambda: 7) == 7
    # one-shot: the second dispatch runs unbracketed
    assert profiling.profiled_dispatch(lambda: 8) == 8
    assert calls == [('start', str(tmp_path / 'prof')), ('stop',)]
    assert counters.get('jaxprof.captures') == 1
