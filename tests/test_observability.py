"""Observability-layer tests (docs/observability.md): gulp-span
tracing with Chrome trace export, log2 latency histograms, the unified
snapshot / Prometheus export surface, and the watchdog flight
recorder — all on the CPU backend, driven where useful by the
deterministic fault harness (bifrost_tpu.testing.faults)."""

import contextlib
import io
import json
import os
import re
import subprocess
import sys
import threading

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu import proclog, trace
from bifrost_tpu.supervision import PipelineStallError
from bifrost_tpu.telemetry import (counters, exporter, histograms,
                                   spans)
from bifrost_tpu.testing import faults
from tests.util import NumpySourceBlock, GatherSink, simple_header

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, 'tools')


@pytest.fixture(autouse=True)
def clean_state():
    faults.clear()
    counters.reset()
    histograms.reset()
    spans.reset()
    yield
    faults.clear()
    counters.reset()
    histograms.reset()
    spans.reset()


def _hdr():
    return simple_header([-1, 3], 'f32')


def _gulps(n=5):
    return [np.full((4, 3), float(k), dtype=np.float32)
            for k in range(n)]


class Ident(bf.TransformBlock):
    """Pass-through host transform with a distinctive name."""

    def on_sequence(self, iseq):
        return dict(iseq.header)

    def on_data(self, ispan, ospan):
        ospan.data.as_numpy()[...] = ispan.data.as_numpy()


def _run_simple_pipeline(ngulp=5, device_hop=False, **pipe_kwargs):
    with bf.Pipeline(**pipe_kwargs) as p:
        src = NumpySourceBlock(_gulps(ngulp), _hdr(), gulp_nframe=4)
        if device_hop:
            up = bf.blocks.copy(src, space='tpu')
            down = bf.blocks.copy(up, space='system')
            sink = GatherSink(down)
        else:
            blk = Ident(src)
            sink = GatherSink(blk)
        p.run()
    return p, sink


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------

def test_histogram_basic_stats():
    h = histograms.Histogram('t.basic')
    for v in (0.001, 0.002, 0.004, 0.008, 0.016):
        h.record(v)
    snap = h.snapshot()
    assert snap['count'] == 5
    assert snap['sum'] == pytest.approx(0.031)
    assert snap['min'] == pytest.approx(0.001)
    assert snap['max'] == pytest.approx(0.016)
    # five distinct powers of two -> five distinct buckets
    assert len(snap['buckets']) == 5
    assert sum(snap['buckets'].values()) == 5


def test_histogram_percentiles_monotonic():
    rng = np.random.RandomState(7)
    h = histograms.get_or_create('t.mono')
    for v in np.exp(rng.randn(500) * 2.0 - 6.0):
        h.record(float(v))
    last = 0.0
    for p in range(1, 101):
        cur = h.percentile(p)
        assert cur >= last, 'p%d < p%d' % (p, p - 1)
        last = cur
    snap = h.snapshot()
    assert snap['p50'] <= snap['p90'] <= snap['p99']
    # estimates stay inside the observed range
    assert snap['min'] <= snap['p50'] <= snap['max']
    assert snap['min'] <= snap['p99'] <= snap['max']


def test_histogram_edge_values():
    h = histograms.Histogram('t.edge')
    assert h.percentile(99) == 0.0        # empty
    h.record(0.0)
    h.record(-1.0)                        # clamps to 0
    h.record(float('nan'))                # clamps to 0
    h.record(1e30)                        # clamps to top bucket
    snap = h.snapshot()
    assert snap['count'] == 4
    assert snap['min'] == 0.0 and snap['max'] == 1e30


def test_histogram_registry_observe_and_reset():
    histograms.observe('t.reg', 0.5)
    histograms.observe('t.reg', 0.5)
    assert histograms.get('t.reg').count == 2
    assert 't.reg' in histograms.snapshot()
    histograms.reset()
    assert histograms.get('t.reg') is None


# ---------------------------------------------------------------------------
# gulp-span tracing / Chrome trace export
# ---------------------------------------------------------------------------

def test_trace_file_has_complete_spans_per_gulp(monkeypatch, tmp_path):
    """The acceptance-criterion run: BF_TRACE_FILE set, a CPU pipeline
    with a device hop produces a valid Chrome trace with block-compute,
    ring-wait, and transfer spans, one complete compute span per
    gulp with (sequence, gulp) identity."""
    path = tmp_path / 'trace.json'
    monkeypatch.setenv('BF_TRACE_FILE', str(path))
    trace.reset()                      # satellite: re-read env
    ngulp = 5
    _run_simple_pipeline(ngulp=ngulp, device_hop=True)

    data = json.loads(path.read_text())
    evs = [e for e in data['traceEvents'] if e.get('ph') == 'X']
    assert evs, 'no complete events exported'
    for e in evs:
        assert 'ts' in e and 'dur' in e and e['dur'] >= 0

    # block-compute spans carry per-gulp identity
    copies = [e for e in evs
              if 'CopyBlock' in e['name'] and e['cat'] == 'compute']
    by_block = {}
    for e in copies:
        by_block.setdefault(e['name'], []).append(e)
    assert len(by_block) == 2          # both copy blocks traced
    for name, block_evs in by_block.items():
        idents = sorted((e['args']['seq'], e['args']['gulp'])
                        for e in block_evs)
        assert idents == [(0, g) for g in range(ngulp)], \
            '%s: %r' % (name, idents)

    # ring-wait spans from the flow-control seam
    ring_evs = [e for e in evs if e['cat'] == 'ring']
    assert any(e['name'].endswith('.reserve') for e in ring_evs)
    assert any(e['name'].endswith('.acquire') for e in ring_evs)
    # transfer spans from the device hop
    xfer_names = {e['name'] for e in evs if e['cat'] == 'xfer'}
    assert 'h2d' in xfer_names and 'd2h' in xfer_names
    # thread tracks are labeled with block names
    meta = [e for e in data['traceEvents']
            if e.get('ph') == 'M' and e.get('name') == 'thread_name']
    tnames = {e['args']['name'] for e in meta}
    assert any('CopyBlock' in t for t in tnames)


def test_spans_nest_and_close_under_faults(monkeypatch, tmp_path):
    monkeypatch.setenv('BF_TRACE_FILE', str(tmp_path / 't.json'))
    spans.reconfigure()
    with faults.injected('xfer.h2d', count=1):
        with pytest.raises(faults.FaultInjected):
            with spans.span('outer', 'test', k=1):
                with spans.span('inner', 'test'):
                    faults.fire('xfer.h2d')
    evs = [ev for _t, ev in spans.events() if ev[1] == 'test']
    assert [ev[0] for ev in evs] == ['inner', 'outer']  # close order
    (iname, _c, its, idur, _a), (oname, _c2, ots, odur, oargs) = evs
    # inner nests inside outer despite the exception exit
    assert ots <= its
    assert its + idur <= ots + odur + 1.0   # 1us slack
    assert oargs == {'k': 1}


def test_trace_exported_even_when_pipeline_aborts(monkeypatch,
                                                  tmp_path):
    path = tmp_path / 'abort.json'
    monkeypatch.setenv('BF_TRACE_FILE', str(path))
    with faults.injected('block.on_data', match='Ident', after=2,
                         count=1):
        with pytest.raises(Exception):
            _run_simple_pipeline(ngulp=5)
    data = json.loads(path.read_text())
    idents = [e for e in data['traceEvents']
              if e.get('ph') == 'X' and 'Ident' in e['name']
              and e.get('cat') == 'compute']
    # gulps 0 and 1 completed; the faulted gulp raised BEFORE its
    # compute span opened (the fault seam precedes dispatch), so
    # exactly the completed gulps are traced
    assert len(idents) == 2


def test_span_buffer_env_bounds_events(monkeypatch, tmp_path):
    monkeypatch.setenv('BF_TRACE_FILE', str(tmp_path / 'b.json'))
    monkeypatch.setenv('BF_SPAN_BUFFER', '16')
    spans.reconfigure()
    for i in range(100):
        spans.record('ev%d' % i, 'test', float(i), 1.0)
    mine = [ev for _t, ev in spans.events() if ev[1] == 'test']
    assert len(mine) == 16
    assert mine[0][0] == 'ev84'        # ring kept the newest
    monkeypatch.delenv('BF_SPAN_BUFFER')
    spans.reconfigure()


# ---------------------------------------------------------------------------
# unified snapshot + exporters
# ---------------------------------------------------------------------------

def test_snapshot_merges_counters_histograms_rings():
    p, sink = _run_simple_pipeline(ngulp=5)
    snap = bf.telemetry.snapshot()
    assert set(snap) == {'counters', 'histograms', 'rings'}
    assert snap['counters'].get('pipeline.gulps', 0) > 0
    assert any(k.startswith('block.') and k.endswith('.gulp_s')
               for k in snap['histograms'])
    assert any(k.startswith('ring.') and k.endswith('.reserve_s')
               for k in snap['histograms'])
    assert snap['rings'], 'live ring occupancy missing'
    for occ in snap['rings'].values():
        if 'fill' in occ:
            assert 0.0 <= occ['fill'] <= 1.0
    # per-ring throughput counters feed the gulps/s rate
    assert any(k.startswith('ring.') and k.endswith('.gulps')
               for k in snap['counters'])


_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [-+0-9.eE]+$|'
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \+Inf$')


def test_prometheus_file_written_and_parses(monkeypatch, tmp_path):
    prom = tmp_path / 'metrics.prom'
    monkeypatch.setenv('BF_METRICS_FILE', str(prom))
    _run_simple_pipeline(ngulp=5)
    text = prom.read_text()
    assert text.endswith('\n')
    for line in text.splitlines():
        if not line or line.startswith('#'):
            continue
        assert _PROM_LINE.match(line), 'unparseable line: %r' % line
    # histogram buckets are cumulative and capped by _count
    counts = {}
    buckets = {}
    for line in text.splitlines():
        m = re.match(r'bifrost_tpu_hist_count\{name="([^"]+)"\} (\d+)',
                     line)
        if m:
            counts[m.group(1)] = int(m.group(2))
        m = re.match(r'bifrost_tpu_hist_bucket\{name="([^"]+)",'
                     r'le="([^"]+)"\} (\d+)', line)
        if m:
            buckets.setdefault(m.group(1), []).append(
                (m.group(2), int(m.group(3))))
    assert counts and buckets
    for name, bs in buckets.items():
        cum = [n for _le, n in bs]
        assert cum == sorted(cum), '%s buckets not cumulative' % name
        assert bs[-1][0] == '+Inf'
        assert bs[-1][1] == counts[name]
    assert 'bifrost_tpu_counter_total{name="pipeline.gulps"}' in text
    assert 'bifrost_tpu_ring_fill_ratio' in text


def test_proclog_metrics_and_rings_flow_published():
    p, _sink = _run_simple_pipeline(ngulp=5)
    contents = proclog.load_by_pid(os.getpid())
    metrics = contents.get('telemetry', {}).get('metrics', {})
    assert any(k.startswith('c.pipeline.gulps') for k in metrics)
    assert any(k.startswith('h.block.') and k.endswith('.p99')
               for k in metrics)
    flow = {}
    for block, logs in contents.items():
        if block.replace(os.sep, '/').startswith('rings_flow'):
            flow.update(logs)
    assert flow, 'no rings_flow/<name> proclogs published'
    entry = next(iter(flow.values()))
    assert 'occupancy_pct' in entry
    assert 'gulps' in entry and 'gulps_per_s' in entry


# ---------------------------------------------------------------------------
# flight recorder + watchdog integration
# ---------------------------------------------------------------------------

def test_watchdog_dump_includes_flight_recorder(monkeypatch):
    """A forced stall dumps the span timeline alongside the thread
    stacks (the PR's acceptance criterion)."""
    monkeypatch.setenv('BF_WATCHDOG_ESCALATE', '1')
    stderr = io.StringIO()
    with faults.injected('block.on_data', match='Ident', count=1,
                         after=1, delay=10, exc=None):
        with bf.Pipeline(watchdog_secs=0.5) as p:
            p.shutdown_timeout = 1.0
            src = NumpySourceBlock(_gulps(50), _hdr(), gulp_nframe=4)
            blk = Ident(src)
            GatherSink(blk)
            box = []

            def target():
                try:
                    with contextlib.redirect_stderr(stderr):
                        p.run()
                    box.append(None)
                except BaseException as exc:
                    box.append(exc)

            t = threading.Thread(target=target, daemon=True)
            t.start()
            t.join(20)
            assert not t.is_alive()
    assert isinstance(box[0], PipelineStallError)
    dump = stderr.getvalue()
    assert 'Thread' in dump                  # stacks, as before
    assert 'flight recorder' in dump         # plus the timeline
    # the recorder shows spans leading up to the stall (gulp 0 made it
    # through before the delay fault wedged gulp 1)
    assert '.on_data' in dump or '.reserve' in dump


def test_flight_record_formats_empty_state():
    spans.reset()
    text = spans.flight_record()
    assert 'no spans recorded' in text


# ---------------------------------------------------------------------------
# satellites: trace.reset, CLI status, tool columns/labels
# ---------------------------------------------------------------------------

def test_trace_reset_rereads_env(monkeypatch):
    monkeypatch.delenv('BF_TRACE', raising=False)
    trace.reset()
    assert not trace.tracing_enabled()
    monkeypatch.setenv('BF_TRACE', '1')
    assert not trace.tracing_enabled()       # cached until reset
    trace.reset()
    assert trace.tracing_enabled()
    monkeypatch.delenv('BF_TRACE')
    trace.reset()
    assert not trace.tracing_enabled()


def test_trace_reset_rereads_span_config(monkeypatch, tmp_path):
    path = str(tmp_path / 'via_reset.json')
    monkeypatch.setenv('BF_TRACE_FILE', path)
    trace.reset()
    assert spans.trace_file() == path
    assert spans.enabled()
    monkeypatch.delenv('BF_TRACE_FILE')
    trace.reset()
    assert spans.trace_file() is None


def _tool(name, *args):
    # explicit cwd: tests elsewhere in the suite may chdir away from
    # the repo root, and the subprocess must still import bifrost_tpu
    return subprocess.run([sys.executable,
                           os.path.join(TOOLS, name)] + list(args),
                          capture_output=True, text=True, cwd=ROOT,
                          env=dict(os.environ), timeout=120)


def test_telemetry_cli_status_prints_live_snapshot(tmp_path):
    env = dict(os.environ)
    env['BF_CACHE_DIR'] = str(tmp_path)
    res = subprocess.run(
        [sys.executable, '-m', 'bifrost_tpu.telemetry', '--status'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=120)
    assert res.returncode == 0, res.stderr
    assert 'live process counters' in res.stdout
    assert 'live process histograms' in res.stdout


def test_like_top_shows_percentile_columns():
    _run_simple_pipeline(ngulp=5)
    res = _tool('like_top.py', '--once')
    assert res.returncode == 0, res.stderr
    assert 'p50(ms)' in res.stdout and 'p99(ms)' in res.stdout
    assert 'Wait99' in res.stdout


def test_pipeline2dot_labels_ring_edges_with_flow():
    _run_simple_pipeline(ngulp=5)
    res = _tool('pipeline2dot.py', str(os.getpid()))
    assert res.returncode == 0, res.stderr
    assert '% full' in res.stdout
    assert 'gulps' in res.stdout


def test_obs_overhead_tool_importable():
    res = _tool('obs_overhead.py', '--help')
    assert res.returncode == 0, res.stderr
    assert '--threshold' in res.stdout
