"""LinAlg tests vs numpy einsum (reference analogue: test/test_linalg.py)."""

import numpy as np

import bifrost_tpu as bf
from bifrost_tpu.ops import LinAlg


def test_matmul_ab():
    rng = np.random.RandomState(0)
    a = (rng.randn(4, 8, 16) + 1j * rng.randn(4, 8, 16)).astype(np.complex64)
    b = (rng.randn(4, 16, 8) + 1j * rng.randn(4, 16, 8)).astype(np.complex64)
    la = LinAlg()
    y = np.asarray(la.matmul(1.0, a, b, 0.0, None))
    np.testing.assert_allclose(y, a @ b, rtol=1e-4)


def test_matmul_aah():
    rng = np.random.RandomState(1)
    a = (rng.randn(3, 8, 16) + 1j * rng.randn(3, 8, 16)).astype(np.complex64)
    la = LinAlg()
    y = np.asarray(la.matmul(1.0, a, None, 0.0, None))
    expect = a @ np.conj(a.transpose(0, 2, 1))
    np.testing.assert_allclose(y, expect, rtol=1e-4)


def test_matmul_aah_int8_mxu_path():
    """ci8 correlation: exact integer arithmetic through the 3-matmul
    path (reference: Cherk3mEx, src/linalg.cu:130-148)."""
    rng = np.random.RandomState(2)
    n, k = 16, 32
    re = rng.randint(-64, 64, size=(n, k)).astype(np.int8)
    im = rng.randint(-64, 64, size=(n, k)).astype(np.int8)
    a = bf.empty((n, k), 'ci8', 'system')
    buf = a.as_numpy()
    buf['re'], buf['im'] = re, im
    ad = a.copy('tpu')
    la = LinAlg()
    y = np.asarray(la.matmul(1.0, ad, None, 0.0, None))
    c = re.astype(np.float64) + 1j * im
    expect = c @ np.conj(c.T)
    np.testing.assert_array_equal(y, expect.astype(np.complex64))


def test_matmul_beta_accumulate():
    rng = np.random.RandomState(3)
    a = rng.randn(8, 4).astype(np.float32)
    b = rng.randn(4, 8).astype(np.float32)
    c = bf.asarray(rng.randn(8, 8).astype(np.float32), space='tpu')
    c0 = np.asarray(c.data).copy()
    la = LinAlg()
    la.matmul(2.0, a, b, 3.0, c)
    np.testing.assert_allclose(np.asarray(c.data), 2 * (a @ b) + 3 * c0,
                               rtol=1e-4)
