"""Closed-loop auto-tuning tests (docs/autotune.md):

- derived per-second rates in ``telemetry.snapshot(rates=...)`` (the
  controller's signal source)
- knob plumbing precedence: BlockScope > Pipeline kwarg > BF_* env for
  every tunable the controller touches (K, sync_depth, bridge
  window/stripes, ring buffering)
- the knob state machine: geometric stepping, cooldown, min-gain
  convergence, revert-on-regression, the static-verifier gate (a
  retune can never introduce a BF-E the analyzer rejects)
- freeze mode: profile dump + warm start
- every retune is visible in telemetry (counter + proclog + span)
- mprobe coin-flip staleness: COIN-FLIP winners are re-raced after
  BF_MPROBE_REPROBE cache uses instead of being frozen forever
"""

import json
import os
import time

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu import autotune
from bifrost_tpu.autotune import AutoTuner
from bifrost_tpu.macro import resolve_gulp_batch, retune_gulp_batch
from bifrost_tpu.pipeline import resolve_sync_depth
from bifrost_tpu.telemetry import counters, histograms, snapshot, spans
from bifrost_tpu.telemetry.exporter import RateTracker
from tests.util import NumpySourceBlock, GatherSink, simple_header

NT = 8


def _hdr(nf=4):
    return simple_header([-1, nf], 'f32', labels=['time', 'freq'])


def _gulps(n=4, nf=4):
    return [np.full((NT, nf), float(k), dtype=np.float32)
            for k in range(n)]


@pytest.fixture(autouse=True)
def clean_telemetry(monkeypatch, tmp_path):
    # never warm-start from (or dump into) a stray CWD profile
    monkeypatch.setenv('BF_AUTOTUNE_PROFILE',
                       str(tmp_path / 'profile.json'))
    # keep this file's many built-but-never-run pipelines out of the
    # process-shared proclog tree other tests walk
    monkeypatch.setenv('BF_PROCLOG_DIR', str(tmp_path / 'proclog'))
    counters.reset()
    histograms.reset()
    spans.reset()
    yield
    counters.reset()
    histograms.reset()
    spans.reset()


# ---------------------------------------------------------------------------
# snapshot(rates=...) — satellite 1
# ---------------------------------------------------------------------------

def test_rate_tracker_derives_per_second_rates():
    tr = RateTracker()
    first = tr.observe({'a': 10}, {'h': {'count': 2, 'sum': 0.5}})
    assert first['dt'] is None and not first['counters']
    time.sleep(0.05)
    out = tr.observe({'a': 30}, {'h': {'count': 6, 'sum': 1.5}})
    assert out['dt'] > 0
    assert out['counters']['a'] == pytest.approx(20 / out['dt'],
                                                rel=0.01)
    h = out['histograms']['h']
    assert h['count_per_s'] == pytest.approx(4 / out['dt'], rel=0.01)
    assert h['sum_per_s'] == pytest.approx(1.0 / out['dt'], rel=0.01)


def test_rate_tracker_clamps_counter_resets():
    tr = RateTracker()
    tr.observe({'a': 100}, {})
    time.sleep(0.02)
    out = tr.observe({'a': 3}, {})   # counters.reset() happened
    assert out['counters']['a'] == 0.0


def test_snapshot_rates_integration():
    counters.inc('rt.test_counter', 5)
    tr = RateTracker()
    s1 = snapshot(rates=tr)
    assert s1['rates']['dt'] is None
    counters.inc('rt.test_counter', 10)
    time.sleep(0.02)
    s2 = snapshot(rates=tr)
    assert s2['rates']['dt'] > 0
    assert s2['rates']['counters']['rt.test_counter'] > 0
    # rates=False leaves the key out entirely
    assert 'rates' not in snapshot()


# ---------------------------------------------------------------------------
# knob plumbing precedence — satellite: scope > kwarg > env
# ---------------------------------------------------------------------------

def test_sync_depth_precedence(monkeypatch):
    monkeypatch.setenv('BF_SYNC_DEPTH', '7')
    with bf.Pipeline() as p_env:
        assert resolve_sync_depth(p_env) == 7
    with bf.Pipeline(sync_depth=3) as p_kw:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        with bf.block_scope(sync_depth=2):
            b = bf.blocks.copy(src, space='system')
        assert resolve_sync_depth(p_kw) == 3       # kwarg beats env
        assert resolve_sync_depth(b) == 2          # scope beats kwarg
        assert resolve_sync_depth(src) == 3        # sibling unaffected


def test_sync_depth_runtime_retune():
    """The controller's write path: mutating the pipeline tunable is
    picked up by the next resolve (what makes the knob retunable at
    runtime — resolve_sync_depth is read per gulp)."""
    with bf.Pipeline(sync_depth=2) as p:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='system')
        assert resolve_sync_depth(b) == 2
        p._sync_depth = 8
        assert resolve_sync_depth(b) == 8


def test_gulp_batch_precedence(monkeypatch):
    monkeypatch.setenv('BF_GULP_BATCH', '4')
    with bf.Pipeline() as p_env:
        assert resolve_gulp_batch(p_env) == 4
    with bf.Pipeline(gulp_batch=2) as p_kw:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        with bf.block_scope(gulp_batch=8):
            b = bf.blocks.copy(src, space='system')
        assert resolve_gulp_batch(p_kw) == 2
        assert resolve_gulp_batch(b) == 8
        assert resolve_gulp_batch(src) == 2
        # the retune helper writes the PIPELINE scope: the block that
        # pinned its own value keeps it
        retune_gulp_batch(p_kw, 16)
        assert resolve_gulp_batch(p_kw) == 16
        assert resolve_gulp_batch(src) == 16
        assert resolve_gulp_batch(b) == 8


def test_bridge_window_and_streams_precedence(monkeypatch):
    from bifrost_tpu.io.bridge import bridge_window, bridge_streams
    monkeypatch.setenv('BF_BRIDGE_WINDOW', '6')
    monkeypatch.setenv('BF_BRIDGE_STREAMS', '3')
    assert bridge_window() == 6
    assert bridge_streams() == 3
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        from bifrost_tpu.blocks.bridge import bridge_sink
        b_env = bridge_sink(src, '127.0.0.1', 1)
        b_kw = bridge_sink(src, '127.0.0.1', 2, window=9, nstreams=2)
    assert b_env.window == 6 and b_env.nstreams == 3
    assert b_kw.window == 9 and b_kw.nstreams == 2
    # runtime retune (the controller's write path, no live sender)
    assert b_kw.retune_window(12) == 12
    assert b_kw.window == 12


def test_ring_buffering_precedence():
    with bf.Pipeline(buffer_factor=5) as p:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        with bf.block_scope(buffer_factor=2, buffer_nframe=64):
            b = bf.blocks.copy(src, space='system')
        assert p.buffer_factor == 5
        assert b.buffer_factor == 2          # scope beats kwarg
        assert b.buffer_nframe == 64
        assert src.buffer_factor == 5        # inherits the pipeline


# ---------------------------------------------------------------------------
# the knob state machine (deterministic: no controller thread)
# ---------------------------------------------------------------------------

def _pipeline():
    with bf.Pipeline(name='tune_test_%d' % int(time.time() * 1e6)) \
            as p:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='system')
        GatherSink(b)
    return p


def _snap_for_batch(disp=10.0, gulps=10.0):
    return {'rates': {'dt': 1.0, 'counters': {
        'block.x.dispatches': disp, 'block.x.gulps': gulps},
        'histograms': {}}, 'rings': {}, 'histograms': {}}


def test_gulp_batch_knob_climbs_geometrically():
    p = _pipeline()
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs if k.name == 'gulp_batch')
    assert knob.read() == 1
    knob.tick(_snap_for_batch(), objective=100.0)
    assert knob.read() == 2                  # doubled
    assert knob.cooldown == tuner.cooldown_ticks
    for _ in range(tuner.cooldown_ticks):
        knob.tick(_snap_for_batch(), objective=100.0)
    # improved objective: the climb continues
    knob.tick(_snap_for_batch(gulps=20.0), objective=120.0)
    assert knob.read() == 4
    assert counters.snapshot()['autotune.retunes'] == 2


def test_step_without_baseline_is_kept_not_pinned():
    """A step taken before the objective window has a baseline
    (objective None on the first live tick) is unjudgeable: it must
    be KEPT without marking the knob converged — judging 'unknown' as
    gain=0 would falsely pin every first-tick step at one doubling."""
    p = _pipeline()
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs if k.name == 'gulp_batch')
    knob.tick(_snap_for_batch(), objective=None)
    assert knob.read() == 2                  # stepped, baseline None
    for _ in range(tuner.cooldown_ticks):
        knob.tick(_snap_for_batch(), objective=100.0)
    # evaluation tick: unjudgeable step is kept, knob stays live and
    # the climb continues against the now-live baseline
    knob.tick(_snap_for_batch(gulps=20.0), objective=100.0)
    assert not knob.converged
    assert knob.read() == 4


def test_knob_reverts_when_step_hurts():
    p = _pipeline()
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs if k.name == 'gulp_batch')
    knob.tick(_snap_for_batch(), objective=100.0)
    assert knob.read() == 2
    for _ in range(tuner.cooldown_ticks):
        knob.tick(_snap_for_batch(), objective=100.0)
    knob.tick(_snap_for_batch(), objective=50.0)   # regression
    assert knob.read() == 1                  # reverted
    assert knob.converged
    assert counters.snapshot()['autotune.reverts'] == 1


def test_knob_pins_when_gain_below_threshold():
    p = _pipeline()
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs if k.name == 'gulp_batch')
    knob.tick(_snap_for_batch(), objective=100.0)
    for _ in range(tuner.cooldown_ticks):
        knob.tick(_snap_for_batch(), objective=100.0)
    knob.tick(_snap_for_batch(), objective=100.5)  # < min_gain
    assert knob.read() == 2                  # kept, but pinned
    assert knob.converged


def test_knob_holds_evaluation_through_traffic_lull():
    """A zero/None objective (sequence boundary, compile pause) must
    not spuriously revert a pending step — the knob holds and judges
    at the next live tick."""
    p = _pipeline()
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs if k.name == 'gulp_batch')
    knob.tick(_snap_for_batch(), objective=100.0)
    for _ in range(tuner.cooldown_ticks):
        knob.tick(_snap_for_batch(), objective=100.0)
    knob.tick(_snap_for_batch(), objective=0.0)    # lull
    assert knob.read() == 2 and not knob.converged
    knob.tick(_snap_for_batch(), objective=None)   # still quiet
    knob.tick(_snap_for_batch(gulps=20.0), objective=150.0)
    assert not knob.converged                # judged against 100: keep


def test_sync_depth_knob_uses_hard_wait_rate():
    p = _pipeline()
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs if k.name == 'sync_depth')
    quiet = {'rates': {'dt': 1.0, 'counters': {
        'pipeline.gulps_device': 100.0, 'pipeline.sync_waits': 0.0}},
        'rings': {}, 'histograms': {}}
    knob.tick(quiet, objective=100.0)
    before = knob.read()
    # the xfer depth-bound stalls count as hard waits too
    busy = {'rates': {'dt': 1.0, 'counters': {
        'pipeline.gulps_device': 100.0, 'pipeline.sync_waits': 4.0,
        'xfer.depth_waits': 4.0}}, 'rings': {}, 'histograms': {}}
    knob.tick(busy, objective=100.0)
    assert knob.read() == before * 2


def test_ring_knob_grows_through_deferred_resize():
    p = _pipeline()
    tuner = AutoTuner(p, mode='on')
    ring_knobs = [k for k in tuner.knobs
                  if k.name.startswith('ring_bytes.')]
    assert ring_knobs
    knob = ring_knobs[0]
    knob.ring.resize(256)                    # known starting geometry
    before = knob.read()
    snap = {'rates': {'dt': 1.0, 'counters': {},
                      'histograms': {
                          'ring.%s.reserve_s' % knob.ring.name:
                          {'count_per_s': 50.0, 'sum_per_s': 0.01}}},
            'rings': {knob.ring.name: {'fill': 0.99}},
            'histograms': {}}
    knob.tick(snap, objective=100.0)
    assert knob.read() >= before * 2         # grew (quiescent: applied)
    assert not knob.reversible               # rings never shrink


def test_ring_floor_clamps_to_verifier_bound():
    """The BF-E101 deadlock bound is a hard floor: the capacity knob's
    write path clamps every target UP to it, so the controller can
    never tune a ring below what the static analyzer requires."""
    from bifrost_tpu.analysis import verify
    p = _pipeline()
    floors = verify.ring_capacity_floors(p)
    assert floors                            # provable on this chain
    for name, f in floors.items():
        assert f['frames'] >= f['writer_span']
        assert f['frames'] == f['writer_span'] + f['max_pin']
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs
                if k.name.startswith('ring_bytes.')
                and tuner.ring_floor_bytes(k.ring.name))
    floor = tuner.ring_floor_bytes(knob.ring.name)
    knob.write(1)                            # absurdly small target
    assert knob.ring.total_span >= floor


def test_verifier_gate_blocks_error_introducing_step(monkeypatch):
    from bifrost_tpu.analysis import verify
    p = _pipeline()
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs if k.name == 'gulp_batch')
    baseline = verify.verify_pipeline(p)

    def fake_verify(pipeline):
        return baseline + [verify.Diagnostic(
            'BF-E101', 'ring too small for the candidate K',
            block='x', ring='r')]
    monkeypatch.setattr(verify, 'verify_pipeline', fake_verify)
    tuner._baseline_diags = baseline
    knob.tick(_snap_for_batch(), objective=100.0)
    assert knob.read() == 1                  # step refused
    assert knob.converged
    assert counters.snapshot()['autotune.rejected'] == 1
    assert 'autotune.retunes' not in counters.snapshot()


def test_scope_overrides_shape_verdict_without_mutation(monkeypatch):
    """verify.scope_overrides evaluates a candidate tunable without
    touching the live configuration: the override shapes the verdict
    on the calling thread only, and root-level K candidates do not
    displace a block's own pinned value (mirroring what
    retune_gulp_batch would actually write)."""
    from bifrost_tpu.analysis import verify
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        with bf.block_scope(gulp_batch=2):
            pinned = bf.blocks.copy(src, space='system')
        free = bf.blocks.copy(src, space='system')
        from bifrost_tpu.blocks.bridge import bridge_sink
        snk = bridge_sink(free, '127.0.0.1', 1, window=2)
    base = verify.verify_pipeline(p)
    assert 'BF-E150' not in [d.code for d in base]
    with verify.scope_overrides({'bridge_window': {snk.name: 0}}):
        cand = verify.verify_pipeline(p)
    assert 'BF-E150' in [d.code for d in cand]
    assert snk.window == 2                   # live config untouched
    with verify.scope_overrides({'gulp_batch': 16}):
        assert verify._static_k_requested(free) == 16
        assert verify._static_k_requested(pinned) == 2   # pin wins
        # the live resolution is untouched even inside the context
        assert resolve_gulp_batch(free) == 1
    assert resolve_gulp_batch(free) == 1


def test_verifier_gate_never_mutates_live_pipeline(monkeypatch):
    """The gate runs the verifier with the candidate supplied through
    the thread-local scope_overrides seam: a block thread resolving
    tunables concurrently with the gate can never observe the
    candidate value (the retune itself happens later, through the
    knob's write path)."""
    from bifrost_tpu.analysis import verify
    p = _pipeline()
    tuner = AutoTuner(p, mode='on')
    tuner._baseline_diags = verify.verify_pipeline(p)
    seen = []
    real = verify.verify_pipeline

    def spying_verify(pipeline):
        # what a concurrently-running block thread would resolve
        seen.append(resolve_gulp_batch(pipeline))
        return real(pipeline)
    monkeypatch.setattr(verify, 'verify_pipeline', spying_verify)
    assert tuner._verifier_allows('_gulp_batch', 16)
    assert seen == [1]                       # live value, not 16
    assert p.__dict__.get('_gulp_batch') is None


def test_new_errors_vs_ignores_preexisting():
    from bifrost_tpu.analysis import verify
    e = verify.Diagnostic('BF-E101', 'old', block='b', ring='r')
    w = verify.Diagnostic('BF-W102', 'warn', block='b', ring='r')
    e2 = verify.Diagnostic('BF-E101', 'new', block='b2', ring='r2')
    assert verify.new_errors_vs([e], [e, w]) == []
    out = verify.new_errors_vs([e], [e, e2])
    assert len(out) == 1 and out[0].block == 'b2'


# ---------------------------------------------------------------------------
# retune visibility: counter + proclog + span (acceptance criterion)
# ---------------------------------------------------------------------------

def test_retune_published_to_counters_proclog_and_spans(
        monkeypatch, tmp_path):
    monkeypatch.setenv('BF_PROCLOG_DIR', str(tmp_path / 'proclog'))
    monkeypatch.setenv('BF_TRACE_FILE', str(tmp_path / 'trace.json'))
    spans.reconfigure()
    try:
        p = _pipeline()
        tuner = AutoTuner(p, mode='on')
        knob = next(k for k in tuner.knobs if k.name == 'gulp_batch')
        knob.tick(_snap_for_batch(), objective=100.0)
        snap = counters.snapshot()
        assert snap['autotune.retunes'] == 1
        assert snap['autotune.gulp_batch'] == 2   # counter == value
        evs = [ev for _t, ev in spans.events()
               if ev[0] == 'autotune.retune']
        assert evs and evs[0][4]['knob'] == 'gulp_batch'
        assert evs[0][4]['to'] == 2
        log = tmp_path / 'proclog' / str(os.getpid()) / \
            'analysis' / 'autotune'
        text = log.read_text()
        assert 'knob.gulp_batch : 2' in text
        assert 'retune gulp_batch -> 2' in text
    finally:
        monkeypatch.delenv('BF_TRACE_FILE')
        spans.reconfigure()


# ---------------------------------------------------------------------------
# freeze profiles: dump + warm start
# ---------------------------------------------------------------------------

def test_freeze_dumps_profile_and_warm_starts(tmp_path, monkeypatch):
    path = tmp_path / 'frozen.json'
    monkeypatch.setenv('BF_AUTOTUNE_PROFILE', str(path))
    p = _pipeline()
    tuner = AutoTuner(p, mode='freeze')
    knob = next(k for k in tuner.knobs if k.name == 'gulp_batch')
    knob.tick(_snap_for_batch(), objective=100.0)
    assert knob.read() == 2
    tuner.stop(wait=False)                   # dumps even unconverged
    prof = json.loads(path.read_text())
    assert prof['knobs']['gulp_batch'] == 2
    assert 'ring_total_bytes' in prof['knobs']
    # a fresh pipeline + tuner warm-starts from the dumped profile
    p2 = _pipeline()
    assert resolve_gulp_batch(p2) == 1
    tuner2 = AutoTuner(p2, mode='on')
    assert tuner2._warm_started
    assert resolve_gulp_batch(p2) == 2


def test_warm_start_profile_is_verifier_gated(tmp_path, monkeypatch):
    """A stale profile (another topology / shared cwd) whose knobs
    would introduce a BF-E on THIS pipeline must not warm-start it:
    the same new_errors_vs gate every live retune passes applies at
    startup, and the rejection is counted."""
    from bifrost_tpu.analysis import verify
    prof_path = tmp_path / 'stale_profile.json'
    prof_path.write_text(json.dumps(
        {'version': 1, 'knobs': {'gulp_batch': 16}}))
    monkeypatch.setenv('BF_AUTOTUNE_PROFILE', str(prof_path))
    p = _pipeline()
    baseline = verify.verify_pipeline(p)

    def vetoing_verify(pipeline):
        if verify._overrides():
            return baseline + [verify.Diagnostic(
                'BF-E101', 'stale profile K deadlocks this ring',
                block='x', ring='r')]
        return baseline
    monkeypatch.setattr(verify, 'verify_pipeline', vetoing_verify)
    tuner = AutoTuner(p, mode='on')
    assert not tuner._warm_started
    assert resolve_gulp_batch(p) == 1        # profile NOT applied
    assert counters.snapshot()['autotune.rejected'] == 1
    # a harmless profile still warm-starts
    monkeypatch.setattr(verify, 'verify_pipeline',
                        lambda pipeline: baseline)
    tuner2 = AutoTuner(p, mode='on')
    assert tuner2._warm_started
    assert resolve_gulp_batch(p) == 16


def test_load_profile_rejects_garbage(tmp_path, monkeypatch):
    path = tmp_path / 'bad.json'
    monkeypatch.setenv('BF_AUTOTUNE_PROFILE', str(path))
    assert autotune.load_profile() is None   # absent
    path.write_text('not json')
    assert autotune.load_profile() is None
    path.write_text('{"no_knobs": 1}')
    assert autotune.load_profile() is None


def test_resolve_mode(monkeypatch):
    assert autotune.resolve_mode(True) == 'on'
    assert autotune.resolve_mode(False) == 'off'
    assert autotune.resolve_mode('freeze') == 'freeze'
    monkeypatch.setenv('BF_AUTOTUNE', '1')
    assert autotune.resolve_mode(None) == 'on'
    monkeypatch.setenv('BF_AUTOTUNE', 'freeze')
    assert autotune.resolve_mode(None) == 'freeze'
    monkeypatch.setenv('BF_AUTOTUNE', '0')
    assert autotune.resolve_mode(None) == 'off'
    monkeypatch.delenv('BF_AUTOTUNE')
    assert autotune.resolve_mode(None) == 'off'
    # an explicit run() argument overrides the environment
    monkeypatch.setenv('BF_AUTOTUNE', '1')
    assert autotune.resolve_mode(False) == 'off'


# ---------------------------------------------------------------------------
# end to end: a real pipeline under the controller thread
# ---------------------------------------------------------------------------

def test_autotune_pipeline_end_to_end(monkeypatch):
    monkeypatch.setenv('BF_AUTOTUNE_INTERVAL', '0.05')
    with bf.Pipeline() as p:
        gulps = [np.full((NT, 4), float(k), dtype=np.float32)
                 for k in range(40)]
        src = NumpySourceBlock(gulps, _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='system')
        sink = GatherSink(b)
        p.run(autotune=True)
    out = sink.result()
    assert out.shape == (40 * NT, 4)
    np.testing.assert_array_equal(out[NT:2 * NT], 1.0)
    snap = counters.snapshot()
    assert snap.get('autotune.ticks', 0) >= 1
    # the knob-value counters were published for every knob
    assert 'autotune.gulp_batch' in snap
    assert 'autotune.sync_depth' in snap


def test_autotune_off_by_default():
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        sink = GatherSink(bf.blocks.copy(src, space='system'))
        p.run()
    assert sink.result() is not None
    assert 'autotune.ticks' not in counters.snapshot()


# ---------------------------------------------------------------------------
# mprobe coin-flip staleness — satellite
# ---------------------------------------------------------------------------

def _seed_mprobe(fam, key, ms):
    from bifrost_tpu.ops import mprobe
    full_key = '%s|%s' % (mprobe.backend_tag(), key)
    mprobe._cache[fam] = {full_key: ('a', dict(ms), {})}
    mprobe._flip_uses.pop((fam, full_key), None)
    return full_key


def test_mprobe_coin_flip_winner_reraced(monkeypatch, tmp_path):
    from bifrost_tpu.ops import mprobe
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    monkeypatch.setenv('BF_MPROBE_REPROBE', '3')
    calls = {'a': 0, 'b': 0}

    def make(name):
        def fn(x):
            calls[name] += 1
            return x
        return fn
    cands = {'a': make('a'), 'b': make('b')}
    # margin 1.05 < noise 1.10: a COIN-FLIP ranking
    _seed_mprobe('flip_fam', 'k1', {'a': 1.0, 'b': 1.05})
    for _ in range(2):               # uses 1-2: served from cache
        w, ms, _e = mprobe.select('flip_fam', 'k1', cands,
                                  lambda: (np.ones(4, np.float32),))
        assert w == 'a' and calls['a'] == 0
    # use 3: budget spent — the entry is evicted and RE-RACED
    w, ms, _e = mprobe.select('flip_fam', 'k1', cands,
                              lambda: (np.ones(4, np.float32),))
    assert calls['a'] > 0 and calls['b'] > 0
    assert w in ('a', 'b')


def test_mprobe_decisive_winner_never_reraced(monkeypatch, tmp_path):
    from bifrost_tpu.ops import mprobe
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    monkeypatch.setenv('BF_MPROBE_REPROBE', '2')
    calls = {'n': 0}

    def fn(x):
        calls['n'] += 1
        return x
    cands = {'a': fn, 'b': fn}
    _seed_mprobe('dec_fam', 'k1', {'a': 1.0, 'b': 2.0})  # decisive
    for _ in range(10):
        w, _ms, _e = mprobe.select('dec_fam', 'k1', cands,
                                   lambda: (np.ones(4, np.float32),))
        assert w == 'a'
    assert calls['n'] == 0


def test_mprobe_reprobe_disabled_with_zero_budget(monkeypatch,
                                                  tmp_path):
    from bifrost_tpu.ops import mprobe
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    monkeypatch.setenv('BF_MPROBE_REPROBE', '0')
    calls = {'n': 0}

    def fn(x):
        calls['n'] += 1
        return x
    cands = {'a': fn, 'b': fn}
    _seed_mprobe('off_fam', 'k1', {'a': 1.0, 'b': 1.05})
    for _ in range(10):
        w, _ms, _e = mprobe.select('off_fam', 'k1', cands,
                                   lambda: (np.ones(4, np.float32),))
        assert w == 'a'
    assert calls['n'] == 0


def test_mprobe_disk_coin_flip_reraced(monkeypatch, tmp_path):
    """A coin-flip winner persisted on DISK (older pre-decisive
    policy) must also hit the reprobe budget: the eviction must not
    reload the same entry from disk with a fresh budget."""
    from bifrost_tpu.ops import mprobe
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    monkeypatch.setenv('BF_MPROBE_REPROBE', '2')
    full_key = '%s|%s' % (mprobe.backend_tag(), 'k1')
    path = mprobe.cache_path('disk_fam')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w') as f:
        json.dump({full_key: {'winner': 'a',
                              'ms': {'a': 1.0, 'b': 1.05}}}, f)
    mprobe._cache.pop('disk_fam', None)
    mprobe._flip_uses.pop(('disk_fam', full_key), None)
    calls = {'a': 0, 'b': 0}

    def make(name):
        def fn(x):
            calls[name] += 1
            return x
        return fn
    cands = {'a': make('a'), 'b': make('b')}
    # use 1: served from disk, budgeted
    w, _ms, _e = mprobe.select('disk_fam', 'k1', cands,
                               lambda: (np.ones(4, np.float32),))
    assert w == 'a' and calls['a'] == 0
    # use 2: budget spent — evicted AND the disk copy must not be
    # reloaded; the candidates are actually re-raced
    w, _ms, _e = mprobe.select('disk_fam', 'k1', cands,
                               lambda: (np.ones(4, np.float32),))
    assert calls['a'] > 0 and calls['b'] > 0


# ---------------------------------------------------------------------------
# structural (topology-hash) freeze profiles — rename portability
# ---------------------------------------------------------------------------

def test_topology_signature_ignores_names():
    p1, p2 = _pipeline(), _pipeline()
    s1 = autotune.topology_signature(p1)
    s2 = autotune.topology_signature(p2)
    # two builds of the same topology share the hash even though
    # every ring/block NAME differs (instance counters are global)
    assert s1[0] == s2[0]
    # renaming a ring changes neither the hash nor its structural key
    ring = p1.blocks[1].orings[0]
    base = getattr(ring, '_base_ring', ring)
    old = base.name
    base.name = 'renamed_ring'
    s1b = autotune.topology_signature(p1)
    assert s1b[0] == s1[0]
    assert s1b[2]['renamed_ring'] == s1[2][old]


def test_profile_v2_is_structurally_keyed_and_portable():
    p = _pipeline()
    tuner = AutoTuner(p, mode='freeze')
    retune_gulp_batch(p, 8)
    prof = tuner._dump_profile()
    assert prof['version'] == 2
    assert prof['topology'] == autotune.topology_signature(p)[0]
    # per-ring knobs key by structural role, never positional name
    rkeys = list(prof['knobs']['ring_total_bytes'])
    assert rkeys and all('#' in k and '.out' in k for k in rkeys)
    # a FRESH build of the same topology — different ring/block names
    # throughout — still receives every knob
    p2 = _pipeline()
    applied = autotune.apply_profile(p2, prof)
    assert applied['gulp_batch'] == 8
    assert resolve_gulp_batch(p2) == 8


def test_profile_v1_name_keys_still_apply():
    p = _pipeline()
    ring = getattr(p.blocks[1].orings[0], '_base_ring',
                   p.blocks[1].orings[0])
    prof = {'version': 1, 'knobs': {
        'gulp_batch': 4,
        'ring_total_bytes': {ring.name: ring.total_span}}}
    applied = autotune.apply_profile(p, prof)
    assert applied['gulp_batch'] == 4
    assert resolve_gulp_batch(p) == 4


# ---------------------------------------------------------------------------
# the bridge stripe-count knob (BF_BRIDGE_STREAMS online)
# ---------------------------------------------------------------------------

def _bridge_pipeline():
    from bifrost_tpu.blocks.bridge import bridge_sink
    with bf.Pipeline(name='tune_streams_%d'
                          % int(time.time() * 1e6)) as p:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        b = bridge_sink(src, '127.0.0.1', 1, window=1, nstreams=1)
    return p, b


def _stall_snap(sink_name, stall=0.5):
    return {'rates': {'dt': 1.0, 'counters': {},
                      'histograms': {
                          'bridge.%s.send_stall_s' % sink_name:
                              {'sum_per_s': stall}}},
            'rings': {}, 'histograms': {}}


def test_bridge_streams_knob_sequences_after_window_and_reverts():
    p, sink = _bridge_pipeline()
    tuner = AutoTuner(p, mode='on')
    wknob = next(k for k in tuner.knobs
                 if k.name.startswith('bridge_window'))
    sknob = next(k for k in tuner.knobs
                 if k.name.startswith('bridge_streams'))
    snap = _stall_snap(sink.name)
    # stalled, but the window knob has not converged: stripes hold
    sknob.tick(snap, objective=100.0)
    assert sknob.read() == 1 and sink.nstreams == 1
    wknob.converged = True
    sknob.tick(snap, objective=100.0)
    assert sknob.read() == 2 and sink.nstreams == 2
    for _ in range(tuner.cooldown_ticks):
        sknob.tick(snap, objective=100.0)
    # the extra stripe HURT (loopback): revert re-narrows and pins
    sknob.tick(snap, objective=10.0)
    assert sknob.read() == 1 and sink.nstreams == 1
    assert sknob.converged
    assert counters.get('autotune.reverts') >= 1


def test_retune_streams_plumbing_without_live_sender():
    _p, sink = _bridge_pipeline()
    assert sink.retune_streams(4) == 4
    assert sink.nstreams == 4
    assert sink.retune_streams(0) == 1       # clamps


# ---------------------------------------------------------------------------
# the segment split/re-fuse knob
# ---------------------------------------------------------------------------

def _segment_pipeline():
    from bifrost_tpu import segments as bseg
    with bf.Pipeline(name='tune_seg_%d' % int(time.time() * 1e6),
                     segments='auto') as p:
        src = NumpySourceBlock(_gulps(), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fftshift(b, 'freq')
        b = bf.blocks.fftshift(b, 'freq')
        GatherSink(bf.blocks.copy(b, space='system'))
    segs = bseg.compile_pipeline(p)
    assert len(segs) == 1
    return p, segs[0]


def _segment_snap(seg, rate=5.0):
    return {'rates': {'dt': 1.0,
                      'counters': {'block.%s.dispatches'
                                   % seg.name: rate},
                      'histograms': {}},
            'rings': {}, 'histograms': {}}


def test_segment_split_knob_probes_then_refuses():
    p, seg = _segment_pipeline()
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs
                if k.name.startswith('segment_split'))
    assert knob.read() == 0
    knob.tick(_segment_snap(seg), objective=100.0)
    assert knob.read() == 1                  # probed one split
    # the split lands at the next sequence; emulate engagement
    seg._splits_active = 1
    for _ in range(tuner.cooldown_ticks):
        knob.tick(_segment_snap(seg), objective=100.0)
    knob.tick(_segment_snap(seg), objective=50.0)   # the split HURT
    assert knob.read() == 0                  # reverted == re-fused
    assert knob.converged
    assert counters.get('autotune.reverts') >= 1


def test_segment_split_knob_requires_traffic():
    p, seg = _segment_pipeline()
    tuner = AutoTuner(p, mode='on')
    knob = next(k for k in tuner.knobs
                if k.name.startswith('segment_split'))
    knob.tick(_segment_snap(seg, rate=0.0), objective=100.0)
    assert knob.read() == 0                  # no segment traffic yet


def test_profile_v2_carries_segment_and_stream_knobs():
    p, seg = _segment_pipeline()
    from bifrost_tpu import segments as bseg
    bseg.retune_split(seg, 1)
    tuner = AutoTuner(p, mode='freeze')
    prof = tuner._dump_profile()
    key = [k for k in prof['knobs'].get('segment_split', {})]
    assert key and key[0].startswith('SegmentBlock#')
    assert prof['knobs']['segment_split'][key[0]] == 1
    # a fresh build receives the split through the structural key
    p2, seg2 = _segment_pipeline()
    autotune.apply_profile(p2, prof)
    assert seg2._segment_split == 1
