"""Fleet observability plane (bifrost_tpu.telemetry.fleet —
docs/observability.md "Fleet plane"): wire round-trips, delta
compactness, collector restart resync, staleness/death marking,
alert-rule edge cases (unknown vs dead, hysteresis), the incident
black box, and the tool surfaces (trace_merge, like_top, Prometheus
export)."""

import json
import os
import socket
import subprocess
import sys
import zlib

import pytest

from bifrost_tpu.telemetry import counters, fleet, histograms

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, 'tools')


@pytest.fixture(autouse=True)
def _clean_fleet_state(monkeypatch):
    for var in ('BF_FLEET_COLLECTOR', 'BF_FLEET_HOST',
                'BF_FLEET_INTERVAL', 'BF_FLEET_FULL_EVERY',
                'BF_FLEET_DEADLINE', 'BF_FLEET_HISTORY',
                'BF_FLEET_ROLLUP_FILE', 'BF_FLEET_PROM_FILE',
                'BF_FLEET_INCIDENT_DIR', 'BF_FLEET_INCIDENT_COOLDOWN',
                'BF_FLEET_SETTLE', 'BF_ALERT_RULES', 'BF_ALERT_LOG',
                'BF_ALERT_WEBHOOK'):
        monkeypatch.delenv(var, raising=False)
    counters.reset()
    histograms.reset()
    yield
    counters.reset()
    histograms.reset()


def make_collector(**kw):
    """An un-started collector: tests feed messages synchronously via
    _handle/tick, no threads or timing races."""
    kw.setdefault('bind', ('127.0.0.1', 0))
    kw.setdefault('interval', 0.1)
    kw.setdefault('deadline', 5.0)
    kw.setdefault('rules', [])
    return fleet.FleetCollector(**kw)


def make_publisher(coll, **kw):
    """An un-started publisher aimed at ``coll``; its messages are
    captured AND pushed straight into the collector, skipping UDP."""
    kw.setdefault('interval', 0.1)
    kw.setdefault('host', 'h1')
    pub = fleet.FleetPublisher(
        collector=('127.0.0.1', coll.port), **kw)
    sent = []
    orig_send = pub._send

    def send_and_feed(msg):
        sent.append(json.loads(json.dumps(msg)))
        coll._handle(json.loads(json.dumps(msg)),
                     pub._sock.getsockname())
        orig_send(msg)
    pub._send = send_and_feed
    pub._sent = sent
    return pub


def full_msg(host='h1', session='s1', seq=1, cnts=None, **extra):
    msg = {'t': 'full', 'host': host, 'session': session, 'seq': seq,
           'wall_ns': 1000000000000, 'mono_us': 1000.0,
           'counters': dict(cnts or {}), 'histograms': {},
           'rings': {}, 'health': {}, 'tenants': {}, 'scheduler': {},
           'identity': {'pid': 42}}
    msg.update(extra)
    return msg


def delta_msg(host='h1', session='s1', seq=2, cnts=None, **extra):
    msg = {'t': 'delta', 'host': host, 'session': session, 'seq': seq,
           'wall_ns': 1000000000000, 'mono_us': 2000.0,
           'counters': dict(cnts or {}), 'histograms': {},
           'rings': {}, 'health': {}, 'tenants': {}, 'scheduler': {}}
    msg.update(extra)
    return msg


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def test_parse_collector_addr():
    assert fleet.parse_collector_addr('10.0.0.7:9123') == \
        ('10.0.0.7', 9123)
    assert fleet.parse_collector_addr(':9123') == ('127.0.0.1', 9123)
    assert fleet.parse_collector_addr('') is None
    assert fleet.parse_collector_addr('nope') is None
    assert fleet.parse_collector_addr('h:x') is None
    assert fleet.parse_collector_addr() is None   # env unset


def test_wire_roundtrip_single_frame():
    msg = {'t': 'full', 'host': 'h1', 'n': 3}
    frames = fleet._encode(msg, 7)
    assert len(frames) == 1
    r = fleet._Reassembler()
    assert r.feed(frames[0], ('127.0.0.1', 1)) == msg


def test_wire_roundtrip_chunked_out_of_order():
    # incompressible payload forces chunking past the 60000B cap
    blob = os.urandom(90000).hex()
    msg = {'t': 'full', 'host': 'h1', 'pad': blob}
    frames = fleet._encode(msg, 9)
    assert len(frames) >= 2
    r = fleet._Reassembler()
    out = None
    for frame in reversed(frames):
        got = r.feed(frame, ('127.0.0.1', 1))
        if got is not None:
            out = got
    assert out == msg
    assert not r._parts


def test_reassembler_rejects_corrupt_frames():
    r = fleet._Reassembler()
    with pytest.raises(ValueError):
        r.feed(b'xx', ('127.0.0.1', 1))
    frame = fleet._encode({'a': 1}, 1)[0]
    with pytest.raises(ValueError):
        r.feed(b'NOPE' + frame[4:], ('127.0.0.1', 1))
    with pytest.raises(zlib.error):
        r.feed(frame[:fleet._HEADER.size] + b'garbage',
               ('127.0.0.1', 1))


# ---------------------------------------------------------------------------
# publisher -> collector round-trip
# ---------------------------------------------------------------------------

def test_full_then_delta_roundtrip_and_compactness():
    coll = make_collector()
    pub = make_publisher(coll, full_every=10)
    try:
        counters.inc('app.work', 5)
        pub.publish()                       # seq 1: forced full
        assert pub._sent[0]['t'] == 'full'
        assert pub._sent[0]['counters']['app.work'] == 5
        assert 'identity' in pub._sent[0]
        assert 'flight' in pub._sent[0]

        counters.inc('app.work', 2)
        pub.publish()                       # seq 2: delta
        d = pub._sent[1]
        assert d['t'] == 'delta'
        # delta carries ONLY changed counters — with CUMULATIVE values
        assert d['counters']['app.work'] == 7
        assert all(k.startswith(('app.', 'fleet.'))
                   for k in d['counters'])
        assert 'identity' not in d

        r = coll.rollup()
        assert r['hosts']['h1']['fresh']
        assert r['hosts']['h1']['counters']['app.work'] == 7
        assert r['counters']['app.work'] == 7   # summed, not doubled
        assert counters.get('fleet.fulls_rx') == 1
        assert counters.get('fleet.deltas_rx') == 1
        assert counters.get('fleet.hosts_adopted') == 1
    finally:
        pub._sock.close()
        coll._sock.close()


def test_unchanged_counters_stay_off_the_delta_wire():
    coll = make_collector()
    pub = make_publisher(coll, full_every=10)
    try:
        counters.inc('app.static', 3)
        counters.inc('app.moving', 1)
        pub.publish()
        counters.inc('app.moving', 1)
        pub.publish()
        d = pub._sent[1]
        assert d['t'] == 'delta'
        assert 'app.static' not in d['counters']
        assert d['counters']['app.moving'] == 2
    finally:
        pub._sock.close()
        coll._sock.close()


def test_full_every_forces_periodic_fulls():
    coll = make_collector()
    pub = make_publisher(coll, full_every=2)
    try:
        for _ in range(4):
            pub.publish()
        kinds = [m['t'] for m in pub._sent]
        assert kinds == ['full', 'delta', 'full', 'delta']
    finally:
        pub._sock.close()
        coll._sock.close()


# ---------------------------------------------------------------------------
# collector restart: re-adoption without double-counting
# ---------------------------------------------------------------------------

def test_collector_restart_readopts_without_double_count():
    coll1 = make_collector()
    pub = make_publisher(coll1, full_every=100)
    try:
        counters.inc('app.work', 10)
        pub.publish()                        # full into collector 1
        counters.inc('app.work', 1)
        pub.publish()                        # delta into collector 1
        assert coll1.rollup()['counters']['app.work'] == 11
    finally:
        coll1._sock.close()

    # the collector restarts; the publisher keeps streaming deltas
    coll2 = make_collector()
    pub2_addr = pub._sock.getsockname()
    try:
        counters.inc('app.work', 1)
        nf0 = counters.get('fleet.need_full_tx')
        # feed the NEXT delta to the fresh collector: unknown session
        # -> it must refuse the delta and ask for a full
        pub._send = lambda m: coll2._handle(
            json.loads(json.dumps(m)), pub2_addr)
        pub.publish()
        assert 'h1' not in coll2.rollup()['hosts']
        assert counters.get('fleet.need_full_tx') == nf0 + 1
        # the publisher answers with a cumulative full: adopted clean
        pub._handle_request({'t': 'need_full'})
        pub.publish()
        r = coll2.rollup()
        assert r['hosts']['h1']['counters']['app.work'] == 12
        assert r['counters']['app.work'] == 12   # NOT 23
        assert counters.get('fleet.pub.full_requests') == 1
    finally:
        pub._sock.close()
        coll2._sock.close()


def test_seq_gap_triggers_resync_request():
    coll = make_collector()
    addr = ('127.0.0.1', 50000)
    coll._handle(full_msg(seq=1, cnts={'a': 1}), addr)
    nf0 = counters.get('fleet.need_full_tx')
    coll._handle(delta_msg(seq=3, cnts={'a': 3}), addr)   # 2 was lost
    assert counters.get('fleet.need_full_tx') == nf0 + 1
    # the gapped delta still applied (cumulative values are safe)
    assert coll.rollup()['hosts']['h1']['counters']['a'] == 3
    coll._sock.close()


def test_session_change_is_a_publisher_restart():
    coll = make_collector()
    addr = ('127.0.0.1', 50001)
    coll._handle(full_msg(session='s1', seq=5, cnts={'a': 5}), addr)
    coll._handle(full_msg(session='s2', seq=1, cnts={'a': 1}), addr)
    r = coll.rollup()['hosts']['h1']
    assert r['session'] == 's2'
    assert r['counters']['a'] == 1
    assert counters.get('fleet.hosts_adopted') == 2
    coll._sock.close()


# ---------------------------------------------------------------------------
# staleness, death, and the hosts_live level
# ---------------------------------------------------------------------------

def test_staleness_marking_and_live_level():
    coll = make_collector(deadline=1.0)
    addr = ('127.0.0.1', 50002)
    coll._handle(full_msg(), addr)
    now = coll._hosts['h1'].last_seen
    coll.tick(now=now + 0.5)
    assert counters.get('fleet.hosts_live') == 1
    assert not coll.rollup()['hosts']['h1']['stale']
    coll.tick(now=now + 2.0)
    r = coll.rollup()
    assert r['hosts']['h1']['stale']
    assert not r['hosts']['h1']['dead']      # stale alone != dead
    assert r['fleet']['hosts_stale'] == ['h1']
    assert counters.get('fleet.hosts_live') == 0
    assert counters.get('fleet.hosts_dead') == 0
    coll._sock.close()


def test_stale_plus_final_is_dead():
    coll = make_collector(deadline=1.0)
    addr = ('127.0.0.1', 50003)
    coll._handle(full_msg(final=True), addr)
    now = coll._hosts['h1'].last_seen
    coll.tick(now=now + 2.0)
    r = coll.rollup()
    assert r['hosts']['h1']['dead']
    assert r['fleet']['hosts_dead'] == ['h1']
    assert counters.get('fleet.hosts_dead') == 1
    coll.tick(now=now + 3.0)                 # counted once, not per tick
    assert counters.get('fleet.hosts_dead') == 1
    coll._sock.close()


class _FakeMembership(object):
    def __init__(self):
        self.dead = set()

    def is_dead(self, host):
        return host in self.dead

    def counts(self):
        return {'dead': sorted(self.dead)}


def test_membership_verdict_overrides_freshness():
    m = _FakeMembership()
    coll = make_collector(deadline=60.0, membership=m)
    addr = ('127.0.0.1', 50004)
    coll._handle(full_msg(), addr)
    coll.tick()
    assert not coll.rollup()['hosts']['h1']['dead']
    m.dead.add('h1')
    coll.tick()
    # dead on the fabric's verdict even though the stream is fresh
    assert coll.rollup()['hosts']['h1']['dead']
    assert counters.get('fleet.hosts_dead') == 1
    coll._sock.close()


# ---------------------------------------------------------------------------
# alert rules: validation, unknown vs dead, hysteresis
# ---------------------------------------------------------------------------

def test_load_rules_validation_errors():
    with pytest.raises(fleet.AlertRuleError):
        fleet.load_rules([{'kind': 'threshold', 'metric': 'a'}])
    with pytest.raises(fleet.AlertRuleError):
        fleet.load_rules([{'name': 'r', 'kind': 'nope'}])
    with pytest.raises(fleet.AlertRuleError):
        fleet.load_rules([{'name': 'r', 'kind': 'threshold'}])
    with pytest.raises(fleet.AlertRuleError):
        fleet.load_rules([{'name': 'r', 'kind': 'absence'}])
    with pytest.raises(fleet.AlertRuleError):
        fleet.load_rules([{'name': 'r', 'metric': 'a', 'op': '~'}])
    with pytest.raises(fleet.AlertRuleError):
        fleet.load_rules([{'name': 'r', 'metric': 'a',
                           'surprise': 1}])
    assert fleet.load_rules(None) == []
    rules = fleet.load_rules({'rules': [
        {'name': 'ok', 'metric': 'counters.x', 'op': '>',
         'value': 2}]})
    assert rules[0].name == 'ok' and rules[0].kind == 'threshold'


def test_load_rules_from_file_and_env(tmp_path, monkeypatch):
    path = tmp_path / 'rules.json'
    path.write_text(json.dumps({'rules': [
        {'name': 'f', 'kind': 'absence', 'host': 'h*'}]}))
    assert fleet.load_rules(str(path))[0].name == 'f'
    monkeypatch.setenv('BF_ALERT_RULES', str(path))
    assert fleet.load_rules()[0].name == 'f'


def test_absence_unknown_is_not_dead():
    """A literal host/tenant the collector has NEVER seen sits in
    'unknown' and never fires; a host that was seen and then died
    fires.  Mirrors Membership's never-seen-is-not-dead."""
    rules = fleet.load_rules([
        {'name': 'ghost', 'kind': 'absence', 'host': 'ghost',
         'for_ticks': 1},
        {'name': 'gone-t', 'kind': 'absence', 'tenant': 'never',
         'for_ticks': 1},
        {'name': 'gone-h', 'kind': 'absence', 'host': 'h1',
         'for_ticks': 1},
    ])
    coll = make_collector(deadline=1.0, rules=rules)
    addr = ('127.0.0.1', 50005)
    coll._handle(full_msg(), addr)
    now = coll._hosts['h1'].last_seen
    for i in range(3):
        coll.tick(now=now + 0.1 * i)
    st = coll.engine.status()
    assert st['ghost@host:ghost'] == 'unknown'
    assert st['gone-t@tenant:never'] == 'unknown'
    assert st['gone-h@host:h1'] == 'ok'
    assert counters.get('alerts.fired') == 0
    # h1 goes silent past the deadline: gone-h fires, ghost does not
    coll.tick(now=now + 5.0)
    st = coll.engine.status()
    assert st['gone-h@host:h1'] == 'firing'
    assert st['ghost@host:ghost'] == 'unknown'
    assert [e['name'] for e in coll.engine.history] == ['gone-h']
    assert counters.get('alerts.fired') == 1
    coll._sock.close()


def _rollup_with_value(v):
    return {'hosts': {'h1': {'fresh': True, 'stale': False,
                             'dead': False,
                             'counters': {'app.depth': v},
                             'histograms': {}, 'rings': {},
                             'tenants': {}}},
            'tenants': {}, 'tenants_seen': {'h1': 'h1'},
            'counters': {'app.depth': v}}


def test_threshold_hysteresis_across_flaps():
    """for_ticks/clear_ticks hysteresis: a metric flapping around the
    threshold fires ONCE and resolves ONCE — no flap storm."""
    eng = fleet.AlertEngine(fleet.load_rules([
        {'name': 'deep', 'metric': 'counters.app.depth', 'op': '>',
         'value': 10, 'for_ticks': 2, 'clear_ticks': 2}]))
    seq = [5, 15, 5, 15, 5,          # flapping: never 2 bad in a row
           15, 15,                   # sustained: fires on the 2nd
           15, 5, 15, 5,             # firing + flap: stays firing
           5, 5]                     # sustained good: resolves
    for i, v in enumerate(seq):
        eng.evaluate(_rollup_with_value(v), now=100.0 + i)
    events = [e['event'] for e in eng.history]
    assert events == ['FIRING', 'RESOLVED']
    assert counters.get('alerts.fired') == 1
    assert counters.get('alerts.resolved') == 1
    # repeat-bad ticks while firing were deduped, not re-fired
    assert counters.get('alerts.suppressed') >= 1


def test_delta_and_rate_rules_window():
    eng = fleet.AlertEngine(fleet.load_rules([
        {'name': 'burst', 'kind': 'delta',
         'metric': 'counters.app.depth', 'op': '>=', 'value': 20,
         'window_s': 10.0, 'for_ticks': 1},
        {'name': 'fast', 'kind': 'rate',
         'metric': 'counters.app.depth', 'op': '>', 'value': 100.0,
         'window_s': 10.0, 'for_ticks': 1}]))
    eng.evaluate(_rollup_with_value(0), now=100.0)
    eng.evaluate(_rollup_with_value(5), now=101.0)
    assert not eng.active()
    eng.evaluate(_rollup_with_value(30), now=102.0)
    assert [a['name'] for a in eng.active()] == ['burst']


def test_alert_log_sink(tmp_path):
    log = tmp_path / 'alerts.jsonl'
    eng = fleet.AlertEngine(fleet.load_rules([
        {'name': 'deep', 'metric': 'counters.app.depth', 'op': '>',
         'value': 10}]), log_path=str(log))
    eng.evaluate(_rollup_with_value(99), now=100.0)
    eng.evaluate(_rollup_with_value(0), now=101.0)
    lines = [json.loads(l) for l in log.read_text().splitlines()]
    assert [l['event'] for l in lines] == ['FIRING', 'RESOLVED']
    assert lines[0]['name'] == 'deep'
    assert lines[0]['instance'] == 'h1:counters.app.depth'


# ---------------------------------------------------------------------------
# incident black box
# ---------------------------------------------------------------------------

def _flight(n=3):
    return [['worker', 'copy', 'blocks', 100.0 + 10 * i, 5.0, None]
            for i in range(n)]


def test_health_escalation_triggers_incident(tmp_path):
    coll = make_collector(incident_dir=str(tmp_path))
    addr = ('127.0.0.1', 50006)
    coll._handle(full_msg(flight=_flight()), addr)
    ev = {'t': 'event', 'host': 'h1', 'session': 's1',
          'kind': 'health', 'pipeline': 'p0', 'from': 'DEGRADED',
          'to': 'FAILED', 'reason': 'wedged'}
    coll._handle(dict(ev), addr)
    assert len(coll.recorder.bundles) == 1
    assert 'health-h1-FAILED' in coll.recorder.bundles[0]
    assert counters.get('incident.bundles') == 1
    coll._handle(dict(ev), addr)      # same escalation: no new bundle
    assert len(coll.recorder.bundles) == 1
    coll._sock.close()


def test_incident_bundle_layout_and_cooldown(tmp_path):
    coll = make_collector(incident_dir=str(tmp_path))
    coll.recorder.cooldown = 60.0
    coll.recorder.settle = 0.0
    addr = ('127.0.0.1', 50007)
    coll._handle(full_msg(cnts={'a': 1}, flight=_flight()), addr)
    path = coll.recorder.trigger('drill', {'why': 'test'})
    assert path is not None
    meta = json.load(open(os.path.join(path, 'meta.json')))
    assert meta['reason'] == 'drill'
    # span_origin = wall_ns - mono_us*1e3: the trace_merge shift base
    assert meta['hosts']['h1']['span_origin_wall_ns'] == \
        1000000000000 - int(1000.0 * 1e3)
    trace = json.load(open(os.path.join(path, 'hosts', 'h1',
                                        'flight.json')))
    assert trace['otherData']['bf_host'] == 'h1'
    assert [e for e in trace['traceEvents'] if e['ph'] == 'X']
    snaps = json.load(open(os.path.join(path, 'hosts', 'h1',
                                        'snapshots.json')))
    assert snaps and snaps[-1]['counters'] == {'a': 1}
    assert os.path.isfile(os.path.join(path, 'rollup.json'))
    assert os.path.isfile(os.path.join(path, 'alerts.json'))
    coll.recorder.poll(now=float('inf'))
    assert os.path.isfile(os.path.join(path, 'post', 'rollup.json'))
    # cooldown: an immediate same-reason re-trigger is suppressed
    assert coll.recorder.trigger('drill') is None
    assert counters.get('incident.suppressed') == 1
    assert counters.get('incident.bundles') == 1
    coll._sock.close()


def test_trace_merge_consumes_bundle(tmp_path):
    coll = make_collector(incident_dir=str(tmp_path))
    addrs = [('127.0.0.1', 50008), ('127.0.0.1', 50009)]
    coll._handle(full_msg(host='h1', flight=_flight()), addrs[0])
    # h2's span clock started 2ms later in wall time
    coll._handle(full_msg(host='h2', session='s2',
                          wall_ns=1000002000000, flight=_flight()),
                 addrs[1])
    path = coll.recorder.trigger('merge-drill')
    out = tmp_path / 'merged.json'
    res = subprocess.run(
        [sys.executable, os.path.join(TOOLS, 'trace_merge.py'),
         '-o', str(out), path],
        capture_output=True, text=True, cwd=ROOT)
    assert res.returncode == 0, res.stderr
    merged = json.load(open(str(out)))
    info = merged['otherData']['bf_merged_from']
    hosts = sorted(i['host'] for i in info.values())
    assert hosts == ['h1', 'h2']
    assert any(i.get('aligned_by') == 'wall_origin'
               for i in info.values())
    # h2's identical span timestamps land +2000us after the shift
    by_pid = {}
    for e in merged['traceEvents']:
        if e.get('ph') == 'X':
            by_pid.setdefault(e['pid'], []).append(e['ts'])
    ts = sorted(min(v) for v in by_pid.values())
    assert abs((ts[1] - ts[0]) - 2000.0) < 1.0
    coll._sock.close()


def test_incident_alert_rule_trips_recorder(tmp_path):
    rules = fleet.load_rules([
        {'name': 'gone', 'kind': 'absence', 'host': 'h1',
         'for_ticks': 1, 'incident': True}])
    coll = make_collector(deadline=0.5, rules=rules,
                          incident_dir=str(tmp_path))
    addr = ('127.0.0.1', 50010)
    coll._handle(full_msg(flight=_flight()), addr)
    now = coll._hosts['h1'].last_seen
    coll.tick(now=now + 2.0)
    assert coll.recorder.bundles
    assert 'alert-gone' in coll.recorder.bundles[0]
    coll._sock.close()


# ---------------------------------------------------------------------------
# exports: prometheus, rollup file, like_top, telemetry_diff
# ---------------------------------------------------------------------------

def test_prometheus_labels_per_host_and_tenant():
    coll = make_collector()
    coll._handle(full_msg(host='h1', cnts={'a.b': 3},
                          tenants={'vic': {'state': 'RUNNING',
                                           'gulps': 7}}),
                 ('127.0.0.1', 50011))
    coll._handle(full_msg(host='h2', session='s2', cnts={'a.b': 4}),
                 ('127.0.0.1', 50012))
    coll.tick()
    text = coll.prometheus_text()
    assert 'bifrost_tpu_fleet_up{host="h1"} 1' in text
    assert ('bifrost_tpu_fleet_counter_total{host="h2",name="a.b"} 4'
            in text)
    assert ('bifrost_tpu_fleet_tenant{host="h1",tenant="vic",'
            'kind="gulps"} 7' in text)
    assert 'bifrost_tpu_fleet_hosts{state="live"} 2' in text
    coll._sock.close()


def test_rollup_file_feeds_like_top_fleet(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import like_top
    finally:
        sys.path.remove(TOOLS)
    rollup_path = tmp_path / 'rollup.json'
    coll = make_collector(rollup_file=str(rollup_path), deadline=1.0)
    coll._handle(full_msg(
        tenants={'vic': {'state': 'RUNNING', 'gulps': 3,
                         'health': 'NOMINAL', 'warm': True,
                         'slo': {'exit_age_p99_s': 0.004}}}),
        ('127.0.0.1', 50013))
    coll.tick()
    rollup = like_top.load_fleet_rollup(str(rollup_path))
    assert rollup is not None
    text = '\n'.join(like_top.render_fleet(rollup))
    assert '1 live' in text
    assert 'h1' in text and 'vic' in text
    # staleness renders too
    now = coll._hosts['h1'].last_seen
    coll.tick(now=now + 5.0)
    text = '\n'.join(like_top.render_fleet(
        like_top.load_fleet_rollup(str(rollup_path))))
    assert 'STALE' in text
    assert like_top.load_fleet_rollup(str(tmp_path / 'nope')) is None
    coll._sock.close()


def test_telemetry_diff_watches_fleet_counters(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import telemetry_diff
    finally:
        sys.path.remove(TOOLS)
    base = {'counters': {'fleet.decode_errors': 0,
                         'fleet.hosts_live': 2}}
    cur = {'counters': {'fleet.decode_errors': 3,
                        'fleet.hosts_live': 1}}
    findings = telemetry_diff.compare(base, cur)
    tripped = {f['path'] for f in findings
               if f.get('severity') == 'regression'}
    assert 'counters.fleet.decode_errors' in tripped
    assert 'counters.fleet.hosts_live' in tripped
    # the same counters improving is NOT a regression
    assert not [f for f in telemetry_diff.compare(cur, base)
                if f.get('severity') == 'regression']


# ---------------------------------------------------------------------------
# singleton wiring
# ---------------------------------------------------------------------------

def test_acquire_publisher_unarmed_without_env():
    assert fleet.acquire_publisher() is None
    fleet.release_publisher(None)            # no-op, no raise


def test_acquire_publisher_refcounted(monkeypatch):
    coll = make_collector()
    monkeypatch.setenv('BF_FLEET_COLLECTOR',
                       '127.0.0.1:%d' % coll.port)
    monkeypatch.setenv('BF_FLEET_INTERVAL', '0.1')
    monkeypatch.setenv('BF_FLEET_HOST', 'solo')
    p1 = fleet.acquire_publisher()
    p2 = fleet.acquire_publisher()
    try:
        assert p1 is not None and p1 is p2
        assert p1.host == 'solo'
        fleet.release_publisher(p1)
        assert p2.is_alive()                 # one hold left
    finally:
        fleet.release_publisher(p2)
        coll._sock.close()
    assert p2._stop_event.is_set()
