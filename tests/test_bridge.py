"""DCN ring bridge tests: ring -> TCP -> ring over loopback (reference
analogue: the RDMA RingSender/RingReceiver, rdma.py:99-203)."""

import socket
import threading

import numpy as np

from bifrost_tpu.ring import Ring
from bifrost_tpu.io.bridge import RingSender, RingReceiver, _send_msg
from tests.util import simple_header


def test_ring_bridge_loopback():
    src_ring = Ring(space='system', name='bridge_src')
    dst_ring = Ring(space='system', name='bridge_dst')

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    rng = np.random.RandomState(0)
    data = rng.randn(24, 6).astype(np.float32)
    hdr = simple_header([-1, 6], 'f32', name='bridged', gulp_nframe=8)

    def writer():
        with src_ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8,
                                   buf_nframe=24) as seq:
                for k in range(3):
                    with seq.reserve(8) as span:
                        span.data.as_numpy()[...] = data[k * 8:(k + 1) * 8]
                        span.commit(8)

    def sender():
        conn = socket.create_connection(('127.0.0.1', port))
        RingSender(src_ring, conn, gulp_nframe=8).run()
        conn.close()

    def receiver():
        conn, _ = srv.accept()
        RingReceiver(conn, dst_ring).run()
        conn.close()

    threads = [threading.Thread(target=f)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()

    got = []
    names = []
    for seq in dst_ring.read(guarantee=True):
        names.append(seq.header['name'])
        for span in seq.read(8):
            got.append(np.array(span.data.as_numpy(), copy=True))
    for t in threads:
        t.join()
    srv.close()
    out = np.concatenate(got, axis=0)
    np.testing.assert_array_equal(out, data)
    assert names == ['bridged']


def test_ring_bridge_multi_sequence_ringlets():
    """Bridge a 2-ringlet stream across two sequences."""
    src_ring = Ring(space='system', name='bridge_src2')
    dst_ring = Ring(space='system', name='bridge_dst2')
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    rng = np.random.RandomState(1)
    datasets = [rng.randn(2, 8, 3).astype(np.float32) for _ in range(2)]

    def writer():
        with src_ring.begin_writing() as wr:
            for s, d in enumerate(datasets):
                hdr = simple_header([2, -1, 3], 'f32',
                                    labels=['beam', 'time', 'chan'],
                                    name='seq%d' % s, gulp_nframe=8)
                hdr['time_tag'] = s
                with wr.begin_sequence(hdr, gulp_nframe=8,
                                       buf_nframe=24) as seq:
                    with seq.reserve(8) as span:
                        span.data.as_numpy()[...] = d
                        span.commit(8)

    def sender():
        conn = socket.create_connection(('127.0.0.1', port))
        RingSender(src_ring, conn, gulp_nframe=8).run()
        conn.close()

    def receiver():
        conn, _ = srv.accept()
        RingReceiver(conn, dst_ring).run()
        conn.close()

    threads = [threading.Thread(target=f)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()
    got = {}
    for seq in dst_ring.read(guarantee=True):
        name = seq.header['name']
        for span in seq.read(8):
            got[name] = np.array(span.data.as_numpy(), copy=True)
    for t in threads:
        t.join()
    srv.close()
    for s, d in enumerate(datasets):
        np.testing.assert_array_equal(got['seq%d' % s], d)
