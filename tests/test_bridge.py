"""DCN ring bridge tests: ring -> TCP -> ring over loopback (reference
analogue: the RDMA RingSender/RingReceiver, rdma.py:99-203)."""

import socket
import threading

import numpy as np

from bifrost_tpu.ring import Ring
from bifrost_tpu.io.bridge import RingSender, RingReceiver, _send_msg
from tests.util import simple_header


def test_ring_bridge_loopback():
    src_ring = Ring(space='system', name='bridge_src')
    dst_ring = Ring(space='system', name='bridge_dst')

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    rng = np.random.RandomState(0)
    data = rng.randn(24, 6).astype(np.float32)
    hdr = simple_header([-1, 6], 'f32', name='bridged', gulp_nframe=8)

    def writer():
        with src_ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8,
                                   buf_nframe=24) as seq:
                for k in range(3):
                    with seq.reserve(8) as span:
                        span.data.as_numpy()[...] = data[k * 8:(k + 1) * 8]
                        span.commit(8)

    def sender():
        conn = socket.create_connection(('127.0.0.1', port))
        RingSender(src_ring, conn, gulp_nframe=8).run()
        conn.close()

    def receiver():
        conn, _ = srv.accept()
        RingReceiver(conn, dst_ring).run()
        conn.close()

    threads = [threading.Thread(target=f)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()

    got = []
    names = []
    for seq in dst_ring.read(guarantee=True):
        names.append(seq.header['name'])
        for span in seq.read(8):
            got.append(np.array(span.data.as_numpy(), copy=True))
    for t in threads:
        t.join()
    srv.close()
    out = np.concatenate(got, axis=0)
    np.testing.assert_array_equal(out, data)
    assert names == ['bridged']


def test_ring_bridge_multi_sequence_ringlets():
    """Bridge a 2-ringlet stream across two sequences."""
    src_ring = Ring(space='system', name='bridge_src2')
    dst_ring = Ring(space='system', name='bridge_dst2')
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    rng = np.random.RandomState(1)
    datasets = [rng.randn(2, 8, 3).astype(np.float32) for _ in range(2)]

    def writer():
        with src_ring.begin_writing() as wr:
            for s, d in enumerate(datasets):
                hdr = simple_header([2, -1, 3], 'f32',
                                    labels=['beam', 'time', 'chan'],
                                    name='seq%d' % s, gulp_nframe=8)
                hdr['time_tag'] = s
                with wr.begin_sequence(hdr, gulp_nframe=8,
                                       buf_nframe=24) as seq:
                    with seq.reserve(8) as span:
                        span.data.as_numpy()[...] = d
                        span.commit(8)

    def sender():
        conn = socket.create_connection(('127.0.0.1', port))
        RingSender(src_ring, conn, gulp_nframe=8).run()
        conn.close()

    def receiver():
        conn, _ = srv.accept()
        RingReceiver(conn, dst_ring).run()
        conn.close()

    threads = [threading.Thread(target=f)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()
    got = {}
    for seq in dst_ring.read(guarantee=True):
        name = seq.header['name']
        for span in seq.read(8):
            got[name] = np.array(span.data.as_numpy(), copy=True)
    for t in threads:
        t.join()
    srv.close()
    for s, d in enumerate(datasets):
        np.testing.assert_array_equal(got['seq%d' % s], d)


def test_ring_bridge_cross_process():
    """Sender in a SEPARATE PROCESS (the real multi-host topology):
    ring -> TCP -> ring across a process boundary."""
    import subprocess
    import sys
    import os

    dst_ring = Ring(space='system', name='bridge_xproc_dst')
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    SENDER = (
        "import sys, socket, numpy as np\n"
        "sys.path.insert(0, %r)\n"
        "sys.path.insert(0, %r)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from bifrost_tpu.ring import Ring\n"
        "from bifrost_tpu.io.bridge import RingSender\n"
        "from util import simple_header\n"
        "import threading\n"
        "port = int(sys.argv[1])\n"
        "ring = Ring(space='system', name='xproc_src')\n"
        "hdr = simple_header([-1, 6], 'f32', name='xproc',\n"
        "                    gulp_nframe=8)\n"
        "rng = np.random.RandomState(3)\n"
        "data = rng.randn(24, 6).astype(np.float32)\n"
        "def writer():\n"
        "    with ring.begin_writing() as wr:\n"
        "        with wr.begin_sequence(hdr, gulp_nframe=8,\n"
        "                               buf_nframe=32) as seq:\n"
        "            for k in range(3):\n"
        "                with seq.reserve(8) as span:\n"
        "                    span.data.as_numpy()[...] = \\\n"
        "                        data[k * 8:(k + 1) * 8]\n"
        "                    span.commit(8)\n"
        "t = threading.Thread(target=writer)\n"
        "t.start()\n"
        "sock = socket.create_connection(('127.0.0.1', port))\n"
        "RingSender(ring, sock).run()\n"
        "t.join()\n"
        "sock.close()\n"
    ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         os.path.dirname(os.path.abspath(__file__)))

    proc = subprocess.Popen([sys.executable, '-c', SENDER, str(port)])
    srv.settimeout(30)
    try:
        conn, _ = srv.accept()
        got = []

        def reader():
            for seq in dst_ring.read(guarantee=True):
                assert seq.header['name'] == 'xproc'
                for span in seq.read(8):
                    got.append(np.array(span.data.as_numpy(),
                                        copy=True))

        rt = threading.Thread(target=reader)
        rt.start()
        RingReceiver(conn, dst_ring).run()
        rt.join(15)
        assert not rt.is_alive()
        out = np.concatenate(got, axis=0)
        rng = np.random.RandomState(3)
        expect = rng.randn(24, 6).astype(np.float32)
        np.testing.assert_array_equal(out, expect)
        conn.close()
    finally:
        try:
            proc.wait(20)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        srv.close()


# ---------------------------------------------------------------------------
# wire v2: zero-copy framing, windowed pipelining, striping
# (docs/networking.md)
# ---------------------------------------------------------------------------

import errno
import pytest

from bifrost_tpu.io.bridge import (BridgeListener, BridgeProtocolError,
                                   connect, connect_striped,
                                   MSG_HEADER, MSG_SPAN, MSG_END_SEQ,
                                   MSG_END)
from bifrost_tpu.header_standard import (serialize_header,
                                         deserialize_header)
from bifrost_tpu.ring import RingPoisonedError


def _gather(ring, gulp):
    """Read every sequence off ``ring``; returns {name: array}
    (gulps concatenated along the header's time axis)."""
    got = {}
    for seq in ring.read(guarantee=True):
        taxis = seq.header['_tensor']['shape'].index(-1)
        chunks = []
        for span in seq.read(gulp):
            chunks.append(np.array(span.data.as_numpy(), copy=True))
        got[seq.header['name']] = np.concatenate(chunks, axis=taxis) \
            if chunks else None
    return got


def _roundtrip(datasets, hdr_fn, gulp, sender_kw=None, receiver_kw=None,
               nstreams=1, ring_tag='rt'):
    """Write ``datasets`` (one per sequence) into a source ring, bridge
    them over loopback, and return {seq_name: received array}."""
    src = Ring(space='system', name='bsrc_%s' % ring_tag)
    dst = Ring(space='system', name='bdst_%s' % ring_tag)
    lst = BridgeListener('127.0.0.1', 0)
    out = {}
    errors = []

    # buffer the WHOLE stream: the unthrottled test writer must not
    # lap the ring before the sender's guarantee registers (a startup
    # race that in-pipeline topologies eliminate via BridgeSink's
    # pre-barrier prime)
    total_frames = sum(d.shape[hdr_fn(s)['_tensor']['shape'].index(-1)]
                       for s, d in enumerate(datasets))

    def writer():
        with src.begin_writing() as wr:
            for s, data in enumerate(datasets):
                hdr = hdr_fn(s)
                taxis = hdr['_tensor']['shape'].index(-1)
                nframe = data.shape[taxis]
                with wr.begin_sequence(hdr, gulp_nframe=gulp,
                                       buf_nframe=total_frames + gulp
                                       ) as seq:
                    off = 0
                    while off < nframe:
                        n = min(gulp, nframe - off)
                        with seq.reserve(n) as span:
                            idx = [slice(None)] * data.ndim
                            idx[taxis] = slice(off, off + n)
                            span.data.as_numpy()[...] = data[tuple(idx)]
                            span.commit(n)
                        off += n

    def sender():
        try:
            socks = connect_striped('127.0.0.1', lst.port, nstreams)
            s = RingSender(src, socks, gulp_nframe=gulp,
                           **(sender_kw or {}))
            s.run()
            s.close()
        except BaseException as exc:    # surfaced by the caller
            errors.append(exc)
            src.poison(exc)

    def receiver():
        try:
            r = RingReceiver(lst, dst, **(receiver_kw or {}))
            r.run()
        except BaseException as exc:
            errors.append(exc)

    threads = [threading.Thread(target=f, daemon=True)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()
    out = _gather(dst, gulp)
    for t in threads:
        t.join(30)
    lst.close()
    assert not errors, errors
    return out


def test_bridge_windowed_pipelining():
    """window>1: spans stay acquired until acked; stream must still be
    byte-identical."""
    rng = np.random.RandomState(7)
    data = rng.randn(64, 5).astype(np.float32)
    out = _roundtrip(
        [data], lambda s: simple_header([-1, 5], 'f32', name='w4',
                                        gulp_nframe=8),
        gulp=8, sender_kw={'window': 4}, ring_tag='win')
    np.testing.assert_array_equal(out['w4'], data)


def test_bridge_striping_reassembly():
    """3 striped connections carry interleaved frames; the receiver
    reassembles them in sequence-number order."""
    rng = np.random.RandomState(8)
    data = rng.randn(96, 7).astype(np.float32)
    out = _roundtrip(
        [data], lambda s: simple_header([-1, 7], 'f32', name='striped',
                                        gulp_nframe=8),
        gulp=8, sender_kw={'window': 6}, nstreams=3, ring_tag='str')
    np.testing.assert_array_equal(out['striped'], data)


def test_bridge_partial_final_gulp():
    """A sequence whose frame count is not a gulp multiple ships a
    short final span."""
    rng = np.random.RandomState(9)
    data = rng.randn(20, 3).astype(np.float32)
    out = _roundtrip(
        [data], lambda s: simple_header([-1, 3], 'f32', name='part',
                                        gulp_nframe=8),
        gulp=8, sender_kw={'window': 2}, ring_tag='part')
    np.testing.assert_array_equal(out['part'], data)


def test_bridge_strided_multi_ringlet_v2():
    """Multi-ringlet (strided span) streams scatter per lane on both
    ends, windowed and striped."""
    rng = np.random.RandomState(10)
    datasets = [rng.randn(3, 16, 4).astype(np.float32)
                for _ in range(2)]

    def hdr_fn(s):
        h = simple_header([3, -1, 4], 'f32',
                          labels=['beam', 'time', 'chan'],
                          name='rl%d' % s, gulp_nframe=8)
        h['time_tag'] = s
        return h

    out = _roundtrip(datasets, hdr_fn, gulp=8,
                     sender_kw={'window': 3}, nstreams=2,
                     ring_tag='ringlets')
    for s, d in enumerate(datasets):
        np.testing.assert_array_equal(out['rl%d' % s], d)


def test_bridge_crc_roundtrip():
    """CRC32 integrity word verified per span."""
    rng = np.random.RandomState(11)
    data = rng.randn(32, 6).astype(np.float32)
    out = _roundtrip(
        [data], lambda s: simple_header([-1, 6], 'f32', name='crc',
                                        gulp_nframe=8),
        gulp=8, sender_kw={'window': 2, 'crc': True}, ring_tag='crc')
    np.testing.assert_array_equal(out['crc'], data)
    from bifrost_tpu.telemetry import counters
    assert counters.get('bridge.rx.crc_errors') == 0


def test_bridge_v1_compat_and_naive():
    """A v2 receiver auto-detects and round-trips the legacy v1 wire
    (protocol=1) and the seed implementation's copying loop
    (naive=True) byte-identically."""
    rng = np.random.RandomState(12)
    data = rng.randn(24, 6).astype(np.float32)
    for tag, kw in (('v1', {'protocol': 1}), ('naive', {'naive': True})):
        out = _roundtrip(
            [data], lambda s: simple_header([-1, 6], 'f32',
                                            name='compat',
                                            gulp_nframe=8),
            gulp=8, sender_kw=kw, ring_tag='compat_%s' % tag)
        np.testing.assert_array_equal(out['compat'], data)


def test_bridge_macro_gulp_frames():
    """A macro-gulp aware sender (gulp_batch=K) ships K gulps per
    frame; the receiver's ring still counts LOGICAL gulps and the
    stream stays byte-identical (the PR-4 macro stream contract)."""
    from bifrost_tpu.telemetry import counters
    rng = np.random.RandomState(13)
    raw = np.zeros((64, 2, 8), dtype=np.dtype([('re', 'i1'),
                                               ('im', 'i1')]))
    raw['re'] = rng.randint(-64, 64, raw.shape)
    raw['im'] = rng.randint(-64, 64, raw.shape)

    def hdr_fn(s):
        return simple_header([-1, 2, 8], 'ci8',
                             labels=['time', 'pol', 'fine'],
                             name='macro', gulp_nframe=8)

    counters.reset()
    out = _roundtrip([raw], hdr_fn, gulp=8,
                     sender_kw={'window': 4, 'gulp_batch': 4},
                     ring_tag='macro')
    np.testing.assert_array_equal(out['macro'], raw)
    # 64 frames / (8-frame gulps) = 8 logical gulps, shipped as 2
    # macro frames of K=4 — the receiver credits logical gulps
    dst_gulps = counters.get('ring.bdst_macro.gulps')
    assert dst_gulps == 8, dst_gulps
    assert counters.get('bridge.tx.spans') == 2


def test_bridge_k1_default_roundtrips_macro_stream():
    """Acceptance: the DEFAULT path (single stream, window=1, CRC off,
    K=1 unbatched framing) round-trips the PR-4 macro test stream
    shapes (ci8 structured gulps) byte-identically."""
    rng = np.random.RandomState(3)
    raw = np.zeros((64, 2, 16), dtype=np.dtype([('re', 'i1'),
                                                ('im', 'i1')]))
    raw['re'] = rng.randint(-64, 64, raw.shape)
    raw['im'] = rng.randint(-64, 64, raw.shape)
    out = _roundtrip(
        [raw], lambda s: simple_header([-1, 2, 16], 'ci8',
                                       labels=['time', 'pol', 'fine'],
                                       name='k1', gulp_nframe=16),
        gulp=16, ring_tag='k1macro')
    np.testing.assert_array_equal(out['k1'], raw)


def test_bridge_span_identity_survives_sender_gulp_override(
        monkeypatch, tmp_path):
    """The (trace, seq, gulp) identity joining tx and rx spans across
    hosts must come from the SHIPPED header's gulp_nframe on both
    sides: a sender reading the ring in bigger batches
    (gulp_nframe override) must not skew the tx-side gulp index."""
    from bifrost_tpu.header_standard import ensure_trace_context
    from bifrost_tpu.telemetry import spans
    monkeypatch.setenv('BF_TRACE_FILE', str(tmp_path / 'ids.json'))
    spans.reconfigure()
    spans.reset()
    try:
        src = Ring(space='system', name='bsrc_gmix')
        dst = Ring(space='system', name='bdst_gmix')
        lst = BridgeListener('127.0.0.1', 0)
        data = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)
        hdr = simple_header([-1, 4], 'f32', name='gmix',
                            gulp_nframe=8)
        tid = ensure_trace_context(hdr)['id']

        def writer():
            with src.begin_writing() as wr:
                with wr.begin_sequence(hdr, gulp_nframe=8,
                                       buf_nframe=40) as seq:
                    for k in range(4):
                        with seq.reserve(8) as span:
                            span.data.as_numpy()[...] = \
                                data[k * 8:(k + 1) * 8]
                            span.commit(8)

        def sender():
            conn = socket.create_connection(('127.0.0.1', lst.port))
            # reads the ring 16 frames at a time — TWICE the header's
            # logical gulp
            s = RingSender(src, [conn], gulp_nframe=16)
            s.run()
            s.close()

        def receiver():
            RingReceiver(lst, dst).run()

        threads = [threading.Thread(target=f, daemon=True)
                   for f in (receiver, writer, sender)]
        for t in threads:
            t.start()
        out = _gather(dst, 8)
        for t in threads:
            t.join(30)
        lst.close()
        np.testing.assert_array_equal(out['gmix'], data)

        evs = [ev for _t, ev in spans.events()
               if ev[0].startswith('bridge.')]
        tx = {(ev[4]['trace'], ev[4]['seq'], ev[4]['gulp'])
              for ev in evs if ev[0].startswith('bridge.tx.')}
        rx = {(ev[4]['trace'], ev[4]['seq'], ev[4]['gulp'])
              for ev in evs if ev[0].startswith('bridge.rx.')}
        # 32 frames in two 16-frame wire spans: header-logical gulp
        # indices 0 and 2 on BOTH timelines
        assert {i[2] for i in tx} == {0, 2}
        assert tx == rx
        assert all(i[0] == tid for i in tx)
    finally:
        monkeypatch.delenv('BF_TRACE_FILE', raising=False)
        spans.reconfigure()
        spans.reset()


def test_header_numpy_values_roundtrip():
    """serialize_header coerces numpy scalars/arrays; a header
    transform that injects them must bridge cleanly."""
    hdr = {'np_int': np.int64(7), 'np_float': np.float32(2.5),
           'np_arr': np.arange(3, dtype=np.int32), 'plain': 'x'}
    back = deserialize_header(serialize_header(hdr))
    assert back['np_int'] == 7
    assert abs(back['np_float'] - 2.5) < 1e-6
    assert back['np_arr'] == [0, 1, 2]
    assert back['plain'] == 'x'
    # a bare json.dumps on the same header throws — the satellite bug
    import json as json_mod
    with pytest.raises(TypeError):
        json_mod.dumps(hdr)

    # end-to-end: bridge a ring whose header transform adds numpy
    # values (ring_view applies transforms on the read side)
    from bifrost_tpu.ring import ring_view
    rng = np.random.RandomState(14)
    data = rng.randn(16, 4).astype(np.float32)
    src = Ring(space='system', name='bsrc_nphdr')
    dst = Ring(space='system', name='bdst_nphdr')
    view = ring_view(src, lambda h: dict(h, cal_gain=np.float64(1.5),
                                         chan_map=np.arange(2)))
    lst = BridgeListener('127.0.0.1', 0)

    def writer():
        with src.begin_writing() as wr:
            hdr2 = simple_header([-1, 4], 'f32', name='nphdr',
                                 gulp_nframe=8)
            with wr.begin_sequence(hdr2, gulp_nframe=8,
                                   buf_nframe=24) as seq:
                with seq.reserve(16) as span:
                    span.data.as_numpy()[...] = data
                    span.commit(16)

    def sender():
        sock = connect('127.0.0.1', lst.port)
        RingSender(view, sock, gulp_nframe=8).run()
        sock.close()

    recv_hdrs = []

    def receiver():
        RingReceiver(lst, dst).run()

    threads = [threading.Thread(target=f, daemon=True)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()
    got = []
    for seq in dst.read(guarantee=True):
        recv_hdrs.append(dict(seq.header))
        for span in seq.read(8):
            got.append(np.array(span.data.as_numpy(), copy=True))
    for t in threads:
        t.join(20)
    lst.close()
    np.testing.assert_array_equal(np.concatenate(got, axis=0), data)
    assert recv_hdrs[0]['cal_gain'] == 1.5
    assert recv_hdrs[0]['chan_map'] == [0, 1]


# ---------------------------------------------------------------------------
# protocol errors, poison propagation, reconnect-and-resume
# ---------------------------------------------------------------------------

def _poisoned(ring):
    return ring.poisoned


def test_bridge_unknown_message_type_raises():
    """Satellite: unknown message types must raise BridgeProtocolError
    (naming the type), not be silently ignored; the destination ring
    is poisoned."""
    dst = Ring(space='system', name='bdst_unknown')
    lst = BridgeListener('127.0.0.1', 0)
    res = []

    def receiver():
        try:
            RingReceiver(lst, dst).run()
        except BridgeProtocolError as exc:
            res.append(exc)

    t = threading.Thread(target=receiver, daemon=True)
    t.start()
    sock = connect('127.0.0.1', lst.port)
    _send_msg(sock, 42, b'bogus')
    t.join(10)
    sock.close()
    lst.close()
    assert res and '42' in str(res[0])
    assert _poisoned(dst)


def test_bridge_span_before_header_raises():
    """Satellite: MSG_SPAN before any MSG_HEADER is a protocol error
    (the seed implementation crashed with NameError)."""
    dst = Ring(space='system', name='bdst_nohdr')
    lst = BridgeListener('127.0.0.1', 0)
    res = []

    def receiver():
        try:
            RingReceiver(lst, dst).run()
        except BridgeProtocolError as exc:
            res.append(exc)

    t = threading.Thread(target=receiver, daemon=True)
    t.start()
    sock = connect('127.0.0.1', lst.port)
    _send_msg(sock, MSG_SPAN, b'\x00' * 64)
    t.join(10)
    sock.close()
    lst.close()
    assert res and 'MSG_HEADER' in str(res[0])
    assert _poisoned(dst)


def test_bridge_sender_death_poisons_receiver_ring():
    """A connection that dies WITHOUT a clean MSG_END poisons the
    destination ring: downstream readers get RingPoisonedError, not a
    silently truncated stream."""
    dst = Ring(space='system', name='bdst_death')
    lst = BridgeListener('127.0.0.1', 0)
    res = []

    def receiver():
        try:
            RingReceiver(lst, dst).run()
        except ConnectionError as exc:
            res.append(exc)

    t = threading.Thread(target=receiver, daemon=True)
    t.start()
    sock = connect('127.0.0.1', lst.port)
    hdr = simple_header([-1, 4], 'f32', name='dead', gulp_nframe=8)
    _send_msg(sock, MSG_HEADER, serialize_header(hdr))
    _send_msg(sock, MSG_SPAN, b'\x01' * (8 * 4 * 4))
    sock.close()             # mid-stream death, no MSG_END
    t.join(10)
    lst.close()
    assert res, "receiver did not surface the dead sender"
    assert _poisoned(dst)
    with pytest.raises(RingPoisonedError):
        for seq in dst.read(guarantee=True):
            for span in seq.read(8):
                pass


class _FlakySock(object):
    """Socket proxy whose sendmsg starts failing after N calls —
    deterministic mid-stream link death for the reconnect test."""

    def __init__(self, sock, fail_after):
        self._sock = sock
        self._calls = 0
        self._fail_after = fail_after

    def sendmsg(self, bufs):
        self._calls += 1
        if self._calls > self._fail_after:
            raise OSError(errno.ECONNRESET, 'injected link death')
        return self._sock.sendmsg(bufs)

    def __getattr__(self, name):
        return getattr(self._sock, name)


def test_bridge_reconnect_and_resume():
    """Sender link dies mid-stream; the sender redials (reconnect
    callable), retransmits unacked frames, and the receiver RESUMES —
    dropping duplicates by sequence number — to a byte-identical
    stream."""
    rng = np.random.RandomState(15)
    data = rng.randn(48, 4).astype(np.float32)
    src = Ring(space='system', name='bsrc_reconn')
    dst = Ring(space='system', name='bdst_reconn')
    lst = BridgeListener('127.0.0.1', 0)
    errors = []
    redials = []

    def writer():
        with src.begin_writing() as wr:
            hdr = simple_header([-1, 4], 'f32', name='reconn',
                                gulp_nframe=8)
            with wr.begin_sequence(hdr, gulp_nframe=8,
                                   buf_nframe=64) as seq:
                for k in range(6):
                    with seq.reserve(8) as span:
                        span.data.as_numpy()[...] = \
                            data[k * 8:(k + 1) * 8]
                        span.commit(8)

    def reconnect():
        redials.append(1)
        return [connect('127.0.0.1', lst.port)]

    def sender():
        try:
            first = _FlakySock(connect('127.0.0.1', lst.port),
                               fail_after=4)
            s = RingSender(src, [first], gulp_nframe=8, window=4,
                           reconnect=reconnect, reconnect_max=3)
            s.run()
            s.close()
        except BaseException as exc:
            errors.append(exc)
            src.poison(exc)

    def receiver():
        r = RingReceiver(lst, dst, poison_on_error=False)
        while True:
            try:
                r.run()
                return
            except BridgeProtocolError as exc:
                errors.append(exc)   # a protocol error is a test bug
                return
            except (ConnectionError, OSError):
                continue             # re-accept and resume

    threads = [threading.Thread(target=f, daemon=True)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()
    out = _gather(dst, 8)
    for t in threads:
        t.join(30)
    lst.close()
    assert not errors, errors
    assert redials, "the flaky link never triggered a redial"
    np.testing.assert_array_equal(out['reconn'], data)


# ---------------------------------------------------------------------------
# pipeline blocks: BridgeSink / BridgeSource under supervision
# ---------------------------------------------------------------------------

def test_bridge_blocks_pipeline():
    """Full block-level topology: NumpySource -> BridgeSink ==TCP==>
    BridgeSource -> GatherSink across two pipelines (the two-host
    shape), striped + windowed, with bridge telemetry observable."""
    import bifrost_tpu as bf
    from tests.util import NumpySourceBlock, GatherSink
    from bifrost_tpu.telemetry import counters

    rng = np.random.RandomState(16)
    NT = 16
    gulps = [rng.randn(NT, 6).astype(np.float32) for _ in range(5)]
    hdr = simple_header([-1, 6], 'f32', name='blkbridge',
                        gulp_nframe=NT)

    counters.reset()
    with bf.Pipeline() as prx:
        bsrc = bf.blocks.bridge_source('127.0.0.1', 0)
        sink = GatherSink(bsrc)
    with bf.Pipeline() as ptx:
        nsrc = NumpySourceBlock(gulps, hdr, gulp_nframe=NT)
        bf.blocks.bridge_sink(nsrc, '127.0.0.1', bsrc.port,
                              nstreams=2, window=3)

    rx_errors = []

    def run_rx():
        try:
            prx.run()
        except BaseException as exc:
            rx_errors.append(exc)

    rx_thread = threading.Thread(target=run_rx, daemon=True)
    rx_thread.start()
    ptx.run()
    rx_thread.join(30)
    assert not rx_thread.is_alive()
    assert not rx_errors, rx_errors
    np.testing.assert_array_equal(sink.result(),
                                  np.concatenate(gulps, axis=0))
    assert counters.get('bridge.tx.spans') == 5
    assert counters.get('bridge.rx.spans') == 5
    assert counters.get('bridge.tx.bytes') == \
        counters.get('bridge.rx.bytes')


def test_bridge_v1_sender_failure_withholds_end():
    """A v1 sender whose source ring dies mid-stream must NOT send a
    clean MSG_END: the receiver sees the connection drop and poisons
    its destination ring (truncation never looks complete)."""
    src = Ring(space='system', name='bsrc_v1fail')
    dst = Ring(space='system', name='bdst_v1fail')
    lst = BridgeListener('127.0.0.1', 0)
    res = []

    def writer():
        with src.begin_writing() as wr:
            hdr = simple_header([-1, 4], 'f32', name='v1fail',
                                gulp_nframe=8)
            with wr.begin_sequence(hdr, gulp_nframe=8,
                                   buf_nframe=24) as seq:
                with seq.reserve(8) as span:
                    span.data.as_numpy()[...] = 1.0
                    span.commit(8)
        # upstream failure after one gulp
        src.poison(RuntimeError("producer died"))

    def sender():
        sock = connect('127.0.0.1', lst.port)
        try:
            RingSender(src, sock, gulp_nframe=8, protocol=1).run()
        except RingPoisonedError as exc:
            res.append(('sender', exc))
        finally:
            sock.close()

    def receiver():
        try:
            RingReceiver(lst, dst).run()
        except ConnectionError as exc:
            res.append(('receiver', exc))

    threads = [threading.Thread(target=f, daemon=True)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20)
    lst.close()
    kinds = {k for k, _ in res}
    assert kinds == {'sender', 'receiver'}, res
    assert dst.poisoned, \
        "truncated v1 stream was presented as a clean end"
