"""DCN ring bridge tests: ring -> TCP -> ring over loopback (reference
analogue: the RDMA RingSender/RingReceiver, rdma.py:99-203)."""

import socket
import threading

import numpy as np

from bifrost_tpu.ring import Ring
from bifrost_tpu.io.bridge import RingSender, RingReceiver, _send_msg
from tests.util import simple_header


def test_ring_bridge_loopback():
    src_ring = Ring(space='system', name='bridge_src')
    dst_ring = Ring(space='system', name='bridge_dst')

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    rng = np.random.RandomState(0)
    data = rng.randn(24, 6).astype(np.float32)
    hdr = simple_header([-1, 6], 'f32', name='bridged', gulp_nframe=8)

    def writer():
        with src_ring.begin_writing() as wr:
            with wr.begin_sequence(hdr, gulp_nframe=8,
                                   buf_nframe=24) as seq:
                for k in range(3):
                    with seq.reserve(8) as span:
                        span.data.as_numpy()[...] = data[k * 8:(k + 1) * 8]
                        span.commit(8)

    def sender():
        conn = socket.create_connection(('127.0.0.1', port))
        RingSender(src_ring, conn, gulp_nframe=8).run()
        conn.close()

    def receiver():
        conn, _ = srv.accept()
        RingReceiver(conn, dst_ring).run()
        conn.close()

    threads = [threading.Thread(target=f)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()

    got = []
    names = []
    for seq in dst_ring.read(guarantee=True):
        names.append(seq.header['name'])
        for span in seq.read(8):
            got.append(np.array(span.data.as_numpy(), copy=True))
    for t in threads:
        t.join()
    srv.close()
    out = np.concatenate(got, axis=0)
    np.testing.assert_array_equal(out, data)
    assert names == ['bridged']


def test_ring_bridge_multi_sequence_ringlets():
    """Bridge a 2-ringlet stream across two sequences."""
    src_ring = Ring(space='system', name='bridge_src2')
    dst_ring = Ring(space='system', name='bridge_dst2')
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    rng = np.random.RandomState(1)
    datasets = [rng.randn(2, 8, 3).astype(np.float32) for _ in range(2)]

    def writer():
        with src_ring.begin_writing() as wr:
            for s, d in enumerate(datasets):
                hdr = simple_header([2, -1, 3], 'f32',
                                    labels=['beam', 'time', 'chan'],
                                    name='seq%d' % s, gulp_nframe=8)
                hdr['time_tag'] = s
                with wr.begin_sequence(hdr, gulp_nframe=8,
                                       buf_nframe=24) as seq:
                    with seq.reserve(8) as span:
                        span.data.as_numpy()[...] = d
                        span.commit(8)

    def sender():
        conn = socket.create_connection(('127.0.0.1', port))
        RingSender(src_ring, conn, gulp_nframe=8).run()
        conn.close()

    def receiver():
        conn, _ = srv.accept()
        RingReceiver(conn, dst_ring).run()
        conn.close()

    threads = [threading.Thread(target=f)
               for f in (receiver, writer, sender)]
    for t in threads:
        t.start()
    got = {}
    for seq in dst_ring.read(guarantee=True):
        name = seq.header['name']
        for span in seq.read(8):
            got[name] = np.array(span.data.as_numpy(), copy=True)
    for t in threads:
        t.join()
    srv.close()
    for s, d in enumerate(datasets):
        np.testing.assert_array_equal(got['seq%d' % s], d)


def test_ring_bridge_cross_process():
    """Sender in a SEPARATE PROCESS (the real multi-host topology):
    ring -> TCP -> ring across a process boundary."""
    import subprocess
    import sys
    import os

    dst_ring = Ring(space='system', name='bridge_xproc_dst')
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(('127.0.0.1', 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    SENDER = (
        "import sys, socket, numpy as np\n"
        "sys.path.insert(0, %r)\n"
        "sys.path.insert(0, %r)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from bifrost_tpu.ring import Ring\n"
        "from bifrost_tpu.io.bridge import RingSender\n"
        "from util import simple_header\n"
        "import threading\n"
        "port = int(sys.argv[1])\n"
        "ring = Ring(space='system', name='xproc_src')\n"
        "hdr = simple_header([-1, 6], 'f32', name='xproc',\n"
        "                    gulp_nframe=8)\n"
        "rng = np.random.RandomState(3)\n"
        "data = rng.randn(24, 6).astype(np.float32)\n"
        "def writer():\n"
        "    with ring.begin_writing() as wr:\n"
        "        with wr.begin_sequence(hdr, gulp_nframe=8,\n"
        "                               buf_nframe=32) as seq:\n"
        "            for k in range(3):\n"
        "                with seq.reserve(8) as span:\n"
        "                    span.data.as_numpy()[...] = \\\n"
        "                        data[k * 8:(k + 1) * 8]\n"
        "                    span.commit(8)\n"
        "t = threading.Thread(target=writer)\n"
        "t.start()\n"
        "sock = socket.create_connection(('127.0.0.1', port))\n"
        "RingSender(ring, sock).run()\n"
        "t.join()\n"
        "sock.close()\n"
    ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         os.path.dirname(os.path.abspath(__file__)))

    proc = subprocess.Popen([sys.executable, '-c', SENDER, str(port)])
    srv.settimeout(30)
    try:
        conn, _ = srv.accept()
        got = []

        def reader():
            for seq in dst_ring.read(guarantee=True):
                assert seq.header['name'] == 'xproc'
                for span in seq.read(8):
                    got.append(np.array(span.data.as_numpy(),
                                        copy=True))

        rt = threading.Thread(target=reader)
        rt.start()
        RingReceiver(conn, dst_ring).run()
        rt.join(15)
        assert not rt.is_alive()
        out = np.concatenate(got, axis=0)
        rng = np.random.RandomState(3)
        expect = rng.randn(24, 6).astype(np.float32)
        np.testing.assert_array_equal(out, expect)
        conn.close()
    finally:
        try:
            proc.wait(20)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        srv.close()
