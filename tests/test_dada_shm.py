"""PSRDADA-style shared-memory ring tests (VERDICT r1 item 8;
reference analogue: python/bifrost/psrdada.py + blocks/psrdada.py)."""

import threading

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.io.dada_shm import (IpcRing, DadaHDU, sysv_available,
                                     shm_accounting_available)

from util import GatherSink

pytestmark = pytest.mark.skipif(not sysv_available(),
                                reason="System V shm unavailable")

#: stale-segment recovery and live-ring protection read nattch from
#: /proc/sysvipc/shm; sandboxed kernels omit it even when shmget/shmat
#: work, and the protections cannot function without it — skip those
#: tests cleanly instead of failing (the PSRDADA shm ENVIRONMENT, not
#: the code, is absent)
needs_shm_accounting = pytest.mark.skipif(
    not shm_accounting_available(),
    reason="SysV shm attachment accounting (/proc/sysvipc/shm nattch) "
           "unavailable in this environment")

# distinct keys per test to dodge stale segments
_KEY = 0x5bf0


def test_ipcring_flow_control_and_eod():
    ring = IpcRing(_KEY, nbufs=2, bufsz=64, create=True)
    try:
        reader = IpcRing(_KEY)       # attach
        got = []

        def read():
            while True:
                buf, n, eod = reader.open_read_buf()
                got.append(bytes(buf[:n]))
                reader.mark_cleared()
                if eod:
                    return

        t = threading.Thread(target=read)
        t.start()
        for k in range(5):           # > nbufs: exercises EMPTY waits
            w = ring.open_write_buf()
            w[:] = k
            ring.mark_filled()
        w = ring.open_write_buf()
        w[:3] = 9
        ring.mark_filled(3, eod=True)
        t.join(10)
        assert not t.is_alive()
        assert len(got) == 6
        assert got[2] == bytes([2]) * 64
        assert got[5] == bytes([9]) * 3
    finally:
        ring.destroy()


def test_hdu_header_roundtrip():
    hdu = DadaHDU(_KEY + 0x10, create=True, data_nbufs=2,
                  data_bufsz=128)
    try:
        peer = DadaHDU(_KEY + 0x10)
        hdu.write_header({'NBIT': 8, 'NCHAN': 4, 'NPOL': 2,
                          'SOURCE': 'J0000+0000'})
        raw = peer.read_header()
        text = raw.decode('ascii')
        assert 'NBIT 8' in text and 'SOURCE J0000+0000' in text
    finally:
        hdu.destroy()


def test_psrdada_pipeline_ingest():
    """Writer process-role fills the ring; the psrdada source block
    streams it into a pipeline."""
    key = _KEY + 0x20
    hdu = DadaHDU(key, create=True, data_nbufs=4, data_bufsz=256)
    try:
        rng = np.random.RandomState(0)
        data = rng.randint(0, 255, size=(64, 4, 2)).astype(np.uint8)

        def writer():
            hdu.write_header({'NBIT': 8, 'NCHAN': 4, 'NPOL': 2,
                              'NDIM': 1, 'TSAMP': 10.0})
            hdu.write_data(data, eod=True)

        t = threading.Thread(target=writer)
        t.start()
        with bf.Pipeline() as p:
            b = bf.blocks.read_psrdada_buffer(key, gulp_nframe=16)
            sink = GatherSink(b)
            p.run()
        t.join(10)
        out = sink.result()
        assert sink.headers[0]['dada_header']['NCHAN'] == 4
        assert out.shape == (64, 4, 2)
        np.testing.assert_array_equal(out.view(np.uint8), data)
    finally:
        hdu.destroy()


def test_psrdada_shutdown_with_stalled_writer():
    """A pipeline whose DADA producer never writes must still shut down
    (timed semaphore waits observing shutdown_event)."""
    import time
    key = _KEY + 0x30
    hdu = DadaHDU(key, create=True, data_nbufs=2, data_bufsz=64)
    try:
        with bf.Pipeline() as p:
            b = bf.blocks.read_psrdada_buffer(key, gulp_nframe=4)
            sink = GatherSink(b)
            t = threading.Thread(target=p.run, daemon=True)
            t.start()
            time.sleep(0.5)          # source is now blocked on the sem
            p.shutdown()
            t.join(10)
            assert not t.is_alive()
    finally:
        hdu.destroy()


@needs_shm_accounting
def test_stale_segment_recreation():
    """Re-creating a ring at a key left by a CRASHED run (creator
    process gone, zero attachments) must start fresh — no leaked
    counters/semaphores; a ring still attached by a live process is
    refused instead of destroyed."""
    import os
    import subprocess
    import sys
    key = _KEY + 0x40
    crasher = (
        "import sys\n"
        "sys.path.insert(0, %r)\n"
        "from bifrost_tpu.io.dada_shm import IpcRing\n"
        "r = IpcRing(%d, nbufs=4, bufsz=32, create=True)\n"
        "w = r.open_write_buf()\n"
        "w[:] = 7\n"
        "r.mark_filled()\n"          # leave FULL=1 and exit uncleanly
    ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         key)
    subprocess.run([sys.executable, '-c', crasher], check=True,
                   timeout=60)
    # recovery run with FEWER buffers: must clean up all 4 stale ones
    r2 = IpcRing(key, nbufs=2, bufsz=32, create=True)
    try:
        assert r2.open_read_buf(timeout=0.2) is None
        from bifrost_tpu.io.dada_shm import _shm_nattch, _get_libc
        # crashed run's extra buffer segments were removed
        libc = _get_libc()
        assert libc.shmget(((key << 8) | 3) & 0x7FFFFFFF, 0,
                           0o666) < 0
    finally:
        r2.destroy()


@needs_shm_accounting
def test_live_ring_not_destroyed():
    """create=True at a key with LIVE attachments refuses rather than
    destroying the ring out from under its owner."""
    key = _KEY + 0x50
    r1 = IpcRing(key, nbufs=2, bufsz=32, create=True)
    try:
        with pytest.raises(OSError):
            IpcRing(key, nbufs=2, bufsz=32, create=True)
    finally:
        r1.destroy()


# ---------------------------------------------------------------------------
# psrdada-layout golden fixtures (VERDICT r2 item 5): the sync-segment
# and header-page bytes below are HAND-BUILT at the documented offsets,
# independently of encode_psrdada_sync / DadaHDU.write_header, so the
# decoders are pinned to the layout rather than to this repo's writer.
# ---------------------------------------------------------------------------

def _hand_built_psrdada_sync():
    """ipcsync_t for a dada_db-style ring: nbufs=4, bufsz=65536,
    writer at buffer 7, one reader at buffer 5, xfer 0 ended at
    buffer 6 byte 1234 (layout doc: bifrost_tpu/io/dada_shm.py)."""
    import struct as s
    raw = bytearray(480)
    s.pack_into('<i', raw, 0, 0x2bf0)        # semkey
    s.pack_into('<i', raw, 4, 0x2bf1)        # semkey_connect
    s.pack_into('<Q', raw, 8, 4)             # nbufs
    s.pack_into('<Q', raw, 16, 65536)        # bufsz
    s.pack_into('<Q', raw, 24, 7)            # w_buf_curr
    s.pack_into('<Q', raw, 32, 8)            # w_buf_next
    s.pack_into('<i', raw, 40, 1)            # w_xfer
    s.pack_into('<i', raw, 44, 2)            # w_state (writing)
    s.pack_into('<Q', raw, 48, 5)            # r_bufs[0]
    s.pack_into('<i', raw, 112, 1)           # r_xfers[0]
    s.pack_into('<i', raw, 144, 3)           # r_states[0]
    s.pack_into('<I', raw, 176, 1)           # num_readers
    s.pack_into('<Q', raw, 184, 0)           # s_buf[0]
    s.pack_into('<Q', raw, 184 + 8, 7)       # s_buf[1] (xfer 1 start)
    s.pack_into('<Q', raw, 248, 64)          # s_byte[0]
    raw[312] = 1                             # eod[0]
    s.pack_into('<Q', raw, 320, 6)           # e_buf[0]
    s.pack_into('<Q', raw, 384, 1234)        # e_byte[0]
    s.pack_into('<i', raw, 448, 0x3bf0)      # semkey_data[0]
    return bytes(raw)


def test_psrdada_sync_golden_decode():
    """decode_psrdada_sync reads a hand-built ipcsync_t without this
    repo's writer being involved."""
    from bifrost_tpu.io.dada_shm import (decode_psrdada_sync,
                                         encode_psrdada_sync,
                                         PSRDADA_SYNC_SIZE)
    raw = _hand_built_psrdada_sync()
    assert len(raw) == PSRDADA_SYNC_SIZE
    d = decode_psrdada_sync(raw)
    assert d['nbufs'] == 4 and d['bufsz'] == 65536
    assert d['semkey'] == 0x2bf0 and d['semkey_connect'] == 0x2bf1
    assert d['w_buf_curr'] == 7 and d['w_buf_next'] == 8
    assert d['w_xfer'] == 1 and d['w_state'] == 2
    assert d['r_bufs'][0] == 5 and d['r_xfers'][0] == 1
    assert d['r_states'][0] == 3
    assert d['num_readers'] == 1
    assert d['s_buf'][:2] == [0, 7] and d['s_byte'][0] == 64
    assert d['eod'][0] is True and d['eod'][1] is False
    assert d['e_buf'][0] == 6 and d['e_byte'][0] == 1234
    assert d['semkey_data'][0] == 0x3bf0
    # the emitter reproduces the hand-built bytes from the decoded form
    re = encode_psrdada_sync(
        nbufs=d['nbufs'], bufsz=d['bufsz'], semkey=d['semkey'],
        semkey_connect=d['semkey_connect'],
        w_buf_curr=d['w_buf_curr'], w_buf_next=d['w_buf_next'],
        w_xfer=d['w_xfer'], w_state=d['w_state'], r_bufs=d['r_bufs'],
        r_xfers=d['r_xfers'], r_states=d['r_states'],
        num_readers=d['num_readers'], s_buf=d['s_buf'],
        s_byte=d['s_byte'], eod=d['eod'], e_buf=d['e_buf'],
        e_byte=d['e_byte'], semkey_data=d['semkey_data'])
    assert re == raw


def test_psrdada_sync_shm_read_and_emit():
    """A psrdada-layout segment planted in REAL SysV shm by raw libc
    calls (standing in for dada_db) is read back by
    IpcRing.read_psrdada_sync; emit_psrdada_sync writes one that
    decodes to this ring's geometry."""
    import ctypes
    from bifrost_tpu.io.dada_shm import (_get_libc, _shm_create,
                                         _shm_map, decode_psrdada_sync,
                                         PSRDADA_SYNC_SIZE, IPC_RMID)
    key = _KEY + 0x40
    libc = _get_libc()
    raw = _hand_built_psrdada_sync()
    shmid = _shm_create(key, PSRDADA_SYNC_SIZE)
    try:
        buf, addr = _shm_map(shmid, PSRDADA_SYNC_SIZE)
        buf[:] = np.frombuffer(raw, np.uint8)
        del buf
        libc.shmdt(ctypes.c_void_p(addr))
        d = IpcRing.read_psrdada_sync(key)
        assert d['nbufs'] == 4 and d['bufsz'] == 65536
        assert d['e_byte'][0] == 1234
    finally:
        libc.shmctl(shmid, IPC_RMID, None)

    # emit: our ring's geometry lands in a psrdada-readable segment
    ring = IpcRing(_KEY + 0x41, nbufs=4, bufsz=4096, create=True)
    out_key = _KEY + 0x42
    out_id = None
    try:
        buf = ring.open_write_buf()
        buf[:8] = 7
        ring.mark_filled(8)
        out_id = ring.emit_psrdada_sync(out_key)
        d = IpcRing.read_psrdada_sync(out_key)
        assert d['nbufs'] == 4 and d['bufsz'] == 4096
        assert d['w_buf_curr'] == 1     # one buffer filled
        assert d['num_readers'] == 1
    finally:
        if out_id is not None:
            libc.shmctl(out_id, IPC_RMID, None)
        ring.destroy()


def test_dada_header_page_golden_decode():
    """_parse_dada_header decodes a hand-built 4096-byte DADA header
    page in the convention dada_dbdisk/dspsr write (ASCII 'KEY value'
    lines, comments, blank lines, NUL padding) — built without
    DadaHDU.write_header."""
    from bifrost_tpu.blocks.psrdada import _parse_dada_header
    page = (
        b"HDR_VERSION 1.0\n"
        b"HDR_SIZE 4096\n"
        b"# produced by a hand-built fixture, not this repo's writer\n"
        b"INSTRUMENT CASPSR\n"
        b"TELESCOPE Parkes\n"
        b"SOURCE J0437-4715\n"
        b"FREQ 1382.0\n"
        b"BW -400.0\n"
        b"TSAMP 0.0125\n"
        b"\n"
        b"NBIT 8\n"
        b"NDIM 2\n"
        b"NPOL 2\n"
        b"NCHAN 1\n"
        b"OBS_OFFSET 0\n"
        b"UTC_START 2026-07-29-01:02:03\n")
    page = page + b"\x00" * (4096 - len(page))
    hdr = _parse_dada_header(page)
    assert hdr['INSTRUMENT'] == 'CASPSR'
    assert hdr['SOURCE'] == 'J0437-4715'
    assert float(hdr['FREQ']) == 1382.0
    assert float(hdr['BW']) == -400.0
    assert float(hdr['TSAMP']) == 0.0125
    assert int(hdr['NBIT']) == 8 and int(hdr['NDIM']) == 2
    assert int(hdr['NPOL']) == 2 and int(hdr['NCHAN']) == 1
    assert hdr['UTC_START'] == '2026-07-29-01:02:03'
    assert 'HDR_SIZE' in hdr and int(hdr['HDR_SIZE']) == 4096
