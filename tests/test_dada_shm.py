"""PSRDADA-style shared-memory ring tests (VERDICT r1 item 8;
reference analogue: python/bifrost/psrdada.py + blocks/psrdada.py)."""

import threading

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.io.dada_shm import IpcRing, DadaHDU, sysv_available

from util import GatherSink

pytestmark = pytest.mark.skipif(not sysv_available(),
                                reason="System V shm unavailable")

# distinct keys per test to dodge stale segments
_KEY = 0x5bf0


def test_ipcring_flow_control_and_eod():
    ring = IpcRing(_KEY, nbufs=2, bufsz=64, create=True)
    try:
        reader = IpcRing(_KEY)       # attach
        got = []

        def read():
            while True:
                buf, n, eod = reader.open_read_buf()
                got.append(bytes(buf[:n]))
                reader.mark_cleared()
                if eod:
                    return

        t = threading.Thread(target=read)
        t.start()
        for k in range(5):           # > nbufs: exercises EMPTY waits
            w = ring.open_write_buf()
            w[:] = k
            ring.mark_filled()
        w = ring.open_write_buf()
        w[:3] = 9
        ring.mark_filled(3, eod=True)
        t.join(10)
        assert not t.is_alive()
        assert len(got) == 6
        assert got[2] == bytes([2]) * 64
        assert got[5] == bytes([9]) * 3
    finally:
        ring.destroy()


def test_hdu_header_roundtrip():
    hdu = DadaHDU(_KEY + 0x10, create=True, data_nbufs=2,
                  data_bufsz=128)
    try:
        peer = DadaHDU(_KEY + 0x10)
        hdu.write_header({'NBIT': 8, 'NCHAN': 4, 'NPOL': 2,
                          'SOURCE': 'J0000+0000'})
        raw = peer.read_header()
        text = raw.decode('ascii')
        assert 'NBIT 8' in text and 'SOURCE J0000+0000' in text
    finally:
        hdu.destroy()


def test_psrdada_pipeline_ingest():
    """Writer process-role fills the ring; the psrdada source block
    streams it into a pipeline."""
    key = _KEY + 0x20
    hdu = DadaHDU(key, create=True, data_nbufs=4, data_bufsz=256)
    try:
        rng = np.random.RandomState(0)
        data = rng.randint(0, 255, size=(64, 4, 2)).astype(np.uint8)

        def writer():
            hdu.write_header({'NBIT': 8, 'NCHAN': 4, 'NPOL': 2,
                              'NDIM': 1, 'TSAMP': 10.0})
            hdu.write_data(data, eod=True)

        t = threading.Thread(target=writer)
        t.start()
        with bf.Pipeline() as p:
            b = bf.blocks.read_psrdada_buffer(key, gulp_nframe=16)
            sink = GatherSink(b)
            p.run()
        t.join(10)
        out = sink.result()
        assert sink.headers[0]['dada_header']['NCHAN'] == 4
        assert out.shape == (64, 4, 2)
        np.testing.assert_array_equal(out.view(np.uint8), data)
    finally:
        hdu.destroy()


def test_psrdada_shutdown_with_stalled_writer():
    """A pipeline whose DADA producer never writes must still shut down
    (timed semaphore waits observing shutdown_event)."""
    import time
    key = _KEY + 0x30
    hdu = DadaHDU(key, create=True, data_nbufs=2, data_bufsz=64)
    try:
        with bf.Pipeline() as p:
            b = bf.blocks.read_psrdada_buffer(key, gulp_nframe=4)
            sink = GatherSink(b)
            t = threading.Thread(target=p.run, daemon=True)
            t.start()
            time.sleep(0.5)          # source is now blocked on the sem
            p.shutdown()
            t.join(10)
            assert not t.is_alive()
    finally:
        hdu.destroy()


def test_stale_segment_recreation():
    """Re-creating a ring at a key left by a CRASHED run (creator
    process gone, zero attachments) must start fresh — no leaked
    counters/semaphores; a ring still attached by a live process is
    refused instead of destroyed."""
    import os
    import subprocess
    import sys
    key = _KEY + 0x40
    crasher = (
        "import sys\n"
        "sys.path.insert(0, %r)\n"
        "from bifrost_tpu.io.dada_shm import IpcRing\n"
        "r = IpcRing(%d, nbufs=4, bufsz=32, create=True)\n"
        "w = r.open_write_buf()\n"
        "w[:] = 7\n"
        "r.mark_filled()\n"          # leave FULL=1 and exit uncleanly
    ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         key)
    subprocess.run([sys.executable, '-c', crasher], check=True,
                   timeout=60)
    # recovery run with FEWER buffers: must clean up all 4 stale ones
    r2 = IpcRing(key, nbufs=2, bufsz=32, create=True)
    try:
        assert r2.open_read_buf(timeout=0.2) is None
        from bifrost_tpu.io.dada_shm import _shm_nattch, _get_libc
        # crashed run's extra buffer segments were removed
        libc = _get_libc()
        assert libc.shmget(((key << 8) | 3) & 0x7FFFFFFF, 0,
                           0o666) < 0
    finally:
        r2.destroy()


def test_live_ring_not_destroyed():
    """create=True at a key with LIVE attachments refuses rather than
    destroying the ring out from under its owner."""
    key = _KEY + 0x50
    r1 = IpcRing(key, nbufs=2, bufsz=32, create=True)
    try:
        with pytest.raises(OSError):
            IpcRing(key, nbufs=2, bufsz=32, create=True)
    finally:
        r1.destroy()
