"""Test configuration: run everything on the JAX CPU backend with 8
virtual devices, so multi-chip sharding tests exercise a real Mesh without
TPU hardware (the 'CPU-only matrix row' of the reference CI,
reference: .github/workflows/main.yml:20-24)."""

import os

flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = \
        (flags + ' --xla_force_host_platform_device_count=8').strip()
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ.setdefault('BF_PROCLOG_DIR', '/tmp/bifrost_tpu_test_proclog')

# The axon TPU plugin ignores JAX_PLATFORMS; force the CPU backend via
# the config API before any computation runs.
import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
