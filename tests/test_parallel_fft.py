"""Distributed pencil FFT over the mesh (parallel/fft.py): one
transform split across all 8 virtual devices via all-to-all."""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bifrost_tpu.parallel.mesh import create_mesh
from bifrost_tpu.parallel.fft import sharded_fft


def _mesh():
    if len(jax.devices()) < 8:
        pytest.skip('needs the 8-device virtual mesh')
    return create_mesh({'sp': 8})


def _untranspose(got, shape, N):
    n1 = 1 << (int(math.log2(N)) // 2)
    n2 = N // n1
    m = got.reshape(shape[:-1] + (n1, n2))
    return np.swapaxes(m, -1, -2).reshape(shape)


@pytest.mark.parametrize('N,shape', [(4096, (4096,)), (1024, (3, 1024)),
                                     (64, (64,))])
@pytest.mark.parametrize('order', ['natural', 'transposed'])
def test_matches_jnp_fft(N, shape, order):
    mesh = _mesh()
    rng = np.random.RandomState(1)
    x = (rng.randn(*shape) + 1j * rng.randn(*shape)).astype(np.complex64)
    want = np.fft.fft(x, axis=-1)
    f = jax.jit(sharded_fft(mesh, N, output_order=order,
                            nbatch=len(shape) - 1))
    got = np.asarray(f(jnp.asarray(x)))
    if order == 'transposed':
        got = _untranspose(got, shape, N)
    rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
    assert rel < 1e-4


def test_inverse_roundtrip_unnormalized():
    mesh = _mesh()
    rng = np.random.RandomState(2)
    x = (rng.randn(512) + 1j * rng.randn(512)).astype(np.complex64)
    f = jax.jit(sharded_fft(mesh, 512))
    fi = jax.jit(sharded_fft(mesh, 512, inverse=True))
    rt = np.asarray(fi(f(jnp.asarray(x)))) / 512
    assert np.max(np.abs(rt - x)) < 1e-4


def test_rejects_indivisible_split():
    mesh = _mesh()
    x = jnp.zeros((32,), jnp.complex64)   # N1=N2=... 32 -> n1=4: 8∤4
    with pytest.raises(Exception):
        jax.jit(sharded_fft(mesh, 32))(x)


def test_custom_radix_split():
    mesh = _mesh()
    rng = np.random.RandomState(3)
    x = (rng.randn(2048) + 1j * rng.randn(2048)).astype(np.complex64)
    f = jax.jit(sharded_fft(mesh, 2048, n1=8))
    got = np.asarray(f(jnp.asarray(x)))
    want = np.fft.fft(x)
    assert np.max(np.abs(got - want)) / np.max(np.abs(want)) < 1e-4
