"""Compiled pipeline segments (bifrost_tpu.segments; docs/perf.md
"Compiled pipeline segments"): fusing a device-block chain into ONE
XLA program must be byte-identical to the unfused chain, elide the
interior rings completely (0 member dispatches, 0 ring traffic), keep
observability alive through synthesis, refuse every unprovable
boundary with the exact BF-I190 reason, and support the auto-tuner's
split/re-fuse knob."""

import os

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu import segments as bseg
from bifrost_tpu.blocks.fft import _StageBlock
from bifrost_tpu.macro import split_ranges
from bifrost_tpu.stages import DetectStage
from bifrost_tpu.telemetry import counters, histograms
from tests.util import NumpySourceBlock, GatherSink, simple_header

NT, NP, NF, RF = 32, 2, 64, 4


def _volts(ngulp, seed=3):
    rng = np.random.RandomState(seed)
    gulps = []
    for _ in range(ngulp):
        raw = np.zeros((NT, NP, NF), dtype=np.dtype([('re', 'i1'),
                                                     ('im', 'i1')]))
        raw['re'] = rng.randint(-64, 64, raw.shape)
        raw['im'] = rng.randint(-64, 64, raw.shape)
        gulps.append(raw)
    return gulps


def _hdr():
    return simple_header([-1, NP, NF], 'ci8',
                         labels=['time', 'pol', 'fine_time'])


def _run_chain(segments=None, gulp_batch=1, ngulp=6, donate=None,
               split=None, **scope):
    """src -> copy h2d -> fft -> detect -> reduce -> copy d2h -> sink
    as SEPARATE stage blocks (the segment compiler's raw material)."""
    counters.reset()
    with bf.Pipeline(segments=segments, gulp_batch=gulp_batch,
                     donate=donate, sync_depth=4, **scope) as p:
        src = NumpySourceBlock(_volts(ngulp), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
        b = bf.blocks.detect(b, mode='stokes', axis='pol')
        b = bf.blocks.reduce(b, 'freq', RF)
        b2 = bf.blocks.copy(b, space='system')
        sink = GatherSink(b2)
        if split is not None:
            # emulate the auto-tuner: compile, then set the split
            # knob before the first sequence resolves it
            segs = bseg.compile_pipeline(p)
            assert segs, 'expected a segment to compile'
            bseg.retune_split(segs[0], split)
        p.run()
    return sink.result(), p, counters.snapshot()


def _type_name(block_name):
    """'Pipeline_3/FftBlock_7' -> 'FftBlock' (instance counters are
    process-global, so assertions key on the type)."""
    return block_name.split('/')[-1].rsplit('_', 1)[0]


def _reasons(pipeline):
    """{(producer block type, reason)} from the shared planner — the
    set the BF-I190 diagnostics mirror."""
    _chains, boundaries = bseg.plan(pipeline)
    return {(_type_name(b['producer']), b['reason'])
            for b in boundaries}


def _i190(diags):
    return [d for d in diags if d.code == 'BF-I190']


# ---------------------------------------------------------------------------
# fusion correctness + elision
# ---------------------------------------------------------------------------

def test_segment_fuses_byte_identical_and_elides():
    base, p0, _ = _run_chain(None)
    out, p1, snap = _run_chain('auto')
    assert np.array_equal(base, out)
    # 7 blocks -> 5: fft/detect/reduce replaced by one SegmentBlock
    assert len(p0.blocks) == 7
    assert len(p1.blocks) == 5
    assert len(p1._segments) == 1
    seg = p1._segments[0]
    assert [_type_name(m) for m in seg._members] == \
        ['FftBlock', 'DetectBlock', 'ReduceBlock']
    # plan-time accounting
    assert snap['segment.compiled'] == 1
    assert snap['segment.elided_rings'] == 2
    assert snap['segment.dispatches'] == 6
    assert snap['segment.gulps'] == 6
    # interior rings registered NO span traffic: no commit counter
    # ever appears for them
    for ring in seg._elided:
        assert counters.get('ring.%s.gulps' % ring) == 0
    # members dispatched ZERO times (block.*.dispatches == segments,
    # not blocks) but their synthesized gulps counters stay live
    for m in seg._members:
        assert ('block.%s.dispatches' % m) not in snap
        assert snap['block.%s.gulps' % m] == 6
    # SLO ages survive fusion: per-member commit-age histograms fed
    # from the segment's markers (the source stamps trace context)
    for m in seg._members:
        h = histograms.get('slo.%s.commit_age_s' % m)
        assert h is not None and h.count == 6


def test_segment_composes_with_macro_gulp():
    base, _, _ = _run_chain(None, ngulp=8)
    out, p, snap = _run_chain('auto', gulp_batch=4, ngulp=8)
    assert np.array_equal(base, out)
    # one dispatch per K-gulp span: 8 gulps at K=4 = 2 dispatches
    assert snap['segment.dispatches'] == 2
    assert snap['segment.gulps'] == 8
    seg = p._segments[0]
    assert seg.impl_info.get('batch') == 4


def test_segment_threads_donation_through_interiors():
    base, _, _ = _run_chain(None, ngulp=8)
    out, _, snap = _run_chain('auto', gulp_batch=4, ngulp=8,
                              donate=True)
    assert np.array_equal(base, out)
    assert snap.get('donation.hits', 0) > 0


def test_force_mode_raises_without_a_fusable_chain():
    with pytest.raises(bseg.SegmentPlanError) as err:
        # a single device block: no chain of >= 2 can form
        counters.reset()
        with bf.Pipeline(segments='force') as p:
            src = NumpySourceBlock(_volts(1), _hdr(), gulp_nframe=NT)
            b = bf.blocks.copy(src, space='tpu')
            b = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
            GatherSink(bf.blocks.copy(b, space='system'))
            p.run()
    assert 'reason' not in str(err.value) or 'host' in str(err.value)


def test_force_mode_runs_when_a_segment_forms():
    base, _, _ = _run_chain(None)
    out, p, _ = _run_chain('force')
    assert np.array_equal(base, out)
    assert len(p._segments) == 1


# ---------------------------------------------------------------------------
# fusion-breaking boundaries: exact BF-I190 reason + unfused-but-
# byte-identical execution
# ---------------------------------------------------------------------------

def test_boundary_multi_reader():
    base, _, _ = _run_chain(None)
    counters.reset()
    with bf.Pipeline(segments='auto', sync_depth=4) as p:
        src = NumpySourceBlock(_volts(6), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        f = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
        d = bf.blocks.detect(f, mode='stokes', axis='pol')
        r = bf.blocks.reduce(d, 'freq', RF)
        sink = GatherSink(bf.blocks.copy(r, space='system'))
        # second reader on the fft->detect ring: that boundary must
        # not fuse...
        tap_sink = GatherSink(bf.blocks.copy(f, space='system'))
        assert ('FftBlock', 'multi_reader') in _reasons(p)
        p.run()
    # ...but detect->reduce still fuses (the safe sub-chain), and the
    # stream is byte-identical to the fully unfused run
    assert np.array_equal(base, sink.result())
    assert counters.get('segment.compiled') == 1
    assert counters.get('segment.elided_rings') == 1
    assert len(p._segments) == 1 and len(p._segments[0]._members) == 2
    assert tap_sink.result() is not None


def test_boundary_tap_via_ring_view():
    base, _, _ = _run_chain(None)
    counters.reset()
    with bf.Pipeline(segments='auto', sync_depth=4) as p:
        src = NumpySourceBlock(_volts(6), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        f = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
        tap = bf.views.rename_axis(f, 'freq', 'chan')
        d = bf.blocks.detect(tap, mode='stokes', axis='pol')
        r = bf.blocks.reduce(d, 'chan', RF)
        sink = GatherSink(bf.blocks.copy(r, space='system'))
        assert ('FftBlock', 'tap') in _reasons(p)
        p.run()
    assert np.array_equal(base, sink.result())
    # detect->reduce still fused behind the tap
    assert counters.get('segment.compiled') == 1


class _OverlapDetect(_StageBlock):
    """An otherwise-eligible stage block that declares FIR-style
    overlap history — a segment must never swallow it."""

    def __init__(self, iring, **kwargs):
        super(_OverlapDetect, self).__init__(
            iring, DetectStage('stokes', axis='pol'), **kwargs)

    def define_input_overlap_nframe(self, iseq):
        return 4


def _build_chain(mutate):
    """Build-only chain for boundary-reason assertions; ``mutate``
    constructs the middle blocks and returns nothing."""
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_volts(1), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        tail = mutate(b)
        GatherSink(bf.blocks.copy(tail, space='system'))
    return p


def test_boundary_overlap():
    def mutate(b):
        f = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
        return _OverlapDetect(f)
    p = _build_chain(mutate)
    assert ('FftBlock', 'overlap') in _reasons(p)


def test_boundary_host_blocks():
    # the plain chain with segments OFF: the copy movers are 'host'
    # boundaries, the stage-stage boundaries report 'disabled'
    def mutate(b):
        f = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
        return bf.blocks.detect(f, mode='stokes', axis='pol')
    p = _build_chain(mutate)
    reasons = _reasons(p)
    assert ('CopyBlock', 'host') in reasons
    assert ('DetectBlock', 'host') in reasons
    assert ('FftBlock', 'disabled') in reasons


def test_boundary_bridge_endpoint():
    with bf.Pipeline() as p:
        src = NumpySourceBlock(_volts(1), _hdr(), gulp_nframe=NT)
        bf.blocks.bridge_sink(src, '127.0.0.1', 1)
    assert ('NumpySourceBlock', 'bridge') in _reasons(p)


def test_boundary_mesh_reshard_seam():
    import jax
    if jax.device_count() < 2:
        pytest.skip('needs a multi-device host platform')
    from bifrost_tpu.parallel import create_mesh
    mesh = create_mesh({'sp': 2})

    def mutate(b):
        with bf.block_scope(mesh=mesh):
            f = bf.blocks.fft(b, axes='fine_time',
                              axis_labels='freq')
        return bf.blocks.detect(f, mode='stokes', axis='pol')
    p = _build_chain(mutate)
    assert ('FftBlock', 'mesh_reshard') in _reasons(p)


def test_boundary_tunables_and_supervision_and_unguaranteed():
    def mutate(b):
        f = bf.blocks.fft(b, axes='fine_time', axis_labels='freq',
                          core=0)
        return bf.blocks.detect(f, mode='stokes', axis='pol', core=1)
    assert ('FftBlock', 'tunables') in _reasons(_build_chain(mutate))

    def mutate2(b):
        f = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
        return bf.blocks.detect(f, mode='stokes', axis='pol',
                                on_failure='restart')
    assert ('FftBlock', 'supervision') in \
        _reasons(_build_chain(mutate2))

    def mutate3(b):
        f = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
        return bf.blocks.detect(f, mode='stokes', axis='pol',
                                guarantee=False)
    assert ('FftBlock', 'unguaranteed') in \
        _reasons(_build_chain(mutate3))


def test_validate_reports_bf_i190_with_reasons():
    """Pipeline.validate() mirrors the planner: one BF-I190 per
    unfused device-ring boundary, message carrying the reason slug."""
    def mutate(b):
        f = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
        d = bf.blocks.detect(f, mode='stokes', axis='pol')
        return bf.blocks.reduce(d, 'freq', RF)
    p = _build_chain(mutate)
    diags = _i190(p.validate())
    # 4 device-ring boundaries: copy->fft (host), fft->detect and
    # detect->reduce (disabled), reduce->copy (host)
    assert len(diags) == 4
    msgs = ' | '.join(d.message for d in diags)
    assert 'reason: disabled' in msgs and 'reason: host' in msgs
    for d in diags:
        assert d.severity == 'info' and d.ring


def test_ringcheck_sees_no_traffic_on_elided_interiors(monkeypatch):
    """BF_RINGCHECK=1 over a fused run: the protocol checker stays
    clean and the elided interior rings register zero span traffic."""
    monkeypatch.setenv('BF_RINGCHECK', '1')
    from bifrost_tpu.analysis import ringcheck
    base, _, _ = _run_chain(None)
    out, p, snap = _run_chain('auto')
    monkeypatch.delenv('BF_RINGCHECK')
    ringcheck.reconfigure()
    assert np.array_equal(base, out)
    assert snap.get('ringcheck.violations', 0) == 0
    for ring in p._segments[0]._elided:
        assert counters.get('ring.%s.gulps' % ring) == 0


# ---------------------------------------------------------------------------
# in-program halo carry: the lifted 'overlap' boundary (BF-I192;
# docs/perf.md "FDMT FRB search")
# ---------------------------------------------------------------------------

F_DM, T_DM, G_DM, MD_DM, NTAP_DM = 8, 256, 32, 8, 4


class _FilterbankSource(bf.SourceBlock):
    """Time-LAST (freq, time) f32 stream — the dedispersion chain's
    native layout (NumpySourceBlock is frame-axis-first)."""

    def __init__(self, **kwargs):
        super(_FilterbankSource, self).__init__(
            ['filterbank'], gulp_nframe=G_DM, **kwargs)

    def create_reader(self, name):
        class R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return R()

    def on_sequence(self, reader, name):
        rng = np.random.RandomState(11)
        self._data = rng.randn(F_DM, T_DM).astype(np.float32)
        self._pos = 0
        return [{'name': 'filterbank', 'time_tag': 0,
                 '_tensor': {'shape': [F_DM, -1], 'dtype': 'f32',
                             'labels': ['freq', 'time'],
                             'scales': [[100.0, 1.0], [0.0, 1e-3]],
                             'units': ['MHz', 's']}}]

    def on_data(self, reader, ospans):
        if self._pos >= T_DM:
            return [0]
        n = min(ospans[0].nframe, T_DM - self._pos)
        ospans[0].data.as_numpy()[:, :n] = \
            self._data[:, self._pos:self._pos + n]
        self._pos += n
        return [n]


def _run_dm_chain(segments=None, gulp_batch=1):
    """src -> copy h2d -> fdmt -> matched_filter -> threshold -> copy
    d2h -> sink: every interior boundary is an overlap boundary the
    halo carry must lift."""
    counters.reset()
    collected = []

    class _TimeLastSink(bf.SinkBlock):
        def on_sequence(self, iseq):
            pass

        def on_data(self, ispan):
            from bifrost_tpu.xfer import to_host
            collected.append(np.array(to_host(ispan.data), copy=True))

    with bf.Pipeline(segments=segments, gulp_batch=gulp_batch,
                     sync_depth=4) as p:
        src = _FilterbankSource()
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fdmt_stage(b, max_delay=MD_DM)
        b = bf.blocks.matched_filter(b, NTAP_DM)
        b = bf.blocks.threshold(b, 0.5)
        b = bf.blocks.copy(b, space='system')
        _TimeLastSink(b)
        p.run()
    out = np.concatenate(collected, axis=-1)
    return out, p, counters.snapshot()


def test_halo_carry_fuses_overlap_chain_byte_identical():
    """A provably-safe overlap chain fuses WITH the in-program halo
    carry: byte-identical output, one segment, interior rings elided
    with zero traffic, and the segment.overlap_carried counter
    records each lifted boundary."""
    base, p0, snap0 = _run_dm_chain(None)
    assert snap0.get('segment.overlap_carried', 0) == 0
    out, p, snap = _run_dm_chain('force')
    assert np.array_equal(base, out)
    assert len(p._segments) == 1
    seg = p._segments[0]
    assert [_type_name(m) for m in seg._members] == \
        ['FdmtStageBlock', 'MatchedFilterBlock', 'ThresholdBlock']
    # both interior boundaries (fdmt->mf, mf->threshold) carried
    assert snap['segment.overlap_carried'] == 1
    assert snap['segment.compiled'] == 1
    assert snap['segment.elided_rings'] == 2
    for ring in seg._elided:
        assert counters.get('ring.%s.gulps' % ring) == 0
    for m in seg._members:
        assert ('block.%s.dispatches' % m) not in snap


def test_halo_carry_macro_gulp_byte_identical():
    """K>1 macro gulps over the carried segment: the ghost history is
    sliced from the span head ONCE and the interior handoffs are
    elided inside the scanned program — still byte-identical, with
    K fewer dispatches."""
    base, _, _ = _run_dm_chain(None)
    out, p, snap = _run_dm_chain('force', gulp_batch=4)
    assert np.array_equal(base, out)
    assert snap['segment.overlap_carried'] == 1
    # 8 logical gulps at K=4 -> 2 dispatches
    assert snap['segment.dispatches'] == 2
    assert snap['segment.gulps'] == 8


def test_boundary_overlap_carried_reason():
    """The planner reports 'overlap_carried' (a FUSING record) for
    derivable stage overlap, and still cuts with 'overlap' when the
    consumer's declaration cannot be derived from its stages
    (test_boundary_overlap holds the mismatch case)."""
    with bf.Pipeline() as p:
        src = _FilterbankSource()
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fdmt_stage(b, max_delay=MD_DM)
        b = bf.blocks.matched_filter(b, NTAP_DM)
        GatherSink(bf.blocks.copy(b, space='system'))
    _chains, boundaries = bseg.plan(p, 'auto')
    reasons = {(_type_name(b['producer']), b['reason'])
               for b in boundaries}
    assert ('FdmtStageBlock', 'overlap_carried') in reasons
    assert ('FdmtStageBlock', 'overlap') not in reasons


def test_validate_reports_bf_i192_for_carried_boundary():
    """Pipeline.validate() surfaces each lifted overlap boundary as a
    BF-I192 info (never an error: carry is an optimization, and its
    silent disengage is what telemetry_diff watches)."""
    with bf.Pipeline(segments='auto') as p:
        src = _FilterbankSource()
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fdmt_stage(b, max_delay=MD_DM)
        b = bf.blocks.matched_filter(b, NTAP_DM)
        GatherSink(bf.blocks.copy(b, space='system'))
    diags = [d for d in p.validate() if d.code == 'BF-I192']
    assert len(diags) == 1
    assert diags[0].severity == 'info'
    assert 'halo carry' in diags[0].message
    assert not [d for d in p.validate()
                if d.code == 'BF-I190' and 'overlap' in d.message]


# ---------------------------------------------------------------------------
# split/re-fuse (the auto-tuner's segment-boundary knob)
# ---------------------------------------------------------------------------

def test_split_ranges_helper():
    assert split_ranges([1, 1, 1], 0) == [(0, 3)]
    assert split_ranges([1, 1, 1], 1) == [(0, 2), (2, 3)]
    assert split_ranges([1, 1, 1], 2) == [(0, 1), (1, 2), (2, 3)]
    assert split_ranges([3, 1], 1) == [(0, 3), (3, 4)]
    # clamps to the boundary count
    assert split_ranges([2, 1, 2], 5) == [(0, 2), (2, 3), (3, 5)]


@pytest.mark.parametrize('split,k,expected_disp', [(1, 1, 16),
                                                   (2, 4, 6)])
def test_split_execution_byte_identical(split, k, expected_disp):
    base, _, _ = _run_chain(None, ngulp=8)
    out, p, snap = _run_chain('auto', gulp_batch=k, ngulp=8,
                              split=split)
    assert np.array_equal(base, out)
    seg = p._segments[0]
    assert seg._splits_active == split
    # split+1 dispatches per (macro-)gulp set, still zero interior
    # ring traffic — and block.<segment>.dispatches agrees with the
    # segment.* counters (real compiled-program dispatches)
    assert snap['segment.dispatches'] == expected_disp
    assert snap['block.%s.dispatches' % seg.name] == expected_disp
    for ring in seg._elided:
        assert counters.get('ring.%s.gulps' % ring) == 0


def test_retune_split_clamps_and_applies_next_sequence():
    _, p, _ = _run_chain('auto')
    seg = p._segments[0]
    assert bseg.retune_split(seg, 99) == 2      # 3 members -> max 2
    assert bseg.retune_split(seg, -1) == 0
    assert bseg.retune_split(seg, 1) == 1
    # resolution happens per sequence, not retroactively
    assert seg._splits_active == 0
    assert seg._resolve_splits() == 1


def test_synthesized_member_spans(monkeypatch, tmp_path):
    """With span recording armed, member blocks get synthesized
    compute spans tagged with their segment (trace timeline survives
    fusion)."""
    from bifrost_tpu.telemetry import spans
    monkeypatch.setenv('BF_TRACE_FILE', str(tmp_path / 'trace.json'))
    try:
        out, p, _ = _run_chain('auto')
        seg = p._segments[0]
        synth = [(name, ev) for name, ev in spans.events()
                 if isinstance(ev[4], dict)
                 and ev[4].get('synthesized')]
        names = {ev[0] for _t, ev in synth}
        for m in seg._members:
            assert ('%s.on_data' % m) in names
        for _t, ev in synth:
            assert ev[4]['segment'] == seg.name
    finally:
        monkeypatch.delenv('BF_TRACE_FILE')
        spans.reconfigure()


def test_member_perf_proclogs_publish(monkeypatch):
    """like_top's discovery path: member perf ProcLogs keep
    publishing, carrying the in_segment marker and the segment's
    amortization ratio."""
    monkeypatch.setenv('BF_PROCLOG_INTERVAL', '0')
    from bifrost_tpu import proclog
    out, p, _ = _run_chain('auto', gulp_batch=4, ngulp=8)
    seg = p._segments[0]
    contents = proclog.load_by_pid(os.getpid())
    found = 0
    for m in seg._members:
        perf = contents.get(m, {}).get('perf')
        if not perf:
            continue
        found += 1
        assert perf.get('in_segment') == seg.name
        assert float(perf.get('gulps_per_dispatch', 0)) >= 1.0
    assert found == len(seg._members)


def test_root_retunes_reach_the_segment():
    """The compiler carries only the chain head's OWN pins, never
    scope-resolved values — a resolved sync_depth pinned onto the
    segment would silently cut the auto-tuner's root retunes (and
    profile warm starts) off from the fused hot path."""
    from bifrost_tpu.macro import resolve_gulp_batch
    from bifrost_tpu.pipeline import resolve_sync_depth
    _, p, _ = _run_chain('auto')            # Pipeline(sync_depth=4)
    seg = p._segments[0]
    assert seg.__dict__.get('_sync_depth') is None
    assert resolve_sync_depth(seg) == 4
    p._sync_depth = 9                       # the sync_depth knob
    assert resolve_sync_depth(seg) == 9
    p._gulp_batch = 8                       # the macro-K knob
    assert resolve_gulp_batch(seg) == 8
