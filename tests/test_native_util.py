"""Native utility C ABI: affinity, aligned memory, ProcLog writer
(native/util.cpp; reference surfaces: src/bifrost/affinity.h,
memory.h, proclog.h)."""
import ctypes
import os
import threading

import numpy as np
import pytest

from bifrost_tpu import native


lib = native.load()
pytestmark = pytest.mark.skipif(lib is None,
                                reason='native library unavailable')


def test_affinity_thread_scoped():
    got = {}

    def worker():
        assert lib.bft_affinity_set_core(0) == 0
        out = ctypes.c_int(-2)
        assert lib.bft_affinity_get_core(ctypes.byref(out)) == 0
        got['worker'] = out.value

    before = os.sched_getaffinity(0)
    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert got['worker'] == 0
    # binding happened on the worker THREAD; the process mask that
    # other threads inherit is untouched
    assert os.sched_getaffinity(0) == before
    assert lib.bft_affinity_set_core(ctypes.c_int(-1)) == 0


def test_affinity_python_wrapper_uses_native():
    from bifrost_tpu import affinity
    got = {}

    def worker():
        affinity.set_core(0)
        got['core'] = affinity.get_core()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert got['core'] == 0


def test_malloc_alignment_and_free():
    p = ctypes.c_void_p()
    assert lib.bft_malloc(ctypes.byref(p), 4096) == 0
    assert p.value is not None and p.value % 512 == 0
    assert lib.bft_memset(p, 0xAB, 4096) == 0
    buf = (ctypes.c_ubyte * 4096).from_address(p.value)
    assert bytes(buf[:8]) == b'\xab' * 8
    assert lib.bft_free(p) == 0
    # zero-size allocation is OK and returns NULL
    q = ctypes.c_void_p(1)
    assert lib.bft_malloc(ctypes.byref(q), 0) == 0
    assert q.value is None
    assert lib.bft_malloc(ctypes.byref(q), -1) != 0


def test_memcpy_and_2d():
    src = np.arange(64, dtype=np.uint8)
    dst = np.zeros(64, dtype=np.uint8)
    assert lib.bft_memcpy(dst.ctypes.data, src.ctypes.data, 64) == 0
    np.testing.assert_array_equal(dst, src)

    # strided 2-D copy: 3 rows of 4 bytes out of 8-byte-stride rows
    s2 = np.arange(24, dtype=np.uint8).reshape(3, 8)
    d2 = np.zeros((3, 16), dtype=np.uint8)
    assert lib.bft_memcpy2d(d2.ctypes.data, 16,
                            s2.ctypes.data, 8, 4, 3) == 0
    np.testing.assert_array_equal(d2[:, :4], s2[:, :4])
    assert not d2[:, 4:].any()
    # width > stride is invalid
    assert lib.bft_memcpy2d(d2.ctypes.data, 2,
                            s2.ctypes.data, 8, 4, 3) != 0

    d3 = np.zeros((2, 8), dtype=np.uint8)
    assert lib.bft_memset2d(d3.ctypes.data, 8, 0x5A, 3, 2) == 0
    assert (d3[:, :3] == 0x5A).all() and not d3[:, 3:].any()


def test_proclog_requires_base():
    """Runs before any set_base in this process: updating without a
    base is a BFT_ERR_STATE (-2), not a silent success."""
    assert lib.bft_proclog_update(b'blk', b'log', b'x : 1\n') == -2


def test_proclog_native_writer(tmp_path):
    assert lib.bft_proclog_set_base(str(tmp_path).encode()) == 0
    assert lib.bft_proclog_update(b'capture_0', b'stats',
                                  b'ngood : 42\nnmissing : 1\n') == 0
    path = os.path.join(str(tmp_path), str(os.getpid()),
                        'capture_0', 'stats')
    with open(path) as f:
        body = f.read()
    assert 'ngood : 42' in body and 'nmissing : 1' in body
    # atomic replace: a second update fully replaces the contents
    assert lib.bft_proclog_update(b'capture_0', b'stats',
                                  b'ngood : 43\n') == 0
    with open(path) as f:
        assert f.read() == 'ngood : 43\n'
    assert lib.bft_proclog_set_base(b'') != 0
