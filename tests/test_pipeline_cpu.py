"""CPU-only pipeline integration tests — the minimum end-to-end slice
(reference analogue: test/test_pipeline_cpu.py; BASELINE config 1)."""

import numpy as np

import bifrost_tpu as bf
from tests.util import NumpySourceBlock, GatherSink, simple_header


def _run(pipeline):
    pipeline.run()


def test_source_to_sink():
    with bf.Pipeline() as p:
        gulps = [np.full((4, 3), float(k), dtype=np.float32)
                 for k in range(5)]
        hdr = simple_header([-1, 3], 'f32')
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=4)
        sink = GatherSink(src)
        _run(p)
    out = sink.result()
    assert out.shape == (20, 3)
    np.testing.assert_array_equal(out[4:8], 1.0)


def test_copy_transpose_reduce_chain():
    """read -> copy -> transpose -> reduce('freq',4) -> sink, all host."""
    rng = np.random.RandomState(0)
    data = rng.rand(16, 8).astype(np.float32)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 8], 'f32', labels=['time', 'freq'])
        src = NumpySourceBlock([data[i * 4:(i + 1) * 4] for i in range(4)],
                               hdr, gulp_nframe=4)
        b = bf.blocks.copy(src, space='system')
        b = bf.blocks.reduce(b, 'freq', 4)
        sink = GatherSink(b)
        _run(p)
    out = sink.result()
    expect = data.reshape(16, 2, 4).sum(axis=2)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_block_chainer():
    data = np.arange(32, dtype=np.float32).reshape(8, 4)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'f32', labels=['time', 'freq'])
        bc = bf.BlockChainer()
        bc.last_block = NumpySourceBlock([data[:4], data[4:]], hdr,
                                         gulp_nframe=4)
        bc.blocks.copy('system')
        sink = GatherSink(bc.last_block)
        _run(p)
    np.testing.assert_array_equal(sink.result(), data)


def test_views_split_merge():
    data = np.arange(64, dtype=np.float32).reshape(8, 8)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 8], 'f32', labels=['time', 'freq'])
        src = NumpySourceBlock([data[:4], data[4:]], hdr, gulp_nframe=4)
        b = bf.views.split_axis(src, 'freq', 4, label='fine_freq')
        headers = []
        sink = GatherSink(b)
        _run(p)
    hdr = sink.headers[0]
    assert hdr['_tensor']['shape'] == [-1, 2, 4]
    assert hdr['_tensor']['labels'] == ['time', 'freq', 'fine_freq']
    out = sink.result()
    np.testing.assert_array_equal(out.reshape(8, 8), data)


def test_pipeline_init_error():
    class BadBlock(bf.TransformBlock):
        def on_sequence(self, iseq):
            raise RuntimeError("boom")

        def on_data(self, ispan, ospan):
            pass

    import pytest
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'f32')
        src = NumpySourceBlock([np.zeros((4, 4), np.float32)], hdr,
                               gulp_nframe=4)
        bad = BadBlock(src)
        import sys, io, contextlib
        with contextlib.redirect_stderr(io.StringIO()):
            with pytest.raises(bf.PipelineInitError):
                p.run()


def test_scrunch_and_accumulate():
    data = np.ones((8, 4), dtype=np.float32)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'f32')
        src = NumpySourceBlock([data[:4], data[4:]], hdr, gulp_nframe=4)
        b = bf.blocks.scrunch(src, 2)
        sink = GatherSink(b)
        _run(p)
    out = sink.result()
    assert out.shape == (4, 4)
    np.testing.assert_array_equal(out, 1.0)


def test_fused_ci8_detect():
    """Regression: ci8 (int-pair device rep) through a fused FFT->detect
    chain — the pair axis must not count toward the logical rank."""
    from bifrost_tpu.stages import FftStage, DetectStage
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    rng = np.random.RandomState(0)
    raw = np.zeros((8, 2, 16), dtype=ci8_dtype)
    raw['re'] = rng.randint(-16, 16, size=(8, 2, 16))
    raw['im'] = rng.randint(-16, 16, size=(8, 2, 16))
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 2, 16], 'ci8',
                            labels=['time', 'pol', 'fine_time'])
        src = NumpySourceBlock([raw], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fused(b, [FftStage('fine_time', axis_labels='freq'),
                                DetectStage('stokes', axis='pol')])
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    out = sink.result()
    v = raw['re'].astype(np.float32) + 1j * raw['im']
    s = np.fft.fft(v, axis=-1)
    x, y = s[:, 0], s[:, 1]
    xy = x * np.conj(y)
    expect = np.stack([np.abs(x)**2 + np.abs(y)**2,
                       np.abs(x)**2 - np.abs(y)**2,
                       2 * xy.real, -2 * xy.imag], axis=1)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-3)


def test_map_stage_complex_atype():
    """Regression: MapStage a_type must be the input's logical dtype."""
    from bifrost_tpu.stages import MapStage
    rng = np.random.RandomState(1)
    data = (rng.randn(8, 4) + 1j * rng.randn(8, 4)).astype(np.complex64)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'cf32')
        src = NumpySourceBlock([data], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fused(b, [MapStage("b = (a_type)a * (a_type)2")])
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
    np.testing.assert_allclose(sink.result(), data * 2, rtol=1e-5)


def _drive_sync_gulp(monkeypatch, depth, strict=None, in_order=True):
    """Drive Block._sync_gulp with fake spans and record which gulps the
    drain waits on (regression for the in-order/newest-gulp assumption
    and strict-readback mode; VERDICT r1 weak 8, ADVICE r1)."""
    import jax.numpy as jnp
    from bifrost_tpu import device
    from bifrost_tpu.pipeline import Block

    waits = {'sync': [], 'force': []}
    monkeypatch.setattr(device, 'stream_synchronize',
                        lambda *a: waits['sync'].append(a))
    monkeypatch.setattr(device, 'force_completion',
                        lambda *a: waits['force'].append(a))
    if not in_order:
        monkeypatch.setenv('BF_ASSUME_IN_ORDER', '0')

    class FakeSpan(object):
        def __init__(self, tag):
            self._device_array = jnp.full((2,), tag)

    with bf.Pipeline():
        blk = Block([], sync_depth=depth, sync_strict=strict)
    gulps = []
    for tag in range(depth + 1):
        span = FakeSpan(tag)
        gulps.append(span._device_array)
        blk._sync_gulp([span])
    return waits, gulps


def test_sync_gulp_waits_on_newest_drained(monkeypatch):
    waits, gulps = _drive_sync_gulp(monkeypatch, depth=4)
    # depth exceeded once: drain all but the newest (gulps 0..3), wait
    # ONLY on the newest popped one (index 3) — valid because execution
    # is in-order; steady state is then ONE wait per sync_depth gulps
    assert waits['force'] == []
    assert len(waits['sync']) == 1
    assert waits['sync'][0][0] is gulps[3]


def test_sync_gulp_strict_uses_readback(monkeypatch):
    waits, gulps = _drive_sync_gulp(monkeypatch, depth=4, strict=True)
    assert waits['sync'] == []
    assert len(waits['force']) == 1
    assert waits['force'][0][0] is gulps[3]


def test_sync_gulp_out_of_order_waits_on_all(monkeypatch):
    waits, gulps = _drive_sync_gulp(monkeypatch, depth=4, in_order=False)
    # without the in-order guarantee every popped gulp must be waited on
    assert [w[0] for w in waits['sync']] == [gulps[0], gulps[1],
                                             gulps[2], gulps[3]]


def test_sync_gulp_wait_rate_bounded(monkeypatch):
    """Steady state: at most one hard wait per sync_depth gulps (the
    transfer-engine acceptance bound; counters verify on live runs)."""
    import jax.numpy as jnp
    from bifrost_tpu import device
    from bifrost_tpu.pipeline import Block

    nwaits = []
    monkeypatch.setattr(device, 'stream_synchronize',
                        lambda *a: nwaits.append(1))
    depth, ngulp = 4, 32

    class FakeSpan(object):
        def __init__(self, tag):
            self._device_array = jnp.full((2,), tag)

    with bf.Pipeline():
        blk = Block([], sync_depth=depth)
    for tag in range(ngulp):
        blk._sync_gulp([FakeSpan(tag)])
    assert len(nwaits) <= ngulp / depth


def test_block_scope_device_placement():
    """BlockScope(device=N) routes the block's transfers to device N
    (the reference analogue: per-block gpu= placement,
    reference: pipeline.py:365-366)."""
    import jax
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip('needs multi-device backend')
    devices_seen = []

    class Probe(bf.pipeline.SinkBlock):
        def on_sequence(self, iseq):
            pass

        def on_data(self, ispan):
            devices_seen.append(list(ispan.data.devices())[0].id)

    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'f32')
        src = NumpySourceBlock([np.ones((8, 4), np.float32)], hdr,
                               gulp_nframe=8)
        with bf.block_scope(device=3):
            b = bf.blocks.copy(src, space='tpu')
        Probe(b)
        p.run()
    assert devices_seen == [3], devices_seen


def _run_stage_chain(auto_fuse, raw, hdr):
    """Reference-style separate fft/detect/reduce blocks; auto_fuse
    collapses them into one FusedBlock (pipeline-level op fusion)."""
    with bf.Pipeline(auto_fuse=auto_fuse) as p:
        src = NumpySourceBlock([raw], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fft(b, axes='fine_time', axis_labels='freq')
        b = bf.blocks.detect(b, mode='stokes')
        b = bf.blocks.reduce(b, 'freq', 4)
        b = bf.blocks.copy(b, space='system')
        sink = GatherSink(b)
        p.run()
        nblocks = len(p.blocks)
    return sink.result(), nblocks


def test_auto_fuse_output_identical_and_blocks_collapse():
    from bifrost_tpu.dtype import ci8 as ci8_dtype
    rng = np.random.RandomState(3)
    raw = np.zeros((8, 2, 64), dtype=ci8_dtype)
    raw['re'] = rng.randint(-32, 32, size=(8, 2, 64))
    raw['im'] = rng.randint(-32, 32, size=(8, 2, 64))
    hdr = simple_header([-1, 2, 64], 'ci8',
                        labels=['time', 'pol', 'fine_time'])
    base, nb_base = _run_stage_chain(False, raw, hdr)
    fused, nb_fused = _run_stage_chain(True, raw, hdr)
    np.testing.assert_allclose(fused, base, rtol=1e-5)
    # src + copy + fft + detect + reduce + copy + sink = 7 blocks;
    # fused: src + copy + AutoFused + copy + sink = 5
    assert nb_base == 7
    assert nb_fused == 5


def test_auto_fuse_skips_tapped_ring():
    """A ring with two consumers must not be swallowed by fusion."""
    rng = np.random.RandomState(4)
    data = (rng.randn(8, 16) +
            1j * rng.randn(8, 16)).astype(np.complex64)
    hdr = simple_header([-1, 16], 'cf32', labels=['time', 'freq'])
    with bf.Pipeline(auto_fuse=True) as p:
        src = NumpySourceBlock([data], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        d = bf.blocks.detect(b, mode='scalar')
        r = bf.blocks.reduce(d, 'freq', 4)
        g1 = GatherSink(bf.blocks.copy(d, space='system'))
        g2 = GatherSink(bf.blocks.copy(r, space='system'))
        p.run()
    want_d = np.abs(data) ** 2
    np.testing.assert_allclose(g1.result(), want_d, rtol=1e-5)
    np.testing.assert_allclose(g2.result(),
                               want_d.reshape(8, 4, 4).sum(-1),
                               rtol=1e-5)


def test_auto_fuse_skips_view_tapped_ring():
    """A block_view tap reads through a RingView whose identity differs
    from the producer's oring; fusion must still see it as a second
    consumer (a swallowed tap would deadlock its sink)."""
    rng = np.random.RandomState(5)
    data = (rng.randn(8, 16) +
            1j * rng.randn(8, 16)).astype(np.complex64)
    hdr = simple_header([-1, 16], 'cf32', labels=['time', 'freq'])
    with bf.Pipeline(auto_fuse=True) as p:
        src = NumpySourceBlock([data], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        d = bf.blocks.detect(b, mode='scalar')
        r = bf.blocks.reduce(d, 'freq', 4)
        tap = bf.views.rename_axis(d, 'freq', 'chan')
        g1 = GatherSink(bf.blocks.copy(tap, space='system'))
        g2 = GatherSink(bf.blocks.copy(r, space='system'))
        p.run()
    want_d = np.abs(data) ** 2
    np.testing.assert_allclose(g1.result(), want_d, rtol=1e-5)
    assert g1.headers[0]['_tensor']['labels'] == ['time', 'chan']
    np.testing.assert_allclose(g2.result(),
                               want_d.reshape(8, 4, 4).sum(-1),
                               rtol=1e-5)


def test_auto_fuse_carries_per_block_tunables():
    """Per-block settings (core= on the blocks themselves) survive
    fusion: the replacement FusedBlock resolves the same values."""
    rng = np.random.RandomState(6)
    data = (rng.randn(8, 16) +
            1j * rng.randn(8, 16)).astype(np.complex64)
    hdr = simple_header([-1, 16], 'cf32', labels=['time', 'freq'])
    with bf.Pipeline(auto_fuse=True) as p:
        src = NumpySourceBlock([data], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        d = bf.blocks.detect(b, mode='scalar', core=0)
        r = bf.blocks.reduce(d, 'freq', 4, core=0)
        g = GatherSink(bf.blocks.copy(r, space='system'))
        p._auto_fuse()
        fused = [blk for blk in p.blocks
                 if blk.name.split('/')[-1].startswith('AutoFused')]
        assert len(fused) == 1, [blk.name for blk in p.blocks]
        assert fused[0].core == 0
        p.auto_fuse = False           # already fused by hand above
        p.run()
    want = np.abs(data) ** 2
    np.testing.assert_allclose(g.result(),
                               want.reshape(8, 4, 4).sum(-1), rtol=1e-5)
