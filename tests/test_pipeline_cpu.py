"""CPU-only pipeline integration tests — the minimum end-to-end slice
(reference analogue: test/test_pipeline_cpu.py; BASELINE config 1)."""

import numpy as np

import bifrost_tpu as bf
from tests.util import NumpySourceBlock, GatherSink, simple_header


def _run(pipeline):
    pipeline.run()


def test_source_to_sink():
    with bf.Pipeline() as p:
        gulps = [np.full((4, 3), float(k), dtype=np.float32)
                 for k in range(5)]
        hdr = simple_header([-1, 3], 'f32')
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=4)
        sink = GatherSink(src)
        _run(p)
    out = sink.result()
    assert out.shape == (20, 3)
    np.testing.assert_array_equal(out[4:8], 1.0)


def test_copy_transpose_reduce_chain():
    """read -> copy -> transpose -> reduce('freq',4) -> sink, all host."""
    rng = np.random.RandomState(0)
    data = rng.rand(16, 8).astype(np.float32)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 8], 'f32', labels=['time', 'freq'])
        src = NumpySourceBlock([data[i * 4:(i + 1) * 4] for i in range(4)],
                               hdr, gulp_nframe=4)
        b = bf.blocks.copy(src, space='system')
        b = bf.blocks.reduce(b, 'freq', 4)
        sink = GatherSink(b)
        _run(p)
    out = sink.result()
    expect = data.reshape(16, 2, 4).sum(axis=2)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_block_chainer():
    data = np.arange(32, dtype=np.float32).reshape(8, 4)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'f32', labels=['time', 'freq'])
        bc = bf.BlockChainer()
        bc.last_block = NumpySourceBlock([data[:4], data[4:]], hdr,
                                         gulp_nframe=4)
        bc.blocks.copy('system')
        sink = GatherSink(bc.last_block)
        _run(p)
    np.testing.assert_array_equal(sink.result(), data)


def test_views_split_merge():
    data = np.arange(64, dtype=np.float32).reshape(8, 8)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 8], 'f32', labels=['time', 'freq'])
        src = NumpySourceBlock([data[:4], data[4:]], hdr, gulp_nframe=4)
        b = bf.views.split_axis(src, 'freq', 4, label='fine_freq')
        headers = []
        sink = GatherSink(b)
        _run(p)
    hdr = sink.headers[0]
    assert hdr['_tensor']['shape'] == [-1, 2, 4]
    assert hdr['_tensor']['labels'] == ['time', 'freq', 'fine_freq']
    out = sink.result()
    np.testing.assert_array_equal(out.reshape(8, 8), data)


def test_pipeline_init_error():
    class BadBlock(bf.TransformBlock):
        def on_sequence(self, iseq):
            raise RuntimeError("boom")

        def on_data(self, ispan, ospan):
            pass

    import pytest
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'f32')
        src = NumpySourceBlock([np.zeros((4, 4), np.float32)], hdr,
                               gulp_nframe=4)
        bad = BadBlock(src)
        import sys, io, contextlib
        with contextlib.redirect_stderr(io.StringIO()):
            with pytest.raises(bf.PipelineInitError):
                p.run()


def test_scrunch_and_accumulate():
    data = np.ones((8, 4), dtype=np.float32)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'f32')
        src = NumpySourceBlock([data[:4], data[4:]], hdr, gulp_nframe=4)
        b = bf.blocks.scrunch(src, 2)
        sink = GatherSink(b)
        _run(p)
    out = sink.result()
    assert out.shape == (4, 4)
    np.testing.assert_array_equal(out, 1.0)
