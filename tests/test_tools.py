"""CLI tools smoke tests (reference analogue: test/test_scripts.py)."""

import os
import subprocess
import sys

import numpy as np

import bifrost_tpu as bf
from tests.util import NumpySourceBlock, GatherSink, simple_header

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), 'tools')


def _run_pipeline_and_leave_proclogs():
    data = np.ones((8, 4), np.float32)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'f32')
        src = NumpySourceBlock([data], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src)
        sink = GatherSink(b)
        p.run()
    return sink


def _tool(name, *args):
    env = dict(os.environ)
    return subprocess.run([sys.executable, os.path.join(TOOLS, name)]
                          + list(args), capture_output=True, text=True,
                          env=env, timeout=60)


def test_like_top_once():
    """like_top renders the reference's panes: load average, process
    counts, CPU/memory/swap, and per-block perf rows with core + %CPU
    columns (reference: tools/like_top.py:52-200)."""
    _run_pipeline_and_leave_proclogs()
    res = _tool('like_top.py', '--once')
    assert res.returncode == 0, res.stderr
    assert 'load average:' in res.stdout
    assert 'Processes:' in res.stdout and 'running' in res.stdout
    assert 'CPU(s):' in res.stdout and '%us' in res.stdout
    assert 'Mem:' in res.stdout and 'Swap:' in res.stdout
    assert 'Block' in res.stdout and 'Core' in res.stdout
    assert '%CPU' in res.stdout and 'Cmd' in res.stdout
    assert 'Acquire' in res.stdout and 'Reserve' in res.stdout
    assert 'CopyBlock' in res.stdout


def test_like_ps():
    """like_ps lists process details, rings with space/size, and block
    ring wiring (reference: tools/like_ps.py:120-196)."""
    _run_pipeline_and_leave_proclogs()
    res = _tool('like_ps.py', str(os.getpid()))
    assert res.returncode == 0, res.stderr
    assert 'PID: %d' % os.getpid() in res.stdout
    assert 'User:' in res.stdout and 'CPU Usage:' in res.stdout
    assert 'Thread Count:' in res.stdout
    assert 'Rings:' in res.stdout and 'Blocks:' in res.stdout
    assert 'on system of size' in res.stdout     # ring geometry pane
    assert 'read ring(s):' in res.stdout
    assert 'write ring(s):' in res.stdout
    assert 'log(s):' in res.stdout


def test_pipeline2dot():
    """pipeline2dot annotates blocks with CPU binding and shape, rings
    with space/size, and emits association edges
    (reference: tools/pipeline2dot.py:97-330)."""
    _run_pipeline_and_leave_proclogs()
    res = _tool('pipeline2dot.py', str(os.getpid()))
    assert res.returncode == 0, res.stderr
    assert 'digraph graph%d' % os.getpid() in res.stdout
    assert 'label="Pipeline:' in res.stdout
    assert 'CPU' in res.stdout or 'Unbound' in res.stdout
    assert 'shape="box"' in res.stdout
    assert 'ring:' in res.stdout and '->' in res.stdout
    assert 'system' in res.stdout          # ring space annotation


def test_like_bmon_once():
    """like_bmon renders per-PID RX/TX rate summaries and per-block
    loss detail (reference: tools/like_bmon.py:108-330)."""
    res = _tool('like_bmon.py', '--once')
    assert res.returncode == 0, res.stderr
    assert 'RX Rate' in res.stdout and 'TX Rate' in res.stdout
    assert 'RX pkt/s' in res.stdout and 'TX pkt/s' in res.stdout


def test_like_bmon_rates_from_capture(tmp_path, monkeypatch):
    """A real capture's proclog stats appear in like_bmon's panes with
    good/missing/loss columns."""
    monkeypatch.setenv('BF_PROCLOG_DIR', str(tmp_path))
    base = os.path.join(str(tmp_path), str(os.getpid()),
                        'rx_capture')
    os.makedirs(base)
    with open(os.path.join(base, 'stats'), 'w') as f:
        f.write('ngood_bytes : 8192\nnmissing_bytes : 1024\n'
                'ninvalid : 3\nnignored : 1\nnpackets : 128\n')
    tx = os.path.join(str(tmp_path), str(os.getpid()),
                      'chips_transmit_1')
    os.makedirs(tx)
    with open(os.path.join(tx, 'stats'), 'w') as f:
        f.write('npackets : 64\nnbytes : 4096\n')
    res = _tool('like_bmon.py', '--once')
    assert res.returncode == 0, res.stderr
    assert 'rx_capture' in res.stdout
    assert 'chips_transmit_1' in res.stdout
    assert 'good_bytes' in res.stdout and 'missing' in res.stdout
    assert '8192' in res.stdout and '1024' in res.stdout
    assert 'loss' in res.stdout


def test_like_pmap():
    """like_pmap reports NUMA-classified memory areas and per-ring
    mapping details (reference: tools/like_pmap.py)."""
    _run_pipeline_and_leave_proclogs()
    res = _tool('like_pmap.py', str(os.getpid()))
    assert res.returncode == 0, res.stderr
    assert 'Rings:' in res.stdout
    assert 'Anonymous Memory Areas:' in res.stdout
    assert 'File Backed Memory Areas:' in res.stdout
    assert 'Ring Mappings:' in res.stdout
    assert 'Space: system' in res.stdout
    assert 'Node:' in res.stdout or 'Area:' in res.stdout
    assert 'Other Non-Ring Areas:' in res.stdout


def test_proclog_roundtrip():
    from bifrost_tpu import proclog
    _run_pipeline_and_leave_proclogs()
    contents = proclog.load_by_pid(os.getpid())
    blocks = [b for b in contents if 'CopyBlock' in b]
    assert blocks
    perf = contents[blocks[0]].get('perf', {})
    assert 'process_time' in perf


def test_telemetry_decorators_inert_when_disabled(monkeypatch,
                                                  tmp_path):
    """The decorator API works regardless of state; with aggregation
    off (the isolated default) nothing is recorded.  Full behavior:
    tests/test_telemetry.py."""
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    import bifrost_tpu.telemetry as tel
    client = tel._LocalClient()
    monkeypatch.setattr(tel, '_client', client)
    assert tel.is_active() is False
    tel.track_module()

    @tel.track_function
    def f(x):
        return x + 1
    assert f(1) == 2
    assert not client._cache


def test_header_standard():
    from bifrost_tpu.header_standard import enforce_header_standard
    good = {'nchans': 4, 'nifs': 1, 'nbits': 8, 'fch1': 1400.0,
            'foff': -1.0, 'tstart': 58000.0, 'tsamp': 1e-3}
    assert enforce_header_standard(good)
    bad = dict(good)
    del bad['tsamp']
    assert not enforce_header_standard(bad)


def test_object_cache_and_envvars():
    from bifrost_tpu.utils import ObjectCache, EnvVars
    c = ObjectCache(capacity=2)
    c.put('a', 1)
    c.put('b', 2)
    c.put('c', 3)
    assert 'a' not in c and c.get('c') == 3
    os.environ['BF_TEST_VAR'] = 'hello'
    EnvVars.clear()
    assert EnvVars.get('BF_TEST_VAR') == 'hello'


def test_proclog_throttling(tmp_path, monkeypatch):
    """ProcLog rate-limits file writes (BF_PROCLOG_INTERVAL) but
    force=True always writes."""
    monkeypatch.setenv('BF_PROCLOG_DIR', str(tmp_path))
    from bifrost_tpu import proclog as plmod
    monkeypatch.setattr(plmod, '_gc_done', True)
    monkeypatch.setattr(plmod.ProcLog, 'MIN_INTERVAL', None)
    monkeypatch.setenv('BF_PROCLOG_INTERVAL', '100')
    log = plmod.ProcLog('throttle/perf')
    log.update({'n': 1})
    log.update({'n': 2})          # throttled away
    text = open(log.path).read()
    assert 'n : 1' in text
    log.update({'n': 3}, force=True)
    assert 'n : 3' in open(log.path).read()
    monkeypatch.setattr(plmod.ProcLog, 'MIN_INTERVAL', None)


def test_lint_envvars_invariant():
    """Repo invariant: every BF_* env var read in bifrost_tpu/ is
    documented in docs/envvars.md and every documented var is read
    somewhere (tools/lint_envvars.py; exit 3 on violations)."""
    res = _tool('lint_envvars.py')
    assert res.returncode == 0, res.stdout + res.stderr
    assert '0 undocumented, 0 phantom' in res.stdout


def test_bf_lint_script_mode():
    """bf_lint lints an example script without running its pipeline
    and exits 0 under --strict when the topology is clean."""
    res = _tool('bf_lint.py', '--strict',
                os.path.join(os.path.dirname(TOOLS),
                             'examples', 'your_first_block.py'))
    assert res.returncode == 0, res.stdout + res.stderr
    assert 'BF-E' not in res.stdout


def test_bf_lint_codes_catalog():
    """--codes prints the stable diagnostic catalog used by
    docs/analysis.md."""
    res = _tool('bf_lint.py', '--codes')
    assert res.returncode == 0, res.stderr
    for code in ('BF-E101', 'BF-E121', 'BF-E130', 'BF-W140', 'BF-E150'):
        assert code in res.stdout, code


def test_mprobe_report_dump_and_clear(tmp_path):
    """mprobe_report renders the disk winner cache (winner, per-
    candidate ms, margin, coin-flip flag) and --clear drops it so the
    next session re-measures."""
    import json
    cache = tmp_path / 'mp'
    cache.mkdir()
    (cache / 'beamform.json').write_text(json.dumps({
        'cpu:x:v0|acc=int8 w=(1,4,8) v=(8,2,1,8) int8': {
            'winner': 'int8_wide',
            'ms': {'int8_wide': 1.0, 'xla': 5.0}},
        'cpu:x:v0|acc=f32 w=(1,4,8) v=(8,2,1,8) float32': {
            'winner': 'planar',
            'ms': {'planar': 1.00, 'xla': 1.01}},
    }))
    # foreign state in the same dir (telemetry_usage.json-style list
    # entries): must be neither rendered nor deleted by --clear
    (cache / 'telemetry_usage.json').write_text(
        json.dumps({'counters.inc': [12, 3, 0.5]}))
    env = dict(os.environ, BF_CACHE_DIR=str(cache))
    run = lambda *a: subprocess.run(
        [sys.executable, os.path.join(TOOLS, 'mprobe_report.py')]
        + list(a), capture_output=True, text=True, env=env, timeout=60)

    res = run()
    assert res.returncode == 0, res.stdout + res.stderr
    assert 'winner=int8_wide' in res.stdout
    assert 'margin=5.000x' in res.stdout
    assert 'COIN-FLIP' in res.stdout          # the 1.01/1.00 key

    res = run('--json', '--family', 'beamform')
    data = json.loads(res.stdout)
    assert set(data) == {'beamform'}
    assert len(data['beamform']) == 2

    res = run('--clear', '--family', 'beamform')
    assert res.returncode == 0
    assert not (cache / 'beamform.json').exists()

    res = run('--clear')
    assert res.returncode == 0
    assert (cache / 'telemetry_usage.json').exists()  # foreign: kept

    res = run()
    assert 'no winner caches' in res.stdout
