"""CLI tools smoke tests (reference analogue: test/test_scripts.py)."""

import os
import subprocess
import sys

import numpy as np

import bifrost_tpu as bf
from tests.util import NumpySourceBlock, GatherSink, simple_header

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), 'tools')


def _run_pipeline_and_leave_proclogs():
    data = np.ones((8, 4), np.float32)
    with bf.Pipeline() as p:
        hdr = simple_header([-1, 4], 'f32')
        src = NumpySourceBlock([data], hdr, gulp_nframe=8)
        b = bf.blocks.copy(src)
        sink = GatherSink(b)
        p.run()
    return sink


def _tool(name, *args):
    env = dict(os.environ)
    return subprocess.run([sys.executable, os.path.join(TOOLS, name)]
                          + list(args), capture_output=True, text=True,
                          env=env, timeout=60)


def test_like_top_once():
    _run_pipeline_and_leave_proclogs()
    res = _tool('like_top.py', str(os.getpid()), '--once')
    assert res.returncode == 0, res.stderr
    assert 'block' in res.stdout
    assert 'CopyBlock' in res.stdout


def test_like_ps():
    _run_pipeline_and_leave_proclogs()
    res = _tool('like_ps.py')
    assert res.returncode == 0, res.stderr
    assert str(os.getpid()) in res.stdout


def test_pipeline2dot():
    _run_pipeline_and_leave_proclogs()
    res = _tool('pipeline2dot.py', str(os.getpid()))
    assert res.returncode == 0, res.stderr
    assert 'digraph pipeline' in res.stdout
    assert '->' in res.stdout


def test_like_bmon_once():
    res = _tool('like_bmon.py', '--once')
    assert res.returncode == 0, res.stderr
    assert 'GOOD_BYTES' in res.stdout


def test_proclog_roundtrip():
    from bifrost_tpu import proclog
    _run_pipeline_and_leave_proclogs()
    contents = proclog.load_by_pid(os.getpid())
    blocks = [b for b in contents if 'CopyBlock' in b]
    assert blocks
    perf = contents[blocks[0]].get('perf', {})
    assert 'process_time' in perf


def test_telemetry_stub():
    import bifrost_tpu.telemetry as tel
    assert tel.is_active() is False
    tel.track_module()

    @tel.track_function
    def f(x):
        return x + 1
    assert f(1) == 2


def test_header_standard():
    from bifrost_tpu.header_standard import enforce_header_standard
    good = {'nchans': 4, 'nifs': 1, 'nbits': 8, 'fch1': 1400.0,
            'foff': -1.0, 'tstart': 58000.0, 'tsamp': 1e-3}
    assert enforce_header_standard(good)
    bad = dict(good)
    del bad['tsamp']
    assert not enforce_header_standard(bad)


def test_object_cache_and_envvars():
    from bifrost_tpu.utils import ObjectCache, EnvVars
    c = ObjectCache(capacity=2)
    c.put('a', 1)
    c.put('b', 2)
    c.put('c', 3)
    assert 'a' not in c and c.get('c') == 3
    os.environ['BF_TEST_VAR'] = 'hello'
    EnvVars.clear()
    assert EnvVars.get('BF_TEST_VAR') == 'hello'


def test_proclog_throttling(tmp_path, monkeypatch):
    """ProcLog rate-limits file writes (BF_PROCLOG_INTERVAL) but
    force=True always writes."""
    monkeypatch.setenv('BF_PROCLOG_DIR', str(tmp_path))
    from bifrost_tpu import proclog as plmod
    monkeypatch.setattr(plmod, '_gc_done', True)
    monkeypatch.setattr(plmod.ProcLog, 'MIN_INTERVAL', None)
    monkeypatch.setenv('BF_PROCLOG_INTERVAL', '100')
    log = plmod.ProcLog('throttle/perf')
    log.update({'n': 1})
    log.update({'n': 2})          # throttled away
    text = open(log.path).read()
    assert 'n : 1' in text
    log.update({'n': 3}, force=True)
    assert 'n : 3' in open(log.path).read()
    monkeypatch.setattr(plmod.ProcLog, 'MIN_INTERVAL', None)
