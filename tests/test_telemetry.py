"""Local-only telemetry (reference capability:
python/bifrost/telemetry/__init__.py:86-360, minus the network — this
build aggregates to a local JSON file, opt-in, and has no transport)."""

import importlib
import json
import subprocess
import sys

import numpy as np  # noqa: F401  (parity with sibling test imports)
import pytest


@pytest.fixture
def tele(monkeypatch, tmp_path):
    """A fresh telemetry module state rooted in tmp_path."""
    monkeypatch.setenv('BF_CACHE_DIR', str(tmp_path))
    from bifrost_tpu import telemetry as T
    client = T._LocalClient()
    monkeypatch.setattr(T, '_client', client)
    return T


def test_default_disabled_and_no_file(tele, tmp_path):
    assert not tele.is_active()
    assert not tele._client.track('bifrost_tpu.whatever')
    tele._client.flush()
    assert not (tmp_path / 'telemetry_usage.json').exists()


def test_enable_track_flush_merge(tele, tmp_path):
    tele.enable()
    assert tele.is_active()

    @tele.track_function
    def f(x):
        return x + 1

    @tele.track_function_timed
    def g(x):
        return x * 2

    assert f(1) == 2 and f(2) == 3 and g(3) == 6
    assert f.__name__ == 'f'              # wraps preserved
    tele._client.flush()
    data = json.loads((tmp_path / 'telemetry_usage.json').read_text())
    fname = [k for k in data if k.endswith('.f()')]
    gname = [k for k in data if k.endswith('.g()')]
    assert fname and data[fname[0]][0] == 2
    assert gname and data[gname[0]][0] == 1
    assert data[gname[0]][1] == 1 and data[gname[0]][2] >= 0.0

    # merge across sessions: a second flush ADDS
    f(4)
    tele._client.flush()
    data2 = json.loads((tmp_path / 'telemetry_usage.json').read_text())
    assert data2[fname[0]][0] == 3


def test_disable_persists_and_stops_tracking(tele, tmp_path):
    tele.enable()
    tele.disable()
    assert not tele.is_active()
    assert (tmp_path / 'telemetry_state').read_text() == 'disabled'
    assert not tele._client.track('bifrost_tpu.x')
    # a fresh client (next session) reads the persisted opt-out
    assert not tele._LocalClient().active


def test_track_method_keys_by_class(tele, tmp_path):
    tele.enable()

    class A:
        @tele.track_method
        def run(self):
            return 'a'

    assert A().run() == 'a'
    tele._client.flush()
    data = json.loads((tmp_path / 'telemetry_usage.json').read_text())
    assert any('.A.run()' in k for k in data), data


def test_flush_backoff_on_failure(tele, monkeypatch):
    """A failing flush (e.g. read-only cache dir) must not turn every
    later tracked call into repeated failing syscalls; an explicit
    flush retries."""
    import os as _os
    tele.enable()
    orig = _os.replace
    calls = []

    def failing(src, dst):
        calls.append(1)
        raise OSError('read-only')

    monkeypatch.setattr(_os, 'replace', failing)
    for i in range(tele.MAX_ENTRIES + 5):
        tele._client.track('bifrost_tpu.n%d' % i)
    assert tele._client._flush_blocked
    n_attempts = len(calls)
    tele._client.track('bifrost_tpu.more')      # backed off: no I/O
    assert len(calls) == n_attempts
    monkeypatch.setattr(_os, 'replace', orig)
    assert tele._client.flush()                 # explicit retry works
    assert not tele._client._flush_blocked


def test_flush_survives_corrupted_usage_file(tele, tmp_path):
    """A malformed telemetry_usage.json (truncated write, foreign JSON)
    must cost at most the bad entries — flush() may never raise
    TypeError/IndexError out of track() or the atexit handler."""
    tele.enable()
    usage = tmp_path / 'telemetry_usage.json'
    # entry shapes that used to explode the merge loop
    usage.write_text(json.dumps({
        'bifrost_tpu.bad_scalar': 42,              # not a list
        'bifrost_tpu.bad_short': [1],              # too short
        'bifrost_tpu.bad_types': ['x', None, {}],  # non-numeric slots
        'bifrost_tpu.good': [3, 1, 0.5],           # valid, must survive
    }))
    tele._client.track('bifrost_tpu.good')
    tele._client.track('bifrost_tpu.fresh')
    assert tele._client.flush()
    data = json.loads(usage.read_text())
    assert data['bifrost_tpu.good'][0] == 4        # merged, not reset
    assert data['bifrost_tpu.fresh'][0] == 1
    for bad in ('bad_scalar', 'bad_short', 'bad_types'):
        assert 'bifrost_tpu.%s' % bad not in data

    # a top-level non-dict document is discarded wholesale
    usage.write_text(json.dumps([1, 2, 3]))
    tele._client.track('bifrost_tpu.after_list')
    assert tele._client.flush()
    data = json.loads(usage.read_text())
    assert data['bifrost_tpu.after_list'][0] == 1


def test_module_has_no_network_code():
    """The privacy stance is structural: no transport modules are ever
    imported by the telemetry package."""
    import bifrost_tpu.telemetry as T
    src = open(T.__file__).read()
    for needle in ('urllib', 'urlopen', 'http', 'socket', 'requests'):
        assert needle not in src, needle


def test_cli_status(tmp_path):
    out = subprocess.run(
        [sys.executable, '-m', 'bifrost_tpu.telemetry', '--status'],
        capture_output=True, text=True, timeout=120,
        env=dict(__import__('os').environ, BF_CACHE_DIR=str(tmp_path),
                 JAX_PLATFORMS='cpu'),
        cwd='/root/repo')
    assert out.returncode == 0, out.stderr
    assert 'in-active' in out.stdout
