"""Overload-resilience tests (docs/robustness.md "Overload &
degradation"): ring overload policies with counted shedding in BOTH
ring cores, the bridge sender's credit-window/quota shedding, the
jittered-backoff/circuit-breaker reconnect machinery, and the pipeline
health state machine."""

import threading
import time

import numpy as np
import pytest

import bifrost_tpu as bf
import bifrost_tpu.native as native_mod
from bifrost_tpu.ring import Ring, EndOfDataStop, WouldBlock
from bifrost_tpu.telemetry import counters, histograms, slo
from bifrost_tpu.analysis import ringcheck
from tests.util import (NumpySourceBlock, GatherSink, simple_header,
                        _NumpyReader)

CORES = ['python'] + (['native'] if native_mod.available() else [])


@pytest.fixture(autouse=True)
def clean_counters():
    counters.reset()
    histograms.reset()
    yield
    counters.reset()
    histograms.reset()


@pytest.fixture(params=CORES)
def ring_core(request, monkeypatch):
    if request.param == 'python':
        monkeypatch.setattr(native_mod, '_lib', None)
        monkeypatch.setattr(native_mod, '_tried', True)
    return request.param


FB = 16        # frame bytes of the (-1, 4) f32 test tensor


def _hdr(gulp=2):
    return {'_tensor': {'shape': [-1, 4], 'dtype': 'f32'},
            'gulp_nframe': gulp, 'name': 'seq'}


def _fill_ring(ring, ngulp=8, gulp=2, buf=6, reader=True):
    """Write ``ngulp`` gulps into a ``buf``-frame ring with a
    registered (never-reading) guaranteed reader; returns the
    reader."""
    rd = None
    with ring.begin_writing() as w:
        with w.begin_sequence(_hdr(gulp), gulp_nframe=gulp,
                              buf_nframe=buf) as seq:
            if reader:
                rd = ring.open_earliest_sequence(guarantee=True)
            for i in range(ngulp):
                with seq.reserve(gulp) as sp:
                    sp.data[...] = np.full((gulp, 4), float(i),
                                           np.float32)
                    sp.commit(gulp)
    return rd


def _audit(rd, gulp=2):
    """Sequential consumer stepping gulp by gulp: returns
    (skipped_frames, first-values delivered)."""
    skipped, got, off = 0, [], 0
    while True:
        try:
            with rd.acquire(off, gulp) as isp:
                skipped += isp.nframe_skipped
                if isp.nframe:
                    got.append(float(isp.data.as_numpy()[0, 0]))
                off += gulp
        except EndOfDataStop:
            return skipped, got


# ---------------------------------------------------------------------------
# ring overload policies (both cores)
# ---------------------------------------------------------------------------

def test_drop_oldest_shed_is_byte_accurate(ring_core):
    """The acceptance audit: ring.<name>.shed_bytes must equal the
    gap a sequential guaranteed reader observes via nframe_skipped —
    and drop_oldest keeps the FRESHEST data flowing."""
    ring = Ring(space='system', name='do_%s' % ring_core)
    ring.set_overload_policy('drop_oldest')
    rd = _fill_ring(ring)                 # 16 frames into 6-frame ring
    skipped, got = _audit(rd)
    rd.close()
    stats = ring.shed_stats()
    assert stats['shed_bytes'] == skipped * FB > 0
    assert stats['shed_gulps'] == skipped // 2
    assert got == [5.0, 6.0, 7.0]         # newest data survived
    assert counters.get('ring.%s.shed_bytes' % ring.name) == \
        stats['shed_bytes']
    assert counters.get('ring.%s.shed_gulps' % ring.name) == \
        stats['shed_gulps']


def test_drop_newest_sheds_writer_side(ring_core):
    """drop_newest refuses the reserve without blocking: the writer's
    gulp lands in scratch, the commit is counted, the OLDEST buffered
    data survives intact."""
    ring = Ring(space='system', name='dn_%s' % ring_core)
    ring.set_overload_policy('drop_newest')
    rd = _fill_ring(ring)
    skipped, got = _audit(rd)
    rd.close()
    stats = ring.shed_stats()
    assert skipped == 0                   # nothing yanked from reader
    assert got == [0.0, 1.0, 2.0]         # oldest data survived
    assert stats['shed_gulps'] == 5
    assert stats['shed_bytes'] == 5 * 2 * FB


def test_block_policy_keeps_classic_backpressure(ring_core):
    """The default policy still blocks — and explicit nonblocking
    reserves keep their WouldBlock contract under every policy."""
    ring = Ring(space='system', name='bp_%s' % ring_core)
    assert ring.overload_policy == 'block'
    with ring.begin_writing() as w:
        with w.begin_sequence(_hdr(), gulp_nframe=2,
                              buf_nframe=6) as seq:
            rd = ring.open_earliest_sequence(guarantee=True)
            for i in range(3):
                with seq.reserve(2) as sp:
                    sp.data[...] = 0.0
                    sp.commit(2)
            with pytest.raises(WouldBlock):
                seq.reserve(2, nonblocking=True)
            rd.close()
    assert ring.shed_stats()['shed_bytes'] == 0


def test_drop_oldest_clamps_at_open_spans(ring_core):
    """A reader HOLDING a span pins the shed floor: drop_oldest must
    never invalidate an open span's zero-copy view — the writer
    blocks until the span releases, then sheds past it."""
    ring = Ring(space='system', name='pin_%s' % ring_core)
    ring.set_overload_policy('drop_oldest')
    done = []
    started = threading.Event()

    def writer():
        with ring.begin_writing() as w:
            with w.begin_sequence(_hdr(), gulp_nframe=2,
                                  buf_nframe=6) as seq:
                # one committed gulp so the reader can pin frame 0
                with seq.reserve(2) as sp:
                    sp.data[...] = 0.0
                    sp.commit(2)
                started.set()
                assert pinned.wait(10)
                for i in range(1, 8):
                    with seq.reserve(2) as sp:
                        sp.data[...] = float(i)
                        sp.commit(2)
                done.append(True)

    pinned = threading.Event()
    t = threading.Thread(target=writer, daemon=True)
    t.start()
    assert started.wait(10)
    rd = ring.open_earliest_sequence(guarantee=True)
    span = rd.acquire(0, 2)           # pins frames [0, 2)
    held = np.array(span.data.as_numpy(), copy=True)
    pinned.set()
    time.sleep(0.5)
    # writer wrote until the ring filled behind the pin, then blocked
    # (shedding cannot advance past the OPEN span)
    assert not done
    assert np.array_equal(span.data.as_numpy(), held)
    span.release()
    t.join(10)
    assert done, "writer never unblocked after the span released"
    rd.close()
    assert ring.shed_stats()['shed_bytes'] > 0


def test_drop_oldest_clean_under_ringcheck(ring_core):
    """The shadow protocol checker must accept drop_oldest's forced
    guarantee advance (shed_advance mirror) — no false
    guarantee_pin violation."""
    ringcheck.set_enabled(True)
    try:
        ring = Ring(space='system', name='rc_%s' % ring_core)
        ring.set_overload_policy('drop_oldest')
        rd = _fill_ring(ring)
        skipped, got = _audit(rd)
        rd.close()
        assert skipped > 0
        assert not ringcheck.violations()
    finally:
        ringcheck.set_enabled(False)
        ringcheck.reset()


def test_overload_stamp_on_next_sequence(ring_core):
    """New sequences on a drop-policy ring carry the cumulative
    ``_overload`` shed ledger in their header."""
    ring = Ring(space='system', name='st_%s' % ring_core)
    ring.set_overload_policy('drop_newest')
    rd = _fill_ring(ring)
    rd.close()
    with ring.begin_writing() as w:
        hdr2 = _hdr()
        hdr2['name'] = 'seq2'
        with w.begin_sequence(hdr2, gulp_nframe=2, buf_nframe=6) as s2:
            stamp = s2.header.get('_overload')
    assert stamp == {'policy': 'drop_newest', 'shed_gulps': 5,
                     'shed_bytes': 5 * 2 * FB}


def test_shed_age_slo_histogram(ring_core):
    """Sheds on a trace-context stream record the age of the dropped
    data on slo.shed_age_s (and never count SLO violations)."""
    from bifrost_tpu.header_standard import ensure_trace_context
    ring = Ring(space='system', name='sa_%s' % ring_core)
    ring.set_overload_policy('drop_newest')
    hdr = _hdr()
    ensure_trace_context(hdr)
    with ring.begin_writing() as w:
        with w.begin_sequence(hdr, gulp_nframe=2, buf_nframe=6) as seq:
            rd = ring.open_earliest_sequence(guarantee=True)
            for i in range(8):
                with seq.reserve(2) as sp:
                    sp.data[...] = 0.0
                    sp.commit(2)
            rd.close()
    h = histograms.get('slo.shed_age_s')
    assert h is not None and h.snapshot()['count'] == 5
    assert counters.get('slo.violations') == 0


def test_invalid_policy_rejected(ring_core):
    ring = Ring(space='system')
    with pytest.raises(ValueError, match='drop_latest'):
        ring.set_overload_policy('drop_latest')
    from bifrost_tpu.pipeline import resolve_overload_policy
    with bf.Pipeline(overload_policy='drop_sideways') as p:
        src = NumpySourceBlock([np.zeros((4, 3), np.float32)],
                               simple_header([-1, 3], 'f32'),
                               gulp_nframe=4)
        with pytest.raises(ValueError, match='drop_sideways'):
            resolve_overload_policy(src)


def test_policy_resolution_scope_and_env(monkeypatch):
    from bifrost_tpu.pipeline import resolve_overload_policy
    hdr = simple_header([-1, 3], 'f32')
    gulps = [np.zeros((4, 3), np.float32)]
    monkeypatch.setenv('BF_OVERLOAD_POLICY', 'drop_newest')
    with bf.Pipeline() as p:
        env_src = NumpySourceBlock(gulps, hdr, gulp_nframe=4)
        scoped = NumpySourceBlock(gulps, hdr, gulp_nframe=4,
                                  overload_policy='drop_oldest')
        assert resolve_overload_policy(env_src) == 'drop_newest'
        assert resolve_overload_policy(scoped) == 'drop_oldest'
    monkeypatch.delenv('BF_OVERLOAD_POLICY')
    with bf.Pipeline() as p2:
        plain = NumpySourceBlock(gulps, hdr, gulp_nframe=4)
        assert resolve_overload_policy(plain) is None


# ---------------------------------------------------------------------------
# static analysis: BF-E180 / BF-W181
# ---------------------------------------------------------------------------

def test_e180_guaranteed_reader_without_tolerance():
    hdr = simple_header([-1, 3], 'f32')
    gulps = [np.zeros((4, 3), np.float32)]
    with bf.Pipeline() as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=4,
                               overload_policy='drop_oldest')
        GatherSink(src)
        codes = [d.code for d in p.validate()]
    assert 'BF-E180' in codes
    # shed_tolerant consumers are fine
    with bf.Pipeline() as p2:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=4,
                               overload_policy='drop_oldest')
        GatherSink(src, shed_tolerant=True)
        codes = [d.code for d in p2.validate()]
    assert 'BF-E180' not in codes
    # unguaranteed consumers already contracted for loss
    with bf.Pipeline() as p3:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=4,
                               overload_policy='drop_oldest')
        GatherSink(src, guarantee=False)
        codes = [d.code for d in p3.validate()]
    assert 'BF-E180' not in codes


def test_w181_quota_below_one_span():
    hdr = simple_header([-1, 3], 'f32', gulp_nframe=4)
    gulps = [np.zeros((4, 3), np.float32)]
    with bf.Pipeline() as p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=4)
        bf.blocks.bridge_sink(src, '127.0.0.1', 9, quota_bytes_per_s=8)
        codes = [d.code for d in p.validate()]
    assert 'BF-W181' in codes
    with bf.Pipeline() as p2:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=4)
        bf.blocks.bridge_sink(src, '127.0.0.1', 9,
                              quota_bytes_per_s=1e6)
        codes = [d.code for d in p2.validate()]
    assert 'BF-W181' not in codes


# ---------------------------------------------------------------------------
# bridge sender: quotas + backoff + circuit breaker
# ---------------------------------------------------------------------------

def test_sender_quota_sheds_fairly_per_stream():
    """A tiny per-stream gulp quota under a drop policy sheds beyond
    the first token — counted on the bridge ledger and the per-stream
    split — while produced == delivered + shed holds."""
    from bifrost_tpu.io.bridge import (RingSender, RingReceiver,
                                       BridgeListener, connect)
    src_ring = Ring(space='system', name='qsrc')
    dst_ring = Ring(space='system', name='qdst')
    ngulp = 6

    def producer():
        with src_ring.begin_writing() as w:
            hdr = _hdr(gulp=2)
            from bifrost_tpu.header_standard import \
                ensure_trace_context
            ensure_trace_context(hdr)
            with w.begin_sequence(hdr, gulp_nframe=2,
                                  buf_nframe=2 * ngulp) as seq:
                for i in range(ngulp):
                    with seq.reserve(2) as sp:
                        sp.data[...] = float(i)
                        sp.commit(2)

    producer()
    lst = BridgeListener('127.0.0.1', 0)
    sender = RingSender(src_ring, gulp_nframe=2, window=4,
                        overload_policy='drop_newest',
                        quota_gulps_per_s=1e-6,
                        sock=connect('127.0.0.1', lst.port))
    receiver = RingReceiver(lst, dst_ring)
    rt = threading.Thread(target=receiver.run, daemon=True)
    rt.start()
    sender.run()
    rt.join(10)
    assert not rt.is_alive()
    sender.close()
    receiver.close()
    stats = sender.shed_stats()
    # capacity = max(rate, 1) = 1 gulp token: exactly one gulp ships
    assert stats['shed_gulps'] == ngulp - 1
    assert counters.get('bridge.tx.quota_shed_gulps') == ngulp - 1
    assert counters.get('bridge.tx.shed_bytes') == \
        (ngulp - 1) * 2 * FB
    assert len(stats['by_stream']) == 1
    # delivered + shed == produced (frames)
    with dst_ring.open_earliest_sequence(guarantee=True) as rd:
        got = 0
        off = 0
        while True:
            try:
                with rd.acquire(off, 2) as isp:
                    got += isp.nframe
                    off += 2
            except EndOfDataStop:
                break
    assert got // 2 + stats['shed_gulps'] == ngulp


def test_retry_backoff_is_full_jitter():
    from bifrost_tpu.io.udp_socket import retry_backoff_s
    for attempt in (1, 3, 8):
        vals = [retry_backoff_s(attempt, backoff=0.01, cap=0.05)
                for _ in range(200)]
        bound = min(0.01 * 2 ** (attempt - 1), 0.05)
        assert all(0.0 <= v <= bound for v in vals)
        # full jitter: values spread over the window, not pinned at it
        assert min(vals) < bound / 4
        assert len(set(round(v, 6) for v in vals)) > 10


def test_circuit_breaker_fast_fails_then_half_opens(monkeypatch):
    from bifrost_tpu.blocks.bridge import (_CircuitBreaker,
                                           CircuitOpenError)
    monkeypatch.setenv('BF_BRIDGE_COOLOFF_SECS', '0.2')
    br = _CircuitBreaker()
    br.check('peer')                 # closed: no-op
    br.failure()
    with pytest.raises(CircuitOpenError):
        br.check('peer')
    time.sleep(0.25)
    br.check('peer')                 # half-open probe admitted
    br.success()
    br.check('peer')                 # closed again


def test_recover_exhaustion_counts_circuit_open():
    """A sender whose redial budget is exhausted counts
    bridge.circuit_open and aborts with the transport error."""
    from bifrost_tpu.io.bridge import RingSender
    ring = Ring(space='system', name='cx')
    sender = RingSender(ring, sock=[], reconnect=None,
                        reconnect_max=0)
    with pytest.raises(ConnectionError):
        sender._recover(ConnectionError('dead link'))
    assert counters.get('bridge.circuit_open') == 1


# ---------------------------------------------------------------------------
# health state machine
# ---------------------------------------------------------------------------

def _mini_pipeline():
    hdr = simple_header([-1, 3], 'f32')
    gulps = [np.zeros((4, 3), np.float32)]
    p = bf.Pipeline()
    with p:
        src = NumpySourceBlock(gulps, hdr, gulp_nframe=4)
        sink = GatherSink(src)
    return p, src, sink


def test_health_monitor_traversal_and_hysteresis(monkeypatch):
    from bifrost_tpu.supervision import Supervisor, HealthMonitor
    monkeypatch.setenv('BF_HEALTH_HYSTERESIS', '2')
    p, src, sink = _mini_pipeline()
    p.supervisor = Supervisor(p)
    mon = HealthMonitor(p.supervisor, 0.0)
    assert mon.evaluate()['state'] == 'OK'
    # shed counters moving -> SHEDDING, attributed to the ring owner
    oring = src.orings[0]
    counters.inc('ring.%s.shed_gulps' % oring.name, 3)
    snap = mon.evaluate()
    assert snap['state'] == 'SHEDDING'
    assert snap['blocks'][src.name] == 'SHEDDING'
    assert src.health_state == 'SHEDDING'
    # hysteresis: one clean tick holds, the second recovers
    assert mon.evaluate()['state'] == 'SHEDDING'
    snap = mon.evaluate()
    assert snap['state'] == 'OK'
    assert src.health_state == 'OK'
    assert counters.get('health.transitions') >= 2
    # SLO violations -> DEGRADED
    counters.inc('slo.violations')
    assert mon.evaluate()['state'] == 'DEGRADED'
    # abort -> FAILED (terminal)
    p.supervisor.abort_event.set()
    assert mon.evaluate()['state'] == 'FAILED'
    assert len(mon.snapshot()['transitions']) >= 3


def test_health_on_health_hook(monkeypatch):
    from bifrost_tpu.supervision import Supervisor, HealthMonitor
    monkeypatch.setenv('BF_HEALTH_HYSTERESIS', '1')
    p, src, sink = _mini_pipeline()
    seen = []
    src.on_health = lambda state, prev: seen.append((prev, state))
    p.supervisor = Supervisor(p)
    mon = HealthMonitor(p.supervisor, 0.0)
    counters.inc('ring.%s.shed_gulps' % src.orings[0].name)
    mon.evaluate()
    mon.evaluate()
    assert ('OK', 'SHEDDING') in seen
    assert ('SHEDDING', 'OK') in seen


def test_pipeline_health_api_without_run():
    p, src, sink = _mini_pipeline()
    h = p.health()
    assert h['state'] == 'OK'
    assert set(h['blocks']) == {src.name, sink.name}


def test_health_live_during_shedding_pipeline():
    """End-to-end: a drop_oldest pipeline with a slow consumer sheds,
    and Pipeline.health() reflects SHEDDING during the run and OK-ish
    terminal states after.

    The slow consumer idles BETWEEN spans (release, then sleep): a
    reader that sleeps while HOLDING its span clamps the guarantee
    advance at the open span, so drop_oldest degrades to plain
    backpressure there — only unread backlog can be shed, never data
    the reader has consumed or is consuming.  (The windowed bridge
    reader sheds the same way: its no-open-spans windows are where
    the backlog skips happen.)  The ledger is byte-exact: produced ==
    delivered + shed, with shed == the skips the reader observes."""
    hdr = simple_header([-1, 3], 'f32')
    hdr['gulp_nframe'] = 4
    NG = 120
    gulps = [np.full((4, 3), float(k), np.float32)
             for k in range(NG)]
    states = []
    got_frames = [0]
    skipped_frames = [0]
    done = threading.Event()

    class PacedSource(NumpySourceBlock):
        # 2x faster than the consumer: the backlog (and the counted
        # shedding) persists long enough for the 0.5 s health ticks
        # to observe it
        def on_data(self, reader, ospans):
            time.sleep(0.01)
            return NumpySourceBlock.on_data(self, reader, ospans)

    with bf.Pipeline() as p:
        src = PacedSource(gulps, hdr, gulp_nframe=4,
                          overload_policy='drop_oldest',
                          buffer_factor=2)
        ring = src.orings[0]

        def consume():
            # external guaranteed reader, bridge-style explicit
            # acquire/release: copy a span, RELEASE it, then idle —
            # the no-open-spans idle window is where the unpaced
            # producer sheds the unread backlog (counted)
            from bifrost_tpu.ring import EndOfDataStop
            try:
                for seq in ring.read(guarantee=True):
                    offset = 0
                    while True:
                        try:
                            span = seq.acquire(offset, 4)
                        except EndOfDataStop:
                            break
                        # the whole gap skipped in one hop counts
                        # (nframe_skipped caps at the span size)
                        skipped_frames[0] += \
                            span.frame_offset - offset
                        advanced = span.frame_offset + span.nframe
                        nframe = span.nframe
                        if nframe:
                            got_frames[0] += nframe
                            span.data.as_numpy()
                        span.release()
                        if nframe == 0:
                            # lapped, not end-of-data: skip forward
                            if advanced <= offset:
                                break
                        offset = advanced
                        if nframe:
                            time.sleep(0.02)
            except Exception:
                pass
            finally:
                done.set()

        def sample():
            while not done.wait(0.05):
                states.append(p.health()['state'])

        ct = threading.Thread(target=consume, daemon=True)
        st = threading.Thread(target=sample, daemon=True)
        ct.start()
        st.start()
        p.run()
        ct.join(timeout=30)
    shed = ring.shed_stats()
    assert shed['shed_bytes'] > 0
    assert 'SHEDDING' in states
    # byte-exact audit: every produced frame was either delivered or
    # counted shed — and the shed ledger equals the reader's own skip
    # observation (no double count of consumed spans)
    frame_nbyte = 3 * 4
    assert shed['shed_bytes'] == skipped_frames[0] * frame_nbyte
    assert got_frames[0] + skipped_frames[0] == NG * 4
