"""Macro-gulp execution (bifrost_tpu.macro; docs/perf.md): K-gulp
batched dispatch must be byte-identical to K=1, amortize dispatches
K-fold on the telemetry counters, flush partial batches at sequence
end, and fall back to K=1 for every ineligible topology."""

import os

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.macro import (resolve_gulp_batch, chain_batch_mode,
                               build_batched_fn)
from bifrost_tpu.stages import (FftStage, DetectStage, ReduceStage,
                                Stage)
from bifrost_tpu.telemetry import counters
from tests.util import NumpySourceBlock, GatherSink, simple_header

NT, NP, NF, RF = 32, 2, 64, 4


def _voltages(ngulp, seed=3):
    rng = np.random.RandomState(seed)
    gulps = []
    for _ in range(ngulp):
        raw = np.zeros((NT, NP, NF), dtype=np.dtype([('re', 'i1'),
                                                     ('im', 'i1')]))
        raw['re'] = rng.randint(-64, 64, raw.shape)
        raw['im'] = rng.randint(-64, 64, raw.shape)
        gulps.append(raw)
    return gulps


def _hdr():
    return simple_header([-1, NP, NF], 'ci8',
                         labels=['time', 'pol', 'fine_time'])


def _run_chain(gulp_batch, ngulp, donate=None, **scope):
    counters.reset()
    with bf.Pipeline(gulp_batch=gulp_batch, donate=donate,
                     **scope) as p:
        src = NumpySourceBlock(_voltages(ngulp), _hdr(),
                               gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(
            b, [FftStage('fine_time', axis_labels='freq'),
                DetectStage('stokes', axis='pol'),
                ReduceStage('freq', RF)])
        b2 = bf.blocks.copy(fb, space='system')
        sink = GatherSink(b2)
        p.run()
    snap = counters.snapshot()

    def block_counter(frag, kind):
        return sum(v for k, v in snap.items()
                   if k.startswith('block.') and frag in k
                   and k.endswith('.' + kind))
    return sink.result(), fb, snap, block_counter


# ---------------------------------------------------------------------------
# correctness + amortization
# ---------------------------------------------------------------------------

def test_batched_chain_identical_and_amortized():
    """K=4 over 8 gulps: identical output stream, fused dispatches
    drop 4x, logical gulp counters unchanged."""
    out1, _, _, c1 = _run_chain(1, 8)
    d1, g1 = c1('Fused', 'dispatches'), c1('Fused', 'gulps')
    out4, fb4, snap4, c4 = _run_chain(4, 8)
    d4, g4 = c4('Fused', 'dispatches'), c4('Fused', 'gulps')
    assert np.array_equal(out1, out4)
    assert (d1, g1) == (8, 8)
    assert (d4, g4) == (2, 8)
    # the amortization is observable as the dispatches/gulp ratio
    assert d4 / g4 <= (d1 / g1) / 4.0 + 1e-9
    # the copy blocks batch too (device movers are macro-eligible)
    assert c4('Copy', 'dispatches') < c4('Copy', 'gulps')
    # the executed plan records the batch mode
    assert fb4.impl_info.get('batch') == 4
    assert fb4.impl_info.get('batch_mode') == 'block'


def test_partial_batch_flushes_at_sequence_end():
    """ngulp not a multiple of K: the tail flushes as a partial batch
    and the stream is still byte-identical."""
    out1, _, _, _ = _run_chain(1, 6)
    out4, _, _, c4 = _run_chain(4, 6)
    assert np.array_equal(out1, out4)
    # one full batch of 4 + one partial batch of 2
    assert c4('Fused', 'dispatches') == 2
    assert c4('Fused', 'gulps') == 6


def test_env_var_enables_batching(monkeypatch):
    monkeypatch.setenv('BF_GULP_BATCH', '4')
    out, _, _, c = _run_chain(None, 8)
    assert c('Fused', 'dispatches') == 2
    out1, _, _, _ = _run_chain(1, 8)
    assert np.array_equal(out, out1)


def test_macro_donation_hits_and_identical():
    """Donation composes with macro spans: the upstream macro commit
    is claimed exclusively and the donating macro plan publishes its
    donate_argnums."""
    out1, _, _, _ = _run_chain(1, 8)
    out4, fb4, snap4, _ = _run_chain(4, 8, donate=True)
    assert np.array_equal(out1, out4)
    assert snap4.get('donation.hits', 0) > 0
    assert fb4.impl_info.get('donate_argnums') == [0]


def test_ring_gulp_counters_count_logical_gulps():
    """ring.<name>.gulps stays a LOGICAL gulp counter when K gulps are
    committed in one span (both the batched device rings and the K=1
    source ring read 8)."""
    _, _, snap, _ = _run_chain(4, 8)
    ring_gulps = [v for k, v in snap.items()
                  if k.startswith('ring.') and k.endswith('.gulps')]
    assert ring_gulps and all(v == 8 for v in ring_gulps)


# ---------------------------------------------------------------------------
# eligibility fallbacks
# ---------------------------------------------------------------------------

def test_host_blocks_fall_back():
    """A host->host chain has no macro-eligible block: K requested but
    every dispatch stays 1:1 and the fallback is counted."""
    counters.reset()
    with bf.Pipeline(gulp_batch=4) as p:
        src = NumpySourceBlock(_voltages(6), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src)            # system -> system
        sink = GatherSink(b)
        p.run()
    snap = counters.snapshot()
    disp = sum(v for k, v in snap.items()
               if k.startswith('block.') and 'Copy' in k
               and k.endswith('.dispatches'))
    gulps = sum(v for k, v in snap.items()
                if k.startswith('block.') and 'Copy' in k
                and k.endswith('.gulps'))
    assert disp == gulps == 6
    assert snap.get('macro.fallback.block', 0) > 0


def test_multi_reader_ring_batches():
    """Two consumers on the fused block's input ring: formerly a K=1
    fallback (``macro.fallback.multi_reader``), retired in PR 6 — each
    reader's guarantee independently pins its own oldest open span, so
    a K-gulp macro acquire cannot wedge a peer.  Both consumers must
    see the full correct stream, the fused block must actually batch,
    and the retirement must be counted."""
    counters.reset()
    with bf.Pipeline(gulp_batch=4) as p:
        src = NumpySourceBlock(_voltages(8), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        fb = bf.blocks.fused(
            b, [FftStage('fine_time', axis_labels='freq'),
                DetectStage('stokes', axis='pol'),
                ReduceStage('freq', RF)])
        # second consumer of the h2d ring
        b_tap = bf.blocks.copy(b, space='system')
        sink1 = GatherSink(bf.blocks.copy(fb, space='system'))
        sink2 = GatherSink(b_tap)
        p.run()
    snap = counters.snapshot()
    assert snap.get('macro.fallback.multi_reader', 0) == 0
    assert snap.get('macro.fallback.multi_reader_retired', 0) > 0
    fused_disp = sum(v for k, v in snap.items()
                     if 'Fused' in k and k.endswith('.dispatches'))
    fused_gulps = sum(v for k, v in snap.items()
                      if 'Fused' in k and k.endswith('.gulps'))
    assert fused_gulps == 8
    assert fused_disp == 2            # 8 gulps / K=4 -> 2 dispatches
    # the tap consumer saw every gulp, unmangled by the macro peer
    base, _fb, _s, _bc = _run_chain(1, 8)
    assert sink1.result() is not None
    np.testing.assert_array_equal(sink1.result(), base)
    raw = np.concatenate([g['re'].astype(np.int8) for g in _voltages(8)])
    np.testing.assert_array_equal(sink2.result()['re'].astype(np.int8),
                                  raw)


def test_overlap_falls_back():
    """FIR-style input overlap is incompatible with macro spans."""
    from bifrost_tpu.pipeline import TransformBlock

    class OverlapIdent(TransformBlock):
        def on_sequence(self, iseq):
            from copy import deepcopy
            return deepcopy(iseq.header)

        def define_input_overlap_nframe(self, iseq):
            return 4

        def define_output_nframes(self, input_nframe):
            return input_nframe - 4

        def macro_gulp_safe(self):
            return True               # overlap must still veto

        def on_data(self, ispan, ospan):
            d = ispan.data
            ospan.set(d[4:] if ospan.ring.space == 'tpu'
                      else d.as_numpy()[4:])

    counters.reset()
    with bf.Pipeline(gulp_batch=4) as p:
        src = NumpySourceBlock(_voltages(6), _hdr(), gulp_nframe=NT)
        b = bf.blocks.copy(src, space='tpu')
        ob = OverlapIdent(b)
        sink = GatherSink(bf.blocks.copy(ob, space='system'))
        p.run()
    snap = counters.snapshot()
    assert snap.get('macro.fallback.overlap', 0) > 0


def test_resolve_gulp_batch_sources(monkeypatch):
    scope = bf.Pipeline(gulp_batch=8)
    assert resolve_gulp_batch(scope) == 8
    monkeypatch.setenv('BF_GULP_BATCH', '16')
    assert resolve_gulp_batch(bf.Pipeline()) == 16
    monkeypatch.setenv('BF_GULP_BATCH', 'junk')
    assert resolve_gulp_batch(bf.Pipeline()) == 1
    monkeypatch.delenv('BF_GULP_BATCH')
    assert resolve_gulp_batch(bf.Pipeline()) == 1


# ---------------------------------------------------------------------------
# the batched-fn builder (sliced mode) and stage classification
# ---------------------------------------------------------------------------

def test_chain_batch_mode_classification():
    assert chain_batch_mode([FftStage('fine_time'),
                             DetectStage('stokes', axis='pol')]) \
        == 'block'

    class Custom(Stage):
        pass
    assert chain_batch_mode([FftStage('fine_time'), Custom()]) \
        == 'sliced'


def test_sliced_batched_fn_matches_per_gulp():
    """The lax.map sliced path (used when a stage is not provably
    batch-safe) equals per-gulp application exactly, including the
    statically-shaped partial tail."""
    import jax.numpy as jnp
    G, K_FULL, REM = 8, 3, 5      # 29 frames: 3 full gulps + tail 5
    n = G * K_FULL + REM
    x = np.random.RandomState(0).randn(n, 4).astype(np.float32)

    def per_gulp_for_shape(shape):
        # a fn that depends on the per-gulp shape (cumsum along time)
        return lambda a: jnp.cumsum(a, axis=0)

    fn = build_batched_fn(per_gulp_for_shape, 0, 0, G,
                          [(n, 4)], 'sliced')
    got = np.asarray(fn(jnp.asarray(x)))
    want = np.concatenate(
        [np.cumsum(x[i:i + G], axis=0)
         for i in range(0, n, G)], axis=0)
    # XLA's cumsum association differs from numpy's in f32 ULPs
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_sliced_batched_fn_multi_part_concat():
    import jax.numpy as jnp
    G = 4
    a = np.arange(16, dtype=np.float32).reshape(8, 2)
    b = np.arange(16, 32, dtype=np.float32).reshape(8, 2)

    def per_gulp_for_shape(shape):
        return lambda v: v * 2.0

    fn = build_batched_fn(per_gulp_for_shape, 0, 0, G,
                          [(8, 2), (8, 2)], 'sliced')
    got = np.asarray(fn(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(
        got, np.concatenate([a, b], axis=0) * 2.0)


# ---------------------------------------------------------------------------
# impl-proclog republish (executed-path changes)
# ---------------------------------------------------------------------------

def test_impl_republish_on_executed_path_change():
    """The published impl record must track the EXECUTED path: donate
    toggling mid-sequence republishes both ways, and a macro batch
    engaging publishes its batch fields."""
    import jax.numpy as jnp
    from bifrost_tpu.blocks.fused import FusedBlock
    from bifrost_tpu.ring import Ring

    with bf.Pipeline():
        ring = Ring(space='tpu')
        fb = FusedBlock(ring, [DetectStage('stokes', axis='pol')])
    hdr = simple_header([-1, NP, NF], 'cf32',
                        labels=['time', 'pol', 'freq'])
    hdr['gulp_nframe'] = NT
    fb._headers = [hdr,
                   fb.stages[0].transform_header(hdr)]
    x = jnp.zeros((NT, NP, NF, 2), jnp.float32)

    fb._execute_plan(x)
    base = dict(fb.impl_info)
    assert 'donate_argnums' not in base

    fb._execute_plan(jnp.zeros_like(x), donate=True)
    assert fb.impl_info.get('donate_argnums') == [0]

    # toggling BACK must republish the non-donating record (the
    # regression this satellite fixes: a cached plan key re-executing
    # must refresh impl_info/_published_impl)
    fb._execute_plan(x)
    assert 'donate_argnums' not in fb.impl_info
    assert fb._published_impl == fb.impl_info

    mx = jnp.zeros((NT * 4, NP, NF, 2), jnp.float32)
    fb._execute_macro([mx], donate=False, gulp_nframe=NT)
    assert fb.impl_info.get('batch') == 4
    assert fb.impl_info.get('batch_mode') == 'block'


# ---------------------------------------------------------------------------
# xfer: batched H2D staging
# ---------------------------------------------------------------------------

def test_to_device_batch_one_call_k_gulps():
    from bifrost_tpu import xfer
    counters.reset()
    rng = np.random.RandomState(1)
    gulps = [rng.randn(16, 8).astype(np.float32) for _ in range(4)]
    before = counters.get('xfer.h2d_issued')
    out = xfer.to_device_batch(gulps)
    assert counters.get('xfer.h2d_issued') == before + 1
    assert counters.get('xfer.h2d_batched') == 4
    assert out.shape == (4, 16, 8)
    for i in range(4):
        np.testing.assert_array_equal(np.asarray(out[i]), gulps[i])


def test_to_device_batch_rejects_ragged():
    from bifrost_tpu import xfer
    with pytest.raises(ValueError):
        xfer.to_device_batch([np.zeros((4, 4), np.float32),
                              np.zeros((4, 5), np.float32)])
