"""UDP loopback harness: writer thread transmits packets through
localhost into a UDPCapture feeding a ring, reader asserts on the result
(the reference's multi-node-without-a-cluster pattern,
reference: test/test_udp_io.py:63-130)."""

import socket
import threading
import time

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.io.udp_socket import Address, UDPSocket
from bifrost_tpu.io.packet_capture import (UDPCapture, DiskReader,
                                           PacketCaptureCallback,
                                           CAPTURE_NO_DATA,
                                           CAPTURE_INTERRUPTED)
from bifrost_tpu.io.packet_formats import (TbnFormat, CorFormat,
                                            VdifFormat)
from bifrost_tpu.io.packet_writer import HeaderInfo, UDPTransmit, DiskWriter
from bifrost_tpu.ring import Ring


PAYLOAD = 64          # bytes per packet
NSRC = 2
BUF_NTIME = 8


def _capture_header(desc):
    hdr = {
        'name': 'udp-test',
        '_tensor': {
            'shape': [-1, NSRC, PAYLOAD],
            'dtype': 'u8',
            'labels': ['time', 'src', 'byte'],
            'scales': [[0, 1]] * 3,
            'units': [None] * 3,
        },
    }
    return 0, hdr


def _run_capture(capture, max_iters=100):
    for _ in range(max_iters):
        status = capture.recv()
        if status in (CAPTURE_NO_DATA, CAPTURE_INTERRUPTED):
            break
    capture.end()


import pytest


@pytest.fixture(params=['native', 'python'])
def capture_engine(request, monkeypatch):
    """Run loopback tests against BOTH capture engines: the native C++
    engine (native/capture.cpp, auto-selected) and the Python engine
    (BF_NO_NATIVE_CAPTURE=1)."""
    if request.param == 'python':
        monkeypatch.setenv('BF_NO_NATIVE_CAPTURE', '1')
    else:
        from bifrost_tpu import native
        if not native.available():
            pytest.skip('native library unavailable')
    return request.param


def test_udp_loopback_chips(capture_engine):
    addr = Address('127.0.0.1', 0)
    rx = UDPSocket().bind(addr)
    port = rx.sock.getsockname()[1]
    rx.set_timeout(0.4)
    tx_sock = UDPSocket().connect(Address('127.0.0.1', port))

    ring = Ring(space='system', name='udp_rx')
    cb = PacketCaptureCallback()
    cb.set_chips(_capture_header)
    capture = UDPCapture('chips', rx, ring, NSRC, 0, PAYLOAD,
                         BUF_NTIME, BUF_NTIME, cb)

    NSEQ = 32
    rng = np.random.RandomState(0)
    data = rng.randint(0, 255, size=(NSEQ, NSRC, PAYLOAD)).astype(np.uint8)
    reader_attached = threading.Event()

    def transmit():
        hi = HeaderInfo()
        hi.set_nsrc(NSRC)
        with UDPTransmit('chips', tx_sock) as tx:
            # first packet opens the sequence; wait for the reader's
            # guarantee before streaming the rest
            # chips wire sequence numbers are 1-based
            tx.send(hi, 1, 1, 0, 1, data[:1])
            assert reader_attached.wait(30)
            tx.send(hi, 2, 1, 0, 1, data[1:])
        pad = np.zeros((BUF_NTIME * 2, NSRC, PAYLOAD), np.uint8)
        with UDPTransmit('chips', tx_sock) as tx:
            tx.send(hi, NSEQ + 1, 1, 0, 1, pad)

    got = []

    def read_ring():
        for seq in ring.read(guarantee=True):
            reader_attached.set()
            for span in seq.read(BUF_NTIME):
                got.append(np.array(span.data.as_numpy(), copy=True))

    # reader must attach before the capture can lap the ring
    reader = threading.Thread(target=read_ring)
    reader.start()
    cap_thread = threading.Thread(target=_run_capture, args=(capture,))
    cap_thread.start()
    t = threading.Thread(target=transmit)
    t.start()
    t.join()
    cap_thread.join()
    reader.join()
    out = np.concatenate(got, axis=0)
    assert out.shape[0] >= NSEQ
    np.testing.assert_array_equal(out[:NSEQ], data)
    assert capture.stats['ngood_bytes'] > 0
    from bifrost_tpu.io.packet_capture import NativeUDPCapture
    is_native = isinstance(capture, NativeUDPCapture)
    assert is_native == (capture_engine == 'native')


def test_udp_loopback_with_packet_loss(capture_engine):
    """Dropped packets leave zeroed slots; loss is accounted per source."""
    addr = Address('127.0.0.1', 0)
    rx = UDPSocket().bind(addr)
    port = rx.sock.getsockname()[1]
    rx.set_timeout(0.4)
    tx_sock = UDPSocket().connect(Address('127.0.0.1', port))

    ring = Ring(space='system', name='udp_rx_loss')
    cb = PacketCaptureCallback()
    cb.set_chips(_capture_header)
    capture = UDPCapture('chips', rx, ring, NSRC, 0, PAYLOAD,
                         BUF_NTIME, BUF_NTIME, cb)

    NSEQ = BUF_NTIME
    data = np.full((NSEQ, NSRC, PAYLOAD), 7, np.uint8)

    reader_attached = threading.Event()

    def transmit():
        hi = HeaderInfo()
        hi.set_nsrc(NSRC)
        with UDPTransmit('chips', tx_sock) as tx:
            # drop seq 3 of src 1 by sending packets individually
            for i in range(NSEQ):
                for j in range(NSRC):
                    if i == 3 and j == 1:
                        continue
                    tx.send(hi, i + 1, 1, j, 1, data[i:i+1, j:j+1])
                if i == 0:
                    assert reader_attached.wait(30)
            pad = np.zeros((BUF_NTIME * 2, NSRC, PAYLOAD), np.uint8)
            tx.send(hi, NSEQ + 1, 1, 0, 1, pad)

    got = []

    def read_ring():
        for seq in ring.read(guarantee=True):
            reader_attached.set()
            for span in seq.read(BUF_NTIME):
                got.append(np.array(span.data.as_numpy(), copy=True))

    reader = threading.Thread(target=read_ring)
    reader.start()
    cap_thread = threading.Thread(target=_run_capture, args=(capture,))
    cap_thread.start()
    t = threading.Thread(target=transmit)
    t.start()
    t.join()
    cap_thread.join()
    reader.join()
    out = np.concatenate(got, axis=0)
    # dropped packet -> zeros at (3, src 1); others intact
    assert np.all(out[3, 1] == 0)
    assert np.all(out[3, 0] == 7)
    assert np.all(out[2, 1] == 7)
    assert capture.stats['nmissing_bytes'] >= PAYLOAD


def test_disk_packet_roundtrip(tmp_path):
    """DiskWriter -> DiskReader capture (replayable ingest)."""
    path = str(tmp_path / 'packets.dat')
    NSEQ = 16
    rng = np.random.RandomState(1)
    data = rng.randint(0, 255, size=(NSEQ, NSRC, PAYLOAD)).astype(np.uint8)
    hi = HeaderInfo()
    hi.set_nsrc(NSRC)
    with open(path, 'wb') as f:
        with DiskWriter('chips', f) as dw:
            dw.send(hi, 1, 1, 0, 1, data)
            pad = np.zeros((BUF_NTIME * 2, NSRC, PAYLOAD), np.uint8)
            dw.send(hi, NSEQ + 1, 1, 0, 1, pad)

    ring = Ring(space='system', name='disk_rx')
    cb = PacketCaptureCallback()
    cb.set_chips(_capture_header)
    with open(path, 'rb') as f:
        capture = DiskReader('chips', f, ring, NSRC, 0, PAYLOAD,
                             BUF_NTIME, BUF_NTIME, cb)
        cap_thread = threading.Thread(target=_run_capture,
                                      args=(capture,))
        cap_thread.start()
        got = []
        for seq in ring.read(guarantee=True):
            for span in seq.read(BUF_NTIME):
                got.append(np.array(span.data.as_numpy(), copy=True))
        cap_thread.join()
    out = np.concatenate(got, axis=0)
    np.testing.assert_array_equal(out[:NSEQ], data)


def test_format_roundtrips():
    """pack -> unpack round trips under the reference wire conventions.

    These complement tests/test_wire_formats.py's golden-bytes fixtures:
    golden bytes prove the layouts; this proves the codec pairs compose
    the way the reference decoder/filler pairs do (including their
    1-based/derived-field conventions)."""
    from bifrost_tpu.io.packet_formats import get_format, PacketDesc
    payload = bytes(range(32))

    def rt(name, desc, **kwargs):
        fmt = get_format(name, **kwargs) if kwargs else get_format(name)
        return fmt.unpack(fmt.pack(desc))

    back = rt('simple', PacketDesc(seq=1234, payload=payload))
    assert back.seq == 1234 and back.payload == payload

    # chips: wire seq is 1-based; filler writes the caller's value
    # verbatim and the decoder subtracts 1 (chips.hpp:64,182)
    back = rt('chips', PacketDesc(seq=1235, src=1, nsrc=4, chan0=32,
                                  nchan=16, tuning=7, payload=payload))
    assert back.seq == 1234 and back.src == 1 and back.chan0 == 32
    assert back.nchan == 16 and back.nsrc == 4 and back.tuning == 7
    assert back.payload == payload

    # ibeam: like chips, wire seq is 1-based and the filler writes the
    # caller's value verbatim -> the pair round-trips to seq-1
    back = rt('ibeam', PacketDesc(seq=1235, src=1, nsrc=4, chan0=32,
                                  nchan=16, payload=payload))
    assert back.seq == 1234 and back.src == 1 and back.chan0 == 32

    # pbeam: decoder src = beam*nserver + server-1 from the 1-based wire
    # beam while the filler writes beam = src//nserver + 1, so the pair
    # round-trips with a +nserver offset (absorbed by capture src0)
    # (like tbn, the writer's seq is the raw wire timestamp)
    back = rt('pbeam', PacketDesc(seq=1234 * 10, src=1, nsrc=4, chan0=32,
                                  nchan=16, decimation=10,
                                  payload=payload))
    assert back.seq == 1234 and back.decimation == 10
    assert back.src == 1 + 4        # + nserver
    assert back.chan0 == 32 - 16 * back.src

    # tbn: the writer's seq IS the wire time_tag (tbn.hpp:139)
    back = rt('tbn', PacketDesc(seq=512 * 10 * 1234, src=1, tuning=77,
                                gain=3, payload=b'\x00' * 1024),
              decimation=10)
    assert back.seq == 1234 and back.src == 1
    assert back.tuning == 77 and back.gain == 3

    # drx: desc.src carries the raw wire ID byte on pack; unpack
    # decodes (tuning-1)<<1 | pol from it
    wire_id = 1 | (2 << 3) | (1 << 7)    # beam 1, tuning 2, pol 1
    back = rt('drx', PacketDesc(seq=4096 * 10 * 99, src=wire_id,
                                tuning=77, decimation=10,
                                payload=b'\x00' * 4096))
    assert back.seq == 99 and back.src == 3 and back.beam == 0
    assert back.tuning1 == 77      # src 3 -> second tuning slot
    back = rt('drx8', PacketDesc(seq=4096 * 10 * 99, src=1 | (1 << 3),
                                 tuning=77, decimation=10,
                                 payload=b'\x00' * 8192))
    assert back.seq == 99 and back.src == 0 and back.tuning == 77

    # cor: src enumerates (baseline, server); tuning carries
    # (nchan_decim, nserver, server)
    from bifrost_tpu.io.packet_formats import CorFormat
    fmt = CorFormat(nsrc=6)
    desc = PacketDesc(seq=196000000 * 2 * 50, src=2, nsrc=3,
                      tuning=(2 << 8) | 1, gain=3, decimation=200,
                      payload=payload)
    back = fmt.unpack(fmt.pack(desc))
    assert back.seq == 50 and back.gain == 3 and back.decimation == 200
    # decoder re-encodes tuning as (nserver << 8) | (server - 1)
    assert back.tuning == (2 << 8) | 0
    # baseline src=2 of 3 -> stand pair (1,1); decode composes
    # (stand0*(2*(nstand-1)+1-stand0)//2 + stand1 + 1)*nserver + server-1
    assert back.src == (1 * (2 * 1 + 1 - 1) // 2 + 1 + 1) * 2 + 0

    back = rt('snap2', PacketDesc(seq=1234, src=1, nsrc=4, chan0=32,
                                  nchan=16, npol=2, npol_tot=2,
                                  payload=payload))
    assert back.seq == 1234 and back.nchan == 16
    assert back.chan0 == 1 * 16    # chan_block_id * nchan

    back = rt('vdif', PacketDesc(seq=1234, src=1, payload=payload))
    assert back.seq == 1234 and back.src == 1
    assert back.payload == payload

    back = rt('tbf', PacketDesc(seq=1234, src=300, nsrc=64,
                                payload=payload))
    assert back.seq == 1234 and back.src == 300 and back.nsrc == 64

    back = rt('vbeam', PacketDesc(seq=1234, time_tag=99, nchan=16,
                                  chan0=32, npol=2, payload=payload))
    assert back.seq == 1234 and back.nchan == 16 and back.chan0 == 32


def test_udp_sniffer_loopback():
    """Raw-socket sniffer sees UDP datagrams addressed to its port and
    strips IP+UDP headers (reference: packet_capture.hpp:287)."""
    import struct
    from bifrost_tpu.io.packet_capture import UDPSniffer
    try:
        rx = UDPSocket().bind(Address('127.0.0.1', 0))
        port = rx.sock.getsockname()[1]
        ring = Ring(space='system', name='sniff_rx')

        def cb(desc):
            return 0, {'name': 'sniff', '_tensor': {
                'shape': [-1, 1, 32], 'dtype': 'u8',
                'labels': ['time', 'src', 'byte'],
                'scales': [[0, 1]] * 3, 'units': [None] * 3}}

        sniff = UDPSniffer('simple', Address('127.0.0.1', port), ring,
                           1, 0, 32, 8, 8, cb)
    except PermissionError:
        import pytest
        pytest.skip("raw sockets need CAP_NET_RAW")
    sniff.set_timeout(0.5)
    tx = UDPSocket().connect(Address('127.0.0.1', port))
    payload = bytes(range(32))
    tx.send(struct.pack('>Q', 7) + payload)
    pkt = sniff._recv_packet()
    assert pkt is not None
    d = sniff.fmt.unpack(pkt)
    assert d.seq == 7 and bytes(d.payload) == payload
    sniff.close()
    tx.close()
    rx.close()


def test_send_recv_mmsg_roundtrip():
    """sendmmsg/recvmmsg batched syscalls round-trip datagrams in order
    with reusable scatter/gather state."""
    rx = UDPSocket().bind(Address('127.0.0.1', 0))
    port = rx.sock.getsockname()[1]
    rx.set_timeout(0.5)
    tx = UDPSocket().connect(Address('127.0.0.1', port))
    pkts = [bytes([i]) * (16 + i) for i in range(8)]
    assert tx.send_mmsg(pkts) == 8
    got = rx.recv_mmsg(16, 64)
    assert [bytes(g) for g in got] == pkts
    # cached-structure reuse (same sizes)
    assert tx.send_mmsg(pkts) == 8
    got = rx.recv_mmsg(16, 64)
    assert [bytes(g) for g in got] == pkts
    tx.close()
    rx.close()


def test_native_transmit_wire_equivalence():
    """The native chips/simple fillers produce byte-identical packets to
    the Python codecs' pack()."""
    from bifrost_tpu import native
    if not native.available():
        pytest.skip('native library unavailable')
    from bifrost_tpu.io.packet_writer import (UDPTransmit,
                                              NativeUDPTransmit)
    from bifrost_tpu.io.packet_formats import get_format, PacketDesc
    for fmt_name in ('simple', 'chips'):
        rx = UDPSocket().bind(Address('127.0.0.1', 0))
        rx.set_timeout(0.5)
        tx_sock = UDPSocket().connect(
            Address('127.0.0.1', rx.sock.getsockname()[1]))
        hi = HeaderInfo()
        hi.set_nsrc(4)
        hi.set_nchan(16)
        hi.set_chan0(32)
        hi.set_tuning(7)
        data = np.arange(2 * 2 * 24, dtype=np.uint8).reshape(2, 2, 24)
        with UDPTransmit(fmt_name, tx_sock) as tx:
            assert isinstance(tx, NativeUDPTransmit)
            tx.send(hi, 100, 1, 1, 1, data)
            assert tx.npackets_sent == 4
        fmt = get_format(fmt_name)
        for i in range(2):
            for j in range(2):
                wire = rx.recv(4096)
                expect = fmt.pack(PacketDesc(
                    seq=100 + i, src=1 + j, nsrc=4, nchan=16, chan0=32,
                    tuning=7, payload=data[i, j].tobytes()))
                assert wire == expect, (fmt_name, i, j)
        tx_sock.close()
        rx.close()


def test_native_tbn_drx_decode_loopback():
    """TBN and DRX frames decode in the native capture engine (C++
    decoders mirroring tbn.hpp/drx.hpp) identically to the Python
    codecs."""
    from bifrost_tpu import native
    if not native.available():
        pytest.skip('native library unavailable')
    from bifrost_tpu.io.packet_capture import NativeUDPCapture
    from bifrost_tpu.io.packet_formats import (TbnFormat, DrxFormat,
                                               PacketDesc)

    # --- TBN: 2 stands, seq via time_tag/decim/512
    rx = UDPSocket().bind(Address('127.0.0.1', 0))
    port = rx.sock.getsockname()[1]
    rx.set_timeout(0.4)
    tx = UDPSocket().connect(Address('127.0.0.1', port))
    ring = Ring(space='system', name='tbn_native')

    def cb(desc):
        return 0, {'name': 'tbn', '_tensor': {
            'shape': [-1, 2, 1024], 'dtype': 'u8',
            'labels': ['time', 'src', 'byte'],
            'scales': [[0, 1]] * 3, 'units': [None] * 3}}

    fmt = TbnFormat(decimation=10)
    cap = UDPCapture(fmt, rx, ring, 2, 0, 1024, 4, 4, cb)
    assert isinstance(cap, NativeUDPCapture)
    NSEQ = 8
    rng = np.random.RandomState(0)
    data = rng.randint(0, 255, (NSEQ, 2, 1024)).astype(np.uint8)
    got = []

    def read_ring():
        for seq in ring.read(guarantee=True):
            for span in seq.read(4):
                got.append(np.array(span.data.as_numpy(), copy=True))

    reader = threading.Thread(target=read_ring)
    reader.start()
    cap_thread = threading.Thread(target=_run_capture, args=(cap,))
    cap_thread.start()
    for i in range(NSEQ + 8):       # pad to flush the window
        for s in range(2):
            pld = data[i, s].tobytes() if i < NSEQ else b'\x00' * 1024
            tx.send(fmt.pack(PacketDesc(seq=512 * 10 * i, src=s,
                                        tuning=5, gain=1,
                                        payload=pld)))
    cap_thread.join()
    reader.join()
    out = np.concatenate(got, axis=0)
    np.testing.assert_array_equal(out[:NSEQ], data)

    # --- DRX: id-byte coding, 4096-byte payloads
    rx2 = UDPSocket().bind(Address('127.0.0.1', 0))
    port2 = rx2.sock.getsockname()[1]
    rx2.set_timeout(0.4)
    tx2 = UDPSocket().connect(Address('127.0.0.1', port2))
    ring2 = Ring(space='system', name='drx_native')

    def cb2(desc):
        return 0, {'name': 'drx', '_tensor': {
            'shape': [-1, 2, 4096], 'dtype': 'u8',
            'labels': ['time', 'src', 'byte'],
            'scales': [[0, 1]] * 3, 'units': [None] * 3}}

    cap2 = UDPCapture('drx', rx2, ring2, 2, 0, 4096, 4, 4, cb2)
    assert isinstance(cap2, NativeUDPCapture)
    data2 = rng.randint(0, 255, (NSEQ, 2, 4096)).astype(np.uint8)
    got2 = []

    def read_ring2():
        for seq in ring2.read(guarantee=True):
            for span in seq.read(4):
                got2.append(np.array(span.data.as_numpy(), copy=True))

    r2 = threading.Thread(target=read_ring2)
    r2.start()
    c2 = threading.Thread(target=_run_capture, args=(cap2,))
    c2.start()
    dfmt = DrxFormat()
    for i in range(NSEQ + 8):
        for pol in range(2):
            # wire id: beam 1, tuning 1, pol -> decoded src = pol
            wire_id = 1 | (1 << 3) | (pol << 7)
            pld = data2[i, pol].tobytes() if i < NSEQ \
                else b'\x00' * 4096
            tx2.send(dfmt.pack(PacketDesc(
                seq=4096 * 10 * i, src=wire_id, decimation=10,
                tuning=7, payload=pld)))
    c2.join()
    r2.join()
    out2 = np.concatenate(got2, axis=0)
    np.testing.assert_array_equal(out2[:NSEQ], data2)


def test_native_capture_stress():
    """Native engine under sustained load with a concurrent consuming
    reader: no crashes, full accounting, data plausible."""
    from bifrost_tpu import native
    if not native.available():
        pytest.skip('native library unavailable')
    import struct
    from bifrost_tpu.io.packet_capture import NativeUDPCapture
    payload = 1024
    rx = UDPSocket().bind(Address('127.0.0.1', 0))
    port = rx.sock.getsockname()[1]
    rx.set_timeout(0.3)
    tx = UDPSocket().connect(Address('127.0.0.1', port))
    ring = Ring(space='system', name='stress_native')

    def cb(desc):
        return 0, {'name': 'stress', '_tensor': {
            'shape': [-1, 1, payload], 'dtype': 'u8',
            'labels': ['time', 'src', 'byte'],
            'scales': [[0, 1]] * 3, 'units': [None] * 3}}

    cap = UDPCapture('simple', rx, ring, 1, 0, payload, 64, 64, cb)
    assert isinstance(cap, NativeUDPCapture)
    consumed = [0]

    def read_ring():
        for seq in ring.read(guarantee=False):
            try:
                for span in seq.read(64):
                    consumed[0] += span.nframe
            except Exception:
                return

    rt = threading.Thread(target=read_ring)
    rt.start()
    ct = threading.Thread(target=_run_capture, args=(cap, 10000))
    ct.start()
    body = b'\xaa' * payload
    NSEQ = 4096
    for base in range(1, NSEQ + 1, 64):
        tx.send_mmsg([struct.pack('>Q', base + i) + body
                      for i in range(64)])
    # flush the window
    tx.send_mmsg([struct.pack('>Q', NSEQ + 200 + i) + body
                  for i in range(8)])
    ct.join(30)
    rt.join(30)
    assert not ct.is_alive() and not rt.is_alive()
    stats = cap.stats._read()
    got = stats['ngood_bytes'] // payload
    assert got > 0
    assert stats['src_ngood'][0] == stats['ngood_bytes']
    assert consumed[0] > 0
    tx.close()
    rx.close()


# ---------------------------------------------------------------------------
# All-format loopback through BOTH engines (native C++ decode/fill and
# the Python codecs), VERDICT r2 items 3+8: every wire format runs
# transmit -> UDP -> capture -> ring in the same suite on both engines.
# Each case maps logical (slot i, source j) onto the format's wire
# conventions so that decoded seq == i and decoded src == j.
# ---------------------------------------------------------------------------

def _fmt_case(fmt, nsrc, payload, wire_seq, tx_src, src0=0,
              hi_setup=None, tx_fmt=None):
    return dict(fmt=fmt, nsrc=nsrc, payload=payload, wire_seq=wire_seq,
                tx_src=tx_src, src0=src0, hi_setup=hi_setup,
                tx_fmt=tx_fmt if tx_fmt is not None else fmt)


def _drx_wire_id(j):
    # beam 1, tuning (j//2)+1, pol j&1  ->  decoded src = j
    return 1 | (((j >> 1) + 1) << 3) | ((j & 1) << 7)


ALL_FORMAT_CASES = {
    'simple': _fmt_case('simple', 1, 64, lambda i: i, lambda j: 0),
    'chips': _fmt_case('chips', 2, 64, lambda i: i + 1, lambda j: j),
    'tbn': _fmt_case(lambda: TbnFormat(decimation=10), 2, 1024,
        lambda i: 512 * 10 * i, lambda j: j,
        hi_setup=lambda hi: hi.set_decimation(10)),
    'drx': _fmt_case('drx', 4, 4096, lambda i: 4096 * 10 * i,
                     _drx_wire_id,
                     hi_setup=lambda hi: hi.set_decimation(10)),
    'drx8': _fmt_case('drx8', 4, 8192, lambda i: 4096 * 10 * i,
                      _drx_wire_id,
                      hi_setup=lambda hi: hi.set_decimation(10)),
    'ibeam': _fmt_case('ibeam', 2, 64, lambda i: i + 1, lambda j: j),
    # pbeam: filler beam = src//nserver + 1 (1-based wire), decoder
    # src = (beam - src0)*nserver + server-1 -> identity with src0=1
    'pbeam': _fmt_case('pbeam', 2, 64, lambda i: i, lambda j: j,
                       src0=1,
                       hi_setup=lambda hi: hi.set_decimation(1)),
    # cor: tuning rides (nserver<<8)|server on the wire; navg=100 makes
    # seq = time_tag // 196e6; src0=1 (baseline units) gives identity
    'cor': _fmt_case(lambda: CorFormat(nsrc=3), 3, 64,
        lambda i: 196000000 * i,
        lambda j: j, src0=1,
        hi_setup=lambda hi: (hi.set_tuning((1 << 8) | 1),
                             hi.set_decimation(100)),
        tx_fmt='cor'),
    'snap2': _fmt_case('snap2', 2, 64, lambda i: i, lambda j: j),
    'vdif': _fmt_case(lambda: VdifFormat(frames_per_second=100), 2, 64,
        lambda i: i, lambda j: j,
        tx_fmt=lambda: VdifFormat(frames_per_second=100)),
    'tbf': _fmt_case('tbf', 2, 64, lambda i: i, lambda j: j),
    'vbeam': _fmt_case('vbeam', 1, 64, lambda i: i, lambda j: 0),
}


@pytest.mark.parametrize('fmt_name', sorted(ALL_FORMAT_CASES))
def test_loopback_all_formats_both_engines(fmt_name, capture_engine):
    """Every wire format round-trips transmit->UDP->capture->ring with
    identical placement on the native and Python engines
    (reference: src/packet_capture.hpp:609-1390,
    packet_writer.hpp:366-580)."""
    case = ALL_FORMAT_CASES[fmt_name]
    fmt = case['fmt']() if callable(case['fmt']) else case['fmt']
    tx_fmt = case['tx_fmt']() if callable(case['tx_fmt']) \
        else case['tx_fmt']
    nsrc, payload = case['nsrc'], case['payload']
    NSEQ, PAD, BUF = 8, 8, 4

    rx = UDPSocket().bind(Address('127.0.0.1', 0))
    port = rx.sock.getsockname()[1]
    rx.set_timeout(0.4)
    tx_sock = UDPSocket().connect(Address('127.0.0.1', port))
    ring = Ring(space='system', name='loop_%s_%s' % (
        fmt_name, capture_engine))

    def cb(desc):
        return 0, {'name': fmt_name, '_tensor': {
            'shape': [-1, nsrc, payload], 'dtype': 'u8',
            'labels': ['time', 'src', 'byte'],
            'scales': [[0, 1]] * 3, 'units': [None] * 3}}

    cap = UDPCapture(fmt, rx, ring, nsrc, case['src0'], payload,
                     BUF, BUF, cb)
    from bifrost_tpu.io.packet_capture import NativeUDPCapture
    assert isinstance(cap, NativeUDPCapture) == \
        (capture_engine == 'native'), capture_engine

    rng = np.random.RandomState(hash(fmt_name) % 2**31)
    data = rng.randint(1, 255, (NSEQ, nsrc, payload)).astype(np.uint8)

    got = []

    def read_ring():
        for seq in ring.read(guarantee=True):
            for span in seq.read(BUF):
                got.append(np.array(span.data.as_numpy(), copy=True))

    reader = threading.Thread(target=read_ring)
    reader.start()
    cap_thread = threading.Thread(target=_run_capture, args=(cap,))
    cap_thread.start()

    hi = HeaderInfo()
    hi.set_nsrc(nsrc)
    hi.set_nchan(16)
    hi.set_chan0(0)
    if case['hi_setup']:
        case['hi_setup'](hi)
    with UDPTransmit(tx_fmt, tx_sock) as tx:
        from bifrost_tpu.io.packet_writer import NativeUDPTransmit
        assert isinstance(tx, NativeUDPTransmit) == \
            (capture_engine == 'native')
        for i in range(NSEQ + PAD):
            for j in range(nsrc):
                pld = data[i, j] if i < NSEQ \
                    else np.zeros(payload, np.uint8)
                tx.send(hi, case['wire_seq'](i), 1, case['tx_src'](j),
                        1, pld.reshape(1, 1, -1))
    cap_thread.join()
    reader.join()

    out = np.concatenate(got, axis=0)
    assert out.shape[0] >= NSEQ, (fmt_name, out.shape)
    np.testing.assert_array_equal(out[:NSEQ], data, err_msg=fmt_name)
    assert cap.stats['ngood_bytes'] >= NSEQ * nsrc * payload
    tx_sock.close()
    rx.close()


def test_native_transmit_wire_equivalence_all_formats():
    """Every native filler produces byte-identical packets to the
    Python codec's pack() for the same HeaderInfo/seq/src inputs
    (reference: packet_writer.hpp:366-580)."""
    from bifrost_tpu import native
    if not native.available():
        pytest.skip('native library unavailable')
    from bifrost_tpu.io.packet_writer import (UDPTransmit,
                                              NativeUDPTransmit)
    from bifrost_tpu.io.packet_formats import get_format, PacketDesc

    for fmt_name in sorted(ALL_FORMAT_CASES):
        case = ALL_FORMAT_CASES[fmt_name]
        tx_fmt = case['tx_fmt']() if callable(case['tx_fmt']) \
            else case['tx_fmt']
        fmt = get_format(tx_fmt)
        nsrc, payload = case['nsrc'], case['payload']
        rx = UDPSocket().bind(Address('127.0.0.1', 0))
        rx.set_timeout(0.5)
        tx_sock = UDPSocket().connect(
            Address('127.0.0.1', rx.sock.getsockname()[1]))
        hi = HeaderInfo()
        hi.set_nsrc(nsrc)
        hi.set_nchan(16)
        hi.set_chan0(0)
        hi.set_gain(3)
        if case['hi_setup']:
            case['hi_setup'](hi)
        data = np.arange(2 * nsrc * payload,
                         dtype=np.uint8).reshape(2, nsrc, payload)
        with UDPTransmit(tx_fmt, tx_sock) as tx:
            assert isinstance(tx, NativeUDPTransmit), fmt_name
            for i in range(2):
                for j in range(nsrc):
                    tx.send(hi, case['wire_seq'](i), 1,
                            case['tx_src'](j), 1,
                            data[i, j].reshape(1, 1, -1))
        k = 0
        for i in range(2):
            for j in range(nsrc):
                wire = rx.recv(16384)
                expect = fmt.pack(PacketDesc(
                    seq=case['wire_seq'](i), src=case['tx_src'](j),
                    nsrc=nsrc, nchan=16, chan0=0, tuning=hi.tuning,
                    gain=3, decimation=hi.decimation,
                    payload=data[i, j].tobytes()), framecount=k)
                assert wire == expect, (fmt_name, i, j)
                k += 1
        tx_sock.close()
        rx.close()
