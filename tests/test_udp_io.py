"""UDP loopback harness: writer thread transmits packets through
localhost into a UDPCapture feeding a ring, reader asserts on the result
(the reference's multi-node-without-a-cluster pattern,
reference: test/test_udp_io.py:63-130)."""

import socket
import threading
import time

import numpy as np
import pytest

import bifrost_tpu as bf
from bifrost_tpu.io.udp_socket import Address, UDPSocket
from bifrost_tpu.io.packet_capture import (UDPCapture, DiskReader,
                                           PacketCaptureCallback,
                                           CAPTURE_NO_DATA,
                                           CAPTURE_INTERRUPTED)
from bifrost_tpu.io.packet_writer import HeaderInfo, UDPTransmit, DiskWriter
from bifrost_tpu.ring import Ring


PAYLOAD = 64          # bytes per packet
NSRC = 2
BUF_NTIME = 8


def _capture_header(desc):
    hdr = {
        'name': 'udp-test',
        '_tensor': {
            'shape': [-1, NSRC, PAYLOAD],
            'dtype': 'u8',
            'labels': ['time', 'src', 'byte'],
            'scales': [[0, 1]] * 3,
            'units': [None] * 3,
        },
    }
    return 0, hdr


def _run_capture(capture, max_iters=100):
    for _ in range(max_iters):
        status = capture.recv()
        if status in (CAPTURE_NO_DATA, CAPTURE_INTERRUPTED):
            break
    capture.end()


def test_udp_loopback_chips():
    addr = Address('127.0.0.1', 0)
    rx = UDPSocket().bind(addr)
    port = rx.sock.getsockname()[1]
    rx.set_timeout(0.4)
    tx_sock = UDPSocket().connect(Address('127.0.0.1', port))

    ring = Ring(space='system', name='udp_rx')
    cb = PacketCaptureCallback()
    cb.set_chips(_capture_header)
    capture = UDPCapture('chips', rx, ring, NSRC, 0, PAYLOAD,
                         BUF_NTIME, BUF_NTIME, cb)

    NSEQ = 32
    rng = np.random.RandomState(0)
    data = rng.randint(0, 255, size=(NSEQ, NSRC, PAYLOAD)).astype(np.uint8)
    reader_attached = threading.Event()

    def transmit():
        hi = HeaderInfo()
        hi.set_nsrc(NSRC)
        with UDPTransmit('chips', tx_sock) as tx:
            # first packet opens the sequence; wait for the reader's
            # guarantee before streaming the rest
            tx.send(hi, 0, 1, 0, 1, data[:1])
            assert reader_attached.wait(30)
            tx.send(hi, 1, 1, 0, 1, data[1:])
        pad = np.zeros((BUF_NTIME * 2, NSRC, PAYLOAD), np.uint8)
        with UDPTransmit('chips', tx_sock) as tx:
            tx.send(hi, NSEQ, 1, 0, 1, pad)

    got = []

    def read_ring():
        for seq in ring.read(guarantee=True):
            reader_attached.set()
            for span in seq.read(BUF_NTIME):
                got.append(np.array(span.data.as_numpy(), copy=True))

    # reader must attach before the capture can lap the ring
    reader = threading.Thread(target=read_ring)
    reader.start()
    cap_thread = threading.Thread(target=_run_capture, args=(capture,))
    cap_thread.start()
    t = threading.Thread(target=transmit)
    t.start()
    t.join()
    cap_thread.join()
    reader.join()
    out = np.concatenate(got, axis=0)
    assert out.shape[0] >= NSEQ
    np.testing.assert_array_equal(out[:NSEQ], data)
    assert capture.stats['ngood_bytes'] > 0


def test_udp_loopback_with_packet_loss():
    """Dropped packets leave zeroed slots; loss is accounted per source."""
    addr = Address('127.0.0.1', 0)
    rx = UDPSocket().bind(addr)
    port = rx.sock.getsockname()[1]
    rx.set_timeout(0.4)
    tx_sock = UDPSocket().connect(Address('127.0.0.1', port))

    ring = Ring(space='system', name='udp_rx_loss')
    cb = PacketCaptureCallback()
    cb.set_chips(_capture_header)
    capture = UDPCapture('chips', rx, ring, NSRC, 0, PAYLOAD,
                         BUF_NTIME, BUF_NTIME, cb)

    NSEQ = BUF_NTIME
    data = np.full((NSEQ, NSRC, PAYLOAD), 7, np.uint8)

    reader_attached = threading.Event()

    def transmit():
        hi = HeaderInfo()
        hi.set_nsrc(NSRC)
        with UDPTransmit('chips', tx_sock) as tx:
            # drop seq 3 of src 1 by sending packets individually
            for i in range(NSEQ):
                for j in range(NSRC):
                    if i == 3 and j == 1:
                        continue
                    tx.send(hi, i, 1, j, 1, data[i:i+1, j:j+1])
                if i == 0:
                    assert reader_attached.wait(30)
            pad = np.zeros((BUF_NTIME * 2, NSRC, PAYLOAD), np.uint8)
            tx.send(hi, NSEQ, 1, 0, 1, pad)

    got = []

    def read_ring():
        for seq in ring.read(guarantee=True):
            reader_attached.set()
            for span in seq.read(BUF_NTIME):
                got.append(np.array(span.data.as_numpy(), copy=True))

    reader = threading.Thread(target=read_ring)
    reader.start()
    cap_thread = threading.Thread(target=_run_capture, args=(capture,))
    cap_thread.start()
    t = threading.Thread(target=transmit)
    t.start()
    t.join()
    cap_thread.join()
    reader.join()
    out = np.concatenate(got, axis=0)
    # dropped packet -> zeros at (3, src 1); others intact
    assert np.all(out[3, 1] == 0)
    assert np.all(out[3, 0] == 7)
    assert np.all(out[2, 1] == 7)
    assert capture.stats['nmissing_bytes'] >= PAYLOAD


def test_disk_packet_roundtrip(tmp_path):
    """DiskWriter -> DiskReader capture (replayable ingest)."""
    path = str(tmp_path / 'packets.dat')
    NSEQ = 16
    rng = np.random.RandomState(1)
    data = rng.randint(0, 255, size=(NSEQ, NSRC, PAYLOAD)).astype(np.uint8)
    hi = HeaderInfo()
    hi.set_nsrc(NSRC)
    with open(path, 'wb') as f:
        with DiskWriter('chips', f) as dw:
            dw.send(hi, 0, 1, 0, 1, data)
            pad = np.zeros((BUF_NTIME * 2, NSRC, PAYLOAD), np.uint8)
            dw.send(hi, NSEQ, 1, 0, 1, pad)

    ring = Ring(space='system', name='disk_rx')
    cb = PacketCaptureCallback()
    cb.set_chips(_capture_header)
    with open(path, 'rb') as f:
        capture = DiskReader('chips', f, ring, NSRC, 0, PAYLOAD,
                             BUF_NTIME, BUF_NTIME, cb)
        cap_thread = threading.Thread(target=_run_capture,
                                      args=(capture,))
        cap_thread.start()
        got = []
        for seq in ring.read(guarantee=True):
            for span in seq.read(BUF_NTIME):
                got.append(np.array(span.data.as_numpy(), copy=True))
        cap_thread.join()
    out = np.concatenate(got, axis=0)
    np.testing.assert_array_equal(out[:NSEQ], data)


def test_format_roundtrips():
    from bifrost_tpu.io.packet_formats import get_format, PacketDesc
    payload = bytes(range(32))
    for name in ('simple', 'chips', 'pbeam', 'tbn', 'drx',
                 'ibeam', 'cor', 'snap2', 'vdif', 'tbf',
                 'drx8', 'vbeam'):
        fmt = get_format(name)
        desc = PacketDesc(seq=1234, src=1, nsrc=4, chan0=32, nchan=16,
                          tuning=77, gain=3, decimation=10,
                          payload=payload)
        pkt = fmt.pack(desc)
        back = fmt.unpack(pkt)
        assert back.seq == 1234, name
        assert back.payload == payload, name
        if name in ('chips', 'pbeam', 'ibeam', 'snap2', 'cor', 'tbf',
                    'vbeam'):
            assert back.src == 1 and back.chan0 == 32 and back.nchan == 16
        if name in ('tbn', 'cor'):
            assert back.src == 1 and back.tuning == 77 or name != 'tbn'
        if name == 'tbn':
            assert back.tuning == 77
        if name == 'vdif':
            assert back.src == 1
