"""ndarray/space tests (reference analogue: test/test_ndarray.py)."""

import numpy as np

import bifrost_tpu as bf


def test_asarray_roundtrip():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    a = bf.asarray(x)
    assert a.space == 'system'
    assert a.shape == (3, 4)
    np.testing.assert_array_equal(a.as_numpy(), x)


def test_copy_to_device_and_back():
    x = np.arange(10, dtype=np.float32)
    a = bf.asarray(x, space='tpu')
    assert a.space == 'tpu'
    b = a.copy('system')
    np.testing.assert_array_equal(b.as_numpy(), x)


def test_cuda_space_alias():
    x = np.arange(4, dtype=np.float32)
    a = bf.asarray(x, space='cuda')
    assert a.space == 'tpu'


def test_empty_zeros():
    a = bf.zeros((5, 3), 'cf32', 'system')
    assert a.as_numpy().dtype == np.complex64
    assert np.all(a.as_numpy() == 0)
    d = bf.zeros((5, 3), 'f32', 'tpu')
    assert d.space == 'tpu'
    assert np.all(np.asarray(d.data) == 0)


def test_structured_ci8():
    a = bf.empty((8,), 'ci8', 'system')
    buf = a.as_numpy()
    buf['re'] = np.arange(8)
    buf['im'] = -np.arange(8)
    j = a.as_jax()
    assert j.shape == (8, 2)
    np.testing.assert_array_equal(np.asarray(j)[:, 0], np.arange(8))


def test_packed_i4():
    a = bf.empty((2, 8), 'i4', 'system')
    assert a.as_numpy().shape == (2, 4)   # bytes
    assert a.shape == (2, 8)              # logical
    assert a.nbytes == 8


def test_copy_array_h2d():
    src = bf.asarray(np.arange(6, dtype=np.float32))
    dst = bf.empty((6,), 'f32', 'tpu')
    bf.copy_array(dst, src)
    np.testing.assert_array_equal(np.asarray(dst.data),
                                  np.arange(6, dtype=np.float32))


def test_space_accessible():
    from bifrost_tpu.memory import space_accessible
    assert space_accessible('system', ['tpu_host'])
    assert not space_accessible('tpu', ['system'])
    assert space_accessible('tpu', ['any'])
    assert space_accessible('cuda', ['tpu'])
