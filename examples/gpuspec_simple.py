"""High-resolution spectroscopy of GUPPI RAW data — the north-star
pipeline (reference: testbench/gpuspec_simple.py:44-58).

  read_guppi_raw -> copy('tpu') -> FUSED[ FFT(fine_time) ->
  detect('stokes') -> reduce(freq x4) ] -> copy('system')
  -> write_sigproc

Usage: python gpuspec_simple.py <file.raw> [outdir]
"""

import os
import sys

try:
    import bifrost_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import bifrost_tpu as bf
from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage


def build(filenames, outdir='.', gulp_nframe=1, rfactor=4):
    bc = bf.BlockChainer()
    bc.blocks.read_guppi_raw(filenames, gulp_nframe=gulp_nframe)
    bc.blocks.copy(space='tpu')
    bc.blocks.fused([
        FftStage('fine_time', axis_labels='fine_freq'),
        DetectStage('stokes', axis='pol'),
        ReduceStage('fine_freq', rfactor),
    ])
    bc.blocks.copy(space='system')
    # merge (freq, fine_freq) into one spectral axis and relabel for
    # filterbank output: ['time', 'pol', 'freq']
    bc.views.merge_axes('freq', 'fine_freq', label='freq')
    bc.blocks.transpose(['time', 'pol', 'freq'])
    bc.blocks.write_sigproc(path=outdir)
    return bc


def main(argv):
    if len(argv) < 2:
        print(__doc__)
        return 1
    outdir = argv[2] if len(argv) > 2 else '.'
    build([argv[1]], outdir)
    pipeline = bf.get_default_pipeline()
    pipeline.shutdown_on_signals()
    pipeline.run()
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv))
