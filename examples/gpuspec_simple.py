"""High-resolution spectroscopy of GUPPI RAW data — the north-star
pipeline (reference: testbench/gpuspec_simple.py:44-58).

  read_guppi_raw -> copy('tpu') -> FUSED[ FFT(fine_time) ->
  detect('stokes') -> reduce(freq x4) ] -> copy('system')
  -> write_sigproc

Usage: python gpuspec_simple.py <file.raw> [outdir]
       python gpuspec_simple.py --demo    # synthesize a small .raw
                                          # with a tone and process it
"""

import os
import sys

try:
    import bifrost_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import bifrost_tpu as bf
from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage


def build(filenames, outdir='.', gulp_nframe=1, rfactor=4):
    bc = bf.BlockChainer()
    bc.blocks.read_guppi_raw(filenames, gulp_nframe=gulp_nframe)
    bc.blocks.copy(space='tpu')
    bc.blocks.fused([
        FftStage('fine_time', axis_labels='fine_freq'),
        DetectStage('stokes', axis='pol'),
        ReduceStage('fine_freq', rfactor),
    ])
    bc.blocks.copy(space='system')
    # merge (freq, fine_freq) into one spectral axis and relabel for
    # filterbank output: ['time', 'pol', 'freq']
    bc.views.merge_axes('freq', 'fine_freq', label='freq')
    bc.blocks.transpose(['time', 'pol', 'freq'])
    bc.blocks.write_sigproc(path=outdir)
    return bc


def make_demo_raw(path, nchan=4, ntime=256, npol=2, nblock=4, k=19):
    """Synthesize a GUPPI RAW file with an x-pol tone at fine bin
    ``k`` in every coarse channel (the reference testbench ships a
    generator too, testbench/generate_test_data.py)."""
    import numpy as np
    from bifrost_tpu.io import guppi as guppi_io
    blocsize = nchan * ntime * npol * 2
    t = np.arange(ntime)
    tone = np.exp(2j * np.pi * k * t / ntime)
    with open(path, 'wb') as f:
        for b in range(nblock):
            raw = np.zeros((nchan, ntime, npol, 2), np.int8)
            raw[:, :, 0, 0] = np.round(60 * tone.real)
            raw[:, :, 0, 1] = np.round(60 * tone.imag)
            guppi_io.write_header(f, {
                'OBSNCHAN': nchan, 'NPOL': npol, 'NBITS': 8,
                'BLOCSIZE': blocsize, 'OBSFREQ': 1500.0, 'OBSBW': 4.0,
                'STT_IMJD': 58000, 'STT_SMJD': 0, 'PKTIDX': b,
                'PKTSIZE': 8192, 'TELESCOP': 'DEMO', 'BACKEND': 'GUPPI',
                'SRC_NAME': 'TONE'})
            f.write(raw.tobytes())


def main(argv):
    if len(argv) < 2:
        print(__doc__)
        return 1
    if argv[1] == '--demo':
        import tempfile
        outdir = argv[2] if len(argv) > 2 else tempfile.mkdtemp()
        os.makedirs(outdir, exist_ok=True)
        path = os.path.join(outdir, 'demo.raw')
        make_demo_raw(path)
        argv = [argv[0], path, outdir]
        print("demo: synthesized %s" % path)
    outdir = argv[2] if len(argv) > 2 else '.'
    build([argv[1]], outdir)
    pipeline = bf.get_default_pipeline()
    pipeline.shutdown_on_signals()
    pipeline.run()
    # write_sigproc names outputs <source basename>.fil
    out = os.path.join(outdir, os.path.basename(argv[1]) + '.fil')
    print("wrote %s" % out)
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv))
