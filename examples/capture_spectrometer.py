"""Live UDP capture into a TPU spectrometer — the data-capture tutorial
flow (reference: tutorial/06_data_capture.ipynb, testbench harness
test/test_udp_io.py).

A transmitter thread streams CHIPS F-engine packets carrying a complex
tone over localhost.  A ``UDPCapture`` (the native C++ engine when
available) decodes and scatters them into a ring; the pipeline then
runs copy('tpu') -> fused[FFT -> Stokes detect] -> copy('system') and
a sink reports the detected tone bin.

    chips/UDP -> capture ring -> copy('tpu')
              -> FUSED[ FFT(fine_time) -> detect('scalar') ]
              -> copy('system') -> peak sink

Runs anywhere (loopback sockets; JAX_PLATFORMS=cpu for no-TPU hosts):

    JAX_PLATFORMS=cpu python examples/capture_spectrometer.py
"""

import os
import sys
import threading

import numpy as np

try:
    import bifrost_tpu as bf
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bifrost_tpu as bf

from bifrost_tpu.io.udp_socket import Address, UDPSocket
from bifrost_tpu.io.packet_capture import (UDPCapture, CAPTURE_NO_DATA,
                                           CAPTURE_INTERRUPTED)
from bifrost_tpu.io.packet_writer import HeaderInfo, UDPTransmit
from bifrost_tpu.ring import Ring
from bifrost_tpu.stages import FftStage, DetectStage

NROACH = 2            # F-engine boards (packet sources)
NTIME = 256           # fine-time samples per source and slot
NSEQ = 32             # time slots to stream
TONE_BIN = 37
BUF_NTIME = 8


def make_packets():
    """ci8 tone payloads: (seq, roach, NTIME complex int8 pairs)."""
    t = np.arange(NTIME)
    tone = np.exp(2j * np.pi * TONE_BIN * t / NTIME)
    pld = np.zeros((NSEQ + 2 * BUF_NTIME, NROACH, NTIME, 2), np.int8)
    pld[:NSEQ, :, :, 0] = np.round(50 * tone.real).astype(np.int8)
    pld[:NSEQ, :, :, 1] = np.round(50 * tone.imag).astype(np.int8)
    return pld.reshape(NSEQ + 2 * BUF_NTIME, NROACH, -1)


def main():
    rx = UDPSocket().bind(Address('127.0.0.1', 0))
    port = rx.sock.getsockname()[1]
    rx.set_timeout(0.5)
    tx_sock = UDPSocket().connect(Address('127.0.0.1', port))

    ring = Ring(space='system', name='capture')
    payload = NTIME * 2

    def on_sequence(desc):
        return 0, {'name': 'chips-tone', 'time_tag': 0,
                   '_tensor': {'shape': [-1, NROACH, NTIME],
                               'dtype': 'ci8',
                               'labels': ['time', 'roach', 'fine_time'],
                               'scales': [[0, 1]] * 3,
                               'units': [None] * 3},
                   'gulp_nframe': BUF_NTIME}

    capture = UDPCapture('chips', rx, ring, NROACH, 0, payload,
                         BUF_NTIME, BUF_NTIME, on_sequence)
    print("capture engine: %s" % type(capture).__name__)

    def run_capture():
        while True:
            status = capture.recv()
            if status in (CAPTURE_NO_DATA, CAPTURE_INTERRUPTED):
                break
        capture.end()

    def run_transmit():
        data = make_packets()
        hi = HeaderInfo()
        hi.set_nsrc(NROACH)
        hi.set_nchan(1)
        with UDPTransmit('chips', tx_sock) as tx:
            # chips wire sequence numbers are 1-based
            for i in range(data.shape[0]):
                tx.send(hi, i + 1, 1, 0, 1, data[i:i + 1])

    total = np.zeros(NTIME)

    class PeakSink(bf.SinkBlock):
        def on_sequence(self, iseq):
            print("sequence: %s  tensor %s"
                  % (iseq.header['name'],
                     iseq.header['_tensor']['shape']))

        def on_data(self, ispan):
            spec = np.asarray(ispan.data.as_numpy())   # (t, roach, F)
            total[:] += spec.sum(axis=(0, 1))

    with bf.Pipeline() as pipeline:
        b = bf.blocks.copy(ring, space='tpu')
        b = bf.blocks.fused(b, [
            FftStage('fine_time', axis_labels='fine_freq'),
            DetectStage('scalar'),
        ])
        b = bf.blocks.copy(b, space='system')
        PeakSink(b)

        # start the pipeline FIRST so the copy block's ring reader is
        # attached before the capture can slide its window past the
        # first buffers, then stream
        import time
        pipe_thread = threading.Thread(target=pipeline.run)
        pipe_thread.start()
        pipeline.all_blocks_finished_initializing_event.wait(30)
        time.sleep(1.0)
        # transmit first: UDP buffers the datagrams, and a capture
        # started with an empty socket would end on its first
        # no-data timeout if the transmitter were scheduled late
        cap_thread = threading.Thread(target=run_capture)
        tx_thread = threading.Thread(target=run_transmit)
        tx_thread.start()
        cap_thread.start()
        tx_thread.join()
        cap_thread.join()
        pipe_thread.join()

    peak = int(np.argmax(total)) if total.any() else None
    print("detected tone at fine bin %s (expected %d)"
          % (peak, TONE_BIN))
    if peak != TONE_BIN:
        raise SystemExit("tone not detected!")
    print("OK")


if __name__ == '__main__':
    main()
