"""Dedispersion search demo (reference: testbench/test_fdmt.py):
synthesize a dispersed pulse in a filterbank stream, dedisperse with
the FDMT block on TPU, and report the detected DM/time.

Run: python fdmt_search.py
"""

import os
import sys

try:
    import bifrost_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

import bifrost_tpu as bf
from bifrost_tpu.xfer import to_host


def cff(f1, f2):
    """Quadratic dispersion delay factor between two frequencies."""
    return abs(f1 ** -2 - f2 ** -2)


NCHAN, NTIME, F0, DF = 64, 1024, 100.0, 1.0   # MHz
D_TRUE, T0 = 40, 200                          # delay (samples), pulse time


class DispersedPulseSource(bf.SourceBlock):
    def create_reader(self, name):
        class R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return R()

    def on_sequence(self, reader, name):
        rng = np.random.RandomState(0)
        x = rng.randn(NCHAN, NTIME).astype(np.float32) * 0.1
        band = cff(F0, F0 + NCHAN * DF)
        for c in range(NCHAN):
            delay = D_TRUE * cff(F0, F0 + c * DF) / band
            x[c, T0 + int(round(delay))] += 3.0
        self.data = x
        self.pos = 0
        return [{'name': 'pulse',
                 '_tensor': {'shape': [NCHAN, -1], 'dtype': 'f32',
                             'labels': ['freq', 'time'],
                             'scales': [[F0, DF], [0.0, 1e-3]],
                             'units': ['MHz', 's']}}]

    def on_data(self, reader, ospans):
        if self.pos >= NTIME:
            return [0]
        n = min(ospans[0].nframe, NTIME - self.pos)
        ospans[0].data.as_numpy()[:, :n] = \
            self.data[:, self.pos:self.pos + n]
        self.pos += n
        return [n]


class PeakFinder(bf.SinkBlock):
    def __init__(self, iring, **kwargs):
        super(PeakFinder, self).__init__(iring, **kwargs)
        self.best = (-np.inf, 0, 0)
        self.offset = 0

    def on_sequence(self, iseq):
        self.dm_step = iseq.header['_tensor']['scales'][-2][1]

    def on_data(self, ispan):
        dmt = to_host(ispan.data)
        row, t = np.unravel_index(np.argmax(dmt), dmt.shape)
        if dmt[row, t] > self.best[0]:
            self.best = (float(dmt[row, t]), int(row),
                         self.offset + int(t))
        self.offset += ispan.nframe


def main():
    with bf.Pipeline() as pipeline:
        src = DispersedPulseSource(['pulse'], gulp_nframe=256)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.fdmt(b, max_delay=64)
        b = bf.blocks.copy(b, space='system')
        peak = PeakFinder(b)
        pipeline.run()
    snr, row, t = peak.best
    print("peak %.1f at DM row %d (true %d), t=%d (true %d), "
          "DM = %.3f pc/cm^3" % (snr, row, D_TRUE, t, T0,
                                 row * peak.dm_step))


if __name__ == '__main__':
    main()
