"""FDMT FRB-search demo — the bench config-22 chain end to end
(reference: testbench/test_fdmt.py; bench_suite.bench_fdmt_chain and
docs/perf.md "FDMT FRB search"): synthesize dispersed pulses in a
filterbank stream, dedisperse with the stage-backed FDMT engine,
matched-filter across pulse widths, threshold at a fixed false-alarm
rate, and report the detected DM/time.

  dispersed filterbank -> copy('tpu') -> fdmt_stage  [DM transform]
    -> matched_filter (boxcar) -> threshold -> copy('system') -> peak

Every device block is stage-backed (batch_safe), so under
``BF_SEGMENTS=auto`` the chain compiles into ONE XLA program per macro
gulp — the ``overlap`` boundaries are lifted by the in-program halo
carry (BF-I192) and the interior DM-transform rings never land in HBM.

Usage:
    python examples/fdmt_search.py             # single host
    python examples/fdmt_search.py --fabric    # two loopback
                                               # bf_fabric hosts:
                                               # 'capture' streams the
                                               # filterbank, 'search'
                                               # dedisperses
"""

import os
import socket
import sys
import threading

try:
    import bifrost_tpu as bf
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bifrost_tpu as bf

import numpy as np

from bifrost_tpu.xfer import to_host


def cff(f1, f2):
    """Quadratic dispersion delay factor between two frequencies."""
    return abs(f1 ** -2 - f2 ** -2)


NCHAN, NTIME, F0, DF = 64, 1024, 100.0, 1.0   # MHz
GULP = 256
MAX_DELAY = 64                                # DM trials (samples)
NTAP = 4                                      # boxcar matched filter
THRESH = 8.0                                  # ~5 sigma after the boxcar
D_TRUE, T0 = 40, 200                          # delay (samples), pulse time


class DispersedPulseSource(bf.SourceBlock):
    def __init__(self, **kwargs):
        super(DispersedPulseSource, self).__init__(
            ['pulse'], gulp_nframe=GULP, **kwargs)

    def create_reader(self, name):
        class R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return R()

    def on_sequence(self, reader, name):
        rng = np.random.RandomState(0)
        x = rng.randn(NCHAN, NTIME).astype(np.float32) * 0.1
        band = cff(F0, F0 + NCHAN * DF)
        for c in range(NCHAN):
            delay = D_TRUE * cff(F0, F0 + c * DF) / band
            x[c, T0 + int(round(delay))] += 3.0
        self.data = x
        self.pos = 0
        return [{'name': 'pulse',
                 '_tensor': {'shape': [NCHAN, -1], 'dtype': 'f32',
                             'labels': ['freq', 'time'],
                             'scales': [[F0, DF], [0.0, 1e-3]],
                             'units': ['MHz', 's']}}]

    def on_data(self, reader, ospans):
        if self.pos >= NTIME:
            return [0]
        n = min(ospans[0].nframe, NTIME - self.pos)
        ospans[0].data.as_numpy()[:, :n] = \
            self.data[:, self.pos:self.pos + n]
        self.pos += n
        return [n]


class PeakFinder(bf.SinkBlock):
    """Tracks the strongest above-threshold candidate in the
    (dm, time) stream; everything below THRESH arrives zeroed."""

    def __init__(self, iring, **kwargs):
        super(PeakFinder, self).__init__(iring, **kwargs)
        self.best = (-np.inf, 0, 0)
        self.ncandidates = 0
        self.offset = 0

    def on_sequence(self, iseq):
        self.dm_step = iseq.header['_tensor']['scales'][-2][1]

    def on_data(self, ispan):
        dmt = np.asarray(to_host(ispan.data))
        self.ncandidates += int(np.count_nonzero(dmt))
        row, t = np.unravel_index(np.argmax(dmt), dmt.shape)
        if dmt[row, t] > self.best[0]:
            self.best = (float(dmt[row, t]), int(row),
                         self.offset + int(t))
        self.offset += ispan.nframe


def build_search_chain(b):
    """The dedispersion device chain (every block stage-backed: one
    halo-carried segment under BF_SEGMENTS=auto)."""
    b = bf.blocks.copy(b, space='tpu')
    b = bf.blocks.fdmt_stage(b, max_delay=MAX_DELAY)
    b = bf.blocks.matched_filter(b, NTAP)
    b = bf.blocks.threshold(b, THRESH)
    return bf.blocks.copy(b, space='system')


def run_single():
    with bf.Pipeline() as pipeline:
        peak = PeakFinder(build_search_chain(DispersedPulseSource()))
        pipeline.run()
    return peak


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_fabric():
    """The same chain split over a two-host loopback fabric: the
    'capture' host streams the filterbank into the 'filterbank' link;
    the 'search' host dedisperses (docs/fabric.md)."""
    from bifrost_tpu import fabric

    spec = fabric.FabricSpec('fdmt_demo', hosts={
        'capture': {'address': '127.0.0.1', 'role': 'capture'},
        'search': {'address': '127.0.0.1', 'role': 'reduce'},
    }, links={
        'filterbank': {'kind': 'pipe', 'src': 'capture',
                       'dst': 'search', 'port': _free_port(),
                       'window': 2,
                       'gulp_nbyte': NCHAN * GULP * 4},
    })

    peaks = []

    def build_capture(ctx):
        ctx.sink('filterbank', DispersedPulseSource())

    def build_search(ctx):
        peaks.append(PeakFinder(
            build_search_chain(ctx.source('filterbank'))))

    hosts = {}
    for name, builder in (('search', build_search),
                          ('capture', build_capture)):
        hosts[name] = fabric.FabricHost(spec, name, builder,
                                        jitter=False)
        hosts[name].build()
    threads = [threading.Thread(target=fh.run,
                                kwargs={'install_signals': False})
               for fh in hosts.values()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    return peaks[0] if peaks else None


def main():
    peak = run_fabric() if '--fabric' in sys.argv[1:] else run_single()
    if peak is None:
        return
    snr, row, t = peak.best
    print("%d candidate samples above %.1f; peak %.1f at DM row %d "
          "(true %d), t=%d (true %d), DM = %.3f pc/cm^3"
          % (peak.ncandidates, THRESH, snr, row, D_TRUE, t, T0,
             row * peak.dm_step))


if __name__ == '__main__':
    main()
