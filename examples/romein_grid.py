"""Streaming Romein gridder demo (reference: src/romein.cu; the
w-projection imaging step, docs/ops.md): grid a stream of visibility
snapshots onto a common uv-grid with ``ops.romein.Romein`` — XLA's
sorted scatter-add standing in for the reference's per-thread atomic
scatter — then image the accumulated grid with a 2-D FFT and report
the recovered point source.

  snapshot visibilities (time, npts) -> copy('tpu')
    -> RomeinGridder (per-frame ksize x ksize kernel scatter)
    -> copy('system') -> grid accumulator + dirty image

Run: python examples/romein_grid.py
"""

import os
import sys

try:
    import bifrost_tpu as bf
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bifrost_tpu as bf

from copy import deepcopy

import numpy as np

from bifrost_tpu.ops.romein import Romein
from bifrost_tpu.xfer import to_host

NPTS, NGRID, KSIZE = 64, 32, 3
NTIME, GULP = 32, 8
SRC_LM = (5, -3)          # point-source offset in image pixels


def make_baselines():
    """Static uv tracks: npts baseline coords on the grid plus a
    ksize x ksize separable triangle (linear-interp) kernel each."""
    rng = np.random.RandomState(7)
    uv = rng.randint(0, NGRID, size=(NPTS, 2)).astype(np.int32)
    tri = np.array([0.5, 1.0, 0.5])
    kern = np.broadcast_to((tri[:, None] * tri[None, :]),
                           (NPTS, KSIZE, KSIZE))
    return uv, kern.astype(np.complex64)


UV, KERNELS = make_baselines()


class SnapshotSource(bf.SourceBlock):
    """One visibility snapshot per frame: the npts baselines sampling
    a unit point source at image offset SRC_LM (a pure fringe)."""

    def create_reader(self, name):
        class R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return R()

    def on_sequence(self, reader, name):
        l, m = SRC_LM
        # kernel-center coords: init positions are the kernel ORIGIN
        u = UV[:, 0] + KSIZE // 2
        v = UV[:, 1] + KSIZE // 2
        fringe = np.exp(2j * np.pi * (u * l + v * m) / NGRID)
        self.vis = fringe.astype(np.complex64)
        self.pos = 0
        return [{'name': 'snapshots',
                 '_tensor': {'shape': [-1, NPTS], 'dtype': 'cf32',
                             'labels': ['time', 'baseline'],
                             'scales': [[0.0, 1.0], [0, 1]],
                             'units': ['s', None]}}]

    def on_data(self, reader, ospans):
        if self.pos >= NTIME:
            return [0]
        n = min(ospans[0].nframe, NTIME - self.pos)
        ospans[0].data.as_numpy()[:n] = self.vis[None, :]
        self.pos += n
        return [n]


class RomeinGridder(bf.TransformBlock):
    """Scatters each frame's npts visibilities through its gridding
    kernel onto a fresh (ngrid, ngrid) plane (grid accumulation across
    frames happens in the sink, keeping the block stateless)."""

    def __init__(self, iring, **kwargs):
        super(RomeinGridder, self).__init__(iring, **kwargs)
        self.engine = Romein().init(UV, KERNELS, NGRID)

    def on_sequence(self, iseq):
        ohdr = deepcopy(iseq.header)
        t = ohdr['_tensor']
        t['shape'] = [-1, NGRID, NGRID]
        t['labels'] = ['time', 'v', 'u']
        t['scales'] = [t['scales'][0], [0, 1], [0, 1]]
        t['units'] = [t['units'][0], None, None]
        return ohdr

    def on_data(self, ispan, ospan):
        ospan.set(self.engine.execute(ispan.data))


class DirtyImager(bf.SinkBlock):
    def __init__(self, iring, **kwargs):
        super(DirtyImager, self).__init__(iring, **kwargs)
        self.grid = np.zeros((NGRID, NGRID), np.complex64)
        self.nsnap = 0

    def on_sequence(self, iseq):
        pass

    def on_data(self, ispan):
        planes = np.asarray(to_host(ispan.data))
        self.grid += planes.sum(axis=0)
        self.nsnap += planes.shape[0]

    def image(self):
        return np.fft.fft2(self.grid).real / max(self.nsnap, 1)


def main():
    with bf.Pipeline() as pipeline:
        src = SnapshotSource(['snapshots'], gulp_nframe=GULP)
        b = bf.blocks.copy(src, space='tpu')
        b = RomeinGridder(b)
        b = bf.blocks.copy(b, space='system')
        imager = DirtyImager(b)
        pipeline.run()
    img = imager.image()
    m, l = np.unravel_index(np.argmax(img), img.shape)
    l = l - NGRID if l >= NGRID // 2 else l
    m = m - NGRID if m >= NGRID // 2 else m
    print('gridded %d snapshots x %d baselines; dirty-image peak at '
          '(l=%d, m=%d), true (l=%d, m=%d)'
          % (imager.nsnap, NPTS, l, m, SRC_LM[0], SRC_LM[1]))


if __name__ == '__main__':
    main()
