"""Multi-chip spectrometer: one pipeline, gulps sharded over a Mesh.

Attach a ``jax.sharding.Mesh`` to a BlockScope and every block inside
scales out: the fused FFT->detect->reduce chain is GSPMD-partitioned
over the gulp's time axis, and the correlator integrates shard-partial
visibilities with a psum over the mesh (see
bifrost_tpu/parallel/scope.py for the conventions).

Run without TPU hardware on a virtual device mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
    JAX_PLATFORMS=cpu python examples/mesh_spectrometer.py
"""

import os
import sys

import numpy as np

try:
    import bifrost_tpu as bf
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bifrost_tpu as bf
from bifrost_tpu.parallel import create_mesh
from bifrost_tpu.stages import FftStage, DetectStage, ReduceStage


class ToneSource(bf.pipeline.SourceBlock):
    """Emits dual-pol complex gulps with a tone at bin 17."""

    NT, NP, NF = 64, 2, 256

    def __init__(self, ngulp=4, **kwargs):
        super(ToneSource, self).__init__(['tone'], self.NT,
                                         space='system', **kwargs)
        self.ngulp = ngulp
        self.count = 0
        t = np.arange(self.NF)
        tone = np.exp(2j * np.pi * 17 * t / self.NF)
        self.gulp = np.zeros((self.NT, self.NP, self.NF), np.complex64)
        self.gulp[:, 0] = tone
        self.gulp[:, 1] = 0.5 * tone

    def create_reader(self, name):
        class R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return R()

    def on_sequence(self, reader, name):
        self.count = 0
        return [{'name': 'tone', 'time_tag': 0,
                 '_tensor': {'shape': [-1, self.NP, self.NF],
                             'dtype': 'cf32',
                             'labels': ['time', 'pol', 'fine_time'],
                             'scales': [[0, 1]] * 3,
                             'units': [None] * 3}}]

    def on_data(self, reader, ospans):
        if self.count >= self.ngulp:
            return [0]
        self.count += 1
        ospans[0].data.as_numpy()[...] = self.gulp
        return [self.NT]


class PrintPeak(bf.pipeline.SinkBlock):
    def on_sequence(self, iseq):
        print("sequence:", iseq.header['name'])

    def on_data(self, ispan):
        from bifrost_tpu.xfer import to_host
        spec = to_host(ispan.data) if ispan.ring.space == 'tpu' \
            else np.asarray(ispan.data.as_numpy())
        stokes_i = spec[0, 0]
        print("  Stokes-I peak at bin %d: %.1f"
              % (int(np.argmax(stokes_i)), float(stokes_i.max())))


def main():
    import jax
    n = len(jax.devices())
    mesh = create_mesh({'sp': n})
    print("mesh:", dict(zip(mesh.axis_names, mesh.devices.shape)))

    with bf.Pipeline() as p:
        src = ToneSource()
        with bf.block_scope(mesh=mesh):
            # the H2D copy lives INSIDE the mesh scope so gulps land
            # on the devices already sharded (sharded H2D placement,
            # docs/parallel.md) — outside it, every gulp would commit
            # single-device and the fused block would pay a per-gulp
            # reshard (the static verifier flags that as BF-W140)
            b = bf.blocks.copy(src, space='tpu')
            # every gulp of this chain runs sharded over all devices
            b = bf.blocks.fused(b, [
                FftStage('fine_time', axis_labels='freq'),
                DetectStage('stokes', axis='pol'),
                ReduceStage('freq', 4)])
        sink = PrintPeak(b)
        p.run()


if __name__ == '__main__':
    main()
