"""Writing your first block (reference: testbench/your_first_block.py).

A TransformBlock needs two methods:
- on_sequence(iseq): inspect/transform the header, return the output
  header
- on_data(ispan, ospan): compute one gulp

Device blocks receive jax arrays from 'tpu'-space rings and publish
results with ospan.set(...); host blocks mutate numpy views in place.
Run: python your_first_block.py
"""

import os
import sys

try:
    import bifrost_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

from copy import deepcopy

import numpy as np

import bifrost_tpu as bf


class UselessAdd(bf.TransformBlock):
    """Adds 1000 to every sample — on TPU when the ring is there."""

    def on_sequence(self, iseq):
        return deepcopy(iseq.header)

    def on_data(self, ispan, ospan):
        if ispan.ring.space == 'tpu':
            ospan.set(ispan.data + 1000.0)
        else:
            ospan.data.as_numpy()[...] = \
                ispan.data.as_numpy() + 1000.0


class PrintStats(bf.SinkBlock):
    def on_sequence(self, iseq):
        print("sequence:", iseq.header['name'])

    def on_data(self, ispan):
        d = ispan.data.as_numpy()
        print("gulp mean = %.2f" % float(d.mean()))


class CountingSource(bf.SourceBlock):
    def create_reader(self, name):
        class R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return R()

    def on_sequence(self, reader, name):
        self.count = 0
        return [{'name': name,
                 '_tensor': {'shape': [-1, 16], 'dtype': 'f32',
                             'labels': ['time', 'chan'],
                             'scales': [[0, 1], [0, 1]],
                             'units': [None, None]}}]

    def on_data(self, reader, ospans):
        if self.count >= 4:
            return [0]
        self.count += 1
        ospans[0].data.as_numpy()[...] = self.count
        return [ospans[0].nframe]


def main():
    with bf.Pipeline() as pipeline:
        src = CountingSource(['demo'], gulp_nframe=8)
        b = bf.blocks.copy(src, space='tpu')
        b = UselessAdd(b)
        b = bf.blocks.copy(b, space='system')
        PrintStats(b)
        pipeline.run()


if __name__ == '__main__':
    main()
