"""Distributed FX correlator demo — the bench config-19 chain end to
end (reference architecture: the xGPU-style FX pipeline, arXiv:
1107.4264; bench_suite.bench_fxcorr and docs/perf.md "FX correlator").

  synthetic ci8 stations -> copy('tpu') -> FFT(fine -> freq)  [F]
    -> requantize ci8 -> CorrelateStageBlock (raced X-engine)  [X]
    -> accumulate -> convert_visibilities('storage') -> sink

The whole device chain is stage-backed (batch_safe), so under
``BF_SEGMENTS=auto`` the five blocks compile into ONE XLA program per
macro gulp — no f32 voltage spectra and no intermediate rings ever
land in HBM.  The X-engine consumes the ci8 planes directly on its
exact int32 path (accuracy='int8' races the quantized candidates;
outputs stay bit-identical to the int64 oracle).

Usage:
    python examples/fx_correlator.py             # single host
    python examples/fx_correlator.py --fabric    # two loopback
                                                 # bf_fabric hosts:
                                                 # 'stations' captures,
                                                 # 'xhost' correlates
"""

import os
import socket
import sys
import threading

import numpy as np

try:
    import bifrost_tpu as bf
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bifrost_tpu as bf

NT, NW, NS, NP = 32, 64, 8, 2    # frames/gulp, window, stations, pols
R, A = 8, 2                      # frames/vis, visibilities accumulated
NGULP = 4
TONE_BIN = 11


class StationSource(bf.pipeline.SourceBlock):
    """Synthesizes ci8 dual-pol station voltages: a common tone at
    fine bin ``TONE_BIN`` with a per-station phase gradient (so the
    visibility matrix shows off-diagonal fringes) over weak noise."""

    def __init__(self, ngulp=NGULP, **kwargs):
        super(StationSource, self).__init__(['stations'], NT,
                                            space='system', **kwargs)
        self.ngulp = ngulp
        self.count = 0
        rng = np.random.RandomState(19)
        t = np.arange(NT * NW).reshape(NT, NW)
        tone = np.exp(2j * np.pi * TONE_BIN * (t % NW) / NW)
        phase = np.exp(2j * np.pi * np.arange(NS) / NS)
        v = tone[:, :, None, None] * phase[None, None, :, None] * 50
        v = v + 4 * (rng.randn(NT, NW, NS, NP) +
                     1j * rng.randn(NT, NW, NS, NP))
        self.gulp = np.zeros((NT, NW, NS, NP),
                             dtype=np.dtype([('re', 'i1'),
                                             ('im', 'i1')]))
        self.gulp['re'] = np.clip(np.round(v.real), -128, 127)
        self.gulp['im'] = np.clip(np.round(v.imag), -128, 127)

    def create_reader(self, name):
        class R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return R()

    def on_sequence(self, reader, name):
        self.count = 0
        return [{'name': 'stations', 'time_tag': 0,
                 '_tensor': {'shape': [-1, NW, NS, NP],
                             'dtype': 'ci8',
                             'labels': ['time', 'fine', 'station',
                                        'pol'],
                             'scales': [[0, 1]] * 4,
                             'units': [None] * 4}}]

    def on_data(self, reader, ospans):
        if self.count >= self.ngulp:
            return [0]
        self.count += 1
        ospans[0].data.as_numpy()[...] = self.gulp
        return [NT]


class PrintVisibilities(bf.pipeline.SinkBlock):
    """Prints per-integration fringe diagnostics from the packed
    storage-format (time, baseline, freq, stokes) stream."""

    def on_sequence(self, iseq):
        shape = iseq.header['_tensor']['shape']
        print('visibilities: %d baselines x %d channels (storage '
              'IQUV)' % (shape[1], shape[2]))

    def on_data(self, ispan):
        from bifrost_tpu.xfer import to_host
        vis = to_host(ispan.data) if ispan.ring.space == 'tpu' \
            else np.asarray(ispan.data.as_numpy())
        stokes_i = np.abs(vis[..., 0])          # (t, nbl, f)
        for t in range(vis.shape[0]):
            peak = int(np.argmax(stokes_i[t].max(axis=0)))
            cross = stokes_i[t, :, peak]
            print('  integration: tone at channel %d, |I| auto %.0f '
                  'cross-mean %.0f'
                  % (peak, cross[0], float(np.mean(cross[1:]))))


def build_xchain(b):
    """The F -> requantize -> X -> accumulate -> storage device chain
    (every block stage-backed: one fused segment under
    BF_SEGMENTS=auto)."""
    b = bf.blocks.copy(b, space='tpu')
    b = bf.blocks.fft(b, axes='fine', axis_labels='freq')
    b = bf.blocks.quantize(b, 'ci8', scale=1. / NW)
    b = bf.blocks.correlate(b, R, accuracy='int8', fusable=True)
    b = bf.blocks.accumulate(b, A, fusable=True)
    b = bf.blocks.convert_visibilities(b, 'storage')
    return bf.blocks.copy(b, space='system')


def run_single():
    with bf.Pipeline() as p:
        PrintVisibilities(build_xchain(StationSource()))
        p.run()


def _free_port():
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_fabric():
    """The same chain split over a two-host loopback fabric: the
    'stations' host captures ci8 voltages into the 'voltages' link;
    the 'xhost' host runs the F/X chain (docs/fabric.md)."""
    from bifrost_tpu import fabric

    spec = fabric.FabricSpec('fxcorr_demo', hosts={
        'stations': {'address': '127.0.0.1', 'role': 'capture'},
        'xhost': {'address': '127.0.0.1', 'role': 'reduce'},
    }, links={
        'voltages': {'kind': 'pipe', 'src': 'stations',
                     'dst': 'xhost', 'port': _free_port(),
                     'window': 2,
                     'gulp_nbyte': NT * NW * NS * NP * 2},
    })

    def build_stations(ctx):
        ctx.sink('voltages', StationSource())

    def build_xhost(ctx):
        PrintVisibilities(build_xchain(ctx.source('voltages')))

    hosts = {}
    for name, builder in (('xhost', build_xhost),
                          ('stations', build_stations)):
        hosts[name] = fabric.FabricHost(spec, name, builder,
                                        jitter=False)
        hosts[name].build()
    threads = [threading.Thread(target=fh.run,
                                kwargs={'install_signals': False})
               for fh in hosts.values()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)


def main():
    if '--fabric' in sys.argv[1:]:
        run_fabric()
    else:
        run_single()


if __name__ == '__main__':
    main()
