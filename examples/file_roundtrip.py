"""File-format round trip (reference: testbench/test_file_read_write.py
+ testbench/generate_test_data.py): synthesize a noise-plus-tone
time/pol stream, write raw binary, read it back, reduce on device, and
write/read SIGPROC filterbank — asserting byte/bit fidelity at each hop.

  [synth] -> binary_write              (.out raw file)
  binary_read -> copy('tpu') -> detect -> reduce -> copy('system')
              -> transpose -> write_sigproc    (.fil)
  read_sigproc -> [gather + verify]

Run: python file_roundtrip.py [workdir]
"""

import os
import sys
import tempfile

try:
    import bifrost_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

import bifrost_tpu as bf

NTIME, NPOL, NCHAN, RF = 64, 2, 128, 4


class SynthSource(bf.SourceBlock):
    """cf32 noise with a strong tone in channel 17 of pol 0."""

    def create_reader(self, name):
        class R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return R()

    def on_sequence(self, reader, name):
        rng = np.random.RandomState(1)
        x = (rng.randn(NTIME, NPOL, NCHAN) +
             1j * rng.randn(NTIME, NPOL, NCHAN)).astype(np.complex64)
        x[:, 0, 17] += 10.0
        self.data = x
        self.pos = 0
        return [{'name': 'synth',
                 '_tensor': {'shape': [-1, NPOL, NCHAN], 'dtype': 'cf32',
                             'labels': ['time', 'pol', 'freq'],
                             'scales': [[0.0, 1e-3], [0, 1],
                                        [1400.0, -0.1]],
                             'units': ['s', None, 'MHz']}}]

    def on_data(self, reader, ospans):
        if self.pos >= NTIME:
            return [0]
        n = min(ospans[0].nframe, NTIME - self.pos)
        ospans[0].set(self.data[self.pos:self.pos + n])
        self.pos += n
        return [n]


class Gather(bf.SinkBlock):
    def __init__(self, iring, **kwargs):
        super(Gather, self).__init__(iring, **kwargs)
        self.chunks = []

    def on_sequence(self, iseq):
        self.header = iseq.header

    def on_data(self, ispan):
        self.chunks.append(np.array(ispan.data))

    def result(self):
        return np.concatenate(self.chunks, axis=0)


def main(workdir):
    os.makedirs(workdir, exist_ok=True)
    os.chdir(workdir)

    # 1. synth -> raw binary file
    with bf.Pipeline() as p:
        src = SynthSource(['synth'], gulp_nframe=16)
        bf.blocks.binary_write(src, file_ext='out')
        p.run()
    raw_path = 'synth.out'
    assert os.path.exists(raw_path), 'binary_write produced no file'
    nbytes = os.path.getsize(raw_path)
    print('wrote %s (%d bytes)' % (raw_path, nbytes))
    assert nbytes == NTIME * NPOL * NCHAN * 8
    # bit fidelity hop 1: the raw file IS the synthesized stream
    rng = np.random.RandomState(1)
    want = (rng.randn(NTIME, NPOL, NCHAN) +
            1j * rng.randn(NTIME, NPOL, NCHAN)).astype(np.complex64)
    want[:, 0, 17] += 10.0
    got = np.fromfile(raw_path, np.complex64).reshape(NTIME, NPOL,
                                                      NCHAN)
    assert np.array_equal(got, want), 'binary file differs from synth'

    # 2. raw binary -> device detect/reduce -> SIGPROC filterbank
    bc = bf.BlockChainer()
    # each frame is one (pol, chan) slice = NPOL*NCHAN cf32 samples
    bc.blocks.binary_read([raw_path], gulp_size=NPOL * NCHAN,
                          gulp_nframe=16, dtype='cf32')
    # binary_read yields flat 'sample' frames; reshape + relabel to
    # the original tensor layout
    bc.views.split_axis('sample', NCHAN, label='freq')
    bc.views.rename_axis('sample', 'pol')
    bc.blocks.copy(space='tpu')
    bc.blocks.detect(mode='stokes_i', axis='pol')
    bc.blocks.reduce('freq', RF)
    bc.blocks.copy(space='system')
    bc.blocks.transpose(['time', 'pol', 'freq'])
    bc.blocks.write_sigproc(path='.')
    pipe = bf.get_default_pipeline()
    pipe.run()
    fil = [f for f in os.listdir('.') if f.endswith('.fil')]
    assert fil, 'write_sigproc produced no .fil'
    print('wrote %s' % fil[0])

    # 3. read the filterbank back and verify the tone survived intact
    with bf.Pipeline() as p:
        b = bf.blocks.read_sigproc([fil[0]], gulp_nframe=16)
        sink = Gather(b)
        p.run()
    out = sink.result()
    # bit fidelity hop 2: the filterbank carries exactly the
    # device-computed Stokes-I reduced spectra (f32 math, numpy oracle)
    oracle = (np.abs(want) ** 2).sum(axis=1)            # I = |x|^2+|y|^2
    oracle = oracle.reshape(NTIME, NCHAN // RF, RF).sum(-1)
    flat = out.reshape(NTIME, -1)
    rel = np.max(np.abs(flat - oracle)) / np.max(np.abs(oracle))
    assert rel < 1e-5, 'filterbank payload differs from oracle (%g)' % rel
    spec = flat.mean(axis=0)
    peak = int(np.argmax(spec))
    print('tone detected in reduced channel %d (expect %d), '
          'payload rel err %.2e' % (peak, 17 // RF, rel))
    assert peak == 17 // RF
    print('file_roundtrip OK')


if __name__ == '__main__':
    main(sys.argv[1] if len(sys.argv) > 1 else
         tempfile.mkdtemp(prefix='bf_roundtrip_'))
