"""Checkpoint & replay (reference: blocks/serialize.py:45-100 and the
disk-replay capture path): record a processed stream to the `.bf.json`
+ `.bf.*.dat` serialize format, then REPLAY it through a second
pipeline and verify the replayed science output is bit-identical.

This is the framework's checkpoint/resume story: a live pipeline can
tee its stream to disk (triggered dumps of still-buffered history work
the same way via `open_sequence_at`), and any later pipeline can resume
from the files as if the original source were still running.

  live:   [synth pulse train] -> detect -> serialize    (-> disk)
  replay: deserialize -> [gather + verify bit-identical]

Run: python serialize_replay.py [workdir]
"""

import os
import sys
import tempfile

try:
    import bifrost_tpu  # noqa: F401
except ImportError:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

import bifrost_tpu as bf

NTIME, NCHAN, PERIOD = 128, 64, 25


class PulseTrain(bf.SourceBlock):
    """cf32 stream with a pulse every PERIOD frames."""

    def create_reader(self, name):
        class R(object):
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False
        return R()

    def on_sequence(self, reader, name):
        rng = np.random.RandomState(2)
        x = (rng.randn(NTIME, NCHAN) +
             1j * rng.randn(NTIME, NCHAN)).astype(np.complex64)
        x[::PERIOD] *= 8.0
        self.data = x
        self.pos = 0
        return [{'name': 'pulses',
                 '_tensor': {'shape': [-1, NCHAN], 'dtype': 'cf32',
                             'labels': ['time', 'freq'],
                             'scales': [[0.0, 1e-3], [1400.0, -0.1]],
                             'units': ['s', 'MHz']}}]

    def on_data(self, reader, ospans):
        if self.pos >= NTIME:
            return [0]
        n = min(ospans[0].nframe, NTIME - self.pos)
        ospans[0].set(self.data[self.pos:self.pos + n])
        self.pos += n
        return [n]


class Gather(bf.SinkBlock):
    def __init__(self, iring, **kwargs):
        super(Gather, self).__init__(iring, **kwargs)
        self.chunks = []
        self.header = None

    def on_sequence(self, iseq):
        self.header = iseq.header

    def on_data(self, ispan):
        self.chunks.append(np.array(ispan.data))

    def result(self):
        return np.concatenate(self.chunks, axis=0)


def main(workdir):
    os.makedirs(workdir, exist_ok=True)

    # live pipeline: synth -> detect (power) -> record to disk
    with bf.Pipeline() as p:
        src = PulseTrain(['pulses'], gulp_nframe=16)
        b = bf.blocks.copy(src, space='tpu')
        b = bf.blocks.detect(b, mode='scalar')
        b = bf.blocks.copy(b, space='system')
        live = Gather(b)                       # what science saw live
        bf.blocks.serialize(b, path=workdir)   # ... and the recording
        p.run()
    base = os.path.join(workdir, 'pulses')
    assert os.path.exists(base + '.bf.json'), 'no serialized header'
    dats = [f for f in os.listdir(workdir) if f.endswith('.dat')]
    print('recorded %s.bf.json + %d data file(s)' % (base, len(dats)))

    # replay pipeline: resume from disk alone
    with bf.Pipeline() as p:
        b = bf.blocks.deserialize([base], gulp_nframe=16)
        replay = Gather(b)
        p.run()

    a, b_ = live.result(), replay.result()
    assert a.shape == b_.shape, (a.shape, b_.shape)
    assert np.array_equal(a, b_), 'replay is not bit-identical'
    assert replay.header['_tensor']['labels'] == ['time', 'freq']
    pulses = int((b_.mean(axis=1) > 2 * np.median(b_)).sum())
    assert pulses == (NTIME + PERIOD - 1) // PERIOD, pulses
    print('replay bit-identical to live run; %d pulses at period %d'
          % (pulses, PERIOD))
    print('serialize_replay OK')


if __name__ == '__main__':
    main(sys.argv[1] if len(sys.argv) > 1 else
         tempfile.mkdtemp(prefix='bf_replay_'))
