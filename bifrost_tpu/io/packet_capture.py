"""Packet capture engine: UDP/disk packets -> ring, with per-source loss
accounting and sequence-change callbacks.

Architecture mirrors the reference capture stack (reference:
src/packet_capture.hpp:150-607, python/bifrost/packet_capture.py):

- a pluggable *method* supplies raw packets (UDP socket, disk reader)
- the *engine* decodes them with a wire format (io.packet_formats),
  scatters payloads into a sliding window of TWO open ring spans
  (double buffering, reference: packet_capture.hpp:485-534), commits
  the oldest span as the window slides, counts good/missing bytes per
  source, and zero-blanks sources with >50% loss in a span
- a user *sequence callback* builds the ring header when a new
  observation starts (C->Python callback boundary in the reference;
  plain Python here)

Ring frame layout: (time, nsrc, payload_bytes) — the sequence callback's
header tensor must describe the same frame size.
"""

from __future__ import annotations

import ctypes
import errno
import os
import select
import socket as socket_mod
import threading
import time as time_mod

import numpy as np

from .packet_formats import get_format, PacketDesc
from ..ring import RingWriter

__all__ = ['PacketCaptureCallback', 'UDPCapture', 'NativeUDPCapture',
           'ShardedUDPCapture', 'UDPSniffer', 'DiskReader',
           'CAPTURE_STARTED', 'CAPTURE_CONTINUED', 'CAPTURE_ENDED',
           'CAPTURE_NO_DATA', 'CAPTURE_INTERRUPTED']

CAPTURE_STARTED = 1
CAPTURE_CONTINUED = 2
CAPTURE_ENDED = 4
CAPTURE_NO_DATA = 8
CAPTURE_INTERRUPTED = 16


class PacketCaptureCallback(object):
    """Holds per-format sequence callbacks (reference:
    python/bifrost/packet_capture.py:45-89).  A callback is
    ``fn(desc: PacketDesc) -> (time_tag, header_dict)``."""

    def __init__(self):
        self._callbacks = {}

    def __getattr__(self, name):
        if name.startswith('set_'):
            fmt = name[4:]

            def setter(fn):
                self._callbacks[fmt] = fn
            return setter
        raise AttributeError(name)

    def get(self, fmt_name):
        return self._callbacks.get(fmt_name)


class _PacketCapture(object):
    def __init__(self, fmt, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None):
        self.nsrc = int(np.prod(nsrc)) if not np.isscalar(nsrc) else nsrc
        # 'cor' decoding depends on the source count (it sets the stand
        # count used to compose baseline indices, reference cor.hpp:74);
        # parameterize the codec with the engine's nsrc.  Other
        # parameterized codecs (TbnFormat(decimation=...)) are passed in
        # as format objects.
        if isinstance(fmt, str) and fmt.split('_')[0] == 'cor':
            self.fmt = get_format('cor', nsrc=self.nsrc)
        else:
            self.fmt = get_format(fmt)
        self.ring = ring
        if getattr(self.fmt, 'applies_src0', False):
            # pbeam/cor apply src0 in composed (beam/baseline) units
            # inside the decoder, like the reference (pbeam.hpp:70,
            # cor.hpp:77); the engine must not rebase again.  Copy the
            # codec first: get_format() may hand back the shared
            # registry singleton.  A src0 already configured on a
            # passed-in format object wins over the engine default 0;
            # conflicting nonzero values are an error.
            import copy as _copy
            fmt_src0 = getattr(self.fmt, 'src0', 0)
            if src0 and fmt_src0 and src0 != fmt_src0:
                raise ValueError(
                    "conflicting src0: capture got %d but the %s codec "
                    "was built with src0=%d" % (src0, self.fmt.name,
                                                fmt_src0))
            self.fmt = _copy.copy(self.fmt)
            self.fmt.src0 = src0 or fmt_src0
            src0 = 0
        self.src0 = src0
        self.payload_size = max_payload_size
        self.buffer_ntime = buffer_ntime
        self.slot_ntime = slot_ntime
        self.callback = sequence_callback.get(self.fmt.name) \
            if isinstance(sequence_callback, PacketCaptureCallback) \
            else sequence_callback
        self.core = core
        self._writer = None
        self._wseq = None
        self._seq0 = None
        self._bufs = []          # [(start_seq, WriteSpan, view, got_mask)]
        # loss ledger: nignored is kept as the historical aggregate and
        # always equals nlate + nalien (late = seq behind the window or
        # before seq0; alien = src outside [src0, src0+nsrc))
        self.stats = {'ngood_bytes': 0, 'nmissing_bytes': 0,
                      'nignored': 0, 'ninvalid': 0,
                      'nlate': 0, 'nalien': 0, 'ndup': 0, 'nreceived': 0,
                      'src_ngood': np.zeros(self.nsrc, np.int64)}
        # one lock serializes all window/ledger state; recvmmsg and
        # header decode run outside it.  RLock: _process_one nests
        # inside _ingest_batch's critical section on mixed batches.
        self._lock = threading.RLock()
        self._claim_cv = threading.Condition(self._lock)
        self._commit_cv = threading.Condition(self._lock)
        self._claims = {}        # span start -> in-flight zero-copy claims
        self._ncommits = 0
        self._max_seq = None     # highest seq seen (reorder-depth ref)
        self._raw_stride = max_payload_size + 1024
        self._reorder_hist = 'capture.%s.reorder_depth' % ring.name
        from ..proclog import ProcLog
        self._stats_proclog = ProcLog('%s_capture/stats' % ring.name)

    # -- method interface --------------------------------------------------
    def _recv_packet(self):
        raise NotImplementedError

    # -- engine ------------------------------------------------------------
    def _begin_sequence(self, desc):
        if self._writer is None:
            self._writer = RingWriter(self.ring)
        time_tag, hdr = self.callback(desc)
        hdr.setdefault('time_tag', time_tag)
        hdr.setdefault('name', hdr.get('name', 'capture-%d' % time_tag))
        # downstream pipeline blocks size their gulps from the header
        hdr.setdefault('gulp_nframe', self.buffer_ntime)
        # stamp cumulative capture loss into _overload so it rides the
        # same shed-accounting channel ring.py merges writer-side
        # (nonzero on sequence restarts after a gapped stream)
        stamp = dict(hdr.get('_overload') or {})
        stamp.update({
            'capture_missing_bytes': int(self.stats['nmissing_bytes']),
            'capture_late': int(self.stats['nlate']),
            'capture_alien': int(self.stats['nalien']),
            'capture_invalid': int(self.stats['ninvalid'])})
        hdr['_overload'] = stamp
        self._wseq = self._writer.begin_sequence(
            hdr, gulp_nframe=self.buffer_ntime,
            buf_nframe=4 * self.buffer_ntime)
        self._seq0 = (desc.seq // self.slot_ntime) * self.slot_ntime
        self._bufs = []
        self._committed_end = 0

    def _open_buf(self, start):
        span = self._wseq.reserve(self.buffer_ntime)
        view = span.data.as_numpy().view(np.uint8).reshape(
            self.buffer_ntime, self.nsrc, -1)
        # NOTE: no view[...] = 0 here — only the cells still missing at
        # commit get blanked (from the got-mask complement), so the hot
        # path never touches bytes a packet is about to overwrite
        got = np.zeros((self.buffer_ntime, self.nsrc), bool)
        self._bufs.append((start, span, view, got))

    def _span_retirable(self, start):
        """Whether the head span may retire now (engine lock held).
        The sharded engine overrides this with bounded-skew
        backpressure; the single-threaded engines always say yes."""
        return True

    def _commit_oldest(self):
        # zero-copy claims pin a span against commit; cv.wait drops
        # the engine lock, so several workers can be in here at once.
        # Each call retires AT MOST the span that was head at entry:
        # if the head moved while we waited, a sibling already retired
        # it and popping again would empty (and then restart!) the
        # window.
        if not self._bufs:
            return
        target = self._bufs[0][0]
        deadline = None
        while self._bufs and self._bufs[0][0] == target:
            if self._claims.get(target, 0):
                self._claim_cv.wait()
                continue
            if not self._span_retirable(target):
                # give lagging zero-copy workers a short grace to fill
                # this span before retiring it (their queued packets
                # would otherwise all turn into late drops); the bound
                # keeps a stalled flow from wedging the window
                now = time_mod.monotonic()
                if deadline is None:
                    deadline = now + 0.05
                if now < deadline:
                    self._claim_cv.wait(deadline - now)
                    continue
            break
        if not self._bufs or self._bufs[0][0] != target:
            return
        start, span, view, got = self._bufs.pop(0)
        self._committed_end = start + self.buffer_ntime
        # blank ONLY what was missed: per-span zero-fill is gone, so
        # never-written cells hold stale ring bytes until this point
        miss_t, miss_s = np.nonzero(~got)
        if miss_t.size:
            view[miss_t, miss_s, :] = 0
        # per-source loss accounting + >50%-loss blanking
        # (reference: packet_capture.hpp:505-534)
        pkt_bytes = self.payload_size
        ngood_col = got.sum(axis=0).astype(np.int64)
        self.stats['src_ngood'] += ngood_col * pkt_bytes
        ngood = int(ngood_col.sum())
        self.stats['ngood_bytes'] += ngood * pkt_bytes
        self.stats['nmissing_bytes'] += \
            (self.buffer_ntime * self.nsrc - ngood) * pkt_bytes
        for src in np.nonzero(ngood_col * 2 < self.buffer_ntime)[0]:
            view[:, src] = 0   # blank unreliable source
        span.commit(self.buffer_ntime)
        span.close()
        self._ncommits += 1
        self._commit_cv.notify_all()
        self._stats_proclog.update(self._stats_snapshot())

    def _stats_snapshot(self):
        st = self.stats
        d = {'ngood_bytes': st['ngood_bytes'],
             'nmissing_bytes': st['nmissing_bytes'],
             'ninvalid': st['ninvalid'],
             'nignored': st['nignored'],
             'nlate': st['nlate'],
             'nalien': st['nalien'],
             'ndup': st['ndup'],
             'nreceived': st['nreceived'],
             'npackets': st['ngood_bytes'] // self.payload_size}
        for i, w in enumerate(getattr(self, '_wstats', ()) or ()):
            d['worker%d_npackets' % i] = w['npackets']
            d['worker%d_nbytes' % i] = w['nbytes']
            d['worker%d_zero_copy' % i] = w['zero_copy']
        return d

    def _ensure_window(self, off):
        """Slide/open spans (engine lock held) until ``off`` lies below
        the window end.  Returns True if any span was committed."""
        committed = False
        while True:
            if self._bufs:
                last_end = self._bufs[-1][0] + self.buffer_ntime
            else:
                # empty window mid-stream (flush, or every span just
                # retired): NEVER restart from 0 — resume at the
                # committed high-water mark, jumping forward to the
                # span holding ``off`` if the stream skipped ahead
                last_end = max(
                    getattr(self, '_committed_end', 0),
                    off // self.buffer_ntime * self.buffer_ntime)
            if self._bufs and off < last_end:
                return committed
            if len(self._bufs) == 2:
                self._commit_oldest()   # may drop the lock on claim waits
                committed = True
                continue                # re-derive: window may have moved
            self._open_buf(last_end)

    def _note_seqs(self, seqs):
        """Track the highest seq seen and feed the reorder-depth
        histogram (how far behind the running max each arrival is)."""
        if not len(seqs):
            return
        prev = self._max_seq
        if prev is None:
            self._max_seq = int(seqs.max())
            return
        seqs = np.asarray(seqs, np.int64)
        run = np.maximum.accumulate(
            np.concatenate(([prev], seqs)))[:-1]
        depths = run - seqs
        from ..telemetry import histograms
        for d in depths[depths > 0][:32]:      # bound the slow path
            histograms.observe(self._reorder_hist, int(d))
        self._max_seq = max(prev, int(seqs.max()))

    # -- vectorized batch path (recvmmsg + decode_batch formats) -----------
    def _assign_batch(self, offs, srcs, payloads, rows=None):
        """Scatter a decoded batch into the open window, sliding it as
        needed.  ``offs``/``srcs`` are compact (already filtered);
        ``rows`` maps them back to rows of ``payloads`` so the gather +
        span write is the only payload copy.  Returns True if any span
        was committed."""
        committed = False
        if rows is None:
            rows = np.arange(len(offs))
        pw = payloads.shape[1]
        remaining = np.ones(len(offs), bool)
        while remaining.any():
            last_end = (self._bufs[-1][0] + self.buffer_ntime) \
                if self._bufs else 0
            beyond = remaining & (offs >= last_end)
            in_window = remaining & (offs < last_end)
            idx = np.nonzero(in_window)[0]
            if idx.size:
                o = offs[idx]
                for start, span, view, got in self._bufs:
                    m = (o >= start) & (o < start + self.buffer_ntime)
                    if m.any():
                        sel = idx[m]
                        ts = offs[sel] - start
                        ss = srcs[sel]
                        ndup = int(got[ts, ss].sum())
                        if ndup:
                            self.stats['ndup'] += ndup
                        view[ts, ss, :pw] = payloads[rows[sel]]
                        if pw < view.shape[2]:
                            view[ts, ss, pw:] = 0   # stale lane tails
                        got[ts, ss] = True
                if self._bufs:
                    nlate = int((o < self._bufs[0][0]).sum())
                    if nlate:
                        self.stats['nlate'] += nlate
                        self.stats['nignored'] += nlate
                remaining[idx] = False
            if beyond.any():
                # slide ONLY to the nearest out-of-window offset: jumping
                # straight to the batch max would retire the intermediate
                # spans before this batch's packets landed in them
                # (anything still pending would then misclassify as late)
                committed |= self._ensure_window(int(offs[beyond].min()))
            elif not idx.size:
                break
        return committed

    def _recv_batched(self):
        """recv() over whole recvmmsg batches with vectorized header
        decode — the per-packet Python cost (struct.unpack + slice +
        scatter) collapses into a handful of numpy ops per batch."""
        started = False
        committed = False
        while not committed:
            raw, lengths = self._recv_raw_batch()
            if raw is None:
                return CAPTURE_NO_DATA if self._seq0 is None \
                    else CAPTURE_INTERRUPTED
            s, c = self._ingest_batch(raw, lengths)
            started = started or s
            committed = committed or c
        return CAPTURE_STARTED if started else CAPTURE_CONTINUED

    def _ingest_batch(self, raw, lengths, wstat=None, info=None):
        """Decode one recvmmsg batch (outside the lock) and scatter it
        into the window (under the lock).  ``wstat`` is an optional
        per-worker counter dict; ``info`` an optional out-dict filled
        with the batch's in-range srcs + max seq (used by sharded
        workers to learn their flow for zero-copy engagement).
        Returns (started, committed)."""
        n = len(lengths)
        stride = self._raw_stride
        arr = np.frombuffer(raw, np.uint8,
                            count=n * stride).reshape(n, stride)
        if wstat is not None:
            wstat['npackets'] += n
            wstat['nbytes'] += int(sum(lengths))
        started = committed = False
        fallback = len(set(lengths)) != 1
        ok = seqs = srcs = hoff = None
        if not fallback:
            if lengths[0] < self.fmt.header_size:
                with self._lock:
                    self.stats['nreceived'] += n
                    self.stats['ninvalid'] += n     # runts
                return False, False
            try:
                out = self.fmt.decode_batch(arr, lengths[0])
            except ValueError:
                # e.g. a VDIF batch mixing legacy/non-legacy framing
                fallback = True
            else:
                seqs, srcs, hoff = out[:3]
                fvalid = out[3] if len(out) > 3 else None
                ok = np.ones(n, bool) if fvalid is None \
                    else np.asarray(fvalid, bool).copy()
        if fallback:
            # mixed sizes / undecodable batch: per-packet slow path
            # over zero-copy slices of the raw buffer
            for i in range(n):
                s, c = self._process_one(
                    raw[i * stride:i * stride + lengths[i]])
                started = started or s
                committed = committed or c
            return started, committed
        srcs = srcs - self.src0
        in_range = (srcs >= 0) & (srcs < self.nsrc)
        with self._lock:
            self.stats['nreceived'] += n
            ninvalid = n - int(ok.sum())
            if ninvalid:
                self.stats['ninvalid'] += ninvalid
            nalien = int((ok & ~in_range).sum())
            if nalien:
                self.stats['nalien'] += nalien
                self.stats['nignored'] += nalien
            ok &= in_range
            if not ok.any():
                return False, False
            if self._seq0 is None:
                first = int(np.nonzero(ok)[0][0])
                desc = self.fmt.unpack(bytes(arr[first, :lengths[first]]))
                if desc is None:
                    self.stats['ninvalid'] += 1
                    return False, False
                desc.src -= self.src0
                self._begin_sequence(desc)
                started = True
            keep = np.nonzero(ok)[0]
            kseqs = seqs[keep].astype(np.int64)
            self._note_seqs(kseqs)
            if info is not None:
                info['srcs'] = np.unique(srcs[keep])
                info['max_seq'] = int(kseqs.max())
            offs = kseqs - self._seq0
            fresh = offs >= 0
            nlate = int((~fresh).sum())
            if nlate:
                self.stats['nlate'] += nlate
                self.stats['nignored'] += nlate
            if not fresh.any():
                return started, False
            payloads = arr[:, hoff:lengths[0]]
            committed = self._assign_batch(
                offs[fresh], srcs[keep[fresh]].astype(np.int64),
                payloads, keep[fresh])
        return started, committed

    def _recv_raw_batch(self):
        return None, None       # only UDPCapture implements this

    def _process_one(self, pkt):
        """Single-packet slow path used by recv() and mixed batches."""
        desc = self.fmt.unpack(pkt)
        with self._lock:
            self.stats['nreceived'] += 1
            if desc is None or desc.valid_mode:
                # reference decoders gate on valid_mode (tbn.hpp:64,
                # drx.hpp:64); the native engine does the same
                self.stats['ninvalid'] += 1
                return False, False
            desc.src -= self.src0
            if desc.src < 0 or desc.src >= self.nsrc:
                self.stats['nalien'] += 1
                self.stats['nignored'] += 1
                return False, False
            started = False
            if self._seq0 is None:
                self._begin_sequence(desc)
                started = True
            self._note_seqs(np.asarray([desc.seq], np.int64))
            off = desc.seq - self._seq0
            if off < 0:
                self.stats['nlate'] += 1
                self.stats['nignored'] += 1
                return started, False
            committed = self._ensure_window(off)
            for start, span, view, got in self._bufs:
                if start <= off < start + self.buffer_ntime:
                    t = off - start
                    payload = np.frombuffer(desc.payload, np.uint8)
                    if got[t, desc.src]:
                        self.stats['ndup'] += 1
                    view[t, desc.src, :len(payload)] = payload
                    if len(payload) < view.shape[2]:
                        view[t, desc.src, len(payload):] = 0
                    got[t, desc.src] = True
                    break
                elif off < start:
                    self.stats['nlate'] += 1
                    self.stats['nignored'] += 1   # too late
                    break
            return started, committed

    def recv(self):
        """Process packets until one buffer's worth of time has been
        committed (reference: bfPacketCaptureRecv)."""
        if getattr(self, '_use_batch', False):
            return self._recv_batched()
        started = False
        committed = False
        while not committed:
            pkt = self._recv_packet()
            if pkt is None:
                return CAPTURE_NO_DATA if self._seq0 is None \
                    else CAPTURE_INTERRUPTED
            s, c = self._process_one(pkt)
            started = started or s
            committed = committed or c
        return CAPTURE_STARTED if started else CAPTURE_CONTINUED

    def flush(self):
        with self._lock:
            # Trim trailing speculative spans first: a zero-copy claim
            # may have opened a span purely on seq prediction (the
            # readable packet turned out late/alien, so nothing ever
            # landed).  An all-empty unclaimed TRAILING span holds no
            # evidence its seqs exist on the wire — drop the
            # reservation (zero-frame commit) rather than publish a
            # phantom all-missing span that breaks the
            # good+missing == window-covered ledger identity.
            while (self._bufs and not self._bufs[-1][3].any()
                   and not self._claims.get(self._bufs[-1][0], 0)):
                _, span, _, _ = self._bufs.pop()
                span.commit(0)
                span.close()
            while self._bufs:
                self._commit_oldest()

    def end(self):
        self.flush()
        with self._lock:
            # final cumulative stats must land regardless of throttling
            self._stats_proclog.update(self._stats_snapshot(), force=True)
            if self._wseq is not None:
                self._wseq.end()
                self._wseq = None
            if self._writer is not None:
                self.ring.end_writing()
                self._writer = None
            self._seq0 = None
        return CAPTURE_ENDED

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()


#: wire formats with a native C++ decoder (native/capture.cpp);
#: ids must match the FMT_* enum there
NATIVE_FMT_IDS = {'simple': 0, 'chips': 1, 'tbn': 2, 'drx': 3,
                  'drx8': 4, 'ibeam': 5, 'cor': 6, 'pbeam': 7,
                  'snap2': 8, 'vdif': 9, 'tbf': 10, 'vbeam': 11}
#: formats the native TRANSMIT engine can fill headers for
NATIVE_TX_FMT_IDS = dict(NATIVE_FMT_IDS)
_NATIVE_FMT_IDS = NATIVE_FMT_IDS    # backwards-compat alias


def native_io_usable(fmt, sock, fmt_ids=None):
    """Shared gate for the native IO engines: env opt-out, format has a
    C++ codec, socket exposes a file descriptor, and the .so was built
    with the (Linux-only) engines rather than portable stubs."""
    import os
    if os.environ.get('BF_NO_NATIVE_CAPTURE'):
        return False
    base = fmt.split('_')[0] if isinstance(fmt, str) else \
        getattr(fmt, 'name', None)
    ids = NATIVE_FMT_IDS if fmt_ids is None else fmt_ids
    if base not in ids or not hasattr(sock, 'fileno'):
        return False
    from ..native import io_engine_supported
    return io_engine_supported()


def _native_capture_usable(fmt, sock, ring):
    try:
        from ..ring_native import NativeRing
    except Exception:
        return False
    if not isinstance(ring, NativeRing):
        return False
    return native_io_usable(fmt, sock)


class UDPCapture(_PacketCapture):
    """Capture packets from a UDP socket (reference:
    bfUdpCaptureCreate, src/packet_capture.cpp:324).

    Dispatch: when the ring is native and the format has a C++ decoder,
    construction returns a :class:`NativeUDPCapture` — the whole
    recv/decode/scatter loop runs in native/capture.cpp like the
    reference engine (set BF_NO_NATIVE_CAPTURE=1 to force Python).
    The Python engine uses recvmmsg batching + vectorized decode when
    the socket and format support it, per-packet recv otherwise."""

    BATCH = 128

    def __new__(cls, fmt=None, sock=None, ring=None, *args, **kwargs):
        if cls is UDPCapture and _native_capture_usable(fmt, sock, ring):
            from ..native import available
            if available():
                return super(UDPCapture, cls).__new__(NativeUDPCapture)
        return super(UDPCapture, cls).__new__(cls)

    def __init__(self, fmt, sock, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None,
                 batch=None):
        super(UDPCapture, self).__init__(
            fmt, ring, nsrc, src0, max_payload_size, buffer_ntime,
            slot_ntime, sequence_callback, core)
        self.sock = sock
        self.batch = batch or self.BATCH
        self._pending = []
        self._pending_idx = 0
        self._use_mmsg = hasattr(sock, 'recv_mmsg')
        # fully-vectorized path: recvmmsg raw buffer + batch header
        # decode (formats that define decode_batch)
        self._raw_stride = max_payload_size + 1024
        self._use_batch = (hasattr(sock, 'recv_mmsg_raw') and
                           hasattr(self.fmt, 'decode_batch'))

    def _recv_raw_batch(self):
        return self.sock.recv_mmsg_raw(self.batch, self._raw_stride)

    def _recv_plain(self):
        from .udp_socket import UDPSocket, retry_transient
        try:
            # retry_transient handles EINTR/ECONNREFUSED with capped
            # backoff (telemetry: io.socket_retries) — a briefly
            # restarting peer must not kill a long-running capture.
            # UDPSocket.recv already retries internally; wrapping it
            # again would square the retry budget, so only plain
            # socket objects handed to the capture get the wrapper.
            if isinstance(self.sock, UDPSocket):
                return self.sock.recv(self.payload_size + 1024)
            return retry_transient(
                lambda: self.sock.recv(self.payload_size + 1024))
        except (socket_mod.timeout, TimeoutError):
            return None
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return None
            raise

    def _recv_packet(self):
        if not self._use_mmsg:
            return self._recv_plain()
        if self._pending_idx >= len(self._pending):
            try:
                batch = self.sock.recv_mmsg(self.batch,
                                            self.payload_size + 1024)
            except (OSError, AttributeError):
                self._use_mmsg = False
                return self._recv_plain()
            if not batch:
                return None
            self._pending = batch
            self._pending_idx = 0
        pkt = self._pending[self._pending_idx]
        self._pending_idx += 1
        return pkt


class _BftPktDesc(ctypes.Structure):
    # mirrors bft_pkt_desc in native/capture.cpp
    _fields_ = [('seq', ctypes.c_longlong),
                ('time_tag', ctypes.c_longlong),
                ('src', ctypes.c_int),
                ('nsrc', ctypes.c_int),
                ('nchan', ctypes.c_int),
                ('chan0', ctypes.c_int),
                ('tuning', ctypes.c_int),
                ('tuning1', ctypes.c_int),
                ('gain', ctypes.c_int),
                ('decimation', ctypes.c_int),
                ('beam', ctypes.c_int),
                ('npol', ctypes.c_int),
                ('npol_tot', ctypes.c_int),
                ('pol0', ctypes.c_int),
                ('nchan_tot', ctypes.c_int),
                ('payload_size', ctypes.c_int)]


class NativeUDPCapture(UDPCapture):
    """UDP capture driven end-to-end by the native engine
    (native/capture.cpp): recvmmsg batches, C++ header decode, scatter
    straight into the native ring's buffer, loss accounting and
    blanking — the reference's capture-thread architecture
    (src/packet_capture.hpp:150-607).  Python is entered only once per
    sequence to build the ring header (the same C->Python callback
    boundary the reference has)."""

    def __init__(self, fmt, sock, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None,
                 batch=None):
        import json
        from .. import native as native_mod
        # shared setup (format/callback resolution, counters, proclog)
        _PacketCapture.__init__(self, fmt, ring, nsrc, src0,
                                max_payload_size, buffer_ntime,
                                slot_ntime, sequence_callback, core)
        self.sock = sock
        self._lib = native_mod.load()
        self._cb_error = None
        handle = ctypes.c_void_p()
        # composed-src formats (pbeam/cor) apply src0 in the C decoder
        # in beam/baseline units; the base init has already folded the
        # engine src0 into the codec, so forward the codec's value
        if getattr(self.fmt, 'applies_src0', False):
            src0 = int(self.fmt.src0)
        native_mod.check(self._lib.bft_capture_create(
            ctypes.byref(handle), _NATIVE_FMT_IDS[self.fmt.name],
            sock.fileno(), ring._handle, self.nsrc, src0,
            max_payload_size, buffer_ntime, slot_ntime), 'capture')
        self._handle = handle
        if getattr(self.fmt, 'decimation', None):
            # TBN derives seq from time_tag via the stream decimation
            self._lib.bft_capture_set_decimation(
                handle, int(self.fmt.decimation))
        elif getattr(self.fmt, 'frames_per_second', None):
            # VDIF: seq = secs * fps + frame; fps rides the same slot
            self._lib.bft_capture_set_decimation(
                handle, int(self.fmt.frames_per_second))
        self._applied_timeout = object()     # force first sync
        self._sync_timeout()

        CB = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                              ctypes.POINTER(_BftPktDesc),
                              ctypes.POINTER(ctypes.c_longlong),
                              ctypes.POINTER(ctypes.c_char),
                              ctypes.c_int,
                              ctypes.POINTER(ctypes.c_char),
                              ctypes.c_int)

        def header_cb(user, desc_p, time_tag_out, name_buf, name_cap,
                      hdr_buf, hdr_cap):
            try:
                d = desc_p.contents
                desc = PacketDesc(seq=d.seq, src=d.src, nsrc=d.nsrc,
                                  nchan=d.nchan, chan0=d.chan0,
                                  time_tag=d.time_tag, tuning=d.tuning,
                                  tuning1=d.tuning1, gain=d.gain,
                                  decimation=max(d.decimation, 1),
                                  beam=d.beam, npol=d.npol,
                                  npol_tot=d.npol_tot, pol0=d.pol0,
                                  nchan_tot=d.nchan_tot)
                time_tag, hdr = self.callback(desc)
                hdr.setdefault('time_tag', time_tag)
                hdr.setdefault('name', 'capture-%d' % time_tag)
                hdr.setdefault('gulp_nframe', self.buffer_ntime)
                name = str(hdr['name']).encode()[:name_cap - 1]
                ctypes.memmove(name_buf, name + b'\x00', len(name) + 1)
                raw = json.dumps(hdr).encode()
                if len(raw) + 1 > hdr_cap:
                    raise ValueError("header JSON too large")
                ctypes.memmove(hdr_buf, raw + b'\x00', len(raw) + 1)
                time_tag_out[0] = time_tag
                return 0
            except BaseException as e:
                # surfaced by the next recv() on the Python side
                self._cb_error = e
                return -1

        self._cb = CB(header_cb)     # keep a reference alive
        self._lib.bft_capture_set_header_callback(
            handle, ctypes.cast(self._cb, ctypes.c_void_p), None)
        self.stats = _NativeCaptureStats(self)

    def _sync_timeout(self):
        """Mirror the socket's (possibly updated) timeout into the
        native poll: None = block like the Python engine's select."""
        t = getattr(self.sock, '_timeout', None)
        if t != self._applied_timeout:
            self._lib.bft_capture_set_timeout_ms(
                self._handle, -1 if t is None else max(int(t * 1000), 1))
            self._applied_timeout = t

    def recv(self):
        from .. import native as native_mod
        self._sync_timeout()
        status = ctypes.c_int(0)
        native_mod.check(self._lib.bft_capture_recv(
            self._handle, ctypes.byref(status)), 'recv')
        if self._cb_error is not None:
            err, self._cb_error = self._cb_error, None
            raise err
        if status.value in (CAPTURE_STARTED, CAPTURE_CONTINUED):
            st = self.stats._read()
            st['npackets'] = st.get('ngood_bytes', 0) // \
                self.payload_size
            self._stats_proclog.update({
                k: v for k, v in st.items() if k != 'src_ngood'})
        return status.value

    def flush(self):
        self._lib.bft_capture_flush(self._handle)

    def end(self):
        self._lib.bft_capture_end(self._handle)
        st = self.stats._read()
        st['npackets'] = st.get('ngood_bytes', 0) // self.payload_size
        self._stats_proclog.update(
            {k: v for k, v in st.items() if k != 'src_ngood'},
            force=True)
        return CAPTURE_ENDED

    def __del__(self):
        try:
            if getattr(self, '_handle', None) is not None:
                self._lib.bft_capture_destroy(self._handle)
                self._handle = None
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()


class _NativeCaptureStats(object):
    """Read-through view of the native engine's counters, dict-like to
    match the Python engine's ``stats``."""

    def __init__(self, cap):
        self._cap = cap

    def _read(self):
        ll = ctypes.c_longlong
        g, m, iv, ig = ll(0), ll(0), ll(0), ll(0)
        self._cap._lib.bft_capture_stats(
            self._cap._handle, ctypes.byref(g), ctypes.byref(m),
            ctypes.byref(iv), ctypes.byref(ig))
        src = (ll * self._cap.nsrc)()
        self._cap._lib.bft_capture_src_ngood(
            self._cap._handle, src, self._cap.nsrc)
        return {'ngood_bytes': g.value, 'nmissing_bytes': m.value,
                'ninvalid': iv.value, 'nignored': ig.value,
                'src_ngood': np.asarray(list(src), np.int64)}

    def __getitem__(self, key):
        return self._read()[key]

    def get(self, key, default=None):
        return self._read().get(key, default)

    def __repr__(self):
        return repr(self._read())


class ShardedUDPCapture(_PacketCapture):
    """N-worker sharded UDP capture: worker threads drain private
    ``SO_REUSEPORT`` socket queues (or dup()s of one shared queue when
    REUSEPORT is unavailable), each pinned through affinity.py, all
    scattering into the SAME double-buffered span window under one
    engine lock — per-source loss accounting and the >50%-blanking
    protocol stay exactly as exact as the single-thread engine's
    (docs/networking.md "Wire-rate capture").

    Zero-copy scatter engages per worker when every condition holds:

    - the format has a fixed frame size (``fmt.frame_size`` or the
      ``frame_size`` hint) and a ``decode_batch``,
    - the frame's payload fits the ring lane,
    - the worker's queue is exclusive (REUSEPORT mode, or a single
      worker), and
    - the worker has learned its flow: REUSEPORT hashes datagrams per
      5-tuple, so a staged batch showing exactly one in-range source
      means this worker owns that source's stream.

    An engaged worker claims its source's next expected span cells
    (claims pin spans against commit), points ``recvmmsg`` split
    iovecs at them (header -> sidecar, payload -> cell), consumes
    nonblockingly, and verifies the decoded headers against the
    prediction — misses are repaired per packet (bounce-copy to the
    true cell) and the worker falls back to the staged
    one-vectorized-copy path until the flow looks clean again.

    Construction: pass an :class:`.udp_socket.Address` to let the
    engine create + bind its worker sockets (REUSEPORT mode), or an
    already-bound socket to shard it across threads."""

    def __init__(self, fmt, addr_or_sock, ring, nsrc, src0,
                 max_payload_size, buffer_ntime, slot_ntime,
                 sequence_callback, core=None, nthreads=None,
                 vlen=None, zero_copy=None, frame_size=None,
                 cores=None, timeout=0.25):
        super(ShardedUDPCapture, self).__init__(
            fmt, ring, nsrc, src0, max_payload_size, buffer_ntime,
            slot_ntime, sequence_callback, core)
        env = os.environ
        if nthreads is None:
            nthreads = int(env.get('BF_CAPTURE_THREADS', '') or 2)
        if vlen is None:
            vlen = int(env.get('BF_CAPTURE_VLEN', '') or 64)
        if zero_copy is None:
            zero_copy = env.get('BF_CAPTURE_ZERO_COPY', '1') != '0'
        self.nthreads = max(int(nthreads), 1)
        self.vlen = max(min(int(vlen), self.buffer_ntime), 1)
        self._timeout = timeout

        from .udp_socket import UDPSocket, Address
        self._own_socks = []
        if hasattr(addr_or_sock, 'sockaddr'):     # an Address
            first = UDPSocket(reuseport=True).bind(addr_or_sock)
            self._own_socks.append(first)
            socks = [first]
            if first.reuseport:
                # siblings bind the RESOLVED port (addr.port may be 0)
                port = first.sock.getsockname()[1]
                sib = Address(addr_or_sock.address, port) \
                    if port != addr_or_sock.port else addr_or_sock
                for _ in range(self.nthreads - 1):
                    s = UDPSocket(reuseport=True).bind(sib)
                    self._own_socks.append(s)
                    socks.append(s)
            else:
                for _ in range(self.nthreads - 1):
                    s = UDPSocket.from_fd(first.fileno())
                    self._own_socks.append(s)
                    socks.append(s)
            self._exclusive = first.reuseport or self.nthreads == 1
        else:
            base = addr_or_sock
            self.sock = base                       # caller still owns it
            if hasattr(base, 'recv_mmsg_raw'):
                socks = [base]
            else:
                w = UDPSocket.from_fd(base.fileno())
                self._own_socks.append(w)
                socks = [w]
            for _ in range(self.nthreads - 1):
                s = UDPSocket.from_fd(base.fileno())
                self._own_socks.append(s)
                socks.append(s)
            self._exclusive = self.nthreads == 1
        self._socks = socks
        for s in self._socks:
            s.set_timeout(timeout)

        # Deterministic source steering: when the wire format carries a
        # single-byte source id (chips' leading roach byte), a classic
        # BPF on the REUSEPORT group routes worker = (id - bias) & mask
        # over the UDP payload, pinning each source's stream to ONE
        # worker queue regardless of sender ports.  Without it the
        # kernel's 4-tuple hash may pile several sources onto one
        # worker (zero-copy then can't engage) — steering makes the
        # flow-learning deterministic.  Power-of-two worker counts
        # only (classic BPF has AND but no modulus).
        steer = getattr(self.fmt, 'SRC_STEER_BYTE', None)
        self._steered = False
        if (steer is not None and self.nthreads > 1 and
                getattr(socks[0], 'reuseport', False) and
                self.nthreads & (self.nthreads - 1) == 0 and
                hasattr(socks[0], 'attach_reuseport_cbpf')):
            off, bias = steer
            try:
                socks[0].attach_reuseport_cbpf([
                    (0x30, 0, 0, off),             # ldb payload[off]
                    (0x14, 0, 0, bias),            # sub #bias
                    (0x54, 0, 0, self.nthreads - 1),   # and #mask
                    (0x16, 0, 0, 0)])              # ret A
                self._steered = True
            except OSError:
                pass

        self._frame_size = frame_size or \
            getattr(self.fmt, 'frame_size', None)
        pay = (self._frame_size - self.fmt.header_size) \
            if self._frame_size else 0
        self._zc_payload = pay
        self._zero_copy_ok = bool(
            zero_copy and self._exclusive and
            hasattr(self.fmt, 'decode_batch') and
            0 < pay <= self.payload_size and
            all(hasattr(s, 'recv_mmsg_scatter') for s in self._socks))

        self._wstats = [dict(npackets=0, nbytes=0, zero_copy=0)
                        for _ in range(self.nthreads)]
        self._wstate = [dict(src=None, next=None, zc=False)
                        for _ in range(self.nthreads)]
        from .. import affinity
        self._cores = affinity.spread_cores(
            self.nthreads, cores if cores is not None else
            ([core] if core is not None and core >= 0 else None))
        self._stop = False
        self._error = None
        self._started_seen = False
        self._threads = []
        for i in range(self.nthreads):
            t = threading.Thread(
                target=self._worker, args=(i,),
                name='capture-%s-w%d' % (ring.name, i), daemon=True)
            self._threads.append(t)
            t.start()

    # -- worker side -------------------------------------------------------
    def _worker(self, widx):
        sock = self._socks[widx]
        try:
            core = self._cores[widx] if self._cores else None
            if core is not None:
                from .. import affinity
                affinity.set_core(core)
            st = self._wstate[widx]
            while not self._stop:
                if st['zc'] and self._seq0 is not None:
                    self._zero_copy_round(widx, sock, st)
                else:
                    self._staged_round(widx, sock, st)
        except BaseException as e:
            with self._lock:
                self._error = e
                self._commit_cv.notify_all()
                self._claim_cv.notify_all()

    def _staged_round(self, widx, sock, st):
        raw, lengths = sock.recv_mmsg_raw(self.vlen, self._raw_stride)
        if raw is None:
            return
        info = {}
        self._ingest_batch(raw, lengths, self._wstats[widx], info)
        if not self._zero_copy_ok:
            return
        u = info.get('srcs')
        with self._lock:
            if u is not None and len(u) == 1:
                # the kernel hashes per flow: one in-range source in
                # the whole batch means this worker owns that source's
                # stream
                st['src'] = int(u[0])
                st['next'] = int(info['max_seq']) + 1
                st['zc'] = True
            else:
                st['src'] = None
                st['zc'] = False
            self._claim_cv.notify_all()

    def _zero_copy_round(self, widx, sock, st):
        H = self.fmt.header_size
        F = self._frame_size
        P = self._zc_payload
        # wait for data BEFORE claiming: claims must only ever be held
        # across the nonblocking recvmmsg below.  While the queue is
        # hot (last batch came back full) skip the select — the claim
        # is released immediately on an empty recv, so the worst case
        # is one wasted claim per queue drain.
        if not st.get('hot'):
            ready, _, _ = select.select([sock.sock], [], [],
                                        self._timeout)
            if not ready or self._stop:
                # idle flow: drop the engagement so a stale cursor
                # can't hold the skew gate (_span_retirable) against
                # commits
                with self._lock:
                    st['zc'] = False
                    st['src'] = None
                    self._claim_cv.notify_all()
                return
        with self._lock:
            claim = self._claim_cells(st['src'], st['next'])
            if claim is None:
                # cursor unreachable (window raced past it) — resync
                # through the staged path
                st['zc'] = False
                st['src'] = None
                self._claim_cv.notify_all()
                return
            addrs, starts = claim
        try:
            side, lens = sock.recv_mmsg_scatter(addrs, H, P)
        except BaseException:
            with self._lock:
                self._release_claims(starts)
            raise
        with self._lock:
            self._release_claims(starts)
            if side is None:
                st['hot'] = False
                return
            n = len(lens)
            st['hot'] = n == len(addrs)
            ws = self._wstats[widx]
            ws['npackets'] += n
            ws['nbytes'] += int(sum(lens))
            ws['zero_copy'] += n
            self.stats['nreceived'] += n
            hdr_arr = np.frombuffer(side, np.uint8,
                                    count=n * H).reshape(n, H)
            try:
                out = self.fmt.decode_batch(hdr_arr, F)
            except ValueError:
                self.stats['ninvalid'] += n
                st['zc'] = False
                st['src'] = None
                self._claim_cv.notify_all()
                return
            seqs, srcs, hoff = out[:3]
            fvalid = out[3] if len(out) > 3 else None
            if hoff != H:
                self.stats['ninvalid'] += n
                st['zc'] = False
                st['src'] = None
                self._claim_cv.notify_all()
                return
            seqs = np.asarray(seqs, np.int64)
            e = int(st['next'])
            exp = np.arange(e, e + n, dtype=np.int64)
            okrow = np.asarray(lens, np.int64) == F
            if fvalid is not None:
                okrow &= np.asarray(fvalid, bool)
            srcs0 = np.asarray(srcs, np.int64) - self.src0
            self._note_seqs(seqs[okrow])
            hit = okrow & (srcs0 == st['src']) & (seqs == exp)
            if bool(hit.all()):
                self._mark_got(exp - self._seq0, st['src'])
                st['next'] = e + n
            else:
                self._repair_zc_batch(st, exp, seqs, srcs0, okrow, P)
            self._claim_cv.notify_all()   # progress: skew gate may open

    def _span_retirable(self, start):
        """Bounded-skew backpressure (engine lock held): the head span
        may not retire while an ENGAGED zero-copy sibling's cursor is
        still inside it.  On skewed hosts one worker would otherwise
        slide the window ahead and turn the other worker's entire
        kernel queue into late drops.  Advisory only — _commit_oldest
        waits a bounded grace, so a stalled flow cannot wedge the
        window."""
        if self._seq0 is None:
            return True
        end = start + self.buffer_ntime
        for st in self._wstate:
            nxt = st['next']
            if st['zc'] and nxt is not None and \
                    nxt - self._seq0 < end:
                return False
        return True

    def _claim_cells(self, src, e):
        """Engine lock held.  Claim the span cells for seqs
        [e, e+vlen) of ``src`` — sliding the window forward as needed —
        and return (cell_addresses, claimed_span_starts), or None when
        the cursor is unreachable (behind seq0 or the window head).
        Claims pin their spans against commit until released."""
        off0 = e - self._seq0
        if off0 < 0:
            return None
        self._ensure_window(off0)
        if not self._bufs or off0 < self._bufs[0][0]:
            return None
        last_end = self._bufs[-1][0] + self.buffer_ntime
        k = min(self.vlen, last_end - off0)
        addrs = np.empty(k, np.uint64)
        starts = []
        P = self._zc_payload
        for start, span, view, got in self._bufs:
            lo = max(off0, start)
            hi = min(off0 + k, start + self.buffer_ntime)
            if lo >= hi:
                continue
            lane = view.shape[2]
            ts = np.arange(lo - start, hi - start, dtype=np.int64)
            addrs[lo - off0:hi - off0] = \
                (view.ctypes.data +
                 (ts * self.nsrc + src) * lane).astype(np.uint64)
            if P < lane:
                view[ts, src, P:] = 0     # pre-zero stale lane tails
            self._claims[start] = self._claims.get(start, 0) + 1
            starts.append(start)
        return addrs, starts

    def _release_claims(self, starts):
        for s in starts:
            c = self._claims.get(s, 0) - 1
            if c > 0:
                self._claims[s] = c
            else:
                self._claims.pop(s, None)
        self._claim_cv.notify_all()

    def _locate(self, off):
        for start, span, view, got in self._bufs:
            if start <= off < start + self.buffer_ntime:
                return view, got, off - start
        return None

    def _mark_got(self, offs, src):
        for start, span, view, got in self._bufs:
            m = (offs >= start) & (offs < start + self.buffer_ntime)
            if m.any():
                ts = offs[m] - start
                ndup = int(got[ts, src].sum())
                if ndup:
                    self.stats['ndup'] += ndup
                got[ts, src] = True

    def _repair_zc_batch(self, st, exp, seqs, srcs0, okrow, P):
        """Engine lock held.  Slow path after a speculative scatter
        whose decoded headers disagree with the prediction: each
        payload currently sits at its PREDICTED cell
        (exp[i], st['src']).  Pass 1 bounce-copies every misplaced
        payload out BEFORE any window motion (a slide for one packet
        must not retire a span still holding another's bytes); pass 2
        places them at their true cells."""
        n = len(exp)
        src_pred = st['src']
        moves = []            # (i, seq, src, payload_copy)
        good_max = None
        demote = False
        for i in range(n):
            if not okrow[i]:
                self.stats['ninvalid'] += 1
                continue
            q = int(seqs[i])
            s = int(srcs0[i])
            if s < 0 or s >= self.nsrc:
                self.stats['nalien'] += 1
                self.stats['nignored'] += 1
                demote = True
                continue
            good_max = q if good_max is None else max(good_max, q)
            if s != src_pred:
                demote = True
            if q == int(exp[i]) and s == src_pred:
                self._mark_got(np.asarray([q - self._seq0]), s)
                continue
            loc = self._locate(int(exp[i]) - self._seq0)
            if loc is None:           # predicted span raced away
                self.stats['nlate'] += 1
                self.stats['nignored'] += 1
                continue
            pview, _, pt = loc
            moves.append((q, s, pview[pt, src_pred, :P].copy()))
        for q, s, payload in moves:
            toff = q - self._seq0
            if self._bufs and toff < self._bufs[0][0]:
                self.stats['nlate'] += 1
                self.stats['nignored'] += 1
                continue
            self._ensure_window(toff)
            loc = self._locate(toff)
            if loc is None:
                self.stats['nlate'] += 1
                self.stats['nignored'] += 1
                continue
            tview, tgot, tt = loc
            if tgot[tt, s]:
                self.stats['ndup'] += 1
            tview[tt, s, :P] = payload
            if P < tview.shape[2]:
                tview[tt, s, P:] = 0
            tgot[tt, s] = True
        if good_max is not None:
            st['next'] = good_max + 1
        if demote:
            st['zc'] = False
            st['src'] = None

    # -- consumer side -----------------------------------------------------
    def set_timeout(self, secs):
        self._timeout = secs
        for s in self._socks:
            s.set_timeout(secs)

    def recv(self):
        """Block until the workers commit a span (or the timeout
        expires): the worker threads ARE the capture loop; recv() is
        the pacing/observation point the single-thread engine's recv()
        is for callers."""
        with self._commit_cv:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            n0 = self._ncommits
            deadline = (time_mod.monotonic() + self._timeout) \
                if self._timeout is not None else None
            while (self._ncommits == n0 and self._error is None and
                    not self._stop):
                if deadline is None:
                    self._commit_cv.wait(1.0)
                else:
                    rem = deadline - time_mod.monotonic()
                    if rem <= 0:
                        break
                    self._commit_cv.wait(rem)
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            if self._ncommits == n0:
                return CAPTURE_NO_DATA if self._seq0 is None \
                    else CAPTURE_INTERRUPTED
            if not self._started_seen:
                self._started_seen = True
                return CAPTURE_STARTED
            return CAPTURE_CONTINUED

    def end(self):
        self._stop = True
        with self._lock:
            self._commit_cv.notify_all()
            self._claim_cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        rc = super(ShardedUDPCapture, self).end()
        for s in self._own_socks:
            try:
                s.close()
            except Exception:
                pass
        self._own_socks = []
        return rc


class UDPSniffer(_PacketCapture):
    """Promiscuous capture: sees every inbound UDP datagram on the host
    via a raw IPPROTO_UDP socket, filtered to ``addr``'s port, with the
    IP + UDP headers stripped (reference: bfUdpSnifferCreate,
    src/packet_capture.cpp:352, UDPSnifferCapture method
    packet_capture.hpp:287-304).  Requires CAP_NET_RAW/root."""

    def __init__(self, fmt, addr, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None):
        super(UDPSniffer, self).__init__(
            fmt, ring, nsrc, src0, max_payload_size, buffer_ntime,
            slot_ntime, sequence_callback, core)
        self.port = addr.port if hasattr(addr, 'port') else int(addr)
        self.raw = socket_mod.socket(socket_mod.AF_INET,
                                     socket_mod.SOCK_RAW,
                                     socket_mod.IPPROTO_UDP)
        self.raw.settimeout(0.5)

    def set_timeout(self, secs):
        self.raw.settimeout(secs)

    def _recv_packet(self):
        while True:
            try:
                dgram = self.raw.recv(65535)
            except (socket_mod.timeout, TimeoutError):
                return None
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    return None
                raise
            if len(dgram) < 1:
                continue
            ihl = (dgram[0] & 0xF) * 4          # IP header length
            if len(dgram) < ihl + 8:
                continue
            dport = int.from_bytes(dgram[ihl + 2:ihl + 4], 'big')
            if self.port and dport != self.port:
                continue
            return dgram[ihl + 8:]              # strip IP + UDP headers

    def close(self):
        self.raw.close()

    def __exit__(self, *exc):
        self.end()
        self.close()


class DiskReader(_PacketCapture):
    """Replay packets from a file of fixed-size records (reference:
    bfDiskReaderCreate, src/packet_capture.cpp:300; seek/tell for
    replayable ingest, packet_capture.cpp:417-426)."""

    def __init__(self, fmt, fh, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None):
        super(DiskReader, self).__init__(
            fmt, ring, nsrc, src0, max_payload_size, buffer_ntime,
            slot_ntime, sequence_callback, core)
        self.fh = fh
        self._pkt_size = self.fmt.header_size + max_payload_size

    def _recv_packet(self):
        raw = self.fh.read(self._pkt_size)
        if len(raw) < self._pkt_size:
            return None
        return raw

    def seek(self, offset, whence=0):
        return self.fh.seek(offset * self._pkt_size, whence)

    def tell(self):
        return self.fh.tell() // self._pkt_size
