"""Packet capture engine: UDP/disk packets -> ring, with per-source loss
accounting and sequence-change callbacks.

Architecture mirrors the reference capture stack (reference:
src/packet_capture.hpp:150-607, python/bifrost/packet_capture.py):

- a pluggable *method* supplies raw packets (UDP socket, disk reader)
- the *engine* decodes them with a wire format (io.packet_formats),
  scatters payloads into a sliding window of TWO open ring spans
  (double buffering, reference: packet_capture.hpp:485-534), commits
  the oldest span as the window slides, counts good/missing bytes per
  source, and zero-blanks sources with >50% loss in a span
- a user *sequence callback* builds the ring header when a new
  observation starts (C->Python callback boundary in the reference;
  plain Python here)

Ring frame layout: (time, nsrc, payload_bytes) — the sequence callback's
header tensor must describe the same frame size.
"""

from __future__ import annotations

import errno
import socket as socket_mod

import numpy as np

from .packet_formats import get_format, PacketDesc
from ..ring import RingWriter

__all__ = ['PacketCaptureCallback', 'UDPCapture', 'DiskReader',
           'CAPTURE_STARTED', 'CAPTURE_CONTINUED', 'CAPTURE_ENDED',
           'CAPTURE_NO_DATA', 'CAPTURE_INTERRUPTED']

CAPTURE_STARTED = 1
CAPTURE_CONTINUED = 2
CAPTURE_ENDED = 4
CAPTURE_NO_DATA = 8
CAPTURE_INTERRUPTED = 16


class PacketCaptureCallback(object):
    """Holds per-format sequence callbacks (reference:
    python/bifrost/packet_capture.py:45-89).  A callback is
    ``fn(desc: PacketDesc) -> (time_tag, header_dict)``."""

    def __init__(self):
        self._callbacks = {}

    def __getattr__(self, name):
        if name.startswith('set_'):
            fmt = name[4:]

            def setter(fn):
                self._callbacks[fmt] = fn
            return setter
        raise AttributeError(name)

    def get(self, fmt_name):
        return self._callbacks.get(fmt_name)


class _PacketCapture(object):
    def __init__(self, fmt, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None):
        self.nsrc = int(np.prod(nsrc)) if not np.isscalar(nsrc) else nsrc
        # 'cor' decoding depends on the source count (it sets the stand
        # count used to compose baseline indices, reference cor.hpp:74);
        # parameterize the codec with the engine's nsrc.  Other
        # parameterized codecs (TbnFormat(decimation=...)) are passed in
        # as format objects.
        if isinstance(fmt, str) and fmt.split('_')[0] == 'cor':
            self.fmt = get_format('cor', nsrc=self.nsrc)
        else:
            self.fmt = get_format(fmt)
        self.ring = ring
        self.src0 = src0
        self.payload_size = max_payload_size
        self.buffer_ntime = buffer_ntime
        self.slot_ntime = slot_ntime
        self.callback = sequence_callback.get(self.fmt.name) \
            if isinstance(sequence_callback, PacketCaptureCallback) \
            else sequence_callback
        self.core = core
        self._writer = None
        self._wseq = None
        self._seq0 = None
        self._bufs = []          # [(start_seq, WriteSpan, view, got_mask)]
        self.stats = {'ngood_bytes': 0, 'nmissing_bytes': 0,
                      'nignored': 0, 'ninvalid': 0,
                      'src_ngood': np.zeros(self.nsrc, np.int64)}
        from ..proclog import ProcLog
        self._stats_proclog = ProcLog('%s_capture/stats' % ring.name)

    # -- method interface --------------------------------------------------
    def _recv_packet(self):
        raise NotImplementedError

    # -- engine ------------------------------------------------------------
    def _begin_sequence(self, desc):
        if self._writer is None:
            self._writer = RingWriter(self.ring)
        time_tag, hdr = self.callback(desc)
        hdr.setdefault('time_tag', time_tag)
        hdr.setdefault('name', hdr.get('name', 'capture-%d' % time_tag))
        # downstream pipeline blocks size their gulps from the header
        hdr.setdefault('gulp_nframe', self.buffer_ntime)
        self._wseq = self._writer.begin_sequence(
            hdr, gulp_nframe=self.buffer_ntime,
            buf_nframe=4 * self.buffer_ntime)
        self._seq0 = (desc.seq // self.slot_ntime) * self.slot_ntime
        self._bufs = []

    def _open_buf(self, start):
        span = self._wseq.reserve(self.buffer_ntime)
        view = span.data.as_numpy().view(np.uint8).reshape(
            self.buffer_ntime, self.nsrc, -1)
        view[...] = 0
        got = np.zeros((self.buffer_ntime, self.nsrc), bool)
        self._bufs.append((start, span, view, got))

    def _commit_oldest(self):
        start, span, view, got = self._bufs.pop(0)
        # per-source loss accounting + >50%-loss blanking
        # (reference: packet_capture.hpp:505-534)
        pkt_bytes = self.payload_size
        for src in range(self.nsrc):
            ngood = int(got[:, src].sum())
            self.stats['src_ngood'][src] += ngood * pkt_bytes
            nmiss = self.buffer_ntime - ngood
            self.stats['nmissing_bytes'] += nmiss * pkt_bytes
            self.stats['ngood_bytes'] += ngood * pkt_bytes
            if ngood * 2 < self.buffer_ntime:
                view[:, src] = 0   # blank unreliable source
        span.commit(self.buffer_ntime)
        span.close()
        self._stats_proclog.update({
            'ngood_bytes': self.stats['ngood_bytes'],
            'nmissing_bytes': self.stats['nmissing_bytes'],
            'ninvalid': self.stats['ninvalid'],
            'nignored': self.stats['nignored']})

    def recv(self):
        """Process packets until one buffer's worth of time has been
        committed (reference: bfPacketCaptureRecv)."""
        started = False
        committed = False
        while not committed:
            pkt = self._recv_packet()
            if pkt is None:
                return CAPTURE_NO_DATA if self._seq0 is None \
                    else CAPTURE_INTERRUPTED
            desc = self.fmt.unpack(pkt)
            if desc is None:
                self.stats['ninvalid'] += 1
                continue
            desc.src -= self.src0
            if desc.src < 0 or desc.src >= self.nsrc:
                self.stats['nignored'] += 1
                continue
            if self._seq0 is None:
                self._begin_sequence(desc)
                started = True
            off = desc.seq - self._seq0
            if off < 0:
                self.stats['nignored'] += 1
                continue
            # slide the double-buffered window forward as needed
            while True:
                last_end = (self._bufs[-1][0] + self.buffer_ntime) \
                    if self._bufs else 0
                if off < last_end:
                    break
                if len(self._bufs) == 2:
                    self._commit_oldest()
                    committed = True
                self._open_buf(last_end)
            for start, span, view, got in self._bufs:
                if start <= off < start + self.buffer_ntime:
                    t = off - start
                    payload = np.frombuffer(desc.payload, np.uint8)
                    view[t, desc.src, :len(payload)] = payload
                    got[t, desc.src] = True
                    break
                elif off < start:
                    self.stats['nignored'] += 1   # too late
                    break
        return CAPTURE_STARTED if started else CAPTURE_CONTINUED

    def flush(self):
        while self._bufs:
            self._commit_oldest()

    def end(self):
        self.flush()
        if self._wseq is not None:
            self._wseq.end()
            self._wseq = None
        if self._writer is not None:
            self.ring.end_writing()
            self._writer = None
        self._seq0 = None
        return CAPTURE_ENDED

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()


class UDPCapture(_PacketCapture):
    """Capture packets from a UDP socket (reference:
    bfUdpCaptureCreate, src/packet_capture.cpp:324)."""

    def __init__(self, fmt, sock, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None):
        super(UDPCapture, self).__init__(
            fmt, ring, nsrc, src0, max_payload_size, buffer_ntime,
            slot_ntime, sequence_callback, core)
        self.sock = sock

    def _recv_packet(self):
        try:
            return self.sock.recv(self.payload_size + 1024)
        except (socket_mod.timeout, TimeoutError):
            return None
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return None
            raise


class DiskReader(_PacketCapture):
    """Replay packets from a file of fixed-size records (reference:
    bfDiskReaderCreate, src/packet_capture.cpp:300; seek/tell for
    replayable ingest, packet_capture.cpp:417-426)."""

    def __init__(self, fmt, fh, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None):
        super(DiskReader, self).__init__(
            fmt, ring, nsrc, src0, max_payload_size, buffer_ntime,
            slot_ntime, sequence_callback, core)
        self.fh = fh
        self._pkt_size = self.fmt.header_size + max_payload_size

    def _recv_packet(self):
        raw = self.fh.read(self._pkt_size)
        if len(raw) < self._pkt_size:
            return None
        return raw

    def seek(self, offset, whence=0):
        return self.fh.seek(offset * self._pkt_size, whence)

    def tell(self):
        return self.fh.tell() // self._pkt_size
