"""Packet capture engine: UDP/disk packets -> ring, with per-source loss
accounting and sequence-change callbacks.

Architecture mirrors the reference capture stack (reference:
src/packet_capture.hpp:150-607, python/bifrost/packet_capture.py):

- a pluggable *method* supplies raw packets (UDP socket, disk reader)
- the *engine* decodes them with a wire format (io.packet_formats),
  scatters payloads into a sliding window of TWO open ring spans
  (double buffering, reference: packet_capture.hpp:485-534), commits
  the oldest span as the window slides, counts good/missing bytes per
  source, and zero-blanks sources with >50% loss in a span
- a user *sequence callback* builds the ring header when a new
  observation starts (C->Python callback boundary in the reference;
  plain Python here)

Ring frame layout: (time, nsrc, payload_bytes) — the sequence callback's
header tensor must describe the same frame size.
"""

from __future__ import annotations

import ctypes
import errno
import socket as socket_mod

import numpy as np

from .packet_formats import get_format, PacketDesc
from ..ring import RingWriter

__all__ = ['PacketCaptureCallback', 'UDPCapture', 'NativeUDPCapture',
           'UDPSniffer', 'DiskReader',
           'CAPTURE_STARTED', 'CAPTURE_CONTINUED', 'CAPTURE_ENDED',
           'CAPTURE_NO_DATA', 'CAPTURE_INTERRUPTED']

CAPTURE_STARTED = 1
CAPTURE_CONTINUED = 2
CAPTURE_ENDED = 4
CAPTURE_NO_DATA = 8
CAPTURE_INTERRUPTED = 16


class PacketCaptureCallback(object):
    """Holds per-format sequence callbacks (reference:
    python/bifrost/packet_capture.py:45-89).  A callback is
    ``fn(desc: PacketDesc) -> (time_tag, header_dict)``."""

    def __init__(self):
        self._callbacks = {}

    def __getattr__(self, name):
        if name.startswith('set_'):
            fmt = name[4:]

            def setter(fn):
                self._callbacks[fmt] = fn
            return setter
        raise AttributeError(name)

    def get(self, fmt_name):
        return self._callbacks.get(fmt_name)


class _PacketCapture(object):
    def __init__(self, fmt, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None):
        self.nsrc = int(np.prod(nsrc)) if not np.isscalar(nsrc) else nsrc
        # 'cor' decoding depends on the source count (it sets the stand
        # count used to compose baseline indices, reference cor.hpp:74);
        # parameterize the codec with the engine's nsrc.  Other
        # parameterized codecs (TbnFormat(decimation=...)) are passed in
        # as format objects.
        if isinstance(fmt, str) and fmt.split('_')[0] == 'cor':
            self.fmt = get_format('cor', nsrc=self.nsrc)
        else:
            self.fmt = get_format(fmt)
        self.ring = ring
        if getattr(self.fmt, 'applies_src0', False):
            # pbeam/cor apply src0 in composed (beam/baseline) units
            # inside the decoder, like the reference (pbeam.hpp:70,
            # cor.hpp:77); the engine must not rebase again.  Copy the
            # codec first: get_format() may hand back the shared
            # registry singleton.  A src0 already configured on a
            # passed-in format object wins over the engine default 0;
            # conflicting nonzero values are an error.
            import copy as _copy
            fmt_src0 = getattr(self.fmt, 'src0', 0)
            if src0 and fmt_src0 and src0 != fmt_src0:
                raise ValueError(
                    "conflicting src0: capture got %d but the %s codec "
                    "was built with src0=%d" % (src0, self.fmt.name,
                                                fmt_src0))
            self.fmt = _copy.copy(self.fmt)
            self.fmt.src0 = src0 or fmt_src0
            src0 = 0
        self.src0 = src0
        self.payload_size = max_payload_size
        self.buffer_ntime = buffer_ntime
        self.slot_ntime = slot_ntime
        self.callback = sequence_callback.get(self.fmt.name) \
            if isinstance(sequence_callback, PacketCaptureCallback) \
            else sequence_callback
        self.core = core
        self._writer = None
        self._wseq = None
        self._seq0 = None
        self._bufs = []          # [(start_seq, WriteSpan, view, got_mask)]
        self.stats = {'ngood_bytes': 0, 'nmissing_bytes': 0,
                      'nignored': 0, 'ninvalid': 0,
                      'src_ngood': np.zeros(self.nsrc, np.int64)}
        from ..proclog import ProcLog
        self._stats_proclog = ProcLog('%s_capture/stats' % ring.name)

    # -- method interface --------------------------------------------------
    def _recv_packet(self):
        raise NotImplementedError

    # -- engine ------------------------------------------------------------
    def _begin_sequence(self, desc):
        if self._writer is None:
            self._writer = RingWriter(self.ring)
        time_tag, hdr = self.callback(desc)
        hdr.setdefault('time_tag', time_tag)
        hdr.setdefault('name', hdr.get('name', 'capture-%d' % time_tag))
        # downstream pipeline blocks size their gulps from the header
        hdr.setdefault('gulp_nframe', self.buffer_ntime)
        self._wseq = self._writer.begin_sequence(
            hdr, gulp_nframe=self.buffer_ntime,
            buf_nframe=4 * self.buffer_ntime)
        self._seq0 = (desc.seq // self.slot_ntime) * self.slot_ntime
        self._bufs = []

    def _open_buf(self, start):
        span = self._wseq.reserve(self.buffer_ntime)
        view = span.data.as_numpy().view(np.uint8).reshape(
            self.buffer_ntime, self.nsrc, -1)
        view[...] = 0
        got = np.zeros((self.buffer_ntime, self.nsrc), bool)
        self._bufs.append((start, span, view, got))

    def _commit_oldest(self):
        start, span, view, got = self._bufs.pop(0)
        # per-source loss accounting + >50%-loss blanking
        # (reference: packet_capture.hpp:505-534)
        pkt_bytes = self.payload_size
        for src in range(self.nsrc):
            ngood = int(got[:, src].sum())
            self.stats['src_ngood'][src] += ngood * pkt_bytes
            nmiss = self.buffer_ntime - ngood
            self.stats['nmissing_bytes'] += nmiss * pkt_bytes
            self.stats['ngood_bytes'] += ngood * pkt_bytes
            if ngood * 2 < self.buffer_ntime:
                view[:, src] = 0   # blank unreliable source
        span.commit(self.buffer_ntime)
        span.close()
        self._stats_proclog.update({
            'ngood_bytes': self.stats['ngood_bytes'],
            'nmissing_bytes': self.stats['nmissing_bytes'],
            'ninvalid': self.stats['ninvalid'],
            'nignored': self.stats['nignored'],
            'npackets': self.stats['ngood_bytes'] // self.payload_size})

    # -- vectorized batch path (recvmmsg + decode_batch formats) -----------
    def _assign_batch(self, offs, srcs, payloads):
        """Scatter a decoded batch into the open window, sliding it as
        needed.  Returns True if any span was committed."""
        committed = False
        remaining = np.ones(len(offs), bool)
        while remaining.any():
            last_end = (self._bufs[-1][0] + self.buffer_ntime) \
                if self._bufs else 0
            beyond = remaining & (offs >= last_end)
            in_window = remaining & (offs < last_end)
            idx = np.nonzero(in_window)[0]
            if idx.size:
                o = offs[idx]
                for start, span, view, got in self._bufs:
                    m = (o >= start) & (o < start + self.buffer_ntime)
                    if m.any():
                        sel = idx[m]
                        ts = offs[sel] - start
                        view[ts, srcs[sel], :payloads.shape[1]] = \
                            payloads[sel]
                        got[ts, srcs[sel]] = True
                if self._bufs:
                    too_late = o < self._bufs[0][0]
                    self.stats['nignored'] += int(too_late.sum())
                remaining[idx] = False
            if beyond.any():
                if len(self._bufs) == 2:
                    self._commit_oldest()
                    committed = True
                self._open_buf(last_end)
            elif not idx.size:
                break
        return committed

    def _recv_batched(self):
        """recv() over whole recvmmsg batches with vectorized header
        decode — the per-packet Python cost (struct.unpack + slice +
        scatter) collapses into a handful of numpy ops per batch."""
        started = False
        committed = False
        while not committed:
            raw, lengths = self._recv_raw_batch()
            if raw is None:
                return CAPTURE_NO_DATA if self._seq0 is None \
                    else CAPTURE_INTERRUPTED
            n = len(lengths)
            stride = self._raw_stride
            arr = np.frombuffer(raw, np.uint8,
                                count=n * stride).reshape(n, stride)
            if len(set(lengths)) != 1:
                # mixed sizes: per-packet fallback for this batch
                for i in range(n):
                    s, c = self._process_one(bytes(arr[i, :lengths[i]]))
                    started = started or s
                    committed = committed or c
                continue
            if lengths[0] < self.fmt.header_size:
                self.stats['ninvalid'] += n     # runts
                continue
            seqs, srcs, hoff = self.fmt.decode_batch(arr)
            srcs = srcs - self.src0
            valid = (srcs >= 0) & (srcs < self.nsrc)
            self.stats['nignored'] += int((~valid).sum())
            if not valid.any():
                continue
            if self._seq0 is None:
                first = int(np.nonzero(valid)[0][0])
                desc = self.fmt.unpack(bytes(arr[first, :lengths[first]]))
                if desc is None:
                    self.stats['ninvalid'] += 1
                    continue
                desc.src -= self.src0
                self._begin_sequence(desc)
                started = True
            offs = seqs - self._seq0
            fresh = valid & (offs >= 0)
            self.stats['nignored'] += int((valid & ~fresh).sum())
            if not fresh.any():
                continue
            payloads = arr[:, hoff:lengths[0]]
            committed = self._assign_batch(offs[fresh].astype(np.int64),
                                           srcs[fresh].astype(np.int64),
                                           payloads[fresh]) or committed
        return CAPTURE_STARTED if started else CAPTURE_CONTINUED

    def _recv_raw_batch(self):
        return None, None       # only UDPCapture implements this

    def _process_one(self, pkt):
        """Single-packet slow path used by recv() and mixed batches."""
        desc = self.fmt.unpack(pkt)
        if desc is None or desc.valid_mode:
            # reference decoders gate on valid_mode (tbn.hpp:64,
            # drx.hpp:64); the native engine does the same
            self.stats['ninvalid'] += 1
            return False, False
        desc.src -= self.src0
        if desc.src < 0 or desc.src >= self.nsrc:
            self.stats['nignored'] += 1
            return False, False
        started = False
        if self._seq0 is None:
            self._begin_sequence(desc)
            started = True
        off = desc.seq - self._seq0
        if off < 0:
            self.stats['nignored'] += 1
            return started, False
        committed = False
        while True:
            last_end = (self._bufs[-1][0] + self.buffer_ntime) \
                if self._bufs else 0
            if off < last_end:
                break
            if len(self._bufs) == 2:
                self._commit_oldest()
                committed = True
            self._open_buf(last_end)
        for start, span, view, got in self._bufs:
            if start <= off < start + self.buffer_ntime:
                t = off - start
                payload = np.frombuffer(desc.payload, np.uint8)
                view[t, desc.src, :len(payload)] = payload
                got[t, desc.src] = True
                break
            elif off < start:
                self.stats['nignored'] += 1   # too late
                break
        return started, committed

    def recv(self):
        """Process packets until one buffer's worth of time has been
        committed (reference: bfPacketCaptureRecv)."""
        if getattr(self, '_use_batch', False):
            return self._recv_batched()
        started = False
        committed = False
        while not committed:
            pkt = self._recv_packet()
            if pkt is None:
                return CAPTURE_NO_DATA if self._seq0 is None \
                    else CAPTURE_INTERRUPTED
            s, c = self._process_one(pkt)
            started = started or s
            committed = committed or c
        return CAPTURE_STARTED if started else CAPTURE_CONTINUED

    def flush(self):
        while self._bufs:
            self._commit_oldest()

    def end(self):
        self.flush()
        # final cumulative stats must land regardless of throttling
        self._stats_proclog.update({
            'ngood_bytes': self.stats['ngood_bytes'],
            'nmissing_bytes': self.stats['nmissing_bytes'],
            'ninvalid': self.stats['ninvalid'],
            'nignored': self.stats['nignored'],
            'npackets': self.stats['ngood_bytes'] // self.payload_size},
            force=True)
        if self._wseq is not None:
            self._wseq.end()
            self._wseq = None
        if self._writer is not None:
            self.ring.end_writing()
            self._writer = None
        self._seq0 = None
        return CAPTURE_ENDED

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()


#: wire formats with a native C++ decoder (native/capture.cpp);
#: ids must match the FMT_* enum there
NATIVE_FMT_IDS = {'simple': 0, 'chips': 1, 'tbn': 2, 'drx': 3,
                  'drx8': 4, 'ibeam': 5, 'cor': 6, 'pbeam': 7,
                  'snap2': 8, 'vdif': 9, 'tbf': 10, 'vbeam': 11}
#: formats the native TRANSMIT engine can fill headers for
NATIVE_TX_FMT_IDS = dict(NATIVE_FMT_IDS)
_NATIVE_FMT_IDS = NATIVE_FMT_IDS    # backwards-compat alias


def native_io_usable(fmt, sock, fmt_ids=None):
    """Shared gate for the native IO engines: env opt-out, format has a
    C++ codec, socket exposes a file descriptor, and the .so was built
    with the (Linux-only) engines rather than portable stubs."""
    import os
    if os.environ.get('BF_NO_NATIVE_CAPTURE'):
        return False
    base = fmt.split('_')[0] if isinstance(fmt, str) else \
        getattr(fmt, 'name', None)
    ids = NATIVE_FMT_IDS if fmt_ids is None else fmt_ids
    if base not in ids or not hasattr(sock, 'fileno'):
        return False
    from ..native import io_engine_supported
    return io_engine_supported()


def _native_capture_usable(fmt, sock, ring):
    try:
        from ..ring_native import NativeRing
    except Exception:
        return False
    if not isinstance(ring, NativeRing):
        return False
    return native_io_usable(fmt, sock)


class UDPCapture(_PacketCapture):
    """Capture packets from a UDP socket (reference:
    bfUdpCaptureCreate, src/packet_capture.cpp:324).

    Dispatch: when the ring is native and the format has a C++ decoder,
    construction returns a :class:`NativeUDPCapture` — the whole
    recv/decode/scatter loop runs in native/capture.cpp like the
    reference engine (set BF_NO_NATIVE_CAPTURE=1 to force Python).
    The Python engine uses recvmmsg batching + vectorized decode when
    the socket and format support it, per-packet recv otherwise."""

    BATCH = 128

    def __new__(cls, fmt=None, sock=None, ring=None, *args, **kwargs):
        if cls is UDPCapture and _native_capture_usable(fmt, sock, ring):
            from ..native import available
            if available():
                return super(UDPCapture, cls).__new__(NativeUDPCapture)
        return super(UDPCapture, cls).__new__(cls)

    def __init__(self, fmt, sock, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None,
                 batch=None):
        super(UDPCapture, self).__init__(
            fmt, ring, nsrc, src0, max_payload_size, buffer_ntime,
            slot_ntime, sequence_callback, core)
        self.sock = sock
        self.batch = batch or self.BATCH
        self._pending = []
        self._pending_idx = 0
        self._use_mmsg = hasattr(sock, 'recv_mmsg')
        # fully-vectorized path: recvmmsg raw buffer + batch header
        # decode (formats that define decode_batch)
        self._raw_stride = max_payload_size + 1024
        self._use_batch = (hasattr(sock, 'recv_mmsg_raw') and
                           hasattr(self.fmt, 'decode_batch'))

    def _recv_raw_batch(self):
        return self.sock.recv_mmsg_raw(self.batch, self._raw_stride)

    def _recv_plain(self):
        from .udp_socket import UDPSocket, retry_transient
        try:
            # retry_transient handles EINTR/ECONNREFUSED with capped
            # backoff (telemetry: io.socket_retries) — a briefly
            # restarting peer must not kill a long-running capture.
            # UDPSocket.recv already retries internally; wrapping it
            # again would square the retry budget, so only plain
            # socket objects handed to the capture get the wrapper.
            if isinstance(self.sock, UDPSocket):
                return self.sock.recv(self.payload_size + 1024)
            return retry_transient(
                lambda: self.sock.recv(self.payload_size + 1024))
        except (socket_mod.timeout, TimeoutError):
            return None
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return None
            raise

    def _recv_packet(self):
        if not self._use_mmsg:
            return self._recv_plain()
        if self._pending_idx >= len(self._pending):
            try:
                batch = self.sock.recv_mmsg(self.batch,
                                            self.payload_size + 1024)
            except (OSError, AttributeError):
                self._use_mmsg = False
                return self._recv_plain()
            if not batch:
                return None
            self._pending = batch
            self._pending_idx = 0
        pkt = self._pending[self._pending_idx]
        self._pending_idx += 1
        return pkt


class _BftPktDesc(ctypes.Structure):
    # mirrors bft_pkt_desc in native/capture.cpp
    _fields_ = [('seq', ctypes.c_longlong),
                ('time_tag', ctypes.c_longlong),
                ('src', ctypes.c_int),
                ('nsrc', ctypes.c_int),
                ('nchan', ctypes.c_int),
                ('chan0', ctypes.c_int),
                ('tuning', ctypes.c_int),
                ('tuning1', ctypes.c_int),
                ('gain', ctypes.c_int),
                ('decimation', ctypes.c_int),
                ('beam', ctypes.c_int),
                ('npol', ctypes.c_int),
                ('npol_tot', ctypes.c_int),
                ('pol0', ctypes.c_int),
                ('nchan_tot', ctypes.c_int),
                ('payload_size', ctypes.c_int)]


class NativeUDPCapture(UDPCapture):
    """UDP capture driven end-to-end by the native engine
    (native/capture.cpp): recvmmsg batches, C++ header decode, scatter
    straight into the native ring's buffer, loss accounting and
    blanking — the reference's capture-thread architecture
    (src/packet_capture.hpp:150-607).  Python is entered only once per
    sequence to build the ring header (the same C->Python callback
    boundary the reference has)."""

    def __init__(self, fmt, sock, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None,
                 batch=None):
        import json
        from .. import native as native_mod
        # shared setup (format/callback resolution, counters, proclog)
        _PacketCapture.__init__(self, fmt, ring, nsrc, src0,
                                max_payload_size, buffer_ntime,
                                slot_ntime, sequence_callback, core)
        self.sock = sock
        self._lib = native_mod.load()
        self._cb_error = None
        handle = ctypes.c_void_p()
        # composed-src formats (pbeam/cor) apply src0 in the C decoder
        # in beam/baseline units; the base init has already folded the
        # engine src0 into the codec, so forward the codec's value
        if getattr(self.fmt, 'applies_src0', False):
            src0 = int(self.fmt.src0)
        native_mod.check(self._lib.bft_capture_create(
            ctypes.byref(handle), _NATIVE_FMT_IDS[self.fmt.name],
            sock.fileno(), ring._handle, self.nsrc, src0,
            max_payload_size, buffer_ntime, slot_ntime), 'capture')
        self._handle = handle
        if getattr(self.fmt, 'decimation', None):
            # TBN derives seq from time_tag via the stream decimation
            self._lib.bft_capture_set_decimation(
                handle, int(self.fmt.decimation))
        elif getattr(self.fmt, 'frames_per_second', None):
            # VDIF: seq = secs * fps + frame; fps rides the same slot
            self._lib.bft_capture_set_decimation(
                handle, int(self.fmt.frames_per_second))
        self._applied_timeout = object()     # force first sync
        self._sync_timeout()

        CB = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                              ctypes.POINTER(_BftPktDesc),
                              ctypes.POINTER(ctypes.c_longlong),
                              ctypes.POINTER(ctypes.c_char),
                              ctypes.c_int,
                              ctypes.POINTER(ctypes.c_char),
                              ctypes.c_int)

        def header_cb(user, desc_p, time_tag_out, name_buf, name_cap,
                      hdr_buf, hdr_cap):
            try:
                d = desc_p.contents
                desc = PacketDesc(seq=d.seq, src=d.src, nsrc=d.nsrc,
                                  nchan=d.nchan, chan0=d.chan0,
                                  time_tag=d.time_tag, tuning=d.tuning,
                                  tuning1=d.tuning1, gain=d.gain,
                                  decimation=max(d.decimation, 1),
                                  beam=d.beam, npol=d.npol,
                                  npol_tot=d.npol_tot, pol0=d.pol0,
                                  nchan_tot=d.nchan_tot)
                time_tag, hdr = self.callback(desc)
                hdr.setdefault('time_tag', time_tag)
                hdr.setdefault('name', 'capture-%d' % time_tag)
                hdr.setdefault('gulp_nframe', self.buffer_ntime)
                name = str(hdr['name']).encode()[:name_cap - 1]
                ctypes.memmove(name_buf, name + b'\x00', len(name) + 1)
                raw = json.dumps(hdr).encode()
                if len(raw) + 1 > hdr_cap:
                    raise ValueError("header JSON too large")
                ctypes.memmove(hdr_buf, raw + b'\x00', len(raw) + 1)
                time_tag_out[0] = time_tag
                return 0
            except BaseException as e:
                # surfaced by the next recv() on the Python side
                self._cb_error = e
                return -1

        self._cb = CB(header_cb)     # keep a reference alive
        self._lib.bft_capture_set_header_callback(
            handle, ctypes.cast(self._cb, ctypes.c_void_p), None)
        self.stats = _NativeCaptureStats(self)

    def _sync_timeout(self):
        """Mirror the socket's (possibly updated) timeout into the
        native poll: None = block like the Python engine's select."""
        t = getattr(self.sock, '_timeout', None)
        if t != self._applied_timeout:
            self._lib.bft_capture_set_timeout_ms(
                self._handle, -1 if t is None else max(int(t * 1000), 1))
            self._applied_timeout = t

    def recv(self):
        from .. import native as native_mod
        self._sync_timeout()
        status = ctypes.c_int(0)
        native_mod.check(self._lib.bft_capture_recv(
            self._handle, ctypes.byref(status)), 'recv')
        if self._cb_error is not None:
            err, self._cb_error = self._cb_error, None
            raise err
        if status.value in (CAPTURE_STARTED, CAPTURE_CONTINUED):
            st = self.stats._read()
            st['npackets'] = st.get('ngood_bytes', 0) // \
                self.payload_size
            self._stats_proclog.update({
                k: v for k, v in st.items() if k != 'src_ngood'})
        return status.value

    def flush(self):
        self._lib.bft_capture_flush(self._handle)

    def end(self):
        self._lib.bft_capture_end(self._handle)
        st = self.stats._read()
        st['npackets'] = st.get('ngood_bytes', 0) // self.payload_size
        self._stats_proclog.update(
            {k: v for k, v in st.items() if k != 'src_ngood'},
            force=True)
        return CAPTURE_ENDED

    def __del__(self):
        try:
            if getattr(self, '_handle', None) is not None:
                self._lib.bft_capture_destroy(self._handle)
                self._handle = None
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()


class _NativeCaptureStats(object):
    """Read-through view of the native engine's counters, dict-like to
    match the Python engine's ``stats``."""

    def __init__(self, cap):
        self._cap = cap

    def _read(self):
        ll = ctypes.c_longlong
        g, m, iv, ig = ll(0), ll(0), ll(0), ll(0)
        self._cap._lib.bft_capture_stats(
            self._cap._handle, ctypes.byref(g), ctypes.byref(m),
            ctypes.byref(iv), ctypes.byref(ig))
        src = (ll * self._cap.nsrc)()
        self._cap._lib.bft_capture_src_ngood(
            self._cap._handle, src, self._cap.nsrc)
        return {'ngood_bytes': g.value, 'nmissing_bytes': m.value,
                'ninvalid': iv.value, 'nignored': ig.value,
                'src_ngood': np.asarray(list(src), np.int64)}

    def __getitem__(self, key):
        return self._read()[key]

    def get(self, key, default=None):
        return self._read().get(key, default)

    def __repr__(self):
        return repr(self._read())


class UDPSniffer(_PacketCapture):
    """Promiscuous capture: sees every inbound UDP datagram on the host
    via a raw IPPROTO_UDP socket, filtered to ``addr``'s port, with the
    IP + UDP headers stripped (reference: bfUdpSnifferCreate,
    src/packet_capture.cpp:352, UDPSnifferCapture method
    packet_capture.hpp:287-304).  Requires CAP_NET_RAW/root."""

    def __init__(self, fmt, addr, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None):
        super(UDPSniffer, self).__init__(
            fmt, ring, nsrc, src0, max_payload_size, buffer_ntime,
            slot_ntime, sequence_callback, core)
        self.port = addr.port if hasattr(addr, 'port') else int(addr)
        self.raw = socket_mod.socket(socket_mod.AF_INET,
                                     socket_mod.SOCK_RAW,
                                     socket_mod.IPPROTO_UDP)
        self.raw.settimeout(0.5)

    def set_timeout(self, secs):
        self.raw.settimeout(secs)

    def _recv_packet(self):
        while True:
            try:
                dgram = self.raw.recv(65535)
            except (socket_mod.timeout, TimeoutError):
                return None
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    return None
                raise
            if len(dgram) < 1:
                continue
            ihl = (dgram[0] & 0xF) * 4          # IP header length
            if len(dgram) < ihl + 8:
                continue
            dport = int.from_bytes(dgram[ihl + 2:ihl + 4], 'big')
            if self.port and dport != self.port:
                continue
            return dgram[ihl + 8:]              # strip IP + UDP headers

    def close(self):
        self.raw.close()

    def __exit__(self, *exc):
        self.end()
        self.close()


class DiskReader(_PacketCapture):
    """Replay packets from a file of fixed-size records (reference:
    bfDiskReaderCreate, src/packet_capture.cpp:300; seek/tell for
    replayable ingest, packet_capture.cpp:417-426)."""

    def __init__(self, fmt, fh, ring, nsrc, src0, max_payload_size,
                 buffer_ntime, slot_ntime, sequence_callback, core=None):
        super(DiskReader, self).__init__(
            fmt, ring, nsrc, src0, max_payload_size, buffer_ntime,
            slot_ntime, sequence_callback, core)
        self.fh = fh
        self._pkt_size = self.fmt.header_size + max_payload_size

    def _recv_packet(self):
        raw = self.fh.read(self._pkt_size)
        if len(raw) < self._pkt_size:
            return None
        return raw

    def seek(self, offset, whence=0):
        return self.fh.seek(offset * self._pkt_size, whence)

    def tell(self):
        return self.fh.tell() // self._pkt_size
