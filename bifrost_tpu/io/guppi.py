"""GUPPI RAW format (Breakthrough-Listen / guppi_daq).

Format (public spec; reference implementation:
python/bifrost/guppi_raw.py:28-99): blocks of 80-char FITS-like header
records ('KEY     = value', 'END' terminated, optional DIRECTIO 512-byte
alignment) each followed by BLOCSIZE bytes of [chan][time][pol] complex
integer voltages.
"""

from __future__ import annotations

__all__ = ['read_header', 'write_header']

RECORD_LEN = 80
DIRECTIO_ALIGN = 512


def read_header(f):
    hdr = {}
    nread = 0
    while True:
        record = f.read(RECORD_LEN)
        nread += RECORD_LEN
        if len(record) < RECORD_LEN:
            if not hdr and len(record) == 0:
                raise EOFError("No more blocks")
            raise IOError("EOF mid-header")
        record = record.decode('ascii', 'replace')
        if record.startswith('END'):
            break
        key, _, val = record.partition('=')
        key, val = key.strip(), val.strip()
        try:
            val = int(val)
        except ValueError:
            try:
                val = float(val)
            except ValueError:
                if val[:1] in ("'", '"'):
                    val = val[1:-1].rstrip()
        hdr[key] = val
    if hdr.get('DIRECTIO', 0):
        pad = (-f.tell()) % DIRECTIO_ALIGN
        if pad:
            f.read(pad)
    if 'NPOL' in hdr:
        # NPOL=4 conventionally counts complex components
        hdr['NPOL'] = 1 if hdr['NPOL'] == 1 else 2
    if 'NTIME' not in hdr and 'BLOCSIZE' in hdr:
        hdr['NTIME'] = hdr['BLOCSIZE'] * 8 // (
            hdr['OBSNCHAN'] * hdr['NPOL'] * 2 * hdr['NBITS'])
    return hdr


def write_header(f, hdr):
    """Write a GUPPI block header (no DIRECTIO padding)."""
    for key, val in hdr.items():
        if key in ('NTIME',):
            continue
        if isinstance(val, str):
            sval = "'%s'" % val
        else:
            sval = repr(val)
        record = '%-8s= %s' % (key[:8], sval)
        f.write(record.ljust(RECORD_LEN)[:RECORD_LEN].encode('ascii'))
    f.write(b'END' + b' ' * (RECORD_LEN - 3))
