"""SIGPROC filterbank / time-series file format.

Format (public SIGPROC spec; reference implementation:
python/bifrost/sigproc.py, sigproc2.py): a header of
``<u4 length><keyword>`` records between HEADER_START and HEADER_END,
with int / double / string values, followed by raw little-endian data
of shape (time, nifs, nchans) at ``nbits`` per sample.
"""

from __future__ import annotations

import os
import struct

import numpy as np

__all__ = ['SigprocFile', 'write_header', 'pack_header',
           'id2telescope', 'telescope2id', 'id2machine', 'machine2id']

_INT_KEYS = {'telescope_id', 'machine_id', 'data_type', 'nchans', 'nbits',
             'nifs', 'scan_number', 'barycentric', 'pulsarcentric',
             'ibeam', 'nbeams', 'nsamples'}
_DBL_KEYS = {'az_start', 'za_start', 'src_raj', 'src_dej', 'tstart',
             'tsamp', 'fch1', 'foff', 'refdm', 'period', 'fchannel'}
_STR_KEYS = {'source_name', 'rawdatafile'}
_CHR_KEYS = {'signed'}

_TELESCOPES = {0: 'fake', 1: 'Arecibo', 2: 'Ooty', 3: 'Nancay',
               4: 'Parkes', 5: 'Jodrell', 6: 'GBT', 7: 'GMRT',
               8: 'Effelsberg', 52: 'LWA-OV', 53: 'LWA-SV', 64: 'MeerKAT',
               65: 'KAT-7'}
_MACHINES = {0: 'FAKE', 1: 'PSPM', 2: 'WAPP', 3: 'AOFTM', 4: 'BPP',
             5: 'OOTY', 6: 'SCAMP', 7: 'GBT Pulsar Spigot', 52: 'LWA-DP',
             53: 'LWA-ADP'}


def id2telescope(tid):
    return _TELESCOPES.get(tid, 'unknown(%s)' % tid)


def telescope2id(name):
    for k, v in _TELESCOPES.items():
        if v.lower() == str(name).lower():
            return k
    return 0


def id2machine(mid):
    return _MACHINES.get(mid, 'unknown(%s)' % mid)


def machine2id(name):
    for k, v in _MACHINES.items():
        if v.lower() == str(name).lower():
            return k
    return 0


def _read_string(f):
    n, = struct.unpack('<i', f.read(4))
    if not 0 < n < 256:
        raise IOError("Invalid sigproc string length: %d" % n)
    return f.read(n).decode('ascii')


def _read_header(f):
    if _read_string(f) != 'HEADER_START':
        raise IOError("Missing HEADER_START (not a sigproc file?)")
    hdr = {}
    while True:
        key = _read_string(f)
        if key == 'HEADER_END':
            break
        if key in _INT_KEYS:
            hdr[key], = struct.unpack('<i', f.read(4))
        elif key in _DBL_KEYS:
            hdr[key], = struct.unpack('<d', f.read(8))
        elif key in _STR_KEYS:
            hdr[key] = _read_string(f)
        elif key in _CHR_KEYS:
            hdr[key], = struct.unpack('<b', f.read(1))
        else:
            raise KeyError("Unknown sigproc header key: %r" % key)
    return hdr


def pack_header(hdr):
    """Serialize a header dict to bytes."""
    def s(txt):
        b = txt.encode('ascii')
        return struct.pack('<i', len(b)) + b

    out = [s('HEADER_START')]
    for key, val in hdr.items():
        if key in _INT_KEYS:
            out.append(s(key) + struct.pack('<i', int(val)))
        elif key in _DBL_KEYS:
            out.append(s(key) + struct.pack('<d', float(val)))
        elif key in _STR_KEYS:
            out.append(s(key) + s(str(val)))
        elif key in _CHR_KEYS:
            out.append(s(key) + struct.pack('<b', int(val)))
        else:
            raise KeyError("Unknown sigproc header key: %r" % key)
    out.append(s('HEADER_END'))
    return b''.join(out)


def write_header(f, hdr):
    f.write(pack_header(hdr))


class SigprocFile(object):
    """Streaming reader (reference: python/bifrost/sigproc2.py
    SigprocFile)."""

    def __init__(self, filename=None):
        self.f = None
        if filename is not None:
            self.open(filename)

    def open(self, filename):
        self.f = open(filename, 'rb')
        self.header = _read_header(self.f)
        # SIGPROC integer data is unsigned unless flagged otherwise
        self.header.setdefault('signed', 0)
        self.header_size = self.f.tell()
        self.nbits = self.header['nbits']
        self.nchans = self.header.get('nchans', 1)
        self.nifs = self.header.get('nifs', 1)
        self.frame_nbit = self.nbits * self.nchans * self.nifs
        if self.frame_nbit % 8:
            raise IOError("Frame does not span whole bytes")
        self.frame_nbyte = self.frame_nbit // 8
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self):
        if self.f is not None:
            self.f.close()
            self.f = None

    def nframe(self):
        pos = self.f.tell()
        self.f.seek(0, os.SEEK_END)
        n = (self.f.tell() - self.header_size) // self.frame_nbyte
        self.f.seek(pos)
        return n

    def readinto(self, buf):
        """Read raw (possibly packed) bytes into a buffer."""
        view = np.asarray(buf).view(np.uint8)
        data = self.f.read(view.nbytes)
        flat = view.reshape(-1)
        flat[:len(data)] = np.frombuffer(data, np.uint8)
        return len(data)

    def read(self, nframe):
        """Read and unpack up to nframe frames into an
        (n, nifs, nchans) array (sub-byte data promoted to 8 bits,
        reference: sigproc unpack path)."""
        raw = self.f.read(nframe * self.frame_nbyte)
        nframe_read = len(raw) // self.frame_nbyte
        raw = np.frombuffer(raw[:nframe_read * self.frame_nbyte], np.uint8)
        nbits = self.nbits
        signed = bool(self.header.get('signed', 0))
        if nbits >= 8:
            dtype = {8: np.int8 if signed else np.uint8,
                     16: np.int16 if signed else np.uint16,
                     32: np.float32}[nbits]
            data = raw.view(dtype)
        else:
            per = 8 // nbits
            # LSB-first sample order within each byte (reference:
            # python/bifrost/sigproc.py:281 'assumes LSB-first')
            shifts = (np.arange(per) * nbits).astype(np.uint8)
            vals = (raw[:, None] >> shifts) & ((1 << nbits) - 1)
            vals = vals.reshape(-1)
            if signed:
                # sign-extend the sub-byte field
                data = ((vals.astype(np.int16) << (8 - nbits)).astype(
                    np.int8) >> (8 - nbits))
            else:
                data = vals.astype(np.uint8)
        return data.reshape(nframe_read, self.nifs, self.nchans)
