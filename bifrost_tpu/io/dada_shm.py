"""PSRDADA-style shared-memory ring buffers over System V IPC, with no
libpsrdada dependency (VERDICT r1 item 8; reference binding:
python/bifrost/psrdada.py:276, block: blocks/psrdada.py:365).

Architecture follows PSRDADA's dada_hdu/ipcbuf model (psrdada
ipcbuf.c): a *header* ring and a *data* ring, each made of one small
sync segment (ring geometry + progress counters) plus ``nbufs`` fixed
size buffer segments, with two counting semaphores (FULL for readers,
EMPTY for writers) providing flow control.  The data block lives at
``key``, the header block at ``key + 1`` — the psrdada convention used
by dada_db and friends.  Headers are 4096-byte ASCII key/value pages
("HDR_SIZE 4096\\nNBIT 8\\n...") exactly like DADA files.

NOTE on interop: the *byte layout of the sync segment* this module's
rings use at runtime is its own (versioned via a magic).  For psrdada
segments, :func:`decode_psrdada_sync` / :func:`encode_psrdada_sync` and
``IpcRing.read_psrdada_sync`` / ``IpcRing.emit_psrdada_sync`` read and
write an ``ipcsync_t`` layout reconstructed from psrdada's public
ipcbuf.h (golden-fixture-tested at the documented offsets in
tests/test_dada_shm.py; see the layout table below).  CAVEAT: the
layout has NOT been byte-diffed against a real libpsrdada build (none
exists in this environment) — validate against a real ``dada_db``
segment before relying on it, and expect at most a one-constant fix.  What
is additionally shared with real PSRDADA: the IPC architecture, key
conventions, the ASCII header page format, and the writer/reader state
machine.
"""

from __future__ import annotations

import ctypes
import struct

import numpy as np

__all__ = ['IpcRing', 'DadaHDU', 'sysv_available',
           'shm_accounting_available',
           'DADA_HEADER_SIZE', 'DEFAULT_KEY',
           'PSRDADA_SYNC_SIZE', 'decode_psrdada_sync',
           'encode_psrdada_sync']

DADA_HEADER_SIZE = 4096
DEFAULT_KEY = 0xdada

IPC_CREAT = 0o1000
IPC_EXCL = 0o2000
IPC_RMID = 0
SETVAL = 16

_SEM_FULL = 0    # count of filled buffers (readers wait on this)
_SEM_EMPTY = 1   # count of free buffers (writers wait on this)

_MAGIC = 0xB1F0DADA00000001
# sync segment: magic, nbufs, bufsz, w_count, r_count, eod_flag,
#               eod_bufno, eod_nbyte, then nbufs u64 byte-counts
_SYNC_FIXED = struct.Struct('<8Q')

# ---------------------------------------------------------------------------
# PSRDADA ipcsync_t codec (VERDICT r2 item 5).
#
# Models the sync struct of psrdada's public ipcbuf.h (the struct the
# reference's generated bindings wrap, /root/reference/python/bifrost/
# psrdada.py:276 via bifrost.libpsrdada_generated) on LP64 x86-64 with
# the library's compile-time defaults IPCBUF_READERS=8, IPCBUF_XFERS=8:
#
#   offset  field                      type
#   0       semkey                     key_t (i32)
#   4       semkey_connect             key_t (i32)
#   8       nbufs                      u64
#   16      bufsz                      u64
#   24      w_buf_curr                 u64
#   32      w_buf_next                 u64
#   40      w_xfer                     i32
#   44      w_state                    i32
#   48      r_bufs[IPCBUF_READERS]     u64[8]
#   112     r_xfers[IPCBUF_READERS]    i32[8]
#   144     r_states[IPCBUF_READERS]   i32[8]
#   176     num_readers                u32     (+4 pad to align u64)
#   184     s_buf[IPCBUF_XFERS]        u64[8]  start-of-data buffer
#   248     s_byte[IPCBUF_XFERS]       u64[8]  start byte within s_buf
#   312     eod[IPCBUF_XFERS]          i8[8]   end-of-data raised
#   320     e_buf[IPCBUF_XFERS]        u64[8]  end-of-data buffer
#   384     e_byte[IPCBUF_XFERS]       u64[8]  end byte within e_buf
#   448     semkey_data[IPCBUF_READERS] i32[8]
#   480     (total)
#
# CAVEAT: no libpsrdada build exists in this environment to
# cross-validate against, so this codec is a reconstruction of the
# public struct shape, versioned here so a byte-diff against a real
# `dada_db` segment is a one-constant fix.  The golden fixture in
# tests/test_dada_shm.py is hand-built to THIS layout independently of
# encode_psrdada_sync.
# ---------------------------------------------------------------------------

IPCBUF_READERS = 8
IPCBUF_XFERS = 8
PSRDADA_SYNC_SIZE = 480
_PSRDADA_HEAD = struct.Struct('<iiQQQQii')           # through w_state
_PSRDADA_RBUFS = struct.Struct('<8Q8i8i')            # r_bufs/r_xfers/r_states
_PSRDADA_XFERS = struct.Struct('<I4x8Q8Q8b8Q8Q8i')   # num_readers..semkey_data


def decode_psrdada_sync(raw):
    """Decode a psrdada-layout ``ipcsync_t`` segment into a dict.
    ``raw`` is bytes-like of >= PSRDADA_SYNC_SIZE bytes (e.g. the shm
    segment a ``dada_db`` created)."""
    raw = bytes(raw[:PSRDADA_SYNC_SIZE])
    if len(raw) < PSRDADA_SYNC_SIZE:
        raise ValueError("psrdada sync segment too small: %d < %d"
                         % (len(raw), PSRDADA_SYNC_SIZE))
    (semkey, semkey_connect, nbufs, bufsz, w_buf_curr, w_buf_next,
     w_xfer, w_state) = _PSRDADA_HEAD.unpack_from(raw, 0)
    off = _PSRDADA_HEAD.size
    rb = _PSRDADA_RBUFS.unpack_from(raw, off)
    off += _PSRDADA_RBUFS.size
    xf = _PSRDADA_XFERS.unpack_from(raw, off)
    return {
        'semkey': semkey, 'semkey_connect': semkey_connect,
        'nbufs': nbufs, 'bufsz': bufsz,
        'w_buf_curr': w_buf_curr, 'w_buf_next': w_buf_next,
        'w_xfer': w_xfer, 'w_state': w_state,
        'r_bufs': list(rb[0:8]), 'r_xfers': list(rb[8:16]),
        'r_states': list(rb[16:24]),
        'num_readers': xf[0],
        's_buf': list(xf[1:9]), 's_byte': list(xf[9:17]),
        'eod': [bool(v) for v in xf[17:25]],
        'e_buf': list(xf[25:33]), 'e_byte': list(xf[33:41]),
        'semkey_data': list(xf[41:49]),
    }


def encode_psrdada_sync(nbufs, bufsz, semkey=0, num_readers=1,
                        w_buf_curr=0, w_buf_next=0, w_xfer=0,
                        w_state=0, r_bufs=None, r_xfers=None,
                        r_states=None, s_buf=None, s_byte=None,
                        eod=None, e_buf=None, e_byte=None,
                        semkey_connect=0, semkey_data=None):
    """Encode a psrdada-layout ``ipcsync_t`` segment (the inverse of
    :func:`decode_psrdada_sync`)."""
    def _arr(v, n, fill=0):
        v = list(v) if v is not None else []
        return (v + [fill] * n)[:n]
    out = bytearray(PSRDADA_SYNC_SIZE)
    _PSRDADA_HEAD.pack_into(out, 0, semkey, semkey_connect, nbufs,
                            bufsz, w_buf_curr, w_buf_next, w_xfer,
                            w_state)
    off = _PSRDADA_HEAD.size
    _PSRDADA_RBUFS.pack_into(out, off,
                             *(_arr(r_bufs, 8) + _arr(r_xfers, 8) +
                               _arr(r_states, 8)))
    off += _PSRDADA_RBUFS.size
    _PSRDADA_XFERS.pack_into(
        out, off, num_readers,
        *(_arr(s_buf, 8) + _arr(s_byte, 8) +
          [1 if v else 0 for v in _arr(eod, 8, False)] +
          _arr(e_buf, 8) + _arr(e_byte, 8) + _arr(semkey_data, 8)))
    return bytes(out)

_libc = None


def _get_libc():
    global _libc
    if _libc is None:
        _libc = ctypes.CDLL(None, use_errno=True)
        _libc.shmat.restype = ctypes.c_void_p
        _libc.shmat.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                ctypes.c_int]
    return _libc


def sysv_available():
    """Whether System V shm works here (it can be disabled in
    containers)."""
    try:
        libc = _get_libc()
        shmid = libc.shmget(0, 4096, IPC_CREAT | 0o600)   # IPC_PRIVATE
        if shmid < 0:
            return False
        libc.shmctl(shmid, IPC_RMID, None)
        return True
    except Exception:
        return False


def shm_accounting_available():
    """Whether SysV segment ATTACHMENT accounting works here: the
    stale-segment recovery and live-ring protection read nattch from
    ``/proc/sysvipc/shm``, which sandboxed kernels (gVisor-style
    containers) omit even when shmget/shmat themselves work.  Without
    it those protections silently degrade (a live ring cannot be
    distinguished from a stale one) — tests exercising them should
    skip rather than fail (tests/test_dada_shm.py)."""
    if not sysv_available():
        return False
    import errno as errno_mod
    probe_key = 0x5bfb
    libc = _get_libc()
    # EXCL: a pre-existing segment at the probe key belongs to someone
    # else and must not be attached (or RMID'd out from under them)
    shmid = libc.shmget(probe_key, 4096, IPC_CREAT | IPC_EXCL | 0o600)
    if shmid < 0:
        if ctypes.get_errno() == errno_mod.EEXIST:
            return _shm_nattch(probe_key) is not None
        return False
    try:
        return _shm_nattch(probe_key) is not None
    finally:
        libc.shmctl(shmid, IPC_RMID, None)


def _shm_nattch(key):
    """Number of processes attached to the segment at ``key`` (from
    /proc/sysvipc/shm), or None if no such segment."""
    try:
        with open('/proc/sysvipc/shm') as f:
            next(f)
            for line in f:
                parts = line.split()
                if len(parts) >= 7 and int(parts[0]) == key:
                    return int(parts[6])   # nattch column
    except (OSError, ValueError, StopIteration):
        pass
    return None


def _shm_create(key, size):
    """Create a fresh segment.  A STALE segment at the key (crashed
    previous run, zero attachments) is removed first so counters never
    carry over; a LIVE one (attached processes) is an error rather
    than silently destroyed out from under its owner."""
    import errno as errno_mod
    libc = _get_libc()
    shmid = libc.shmget(key, size, IPC_CREAT | IPC_EXCL | 0o666)
    if shmid < 0 and ctypes.get_errno() == errno_mod.EEXIST:
        nattch = _shm_nattch(key)
        if nattch:
            raise OSError(
                errno_mod.EEXIST,
                'DADA segment 0x%x is in use by %d process(es); '
                'destroy it first or use another key' % (key, nattch))
        old = libc.shmget(key, 0, 0o666)
        if old >= 0:
            libc.shmctl(old, IPC_RMID, None)
        shmid = libc.shmget(key, size, IPC_CREAT | IPC_EXCL | 0o666)
    if shmid < 0:
        raise OSError(ctypes.get_errno(), 'shmget(create) failed')
    return shmid


def _destroy_stale_ring(key):
    """Remove ALL IPC objects of a stale ring at ``key`` (sync, every
    buffer segment per its recorded nbufs, semaphores) so a recovery
    run with fewer buffers does not leak the crashed run's extras."""
    import struct as struct_mod
    libc = _get_libc()
    old = libc.shmget(key, 0, 0o666)
    if old < 0:
        return
    try:
        head, addr = _shm_map(old, _SYNC_FIXED.size)
        magic, nbufs, _bufsz = struct_mod.unpack_from('<3Q', head)
        del head
        libc.shmdt(ctypes.c_void_p(addr))
        if magic == _MAGIC:
            for i in range(int(nbufs)):
                bid = libc.shmget(((key << 8) | i) & 0x7FFFFFFF, 0,
                                  0o666)
                if bid >= 0:
                    libc.shmctl(bid, IPC_RMID, None)
        libc.shmctl(old, IPC_RMID, None)
        sem = libc.semget(key, 2, 0o666)
        if sem >= 0:
            libc.semctl(sem, 0, IPC_RMID)
    except OSError:
        pass


def _shm_attach(key, size=0):
    libc = _get_libc()
    shmid = libc.shmget(key, size, 0o666)
    if shmid < 0:
        raise OSError(ctypes.get_errno(),
                      'shmget: no segment at key 0x%x' % key)
    return shmid


def _shm_map(shmid, size):
    libc = _get_libc()
    addr = libc.shmat(shmid, None, 0)
    if addr in (None, ctypes.c_void_p(-1).value):
        raise OSError(ctypes.get_errno(), 'shmat failed')
    buf = (ctypes.c_ubyte * size).from_address(addr)
    return np.frombuffer(buf, np.uint8), addr


class _sembuf(ctypes.Structure):
    _fields_ = [('sem_num', ctypes.c_ushort),
                ('sem_op', ctypes.c_short),
                ('sem_flg', ctypes.c_short)]


class _timespec(ctypes.Structure):
    _fields_ = [('tv_sec', ctypes.c_long),
                ('tv_nsec', ctypes.c_long)]


def _sem_op(semid, num, op, timeout=None):
    """semop / semtimedop.  With a timeout, returns False on expiry
    instead of blocking forever (lets ring waits observe shutdown)."""
    import errno as errno_mod
    sb = _sembuf(num, op, 0)
    libc = _get_libc()
    if timeout is None:
        rc = libc.semop(semid, ctypes.byref(sb), 1)
    else:
        ts = _timespec(int(timeout),
                       int((timeout - int(timeout)) * 1e9))
        rc = libc.semtimedop(semid, ctypes.byref(sb), 1,
                             ctypes.byref(ts))
    if rc < 0:
        err = ctypes.get_errno()
        if timeout is not None and err in (errno_mod.EAGAIN,
                                           errno_mod.EINTR):
            return False
        raise OSError(err, 'semop failed')
    return True


class IpcRing(object):
    """One PSRDADA-style ring: sync segment + nbufs buffer segments +
    a FULL/EMPTY semaphore pair (psrdada analogue: ipcbuf_t)."""

    #: buffer segment i lives at key (ring_key << 8) | i, giving each
    #: ring (data at key, header at key+1) a disjoint buffer key space
    MAX_NBUFS = 256

    def _buf_key(self, i):
        return ((self.key << 8) | i) & 0x7FFFFFFF

    def __init__(self, key, nbufs=None, bufsz=None, create=False):
        libc = _get_libc()
        self.key = key
        self.owner = create
        if create:
            if not nbufs or not bufsz:
                raise ValueError("create=True requires nbufs and bufsz")
            if nbufs > self.MAX_NBUFS:
                raise ValueError("nbufs is limited to %d" % self.MAX_NBUFS)
            if _shm_nattch(key) in (0,):
                _destroy_stale_ring(key)
            self.nbufs, self.bufsz = nbufs, bufsz
            sync_size = _SYNC_FIXED.size + 8 * nbufs
            self._sync_id = _shm_create(key, sync_size)
            self._sync, _ = _shm_map(self._sync_id, sync_size)
            self._write_sync(_MAGIC, nbufs, bufsz, 0, 0, 0, 0, 0)
            self._bufs = []
            self._buf_ids = []
            for i in range(nbufs):
                bid = _shm_create(self._buf_key(i), bufsz)
                self._buf_ids.append(bid)
                self._bufs.append(_shm_map(bid, bufsz)[0])
            # recreate the semaphore set too, in case a stale one
            # holds nonzero counts
            old_sem = libc.semget(key, 2, 0o666)
            if old_sem >= 0:
                libc.semctl(old_sem, 0, IPC_RMID)
            self._semid = libc.semget(key, 2, IPC_CREAT | 0o666)
            if self._semid < 0:
                raise OSError(ctypes.get_errno(), 'semget failed')
            libc.semctl(self._semid, _SEM_FULL, SETVAL, 0)
            libc.semctl(self._semid, _SEM_EMPTY, SETVAL, nbufs)
        else:
            self._sync_id = _shm_attach(key)
            head, head_addr = _shm_map(self._sync_id, _SYNC_FIXED.size)
            magic, nbufs, bufsz = struct.unpack_from('<3Q', head)
            del head
            libc.shmdt(ctypes.c_void_p(head_addr))
            if magic != _MAGIC:
                # is it a real psrdada segment? (dada_db layout)
                hint = ''
                try:
                    pd = IpcRing.read_psrdada_sync(key)
                    if 0 < pd['nbufs'] <= 1 << 20 and pd['bufsz'] > 0:
                        hint = ('; the segment decodes as a psrdada '
                                'ipcsync_t (nbufs=%d bufsz=%d) — read '
                                'it with IpcRing.read_psrdada_sync or '
                                'psrdada tools'
                                % (pd['nbufs'], pd['bufsz']))
                except OSError:
                    pass
                raise IOError(
                    "Segment at key 0x%x is not a bifrost_tpu DADA ring "
                    "(magic %x)%s" % (key, magic, hint))
            self.nbufs, self.bufsz = nbufs, bufsz
            sync_size = _SYNC_FIXED.size + 8 * nbufs
            self._sync, _ = _shm_map(self._sync_id, sync_size)
            self._buf_ids = []
            self._bufs = []
            for i in range(nbufs):
                bid = _shm_attach(self._buf_key(i), bufsz)
                self._buf_ids.append(bid)
                self._bufs.append(_shm_map(bid, bufsz)[0])
            self._semid = libc.semget(key, 2, 0o666)
            if self._semid < 0:
                raise OSError(ctypes.get_errno(), 'semget failed')
        self._w_open = None
        self._r_open = None

    # -- sync helpers ------------------------------------------------------
    def _write_sync(self, *vals):
        _SYNC_FIXED.pack_into(self._sync, 0, *vals)

    def _read_sync(self):
        return _SYNC_FIXED.unpack_from(self._sync, 0)

    def _set_field(self, idx, val):
        struct.pack_into('<Q', self._sync, idx * 8, val)

    def _get_field(self, idx):
        return struct.unpack_from('<Q', self._sync, idx * 8)[0]

    def _set_buf_nbyte(self, bufno, nbyte):
        struct.pack_into('<Q', self._sync,
                         _SYNC_FIXED.size + 8 * bufno, nbyte)

    def _get_buf_nbyte(self, bufno):
        return struct.unpack_from(
            '<Q', self._sync, _SYNC_FIXED.size + 8 * bufno)[0]

    # -- writer side (psrdada: ipcio_open / ipcbuf_mark_filled) -----------
    def open_write_buf(self):
        """Block until a buffer is free; return a writable numpy view."""
        _sem_op(self._semid, _SEM_EMPTY, -1)
        w = self._get_field(3)
        self._w_open = w % self.nbufs
        return self._bufs[self._w_open]

    def mark_filled(self, nbyte=None, eod=False):
        """Publish the open write buffer (psrdada: ipcbuf_mark_filled).
        End-of-data is EXPLICIT (``eod=True``, like ipcbuf_enable_eod) —
        a short buffer alone does not end the observation, so streaming
        writers may fill buffers partially."""
        assert self._w_open is not None
        nbyte = self.bufsz if nbyte is None else nbyte
        self._set_buf_nbyte(self._w_open, nbyte)
        w = self._get_field(3)
        if eod:
            self._set_field(5, 1)
            self._set_field(6, w)
            self._set_field(7, nbyte)
        self._set_field(3, w + 1)
        self._w_open = None
        _sem_op(self._semid, _SEM_FULL, +1)

    # -- reader side (psrdada: ipcbuf_get_next_read / mark_cleared) -------
    def open_read_buf(self, timeout=None):
        """Block until a buffer is filled; return (view, nbyte, is_eod),
        or None if ``timeout`` (seconds) expires first."""
        if not _sem_op(self._semid, _SEM_FULL, -1, timeout):
            return None
        r = self._get_field(4)
        bufno = r % self.nbufs
        nbyte = self._get_buf_nbyte(bufno)
        eod = bool(self._get_field(5)) and self._get_field(6) == r
        self._r_open = bufno
        return self._bufs[bufno], nbyte, eod

    def mark_cleared(self):
        assert self._r_open is not None
        self._set_field(4, self._get_field(4) + 1)
        self._r_open = None
        _sem_op(self._semid, _SEM_EMPTY, +1)

    # -- psrdada-layout interop (VERDICT r2 item 5) ------------------------
    @classmethod
    def read_psrdada_sync(cls, key):
        """Attach to the shm segment at ``key`` and decode it as a
        psrdada ``ipcsync_t`` (the segment a ``dada_db -k <key>``
        creates).  Returns the decoded dict; raises OSError when no
        segment exists.  CAVEAT: decodes the reconstructed layout
        documented above, which has not been validated against a real
        libpsrdada build — cross-check before relying on the fields."""
        libc = _get_libc()
        shmid = _shm_attach(key)
        buf, addr = _shm_map(shmid, PSRDADA_SYNC_SIZE)
        try:
            return decode_psrdada_sync(bytes(buf))
        finally:
            del buf
            libc.shmdt(ctypes.c_void_p(addr))

    def emit_psrdada_sync(self, key):
        """Write a psrdada-layout ``ipcsync_t`` describing THIS ring's
        geometry and cursors into a fresh shm segment at ``key`` (so
        psrdada-side tooling can inspect the ring).  Returns the shmid;
        the caller owns the segment's lifetime.  Same layout CAVEAT as
        :meth:`read_psrdada_sync`."""
        _, nbufs, bufsz, w, r, eodf, eodb, eodn = self._read_sync()
        raw = encode_psrdada_sync(
            nbufs=nbufs, bufsz=bufsz, semkey=self.key,
            num_readers=1, w_buf_curr=w, w_buf_next=w + 1,
            r_bufs=[r], eod=[bool(eodf)], e_buf=[eodb],
            e_byte=[eodn])
        shmid = _shm_create(key, PSRDADA_SYNC_SIZE)
        buf, addr = _shm_map(shmid, PSRDADA_SYNC_SIZE)
        buf[:] = np.frombuffer(raw, np.uint8)
        del buf
        _get_libc().shmdt(ctypes.c_void_p(addr))
        return shmid

    # -- lifecycle ---------------------------------------------------------
    def destroy(self):
        """Remove the IPC objects (creator side)."""
        libc = _get_libc()
        for bid in self._buf_ids:
            libc.shmctl(bid, IPC_RMID, None)
        libc.shmctl(self._sync_id, IPC_RMID, None)
        libc.semctl(self._semid, 0, IPC_RMID)


class DadaHDU(object):
    """A header + data ring pair (psrdada analogue: dada_hdu_t).
    Data ring at ``key``, header ring at ``key + 1``."""

    def __init__(self, key=DEFAULT_KEY, create=False, data_nbufs=8,
                 data_bufsz=1 << 20, header_nbufs=4,
                 header_bufsz=DADA_HEADER_SIZE):
        self.key = key
        self.data = IpcRing(key, data_nbufs, data_bufsz, create=create)
        self.header = IpcRing(key + 1, header_nbufs, header_bufsz,
                              create=create)

    # -- writer ------------------------------------------------------------
    def write_header(self, fields):
        """Write one observation's ASCII header page."""
        lines = []
        fields = dict(fields)
        fields.setdefault('HDR_SIZE', self.header.bufsz)
        fields.setdefault('HDR_VERSION', '1.0')
        for k, v in fields.items():
            lines.append('%s %s' % (k, v))
        raw = ('\n'.join(lines) + '\n').encode('ascii')
        if len(raw) > self.header.bufsz:
            raise ValueError("header too large")
        buf = self.header.open_write_buf()
        buf[:] = 0
        buf[:len(raw)] = np.frombuffer(raw, np.uint8)
        self.header.mark_filled()

    def write_data(self, data, eod=False):
        """Write bytes into consecutive data buffers."""
        data = np.asarray(data).reshape(-1).view(np.uint8)
        off = 0
        while off < len(data) or (eod and off == len(data) == 0):
            buf = self.data.open_write_buf()
            n = min(self.data.bufsz, len(data) - off)
            buf[:n] = data[off:off + n]
            off += n
            last = off >= len(data)
            self.data.mark_filled(n, eod=eod and last)
            if last:
                break

    def end_data(self):
        """Mark end-of-data with an empty buffer."""
        self.data.open_write_buf()
        self.data.mark_filled(0, eod=True)

    # -- reader ------------------------------------------------------------
    def read_header(self, timeout=None, should_stop=None):
        """Block for the next observation header; returns the raw ASCII
        bytes (parse with blocks.psrdada._parse_dada_header), or None
        if ``should_stop()`` turns true while waiting."""
        while True:
            got = self.header.open_read_buf(
                timeout if should_stop is not None else None)
            if got is not None:
                buf, nbyte, _ = got
                raw = bytes(buf[:nbyte])
                self.header.mark_cleared()
                return raw
            if should_stop is not None and should_stop():
                return None

    def destroy(self):
        self.data.destroy()
        self.header.destroy()
